"""Headline benchmark: gemm GFLOP/s on one chip (BASELINE.json config #1,
"dgemm n=4096 nb=256, 1x1 grid" — examples/ex05_blas.cc / test_gemm in the reference).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Precision envelope: the reference's headline is double precision on GPU; TPU has no
f64 ALUs, so the comparable configuration is f32 accumulation with
``Precision.HIGHEST`` (6-pass bf16 emulation — the dtype the z/d routine family maps
to on TPU, SURVEY.md §7 hard-part 6).  ``vs_baseline`` divides by 15,000 GFLOP/s — a
measured cuBLAS A100 dgemm figure at n=4096, the reference's native configuration —
so >1.0 beats the reference hardware's double-precision rate.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
from jax import lax

BASELINE_GFLOPS = 15_000.0  # cuBLAS dgemm n=4096 on A100 (reference-native config)


def _time_chain(a, b, k: int, precision, repeats: int = 3) -> float:
    """Best wall time of one jitted call running k chained matmuls."""
    scale = 1.0 / jnp.sqrt(jnp.asarray(a.shape[-1], a.dtype))

    def body(i, c):
        return jnp.matmul(c, b, precision=precision) * scale

    fn = jax.jit(lambda a: lax.fori_loop(0, k, body, a))
    fn(a).block_until_ready()  # compile + warm up
    times = []
    for i in range(repeats):
        t0 = time.perf_counter()
        fn(a + jnp.asarray(i, a.dtype)).block_until_ready()
        times.append(time.perf_counter() - t0)
    return min(times)


def bench_gemm(n: int = 4096, dtype=jnp.float32, precision=lax.Precision.HIGHEST,
               k_small: int = 8, k_large: int = 136):
    """Compute-only GFLOP/s via a chain-length delta: timing (k_large - k_small)
    extra matmuls inside one jit call cancels dispatch/transfer overhead (the
    tunnel round-trip here is ~70 ms — larger than a single n=4096 matmul)."""
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), dtype=dtype)
    b = jax.random.normal(jax.random.fold_in(key, 1), (n, n), dtype=dtype)

    t_small = _time_chain(a, b, k_small, precision)
    t_large = _time_chain(a, b, k_large, precision)
    per_matmul = (t_large - t_small) / (k_large - k_small)
    return 2.0 * n**3 / per_matmul / 1e9


def main():
    gflops = bench_gemm()
    print(json.dumps({
        "metric": "gemm_f32hi_n4096_gflops",
        "value": round(gflops, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(gflops / BASELINE_GFLOPS, 3),
    }))


if __name__ == "__main__":
    main()
