"""TPU benchmark driver covering the five BASELINE.md north-star configs.

Prints exactly ONE JSON line (the headline gemm metric), with every config's
GFLOP/s + vs_baseline nested under ``"configs"``; full detail (timings, attempts,
failures) is written to ``BENCH_DETAIL.json`` next to this file.

Architecture (hardened after round 1, where a single in-process backend-init
failure produced no number at all):

- the parent process never imports jax.  Each measurement runs in a fresh child
  subprocess (``python bench.py --child <config>``), so a wedged TPU-tunnel
  backend init cannot poison later attempts (jax caches backend-init failures
  per process).
- the parent first runs a cheap ``--child probe`` (device enumeration + one tiny
  matmul) with bounded retries; if the TPU backend never comes up it falls back
  to CPU (smaller sizes) so the output line is parseable either way, with
  ``"backend"`` recording which hardware produced it.
- every child prints its result as the last stdout line in JSON; the parent
  enforces per-config timeouts and a global deadline.

Precision envelope: the reference's headline is double precision on GPU; TPU has
no f64 ALUs, so the comparable configuration is f32 with
``lax.Precision.HIGHEST`` (bf16-emulated full-precision accumulation — the dtype
the d/z routine family maps to on TPU, SURVEY.md §7 hard-part 6).
``vs_baseline`` divides by measured/estimated cuBLAS/cuSOLVER A100 fp64 rates
for the reference's native configuration (see BASELINES below), so >1.0 beats
the reference hardware's double-precision rate at the same job.

Flop models follow the LAPACK conventions the reference's tester uses
(blas/lapack flops.hh, cited in BASELINE.md): gemm 2n^3; potrf n^3/3;
getrf 2n^3/3; tall-skinny least squares 2n^2(m - n/3); heev values 4n^3/3;
svd values 8n^3/3.  Where our algorithm does *more* arithmetic than the model
(CholeskyQR2 vs Householder QR) the model still counts the *job*, so the rate
is an honest effective rate for the same problem.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
DETAIL_PATH = os.path.join(REPO, "BENCH_DETAIL.json")
# Last-known-good TPU results, committed to the repo.  Every successful
# TPU-backend measurement overwrites its config entry here (written
# immediately, not at exit, so a later wedge cannot lose it); at emission
# time any config without a fresh TPU number is backfilled from this file
# with ``"source": "cached"`` + the recording timestamp.  This makes the
# hardware evidence durable against tunnel health at capture time.
LKG_PATH = os.path.join(REPO, "BENCH_LKG.json")

# A100 80GB fp64 rates for the reference-native configuration (cuBLAS/cuSOLVER;
# gemm figure measured, factorization/eig figures are published-order estimates —
# documented so vs_baseline is interpretable, not a black box).
BASELINES = {
    "gemm": 15000.0,   # cuBLAS dgemm n=4096
    "potrf": 13000.0,  # cuSOLVER/MAGMA dpotrf n=16384 (gemm-rich, near dgemm rate)
    "getrf": 9000.0,   # dgetrf n=16384 (pivoting + panel overhead)
    "gels": 9000.0,    # tall dgels 131072x4096, cholqr path
    "heev": 300.0,     # dsyevd values n=16384 on 4n^3/3 model (the n=4096
                       # config used 150; published-order A100 rates roughly
                       # double from 4k to 16k as the tridiagonal stage
                       # amortizes — VERDICT r2 asked for the BASELINE-scale
                       # config, so the denominator moves with it)
    "svd": 200.0,      # dgesvd values n=16384 on 8n^3/3 model (was 100 at
                       # n=4096; same scaling rationale)
    "norm": 450.0,     # dlange Fro n=16384: bandwidth-bound, ~1.8 TB/s HBM
                       # at 8 B/elem and 2 flops/elem -> ~450 GFLOP/s
    "potrf_la": 13000.0,  # same job/denominator as potrf: the lookahead-
                          # pipelined schedule vs the unrolled tiled one
    "f64gemm": 15000.0,   # A100 cuBLAS dgemm n=4096 — TRUE fp64-class vs
                          # fp64 (the one apples-to-apples ratio; every other
                          # config crosses f32-HIGHEST vs fp64, BENCH_NOTES)
    "gesvir": 9000.0,     # A100 dgesv n=4096-class (dgetrf-rate bound);
                          # ours = f32 LU + emulated-f64 IR to double-class
                          # forward error (gesv_f64ir), flops on the 2n^3/3
                          # dgetrf model
    "getrf_pp": 9000.0,   # same job/denominator as getrf: CALU with the
                          # pp panel (Options.lu_panel="pp" — one partial-
                          # pivot subpanel LU instead of the merge tree) so
                          # the two panel schemes read as a direct A/B and
                          # the r5 regression bisection has its second arm
    "svd2s": 150.0,       # dgesvd values n=8192 published-order estimate
                          # (between the n=4096 100 and n=16384 200 rates);
                          # times the SLATE-parity SVD pipeline next to the
                          # fused default
    "heev2s": 225.0,      # dsyevd values n=8192 published-order estimate
                          # (between the n=4096 150 and n=16384 300 rates);
                          # config exists to time the SLATE-parity two-stage
                          # pipeline next to the fused QDWH default
    "serve_mixed": 20000.0,   # solves/s — nominal A100 batched-cuSOLVER
                              # order-of-magnitude for mixed n<=96 small
                              # solves (getrfBatched-class throughput); a
                              # rough denominator documented so the ratio is
                              # a trend line, not a hardware-parity claim.
                              # This config's unit is solves/s, not GFLOP/s:
                              # the serving axis measures throughput + p50/
                              # p99 latency of the slate_tpu.serve queue
                              # under synthetic mixed traffic (ROADMAP 2)
    "serve_scale": 40000.0,   # solves/s — the serve_mixed denominator x2:
                              # the scale axis reports the N=2 executor-pool
                              # warm rate, so its trend line is read against
                              # a two-worker batched-cuSOLVER-class figure.
                              # Unit is warm solves/s at N=2 (scaling gates
                              # — N=2 >= N=1 — ride in the metrics blob)
}

# ordered safest-first: a child killed mid-execution can wedge the
# single-session TPU tunnel for every later child, so the configs proven
# cheap/robust on hardware run before the risky ones (LU last: both the fused
# and tournament paths are slow enough at n=16384 to risk the per-config
# timeout)
CONFIGS = ["gemm", "norm", "serve_mixed", "serve_scale", "f64gemm", "potrf",
           "potrf_la", "gels", "gesvir", "heev", "svd", "getrf", "getrf_pp",
           "heev2s", "svd2s"]
HEADLINE = "gemm"

# per-config child timeouts: the BASELINE-scale eig/SVD configs and the
# 8-panel CALU programs carry minutes of (remote) XLA compile before the
# first timed call — measured 3 min of compile for the getrf program on CPU
CONFIG_TIMEOUTS = {"heev": 1300, "svd": 1500, "getrf": 1500, "getrf_pp": 1500,
                   "potrf_la": 1300, "heev2s": 1800, "svd2s": 1800}

# ---------------------------------------------------------------------------
# children — each runs in its own process, imports jax lazily
# ---------------------------------------------------------------------------


def _emit(obj):
    if isinstance(obj, dict) and "metric" in obj:
        # attach the child's observability blob (slate_tpu.obs registry:
        # driver spans, phase histograms, robust events) so each config's
        # BENCH_DETAIL.json entry carries its metrics.json alongside the
        # rate — only when the library actually ran (probe emits none)
        mod = sys.modules.get("slate_tpu.obs")
        if mod is not None:
            try:
                doc = mod.metrics_doc(source="bench")
                if doc.get("metrics"):
                    obj = dict(obj, metrics=doc)
            except Exception:
                pass
    print(json.dumps(obj), flush=True)


# Child-side soft deadline (set from BENCH_CHILD_BUDGET_SEC in __main__).
# Round-5 wedge forensics: the heev/svd children at n=16384 need ~9 forced
# eigh/svd calls under the chain protocol — more than their per-config
# timeout on the tunnel — so the parent SIGKILLed them mid-RPC, and a child
# killed mid-execution is exactly the documented tunnel-wedge trigger (today:
# getrf captured fresh at 08:35, the heev group timed out, every probe after
# 09:20 hung).  The fix: children track a soft deadline 120 s inside the
# parent timeout, never START a call whose estimated cost does not fit, and
# emit a truncated-but-real measurement instead of dying.
_CHILD_DEADLINE = None


def _budget_left():
    if _CHILD_DEADLINE is None:
        return float("inf")
    return _CHILD_DEADLINE - time.time()


def child_probe():
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    x = jnp.ones((128, 128))
    s = float(jnp.sum(x @ x))
    _emit({"ok": True, "platform": devs[0].platform,
           "device_kind": devs[0].device_kind, "n_devices": len(devs), "sum": s})


def _chain_rate(body, a0, consts, k_small, k_large, flops_per_iter, repeats=3):
    """GFLOP/s via chain-length delta: timing (k_large - k_small) extra
    iterations of a data-dependent loop inside one jit call cancels dispatch
    and transfer overhead (the TPU tunnel round-trip is ~70 ms — larger than
    many single calls at these sizes).  The chain is mandatory on the tunnel
    backend, which memoizes repeated identical executions.

    ``body(i, carry, *consts)``: loop-invariant operands MUST come through
    ``consts`` (jit arguments), never closures — a closed-over array becomes
    an HLO constant shipped inside the remote-compile request, and the tunnel
    rejects bodies past ~128 MB (HTTP 413; a 16k x 16k f32 operand is 1 GB).

    Timing protocol: the tunnel backend defers execution (block_until_ready
    returns immediately), so each timed call ends with a one-element fetch,
    which forces the whole computation; every repeat gets a freshly perturbed
    carry so no caching layer can satisfy it.
    """
    import jax
    from jax import lax

    def timed(k):
        """(min timed sec, compile+warm sec).  Budget-aware: repeats stop
        early when the next timed call would not fit inside the soft
        deadline; with zero repeats the warm time stands in (compile-
        inclusive, so the derived rate is an under-estimate, never inflated)."""
        fn = jax.jit(lambda c0, *cs: lax.fori_loop(
            0, k, lambda i, c: body(i, c, *cs), c0))
        t0 = time.perf_counter()
        float(jnp_ravel0(fn(a0, *consts)))   # compile + warm (forced)
        warm = time.perf_counter() - t0
        ts = []
        for j in range(repeats):
            est = min(ts) if ts else warm
            if _budget_left() < 1.3 * est + 10:
                break
            c0 = a0 + (j + 1) * 1e-7
            float(jnp_ravel0(c0))            # materialize before the clock
            t0 = time.perf_counter()
            r = fn(c0, *consts)
            float(jnp_ravel0(r))             # fetch forces execution
            ts.append(time.perf_counter() - t0)
        return (min(ts) if ts else warm), warm

    def jnp_ravel0(x):
        return x.ravel()[0]

    info = {}
    t_small, warm_small = timed(k_small)
    # cost of the large-chain round: one compile+warm (k_large iters) plus up
    # to `repeats` timed calls, scaled from the small-chain reading
    est_large = (t_small / k_small) * k_large * (repeats + 1) + 0.5 * warm_small
    if _budget_left() < 1.2 * est_large + 10:
        # not enough budget for the delta protocol: report the overhead-
        # inclusive small-chain rate and SAY so, rather than risk the parent
        # killing this child mid-RPC (the tunnel-wedge trigger)
        per_iter = t_small / k_small
        info["budget_truncated"] = f"k_large={k_large} skipped; rate is " \
                                   f"overhead-inclusive from k={k_small}"
        return flops_per_iter / per_iter / 1e9, per_iter, info
    t_large, _ = timed(k_large)
    per_iter = (t_large - t_small) / (k_large - k_small)
    if per_iter <= 0:
        # short chains on fast ops can lose the delta to timing noise; fall
        # back to the overhead-inclusive total (always positive, and an
        # *under*-estimate of the rate — never an absurd number)
        per_iter = t_large / k_large
    return flops_per_iter / per_iter / 1e9, per_iter, info


def child_gemm(cpu_fallback):
    """dgemm n=4096 (BASELINE config #1; reference examples/ex05_blas.cc).

    Times the framework's gemm driver (slate_tpu.blas.gemm, traced under jit —
    it lowers to one fused XLA matmul at Precision.HIGHEST)."""
    import jax
    import jax.numpy as jnp
    import slate_tpu

    n = 2048 if cpu_fallback else 4096
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), dtype=jnp.float32)
    b = jax.random.normal(jax.random.fold_in(key, 1), (n, n), dtype=jnp.float32)
    scale = 1.0 / jnp.sqrt(jnp.asarray(n, jnp.float32))

    def body(i, c, b, scale):
        # the framework's gemm always computes at lax.Precision.HIGHEST
        # (ops/blas3.py), which is what the f32hi metric name asserts
        return slate_tpu.gemm(scale, c, b, 0.0, c)

    ks, kl = (2, 10) if cpu_fallback else (8, 136)
    gflops, per_iter, info = _chain_rate(body, a, (b, scale), ks, kl, 2.0 * n**3)
    _emit({"metric": f"gemm_f32hi_n{n}_gflops", "value": round(gflops, 1),
           "unit": "GFLOP/s", "n": n, "sec_per_call": per_iter, **info})


def child_potrf(cpu_fallback):
    """dpotrf n=16384 (BASELINE config #2; reference ex07 / test_posv).

    Times the framework's potrf XLA target (linalg/chol.py: tril(cholesky(A))).
    The loop body perturbs the diagonal with a value data-dependent on the
    previous factor so XLA cannot collapse the chain."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = 4096 if cpu_fallback else 16384
    key = jax.random.PRNGKey(0)
    m = jax.random.normal(key, (n, n), dtype=jnp.float32) / jnp.sqrt(
        jnp.asarray(n, jnp.float32))
    a = jnp.matmul(m, m.T, precision=lax.Precision.HIGHEST) + 2.0 * jnp.eye(
        n, dtype=jnp.float32)

    import slate_tpu

    # the blocked Tiled target: XLA's fused Cholesky serializes its internal
    # panel steps and crawls at large n on TPU; the framework's right-looking
    # blocked factorization keeps the trailing updates as big MXU gemms —
    # the reason SLATE-style blocking exists (potrf.cc:84-195).
    # BENCH_POTRF_NB overrides for on-chip block-size sweeps;
    # BENCH_POTRF_INVTRSM=1 selects the inverse-apply panel variant
    # (Options.trsm_via_inverse) and marks the metric accordingly so the
    # sweep rows never conflate with the true-trsm baseline.
    import os as _os
    inv = _os.environ.get("BENCH_POTRF_INVTRSM") == "1"
    opts = {"target": "tiled",
            "block_size": int(_os.environ.get("BENCH_POTRF_NB", 2048)),
            "trsm_via_inverse": inv}

    def body(i, c, a):
        ap = a + (1e-6 * c[0, 0]) * jnp.eye(n, dtype=a.dtype)
        return slate_tpu.potrf(ap, opts=opts)[0]

    gflops, per_iter, info = _chain_rate(body, a, (a,), 1, 3, n**3 / 3.0,
                                         repeats=2)
    tag = "_invtrsm" if inv else ""
    _emit({"metric": f"potrf{tag}_f32_n{n}_gflops", "value": round(gflops, 1),
           "unit": "GFLOP/s", "n": n, "sec_per_call": per_iter, **info})


def child_getrf(cpu_fallback, panel=None):
    """dgetrf (BASELINE config #3; reference test_gesv). Partial-pivot LU via the
    framework's getrf XLA target (linalg/lu.py: lax.linalg.lu).

    ``panel`` pins Options.lu_panel for the first-class A/B configs
    ("getrf" = tournament, "getrf_pp" = pp); the BENCH_GETRF_PANEL env knob
    remains for ad-hoc sweeps."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = 4096 if cpu_fallback else 16384
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), dtype=jnp.float32)

    import slate_tpu

    # tournament pivoting (getrf_tntpiv): partial-pivot via the fused
    # lax.linalg.lu provably does not finish a single n=16384 call on the
    # tunnel within the config budget, while CALU keeps the panel work as
    # sorts+gemms — the SURVEY §7 prediction that tournament pivoting is the
    # better-fit default on TPU
    # BENCH_GETRF_NB / BENCH_GETRF_IB override the outer/inner blocking for
    # on-chip sweeps (VERDICT r2 next-step #2 asks for nb in {256,512,1024})
    import os as _os
    panel = panel or _os.environ.get("BENCH_GETRF_PANEL", "tournament")
    # ib defaults to nb (FLAT panel): the round-6 bisection of the r5 getrf
    # regression (5,493 vs the 6,364-6,795 LKG) landed on the r3 two-level
    # split — cost_analysis at the scaled shape shows ib=nb/8 costs 2.96x
    # the bytes accessed of the flat panel for an 11% flop saving
    # (BENCH_NOTES.md round 6).  The LKG configuration is the flat panel;
    # two-level stays available as the BENCH_GETRF_IB sweep knob.
    nb_ = int(_os.environ.get("BENCH_GETRF_NB", 2048))
    opts = {"method_lu": "calu", "lu_panel": panel,
            "block_size": nb_,
            "inner_blocking": int(_os.environ.get("BENCH_GETRF_IB", nb_))}

    def body(i, c, a):
        ap = a + (1e-6 * c[0, 0]) * jnp.eye(n, dtype=a.dtype)
        return slate_tpu.getrf(ap, opts=opts)[0]

    gflops, per_iter, info = _chain_rate(body, a, (a,), 1, 3, 2.0 * n**3 / 3.0,
                                         repeats=2)
    tag = "" if panel == "tournament" else f"_{panel}"
    _emit({"metric": f"getrf_calu{tag}_f32_n{n}_gflops",
           "value": round(gflops, 1),
           "unit": "GFLOP/s", "n": n, "sec_per_call": per_iter, **info})


def child_gels(cpu_fallback):
    """Tall-skinny least squares m=131072 n=4096, CholQR path (BASELINE config
    #4; reference test_gels). Times the framework's jittable cholqr2 + solve
    (linalg/qr.py). Rate uses the Householder QR job model 2n^2(m - n/3) so it
    is comparable with the reference's dgeqrf/dgels rate for the same problem."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    m, n = (16384, 512) if cpu_fallback else (131072, 4096)
    nrhs = 16
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (m, n), dtype=jnp.float32)
    b = jax.random.normal(jax.random.fold_in(key, 1), (m, nrhs), dtype=jnp.float32)

    import slate_tpu

    def body(i, bc, a):
        # the framework's CSNE least-squares path (linalg/qr.py gels_cholqr).
        # A must be perturbed by the carry: with a loop-invariant A, XLA
        # hoists the entire O(m n^2) factorization out of the fori_loop and
        # the chain delta times only the thin RHS solve (observed: t(k=3) -
        # t(k=1) = 0.02 s for a 0.65 s job)
        ap = a + 1e-7 * bc[0, 0]
        X = slate_tpu.gels_cholqr(ap, bc)
        return bc + 1e-6 * X[0, 0]

    flops = 2.0 * n * n * (m - n / 3.0) + 4.0 * m * n * nrhs
    gflops, sec, info = _chain_rate(body, b, (a,), 1, 3, flops, repeats=2)
    _emit({"metric": f"gels_cholqr_f32_{m}x{n}_gflops", "value": round(gflops, 1),
           "unit": "GFLOP/s", "m": m, "n": n, "sec_per_call": sec, **info})


def child_heev(cpu_fallback):
    """Hermitian eigenvalues at BASELINE scale (config #5a: the n=20,000-class
    problem; reference test_heev). Times the framework's heev values driver
    (linalg/eig.py default = fused XLA eigh — QDWH spectral D&C, all-matmul).
    Model: 4n^3/3 (tridiagonal reduction dominates)."""
    import jax
    import jax.numpy as jnp

    n = 1024 if cpu_fallback else 16384
    key = jax.random.PRNGKey(0)
    m = jax.random.normal(key, (n, n), dtype=jnp.float32)
    a = (m + m.T) / 2.0

    import slate_tpu

    def body(i, c, a):
        ap = a + (1e-6 * c[0]) * jnp.eye(n, dtype=a.dtype)
        lam = slate_tpu.heev(ap, uplo="lower", want_vectors=False)[0]
        return c + 1e-6 * lam

    c0 = jnp.zeros((n,), jnp.float32)
    gflops, sec, info = _chain_rate(body, c0, (a,), 1, 2, 4.0 * n**3 / 3.0,
                                    repeats=2)
    _emit({"metric": f"heev_vals_f32_n{n}_gflops", "value": round(gflops, 1),
           "unit": "GFLOP/s", "n": n, "sec_per_call": sec, **info})


def child_svd(cpu_fallback):
    """Singular values at BASELINE scale (config #5b: the n=20,000-class
    problem; reference test_svd). Times the framework's svd_vals path
    (linalg/svd.py). Model: 8n^3/3."""
    import jax
    import jax.numpy as jnp

    n = 1024 if cpu_fallback else 16384
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), dtype=jnp.float32)

    import slate_tpu

    def body(i, c, a):
        ap = a + (1e-6 * c[0]) * jnp.eye(n, dtype=a.dtype)
        s = slate_tpu.svd_vals(ap)
        return c + 1e-6 * s

    c0 = jnp.zeros((n,), jnp.float32)
    gflops, sec, info = _chain_rate(body, c0, (a,), 1, 2, 8.0 * n**3 / 3.0,
                                    repeats=2)
    _emit({"metric": f"svd_vals_f32_n{n}_gflops", "value": round(gflops, 1),
           "unit": "GFLOP/s", "n": n, "sec_per_call": sec, **info})


def child_norm(cpu_fallback):
    """General-matrix norms n=16384 via the Pallas streaming kernels
    (reference device_genorm.cu / test_genorm).  Bandwidth-bound: the metric
    is the Frobenius rate on the 2n^2 flop model (square + add per element);
    the one-norm runs in the same chain so both custom kernels execute on
    hardware.  vs_baseline compares against A100 fp64 dlange at HBM speed."""
    import jax
    import jax.numpy as jnp

    n = 4096 if cpu_fallback else 16384
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), dtype=jnp.float32)

    import slate_tpu

    # BENCH_NORM_IMPL=xla times the plain fused-XLA reduction instead of
    # the Pallas streaming kernels — the on-chip A/B for the 0.26x round-3
    # reading (if XLA's reduction already runs at bandwidth, the fix is a
    # routing default, not a kernel)
    tag = ""
    if os.environ.get("BENCH_NORM_IMPL", "").lower() == "xla":
        from slate_tpu.ops import norms as _norm_ops
        _norm_ops.USE_PALLAS = False
        tag = "_xla"

    def body(i, c, a):
        ap = a + c[0]                      # chain dependence: ~2 HBM passes
        f = slate_tpu.norm("fro", ap)      # 1 pass (Pallas streaming kernel)
        o = slate_tpu.norm("one", ap)      # 1 pass (reuse ap — no extra add)
        return c + 1e-9 * (f + o)

    c0 = jnp.zeros((1,), jnp.float32)
    ks, kl = (2, 6) if cpu_fallback else (4, 20)
    # traffic accounting (round-3 review: the old body did ~6 HBM passes per
    # iter while the metric modeled 2, understating the kernel ~3x): one iter
    # is ~4 same-cost bandwidth-bound passes (perturb copy 2, fro 1, one 1),
    # so the fro job (2n^2 flops over its 1 pass) is attributed 1/4 of the
    # iter time.  Exact pass count depends on XLA fusing the perturb-add
    # into the norm reads (then 3); the 1/4 attribution is the conservative
    # end, stated here so the number is interpretable.
    gflops, per_iter, info = _chain_rate(body, c0, (a,), ks, kl,
                                         4.0 * 2.0 * n * n)
    _emit({"metric": f"genorm_fro{tag}_f32_n{n}_gflops",
           "value": round(gflops, 1),
           "unit": "GFLOP/s", "n": n, "sec_per_call": per_iter,
           "note": "fro+one+perturb per iter (~4 passes); rate = fro model "
                   "over 1/4 iter time", **info})


def _direct_rate(run, make_input, fetch, flops, repeats=3):
    """GFLOP/s for drivers that are not chain-able (multi-call pipelines /
    internal while_loops): warm once, then time ``run`` on a freshly
    perturbed input each repeat, forcing with a one-element fetch.  The
    ~70 ms tunnel dispatch overhead is included, so rates are honest
    under-estimates for second-scale jobs.  Budget-aware like _chain_rate:
    repeats stop when the next call would not fit the soft deadline; with
    zero repeats the compile-inclusive warm time stands in (noted)."""
    info = {}
    t0 = time.perf_counter()
    fetch(run(make_input(0)))          # compile + warm
    warm = time.perf_counter() - t0
    ts = []
    for j in range(repeats):
        est = min(ts) if ts else warm
        if _budget_left() < 1.3 * est + 10:
            info["budget_truncated"] = (
                f"{len(ts)}/{repeats} repeats ran"
                + ("" if ts else "; rate is compile-inclusive warm time"))
            break
        x = make_input(j + 1)
        fetch(x)                       # materialize before the clock
        t0 = time.perf_counter()
        fetch(run(x))
        ts.append(time.perf_counter() - t0)
    sec = min(ts) if ts else warm
    return flops / sec / 1e9, sec, info


def child_potrf_la(cpu_fallback):
    """potrf through the explicit lookahead pipeline (parallel/pipeline.py,
    potrf.cc:136-177's overlap structure) on a 1-device grid — the
    single-chip analogue of potrf_distributed(lookahead>=2).  Same job and
    denominator as the 'potrf' config, so the two rows read as a direct
    schedule comparison (VERDICT r3 #2)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = 2048 if cpu_fallback else 16384
    key = jax.random.PRNGKey(0)
    m = jax.random.normal(key, (n, n), dtype=jnp.float32) / jnp.sqrt(
        jnp.asarray(n, jnp.float32))
    a = jnp.matmul(m, m.T, precision=lax.Precision.HIGHEST) + 2.0 * jnp.eye(
        n, dtype=jnp.float32)

    from slate_tpu.parallel.mesh import ProcessGrid
    from slate_tpu.parallel.pipeline import potrf_pipelined

    import os as _os
    nb = int(_os.environ.get("BENCH_POTRF_LA_NB", 2048))
    grid = ProcessGrid(1, 1)

    def make_input(j):
        return a + (1e-6 * j) * jnp.eye(n, dtype=a.dtype)

    gflops, sec, info = _direct_rate(
        lambda x: potrf_pipelined(x, grid, nb=nb),
        make_input, lambda r: float(r.ravel()[0]), n**3 / 3.0,
        repeats=2)
    _emit({"metric": f"potrf_lookahead_f32_n{n}_gflops",
           "value": round(gflops, 1), "unit": "GFLOP/s", "n": n, "nb": nb,
           "sec_per_call": sec, **info})


def child_f64gemm(cpu_fallback):
    """Emulated-f64 gemm n=4096 (ops/f64emu.py: exact Ozaki bf16 splitting,
    ~s(s+1)/2 = 28 MXU passes at s=7).  The one config whose vs_baseline is
    fp64-class against fp64 (A100 dgemm) with no precision crossing — the
    d-precision story VERDICT r3 #3 asked to measure, not just claim."""
    import jax
    import jax.numpy as jnp

    n = 1024 if cpu_fallback else 4096
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), dtype=jnp.float32)
    b = jax.random.normal(jax.random.fold_in(key, 1), (n, n), dtype=jnp.float32)
    scale = 1.0 / jnp.sqrt(jnp.asarray(n, jnp.float32))

    from slate_tpu.ops.f64emu import gemm_f64emu

    def body(i, c, b, scale):
        # the full job each iteration: split both operands, 28 bf16 passes,
        # hilo accumulate, collapse (alpha folds in exactly: power of two
        # only when n is a power of 4; the rounding is one f32 multiply)
        return gemm_f64emu(c, b, alpha=scale)

    ks, kl = (1, 3) if cpu_fallback else (2, 8)
    gflops, per_iter, info = _chain_rate(body, a, (b, scale), ks, kl,
                                         2.0 * n**3, repeats=2)
    _emit({"metric": f"gemm_f64emu_n{n}_gflops", "value": round(gflops, 1),
           "unit": "GFLOP/s", "n": n, "sec_per_call": per_iter,
           "note": "double-precision-class result (Ozaki s=7); honest fp64 "
                   "vs fp64 ratio", **info})


def child_gesvir(cpu_fallback):
    """gesv_f64ir n=4096: f32 LU factor + emulated-f64 iterative refinement
    to double-class forward error (ops/f64emu.py; the reference's dsgesv
    with the f64 refinement EMULATED).  Rate on the dgetrf 2n^3/3 model +
    the thin IR solves, vs A100 dgesv."""
    import jax
    import jax.numpy as jnp

    n = 1024 if cpu_fallback else 4096
    nrhs = 16
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), dtype=jnp.float32) + 2.0 * jnp.sqrt(
        jnp.asarray(n, jnp.float32)) * jnp.eye(n, dtype=jnp.float32)
    b = jax.random.normal(jax.random.fold_in(key, 1), (n, nrhs),
                          dtype=jnp.float32)

    from slate_tpu.ops.f64emu import gesv_f64ir

    def run(x):
        Xh, Xl, iters, info = gesv_f64ir(x, b)
        return Xh

    def make_input(j):
        return a + (1e-6 * j) * jnp.eye(n, dtype=a.dtype)

    flops = 2.0 * n**3 / 3.0 + 2.0 * n * n * nrhs
    gflops, sec, info = _direct_rate(run, make_input,
                                     lambda r: float(r.ravel()[0]), flops,
                                     repeats=2)
    _emit({"metric": f"gesv_f64ir_n{n}_gflops", "value": round(gflops, 1),
           "unit": "GFLOP/s", "n": n, "nrhs": nrhs, "sec_per_call": sec,
           "note": "double-class forward error on f32 hardware; one host "
                   "sync per solve (lax.while_loop IR)", **info})


def child_heev2s(cpu_fallback):
    """heev values via the SLATE-parity two-stage pipeline (he2hb -> hb2st ->
    Sturm/D&C, linalg/eig.py method='two_stage') at n=8192 — timed next to
    the fused-QDWH default so the method choice is data, not stance
    (VERDICT r3 #4)."""
    import jax
    import jax.numpy as jnp

    n = 512 if cpu_fallback else int(os.environ.get("BENCH_HEEV2S_N", 8192))
    key = jax.random.PRNGKey(0)
    m = jax.random.normal(key, (n, n), dtype=jnp.float32)
    a = (m + m.T) / 2.0

    import slate_tpu

    def run(x):
        # chase_pipeline: the multi-sweep batched chase (hb2st.cc's pass/step
        # concurrency) — the accelerator-shaped stage 2; the sequential
        # window form is for CPU (linalg/eig.py hb2st docstring)
        lam, _ = slate_tpu.heev(x, want_vectors=False, method="two_stage",
                                chase_pipeline=not cpu_fallback)
        return lam

    def make_input(j):
        return a + (1e-6 * j) * jnp.eye(n, dtype=a.dtype)

    gflops, sec, info = _direct_rate(run, make_input,
                                     lambda r: float(r.ravel()[0]),
                                     4.0 * n**3 / 3.0, repeats=2)

    # phase split (heev.cc:126-212's timer-level-2 analogue): time each
    # stage once through the shared Timers/phase_report machinery,
    # fetch-FORCED per stage so the spans are device time, not dispatch —
    # a single chip capture carries the he2hb / chase / tridiag breakdown
    # alongside the end-to-end rate, and the same map shape the tester
    # prints under --timers
    from slate_tpu.linalg.eig import hb2st, he2hb, sterf
    from slate_tpu.utils.trace import Timers, phase_report

    # the phase split costs roughly one more end-to-end run (plus compiles);
    # skip it rather than let the parent kill this child mid-RPC
    phases = {}
    if _budget_left() < 1.5 * sec + 60:
        phases["skipped"] = "insufficient budget after rate measurement"
    else:
        tm = Timers()
        with tm.time("he2hb"):
            band, Vs, Ts = he2hb(a)
            float(band.ravel()[0])
        with tm.time("hb2st"):
            d, e = hb2st(band, want_vectors=False, pipeline=not cpu_fallback)
            float(d.ravel()[0])
        with tm.time("sterf"):
            lam = sterf(d, e)
            float(lam.ravel()[0])
        phases = phase_report(tm)

    _emit({"metric": f"heev_two_stage_f32_n{n}_gflops",
           "value": round(gflops, 1), "unit": "GFLOP/s", "n": n,
           "sec_per_call": sec, "phases_first_call": phases, **info})


def child_svd2s(cpu_fallback):
    """Singular values via the SLATE-parity two-stage pipeline (ge2tb ->
    tb2bd -> Golub–Kahan bisection, linalg/svd.py method='two_stage') at
    n=8192 — timed next to the fused-QDWH default, with the ge2tb/tb2bd/
    bdsqr phase split in the record (svd.cc:270-304 timer analogue)."""
    import jax
    import jax.numpy as jnp

    n = 512 if cpu_fallback else int(os.environ.get("BENCH_SVD2S_N", 8192))
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), dtype=jnp.float32)

    import slate_tpu

    def run(x):
        S, _, _ = slate_tpu.svd(x, want_u=False, want_vt=False,
                                method="two_stage",
                                chase_pipeline=not cpu_fallback)
        return S

    def make_input(j):
        return a + (1e-6 * j) * jnp.eye(n, dtype=a.dtype)

    gflops, sec, info = _direct_rate(run, make_input,
                                     lambda r: float(r.ravel()[0]),
                                     8.0 * n**3 / 3.0, repeats=2)

    from slate_tpu.linalg.svd import bdsqr, ge2tb, tb2bd
    from slate_tpu.utils.trace import Timers, phase_report

    phases = {}
    if _budget_left() < 1.5 * sec + 60:
        phases["skipped"] = "insufficient budget after rate measurement"
    else:
        tm = Timers()
        with tm.time("ge2tb"):
            d, e, _, _ = ge2tb(a, chase_pipeline=not cpu_fallback)
            float(d.ravel()[0])
        with tm.time("bdsqr"):
            S, _, _ = bdsqr(d, e)
            float(S.ravel()[0])
        phases = phase_report(tm)

    _emit({"metric": f"svd_two_stage_f32_n{n}_gflops",
           "value": round(gflops, 1), "unit": "GFLOP/s", "n": n,
           "sec_per_call": sec, "phases_first_call": phases, **info})


def child_serve_mixed(cpu_fallback):
    """Mixed-traffic serving throughput (slate_tpu.serve; ROADMAP item 2's
    new bench axis): ≥1000 small gesv/posv/gels requests across ≥4 shape
    buckets through the async queue — solves/sec + p50/p99 latency, with
    batch-occupancy and cache hit-rate riding in the metrics blob _emit
    attaches.  Runs the same protocol on CPU and TPU (the problems are
    small; the axis is queue+cache throughput, not peak flops): warm-up
    compiles every (routine, bucket, batch-bucket) executable, then the
    measured pass must take zero cache misses."""
    from slate_tpu.serve.queue import BucketPolicy
    from slate_tpu.serve.workload import (run_continuous_ab,
                                          run_mixed_workload)

    stats = run_mixed_workload(num_requests=1200, seed=0)
    # continuous-batching A/B (ROADMAP 2(a)): interleaved flush-vs-
    # continuous rounds — queue_wait p50 at equal paced load plus the warm
    # throughput ratio and slot-join rate ride in the metric blob.  A
    # tight policy bounds the per-run warmup compile bill.
    ab = None
    if _budget_left() > 240:
        ab = run_continuous_ab(
            num_requests=300, seed=0, rounds=2, executors=2,
            dims=(8, 13),
            policy=BucketPolicy(dims=(16, 32), nrhs_dims=(1, 4),
                                batch_dims=(1, 4, 16), max_batch=16))
    _emit({"metric": "serve_mixed_solves_per_sec",
           "value": stats["solves_per_sec"], "unit": "solves/s",
           "requests": stats["requests"], "wall_s": stats["wall_s"],
           "p50_ms": stats["p50_ms"], "p99_ms": stats["p99_ms"],
           "distinct_buckets": stats["distinct_buckets"],
           "routines": stats["routines"],
           "misses_after_warmup": stats["misses_after_warmup"],
           "cache": stats["cache"], "warmup": stats["warmup"],
           "continuous_ab": ab})


def child_serve_scale(cpu_fallback):
    """Executor-pool scaling axis (multi-executor serving data path): the
    same warm mixed-traffic protocol as serve_mixed run at pool sizes
    N in {1, 2, 4} on one host.  Headline value is the N=2 warm rate
    (scored against the 2x serve_mixed denominator); the N=1/N=4 rates
    and the N2/N1 speedup ride along so regressions in routing, stealing,
    or the dispatch/resolve overlap show up as a trend break even when
    the absolute rate moves with the host."""
    from slate_tpu.serve.workload import run_scale_workload

    out = run_scale_workload(executor_counts=(1, 2, 4), num_requests=900,
                             seed=0)
    sps = out["solves_per_sec"]
    runs = out["runs"]
    # the continuous axis at N=2: same stream under rolling admission —
    # eager dispatch + staged merges/joins must hold the warm rate
    cont = None
    if _budget_left() > 120:
        cont = run_scale_workload(executor_counts=(2,), num_requests=900,
                                  seed=0, continuous=True)["runs"]["2"]
    _emit({"metric": "serve_scale_n2_solves_per_sec",
           "value": sps["2"], "unit": "solves/s",
           "solves_per_sec": sps,
           "n2_over_n1": round(sps["2"] / max(sps["1"], 1e-9), 3),
           "steals": {n: runs[n].get("steals", 0) for n in runs},
           "misses_after_warmup": {
               n: runs[n].get("misses_after_warmup") for n in runs},
           "p99_ms": {n: runs[n].get("p99_ms") for n in runs},
           "continuous_n2": None if cont is None else {
               "solves_per_sec": cont["solves_per_sec"],
               "slot_joins": cont.get("slot_joins"),
               "slot_join_rate": cont.get("slot_join_rate"),
               "queue_wait_p50_ms": cont.get("queue_wait_p50_ms"),
               "misses_after_warmup": cont.get("misses_after_warmup")}})


CHILDREN = {
    "probe": lambda cpu: child_probe(),
    "serve_mixed": child_serve_mixed,
    "serve_scale": child_serve_scale,
    "norm": child_norm,
    "gemm": child_gemm,
    "potrf": child_potrf,
    "getrf": child_getrf,
    "getrf_pp": lambda cpu: child_getrf(cpu, panel="pp"),
    "gels": child_gels,
    "heev": child_heev,
    "svd": child_svd,
    "potrf_la": child_potrf_la,
    "f64gemm": child_f64gemm,
    "gesvir": child_gesvir,
    "heev2s": child_heev2s,
    "svd2s": child_svd2s,
}


# ---------------------------------------------------------------------------
# parent — orchestration, retries, fallback; never imports jax
# ---------------------------------------------------------------------------


def _run_child(name, cpu_fallback, timeout):
    env = dict(os.environ)
    # variant A/B knobs are --child-only: a parent run must never record a
    # variant-tagged measurement into BENCH_LKG.json under the default
    # config key (it would be scored against the default baseline and
    # backfilled as the kernel's last-known-good)
    for knob in ("BENCH_NORM_IMPL", "BENCH_POTRF_INVTRSM",
                 "BENCH_GETRF_PANEL", "BENCH_HEEV2S_N", "BENCH_SVD2S_N"):
        env.pop(knob, None)
    # soft deadline 120 s inside the hard timeout: the child finishes (or
    # truncates) and exits on its own instead of being SIGKILLed mid-RPC,
    # which is what wedges the tunnel for every later child
    env["BENCH_CHILD_BUDGET_SEC"] = str(max(60, int(timeout) - 120))
    if cpu_fallback:
        # JAX_PLATFORMS=cpu alone is NOT enough: the ambient sitecustomize hook
        # registers the real-TPU 'axon' PJRT plugin and hangs on a wedged
        # tunnel.  PALLAS_AXON_POOL_IPS="" skips the plugin registration
        # entirely (same defense as tests/conftest.py's factory pop).
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["BENCH_CPU_FALLBACK"] = "1"
    t0 = time.time()
    try:
        p = subprocess.run([sys.executable, os.path.abspath(__file__),
                            "--child", name],
                           capture_output=True, text=True, timeout=timeout,
                           env=env, cwd=REPO)
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": "timeout", "elapsed": time.time() - t0}
    elapsed = time.time() - t0
    lines = [ln for ln in p.stdout.strip().splitlines() if ln.strip()]
    if p.returncode == 0 and lines:
        try:
            out = json.loads(lines[-1])
            out.update({"ok": True, "elapsed": elapsed})
            return out
        except json.JSONDecodeError:
            pass
    return {"ok": False, "error": f"rc={p.returncode}",
            "stderr_tail": p.stderr[-2000:], "elapsed": elapsed}


def _load_lkg():
    try:
        with open(LKG_PATH) as f:
            lkg = json.load(f)
        return lkg if isinstance(lkg, dict) else {}
    except (OSError, json.JSONDecodeError):
        return {}


def _save_lkg(lkg):
    try:
        with open(LKG_PATH, "w") as f:
            json.dump(lkg, f, indent=1, sort_keys=True)
            f.write("\n")
    except OSError:
        pass


def main(only=None):
    configs = [c for c in CONFIGS if not only or c in only]
    t_start = time.time()
    deadline = t_start + float(os.environ.get("BENCH_DEADLINE_SEC", 4200))
    detail = {"attempts": [], "configs": {}, "backend": None}
    if only:
        # subset runs refresh their own configs in BENCH_DETAIL.json without
        # dropping the others' recorded history
        try:
            with open(DETAIL_PATH) as f:
                detail["configs"] = json.load(f).get("configs", {})
        except (OSError, json.JSONDecodeError, AttributeError):
            pass
    lkg = _load_lkg()

    # 1) probe the TPU backend with bounded retries (fresh process each try).
    #    Staged timeouts: a healthy tunnel answers a probe in well under 90 s,
    #    so the cheap attempts come first and a wedged tunnel costs minutes,
    #    not 3x420 s (the round-2 failure mode).
    probe = None
    for attempt, probe_timeout in enumerate((90, 240, 420)):
        probe = _run_child("probe", cpu_fallback=False, timeout=probe_timeout)
        detail["attempts"].append({"config": "probe", "attempt": attempt, **probe})
        if probe.get("ok"):
            break
        time.sleep(15)
    # an accelerator probe that lands on the CPU backend is NOT a live TPU —
    # running TPU-sized configs there would just burn the timeouts
    tpu_up = bool(probe and probe.get("ok")
                  and probe.get("platform") not in (None, "cpu"))
    detail["backend"] = probe.get("platform", "unknown") if tpu_up else "cpu-fallback"

    # 2) run each config; on TPU allow one retry for transient tunnel errors,
    #    then fall back to CPU so a number exists either way
    for name in configs:
        budget = deadline - time.time()
        if budget < 60:
            detail["configs"][name] = {"ok": False, "error": "global deadline"}
            continue
        res = None
        cto = CONFIG_TIMEOUTS.get(name, 900)
        if tpu_up:
            for attempt in range(2):
                res = _run_child(name, cpu_fallback=False,
                                 timeout=min(cto, max(120, budget)))
                detail["attempts"].append({"config": name, "attempt": attempt, **res})
                if res.get("ok"):
                    break
                # a killed child may have wedged the tunnel; re-probe before
                # spending more TPU budget (a dead tunnel hangs, not errors)
                reprobe = _run_child("probe", cpu_fallback=False, timeout=180)
                detail["attempts"].append({"config": "reprobe", **reprobe})
                if not (reprobe.get("ok")
                        and reprobe.get("platform") not in (None, "cpu")):
                    tpu_up = False
                    detail["backend"] = "cpu-fallback (tunnel lost)"
                    break
                time.sleep(10)
        if not (res and res.get("ok")):
            res = _run_child(name, cpu_fallback=True,
                             timeout=min(900, max(120, deadline - time.time())))
            res["backend"] = "cpu-fallback"
            detail["attempts"].append({"config": name, "attempt": "cpu", **res})
        else:
            res["backend"] = detail["backend"]
        if res.get("ok") and isinstance(res.get("value"), (int, float)):
            res["vs_baseline"] = round(res["value"] / BASELINES[name], 3)
        detail["configs"][name] = res
        # persist every fresh TPU-backend success immediately: a later child
        # wedging the tunnel (or the process dying) must not lose it
        if (res.get("ok") and isinstance(res.get("value"), (int, float))
                and res.get("backend") not in (None, "cpu-fallback")
                and not str(res.get("backend", "")).startswith("cpu")):
            lkg[name] = {
                "metric": res.get("metric"), "value": res.get("value"),
                "unit": res.get("unit"), "vs_baseline": res.get("vs_baseline"),
                "baseline": BASELINES.get(name),
                "backend": res.get("backend"),
                "sec_per_call": res.get("sec_per_call"),
                "recorded_unix": round(time.time(), 1),
                "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            }
            _save_lkg(lkg)

    try:
        with open(DETAIL_PATH, "w") as f:
            json.dump(detail, f, indent=1, default=str)
    except OSError:
        pass

    # 3) the ONE json line: headline gemm + nested per-config summary.
    #    Configs with a fresh TPU number report source=fresh; configs that
    #    failed or fell back to CPU are backfilled from the last-known-good
    #    file (source=cached + timestamp) so the artifact always carries
    #    hardware numbers once any run has recorded them.
    # summarize ALL configs regardless of --only: un-run configs backfill from
    # the last-known-good file, so the one-line artifact (headline included)
    # never shrinks or nulls out because of a subset run
    summary = {}
    for name in CONFIGS:
        res = detail["configs"].get(name, {})
        # preloaded entries from a prior run's BENCH_DETAIL.json are never
        # "fresh" — only configs actually run this session qualify; the rest
        # backfill from the LKG file with their recorded timestamp
        fresh_tpu = (name in configs and res.get("ok")
                     and res.get("backend") not in (None, "cpu-fallback")
                     and not str(res.get("backend", "")).startswith("cpu"))
        if fresh_tpu:
            summary[name] = {"metric": res.get("metric"), "value": res.get("value"),
                             "vs_baseline": res.get("vs_baseline"),
                             "backend": res.get("backend"), "source": "fresh"}
        elif name in lkg:
            c = lkg[name]
            summary[name] = {"metric": c.get("metric"), "value": c.get("value"),
                             "vs_baseline": c.get("vs_baseline"),
                             "backend": c.get("backend"), "source": "cached",
                             "cached_from": c.get("recorded_at")}
            # a cached vs_baseline divides by the denominator in force when
            # it was recorded; flag it when BASELINES has since moved (e.g.
            # the heev/svd configs were re-scaled this round) so readers do
            # not compare incomparable ratios
            if c.get("provenance"):
                summary[name]["provenance"] = c["provenance"]
            if c.get("baseline") is not None \
                    and c.get("baseline") != BASELINES.get(name) \
                    and isinstance(c.get("value"), (int, float)):
                if c.get("size_mismatch"):
                    # the cached value was measured at a DIFFERENT problem
                    # size than the current config (e.g. the round-2 svd
                    # n=4096 capture vs today's n=16384 config): dividing it
                    # by the current denominator would present a
                    # cross-problem-size ratio as the current reading.  Keep
                    # the ratio null and let the flag + provenance tell the
                    # story until a fresh same-size capture replaces it.
                    summary[name]["vs_baseline"] = None
                else:
                    # same job, re-estimated denominator: RENORMALIZE — the
                    # reported ratio must be the honest current reading, the
                    # recorded one is side info (VERDICT r3 weak-#2: a flag
                    # alone let the stale 1.131 read as the headline while
                    # current=0.57)
                    summary[name]["vs_baseline"] = round(
                        c["value"] / BASELINES[name], 3)
                summary[name]["baseline_changed"] = {
                    "recorded": c.get("baseline"),
                    "recorded_ratio": c.get("vs_baseline"),
                    "current": BASELINES.get(name)}
            if res.get("ok"):   # CPU-fallback number, kept as side info
                summary[name]["cpu_fallback_value"] = res.get("value")
            elif res.get("error"):
                summary[name]["fresh_error"] = res.get("error")
        elif res.get("ok"):
            # CPU-fallback number with no TPU history: NOT hardware evidence
            summary[name] = {"metric": res.get("metric"), "value": res.get("value"),
                             "vs_baseline": res.get("vs_baseline"),
                             "backend": res.get("backend"), "source": "cpu-only"}
        else:
            summary[name] = {"error": res.get("error")}
    head = summary.get(HEADLINE, {})
    any_tpu = any(v.get("backend") not in (None, "cpu-fallback")
                  and not str(v.get("backend", "")).startswith("cpu")
                  for v in summary.values() if isinstance(v, dict))
    # full nested summary goes to a file; the printed line stays COMPACT.
    # Round-4 lesson (VERDICT weak-#7): the driver tails stdout and the
    # multi-KB nested line truncated into an unparseable artifact
    # ("parsed": null), so the terminal line now carries only the headline
    # plus a [value, ratio, source] triple per config (<1 KB total).
    summary_ref = "BENCH_SUMMARY.json"
    try:
        with open(os.path.join(REPO, "BENCH_SUMMARY.json"), "w") as f:
            json.dump({"headline": HEADLINE, "tpu_evidence": any_tpu,
                       "backend": detail["backend"], "configs": summary},
                      f, indent=1, default=str)
            f.write("\n")
    except OSError:
        # the pointer must not claim a file this run failed to write — a
        # stale previous summary would read as current
        summary_ref = "unwritten (OSError); see stdout line only"
    compact = {}
    for name, v in summary.items():
        if isinstance(v.get("value"), (int, float)):
            src = {"fresh": "fresh", "cached": "cached",
                   "cpu-only": "cpu"}.get(v.get("source"), "?")
            compact[name] = [v.get("value"), v.get("vs_baseline"), src]
        else:
            compact[name] = [None, None, "error"]
    print(json.dumps({
        "metric": head.get("metric", "gemm_f32hi_n4096_gflops"),
        "value": head.get("value"),
        "unit": "GFLOP/s",
        "vs_baseline": head.get("vs_baseline"),
        "backend": head.get("backend", detail["backend"]),
        "source": head.get("source"),
        "tpu_evidence": any_tpu,
        "configs": compact,
        "detail": summary_ref,
    }))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", default=None)
    ap.add_argument("--only", default=None,
                    help="comma-separated subset of configs to run")
    ns = ap.parse_args()
    if ns.child:
        cpu_fb = os.environ.get("BENCH_CPU_FALLBACK") == "1"
        budget = os.environ.get("BENCH_CHILD_BUDGET_SEC")
        if budget:
            _CHILD_DEADLINE = time.time() + float(budget)
        CHILDREN[ns.child](cpu_fb)
    else:
        if ns.only:
            sel = set(ns.only.split(","))
            unknown = sel - set(CONFIGS)
            if unknown:
                sys.exit(f"unknown configs {sorted(unknown)}; valid: {CONFIGS}")
            main(only=sel)
        else:
            main()
