/* ex05: BLAS through the C API (reference examples/c_api/ex05_blas.c is the
 * same exercise against slate's C API).  C = alpha A B + beta C with a
 * residual check against a naive triple loop. */

#include <math.h>
#include <stdio.h>
#include <stdlib.h>

#include "slate_tpu.h"

int main(void) {
    const int64_t m = 37, n = 29, k = 41;
    double *A = malloc(m * k * sizeof(double));
    double *B = malloc(k * n * sizeof(double));
    double *C = malloc(m * n * sizeof(double));
    double *R = malloc(m * n * sizeof(double));
    const double alpha = 1.5, beta = -0.5;

    if (slate_init() != 0) {
        fprintf(stderr, "slate_init failed\n");
        return 1;
    }

    /* column-major fill, like every LAPACK-convention caller */
    unsigned s = 12345;
    for (int64_t i = 0; i < m * k; ++i) A[i] = (double)(s = s * 1103515245u + 12345u) / 4.3e9 - 0.5;
    for (int64_t i = 0; i < k * n; ++i) B[i] = (double)(s = s * 1103515245u + 12345u) / 4.3e9 - 0.5;
    for (int64_t i = 0; i < m * n; ++i) C[i] = R[i] = (double)(s = s * 1103515245u + 12345u) / 4.3e9 - 0.5;

    int info = slate_dgemm('n', 'n', m, n, k, alpha, A, m, B, k, beta, C, m);
    if (info != 0) {
        fprintf(stderr, "slate_dgemm info=%d\n", info);
        return 1;
    }

    /* naive reference */
    double err = 0.0;
    for (int64_t j = 0; j < n; ++j) {
        for (int64_t i = 0; i < m; ++i) {
            double acc = beta * R[i + j * m];
            for (int64_t p = 0; p < k; ++p)
                acc += alpha * A[i + p * m] * B[p + j * k];
            double d = fabs(acc - C[i + j * m]);
            if (d > err) err = d;
        }
    }
    printf("ex05 gemm max err = %.3e\n", err);
    slate_finalize();
    free(A); free(B); free(C); free(R);
    if (err > 1e-10) {
        fprintf(stderr, "ex05 FAILED\n");
        return 1;
    }
    printf("ex05 OK\n");
    return 0;
}
