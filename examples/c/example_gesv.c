/* C API smoke example (reference examples/c): solve A X = B through the
 * embedded slate_tpu runtime and verify the residual.
 *
 * Build (from repo root):
 *   make -C native libslate_c_api.so
 *   cc examples/c/example_gesv.c -Iinclude -Lnative -lslate_c_api \
 *      -Wl,-rpath,$PWD/native -o example_gesv
 *   SLATE_TPU_ROOT=$PWD JAX_PLATFORMS=cpu ./example_gesv
 */

#include <math.h>
#include <stdio.h>
#include <stdlib.h>

#include "slate_tpu.h"

int main(void) {
  const int64_t n = 24, nrhs = 2;
  double *A = malloc(n * n * sizeof(double));
  double *Asave = malloc(n * n * sizeof(double));
  double *B = malloc(n * nrhs * sizeof(double));
  double *Bsave = malloc(n * nrhs * sizeof(double));
  int64_t *ipiv = malloc(n * sizeof(int64_t));

  srand(7);
  for (int64_t j = 0; j < n; ++j)
    for (int64_t i = 0; i < n; ++i)
      Asave[i + j * n] = A[i + j * n] =
          (double)rand() / RAND_MAX - 0.5 + (i == j ? n : 0);
  for (int64_t j = 0; j < nrhs; ++j)
    for (int64_t i = 0; i < n; ++i)
      Bsave[i + j * n] = B[i + j * n] = (double)rand() / RAND_MAX - 0.5;

  int info = slate_dgesv(n, nrhs, A, n, ipiv, B, n);
  if (info != 0) {
    fprintf(stderr, "slate_dgesv info=%d\n", info);
    return 1;
  }

  /* residual ||A X - B||_max against the saved operands */
  double maxres = 0.0;
  for (int64_t j = 0; j < nrhs; ++j)
    for (int64_t i = 0; i < n; ++i) {
      double acc = 0.0;
      for (int64_t k = 0; k < n; ++k) acc += Asave[i + k * n] * B[k + j * n];
      double r = fabs(acc - Bsave[i + j * n]);
      if (r > maxres) maxres = r;
    }
  printf("gesv residual: %.3e\n", maxres);

  double nrm = slate_dlange('f', n, n, Asave, n);
  printf("lange fro: %.6f\n", nrm);

  slate_finalize();
  if (maxres > 1e-8) {
    fprintf(stderr, "FAIL residual\n");
    return 1;
  }
  printf("PASS\n");
  return 0;
}
