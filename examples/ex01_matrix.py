"""ex01: creating matrices — ctors, from_array, typed variants, tile metadata
(≅ examples/ex01_matrix.cc)."""

import numpy as np

import slate_tpu as slate


def main():
    # empty distributed matrix: m x n, tile nb, p x q grid
    A = slate.Matrix(512, 384, nb=128, p=2, q=2)
    print(f"A: {A.m}x{A.n}, tiles {A.mt}x{A.nt} of {A.mb}x{A.nb}, "
          f"grid {A.gridinfo()}")
    assert (A.mt, A.nt) == (4, 3)

    # wrap existing data (fromLAPACK analogue — adopted, not copied)
    a = np.arange(36, dtype=np.float32).reshape(6, 6)
    B = slate.Matrix.from_array(a, nb=2)
    assert B.tileMb(2) == 2 and float(B.tile(1, 1)[0, 0]) == a[2, 2]

    # typed variants share the same storage design
    H = slate.HermitianMatrix.from_array(slate.Uplo.Lower, a @ a.T, nb=3)
    T = slate.TriangularMatrix.from_array(slate.Uplo.Upper, a, nb=3)
    S = slate.SymmetricMatrix.from_array(slate.Uplo.Lower, a + a.T, nb=3)
    print("typed:", type(H).__name__, type(T).__name__, type(S).__name__)

    # tile ownership on a 2x2 grid
    G = slate.Matrix(8 * 64, 8 * 64, nb=64, p=2, q=2)
    print("owner map:\n", G.owner_map())
    assert G.owner_map().shape == (8, 8)
    print("ex01 OK")


if __name__ == "__main__":
    main()
