"""ex02: converting between matrix types — general <-> hermitian/triangular views
(≅ examples/ex02_conversion.cc)."""

import numpy as np

import slate_tpu as slate


def main():
    a = np.random.default_rng(0).standard_normal((8, 8)).astype(np.float32)
    A = slate.Matrix.from_array(a, nb=4)

    # view the lower triangle as Hermitian / the upper as triangular, no copy
    H = slate.HermitianMatrix.from_array(slate.Uplo.Lower, np.asarray(A.array), nb=4)
    full = np.asarray(H.full_array())
    np.testing.assert_allclose(full, np.tril(a) + np.tril(a, -1).T)

    T = slate.TriangularMatrix.from_array(slate.Uplo.Upper, a, nb=4)
    np.testing.assert_allclose(np.asarray(T.masked_array()), np.triu(a))

    # transpose is a flag flip (Tile.hh:40-52) — same storage
    At = A.T
    assert At.m == A.n and float(At.tile(0, 0)[1, 0]) == a[0, 1]
    print("ex02 OK")


if __name__ == "__main__":
    main()
