"""ex03: sub-matrices and slices — cheap views sharing storage
(≅ examples/ex03_submatrix.cc)."""

import numpy as np

import slate_tpu as slate


def main():
    a = np.arange(64, dtype=np.float32).reshape(8, 8)
    A = slate.Matrix.from_array(a, nb=2)

    # tile-aligned sub-matrix: tiles [1..2] x [0..1]  (BaseMatrix.hh:104-106)
    S = A.sub(1, 2, 0, 1)
    np.testing.assert_array_equal(np.asarray(S.array), a[2:6, 0:4])

    # element slice at arbitrary offsets (BaseMatrix.hh:110-121)
    L = A.slice(3, 6, 1, 4)
    np.testing.assert_array_equal(np.asarray(L.array), a[3:7, 1:5])

    # writes through a view land in the shared storage
    S.set_array(np.zeros((4, 4), np.float32))
    assert not np.asarray(A.array)[2:6, 0:4].any()
    print("ex03 OK")


if __name__ == "__main__":
    main()
