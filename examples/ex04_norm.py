"""ex04: matrix norms — max/one/inf/fro over general/hermitian/triangular
(≅ examples/ex04_norm.cc).  On TPU these stream through the Pallas kernels."""

import numpy as np

import slate_tpu as slate


def main():
    a = np.random.default_rng(1).standard_normal((200, 150)).astype(np.float32)
    A = slate.Matrix.from_array(a, nb=64)
    for which, ref in [("max", np.abs(a).max()), ("one", np.abs(a).sum(0).max()),
                       ("inf", np.abs(a).sum(1).max()), ("fro", np.linalg.norm(a))]:
        v = float(slate.norm(which, A))
        print(f"norm {which}: {v:.4f} (numpy {ref:.4f})")
        assert abs(v - ref) < 1e-2 * max(1.0, ref)

    # column-scope (colNorms)
    cn = np.asarray(slate.col_norms("max", A))
    np.testing.assert_allclose(cn, np.abs(a).max(0), rtol=1e-5)

    # hermitian norm from the stored triangle only
    h = a[:150] + a[:150].T
    H = slate.HermitianMatrix.from_array(slate.Uplo.Lower, h, nb=64)
    assert abs(float(slate.norm("one", H)) - np.abs(h).sum(0).max()) < 1e-2
    print("ex04 OK")


if __name__ == "__main__":
    main()
