"""ex05: parallel BLAS-3 — gemm / hemm / herk / trsm (≅ examples/ex05_blas.cc,
one of the BASELINE configs)."""

import numpy as np

import slate_tpu as slate


def main():
    r = np.random.default_rng(2)
    n = 256
    a = r.standard_normal((n, n)).astype(np.float32)
    b = r.standard_normal((n, n)).astype(np.float32)
    c = r.standard_normal((n, n)).astype(np.float32)

    C = slate.Matrix.from_array(c.copy(), nb=64)
    slate.gemm(1.0, slate.Matrix.from_array(a, nb=64),
               slate.Matrix.from_array(b, nb=64), 0.5, C)
    np.testing.assert_allclose(np.asarray(C.array), a @ b + 0.5 * c, rtol=1e-3,
                               atol=1e-3)

    # herk updates only the stored triangle
    H = slate.HermitianMatrix.from_array(slate.Uplo.Lower, (a @ a.T), nb=64)
    slate.herk(1.0, slate.Matrix.from_array(b, nb=64), 1.0, H)
    np.testing.assert_allclose(np.asarray(H.full_array()), a @ a.T + b @ b.T,
                               rtol=1e-2, atol=1e-2)

    # triangular solve
    t = np.tril(a) + n * np.eye(n, dtype=np.float32)
    B = slate.Matrix.from_array(b.copy(), nb=64)
    slate.trsm("left", 1.0, slate.TriangularMatrix.from_array(slate.Uplo.Lower, t, nb=64), B)
    np.testing.assert_allclose(t @ np.asarray(B.array), b, rtol=1e-3, atol=1e-3)
    print("ex05 OK")


if __name__ == "__main__":
    main()
