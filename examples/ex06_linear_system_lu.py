"""ex06: LU linear systems — gesv, factor/solve split, tournament pivoting, RBT
(≅ examples/ex06_linear_system_lu.cc)."""

import numpy as np

import slate_tpu as slate


def main():
    r = np.random.default_rng(3)
    n = 128
    a = r.standard_normal((n, n)).astype(np.float32) + n * np.eye(n, dtype=np.float32)
    b = r.standard_normal((n, 4)).astype(np.float32)

    X, perm, info = slate.gesv(a.copy(), b.copy())
    assert int(info) == 0
    print("gesv resid:", np.linalg.norm(a @ np.asarray(X) - b))

    # factor once, solve twice (getrf + getrs)
    lu_, perm, info = slate.getrf(a.copy())
    x1 = slate.getrs(lu_, perm, b.copy())
    x2 = slate.getrs(lu_, perm, (2 * b).copy())
    np.testing.assert_allclose(np.asarray(x2), 2 * np.asarray(x1), rtol=1e-4)

    # communication-avoiding tournament pivoting (CALU)
    lu2, perm2, info2 = slate.getrf_tntpiv(a.copy())
    x3 = slate.getrs(lu2, perm2, b.copy())
    assert np.linalg.norm(a @ np.asarray(x3) - b) < 1e-2

    # random butterfly transform avoids pivoting entirely
    out = slate.gesv_rbt(a.copy(), b[:, :1].copy())
    assert np.linalg.norm(a @ np.asarray(out[0]) - b[:, :1]) < 1e-2
    print("ex06 OK")


if __name__ == "__main__":
    main()
