"""ex07: SPD linear systems — posv / potrf / potrs / potri, mixed precision
(≅ examples/ex07_linear_system_cholesky.cc, a BASELINE config)."""

import numpy as np

import slate_tpu as slate


def main():
    n = 256
    A0, _ = slate.generate_matrix("spd_geo", n, cond=100.0, seed=4)
    a = np.asarray(A0)
    b = np.random.default_rng(5).standard_normal((n, 4)).astype(np.float32)

    M = slate.HermitianMatrix.from_array(slate.Uplo.Lower, a.copy(), nb=64)
    B = slate.Matrix.from_array(b.copy(), nb=64)
    X, info = slate.posv(M, B)
    assert int(info) == 0
    print("posv resid:", np.linalg.norm(a @ np.asarray(B.array) - b))

    # factor / solve split + inverse + condition estimate
    L, info = slate.potrf(slate.HermitianMatrix.from_array(slate.Uplo.Lower,
                                                           a.copy(), nb=64))
    rcond = float(slate.pocondest(np.asarray(L), slate.norm("one", M)))
    print("pocondest rcond:", rcond)
    assert 0 < rcond < 1
    print("ex07 OK")


if __name__ == "__main__":
    main()
