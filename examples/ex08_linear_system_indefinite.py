"""ex08: Hermitian-indefinite systems — hesv/hetrf/hetrs Aasen factorization
(≅ examples/ex08_linear_system_indefinite.cc)."""

import numpy as np

import slate_tpu as slate


def main():
    n = 96
    A0, S = slate.generate_matrix("heev_geo", n, cond=50.0, seed=6)  # mixed signs
    a = np.asarray(A0)
    assert (np.asarray(S) < 0).any()     # genuinely indefinite
    b = np.random.default_rng(7).standard_normal((n, 2)).astype(np.float32)

    out = slate.hesv(a.copy(), b.copy(), None)
    x = np.asarray(out[0])
    print("hesv resid:", np.linalg.norm(a @ x - b))
    assert np.linalg.norm(a @ x - b) / np.linalg.norm(b) < 1e-3

    # factor once / solve many (hetrf + hetrs)
    fac, info = slate.hetrf(a.copy())
    x2 = slate.hetrs(fac, b.copy())
    np.testing.assert_allclose(np.asarray(x2), x, rtol=1e-3, atol=1e-4)
    print("ex08 OK")


if __name__ == "__main__":
    main()
