"""ex09: least squares — gels QR/CholQR, over- and under-determined
(≅ examples/ex09_least_squares.cc)."""

import numpy as np

import slate_tpu as slate


def main():
    r = np.random.default_rng(8)
    a = r.standard_normal((200, 40)).astype(np.float32)
    b = r.standard_normal((200, 2)).astype(np.float32)

    x = slate.gels(a.copy(), b.copy())
    expect, *_ = np.linalg.lstsq(a, b, rcond=None)
    np.testing.assert_allclose(np.asarray(x)[:40], expect, rtol=1e-2, atol=1e-3)

    x_qr = slate.gels_qr(a.copy(), b.copy())
    x_cq = slate.gels_cholqr(a.copy(), b.copy())
    np.testing.assert_allclose(np.asarray(x_qr)[:40], np.asarray(x_cq)[:40],
                               rtol=1e-2, atol=1e-3)

    # underdetermined: minimum-norm solution via LQ
    au = r.standard_normal((30, 80)).astype(np.float32)
    bu = r.standard_normal((30,)).astype(np.float32)
    xu = np.asarray(slate.gels(au.copy(), bu.copy()))
    assert np.linalg.norm(au @ xu - bu) / np.linalg.norm(bu) < 1e-3
    print("ex09 OK")


if __name__ == "__main__":
    main()
