"""ex10: singular value decomposition — values only and full factors, two-stage
scaffolding (≅ examples/ex10_svd.cc)."""

import numpy as np

import slate_tpu as slate


def main():
    n, cond = 96, 1e3
    A0, S = slate.generate_matrix("svd_logrand", n, cond=cond, seed=9)
    a = np.asarray(A0)

    vals = np.sort(np.asarray(slate.svd_vals(a)))[::-1]
    np.testing.assert_allclose(vals, np.sort(np.asarray(S))[::-1], rtol=1e-3)

    s, u, vt = slate.svd(a)
    recon = (np.asarray(u) * np.asarray(s)[None, :]) @ np.asarray(vt)
    print("svd recon err:", np.linalg.norm(recon - a) / np.linalg.norm(a))
    assert np.linalg.norm(recon - a) / np.linalg.norm(a) < 1e-4

    # the explicit two-stage pipeline (ge2tb -> tb2bd -> bdsqr)
    d, e, U1, VT1 = slate.ge2tb(a[:32, :24])
    sv2 = np.asarray(slate.bdsqr(d, e)[0])
    np.testing.assert_allclose(np.sort(sv2)[::-1],
                               np.linalg.svd(a[:32, :24], compute_uv=False),
                               rtol=1e-3)
    print("ex10 OK")


if __name__ == "__main__":
    main()
