"""ex11: Hermitian eigenproblem — heev values + vectors, two-stage pipeline
(≅ examples/ex11_hermitian_eig.cc)."""

import numpy as np

import slate_tpu as slate


def main():
    n = 96
    A0, S = slate.generate_matrix("heev_geo", n, cond=100.0, seed=10)
    a = np.asarray(A0)

    lam, Z = slate.heev(a.copy())
    lam, Z = np.asarray(lam), np.asarray(Z)
    np.testing.assert_allclose(np.sort(lam), np.sort(np.asarray(S)), rtol=1e-3,
                               atol=1e-4)
    print("heev |AZ-ZL|:", np.linalg.norm(a @ Z - Z * lam[None, :]))

    # explicit two-stage pipeline with back-transforms
    band, refl, taus = slate.he2hb(a)
    d, e, Q2 = slate.hb2st(np.asarray(band), want_vectors=True)
    lam2, W = slate.steqr(d, e)
    W = slate.unmtr_hb2st("left", "n", Q2, np.asarray(W))
    W = np.asarray(slate.unmtr_he2hb("left", "n", refl, taus, np.asarray(W)))
    err = np.linalg.norm(a @ W - W * np.asarray(lam2)[None, :]) / np.linalg.norm(a)
    print("two-stage |AZ-ZL|/|A|:", err)
    assert err < 1e-4
    print("ex11 OK")


if __name__ == "__main__":
    main()
