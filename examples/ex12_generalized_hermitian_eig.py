"""ex12: generalized Hermitian eigenproblem A x = lambda B x — hegv/hegst
(≅ examples/ex12_generalized_hermitian_eig.cc)."""

import numpy as np
from scipy.linalg import eigh as scipy_eigh

import slate_tpu as slate


def main():
    n = 64
    A0, _ = slate.generate_matrix("heev_geo", n, cond=50.0, seed=11)
    B0, _ = slate.generate_matrix("spd_geo", n, cond=10.0, seed=12)
    a, bmat = np.asarray(A0), np.asarray(B0)

    lam, Z = slate.hegv(1, a.copy(), bmat.copy())
    lam, Z = np.asarray(lam), np.asarray(Z)
    ref = scipy_eigh(a.astype(np.float64), bmat.astype(np.float64),
                     eigvals_only=True)
    np.testing.assert_allclose(np.sort(lam), ref, rtol=1e-2, atol=1e-3)
    resid = np.linalg.norm(a @ Z - (bmat @ Z) * lam[None, :]) / np.linalg.norm(a)
    print("hegv |AZ - BZL|/|A|:", resid)
    assert resid < 1e-3

    # the hegst standard-form transform by itself
    L, info = slate.potrf(slate.HermitianMatrix.from_array(slate.Uplo.Lower,
                                                           bmat.copy(), nb=32))
    C = slate.hegst(1, a, np.asarray(L))
    np.testing.assert_allclose(np.sort(np.linalg.eigvalsh(np.asarray(C))),
                               ref, rtol=1e-2, atol=1e-3)
    print("ex12 OK")


if __name__ == "__main__":
    main()
