"""ex13: non-uniform tiles — rectangular mb x nb tiles, ragged edges, custom
rank maps (≅ examples/ex13_non_uniform_block_size.cc — the reference's lambda
distributions, func.hh)."""

import numpy as np

import slate_tpu as slate
from slate_tpu.core import func


def main():
    # rectangular tiles + ragged last tiles
    a = np.arange(7 * 10, dtype=np.float32).reshape(7, 10)
    A = slate.Matrix.from_array(a, nb=4, mb=3)
    assert (A.mt, A.nt) == (3, 3)
    assert A.tileMb(2) == 1 and A.tileNb(2) == 2     # ragged edges
    np.testing.assert_array_equal(np.asarray(A.tile(2, 2)), a[6:, 8:])

    # custom distribution lambda (1D row-cyclic) — first-class like func.hh
    from slate_tpu.core.matrix import Matrix, MatrixStorage
    import jax.numpy as jnp
    st = MatrixStorage(jnp.asarray(a), 3, 4, p=2, q=1,
                       tile_rank=func.process_1d_grid("col", 2))
    M = Matrix(7, 10, 4, _storage=st)
    om = M.owner_map()
    np.testing.assert_array_equal(om[:, 0], [0, 1, 0])   # i % 2 down rows

    # block-size helpers
    mb = func.uniform_blocksize(7, 3)
    assert [mb(i) for i in range(3)] == [3, 3, 1]

    # ---- genuinely non-uniform per-index tile grids (round 5) ----------
    # MatrixStorage.hh:339-342 / func.hh:39-42: tileMb/tileNb as first-class
    # lambdas (or explicit size vectors), honored by tiles, views, owner
    # maps, and redistribute.
    b = np.arange(10 * 12, dtype=np.float32).reshape(10, 12)
    N = slate.Matrix.from_array(b, tile_mb=[2, 3, 1, 4], tile_nb=[5, 4, 3])
    assert (N.mt, N.nt) == (4, 3)
    assert [N.tileMb(i) for i in range(4)] == [2, 3, 1, 4]
    np.testing.assert_array_equal(np.asarray(N.tile(1, 1)), b[2:5, 5:9])
    # views keep the non-uniform grid: sub over tiles, transpose flips it
    S = N.sub(1, 2, 0, 1)
    assert [S.tileMb(i) for i in range(S.mt)] == [3, 1]
    np.testing.assert_array_equal(np.asarray(N.T.tile(1, 1)), b[2:5, 5:9].T)
    # custom rank map over the non-uniform grid
    N2 = slate.Matrix.from_array(b, tile_mb=[2, 3, 1, 4], tile_nb=[5, 4, 3],
                                 p=2, q=2, tile_rank=lambda i, j: (i + j) % 4)
    assert N2.owner_map()[2, 1] == 3

    # redistribute round-trip between two differently-distributed
    # non-uniform wrappers (src/redistribute.cc)
    from slate_tpu.parallel import redistribute_matrix
    dst = slate.Matrix.from_array(np.zeros_like(b),
                                  tile_mb=[2, 3, 1, 4], tile_nb=[5, 4, 3],
                                  p=2, q=2, tile_rank=lambda i, j: (i * 3 + j) % 4)
    redistribute_matrix(N2, dst)
    np.testing.assert_array_equal(np.asarray(dst.array), b)
    back = slate.Matrix.from_array(np.zeros_like(b),
                                   tile_mb=[2, 3, 1, 4], tile_nb=[5, 4, 3])
    redistribute_matrix(dst, back)
    np.testing.assert_array_equal(np.asarray(back.array), b)
    print("ex13 OK")


if __name__ == "__main__":
    main()
