"""ex13: non-uniform tiles — rectangular mb x nb tiles, ragged edges, custom
rank maps (≅ examples/ex13_non_uniform_block_size.cc — the reference's lambda
distributions, func.hh)."""

import numpy as np

import slate_tpu as slate
from slate_tpu.core import func


def main():
    # rectangular tiles + ragged last tiles
    a = np.arange(7 * 10, dtype=np.float32).reshape(7, 10)
    A = slate.Matrix.from_array(a, nb=4, mb=3)
    assert (A.mt, A.nt) == (3, 3)
    assert A.tileMb(2) == 1 and A.tileNb(2) == 2     # ragged edges
    np.testing.assert_array_equal(np.asarray(A.tile(2, 2)), a[6:, 8:])

    # custom distribution lambda (1D row-cyclic) — first-class like func.hh
    from slate_tpu.core.matrix import Matrix, MatrixStorage
    import jax.numpy as jnp
    st = MatrixStorage(jnp.asarray(a), 3, 4, p=2, q=1,
                       tile_rank=func.process_1d_grid("col", 2))
    M = Matrix(7, 10, 4, _storage=st)
    om = M.owner_map()
    np.testing.assert_array_equal(om[:, 0], [0, 1, 0])   # i % 2 down rows

    # block-size helpers
    mb = func.uniform_blocksize(7, 3)
    assert [mb(i) for i in range(3)] == [3, 3, 1]
    print("ex13 OK")


if __name__ == "__main__":
    main()
