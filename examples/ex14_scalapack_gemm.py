"""ex14: ScaLAPACK-compatibility gemm over a process grid
(≅ examples/ex14_scalapack_gemm.cc).  Run with a multi-device mesh:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu python ex14...
"""

import numpy as np

import jax

from slate_tpu import scalapack_api as slapi


def main():
    r = np.random.default_rng(13)
    a = r.standard_normal((64, 48)).astype(np.float32)
    b = r.standard_normal((48, 32)).astype(np.float32)
    c = np.zeros((64, 32), np.float32)

    ndev = len(jax.devices())
    if ndev >= 4:
        grid = slapi.gridinit(2, 2)          # ≅ Cblacs_gridinit
        print(f"grid 2x2 over {ndev} devices")
    else:
        print(f"single device ({ndev}); pgemm falls through to local path")

    out = slapi.psgemm("n", "n", 1.0, a, b, 0.0, c)
    np.testing.assert_allclose(out, a @ b, rtol=1e-4, atol=1e-4)
    slapi.gridexit()
    print("ex14 OK")


if __name__ == "__main__":
    main()
