"""ex15: setting matrix entries — set/scale/add elementwise drivers and matgen
kinds (≅ examples/ex15_set_matrix.cc)."""

import numpy as np

import slate_tpu as slate


def main():
    A = slate.Matrix.from_array(np.zeros((6, 6), np.float32), nb=2)

    # set(offdiag, diag) — geset
    slate.set(1.0, 5.0, A)
    a = np.asarray(A.array)
    assert (np.diag(a) == 5).all() and a[0, 1] == 1

    # scale by numer/denom (overflow-safe two-scalar form)
    slate.scale(3.0, 2.0, A)
    assert np.diag(np.asarray(A.array))[0] == 7.5

    # add: B = alpha A + beta B
    B = slate.Matrix.from_array(np.ones((6, 6), np.float32), nb=2)
    slate.add(2.0, A, 1.0, B)
    assert np.asarray(B.array)[0, 1] == 2 * 1.5 + 1   # offdiag
    assert np.asarray(B.array)[0, 0] == 2 * 7.5 + 1   # diag

    # named generator kinds (matgen)
    hilb, _ = slate.generate_matrix("hilb", 4)
    np.testing.assert_allclose(np.asarray(hilb)[0],
                               [1, 1 / 2, 1 / 3, 1 / 4], rtol=1e-5)
    print("ex15 OK")


if __name__ == "__main__":
    main()
