"""ex16: round-3 distributed stragglers — band Cholesky/LU on compact sharded
storage, Aasen indefinite solve, matrix inversion, and LQ minimum-norm least
squares, all over the process grid (reference: test_pbsv / test_gbsv /
test_hesv / test_trtri / test_gelqf exercised through its grid tester).

Run on the virtual mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/ex16_distributed_band_indefinite.py
"""

import numpy as np

from slate_tpu.parallel import (
    ProcessGrid, dense_to_band_lower, gels_lq_distributed, hesv_distributed,
    pbsv_distributed, potrf_distributed, potri_distributed)


def main():
    import jax.numpy as jnp

    grid = ProcessGrid(2, 4)
    rng = np.random.default_rng(16)
    n, kd, nb = 192, 7, 16

    # SPD band system on compact (kd+1, n) storage — O((kd+1)n/P) per device
    A = np.zeros((n, n), np.float32)
    for j in range(1, kd + 1):
        v = rng.standard_normal(n - j).astype(np.float32)
        A += np.diag(v, j) + np.diag(v, -j)
    A += np.diag(np.abs(rng.standard_normal(n)).astype(np.float32) + 4 * kd)
    Ab = dense_to_band_lower(jnp.asarray(np.tril(A)), kd)
    B = rng.standard_normal((n, 3)).astype(np.float32)
    X, info = pbsv_distributed(Ab, jnp.asarray(B), grid, kd, nb=nb)
    print("pbsv resid:", np.linalg.norm(A @ np.asarray(X) - B)
          / np.linalg.norm(B))
    assert int(info) == 0

    # Hermitian-indefinite (Aasen) solve over the mesh
    H = rng.standard_normal((n, n)).astype(np.float32)
    H = (H + H.T) / 2
    Xh, info = hesv_distributed(jnp.asarray(H), jnp.asarray(B), grid, nb=nb)
    print("hesv resid:", np.linalg.norm(H @ np.asarray(Xh) - B)
          / np.linalg.norm(B))

    # SPD inverse: potrf + potri riding the sharded kernels
    S = (H @ H.T + n * np.eye(n)).astype(np.float32)
    L = potrf_distributed(jnp.asarray(S), grid, nb=32)
    Sinv = np.asarray(potri_distributed(L, grid))
    full = np.tril(Sinv) + np.tril(Sinv, -1).T
    print("potri resid:", np.linalg.norm(S @ full - np.eye(n)))

    # wide minimum-norm least squares through the distributed LQ
    W = rng.standard_normal((48, 160)).astype(np.float32)
    Bw = rng.standard_normal((48, 2)).astype(np.float32)
    Xmn = np.asarray(gels_lq_distributed(jnp.asarray(W), jnp.asarray(Bw),
                                         grid, nb=16))
    ref = np.linalg.lstsq(W, Bw, rcond=None)[0]
    print("gels-lq vs lstsq:", np.linalg.norm(Xmn - ref)
          / max(np.linalg.norm(ref), 1e-30))
    print("ex16 OK")


if __name__ == "__main__":
    main()
