"""ex17: round-4 additions — double-precision-class solves on f32 hardware
(the Ozaki-splitting emulated-f64 gemm + iterative refinement,
``ops/f64emu.py``) and the distributed random-butterfly solver
(``parallel/rbt.py``; reference src/gesv_rbt.cc).

Run on the virtual mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/ex17_f64_emulation_and_rbt.py
"""

import numpy as np


def main():
    import jax.numpy as jnp

    from slate_tpu.ops.f64emu import gemm_f64emu, gesv_f64ir
    from slate_tpu.parallel import ProcessGrid, gesv_rbt_distributed

    rng = np.random.default_rng(17)
    n = 160

    # --- emulated-f64 residual: alpha/beta combine inside the compensated
    # accumulator, so r = A x - b is accurate even when it is tiny vs A@x.
    # Cast FIRST, then build b from the cast values in f64 — otherwise the
    # f64→f32 storage rounding (~1e-7) dominates and hides the emulation.
    A = rng.standard_normal((n, n)).astype(np.float32)
    x = rng.standard_normal((n, 2)).astype(np.float32)
    b = (A.astype(np.float64) @ x.astype(np.float64)).astype(np.float64)
    # r in double-f32: b crosses as its hi part (f32) + the f64 tail folds in
    rh, rl = gemm_f64emu(jnp.asarray(A), jnp.asarray(x), alpha=1.0,
                         beta=-1.0, C=jnp.asarray(b.astype(np.float32)),
                         return_hilo=True)
    tail = (b - b.astype(np.float32).astype(np.float64))
    r = (np.asarray(rh, np.float64) + np.asarray(rl, np.float64)) - tail
    print(f"f64emu residual |A x - b|_max = {np.abs(r).max():.3e} "
          "(plain f32 HIGHEST leaves ~1e-4 here)")

    # --- double-class solve: f32 LU factor + emulated-f64 refinement
    Xh, Xl, iters, info = gesv_f64ir(jnp.asarray(A),
                                     jnp.asarray(b.astype(np.float32)))
    X = np.asarray(Xh, np.float64) + np.asarray(Xl, np.float64)
    res = np.linalg.norm(A.astype(np.float64) @ X - b) / np.linalg.norm(b)
    print(f"gesv_f64ir: rel residual {res:.3e} after {int(iters)} refinement "
          f"rounds (info={int(info)}) — f32-native solves stop ~1e-6")

    # --- distributed RBT: butterfly transform + nopiv LU + IR on the mesh
    grid = ProcessGrid(2, 4)
    Xr, info, it, via_rbt = gesv_rbt_distributed(jnp.asarray(A),
                                                 jnp.asarray(b),
                                                 grid, depth=2, nb=32)
    err = np.linalg.norm(np.asarray(Xr) - x) / np.linalg.norm(x)
    print(f"gesv_rbt_distributed (2x4 grid): rel err {err:.3e} "
          f"(info={int(info)}, iters={int(it)}, "
          f"via {'rbt' if via_rbt else 'partialpiv fallback'})")
    print("ex17 OK")


if __name__ == "__main__":
    main()
