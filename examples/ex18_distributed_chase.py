"""ex18: round-5 distributed stage 2 — the segment-parallel bulge chases
(hb2st for eig, tb2bd for SVD) and the two-stage drivers that consume them.

The reference confines the chase to rank 0 (src/heev.cc:137-160 gathers the
band there; src/hb2st.cc schedules threads on one process).  Here the band's
column range partitions across the mesh and neighbors reconcile with O(kd²)
ppermute deltas per round — per-device window work divided by P
(parallel/chase_dist.py; compiled-cost table in PERF_CPU.md).

Run on the virtual mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/ex18_distributed_chase.py
"""

import numpy as np

from slate_tpu.parallel import (
    ProcessGrid, heev_distributed, hb2st_chase_distributed, svd_distributed,
    tb2bd_chase_distributed)


def main():
    import jax.numpy as jnp

    grid = ProcessGrid(2, 4)
    rng = np.random.default_rng(18)
    n, kd = 192, 6

    # --- the chase kernels directly, on synthetic bands ------------------
    m = rng.standard_normal((n, n)).astype(np.float32)
    sym = (m + m.T) / 2
    ii = np.arange(n)
    hband = jnp.asarray(np.where(np.abs(ii[:, None] - ii[None, :]) <= kd,
                                 sym, 0))
    d, e_c, _, _ = hb2st_chase_distributed(hband, kd, grid)
    T = (np.diag(np.asarray(d))
         + np.diag(np.abs(np.asarray(e_c)), -1)
         + np.diag(np.abs(np.asarray(e_c)), 1))
    err = np.max(np.abs(np.linalg.eigvalsh(T)
                        - np.linalg.eigvalsh(np.asarray(hband))))
    print("hb2st_chase_distributed spectrum err:", err)
    assert err < 1e-3

    uband = jnp.asarray(np.where((ii[None, :] >= ii[:, None])
                                 & (ii[None, :] - ii[:, None] <= kd), m, 0))
    db, eb, *_ = tb2bd_chase_distributed(uband, kd, grid)
    Bd = np.diag(np.abs(np.asarray(db))).astype(np.float64)
    Bd[np.arange(n - 1), np.arange(1, n)] = np.abs(np.asarray(eb))
    sv_err = np.max(np.abs(np.linalg.svd(Bd, compute_uv=False)
                           - np.linalg.svd(np.asarray(uband),
                                           compute_uv=False)))
    print("tb2bd_chase_distributed singular-value err:", sv_err)
    assert sv_err < 1e-3

    # --- end to end: two-stage drivers with the sharded stage 2 ----------
    lam, Z = heev_distributed(jnp.asarray(sym), ProcessGrid(2, 2), nb=8,
                              want_vectors=True, chase_distributed=True)
    resid = np.linalg.norm(sym @ np.asarray(Z)
                           - np.asarray(Z) * np.asarray(lam)[None, :]) \
        / (np.linalg.norm(sym) * n)
    print("heev_distributed(chase_distributed) resid:", resid)
    assert resid < 1e-6

    S, U, VT = svd_distributed(jnp.asarray(m), ProcessGrid(2, 2), nb=8,
                               want_vectors=True, chase_distributed=True)
    rec = np.asarray(U) * np.asarray(S)[None, :] @ np.asarray(VT)
    rec_err = np.linalg.norm(rec - m) / np.linalg.norm(m)
    print("svd_distributed(chase_distributed) reconstruction:", rec_err)
    assert rec_err < 1e-4
    print("ex18 OK")


if __name__ == "__main__":
    main()
