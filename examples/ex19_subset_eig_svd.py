"""ex19: round-5 subset solvers — index-range eigenpairs, spectral counting,
and top-k singular triplets (no reference analogue: SLATE's heev/svd always
compute the full spectrum; LAPACK's heevx/gesvdx families are the model).

The bisection representation makes subsets first-class: index-targeted
Sturm brackets cost O(n·k), stein inverse iteration batches the k vectors,
and the reverse sweep accumulation applies the bulge-chase Q to thin
blocks without materializing it (linalg/{eig,svd,sturm}.py).

Run:
  JAX_PLATFORMS=cpu python examples/ex19_subset_eig_svd.py
"""

import numpy as np

import slate_tpu as slate


def main():
    import jax
    jax.config.update("jax_enable_x64", True)   # gates below are f64-level
    import jax.numpy as jnp

    rng = np.random.default_rng(19)
    n = 128
    m = rng.standard_normal((n, n))
    A = jnp.asarray((m + m.T) / 2)
    ref = np.linalg.eigvalsh(np.asarray(A))

    # the 10 smallest eigenpairs
    lam, Z = slate.heev_range(A, il=0, iu=10)
    print("smallest-10 err:", np.max(np.abs(np.asarray(lam) - ref[:10])))
    resid = np.linalg.norm(np.asarray(A) @ np.asarray(Z)
                           - np.asarray(Z) * np.asarray(lam)[None, :])
    print("residual:", resid)
    assert np.max(np.abs(np.asarray(lam) - ref[:10])) < 1e-10
    assert resid < 1e-9 * n

    # how many eigenvalues in [-1, 1)?
    c = slate.eig_count(A, -1.0, 1.0)
    expect = int(np.sum((ref >= -1.0) & (ref < 1.0)))
    print(f"eig_count([-1,1)): {int(c)} (dense check {expect})")
    assert int(c) == expect

    # top-5 singular triplets of a rectangular matrix
    G = jnp.asarray(rng.standard_normal((192, 96)))
    sref = np.linalg.svd(np.asarray(G), compute_uv=False)
    S, U, VT = slate.svd_range(G, il=0, iu=5)
    print("top-5 sigma err:", np.max(np.abs(np.asarray(S) - sref[:5])))
    rec = (np.asarray(G) @ np.asarray(VT).T
           - np.asarray(U) * np.asarray(S)[None, :])
    print("triplet residual:", np.linalg.norm(rec))
    assert np.max(np.abs(np.asarray(S) - sref[:5])) < 1e-10
    assert np.linalg.norm(rec) < 1e-9

    # LAPACK-skin forms (1-based inclusive ranges)
    from slate_tpu import lapack_api as lp

    lam2, _ = lp.dsyevx("N", "L", np.asarray(A).copy(), 1, 10)
    assert np.max(np.abs(lam2 - ref[:10])) < 1e-10
    S2, _, _ = lp.dgesvdx("N", "N", np.asarray(G).copy(), 1, 5)
    assert np.max(np.abs(S2 - sref[:5])) < 1e-10
    print("ex19 OK")


if __name__ == "__main__":
    main()
