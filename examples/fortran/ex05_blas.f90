! ex05: BLAS from Fortran through the generated iso_c_binding module
! (reference examples/fortran/ex05_blas.f90 is the same exercise).
!
!   gfortran tools/fortran/slate_tpu.f90 examples/fortran/ex05_blas.f90 \
!     -L native -lslate_c_api -Wl,-rpath,native -o ex05 && ./ex05
program ex05_blas
  use slate_tpu
  use iso_c_binding
  implicit none
  integer(c_int64_t), parameter :: m = 23, n = 17, k = 31
  real(c_double) :: A(m, k), B(k, n), C(m, n), R(m, n)
  real(c_double) :: alpha, beta, err
  integer(c_int) :: info
  integer :: i, j, p

  alpha = 1.5d0
  beta = -0.5d0
  call random_number(A); A = A - 0.5d0
  call random_number(B); B = B - 0.5d0
  call random_number(C); C = C - 0.5d0
  R = C

  info = slate_init()
  if (info /= 0) stop 'slate_init failed'
  info = slate_dgemm('n', 'n', m, n, k, alpha, A, m, B, k, beta, C, m)
  if (info /= 0) stop 'slate_dgemm failed'

  err = 0d0
  do j = 1, int(n)
     do i = 1, int(m)
        R(i, j) = beta * R(i, j)
        do p = 1, int(k)
           R(i, j) = R(i, j) + alpha * A(i, p) * B(p, j)
        end do
        err = max(err, abs(R(i, j) - C(i, j)))
     end do
  end do
  call slate_finalize()
  print '(a, es10.3)', 'ex05 gemm max err = ', err
  if (err > 1d-10) stop 'ex05 FAILED'
  print '(a)', 'ex05 OK'
end program ex05_blas
