#!/usr/bin/env python
"""Run every example as a smoke test (≅ examples/run_tests.py in the reference —
the examples double as the smoke tier of the test strategy, SURVEY.md §4)."""

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

REPO = os.path.dirname(HERE)

env = dict(os.environ)
env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
# force CPU (the ambient env PINS the TPU tunnel platform, so setdefault is
# no defense — see tools/force_cpu.py); opt into another platform explicitly
_plat = os.environ.get("SLATE_EXAMPLES_PLATFORM", "cpu")
env["JAX_PLATFORMS"] = _plat
if _plat == "cpu":
    env["PALLAS_AXON_POOL_IPS"] = ""
flags = env.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    env["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"


def main() -> int:
    failures = []
    examples = sorted(f for f in os.listdir(HERE)
                      if f.startswith("ex") and f.endswith(".py"))
    for ex in examples:
        proc = subprocess.run([sys.executable, os.path.join(HERE, ex)],
                              capture_output=True, text=True, env=env,
                              timeout=600)
        status = "ok" if proc.returncode == 0 else "FAILED"
        print(f"{ex:42s} {status}")
        if proc.returncode != 0:
            failures.append(ex)
            print(proc.stdout[-2000:])
            print(proc.stderr[-2000:])
    print(f"\n{len(examples) - len(failures)}/{len(examples)} examples pass")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
