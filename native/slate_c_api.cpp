// slate_tpu C API implementation.
//
// Reference analogue: src/c_api/wrappers.cc — the reference mirrors its C++
// classes into C structs; here the compute path is the JAX runtime, so the C
// ABI embeds a Python interpreter once per process and forwards each entry
// point to the same scalapack-skin drivers the Python API uses (they in turn
// dispatch to the distributed mesh implementations when a grid is active —
// slate_gridinit maps to scalapack_api.gridinit).
//
// Buffers cross the boundary zero-copy: each C pointer is wrapped as a
// writable memoryview, reshaped column-major (LAPACK convention) on the
// Python side, and results are written back through the view.  Works both
// embedded in a C program (Py_Initialize path) and loaded into an existing
// Python process (ctypes path; PyGILState handles the interpreter).

#include <Python.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

PyObject* g_globals = nullptr;
bool g_we_initialized = false;

const char* kPrelude =
    "import sys, os\n"
    "_root = os.environ.get('SLATE_TPU_ROOT')\n"
    "if _root and _root not in sys.path:\n"
    "    sys.path.insert(0, _root)\n"
    "import jax\n"
    "jax.config.update('jax_enable_x64', True)\n"  // d/z routines need f64
    "import numpy as np\n"
    "import slate_tpu\n"
    "import slate_tpu.scalapack_api as sk\n"
    "_DT = dict(s=np.float32, d=np.float64, c=np.complex64, z=np.complex128)\n"
    "_handles = {}\n"          // matrix-object registry (handle API)
    "_next_handle = [1]\n";

int ensure_init() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = true;
    // release the GIL so every entry point can use Ensure/Release uniformly
    PyEval_SaveThread();
  }
  if (g_globals != nullptr) return 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  g_globals = PyDict_New();
  PyDict_SetItemString(g_globals, "__builtins__", PyEval_GetBuiltins());
  PyObject* r = PyRun_String(kPrelude, Py_file_input, g_globals, g_globals);
  int rc = 0;
  if (r == nullptr) {
    PyErr_Print();
    Py_CLEAR(g_globals);
    rc = -999;
  } else {
    Py_DECREF(r);
  }
  PyGILState_Release(gil);
  return rc;
}

void set_mem(PyObject* locals, const char* name, void* ptr, Py_ssize_t bytes) {
  PyObject* mv = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(ptr), bytes, PyBUF_WRITE);
  PyDict_SetItemString(locals, name, mv);
  Py_DECREF(mv);
}

void set_int(PyObject* locals, const char* name, int64_t v) {
  PyObject* o = PyLong_FromLongLong(v);
  PyDict_SetItemString(locals, name, o);
  Py_DECREF(o);
}

void set_dbl(PyObject* locals, const char* name, double v) {
  PyObject* o = PyFloat_FromDouble(v);
  PyDict_SetItemString(locals, name, o);
  Py_DECREF(o);
}

void set_chr(PyObject* locals, const char* name, char c) {
  char buf[2] = {c, 0};
  PyObject* o = PyUnicode_FromString(buf);
  PyDict_SetItemString(locals, name, o);
  Py_DECREF(o);
}

// Run `code` with `locals`; returns locals["info"] (0 when unset), or -998 on
// a Python exception (printed to stderr).
int run_code(const char* code, PyObject* locals) {
  PyObject* r = PyRun_String(code, Py_file_input, g_globals, locals);
  if (r == nullptr) {
    PyErr_Print();
    return -998;
  }
  Py_DECREF(r);
  PyObject* info = PyDict_GetItemString(locals, "info");
  return info != nullptr ? static_cast<int>(PyLong_AsLong(info)) : 0;
}

double run_code_dbl(const char* code, PyObject* locals, const char* out) {
  PyObject* r = PyRun_String(code, Py_file_input, g_globals, locals);
  if (r == nullptr) {
    PyErr_Print();
    return -1.0;
  }
  Py_DECREF(r);
  PyObject* v = PyDict_GetItemString(locals, out);
  return v != nullptr ? PyFloat_AsDouble(v) : -1.0;
}

struct Call {
  PyGILState_STATE gil;
  PyObject* locals;
  bool ok;
  Call() : ok(false) {
    if (ensure_init() != 0) return;
    gil = PyGILState_Ensure();
    locals = PyDict_New();
    ok = true;
  }
  ~Call() {
    if (ok) {
      Py_DECREF(locals);
      PyGILState_Release(gil);
    }
  }
};

}  // namespace

extern "C" {

int slate_init(void) { return ensure_init(); }

void slate_finalize(void) {
  if (g_we_initialized && Py_IsInitialized()) {
    PyGILState_Ensure();
    Py_CLEAR(g_globals);
    Py_Finalize();
    g_we_initialized = false;
  }
}

const char* slate_version(void) { return "slate_tpu-c-api 2.0"; }

int slate_gridinit(int p, int q) {
  Call c;
  if (!c.ok) return -999;
  set_int(c.locals, "p", p);
  set_int(c.locals, "q", q);
  return run_code(
      "try:\n"
      "    sk.gridinit(int(p), int(q)); info = 0\n"
      "except Exception as e:\n"
      "    import sys; print(e, file=sys.stderr); info = 1\n",
      c.locals);
}

void slate_gridexit(void) {
  Call c;
  if (!c.ok) return;
  run_code("sk.gridexit()\ninfo = 0\n", c.locals);
}

// ---------------------------------------------------------------------------

static int gemm_impl(const char* pyname, char transa, char transb, int64_t m,
                     int64_t n, int64_t k, double alpha, const void* A,
                     int64_t lda, const void* B, int64_t ldb, double beta,
                     void* C, int64_t ldc, int64_t esz, const char* npdt) {
  Call c;
  if (!c.ok) return -999;
  int64_t acols = (transa == 'n' || transa == 'N') ? k : m;
  int64_t bcols = (transb == 'n' || transb == 'N') ? n : k;
  set_mem(c.locals, "Abuf", const_cast<void*>(A), lda * acols * esz);
  set_mem(c.locals, "Bbuf", const_cast<void*>(B), ldb * bcols * esz);
  set_mem(c.locals, "Cbuf", C, ldc * n * esz);
  set_chr(c.locals, "ta", transa);
  set_chr(c.locals, "tb", transb);
  set_int(c.locals, "m", m);
  set_int(c.locals, "n", n);
  set_int(c.locals, "k", k);
  set_int(c.locals, "lda", lda);
  set_int(c.locals, "ldb", ldb);
  set_int(c.locals, "ldc", ldc);
  set_dbl(c.locals, "alpha", alpha);
  set_dbl(c.locals, "beta", beta);
  set_chr(c.locals, "dtc", npdt[0]);
  PyDict_SetItemString(c.locals, "fn",
                       PyDict_GetItemString(g_globals, "sk"));
  char code[1024];
  snprintf(code, sizeof(code),
           "dt = np.float64 if dtc == 'd' else np.float32\n"
           "ar = (m, k) if ta.lower() == 'n' else (k, m)\n"
           "br = (k, n) if tb.lower() == 'n' else (n, k)\n"
           "a = np.frombuffer(Abuf, dt).reshape((lda, -1), order='F')[:ar[0], :ar[1]]\n"
           "b = np.frombuffer(Bbuf, dt).reshape((ldb, -1), order='F')[:br[0], :br[1]]\n"
           "cm = np.frombuffer(Cbuf, dt).reshape((ldc, -1), order='F')[:m, :n]\n"
           "out = sk.%s(ta, tb, alpha, a, b, beta, cm)\n"
           "cm[...] = out\n"
           "info = 0\n",
           pyname);
  return run_code(code, c.locals);
}

int slate_dgemm(char transa, char transb, int64_t m, int64_t n, int64_t k,
                double alpha, const double* A, int64_t lda, const double* B,
                int64_t ldb, double beta, double* C, int64_t ldc) {
  return gemm_impl("pdgemm", transa, transb, m, n, k, alpha, A, lda, B, ldb,
                   beta, C, ldc, 8, "d");
}

int slate_sgemm(char transa, char transb, int64_t m, int64_t n, int64_t k,
                float alpha, const float* A, int64_t lda, const float* B,
                int64_t ldb, float beta, float* C, int64_t ldc) {
  return gemm_impl("psgemm", transa, transb, m, n, k, alpha, A, lda, B, ldb,
                   beta, C, ldc, 4, "s");
}

// complex gemm: alpha/beta cross as pointers to one interleaved element
static int gemm_cz_impl(char dtc, char transa, char transb, int64_t m,
                        int64_t n, int64_t k, const void* alpha,
                        const void* A, int64_t lda, const void* B,
                        int64_t ldb, const void* beta, void* C, int64_t ldc,
                        int64_t esz) {
  Call c;
  if (!c.ok) return -999;
  double ar, ai, br, bi;
  if (esz == 16) {
    const double* ap = static_cast<const double*>(alpha);
    const double* bp = static_cast<const double*>(beta);
    ar = ap[0]; ai = ap[1]; br = bp[0]; bi = bp[1];
  } else {
    const float* ap = static_cast<const float*>(alpha);
    const float* bp = static_cast<const float*>(beta);
    ar = ap[0]; ai = ap[1]; br = bp[0]; bi = bp[1];
  }
  int64_t acols = (transa == 'n' || transa == 'N') ? k : m;
  int64_t bcols = (transb == 'n' || transb == 'N') ? n : k;
  set_mem(c.locals, "Abuf", const_cast<void*>(A), lda * acols * esz);
  set_mem(c.locals, "Bbuf", const_cast<void*>(B), ldb * bcols * esz);
  set_mem(c.locals, "Cbuf", C, ldc * n * esz);
  set_chr(c.locals, "ta", transa);
  set_chr(c.locals, "tb", transb);
  set_int(c.locals, "m", m);
  set_int(c.locals, "n", n);
  set_int(c.locals, "k", k);
  set_int(c.locals, "lda", lda);
  set_int(c.locals, "ldb", ldb);
  set_int(c.locals, "ldc", ldc);
  set_dbl(c.locals, "ar", ar);
  set_dbl(c.locals, "ai", ai);
  set_dbl(c.locals, "br", br);
  set_dbl(c.locals, "bi", bi);
  set_chr(c.locals, "dtc", dtc);
  return run_code(
      "dt = _DT[dtc]\n"
      "alpha = dt(complex(ar, ai)); beta = dt(complex(br, bi))\n"
      "arr = (m, k) if ta.lower() == 'n' else (k, m)\n"
      "brr = (k, n) if tb.lower() == 'n' else (n, k)\n"
      "a = np.frombuffer(Abuf, dt).reshape((lda, -1), order='F')[:arr[0], :arr[1]]\n"
      "b = np.frombuffer(Bbuf, dt).reshape((ldb, -1), order='F')[:brr[0], :brr[1]]\n"
      "cm = np.frombuffer(Cbuf, dt).reshape((ldc, -1), order='F')[:m, :n]\n"
      "fn = getattr(sk, 'p' + dtc + 'gemm')\n"
      "cm[...] = fn(ta, tb, alpha, a, b, beta, cm.copy())\n"
      "info = 0\n",
      c.locals);
}

int slate_zgemm(char transa, char transb, int64_t m, int64_t n, int64_t k,
                const void* alpha, const void* A, int64_t lda, const void* B,
                int64_t ldb, const void* beta, void* C, int64_t ldc) {
  return gemm_cz_impl('z', transa, transb, m, n, k, alpha, A, lda, B, ldb,
                      beta, C, ldc, 16);
}

int slate_cgemm(char transa, char transb, int64_t m, int64_t n, int64_t k,
                const void* alpha, const void* A, int64_t lda, const void* B,
                int64_t ldb, const void* beta, void* C, int64_t ldc) {
  return gemm_cz_impl('c', transa, transb, m, n, k, alpha, A, lda, B, ldb,
                      beta, C, ldc, 8);
}

// ---------------------------------------------------------------------------

static int gesv_impl(const char* pre, int64_t n, int64_t nrhs, void* A,
                     int64_t lda, int64_t* ipiv, void* B, int64_t ldb,
                     int64_t esz) {
  Call c;
  if (!c.ok) return -999;
  set_mem(c.locals, "Abuf", A, lda * n * esz);
  set_mem(c.locals, "Bbuf", B, ldb * nrhs * esz);
  set_mem(c.locals, "Pbuf", ipiv, n * 8);
  set_int(c.locals, "n", n);
  set_int(c.locals, "nrhs", nrhs);
  set_int(c.locals, "lda", lda);
  set_int(c.locals, "ldb", ldb);
  set_chr(c.locals, "dtc", pre[0]);
  return run_code(
      "dt = _DT[dtc]\n"
      "a = np.frombuffer(Abuf, dt).reshape((lda, -1), order='F')[:n, :n]\n"
      "b = np.frombuffer(Bbuf, dt).reshape((ldb, -1), order='F')[:n, :nrhs]\n"
      "pv = np.frombuffer(Pbuf, np.int64)[:n]\n"
      "fac = getattr(sk, 'p' + dtc + 'getrf')\n"
      "slv = getattr(sk, 'p' + dtc + 'getrs')\n"
      "lu, piv, info = fac(a.copy())\n"
      "a[...] = lu\n"
      "pv[...] = np.asarray(piv, np.int64)\n"
      "if info == 0:\n"
      "    b[...] = slv('n', lu, piv, b.copy())\n",
      c.locals);
}

int slate_zgesv(int64_t n, int64_t nrhs, void* A, int64_t lda, int64_t* ipiv,
                void* B, int64_t ldb) {
  return gesv_impl("z", n, nrhs, A, lda, ipiv, B, ldb, 16);
}

int slate_cgesv(int64_t n, int64_t nrhs, void* A, int64_t lda, int64_t* ipiv,
                void* B, int64_t ldb) {
  return gesv_impl("c", n, nrhs, A, lda, ipiv, B, ldb, 8);
}

int slate_dgesv(int64_t n, int64_t nrhs, double* A, int64_t lda, int64_t* ipiv,
                double* B, int64_t ldb) {
  return gesv_impl("d", n, nrhs, A, lda, ipiv, B, ldb, 8);
}

int slate_sgesv(int64_t n, int64_t nrhs, float* A, int64_t lda, int64_t* ipiv,
                float* B, int64_t ldb) {
  return gesv_impl("s", n, nrhs, A, lda, ipiv, B, ldb, 4);
}

// ---------------------------------------------------------------------------

static int posv_impl(const char* pre, char uplo, int64_t n, int64_t nrhs,
                     void* A, int64_t lda, void* B, int64_t ldb, int64_t esz) {
  Call c;
  if (!c.ok) return -999;
  set_mem(c.locals, "Abuf", A, lda * n * esz);
  if (B != nullptr)
    set_mem(c.locals, "Bbuf", B, ldb * nrhs * esz);
  set_chr(c.locals, "uplo", uplo);
  set_int(c.locals, "n", n);
  set_int(c.locals, "nrhs", nrhs);
  set_int(c.locals, "lda", lda);
  set_int(c.locals, "ldb", ldb);
  set_chr(c.locals, "dtc", pre[0]);
  return run_code(
      "dt = _DT[dtc]\n"
      "a = np.frombuffer(Abuf, dt).reshape((lda, -1), order='F')[:n, :n]\n"
      "fac = getattr(sk, 'p' + dtc + 'potrf')\n"
      "slv = getattr(sk, 'p' + dtc + 'potrs')\n"
      "Lf, info = fac(uplo, a.copy())\n"
      "mask = np.tril(np.ones((n, n), bool)) if uplo.lower().startswith('l') "
      "else np.triu(np.ones((n, n), bool))\n"
      "a[mask] = np.asarray(Lf, dt)[mask]\n"
      "if info == 0 and 'Bbuf' in dir():\n"
      "    b = np.frombuffer(Bbuf, dt).reshape((ldb, -1), order='F')[:n, :nrhs]\n"
      "    b[...] = slv(uplo, np.asarray(Lf, dt), b.copy())\n",
      c.locals);
}

int slate_dposv(char uplo, int64_t n, int64_t nrhs, double* A, int64_t lda,
                double* B, int64_t ldb) {
  return posv_impl("d", uplo, n, nrhs, A, lda, B, ldb, 8);
}

int slate_sposv(char uplo, int64_t n, int64_t nrhs, float* A, int64_t lda,
                float* B, int64_t ldb) {
  return posv_impl("s", uplo, n, nrhs, A, lda, B, ldb, 4);
}

int slate_dpotrf(char uplo, int64_t n, double* A, int64_t lda) {
  return posv_impl("d", uplo, n, 0, A, lda, nullptr, 1, 8);
}

int slate_spotrf(char uplo, int64_t n, float* A, int64_t lda) {
  return posv_impl("s", uplo, n, 0, A, lda, nullptr, 1, 4);
}

int slate_zposv(char uplo, int64_t n, int64_t nrhs, void* A, int64_t lda,
                void* B, int64_t ldb) {
  return posv_impl("z", uplo, n, nrhs, A, lda, B, ldb, 16);
}

int slate_cposv(char uplo, int64_t n, int64_t nrhs, void* A, int64_t lda,
                void* B, int64_t ldb) {
  return posv_impl("c", uplo, n, nrhs, A, lda, B, ldb, 8);
}

int slate_zpotrf(char uplo, int64_t n, void* A, int64_t lda) {
  return posv_impl("z", uplo, n, 0, A, lda, nullptr, 1, 16);
}

int slate_cpotrf(char uplo, int64_t n, void* A, int64_t lda) {
  return posv_impl("c", uplo, n, 0, A, lda, nullptr, 1, 8);
}

// ---------------------------------------------------------------------------

int slate_dgels(char trans, int64_t m, int64_t n, int64_t nrhs, double* A,
                int64_t lda, double* B, int64_t ldb) {
  Call c;
  if (!c.ok) return -999;
  set_mem(c.locals, "Abuf", A, lda * n * 8);
  set_mem(c.locals, "Bbuf", B, ldb * nrhs * 8);
  set_chr(c.locals, "trans", trans);
  set_int(c.locals, "m", m);
  set_int(c.locals, "n", n);
  set_int(c.locals, "nrhs", nrhs);
  set_int(c.locals, "lda", lda);
  set_int(c.locals, "ldb", ldb);
  return run_code(
      "a = np.frombuffer(Abuf, np.float64).reshape((lda, -1), order='F')[:m, :n]\n"
      "b = np.frombuffer(Bbuf, np.float64).reshape((ldb, -1), order='F')\n"
      "x = sk.pdgels(trans, a.copy(), b[:m, :nrhs].copy())\n"
      "b[:x.shape[0], :nrhs] = x\n"
      "info = 0\n",
      c.locals);
}

int slate_dsyev(char jobz, char uplo, int64_t n, double* A, int64_t lda,
                double* W) {
  Call c;
  if (!c.ok) return -999;
  set_mem(c.locals, "Abuf", A, lda * n * 8);
  set_mem(c.locals, "Wbuf", W, n * 8);
  set_chr(c.locals, "jobz", jobz);
  set_chr(c.locals, "uplo", uplo);
  set_int(c.locals, "n", n);
  set_int(c.locals, "lda", lda);
  return run_code(
      "a = np.frombuffer(Abuf, np.float64).reshape((lda, -1), order='F')[:n, :n]\n"
      "w = np.frombuffer(Wbuf, np.float64)[:n]\n"
      "lam, z = sk.pdsyev(jobz, uplo, a.copy())\n"
      "w[...] = np.asarray(lam, np.float64)\n"
      "if jobz.lower() == 'v' and z is not None:\n"
      "    a[...] = np.asarray(z, np.float64)\n"
      "info = 0\n",
      c.locals);
}

int slate_dgesvd(char jobu, char jobvt, int64_t m, int64_t n, double* A,
                 int64_t lda, double* S, double* U, int64_t ldu, double* VT,
                 int64_t ldvt) {
  Call c;
  if (!c.ok) return -999;
  int64_t kmin = m < n ? m : n;
  set_mem(c.locals, "Abuf", A, lda * n * 8);
  set_mem(c.locals, "Sbuf", S, kmin * 8);
  if (U != nullptr) set_mem(c.locals, "Ubuf", U, ldu * kmin * 8);
  if (VT != nullptr) set_mem(c.locals, "Vbuf", VT, ldvt * n * 8);
  set_chr(c.locals, "jobu", jobu);
  set_chr(c.locals, "jobvt", jobvt);
  set_int(c.locals, "m", m);
  set_int(c.locals, "n", n);
  set_int(c.locals, "lda", lda);
  set_int(c.locals, "ldu", ldu);
  set_int(c.locals, "ldvt", ldvt);
  return run_code(
      "k = min(m, n)\n"
      "a = np.frombuffer(Abuf, np.float64).reshape((lda, -1), order='F')[:m, :n]\n"
      "s, u, vt = sk.pdgesvd(jobu, jobvt, a.copy())\n"
      "np.frombuffer(Sbuf, np.float64)[:k] = np.asarray(s)[:k]\n"
      "if u is not None and 'Ubuf' in dir():\n"
      "    um = np.frombuffer(Ubuf, np.float64).reshape((ldu, -1), order='F')\n"
      "    um[:m, :u.shape[1]] = u\n"
      "if vt is not None and 'Vbuf' in dir():\n"
      "    vm = np.frombuffer(Vbuf, np.float64).reshape((ldvt, -1), order='F')\n"
      "    vm[:vt.shape[0], :n] = vt\n"
      "info = 0\n",
      c.locals);
}

static int heev_cz_impl(char dtc, char jobz, char uplo, int64_t n, void* A,
                        int64_t lda, void* W, int64_t esz, int64_t wsz) {
  Call c;
  if (!c.ok) return -999;
  set_mem(c.locals, "Abuf", A, lda * n * esz);
  set_mem(c.locals, "Wbuf", W, n * wsz);
  set_chr(c.locals, "jobz", jobz);
  set_chr(c.locals, "uplo", uplo);
  set_int(c.locals, "n", n);
  set_int(c.locals, "lda", lda);
  set_chr(c.locals, "dtc", dtc);
  return run_code(
      "dt = _DT[dtc]\n"
      "wdt = np.float64 if dtc == 'z' else np.float32\n"
      "a = np.frombuffer(Abuf, dt).reshape((lda, -1), order='F')[:n, :n]\n"
      "w = np.frombuffer(Wbuf, wdt)[:n]\n"
      "lam, z = getattr(sk, 'p' + dtc + 'heev')(jobz, uplo, a.copy())\n"
      "w[...] = np.asarray(lam, wdt)\n"
      "if jobz.lower() == 'v' and z is not None:\n"
      "    a[...] = np.asarray(z, dt)\n"
      "info = 0\n",
      c.locals);
}

int slate_zheev(char jobz, char uplo, int64_t n, void* A, int64_t lda,
                double* W) {
  return heev_cz_impl('z', jobz, uplo, n, A, lda, W, 16, 8);
}

int slate_cheev(char jobz, char uplo, int64_t n, void* A, int64_t lda,
                float* W) {
  return heev_cz_impl('c', jobz, uplo, n, A, lda, W, 8, 4);
}

int slate_zgesvd(char jobu, char jobvt, int64_t m, int64_t n, void* A,
                 int64_t lda, double* S, void* U, int64_t ldu, void* VT,
                 int64_t ldvt) {
  Call c;
  if (!c.ok) return -999;
  int64_t kmin = m < n ? m : n;
  set_mem(c.locals, "Abuf", A, lda * n * 16);
  set_mem(c.locals, "Sbuf", S, kmin * 8);
  if (U != nullptr) set_mem(c.locals, "Ubuf", U, ldu * kmin * 16);
  if (VT != nullptr) set_mem(c.locals, "Vbuf", VT, ldvt * n * 16);
  set_chr(c.locals, "jobu", jobu);
  set_chr(c.locals, "jobvt", jobvt);
  set_int(c.locals, "m", m);
  set_int(c.locals, "n", n);
  set_int(c.locals, "lda", lda);
  set_int(c.locals, "ldu", ldu);
  set_int(c.locals, "ldvt", ldvt);
  return run_code(
      "k = min(m, n)\n"
      "a = np.frombuffer(Abuf, np.complex128).reshape((lda, -1), order='F')[:m, :n]\n"
      "s, u, vt = sk.pzgesvd(jobu, jobvt, a.copy())\n"
      "np.frombuffer(Sbuf, np.float64)[:k] = np.asarray(np.real(s))[:k]\n"
      "if u is not None and 'Ubuf' in dir():\n"
      "    um = np.frombuffer(Ubuf, np.complex128).reshape((ldu, -1), order='F')\n"
      "    um[:m, :u.shape[1]] = u\n"
      "if vt is not None and 'Vbuf' in dir():\n"
      "    vm = np.frombuffer(Vbuf, np.complex128).reshape((ldvt, -1), order='F')\n"
      "    vm[:vt.shape[0], :n] = vt\n"
      "info = 0\n",
      c.locals);
}

// ---------------------------------------------------------------------------
// band + indefinite solvers (LAPACK band layouts at the ABI)

static int pbsv_impl(char dtc, char uplo, int64_t n, int64_t kd, int64_t nrhs,
                     void* AB, int64_t ldab, void* B, int64_t ldb,
                     int64_t esz) {
  Call c;
  if (!c.ok) return -999;
  if (ldab < kd + 1) return -6;   // LAPACK-style argument error, matching
                                  // gbsv's undersized-ldab contract
  set_mem(c.locals, "ABbuf", AB, ldab * n * esz);
  set_mem(c.locals, "Bbuf", B, ldb * nrhs * esz);
  set_chr(c.locals, "uplo", uplo);
  set_int(c.locals, "n", n);
  set_int(c.locals, "kd", kd);
  set_int(c.locals, "nrhs", nrhs);
  set_int(c.locals, "ldab", ldab);
  set_int(c.locals, "ldb", ldb);
  set_chr(c.locals, "dtc", dtc);
  return run_code(
      "dt = _DT[dtc]\n"
      "ab = np.frombuffer(ABbuf, dt).reshape((ldab, -1), order='F')[:, :n]\n"
      "b = np.frombuffer(Bbuf, dt).reshape((ldb, -1), order='F')[:n, :nrhs]\n"
      "# LAPACK band -> dense: lower AB[i-j, j] = A[i, j]; upper\n"
      "# AB[kd+i-j, j] = A[i, j]\n"
      "A = np.zeros((n, n), dt)\n"
      "low = uplo.lower().startswith('l')\n"
      "for d in range(kd + 1):\n"
      "    r = ab[d, :n - d] if low else ab[kd - d, d:]\n"
      "    A += np.diag(r, -d if low else d)\n"
      "A = A + (np.tril(A, -1) if low else np.triu(A, 1)).conj().T\n"
      "# factor ONCE, then solve from the factor and write the factor band\n"
      "# back LAPACK-style (lower storage gets L, upper storage gets U=L^H)\n"
      "Lf, info = getattr(sk, 'p' + dtc + 'pbtrf')('l', int(kd), A)\n"
      "if info == 0:\n"
      "    Lf = np.asarray(Lf, dt)\n"
      "    b[...] = np.asarray(\n"
      "        getattr(sk, 'p' + dtc + 'pbtrs')('l', int(kd), Lf, b.copy()), dt)\n"
      "    for d in range(kd + 1):\n"
      "        diag = np.diagonal(Lf, -d)\n"
      "        if low:\n"
      "            ab[d, :n - d] = diag\n"
      "        else:\n"
      "            ab[kd - d, d:] = diag.conj()\n",
      c.locals);
}

int slate_dpbsv(char uplo, int64_t n, int64_t kd, int64_t nrhs, double* AB,
                int64_t ldab, double* B, int64_t ldb) {
  return pbsv_impl('d', uplo, n, kd, nrhs, AB, ldab, B, ldb, 8);
}

int slate_spbsv(char uplo, int64_t n, int64_t kd, int64_t nrhs, float* AB,
                int64_t ldab, float* B, int64_t ldb) {
  return pbsv_impl('s', uplo, n, kd, nrhs, AB, ldab, B, ldb, 4);
}

static int gbsv_impl(char dtc, int64_t n, int64_t kl, int64_t ku,
                     int64_t nrhs, const void* AB, int64_t ldab, void* B,
                     int64_t ldb, int64_t esz) {
  Call c;
  if (!c.ok) return -999;
  if (ldab < 2 * kl + ku + 1) return -6;   // dgbsv layout required; an ldab
                                           // heuristic would silently misread
                                           // compact-layout callers
  set_mem(c.locals, "ABbuf", const_cast<void*>(AB), ldab * n * esz);
  set_mem(c.locals, "Bbuf", B, ldb * nrhs * esz);
  set_int(c.locals, "n", n);
  set_int(c.locals, "kl", kl);
  set_int(c.locals, "ku", ku);
  set_int(c.locals, "nrhs", nrhs);
  set_int(c.locals, "ldab", ldab);
  set_int(c.locals, "ldb", ldb);
  set_chr(c.locals, "dtc", dtc);
  return run_code(
      "dt = _DT[dtc]\n"
      "ab = np.frombuffer(ABbuf, dt).reshape((ldab, -1), order='F')[:, :n]\n"
      "b = np.frombuffer(Bbuf, dt).reshape((ldb, -1), order='F')[:n, :nrhs]\n"
      "# LAPACK dgbsv layout: AB[kl+ku+i-j, j] = A[i, j] (top kl rows are\n"
      "# factor workspace, ignored on input)\n"
      "off = kl + ku\n"
      "A = np.zeros((n, n), dt)\n"
      "for d in range(-kl, ku + 1):\n"
      "    A += np.diag(ab[off - d, max(0, d):n + min(0, d)], d)\n"
      "X, info = getattr(sk, 'p' + dtc + 'gbsv')(int(kl), int(ku), A, b.copy())\n"
      "if info == 0:\n"
      "    b[...] = np.asarray(X, dt)\n",
      c.locals);
}

int slate_dgbsv(int64_t n, int64_t kl, int64_t ku, int64_t nrhs,
                const double* AB, int64_t ldab, double* B, int64_t ldb) {
  return gbsv_impl('d', n, kl, ku, nrhs, AB, ldab, B, ldb, 8);
}

int slate_sgbsv(int64_t n, int64_t kl, int64_t ku, int64_t nrhs,
                const float* AB, int64_t ldab, float* B, int64_t ldb) {
  return gbsv_impl('s', n, kl, ku, nrhs, AB, ldab, B, ldb, 4);
}

static int sysv_impl(char dtc, char uplo, int64_t n, int64_t nrhs,
                     const void* A, int64_t lda, void* B, int64_t ldb,
                     int64_t esz) {
  Call c;
  if (!c.ok) return -999;
  set_mem(c.locals, "Abuf", const_cast<void*>(A), lda * n * esz);
  set_mem(c.locals, "Bbuf", B, ldb * nrhs * esz);
  set_chr(c.locals, "uplo", uplo);
  set_int(c.locals, "n", n);
  set_int(c.locals, "nrhs", nrhs);
  set_int(c.locals, "lda", lda);
  set_int(c.locals, "ldb", ldb);
  set_chr(c.locals, "dtc", dtc);
  return run_code(
      "dt = _DT[dtc]\n"
      "a = np.frombuffer(Abuf, dt).reshape((lda, -1), order='F')[:n, :n]\n"
      "b = np.frombuffer(Bbuf, dt).reshape((ldb, -1), order='F')[:n, :nrhs]\n"
      "name = 'hesv' if dtc in 'cz' else 'sysv'\n"
      "X, info = getattr(sk, 'p' + dtc + name)(uplo, a.copy(), b.copy())\n"
      "if info == 0:\n"
      "    b[...] = np.asarray(X, dt)\n",
      c.locals);
}

int slate_dsysv(char uplo, int64_t n, int64_t nrhs, const double* A,
                int64_t lda, double* B, int64_t ldb) {
  return sysv_impl('d', uplo, n, nrhs, A, lda, B, ldb, 8);
}

int slate_ssysv(char uplo, int64_t n, int64_t nrhs, const float* A,
                int64_t lda, float* B, int64_t ldb) {
  return sysv_impl('s', uplo, n, nrhs, A, lda, B, ldb, 4);
}

int slate_zhesv(char uplo, int64_t n, int64_t nrhs, const void* A,
                int64_t lda, void* B, int64_t ldb) {
  return sysv_impl('z', uplo, n, nrhs, A, lda, B, ldb, 16);
}

int slate_chesv(char uplo, int64_t n, int64_t nrhs, const void* A,
                int64_t lda, void* B, int64_t ldb) {
  return sysv_impl('c', uplo, n, nrhs, A, lda, B, ldb, 8);
}

// ---------------------------------------------------------------------------
// factor / solve split + triangular solve + generalized eigen

static int getrf_impl(char dtc, int64_t m, int64_t n, void* A, int64_t lda,
                      int64_t* ipiv, int64_t esz) {
  Call c;
  if (!c.ok) return -999;
  int64_t k = m < n ? m : n;
  set_mem(c.locals, "Abuf", A, lda * n * esz);
  set_mem(c.locals, "Pbuf", ipiv, k * 8);
  set_int(c.locals, "m", m);
  set_int(c.locals, "n", n);
  set_int(c.locals, "lda", lda);
  set_chr(c.locals, "dtc", dtc);
  return run_code(
      "dt = np.float64 if dtc == 'd' else np.float32\n"
      "a = np.frombuffer(Abuf, dt).reshape((lda, -1), order='F')[:m, :n]\n"
      "k = min(m, n)\n"
      "pv = np.frombuffer(Pbuf, np.int64)[:k]\n"
      "fac = sk.pdgetrf if dtc == 'd' else sk.psgetrf\n"
      "lu, piv, info = fac(a.copy())\n"
      "piv = np.asarray(piv, np.int64)\n"
      "lu = np.asarray(lu)\n"
      "if m > k:\n"
      "    # LAPACK ipiv stops at k swaps; rows below k must sit where those\n"
      "    # k interchanges (alone) put them, or the truncated ipiv and the\n"
      "    # returned L rows disagree for tall factors\n"
      "    import slate_tpu.linalg.lu as _lum\n"
      "    invp = np.argsort(np.asarray(_lum.pivots_to_perm(piv)))\n"
      "    piv2 = np.concatenate([piv[:k], np.arange(k + 1, m + 1)])\n"
      "    perm2 = np.asarray(_lum.pivots_to_perm(piv2))\n"
      "    lu = lu[invp[perm2]]\n"
      "a[...] = lu\n"
      "pv[...] = piv[:k]\n",
      c.locals);
}

int slate_dgetrf(int64_t m, int64_t n, double* A, int64_t lda,
                 int64_t* ipiv) {
  return getrf_impl('d', m, n, A, lda, ipiv, 8);
}

int slate_sgetrf(int64_t m, int64_t n, float* A, int64_t lda, int64_t* ipiv) {
  return getrf_impl('s', m, n, A, lda, ipiv, 4);
}

static int getrs_impl(char dtc, char trans, int64_t n, int64_t nrhs,
                      const void* A, int64_t lda, const int64_t* ipiv,
                      void* B, int64_t ldb, int64_t esz) {
  Call c;
  if (!c.ok) return -999;
  set_mem(c.locals, "Abuf", const_cast<void*>(A), lda * n * esz);
  set_mem(c.locals, "Pbuf", const_cast<int64_t*>(ipiv), n * 8);
  set_mem(c.locals, "Bbuf", B, ldb * nrhs * esz);
  set_chr(c.locals, "trans", trans);
  set_int(c.locals, "n", n);
  set_int(c.locals, "nrhs", nrhs);
  set_int(c.locals, "lda", lda);
  set_int(c.locals, "ldb", ldb);
  set_chr(c.locals, "dtc", dtc);
  return run_code(
      "dt = np.float64 if dtc == 'd' else np.float32\n"
      "a = np.frombuffer(Abuf, dt).reshape((lda, -1), order='F')[:n, :n]\n"
      "pv = np.frombuffer(Pbuf, np.int64)[:n]\n"
      "b = np.frombuffer(Bbuf, dt).reshape((ldb, -1), order='F')[:n, :nrhs]\n"
      "slv = sk.pdgetrs if dtc == 'd' else sk.psgetrs\n"
      "b[...] = slv(trans, a.copy(), pv.copy(), b.copy())\n"
      "info = 0\n",
      c.locals);
}

int slate_dgetrs(char trans, int64_t n, int64_t nrhs, const double* A,
                 int64_t lda, const int64_t* ipiv, double* B, int64_t ldb) {
  return getrs_impl('d', trans, n, nrhs, A, lda, ipiv, B, ldb, 8);
}

int slate_sgetrs(char trans, int64_t n, int64_t nrhs, const float* A,
                 int64_t lda, const int64_t* ipiv, float* B, int64_t ldb) {
  return getrs_impl('s', trans, n, nrhs, A, lda, ipiv, B, ldb, 4);
}

static int trsm_impl(char dtc, char side, char uplo, char transa, char diag,
                     int64_t m, int64_t n, double alpha, const void* A,
                     int64_t lda, void* B, int64_t ldb, int64_t esz) {
  Call c;
  if (!c.ok) return -999;
  int64_t ka = (side == 'l' || side == 'L') ? m : n;
  set_mem(c.locals, "Abuf", const_cast<void*>(A), lda * ka * esz);
  set_mem(c.locals, "Bbuf", B, ldb * n * esz);
  set_chr(c.locals, "side", side);
  set_chr(c.locals, "uplo", uplo);
  set_chr(c.locals, "transa", transa);
  set_chr(c.locals, "diag", diag);
  set_int(c.locals, "m", m);
  set_int(c.locals, "n", n);
  set_int(c.locals, "lda", lda);
  set_int(c.locals, "ldb", ldb);
  set_dbl(c.locals, "alpha", alpha);
  set_chr(c.locals, "dtc", dtc);
  return run_code(
      "dt = np.float64 if dtc == 'd' else np.float32\n"
      "ka = m if side.lower() == 'l' else n\n"
      "a = np.frombuffer(Abuf, dt).reshape((lda, -1), order='F')[:ka, :ka]\n"
      "b = np.frombuffer(Bbuf, dt).reshape((ldb, -1), order='F')[:m, :n]\n"
      "fn = sk.pdtrsm if dtc == 'd' else sk.pstrsm\n"
      "b[...] = fn(side, uplo, transa, diag, dt(alpha), a.copy(), b.copy())\n"
      "info = 0\n",
      c.locals);
}

int slate_dtrsm(char side, char uplo, char transa, char diag, int64_t m,
                int64_t n, double alpha, const double* A, int64_t lda,
                double* B, int64_t ldb) {
  return trsm_impl('d', side, uplo, transa, diag, m, n, alpha, A, lda, B,
                   ldb, 8);
}

int slate_strsm(char side, char uplo, char transa, char diag, int64_t m,
                int64_t n, float alpha, const float* A, int64_t lda,
                float* B, int64_t ldb) {
  return trsm_impl('s', side, uplo, transa, diag, m, n, alpha, A, lda, B,
                   ldb, 4);
}

int slate_dsyevx(char jobz, char uplo, int64_t n, double* A, int64_t lda,
                 int64_t il, int64_t iu, double* W, double* Z, int64_t ldz) {
  /* LAPACK-style argument validation: info = -(1-based position of the first
   * invalid argument), checked before the runtime spins up.  jobz='v' with a
   * NULL Z used to be accepted and silently dropped the vectors with info=0. */
  bool wantz = (jobz == 'v' || jobz == 'V');
  if (!wantz && jobz != 'n' && jobz != 'N') return -1;
  if (uplo != 'l' && uplo != 'L' && uplo != 'u' && uplo != 'U') return -2;
  if (n < 0) return -3;
  if (A == nullptr) return -4;
  if (lda < (n > 1 ? n : 1)) return -5;
  if (il < 1) return -6;
  if (iu > n || iu < il) return -7;
  if (W == nullptr) return -8;
  if (wantz && Z == nullptr) return -9;
  if (wantz && ldz < (n > 1 ? n : 1)) return -10;
  Call c;
  if (!c.ok) return -999;
  int64_t k = iu - il + 1;
  set_mem(c.locals, "Abuf", A, lda * n * 8);
  set_mem(c.locals, "Wbuf", W, k * 8);
  if (Z != nullptr) set_mem(c.locals, "Zbuf", Z, ldz * k * 8);
  set_chr(c.locals, "jobz", jobz);
  set_chr(c.locals, "uplo", uplo);
  set_int(c.locals, "n", n);
  set_int(c.locals, "lda", lda);
  set_int(c.locals, "il", il);
  set_int(c.locals, "iu", iu);
  set_int(c.locals, "ldz", ldz);
  return run_code(
      "from slate_tpu import lapack_api as _lp\n"
      "a = np.frombuffer(Abuf, np.float64).reshape((lda, -1), order='F')[:n, :n]\n"
      "lam, z = _lp.dsyevx(jobz, uplo, a.copy(), il, iu)\n"
      "k = iu - il + 1\n"
      "np.frombuffer(Wbuf, np.float64)[:k] = np.asarray(lam)\n"
      "if z is not None and 'Zbuf' in dir():\n"
      "    zf = np.frombuffer(Zbuf, np.float64).reshape((ldz, -1), order='F')\n"
      "    zf[:n, :k] = np.asarray(z)\n"
      "info = 0\n",
      c.locals);
}

int slate_dgesvdx(char jobu, char jobvt, int64_t m, int64_t n, double* A,
                  int64_t lda, int64_t il, int64_t iu, double* S,
                  double* U, int64_t ldu, double* VT, int64_t ldvt) {
  /* LAPACK-style argument validation: info = -(1-based position of the first
   * invalid argument), checked before the runtime spins up.  jobu/jobvt='v'
   * with NULL U/VT used to be accepted and silently dropped the vectors with
   * info=0.  Header contract: U is m x k (ldu >= m), VT is k x n (ldvt >= k). */
  bool wantu = (jobu == 'v' || jobu == 'V');
  if (!wantu && jobu != 'n' && jobu != 'N') return -1;
  bool wantvt = (jobvt == 'v' || jobvt == 'V');
  if (!wantvt && jobvt != 'n' && jobvt != 'N') return -2;
  if (m < 0) return -3;
  if (n < 0) return -4;
  if (A == nullptr) return -5;
  if (lda < (m > 1 ? m : 1)) return -6;
  int64_t kmin = m < n ? m : n;
  int64_t k = iu - il + 1;
  if (il < 1) return -7;
  if (iu > kmin || iu < il) return -8;
  if (S == nullptr) return -9;
  if (wantu && U == nullptr) return -10;
  if (wantu && ldu < (m > 1 ? m : 1)) return -11;
  if (wantvt && VT == nullptr) return -12;
  if (wantvt && ldvt < (k > 1 ? k : 1)) return -13;
  Call c;
  if (!c.ok) return -999;
  set_mem(c.locals, "Abuf", A, lda * n * 8);
  set_mem(c.locals, "Sbuf", S, k * 8);
  if (U != nullptr) set_mem(c.locals, "Ubuf", U, ldu * k * 8);
  if (VT != nullptr) set_mem(c.locals, "Vbuf", VT, ldvt * n * 8);
  set_chr(c.locals, "jobu", jobu);
  set_chr(c.locals, "jobvt", jobvt);
  set_int(c.locals, "m", m);
  set_int(c.locals, "n", n);
  set_int(c.locals, "lda", lda);
  set_int(c.locals, "il", il);
  set_int(c.locals, "iu", iu);
  set_int(c.locals, "ldu", ldu);
  set_int(c.locals, "ldvt", ldvt);
  return run_code(
      "from slate_tpu import lapack_api as _lp\n"
      "a = np.frombuffer(Abuf, np.float64).reshape((lda, -1), order='F')[:m, :n]\n"
      "s, u, vt = _lp.dgesvdx(jobu, jobvt, a.copy(), il, iu)\n"
      "k = iu - il + 1\n"
      "np.frombuffer(Sbuf, np.float64)[:k] = np.asarray(s)\n"
      "if u is not None and 'Ubuf' in dir():\n"
      "    uf = np.frombuffer(Ubuf, np.float64).reshape((ldu, -1), order='F')\n"
      "    uf[:m, :k] = np.asarray(u)\n"
      "if vt is not None and 'Vbuf' in dir():\n"
      "    vf = np.frombuffer(Vbuf, np.float64).reshape((ldvt, -1), order='F')\n"
      "    vf[:k, :n] = np.asarray(vt)\n"
      "info = 0\n",
      c.locals);
}

int slate_dsygv(int64_t itype, char jobz, char uplo, int64_t n, double* A,
                int64_t lda, double* B, int64_t ldb, double* W) {
  Call c;
  if (!c.ok) return -999;
  set_mem(c.locals, "Abuf", A, lda * n * 8);
  set_mem(c.locals, "Bbuf", B, ldb * n * 8);
  set_mem(c.locals, "Wbuf", W, n * 8);
  set_int(c.locals, "itype", itype);
  set_chr(c.locals, "jobz", jobz);
  set_chr(c.locals, "uplo", uplo);
  set_int(c.locals, "n", n);
  set_int(c.locals, "lda", lda);
  set_int(c.locals, "ldb", ldb);
  return run_code(
      "a = np.frombuffer(Abuf, np.float64).reshape((lda, -1), order='F')[:n, :n]\n"
      "bm = np.frombuffer(Bbuf, np.float64).reshape((ldb, -1), order='F')[:n, :n]\n"
      "w = np.frombuffer(Wbuf, np.float64)[:n]\n"
      "# factor B first (LAPACK order: non-SPD B -> info = n + i, eigensolve\n"
      "# skipped); the driver re-factors internally — an accepted duplicate\n"
      "# worth ~n^3/3 next to the O(n^3) eigensolve, in exchange for the\n"
      "# returned info and triangle coming from ONE factorization\n"
      "Lf, finfo = sk.pdpotrf(uplo, bm.copy())\n"
      "if finfo != 0:\n"
      "    info = int(n) + int(finfo)\n"
      "else:\n"
      "    mask = np.tril(np.ones((n, n), bool)) if uplo.lower().startswith('l') "
      "else np.triu(np.ones((n, n), bool))\n"
      "    lam, z = sk.pdsygv(int(itype), jobz, uplo, a.copy(), bm.copy())\n"
      "    w[...] = np.asarray(lam, np.float64)\n"
      "    if jobz.lower() == 'v' and z is not None:\n"
      "        a[...] = np.asarray(z, np.float64)\n"
      "    bm[mask] = np.asarray(Lf, np.float64)[mask]\n"
      "    info = 0\n",
      c.locals);
}

// ---------------------------------------------------------------------------
// matrix-object handles (reference slate_Matrix_create mirror)

static int64_t matrix_create_impl(char dtc, int64_t m, int64_t n,
                                  const void* data, int64_t lda,
                                  int64_t esz) {
  Call c;
  if (!c.ok) return 0;
  set_mem(c.locals, "Dbuf", const_cast<void*>(data), lda * n * esz);
  set_int(c.locals, "m", m);
  set_int(c.locals, "n", n);
  set_int(c.locals, "lda", lda);
  set_chr(c.locals, "dtc", dtc);
  int64_t h = run_code(
      "dt = _DT[dtc]\n"
      "arr = np.frombuffer(Dbuf, dt).reshape((lda, -1), order='F')[:m, :n]\n"
      "_handles[_next_handle[0]] = np.ascontiguousarray(arr).copy()\n"
      "info = _next_handle[0]\n"
      "_next_handle[0] += 1\n",
      c.locals);
  return h > 0 ? h : 0;
}

int64_t slate_matrix_create_d(int64_t m, int64_t n, const double* data,
                              int64_t lda) {
  return matrix_create_impl('d', m, n, data, lda, 8);
}

int64_t slate_matrix_create_s(int64_t m, int64_t n, const float* data,
                              int64_t lda) {
  return matrix_create_impl('s', m, n, data, lda, 4);
}

int64_t slate_matrix_create_z(int64_t m, int64_t n, const void* data,
                              int64_t lda) {
  return matrix_create_impl('z', m, n, data, lda, 16);
}

int64_t slate_matrix_create_c(int64_t m, int64_t n, const void* data,
                              int64_t lda) {
  return matrix_create_impl('c', m, n, data, lda, 8);
}

static int matrix_read_impl(char dtc, int64_t h, void* out, int64_t ld,
                            int64_t esz) {
  Call c;
  if (!c.ok) return -999;
  set_int(c.locals, "h", h);
  set_int(c.locals, "ld", ld);
  set_chr(c.locals, "dtc", dtc);
  // stage 1: look up shape so the out view can be sized server-side
  int rc = run_code(
      "a = _handles.get(int(h))\n"
      "info = 0 if a is not None else -1\n"
      "if a is not None:\n"
      "    rows, cols = a.shape\n",
      c.locals);
  if (rc != 0) return rc;
  PyObject* ro = PyDict_GetItemString(c.locals, "rows");
  PyObject* co = PyDict_GetItemString(c.locals, "cols");
  if (ro == nullptr || co == nullptr) return -1;
  int64_t cols = PyLong_AsLongLong(co);
  int64_t rows = PyLong_AsLongLong(ro);
  if (ld < rows) return -7;   // undersized ld: distinct code, not a broadcast
                              // exception surfaced as a generic failure
  set_mem(c.locals, "Obuf", out, ld * cols * esz);
  return run_code(
      "dt = _DT[dtc]\n"
      "om = np.frombuffer(Obuf, dt).reshape((ld, -1), order='F')\n"
      "om[:rows, :cols] = a\n"
      "info = 0\n",
      c.locals);
}

int slate_matrix_read_d(int64_t h, double* out, int64_t ld) {
  return matrix_read_impl('d', h, out, ld, 8);
}

int slate_matrix_read_s(int64_t h, float* out, int64_t ld) {
  return matrix_read_impl('s', h, out, ld, 4);
}

int slate_matrix_read_z(int64_t h, void* out, int64_t ld) {
  return matrix_read_impl('z', h, out, ld, 16);
}

int slate_matrix_read_c(int64_t h, void* out, int64_t ld) {
  return matrix_read_impl('c', h, out, ld, 8);
}

void slate_matrix_destroy(int64_t h) {
  Call c;
  if (!c.ok) return;
  set_int(c.locals, "h", h);
  run_code("_handles.pop(int(h), None)\ninfo = 0\n", c.locals);
}

int slate_matrix_gemm(char transa, char transb, double alpha, int64_t hA,
                      int64_t hB, double beta, int64_t hC) {
  Call c;
  if (!c.ok) return -999;
  set_chr(c.locals, "ta", transa);
  set_chr(c.locals, "tb", transb);
  set_dbl(c.locals, "alpha", alpha);
  set_dbl(c.locals, "beta", beta);
  set_int(c.locals, "ha", hA);
  set_int(c.locals, "hb", hB);
  set_int(c.locals, "hc", hC);
  return run_code(
      "a, b, cm = (_handles.get(int(x)) for x in (ha, hb, hc))\n"
      "if a is None or b is None or cm is None:\n"
      "    info = -1\n"
      "else:\n"
      "    fn = sk.pdgemm if cm.dtype == np.float64 else sk.psgemm\n"
      "    _handles[int(hc)] = np.asarray(\n"
      "        fn(ta, tb, cm.dtype.type(alpha), a, b, cm.dtype.type(beta),\n"
      "           cm.copy()), cm.dtype)\n"
      "    info = 0\n",
      c.locals);
}

int slate_matrix_potrf(int64_t h, char uplo) {
  Call c;
  if (!c.ok) return -999;
  set_int(c.locals, "h", h);
  set_chr(c.locals, "uplo", uplo);
  return run_code(
      "a = _handles.get(int(h))\n"
      "if a is None:\n"
      "    info = -1\n"
      "else:\n"
      "    fn = sk.pdpotrf if a.dtype == np.float64 else sk.pspotrf\n"
      "    Lf, info = fn(uplo, a.copy())\n"
      "    if info == 0:\n"
      "        _handles[int(h)] = np.asarray(Lf, a.dtype)\n",
      c.locals);
}

int slate_matrix_gesv(int64_t hA, int64_t hB) {
  Call c;
  if (!c.ok) return -999;
  set_int(c.locals, "ha", hA);
  set_int(c.locals, "hb", hB);
  return run_code(
      "a, b = _handles.get(int(ha)), _handles.get(int(hb))\n"
      "if a is None or b is None:\n"
      "    info = -1\n"
      "else:\n"
      "    fac = sk.pdgetrf if a.dtype == np.float64 else sk.psgetrf\n"
      "    slv = sk.pdgetrs if a.dtype == np.float64 else sk.psgetrs\n"
      "    lu, piv, info = fac(a.copy())\n"
      "    if info == 0:\n"
      "        _handles[int(hb)] = np.asarray(\n"
      "            slv('n', lu, piv, b.copy()), b.dtype)\n",
      c.locals);
}

int slate_matrix_syev(int64_t h, char jobz, char uplo, double* W) {
  Call c;
  if (!c.ok) return -999;
  set_int(c.locals, "h", h);
  set_chr(c.locals, "jobz", jobz);
  set_chr(c.locals, "uplo", uplo);
  // stage 1: size the W view from the handle
  int rc = run_code(
      "a = _handles.get(int(h))\n"
      "info = 0 if a is not None else -1\n"
      "if a is not None:\n"
      "    rows = a.shape[0]\n",
      c.locals);
  if (rc != 0) return rc;
  PyObject* ro = PyDict_GetItemString(c.locals, "rows");
  if (ro == nullptr) return -1;
  int64_t n = PyLong_AsLongLong(ro);
  set_mem(c.locals, "Wbuf", W, n * 8);
  return run_code(
      "letter = {np.dtype(np.float32): 's', np.dtype(np.float64): 'd',\n"
      "          np.dtype(np.complex64): 'c', np.dtype(np.complex128): 'z'}"
      "[a.dtype]\n"
      "name = 'heev' if letter in 'cz' else 'syev'\n"
      "lam, z = getattr(sk, 'p' + letter + name)(jobz, uplo, a.copy())\n"
      "np.frombuffer(Wbuf, np.float64)[:rows] = np.asarray(lam, np.float64)\n"
      "if jobz.lower() == 'v' and z is not None:\n"
      "    _handles[int(h)] = np.asarray(z, a.dtype)\n"
      "info = 0\n",
      c.locals);
}

int slate_matrix_gesvd(int64_t h, double* S, int64_t* hU, int64_t* hVT) {
  Call c;
  if (!c.ok) return -999;
  set_int(c.locals, "h", h);
  set_int(c.locals, "wantu", hU != nullptr);
  set_int(c.locals, "wantv", hVT != nullptr);
  int rc = run_code(
      "a = _handles.get(int(h))\n"
      "info = 0 if a is not None else -1\n"
      "if a is not None:\n"
      "    kmin = min(a.shape)\n",
      c.locals);
  if (rc != 0) return rc;
  PyObject* ko = PyDict_GetItemString(c.locals, "kmin");
  if (ko == nullptr) return -1;
  int64_t k = PyLong_AsLongLong(ko);
  set_mem(c.locals, "Sbuf", S, k * 8);
  rc = run_code(
      "letter = {np.dtype(np.float32): 's', np.dtype(np.float64): 'd',\n"
      "          np.dtype(np.complex64): 'c', np.dtype(np.complex128): 'z'}"
      "[a.dtype]\n"
      "ju = 's' if wantu else 'n'\n"
      "jv = 's' if wantv else 'n'\n"
      "s, u, vt = getattr(sk, 'p' + letter + 'gesvd')(ju, jv, a.copy())\n"
      "np.frombuffer(Sbuf, np.float64)[:kmin] = "
      "np.asarray(np.real(s), np.float64)[:kmin]\n"
      "hu = hv = 0\n"
      "if wantu and u is not None:\n"
      "    _handles[_next_handle[0]] = np.ascontiguousarray("
      "np.asarray(u, a.dtype))\n"
      "    hu = _next_handle[0]; _next_handle[0] += 1\n"
      "if wantv and vt is not None:\n"
      "    _handles[_next_handle[0]] = np.ascontiguousarray("
      "np.asarray(vt, a.dtype))\n"
      "    hv = _next_handle[0]; _next_handle[0] += 1\n"
      "info = 0\n",
      c.locals);
  if (rc != 0) return rc;
  if (hU != nullptr) {
    PyGILState_STATE g2 = PyGILState_Ensure();
    PyObject* v = PyDict_GetItemString(c.locals, "hu");
    *hU = v != nullptr ? PyLong_AsLongLong(v) : 0;
    PyGILState_Release(g2);
  }
  if (hVT != nullptr) {
    PyGILState_STATE g2 = PyGILState_Ensure();
    PyObject* v = PyDict_GetItemString(c.locals, "hv");
    *hVT = v != nullptr ? PyLong_AsLongLong(v) : 0;
    PyGILState_Release(g2);
  }
  return 0;
}

double slate_dlange(char norm, int64_t m, int64_t n, const double* A,
                    int64_t lda) {
  Call c;
  if (!c.ok) return -1.0;
  set_mem(c.locals, "Abuf", const_cast<double*>(A), lda * n * 8);
  set_chr(c.locals, "norm", norm);
  set_int(c.locals, "m", m);
  set_int(c.locals, "n", n);
  set_int(c.locals, "lda", lda);
  return run_code_dbl(
      "a = np.frombuffer(Abuf, np.float64).reshape((lda, -1), order='F')[:m, :n]\n"
      "val = float(sk.pdlange(norm, a))\n",
      c.locals, "val");
}

}  // extern "C"
