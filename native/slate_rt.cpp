// slate_rt — native host-side runtime for slate_tpu.
//
// Reference analogue: the C++ runtime layer of the reference —
//   * include/slate/func.hh block-cyclic tile->rank lambdas and
//     include/slate/internal/MatrixStorage.hh's tile directory,
//   * src/core/Memory.cc fixed-block free-list pool (per-device tile allocator),
//   * src/auxiliary/Trace.cc low-overhead event recording.
//
// On TPU the device compute path is XLA/Pallas, but the *host* bookkeeping —
// owner-map materialization over large tile grids, local-tile enumeration,
// redistribution planning, workspace-pool accounting, trace event capture — is
// exactly the kind of integer-heavy, allocation-free work the reference keeps in
// C++.  This library provides those pieces behind a plain C ABI consumed via
// ctypes (slate_tpu/native.py), with pure-Python fallbacks when the shared
// library is unavailable.
//
// Build: `make` in this directory (g++ -O3 -shared -fPIC).

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// block-cyclic maps (func.hh:100-217; GridOrder col=0 / row=1)

static inline int32_t tile_rank(int64_t i, int64_t j, int32_t p, int32_t q,
                                int32_t order) {
    return order == 0 ? static_cast<int32_t>((i % p) + (j % q) * p)
                      : static_cast<int32_t>((i % p) * q + (j % q));
}

// Fill the full mt x nt owner map (row-major out[i*nt + j]).
void srt_owner_map(int64_t mt, int64_t nt, int32_t p, int32_t q, int32_t order,
                   int32_t* out) {
    for (int64_t i = 0; i < mt; ++i) {
        int64_t ip = i % p;
        for (int64_t j = 0; j < nt; ++j) {
            int64_t jq = j % q;
            out[i * nt + j] = order == 0
                ? static_cast<int32_t>(ip + jq * p)
                : static_cast<int32_t>(ip * q + jq);
        }
    }
}

// Enumerate the tiles owned by `rank`; fills (i, j) pairs when out != nullptr.
// Returns the count either way (call once with nullptr to size the buffer).
int64_t srt_local_tiles(int64_t mt, int64_t nt, int32_t p, int32_t q,
                        int32_t order, int32_t rank, int64_t* out) {
    int64_t count = 0;
    for (int64_t i = 0; i < mt; ++i)
        for (int64_t j = 0; j < nt; ++j)
            if (tile_rank(i, j, p, q, order) == rank) {
                if (out) { out[2 * count] = i; out[2 * count + 1] = j; }
                ++count;
            }
    return count;
}

// Redistribution plan between two block-cyclic layouts (src/redistribute.cc:
// the reference walks every tile and isend/irecvs those whose owner changes).
// Fills per-tile src/dst rank maps (row-major) and returns the number of tiles
// that actually move.
int64_t srt_redist_plan(int64_t mt, int64_t nt,
                        int32_t p1, int32_t q1, int32_t order1,
                        int32_t p2, int32_t q2, int32_t order2,
                        int32_t* src, int32_t* dst) {
    int64_t moved = 0;
    for (int64_t i = 0; i < mt; ++i)
        for (int64_t j = 0; j < nt; ++j) {
            int32_t s = tile_rank(i, j, p1, q1, order1);
            int32_t d = tile_rank(i, j, p2, q2, order2);
            src[i * nt + j] = s;
            dst[i * nt + j] = d;
            if (s != d) ++moved;
        }
    return moved;
}

// ---------------------------------------------------------------------------
// fixed-block memory pool accounting (src/core/Memory.cc free list — here the
// bookkeeping layer for HBM workspace budgeting: XLA owns the actual bytes)

struct SrtPool {
    int64_t block_bytes;
    std::vector<int64_t> free_list;
    std::vector<uint8_t> in_use;   // per block-id
    int64_t peak;
    std::mutex mu;
};

void* srt_pool_new(int64_t block_bytes, int64_t nblocks) {
    auto* pool = new SrtPool();
    pool->block_bytes = block_bytes;
    pool->in_use.assign(static_cast<size_t>(nblocks), 0);
    pool->free_list.reserve(static_cast<size_t>(nblocks));
    for (int64_t b = nblocks - 1; b >= 0; --b) pool->free_list.push_back(b);
    pool->peak = 0;
    return pool;
}

void srt_pool_delete(void* p) { delete static_cast<SrtPool*>(p); }

// Returns a block id, or -1 when exhausted (Memory::alloc grows on demand in the
// reference; on TPU exhaustion must surface so the planner can spill/refit).
int64_t srt_pool_alloc(void* p) {
    auto* pool = static_cast<SrtPool*>(p);
    std::lock_guard<std::mutex> lock(pool->mu);
    if (pool->free_list.empty()) return -1;
    int64_t id = pool->free_list.back();
    pool->free_list.pop_back();
    pool->in_use[static_cast<size_t>(id)] = 1;
    int64_t used = static_cast<int64_t>(pool->in_use.size())
                 - static_cast<int64_t>(pool->free_list.size());
    if (used > pool->peak) pool->peak = used;
    return id;
}

// Returns 0 on success, -1 on double-free / bad id (Debug.cc leak checks).
int32_t srt_pool_free(void* p, int64_t id) {
    auto* pool = static_cast<SrtPool*>(p);
    std::lock_guard<std::mutex> lock(pool->mu);
    if (id < 0 || id >= static_cast<int64_t>(pool->in_use.size()) ||
        !pool->in_use[static_cast<size_t>(id)])
        return -1;
    pool->in_use[static_cast<size_t>(id)] = 0;
    pool->free_list.push_back(id);
    return 0;
}

int64_t srt_pool_in_use(void* p) {
    auto* pool = static_cast<SrtPool*>(p);
    std::lock_guard<std::mutex> lock(pool->mu);
    return static_cast<int64_t>(pool->in_use.size())
         - static_cast<int64_t>(pool->free_list.size());
}

int64_t srt_pool_capacity(void* p) {
    return static_cast<int64_t>(static_cast<SrtPool*>(p)->in_use.size());
}

int64_t srt_pool_peak(void* p) { return static_cast<SrtPool*>(p)->peak; }

// ---------------------------------------------------------------------------
// trace event capture (Trace.cc: per-thread event vectors + one writer; here a
// mutex-guarded vector + chrome://tracing JSON dump, the portable successor of
// the reference's SVG timeline)

struct SrtEvent {
    std::string name;
    double ts_us;     // event time
    double dur_us;    // duration (complete events)
    int32_t tid;
};

static std::vector<SrtEvent> g_events;
static std::mutex g_trace_mu;
static bool g_trace_on = false;
static const auto g_t0 = std::chrono::steady_clock::now();

// per-thread open-block stacks, matching Trace.cc's per-thread event vectors:
// begin/end pairs from different threads must never cross
static thread_local std::vector<SrtEvent> t_open;
static std::atomic<int32_t> g_next_tid{0};
static thread_local int32_t t_tid = -1;

static int32_t my_tid() {
    if (t_tid < 0) t_tid = g_next_tid.fetch_add(1);
    return t_tid;
}

static double now_us() {
    return std::chrono::duration<double, std::micro>(
        std::chrono::steady_clock::now() - g_t0).count();
}

void srt_trace_enable(int32_t on) {
    std::lock_guard<std::mutex> lock(g_trace_mu);
    g_trace_on = on != 0;
}

void srt_trace_begin(const char* name) {
    {
        std::lock_guard<std::mutex> lock(g_trace_mu);
        if (!g_trace_on) return;
    }
    t_open.push_back({name ? name : "", now_us(), 0.0, my_tid()});
}

void srt_trace_end() {
    if (t_open.empty()) return;
    SrtEvent ev = t_open.back();
    t_open.pop_back();
    ev.dur_us = now_us() - ev.ts_us;
    std::lock_guard<std::mutex> lock(g_trace_mu);
    if (g_trace_on) g_events.push_back(std::move(ev));
}

int64_t srt_trace_count() {
    std::lock_guard<std::mutex> lock(g_trace_mu);
    return static_cast<int64_t>(g_events.size());
}

void srt_trace_clear() {
    std::lock_guard<std::mutex> lock(g_trace_mu);
    g_events.clear();
    t_open.clear();
}

// Minimal JSON string escaping (quotes, backslashes, control chars) so arbitrary
// block names can't corrupt the dump.
static std::string json_escape(const std::string& s) {
    std::string out;
    out.reserve(s.size() + 8);
    for (unsigned char c : s) {
        switch (c) {
            case '"': out += "\\\""; break;
            case '\\': out += "\\\\"; break;
            case '\n': out += "\\n"; break;
            case '\t': out += "\\t"; break;
            case '\r': out += "\\r"; break;
            default:
                if (c < 0x20) {
                    char buf[8];
                    std::snprintf(buf, sizeof buf, "\\u%04x", c);
                    out += buf;
                } else {
                    out += static_cast<char>(c);
                }
        }
    }
    return out;
}

// Chrome trace-event JSON ("X" complete events). Returns 0 on success.
int32_t srt_trace_dump(const char* path) {
    std::lock_guard<std::mutex> lock(g_trace_mu);
    FILE* f = std::fopen(path, "w");
    if (!f) return -1;
    std::fputs("{\"traceEvents\":[", f);
    for (size_t k = 0; k < g_events.size(); ++k) {
        const auto& ev = g_events[k];
        std::fprintf(f,
            "%s{\"name\":\"%s\",\"ph\":\"X\",\"pid\":0,\"tid\":%d,"
            "\"ts\":%.3f,\"dur\":%.3f}",
            k ? "," : "", json_escape(ev.name).c_str(), ev.tid, ev.ts_us,
            ev.dur_us);
    }
    std::fputs("]}", f);
    std::fclose(f);
    return 0;
}

}  // extern "C"
