"""slate_tpu — a TPU-native distributed dense linear algebra framework.

A brand-new JAX/XLA/Pallas re-design with the capabilities of SLATE (Software for Linear
Algebra Targeting Exascale): tiled distributed matrices with pluggable 2D block-cyclic
layouts, parallel BLAS-3, linear solvers (Cholesky / LU variants / mixed-precision
iterative refinement / band), least squares (QR, CholQR), and eigenvalue/SVD drivers —
where a ``jax.sharding.Mesh`` over a TPU pod slice replaces the reference's MPI process
grid, ICI collectives replace tile broadcasts, and XLA/Pallas kernels replace
cuBLAS/CUDA (see SURVEY.md for the layer-by-layer mapping).

Public API mirrors ``include/slate/slate.hh`` (~92 routines) with snake_case names; the
verb-style convenience layer mirroring ``include/slate/simplified_api.hh`` lives in
:mod:`slate_tpu.simplified`.
"""

from .core import (BandMatrix, BaseMatrix, ConvergenceError,
                   DeadlineExceededError, Diag, GridOrder,
                   HermitianBandMatrix, HermitianMatrix, Layout, Matrix,
                   MethodCholQR, MethodEig, MethodGels, MethodGemm, MethodHemm,
                   MethodLU, MethodSVD, MethodTrsm, Norm, NormScope,
                   NumericalError, Op, Options, QueueOverloadError, Side,
                   SingularMatrixError, SlateError, SymmetricMatrix, Target,
                   TileKind, TrapezoidMatrix, TriangularBandMatrix,
                   TriangularMatrix, Uplo, func)

from .blas import (add, col_norms, copy, gemm, gemmA, gemmC, hemm, hemmA,
                   hemmC, her2k, herk, norm, scale, scale_row_col, set,
                   set_from_function, set_lambdas, symm, syr2k, syrk, trmm,
                   trsm, trsmA, trsmB)
from .linalg import (bdsqr, cholqr, gbmm, gbsv, gbtrf, gbtrs, ge2tb, ge2tb_band, gecondest,
                     gelqf, gels, gels_cholqr, gels_qr, geqrf, gerbt, gesv,
                     gesv_mixed, gesv_mixed_gmres, gesv_nopiv, gesv_rbt, getrf,
                     getrf_nopiv, getrf_tntpiv, getri, getri_oop, getrs,
                     getrs_nopiv, hb2st, hbmm, he2hb, he2hb_q, heev,
                     heev_range, eig_count, hegst, hegv_range,
                     hegv, hesv, hetrf, hetrs, norm1est, pbsv, pbtrf, pbtrs,
                     pocondest, posv, posv_mixed, posv_mixed_gmres, potrf, potri,
                     potrs, stedc, stedc_deflate, stedc_merge, stedc_secular,
                     stedc_solve, stedc_sort, stedc_z_vector, stein, steqr,
                     steqr2, sterf, sterf_bisect, svd, svd_range, svd_vals,
                     syev, sygst,
                     sygv, sysv, sytrf,
                     sytrs, tb2bd, tbsm, tbsm_pivots, tbsmPivots, trcondest,
                     trtri, trtrm, unmbr_ge2tb,
                     unmbr_tb2bd, unmlq, unmqr, unmtr_hb2st, unmtr_he2hb)
from . import robust
from .robust import (FaultPlan, FaultSpec, RetryPolicy, SolveReport,
                     reduce_info)
from . import serve
from .serve import gels_batched, gesv_batched, posv_batched
from . import simplified
from . import matgen
from . import native
from .utils import debug, load_matrix, print_matrix, save_matrix, trace
from .matgen import generate_matrix
from .ops.f64emu import gemm_f64emu, gesv_f64ir, posv_f64ir
from . import lapack_api
from . import scalapack_api

try:
    # distributed layer needs jax.shard_map / NamedSharding; single-device use of
    # the library must survive without it (blas.py raises a clear SlateError if a
    # SUMMA method is requested while it is absent)
    from . import parallel
except ImportError:  # pragma: no cover - environment-specific
    parallel = None

__version__ = "0.2.0"
VERSION = 2026_07_00   # yyyymmrr, the reference's integer form (version.cc)


def version() -> int:
    """Library version as the reference's yyyymmrr integer
    (src/version.cc: slate::version())."""
    return VERSION


def id() -> str:  # noqa: A001 - reference name (slate::id)
    """Git commit hash of this build, or "unknown" (src/version.cc: slate::id())."""
    import os
    import subprocess

    try:
        pkg = os.path.realpath(__path__[0])
        # an installed copy may sit under an unrelated enclosing repo — only
        # report a hash when the repo actually *tracks* this package
        tracked = subprocess.run(
            ["git", "ls-files", "--error-unmatch", pkg], capture_output=True,
            text=True, timeout=5, cwd=pkg)
        if tracked.returncode != 0:
            return "unknown"
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=5, cwd=pkg).stdout.strip() or "unknown"
    # slate-lint: disable=SLT501 -- git metadata probe: the block runs only
    # subprocess/os calls, the NumericalError taxonomy cannot arise here
    except Exception:
        return "unknown"
