"""slate-lint: JAX-aware static analysis + compile-time collective auditor.

Two tiers, one gate (``python -m slate_tpu.analysis --check``):

* **Tier A — AST linter** (:mod:`.rules` / :mod:`.lint`): ~10
  codebase-specific rules over the package's sources — tracer hygiene inside
  jitted/vmapped/shard_mapped cores, recompilation hazards, x64 scope leaks,
  leftover debug hooks, donation misuse, taxonomy-swallowing ``except``
  blocks, and missing ``@obs.instrument`` on public drivers.  Accepted
  pre-existing findings live in ``analysis/baseline.json`` (every entry with
  a written reason); anything new fails CI.
* **Tier B — collective race auditor** (:mod:`.collective_audit`): extends
  ``obs/costaudit.py``'s compiled-HLO walk from counting collectives to
  *ordering* them — per-participant schedules, channel discipline, and
  divergent-``lax.cond`` reachability for every AOT-audited distributed
  routine at P ∈ {2, 4, 8} on the virtual CPU mesh, zero TPU time.

The AST tier is pure-stdlib AST work, and this module keeps it that way:
the Tier B names below resolve lazily (PEP 562), so importing the linter
never pulls ``collective_audit`` → ``obs.costaudit``.  (The ``python -m``
CLI still executes the parent ``slate_tpu`` package init first — that, not
the analysis package, is what makes jax a runtime requirement of the
gate.)  Motivation (ISSUE 10): every proof channel this repo built before —
kernel_plan pins, SCALING_PINS, compile-count pins — was written *after* a
bug class bit us.  These passes reject the known classes before a TPU
capture window is spent on them.
"""

from .findings import Finding, SEVERITIES
from .rules import RULES, Rule, rule_table
from .lint import lint_file, lint_package, lint_paths, lint_source
from . import baseline

#: Tier B re-exports, resolved on first attribute access so the AST tier's
#: imports stay stdlib-only
_TIER_B = ("CollectiveEvent", "audit_compiled", "audit_hlo",
           "audit_routines", "extract_events", "participant_schedules",
           "verify_events", "verify_participant_schedules")

__all__ = [
    "Finding", "SEVERITIES", "RULES", "Rule", "rule_table",
    "lint_file", "lint_package", "lint_paths", "lint_source", "baseline",
] + list(_TIER_B)


def __getattr__(name):
    if name in _TIER_B:
        from . import collective_audit
        return getattr(collective_audit, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
