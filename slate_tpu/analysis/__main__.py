"""CLI for slate-lint: ``python -m slate_tpu.analysis``.

Modes::

    python -m slate_tpu.analysis                 # report all findings
    python -m slate_tpu.analysis --check         # CI gate: rc!=0 on any
                                                 # non-baseline finding or
                                                 # reason-less baseline entry
    python -m slate_tpu.analysis --update-baseline
    python -m slate_tpu.analysis --rules         # rule table
    python -m slate_tpu.analysis --collectives --pset 2,4,8
                                                 # Tier B ordering audit over
                                                 # the scaling registry

``tools/run_analysis.py`` wraps this main with the CPU-mesh bootstrap so the
collective audit can run outside pytest/CI environments too.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from . import baseline as baseline_mod
from .lint import lint_package
from .rules import RULES, rule_table


def _print_rules() -> None:
    print(f"{'ID':8s} {'severity':8s} title")
    for rid, sev, title in rule_table():
        print(f"{rid:8s} {sev:8s} {title}")
        doc = RULES[rid].doc.replace("\n", " ")
        print(f"{'':8s} {'':8s}   {doc}")


def _run_lint(args) -> int:
    findings = lint_package()
    doc = baseline_mod.load(args.baseline)
    problems = baseline_mod.validate(doc)
    new, accepted, stale = baseline_mod.apply(findings, doc)

    if args.update_baseline:
        out = baseline_mod.build(findings, prev=doc)
        path = baseline_mod.save(out, args.baseline)
        todo = sum(1 for e in out["entries"]
                   if e["reason"].startswith("TODO"))
        print(f"wrote {path}: {len(out['entries'])} entries"
              + (f" ({todo} need a reason before --check passes)"
                 if todo else ""))
        return 0

    for f in accepted:
        if args.verbose:
            print(f.render(baselined=True))
    for f in new:
        print(f.render())
        if f.suggestion and (args.explain or args.check):
            print(f"    fix: {f.suggestion}")
    for e in stale:
        print(f"stale baseline entry (no longer matches): "
              f"{e['rule']} {e['path']} :: {e['line_text'][:60]}")
    for p in problems:
        print(f"baseline problem: {p}")

    print(f"slate-lint: {len(findings)} finding(s), {len(accepted)} "
          f"baselined, {len(new)} new, {len(stale)} stale baseline "
          f"entr{'y' if len(stale) == 1 else 'ies'}")
    if args.check:
        return 1 if (new or problems) else 0
    return 0


def _run_collectives(args) -> int:
    from .collective_audit import audit_routines, summarize

    pset = [int(p) for p in args.pset.split(",") if p]
    names = [t for t in args.routines.split(",") if t] \
        if args.routines else None

    def progress(row):
        status = (row.get("error") or row.get("skipped")
                  or f"{row['collective_sites']} collective site(s), "
                     f"{len(row['findings'])} finding(s)")
        print(f"P={row['P']} {row['routine']:28s} {status}", flush=True)

    try:
        rows = audit_routines(pset, names=names, progress=progress)
    except (ValueError, RuntimeError) as e:
        # unknown routine names, or too few visible devices for the mesh
        # (make_grid raises RuntimeError without the tools/run_analysis.py
        # XLA_FLAGS bootstrap) — report cleanly, don't traceback
        print(f"error: {e}")
        return 2
    audited, nfind, lines = summarize(rows)
    for line in lines:
        print(f"RACE {line}")
    skipped = sum(1 for r in rows if r.get("skipped"))
    errors = [r for r in rows if r.get("error")]
    for r in errors:
        print(f"ERROR P={r['P']} {r['routine']}: {r['error']}")
    print(f"collective-audit: {audited} routine-compilations verified at "
          f"P∈{{{args.pset}}}, {skipped} skipped (grid constraints), "
          f"{len(errors)} compile errors, {nfind} schedule finding(s)")
    return 1 if (nfind or errors) else 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m slate_tpu.analysis",
        description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="CI gate: exit nonzero on non-baseline findings")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite analysis/baseline.json from current "
                         "findings (reasons carry over by fingerprint)")
    ap.add_argument("--baseline", default=None,
                    help="baseline path (default: analysis/baseline.json)")
    ap.add_argument("--rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--explain", action="store_true",
                    help="print fix suggestions under each finding")
    ap.add_argument("--verbose", action="store_true",
                    help="also print baselined findings")
    ap.add_argument("--collectives", action="store_true",
                    help="run the Tier B collective-ordering audit instead "
                         "of (or after) the AST tier")
    ap.add_argument("--pset", default="2,4,8",
                    help="device counts for --collectives (default 2,4,8)")
    ap.add_argument("--routines", default=None,
                    help="comma list of routine names for --collectives")
    args = ap.parse_args(argv)

    if args.check and args.update_baseline:
        # --update-baseline rewrites the baseline to absorb every current
        # finding, so a combined invocation would always "pass" — a CI job
        # wired that way gates nothing.  Refuse instead of silently skipping.
        ap.error("--check and --update-baseline are mutually exclusive "
                 "(updating the baseline makes the check vacuous)")
    if args.rules:
        _print_rules()
        return 0
    rc = 0
    if not args.collectives or args.check or args.update_baseline:
        rc = _run_lint(args)
    if args.collectives:
        rc = max(rc, _run_collectives(args))
    return rc


if __name__ == "__main__":
    sys.exit(main())
