"""Committed-baseline handling for slate-lint.

``analysis/baseline.json`` records pre-existing accepted findings so they
don't block CI while anything *new* fails it.  Every entry carries a
mandatory ``reason`` (the acceptance criterion: an accepted finding without
a written justification is itself a gate failure), and entries match
findings by the line-number-free fingerprint (rule, path, context,
line_text) so unrelated edits don't invalidate the file.

Matching is multiset-aware: an entry absorbs at most ``count`` occurrences
(default 1), so a second identical violation in the same function is a new
finding, not a free ride.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .findings import Finding

SCHEMA = "slate_tpu.lint_baseline/v1"

#: default baseline location, next to this module
DEFAULT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "baseline.json")


def load(path: Optional[str] = None) -> Dict[str, Any]:
    """Load the baseline document ({} shape when the file is absent)."""
    path = path or DEFAULT_PATH
    if not os.path.exists(path):
        return {"schema": SCHEMA, "entries": []}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != SCHEMA:
        raise ValueError(f"{path}: schema must be {SCHEMA!r}, "
                         f"got {doc.get('schema')!r}")
    return doc


def validate(doc: Dict[str, Any]) -> List[str]:
    """Structural problems in a baseline document (empty list = valid).

    The reason requirement is enforced here: the gate fails on an entry
    whose reason is missing/empty/TODO."""
    problems: List[str] = []
    entries = doc.get("entries")
    if not isinstance(entries, list):
        return ["entries must be a list"]
    for i, e in enumerate(entries):
        where = f"entry {i} ({e.get('rule')} {e.get('path')})"
        for key in ("rule", "path", "context", "line_text"):
            if not isinstance(e.get(key), str) or not e.get(key):
                problems.append(f"{where}: missing/empty {key!r}")
        reason = e.get("reason")
        if not isinstance(reason, str) or len(reason.strip()) < 8 \
                or reason.strip().upper().startswith("TODO"):
            problems.append(f"{where}: needs a real reason "
                            "(>= 8 chars, not TODO)")
        count = e.get("count", 1)
        if not isinstance(count, int) or isinstance(count, bool) or count < 1:
            problems.append(f"{where}: count must be a positive int")
    return problems


def _key(e: Dict[str, Any]) -> Tuple[str, str, str, str]:
    return (e["rule"], e["path"], e["context"], e["line_text"])


def _entry_count(e: Dict[str, Any]) -> Optional[int]:
    """The entry's finding budget, or None when malformed (a hand-edited
    ``"count": "two"`` must surface as a validate() problem, not a
    traceback out of the --check gate)."""
    c = e.get("count", 1)
    return c if isinstance(c, int) and not isinstance(c, bool) and c >= 1 \
        else None


def _well_formed(e: Any) -> bool:
    """Entry is usable by apply(): the four fingerprint fields are
    non-empty strings and the count is sane.  Hand-edited entries failing
    this are skipped here and reported by validate() — apply() must never
    traceback on them."""
    return (isinstance(e, dict)
            and all(isinstance(e.get(k), str) and e.get(k)
                    for k in ("rule", "path", "context", "line_text"))
            and _entry_count(e) is not None)


def apply(findings: Sequence[Finding], doc: Dict[str, Any]
          ) -> Tuple[List[Finding], List[Finding], List[Dict[str, Any]]]:
    """Partition findings against the baseline.

    Returns ``(new, accepted, stale_entries)`` — findings not covered by
    the baseline, findings absorbed by it, and baseline entries that no
    longer match anything (prime candidates for deletion; reported, not
    fatal, so a fix doesn't force a lockstep baseline edit)."""
    entries = [e for e in doc.get("entries", []) if _well_formed(e)]
    totals: Dict[Tuple[str, str, str, str], int] = {}
    for e in entries:
        totals[_key(e)] = totals.get(_key(e), 0) + _entry_count(e)
    budget = dict(totals)
    new: List[Finding] = []
    accepted: List[Finding] = []
    for f in findings:
        k = f.fingerprint()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            accepted.append(f)
        else:
            new.append(f)
    # stale: allocate each fingerprint's *consumed* budget to its entries
    # in file order; an entry none of whose count was needed is stale.
    # (Per-entry, not per-fingerprint: two duplicate entries pooling to
    # count 2 with one live finding must report exactly one stale, not
    # both — one of them is still absorbing.)
    used = {k: totals[k] - budget.get(k, 0) for k in totals}
    stale: List[Dict[str, Any]] = []
    for e in entries:
        k = _key(e)
        take = min(used.get(k, 0), _entry_count(e))
        used[k] = used.get(k, 0) - take
        if take == 0:
            stale.append(e)
    return new, accepted, stale


def build(findings: Sequence[Finding],
          prev: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Baseline document covering ``findings``; reasons carry over from
    ``prev`` where fingerprints match, else are stamped TODO for a human
    (the gate refuses TODO reasons, so --update-baseline output cannot be
    committed unreviewed)."""
    reasons: Dict[Tuple[str, str, str, str], str] = {}
    for e in (prev or {}).get("entries", []):
        if _well_formed(e) and isinstance(e.get("reason"), str):
            reasons[_key(e)] = e["reason"]
    counts: Dict[Tuple[str, str, str, str], int] = {}
    meta: Dict[Tuple[str, str, str, str], Finding] = {}
    for f in findings:
        k = f.fingerprint()
        counts[k] = counts.get(k, 0) + 1
        meta.setdefault(k, f)
    entries = []
    for k in sorted(counts):
        rule, path, context, line_text = k
        e: Dict[str, Any] = {
            "rule": rule, "path": path, "context": context,
            "line_text": line_text,
            "reason": reasons.get(k, "TODO: justify or fix"),
        }
        if counts[k] > 1:
            e["count"] = counts[k]
        entries.append(e)
    return {"schema": SCHEMA, "entries": entries}


def save(doc: Dict[str, Any], path: Optional[str] = None) -> str:
    path = path or DEFAULT_PATH
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=False)
        f.write("\n")
    return path
