"""Tier B: compile-time collective race auditor.

``obs/costaudit.py`` *counts* collectives; this module *orders* them.  For a
compiled SPMD executable it extracts the per-participant collective sequence
— op kind, channel id, replica groups, and the call context (while body /
conditional branch) each site sits in — and statically verifies schedule
consistency:

* **coverage** — every replica group names valid participants and no device
  appears twice in one collective's groups (a duplicated id deadlocks the
  rendezvous);
* **channel discipline** — no two distinct collective instructions share a
  channel id (interleaved channel reuse is how mismatched schedules corrupt
  each other's payloads);
* **uniform control flow** — no collective reachable only under a
  ``conditional`` branch (a ``lax.cond`` whose predicate diverges across
  participants leaves part of the mesh waiting at a rendezvous the rest
  never reaches: the classic distributed deadlock, caught at compile time);
* **cross-participant agreement** — projecting the schedule onto each
  participant, every pair of devices must see their *joint* collectives in
  the same order (:func:`verify_participant_schedules` — the check the
  corruption test in tests/test_analysis.py drives directly).

Everything runs on the virtual CPU mesh (``jit(...).lower(...).compile()``,
nothing executes), so the audit gates in CI with zero TPU time — the same
discipline as ``obs/scaling.py``, whose :func:`~slate_tpu.obs.scaling.specs`
registry supplies the audited routines.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..obs.costaudit import (COLLECTIVE_OPS, Instr, module_num_partitions,
                             parse_computations)


@dataclasses.dataclass(frozen=True)
class CollectiveEvent:
    """One collective site in schedule order, with its call context."""

    op: str                                   #: base opcode (``-start`` folded)
    name: str                                 #: HLO instruction name
    computation: str                          #: owning computation
    channel_id: Optional[int]
    groups: Tuple[Tuple[int, ...], ...]       #: () = all devices, one group
    branch_path: Tuple[Tuple[str, int], ...]  #: (cond instr, branch idx) chain
    while_depth: int                          #: enclosing while-loop nesting
    #: True when every enclosing conditional's predicate is *proven* uniform
    #: across participants (derived from full-mesh collectives/constants
    #: only) — such a branch collective cannot strand part of the mesh
    cond_uniform: bool = False
    #: True when an enclosing ``while``'s trip count can differ across the
    #: mesh: its condition reads a per-device divergence seed (partition-id/
    #: replica-id/rng/infeed/recv) directly, or reads a carry element whose
    #: body update chain is tainted by one — either way a body collective
    #: runs a different number of rendezvous on different devices
    while_divergent: bool = False
    #: ``source_target_pairs`` for collective-permute (None otherwise):
    #: direction matters at the rendezvous, so it participates in identity
    pairs: Optional[Tuple[Tuple[int, int], ...]] = None

    def participants(self, nproc: int) -> Tuple[int, ...]:
        if not self.groups:
            return tuple(range(nproc))
        out = sorted({d for g in self.groups for d in g})
        return tuple(out)

    def key(self) -> Tuple[str, Tuple[Tuple[int, ...], ...],
                           Optional[Tuple[Tuple[int, int], ...]]]:
        """Identity used when comparing schedules across participants:
        the semantic rendezvous (opcode + replica groups + permute
        direction), *not* the HLO instruction name or channel id — those
        are compilation artifacts that legitimately differ between
        independently compiled modules (one extra local op shifts every
        later auto-assigned name/id), and the cross-schedule comparator
        must not flag renames as races.  ``pairs`` is included because a
        collective-permute's groups flatten its source_target_pairs into
        an unordered device set — two permutes with opposite directions
        share groups but mismatch at runtime."""
        return (self.op, self.groups, self.pairs)

    def describe(self) -> str:
        loc = self.computation
        if self.while_depth:
            loc += f" (while depth {self.while_depth})"
        if self.branch_path:
            loc += " (conditional branch " + "/".join(
                f"{c}#{i}" for c, i in self.branch_path) + \
                (", uniform predicate)" if self.cond_uniform else ")")
        groups = "all" if not self.groups else \
            ",".join("{" + ",".join(map(str, g)) + "}" for g in self.groups)
        pairs = "" if self.pairs is None else " pairs=" + \
            ",".join(f"{a}->{b}" for a, b in self.pairs)
        return (f"{self.op} %{self.name} channel={self.channel_id} "
                f"groups={groups}{pairs} in {loc}")


# opcodes whose *output* is uniform across the full mesh regardless of their
# inputs (the result of a full-group rendezvous is the same everywhere)
_UNIFORM_SOURCES = frozenset({"all-reduce", "all-gather",
                              "collective-broadcast"})
# opcodes whose output is intrinsically per-device (or not worth proving)
_NONUNIFORM_OPS = frozenset({"parameter", "partition-id", "replica-id",
                             "rng", "rng-bit-generator", "infeed", "recv",
                             "recv-done", "while", "conditional",
                             "collective-permute", "reduce-scatter",
                             "all-to-all"})


def _full_mesh(groups: Tuple[Tuple[int, ...], ...], nproc: int) -> bool:
    if not groups:
        return True                    # replica_groups={}: all devices
    return len(groups) == 1 and set(groups[0]) == set(range(nproc))


class _UniformityAnalysis:
    """Backward dataflow over one computation: is a value provably identical
    on every participant?

    A value is uniform when every path of its def chain bottoms out in a
    constant/iota or a *full-mesh* all-reduce/all-gather/broadcast (whose
    output is the same everywhere by construction); elementwise/structural
    ops and deterministic local kernels (fusions, custom-calls) propagate
    uniformity from their operands.  Per-device seeds — parameters
    (sharded inputs), partition/replica ids, permutes, scatters, loop
    carries — are conservatively non-uniform.  This is what lets the
    auditor pass CholQR's rank-deficiency fallback (predicate derived from
    the psum'd Gram matrix: uniform) while still flagging a lax.cond on a
    genuinely local value."""

    def __init__(self, comps: Dict[str, List[Instr]], nproc: int):
        self.comps = comps
        self.by_name = {cname: {i.name: i for i in instrs}
                        for cname, instrs in comps.items()}
        self.nproc = nproc
        self._memo: Dict[Tuple[str, str], bool] = {}
        self._comp_pure: Dict[str, bool] = {}

    def _computation_pure(self, cname: str) -> bool:
        """No per-device seed op anywhere inside (fusion-body scan)."""
        cached = self._comp_pure.get(cname)
        if cached is not None:
            return cached
        self._comp_pure[cname] = True      # break cycles optimistically
        ok = True
        for ins in self.comps.get(cname, ()):
            base = ins.base_opcode()
            if base in _NONUNIFORM_OPS and base != "parameter" \
                    or base in COLLECTIVE_OPS:
                ok = False
                break
            for names in ins.callees().values():
                for c in names:
                    if c != cname and not self._computation_pure(c):
                        ok = False
                        break
        self._comp_pure[cname] = ok
        return ok

    def uniform(self, cname: str, ref: str, depth: int = 0) -> bool:
        key = (cname, ref)
        if key in self._memo:
            return self._memo[key]
        if depth > 200:
            return False
        self._memo[key] = False            # conservative while in-flight
        ins = self.by_name.get(cname, {}).get(ref)
        if ins is None:
            return False                   # parameter / cross-computation
        base = ins.base_opcode()
        if base in _UNIFORM_SOURCES:
            rg = ins.replica_groups()
            out = _full_mesh(rg if rg is not None else (), self.nproc)
        elif base in _NONUNIFORM_OPS:
            out = False
        elif base in ("constant", "iota"):
            out = True
        else:
            # elementwise / structural / fusion / custom-call: propagate,
            # requiring any called computation to be free of per-device seeds
            out = all(self._computation_pure(c)
                      for names in ins.callees().values() for c in names) \
                and all(self.uniform(cname, r, depth + 1)
                        for r in ins.operand_refs())
        self._memo[key] = out
        return out


def extract_events(hlo_text: str,
                   nproc: Optional[int] = None) -> List[CollectiveEvent]:
    """Walk the compiled module from ENTRY in schedule order, expanding
    called computations (`while` bodies, `conditional` branches, fusions),
    and emit every collective site with its context — including whether the
    predicates guarding it are provably uniform.

    ``nproc`` is the mesh size the uniformity proof runs at.  Pass the real
    device count whenever you know it: inferring it from the module (header
    ``num_partitions``, else max participant seen) under-counts when every
    collective in the module is a subgroup one, and a subgroup rendezvous
    mistaken for full-mesh turns a genuinely divergent predicate into a
    false uniformity proof."""
    comps, entry = parse_computations(hlo_text)
    if entry is None:
        # fall back: modules without an ENTRY marker (shouldn't happen on
        # Compiled.as_text(), but the parser must not invent a schedule)
        entry = next(iter(comps), None)
    events: List[CollectiveEvent] = []
    if entry is None:
        return events
    if nproc is None:
        nproc = module_num_partitions(hlo_text) or _max_participant(comps) + 1
    uni = _UniformityAnalysis(comps, nproc)

    def walk(comp: str, branch_path: Tuple[Tuple[str, int], ...],
             while_depth: int, uniform_so_far: bool,
             seen: Tuple[str, ...], while_divergent: bool = False) -> None:
        if comp in seen:               # defensive: HLO computations form a DAG
            return
        for ins in comps.get(comp, ()):
            base = ins.base_opcode()
            if base in COLLECTIVE_OPS and not ins.opcode.endswith("-done"):
                pairs = ins.source_target_pairs()
                if pairs is not None:
                    groups: Tuple[Tuple[int, ...], ...] = (
                        tuple(sorted({d for p in pairs for d in p})),)
                else:
                    rg = ins.replica_groups()
                    groups = rg if rg is not None else ()
                events.append(CollectiveEvent(
                    op=base, name=ins.name, computation=comp,
                    channel_id=ins.channel_id(), groups=groups,
                    branch_path=branch_path, while_depth=while_depth,
                    cond_uniform=bool(branch_path) and uniform_so_far,
                    while_divergent=while_divergent, pairs=pairs))
            callees = ins.callees()
            if ins.opcode == "while":
                div = while_divergent or \
                    _while_trip_count_divergent(comps, ins, nproc)
                for attr in ("condition", "body"):
                    for c in callees.get(attr, ()):
                        walk(c, branch_path, while_depth + 1,
                             uniform_so_far, seen + (comp,), div)
            elif ins.opcode == "conditional":
                refs = ins.operand_refs()
                pred_uniform = bool(refs) and uni.uniform(comp, refs[0])
                branches = callees.get("branch_computations") or \
                    [c for attr in ("true_computation", "false_computation")
                     for c in callees.get(attr, ())]
                for idx, c in enumerate(branches):
                    walk(c, branch_path + ((ins.name, idx),), while_depth,
                         uniform_so_far and pred_uniform, seen + (comp,),
                         while_divergent)
            else:
                for attr, names in callees.items():
                    if attr == "branch_computations":
                        continue
                    for c in names:
                        walk(c, branch_path, while_depth, uniform_so_far,
                             seen + (comp,), while_divergent)

    walk(entry, (), 0, True, ())
    return events


# ops whose value is intrinsically per-device: a while condition touching
# one (directly, or through a carry element whose body update is tainted by
# one) can give the mesh divergent trip counts.  Counter-driven carries stay
# clean — in an SPMD module they start and update identically everywhere —
# so :func:`_carry_taint` tracks taint per carry element instead of flagging
# every loop in the registry (the blocked eigensolver/iterative-refinement
# whiles are counter-driven and race-free, even where their *data* elements
# are computed with partition-id shard indexing).
_DIVERGENCE_SEEDS = frozenset({"partition-id", "replica-id", "rng",
                               "rng-bit-generator", "infeed", "recv"})

_INDEX_RE = re.compile(r"\bindex=(\d+)")


def _has_divergence_seed(comps: Dict[str, List[Instr]], cname: str,
                         _seen: Optional[set] = None) -> bool:
    """Does ``cname`` (transitively through its callees) contain an op from
    ``_DIVERGENCE_SEEDS``?"""
    seen = _seen if _seen is not None else set()
    if cname in seen:
        return False
    seen.add(cname)
    for ins in comps.get(cname, ()):
        base = ins.base_opcode()
        if base in _DIVERGENCE_SEEDS:
            return True
        for names in ins.callees().values():
            for c in names:
                if _has_divergence_seed(comps, c, seen):
                    return True
    return False


def _while_trip_count_divergent(comps: Dict[str, List[Instr]], ins: Instr,
                                nproc: int) -> bool:
    """Can this ``while``'s trip count differ across the mesh?

    True when the condition computation contains a divergence seed itself,
    or when it reads a carry element whose update chain in the body is
    tainted by one (the carry-laundering case: ``body`` folds partition-id
    into the counter, ``cond`` compares the counter against a constant —
    no seed ever appears in the condition, yet trip counts diverge)."""
    callees = ins.callees()
    conds = callees.get("condition", ())
    if any(_has_divergence_seed(comps, c) for c in conds):
        return True
    reads: Optional[Set[int]] = set()
    for c in conds:
        r = _condition_carry_reads(comps, c)
        if r is None:
            reads = None               # non-tuple carry / whole-tuple use
            break
        reads.update(r)
    if reads is not None and not reads:
        return False                   # condition reads no carry state at all
    for b in callees.get("body", ()):
        tainted = _carry_taint(comps, b, nproc)
        if tainted and (reads is None or reads & tainted):
            return True
    return False


def _condition_carry_reads(comps: Dict[str, List[Instr]], cname: str
                           ) -> Optional[Set[int]]:
    """Carry-tuple indices the condition computation reads through
    ``get-tuple-element`` on its parameter; None = conservatively all
    (non-tuple carry, or the parameter used whole)."""
    instrs = comps.get(cname, ())
    params = {i.name for i in instrs if i.opcode == "parameter"}
    reads: Set[int] = set()
    for ins in instrs:
        refs = ins.operand_refs()
        if ins.opcode == "get-tuple-element" and refs and refs[0] in params:
            m = _INDEX_RE.search(ins.tail)
            if m is None:
                return None
            reads.add(int(m.group(1)))
        elif any(r in params for r in refs):
            return None
    return reads


def _carry_taint(comps: Dict[str, List[Instr]], bname: str,
                 nproc: int) -> Set[int]:
    """Carry-tuple indices whose next-iteration value (the body's ROOT
    tuple element) is tainted by a divergence seed.

    Per-instruction dataflow: seeds taint; a *full-mesh*
    all-reduce/all-gather/broadcast launders taint (its output is identical
    everywhere no matter the inputs); everything else propagates taint from
    its operands and from seeds inside called computations (fusion bodies).
    ``get-tuple-element`` on the body parameter turns into a dependence on
    that carry index, resolved by fixpoint so taint flows across iterations
    (element k updated from tainted element j)."""
    instrs = comps.get(bname, ())
    if not instrs:
        return set()
    by_name = {i.name: i for i in instrs}
    params = {i.name for i in instrs if i.opcode == "parameter"}
    root = next((i for i in instrs if i.is_root), instrs[-1])
    elems = root.operand_refs() if root.opcode == "tuple" else [root.name]

    # ref -> (seed_tainted, carry indices depended on; None = whole carry)
    memo: Dict[str, Tuple[bool, Optional[Set[int]]]] = {}

    def deps(ref: str) -> Tuple[bool, Optional[Set[int]]]:
        if ref in memo:
            return memo[ref]
        memo[ref] = (False, set())     # in-flight (HLO is a DAG; defensive)
        ins2 = by_name.get(ref)
        if ins2 is None:
            out: Tuple[bool, Optional[Set[int]]] = (False, set())
        elif ins2.opcode == "parameter":
            out = (False, None)
        else:
            base = ins2.base_opcode()
            refs = ins2.operand_refs()
            if ins2.opcode == "get-tuple-element" and refs \
                    and refs[0] in params:
                m = _INDEX_RE.search(ins2.tail)
                out = (False, {int(m.group(1))} if m else None)
            elif base in _DIVERGENCE_SEEDS:
                out = (True, set())
            elif base in _UNIFORM_SOURCES:
                rg = ins2.replica_groups()
                out = (False, set()) if _full_mesh(
                    rg if rg is not None else (), nproc) \
                    else _merge(refs)
            else:
                seed = any(_has_divergence_seed(comps, c)
                           for names in ins2.callees().values()
                           for c in names)
                s, idxs = _merge(refs)
                out = (seed or s, idxs)
        memo[ref] = out
        return out

    def _merge(refs: List[str]) -> Tuple[bool, Optional[Set[int]]]:
        seed, idxs = False, set()
        for r in refs:
            s, i = deps(r)
            seed = seed or s
            if i is None or idxs is None:
                idxs = None
            else:
                idxs |= i
        return seed, idxs

    elem_deps = [deps(r) for r in elems]
    tainted = {k for k, (s, _) in enumerate(elem_deps) if s}
    changed = True
    while changed:
        changed = False
        for k, (_, idxs) in enumerate(elem_deps):
            if k in tainted:
                continue
            if (idxs is None and tainted) or (idxs and idxs & tainted):
                tainted.add(k)
                changed = True
    return tainted


def _max_participant(comps: Dict[str, List[Instr]]) -> int:
    top = 0
    for instrs in comps.values():
        for ins in instrs:
            rg = ins.replica_groups()
            for g in rg or ():
                top = max(top, max(g, default=0))
            for a, b in ins.source_target_pairs() or ():
                top = max(top, a, b)
    return top


def participant_schedules(events: Sequence[CollectiveEvent], nproc: int
                          ) -> Dict[int, List[CollectiveEvent]]:
    """Project the global schedule onto each participant: device ``d`` sees
    exactly the collectives whose groups include it.

    Projections of a *single* SPMD module are self-consistent by
    construction (every pair filters the same ordered list), so feed
    :func:`verify_participant_schedules` views from independent sources —
    separately compiled per-host modules, or a deliberately corrupted
    schedule as in the corruption test."""
    out: Dict[int, List[CollectiveEvent]] = {d: [] for d in range(nproc)}
    for ev in events:
        for d in ev.participants(nproc):
            if d in out:
                out[d].append(ev)
    return out


# ---------------------------------------------------------------------------
# checks


def verify_events(events: Sequence[CollectiveEvent], nproc: int) -> List[str]:
    """Structural checks on the global schedule (coverage, channels, control
    flow).  Returns findings; empty list = consistent."""
    findings: List[str] = []
    chan_sites: Dict[int, List[str]] = {}
    for ev in events:
        seen: Dict[int, int] = {}
        for g in ev.groups:
            for d in g:
                seen[d] = seen.get(d, 0) + 1
                if d >= nproc or d < 0:
                    findings.append(
                        f"{ev.describe()}: participant {d} outside the "
                        f"P={nproc} mesh")
        dups = sorted(d for d, c in seen.items() if c > 1)
        if dups:
            findings.append(
                f"{ev.describe()}: device(s) {dups} appear in more than one "
                "replica group of the same collective (rendezvous deadlock)")
        if ev.channel_id is not None:
            chan_sites.setdefault(ev.channel_id, []).append(
                f"%{ev.name}@{ev.computation}")
        if ev.branch_path and not ev.cond_uniform:
            findings.append(
                f"{ev.describe()}: collective reachable only under a "
                "conditional branch whose predicate is not provably uniform "
                "— a divergent lax.cond predicate strands part of the mesh "
                "at the rendezvous")
        if ev.while_depth and ev.while_divergent:
            findings.append(
                f"{ev.describe()}: collective inside a while loop whose "
                "condition reads a per-device value (partition-id/replica-"
                "id/rng/infeed/recv) — divergent trip counts run a "
                "different number of rendezvous on different devices")
    for chan, sites in sorted(chan_sites.items()):
        uniq = sorted(set(sites))
        if len(uniq) > 1:
            findings.append(
                f"channel {chan} reused by {len(uniq)} distinct collective "
                f"instructions: {', '.join(uniq)} (interleaved channel "
                "reuse corrupts rendezvous matching)")
    return findings


def verify_participant_schedules(
        schedules: Dict[int, List[CollectiveEvent]],
        nproc: Optional[int] = None) -> List[str]:
    """Cross-participant agreement: for every device pair (p, q), the
    subsequence of collectives involving *both* must be identical on both
    sides — same sites, same order.  A participant missing a psum the rest
    of its group executes (the corruption test's scenario) surfaces here.

    Only meaningful when the schedules come from *independent* sources
    (separately compiled per-host programs, replayed traces, corrupted
    fixtures): per-participant views projected from one SPMD module agree
    trivially, which is why :func:`audit_hlo` relies on
    :func:`verify_events` for its single-module guarantees."""
    nproc = nproc if nproc is not None else len(schedules)
    findings: List[str] = []
    devs = sorted(schedules)
    for i, p in enumerate(devs):
        for q in devs[i + 1:]:
            jp = [ev for ev in schedules[p]
                  if q in ev.participants(nproc)]
            jq = [ev for ev in schedules[q]
                  if p in ev.participants(nproc)]
            kp = [ev.key() for ev in jp]
            kq = [ev.key() for ev in jq]
            if kp == kq:
                continue
            # name the first divergence precisely
            k = 0
            while k < min(len(kp), len(kq)) and kp[k] == kq[k]:
                k += 1
            if k < len(kp) and k < len(kq):
                findings.append(
                    f"participants {p} and {q} disagree at joint collective "
                    f"#{k}: device {p} expects {jp[k].describe()} but device "
                    f"{q} expects {jq[k].describe()}")
            elif k < len(kp):
                findings.append(
                    f"participant {q} is missing joint collective #{k} that "
                    f"device {p} executes: {jp[k].describe()} — device {p} "
                    "blocks at a rendezvous the peer never reaches")
            else:
                findings.append(
                    f"participant {p} is missing joint collective #{k} that "
                    f"device {q} executes: {jq[k].describe()} — device {q} "
                    "blocks at a rendezvous the peer never reaches")
    return findings


def audit_hlo(hlo_text: str, nproc: int) -> Dict[str, Any]:
    """Full audit of one compiled module's HLO text.

    Runs the structural checks (:func:`verify_events`: group coverage,
    channel discipline, divergent-cond reachability).  The pairwise
    cross-schedule comparison is deliberately *not* run here — projections
    of one SPMD module agree by construction, so it would be a constant-
    empty check at O(P² · events) cost; use
    :func:`verify_participant_schedules` on independently sourced
    schedules instead."""
    events = extract_events(hlo_text, nproc)
    findings = verify_events(events, nproc)
    return {"collective_sites": len(events),
            "uniform_cond_sites": sum(
                1 for e in events if e.branch_path and e.cond_uniform),
            "schedule": [ev.describe() for ev in events],
            "findings": findings}


def audit_compiled(compiled, nproc: int) -> Dict[str, Any]:
    """Audit one ``jax.stages.Compiled`` executable."""
    try:
        hlo = compiled.as_text()
    # slate-lint: disable=SLT501 -- HLO rendering shim (same as costaudit's):
    # the failure is reported as an audit finding, nothing numerical runs here
    except Exception as e:
        return {"collective_sites": 0, "schedule": [],
                "findings": [f"could not render compiled HLO: "
                             f"{type(e).__name__}: {e}"]}
    return audit_hlo(hlo, nproc)


def audit_routines(pset: Sequence[int] = (2, 4, 8),
                   names: Optional[Sequence[str]] = None,
                   progress=None) -> List[Dict[str, Any]]:
    """Run the ordering audit over the obs/scaling routine registry — every
    AOT-audited distributed routine at each requested device count.

    Imports jax lazily (the AST tier must stay importable without it)."""
    from ..obs import scaling

    rows: List[Dict[str, Any]] = []
    wanted = set(names) if names else None
    if wanted is not None:
        unknown = sorted(wanted - {s.name for s in scaling.specs()})
        if unknown:
            # a typo must not read as "audited clean, 0 findings"
            raise ValueError(
                f"unknown routine name(s): {', '.join(unknown)} "
                f"(see obs.scaling.spec_names())")
    for nproc in pset:
        grid = scaling.make_grid(nproc)
        for spec in scaling.specs():
            if wanted is not None and spec.name not in wanted:
                continue
            row: Dict[str, Any] = {"routine": spec.name, "P": nproc,
                                   "module": spec.module}
            compiled, problem = scaling.compile_spec(spec, grid)
            if problem is not None:
                row.update(problem)
            else:
                row.update(audit_compiled(compiled, nproc))
            rows.append(row)
            if progress is not None:
                progress(row)
    return rows


def summarize(rows: Iterable[Dict[str, Any]]) -> Tuple[int, int, List[str]]:
    """(audited, total_findings, flattened finding lines) over audit rows."""
    audited = 0
    lines: List[str] = []
    for row in rows:
        if row.get("error") or row.get("skipped"):
            continue
        audited += 1
        for f in row.get("findings", ()):
            lines.append(f"P={row['P']} {row['routine']}: {f}")
    return audited, len(lines), lines
