"""Finding model for slate-lint (the AST tier's output currency).

A :class:`Finding` is one rule violation at one source location.  Its
:meth:`~Finding.fingerprint` deliberately excludes the line *number*: the
committed baseline (``analysis/baseline.json``) must keep matching a finding
when unrelated edits shift the file, so identity is
``(rule, path, context, line_text)`` — the enclosing ``def``/``class``
qualname plus the stripped source line.  Two identical lines in the same
function are the one case this collapses; the linter disambiguates by
allowing a baseline entry to absorb several occurrences only when
``count`` says so.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

#: severity ladder — ``error`` findings are CI-blocking when unbaselined;
#: ``warning`` findings also fail ``--check`` (one gate, no second-class
#: rules) but are rendered distinctly so humans triage errors first
SEVERITIES = ("error", "warning")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str          #: rule ID, e.g. ``SLT501``
    severity: str      #: ``error`` | ``warning``
    path: str          #: repo-relative posix path
    line: int          #: 1-based line of the offending node
    col: int           #: 0-based column of the offending node
    message: str       #: human sentence: what is wrong here
    context: str       #: enclosing qualname (``mod.fn.inner``) or ``<module>``
    line_text: str     #: stripped source line (fingerprint component)
    suggestion: str = ""   #: autofix hint (``--explain`` renders it)

    def fingerprint(self) -> Tuple[str, str, str, str]:
        """Baseline identity — line-number-free (module docstring)."""
        return (self.rule, self.path, self.context, self.line_text)

    def render(self, baselined: bool = False) -> str:
        tag = " [baselined]" if baselined else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.severity}: {self.message}{tag}")
