"""slate-lint driver: parse package sources, run the rule set, apply
suppressions and the committed baseline.

This module imports no jax itself: the AST tier is pure-stdlib work over
source text, so linting stays fast even where the array stack is heavy to
initialize (the package ``__init__`` may still load jax on import).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding
from .rules import RULES

#: inline suppression: ``# slate-lint: disable=SLT501 -- reason`` on the
#: finding's line or the line directly above it
_SUPPRESS_RE = re.compile(
    r"#\s*slate-lint:\s*disable=([A-Z0-9, ]+?)(?:\s*--\s*(.*))?\s*$")


class ModuleCtx:
    """One parsed source module handed to every rule checker."""

    def __init__(self, path: str, relpath: str, text: str):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent
        from .rules import traced_cores
        self.cores = traced_cores(self.tree)
        self.suppressions = self._parse_suppressions()

    # -- structure ----------------------------------------------------------
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def qualname(self, node: ast.AST) -> str:
        """Enclosing def/class chain of ``node`` (``outer.inner``), or
        ``<module>``."""
        parts: List[str] = []
        cur = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self._parents.get(cur)
        return ".".join(reversed(parts)) or "<module>"

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    # -- findings -----------------------------------------------------------
    def finding(self, rule_id: str, node: ast.AST, message: str,
                suggestion: str = "") -> Finding:
        rule = RULES[rule_id]
        line = getattr(node, "lineno", 1)
        return Finding(rule=rule_id, severity=rule.severity,
                       path=self.relpath, line=line,
                       col=getattr(node, "col_offset", 0), message=message,
                       context=self.qualname(node),
                       line_text=self.line_text(line),
                       suggestion=suggestion)

    # -- suppressions -------------------------------------------------------
    def _parse_suppressions(self) -> Dict[int, Set[str]]:
        # tokenize, not a raw line scan: the directive must sit in a real
        # comment — a string literal or docstring that merely *mentions*
        # "# slate-lint: disable=..." (rule docs, fix-suggestion text,
        # jax.debug.print payloads) must not suppress anything.  ast.parse
        # already succeeded in __init__, so tokenization cannot fail.
        out: Dict[int, Set[str]] = {}
        for tok in tokenize.generate_tokens(io.StringIO(self.text).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            ids = {t.strip() for t in m.group(1).split(",") if t.strip()}
            out.setdefault(tok.start[0], set()).update(ids)
        return out

    def suppressed(self, f: Finding) -> bool:
        if f.rule in self.suppressions.get(f.line, ()):
            return True
        # look upward through the contiguous comment block above the finding
        # (a disable= line may carry a multi-line justification under it)
        ln = f.line - 1
        while ln >= 1 and self.line_text(ln).startswith("#"):
            if f.rule in self.suppressions.get(ln, ()):
                return True
            ln -= 1
        return False


def package_root() -> str:
    """The ``slate_tpu`` package directory this module ships in."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root() -> str:
    return os.path.dirname(package_root())


def iter_source_files(root: Optional[str] = None) -> Iterable[str]:
    """Every ``.py`` file under the package, sorted for stable output."""
    root = root or package_root()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _syntax_finding(relpath: str, e: SyntaxError) -> Finding:
    """The synthetic SLT000 finding every entry point returns for
    unparseable input."""
    return Finding(rule="SLT000", severity="error", path=relpath,
                   line=e.lineno or 1, col=e.offset or 0,
                   message=f"syntax error: {e.msg}", context="<module>",
                   line_text="")


def _run_rules(ctx: ModuleCtx,
               rules: Optional[Sequence[str]]) -> List[Finding]:
    """Apply the (optionally filtered) rule set to one parsed module,
    dropping suppressed findings — the one body shared by every lint
    entry point so filtering/suppression/sort order can't diverge."""
    out: List[Finding] = []
    for rule_id, rule in sorted(RULES.items()):
        if rules is not None and rule_id not in rules:
            continue
        for f in rule.checker(ctx) or ():
            if not ctx.suppressed(f):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def lint_file(path: str, rel_root: Optional[str] = None,
              rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the rule set over one file; suppressed findings are dropped."""
    rel_root = rel_root or repo_root()
    relpath = os.path.relpath(os.path.abspath(path), rel_root)
    relpath = relpath.replace(os.sep, "/")
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        ctx = ModuleCtx(path, relpath, text)
    except SyntaxError as e:
        return [_syntax_finding(relpath, e)]
    return _run_rules(ctx, rules)


def lint_package(root: Optional[str] = None,
                 rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint every source file in the package (the repo gate's input)."""
    root = root or package_root()
    rel_root = repo_root() if root == package_root() \
        else _rel_root_for(root)
    out: List[Finding] = []
    for path in iter_source_files(root):
        out.extend(lint_file(path, rel_root=rel_root, rules=rules))
    return out


def lint_source(text: str, relpath: str = "snippet.py",
                rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint a source string (fixture tests; editor integrations).

    ``relpath`` participates in path-scoped rules — pass e.g.
    ``slate_tpu/serve/x.py`` to exercise the serve-path rules.  Unparseable
    input yields the same synthetic SLT000 finding as :func:`lint_file`
    (editors routinely lint in-progress buffers; they get a finding, not a
    traceback)."""
    try:
        ctx = ModuleCtx(relpath, relpath, text)
    except SyntaxError as e:
        return [_syntax_finding(relpath, e)]
    return _run_rules(ctx, rules)


def _rel_root_for(path: str) -> str:
    """Directory relpaths are taken against: the parent of the *topmost*
    package directory containing ``path``, found by walking up while an
    ``__init__.py`` is present.  This keeps relpaths package-qualified
    (``slate_tpu/parallel/pivot.py``, never ``parallel/pivot.py``) so the
    path-scoped rules (SLT203/SLT301/SLT601) and baseline fingerprints
    behave identically to :func:`lint_package`."""
    d = os.path.abspath(path)
    if not os.path.isdir(d):
        d = os.path.dirname(d)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return d


def lint_paths(paths: Sequence[str],
               rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint an explicit mix of files and directories (CLI convenience)."""
    out: List[Finding] = []
    for p in paths:
        if os.path.isdir(p):
            for f in iter_source_files(p):
                out.extend(lint_file(f, rel_root=_rel_root_for(p),
                                     rules=rules))
        else:
            out.extend(lint_file(p, rel_root=_rel_root_for(p), rules=rules))
    return out
