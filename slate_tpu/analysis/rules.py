"""slate-lint rule set: ~10 codebase-specific AST rules.

Each rule is a checker registered in :data:`RULES` with an ID, severity, and
one-line title.  Checkers receive a ``ModuleCtx`` (see ``lint.py``) exposing
the parsed tree, parent links, qualnames, and a ``finding()`` factory; they
yield :class:`~slate_tpu.analysis.findings.Finding` objects.

The rules encode the JAX pitfalls that have cost this repo debugging rounds
(ISSUE 10): tracer hygiene inside jitted/vmapped/shard_mapped cores,
recompilation hazards, x64 scope leaks, leftover debug hooks, donation
misuse, taxonomy-swallowing ``except`` blocks, and missing ``@obs.instrument``
on public distributed drivers.

Suppression: any rule can be silenced at one site with a trailing or
preceding comment ``# slate-lint: disable=SLT501 -- reason`` (the reason is
mandatory by convention and checked in review, not by the parser).  Accepted
pre-existing findings live in ``analysis/baseline.json`` instead.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .findings import Finding

# ---------------------------------------------------------------------------
# registry


@dataclasses.dataclass(frozen=True)
class Rule:
    """One registered lint rule."""

    id: str
    severity: str
    title: str
    doc: str
    checker: Callable


RULES: Dict[str, Rule] = {}


def rule(rule_id: str, severity: str, title: str):
    """Register a checker under ``rule_id`` (decorator)."""
    def deco(fn):
        RULES[rule_id] = Rule(rule_id, severity, title,
                              (fn.__doc__ or "").strip(), fn)
        return fn
    return deco


# ---------------------------------------------------------------------------
# shared AST helpers

#: attribute reads on a traced array that are static at trace time — Python
#: control flow on these is NOT a tracer leak
STATIC_SAFE_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "sharding",
                               "itemsize"})

#: transforms whose function argument becomes a traced core
_TRACE_WRAPPERS = ("jit", "vmap", "pmap", "shard_map")


def dotted(node: ast.AST) -> str:
    """``a.b.c`` for an Attribute/Name chain, else ''."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_wrapper_name(name: str, kinds: Sequence[str] = _TRACE_WRAPPERS) -> bool:
    last = name.rsplit(".", 1)[-1]
    return last in kinds


def _partial_jit_target(call: ast.Call) -> Optional[ast.Call]:
    """``functools.partial(jax.jit, ...)`` -> the partial call, else None."""
    if not isinstance(call, ast.Call):
        return None
    if _is_wrapper_name(dotted(call.func), ("partial",)) and call.args:
        inner = dotted(call.args[0])
        if _is_wrapper_name(inner, ("jit", "vmap", "pmap")):
            return call
    return None


def _literal_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """Literal int / tuple-or-list of ints -> values, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                vals.append(e.value)
            else:
                return None
        return tuple(vals)
    return None


def _literal_str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                vals.append(e.value)
            else:
                return None
        return tuple(vals)
    return None


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _static_params_from_kwargs(fn: ast.AST, kwargs: List[ast.keyword]
                               ) -> Set[str]:
    """static_argnums/static_argnames keywords -> static param name set."""
    params = _param_names(fn)
    static: Set[str] = set()
    for kw in kwargs:
        if kw.arg == "static_argnames":
            static.update(_literal_str_tuple(kw.value) or ())
        elif kw.arg == "static_argnums":
            for i in _literal_int_tuple(kw.value) or ():
                if 0 <= i < len(params):
                    static.add(params[i])
    return static


@dataclasses.dataclass
class TracedCore:
    """A function whose body traces: decorated with jit/vmap, or passed by
    name into jit/vmap/pmap/shard_map within the module."""

    fn: ast.AST                    # FunctionDef / AsyncFunctionDef
    how: str                       # "decorator" | "call:<wrapper>"
    static: Set[str]               # params that are static at trace time


def traced_cores(tree: ast.Module) -> List[TracedCore]:
    """Collect every function in the module whose body is traced."""
    cores: Dict[ast.AST, TracedCore] = {}
    fns_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns_by_name.setdefault(node.name, []).append(node)
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    p = _partial_jit_target(dec)
                    if p is not None:
                        cores[node] = TracedCore(
                            node, "decorator",
                            _static_params_from_kwargs(node, p.keywords))
                    elif _is_wrapper_name(dotted(dec.func), ("jit", "vmap")):
                        cores[node] = TracedCore(
                            node, "decorator",
                            _static_params_from_kwargs(node, dec.keywords))
                elif _is_wrapper_name(dotted(dec), ("jit", "vmap")):
                    cores.setdefault(node, TracedCore(node, "decorator",
                                                      set()))
    # call form: jit(fn, ...) / shard_map(fn, ...) / vmap(fn) with fn a
    # module-or-locally defined function referenced by name
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if not _is_wrapper_name(name):
            continue
        wrapper = name.rsplit(".", 1)[-1]
        for arg in node.args[:1]:      # the traced callable is arg 0
            if isinstance(arg, ast.Name):
                for fn in fns_by_name.get(arg.id, ()):
                    if fn not in cores:
                        static = (_static_params_from_kwargs(fn, node.keywords)
                                  if wrapper == "jit" else set())
                        cores[fn] = TracedCore(fn, f"call:{wrapper}", static)
    return list(cores.values())


def _traced_param_uses(core: TracedCore, scope: ast.AST, ctx
                       ) -> Iterator[ast.Name]:
    """Bare loads of non-static core params within ``scope`` that are not in
    a static-safe position (``x.shape``, ``x is None``, ``len(x)``,
    ``isinstance(x, ...)``)."""
    traced = set(_param_names(core.fn)) - core.static
    for n in ast.walk(scope):
        if not (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                and n.id in traced):
            continue
        parent = ctx.parent(n)
        if isinstance(parent, ast.Attribute) \
                and parent.attr in STATIC_SAFE_ATTRS:
            continue
        if isinstance(parent, ast.Call) and parent.func is n:
            continue                       # the name is being *called*
        if isinstance(parent, ast.Call) \
                and dotted(parent.func) in ("len", "isinstance", "type",
                                            "repr", "str"):
            continue
        if isinstance(parent, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops):
            continue                       # `x is None` identity checks
        yield n


# ---------------------------------------------------------------------------
# tracer hygiene


@rule("SLT101", "error", "Python control flow on a traced value")
def _tracer_branch(ctx):
    """`if`/`while`/ternary on a jitted core's traced parameter forces a
    concrete bool from a tracer — TracerBoolConversionError at trace time,
    or silent trace-time specialization.  Use `lax.cond`/`lax.select`, or
    mark the argument static."""
    for core in ctx.cores:
        for node in ast.walk(core.fn):
            tests = []
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                tests.append(node.test)
            elif isinstance(node, ast.Assert):
                tests.append(node.test)
            for test in tests:
                for use in _traced_param_uses(core, test, ctx):
                    yield ctx.finding(
                        "SLT101", use,
                        f"Python control flow on traced value "
                        f"{use.id!r} inside traced core "
                        f"{core.fn.name!r} ({core.how})",
                        suggestion="use lax.cond/lax.select, or declare the "
                                   "argument in static_argnames")
                    break                  # one finding per test expression


@rule("SLT102", "error", "host materialization of a traced value")
def _host_materialize(ctx):
    """`float()`/`int()`/`bool()`/`.item()`/`.tolist()` on a traced value
    inside a jitted core forces a device sync + concretization — trace-time
    error under jit, silent host round-trip under eager fallback."""
    for core in ctx.cores:
        traced = set(_param_names(core.fn)) - core.static
        for node in ast.walk(core.fn):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            hit = None
            if fname in ("float", "int", "bool", "complex"):
                for a in node.args:
                    if isinstance(a, ast.Name) and a.id in traced:
                        hit = f"{fname}({a.id})"
                        break
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("item", "tolist"):
                names = {n.id for n in ast.walk(node.func.value)
                         if isinstance(n, ast.Name)}
                if names & traced:
                    hit = f".{node.func.attr}() on " \
                          f"{sorted(names & traced)[0]!r}"
            if hit:
                yield ctx.finding(
                    "SLT102", node,
                    f"host materialization {hit} of a traced value inside "
                    f"traced core {core.fn.name!r}",
                    suggestion="keep the value on device (jnp ops), or hoist "
                               "the concretization out of the jitted core")


@rule("SLT103", "error", "numpy call on a traced value in a jitted core")
def _numpy_in_core(ctx):
    """`np.*` calls on traced values inside a jitted core concretize the
    tracer (TracerArrayConversionError) or silently compute on host at trace
    time.  Use the `jnp` equivalent."""
    for core in ctx.cores:
        traced = set(_param_names(core.fn)) - core.static
        for node in ast.walk(core.fn):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            if not (fname.startswith("np.") or fname.startswith("numpy.")):
                continue
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(a, ast.Name) and a.id in traced:
                    yield ctx.finding(
                        "SLT103", node,
                        f"numpy call {fname}() on traced value {a.id!r} "
                        f"inside traced core {core.fn.name!r}",
                        suggestion=f"use jnp.{fname.split('.', 1)[1]} (or "
                                   "hoist the numpy work out of the core)")
                    break


# ---------------------------------------------------------------------------
# recompilation hazards


@rule("SLT201", "warning", "jit constructed inside a loop")
def _jit_in_loop(ctx):
    """`jax.jit(...)` inside a `for`/`while` body builds a fresh wrapper per
    iteration; cache hits still pay wrapper setup, and closure-captured
    values defeat the cache entirely.  Hoist the jit (or memoize the
    builder, as the package's `lru_cache`d program builders do)."""
    seen = set()                  # nested loops reach the same Call twice
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and _is_wrapper_name(dotted(sub.func), ("jit",)) \
                    and id(sub) not in seen:
                seen.add(id(sub))
                yield ctx.finding(
                    "SLT201", sub,
                    "jax.jit constructed inside a loop body "
                    "(per-iteration wrapper; recompilation hazard when "
                    "closures differ)",
                    suggestion="hoist the jit out of the loop or memoize "
                               "the builder with functools.lru_cache")


@rule("SLT202", "error", "unhashable default for a static argument")
def _unhashable_static(ctx):
    """A parameter named in `static_argnames`/`static_argnums` whose default
    is a list/dict/set literal raises `TypeError: unhashable type` on the
    first defaulted call — and a hashable-but-mutable stand-in recompiles on
    every new object.  Static args must be hashable values with stable
    equality (the package's Options carries `cache_key()` for this)."""
    for core in ctx.cores:
        if not core.static:
            continue
        a = core.fn.args
        params = a.posonlyargs + a.args
        defaults = [None] * (len(params) - len(a.defaults)) + list(a.defaults)
        pairs = list(zip(params, defaults)) + \
            list(zip(a.kwonlyargs, a.kw_defaults))
        for p, d in pairs:
            if p.arg in core.static and isinstance(
                    d, (ast.List, ast.Dict, ast.Set)):
                yield ctx.finding(
                    "SLT202", d,
                    f"static argument {p.arg!r} of traced core "
                    f"{core.fn.name!r} defaults to an unhashable "
                    f"{type(d).__name__.lower()} literal",
                    suggestion="use a tuple/frozenset/None default, or drop "
                               "the argument from static_argnames")


@rule("SLT203", "warning", "Options used as a cache key without cache_key()")
def _options_key(ctx):
    """On serve paths, an `Options` instance folded into an executable-cache
    key without `.cache_key()` keys the cache on object identity — every
    request misses and recompiles.  `serve/cache.py` documents the canonical
    key shape."""
    if not ctx.relpath.startswith("slate_tpu/serve/"):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted(node.func)
        if fname not in ("Options", "Options.make"):
            continue
        parent = ctx.parent(node)
        if isinstance(parent, ast.Attribute) and parent.attr == "cache_key":
            continue
        if isinstance(parent, (ast.Tuple, ast.Dict, ast.Subscript)):
            yield ctx.finding(
                "SLT203", node,
                f"{fname}(...) folded into a key structure without "
                ".cache_key() — identity-keyed cache, every request misses",
                suggestion="call .cache_key() on the Options before keying")


# ---------------------------------------------------------------------------
# x64 + debug hygiene

#: files allowed to flip process-global x64 (the tester entrypoint owns the
#: process; everything else must use the scoped jax.experimental.enable_x64)
X64_ALLOWED = ("slate_tpu/testing/__main__.py",)


@rule("SLT301", "error", "process-global x64 toggle outside the entrypoint")
def _global_x64(ctx):
    """`jax.config.update("jax_enable_x64", ...)` flips precision for the
    whole process and leaks across sweep rows and library callers.  Use the
    scoped `jax.experimental.enable_x64` context (testing/routines.py's
    gesv_mixed shows the pattern); only the tester entrypoint may set the
    global."""
    if ctx.relpath in X64_ALLOWED:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not dotted(node.func).endswith("config.update"):
            continue
        if node.args and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value == "jax_enable_x64":
            yield ctx.finding(
                "SLT301", node,
                "process-global jax_enable_x64 toggle outside the tester "
                "entrypoint (leaks x64 across sweep rows and callers)",
                suggestion="wrap the region in "
                           "`with jax.experimental.enable_x64():`")


@rule("SLT302", "warning", "leftover debug hook")
def _debug_left(ctx):
    """`jax.debug.print`/`jax.debug.breakpoint`/`pdb.set_trace`/
    `breakpoint()` left in library code: debug prints serialize the program
    at every call site and breakpoints hang non-interactive runs (CI,
    serving)."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted(node.func)
        if fname in ("jax.debug.print", "jax.debug.breakpoint",
                     "pdb.set_trace", "breakpoint") \
                or fname.endswith(".debug.print") \
                or fname.endswith(".debug.breakpoint"):
            yield ctx.finding(
                "SLT302", node,
                f"leftover debug hook {fname}()",
                suggestion="remove it (or route through utils/debug.py, "
                           "which gates on an env switch)")


# ---------------------------------------------------------------------------
# donation


@rule("SLT401", "error", "donated argument is also static")
def _donate_static_overlap(ctx):
    """An argument index in both `donate_argnums` and `static_argnums`:
    static args are hashed into the cache key, not passed as buffers, so
    XLA rejects the donation (or silently ignores it) — the overlap is
    always a mistake."""
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and (
                _is_wrapper_name(dotted(node.func), ("jit",))
                or _partial_jit_target(node) is not None)):
            continue
        call = node
        donate = static = None
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                donate = _literal_int_tuple(kw.value)
            elif kw.arg == "static_argnums":
                static = _literal_int_tuple(kw.value)
        if donate and static:
            overlap = sorted(set(donate) & set(static))
            if overlap:
                yield ctx.finding(
                    "SLT401", call,
                    f"argument index(es) {overlap} appear in both "
                    "donate_argnums and static_argnums",
                    suggestion="drop the index from one of the two lists")


# ---------------------------------------------------------------------------
# exception taxonomy


@rule("SLT501", "error", "broad except can swallow the NumericalError taxonomy")
def _broad_except(ctx):
    """`except Exception:` / bare `except:` without a re-raise swallows
    `NumericalError`/`SingularMatrixError`/`ConvergenceError`, turning a
    diagnosable numerical failure into silent fallback behavior.  Narrow the
    handler, re-raise the taxonomy first, or mark the swallow intentional
    with `# slate-lint: disable=SLT501 -- reason`."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        broad = node.type is None
        if isinstance(node.type, ast.Name) \
                and node.type.id in ("Exception", "BaseException"):
            broad = True
        if isinstance(node.type, ast.Tuple) and any(
                isinstance(e, ast.Name)
                and e.id in ("Exception", "BaseException")
                for e in node.type.elts):
            broad = True
        if not broad:
            continue
        if any(isinstance(sub, ast.Raise) for sub in ast.walk(node)):
            continue                       # handler re-raises — not a swallow
        yield ctx.finding(
            "SLT501", node,
            "broad except without re-raise can swallow "
            "NumericalError/SingularMatrixError/ConvergenceError",
            suggestion="narrow the exception type, add `except "
                       "NumericalError: raise` above it, or suppress with "
                       "`# slate-lint: disable=SLT501 -- reason`")


# ---------------------------------------------------------------------------
# observability coverage

#: module-level function suffixes that mark a public distributed driver
#: (mirrors tests/test_obs.py's runtime meta-test, statically)
_DRIVER_SUFFIXES = ("_distributed", "_pipelined", "_sharded")


@rule("SLT601", "warning", "public distributed driver missing @obs.instrument")
def _missing_instrument(ctx):
    """Every public driver in `slate_tpu/parallel` wears `@instrument` so
    SCALING.md and metrics.json coverage stay complete (the PR-3 runtime
    meta-test, enforced statically with an autofix suggestion)."""
    if not ctx.relpath.startswith("slate_tpu/parallel/"):
        return
    for node in ctx.tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name.startswith("_") \
                or not node.name.endswith(_DRIVER_SUFFIXES):
            continue
        has = False
        for dec in node.decorator_list:
            base = dec.func if isinstance(dec, ast.Call) else dec
            if dotted(base).rsplit(".", 1)[-1] == "instrument":
                has = True
        if not has:
            yield ctx.finding(
                "SLT601", node,
                f"public distributed driver {node.name!r} is not "
                "@instrument-ed (invisible to spans/SCALING coverage)",
                suggestion="add `@instrument` (from ..obs import instrument) "
                           "above the def")


def rule_table() -> List[Tuple[str, str, str]]:
    """(id, severity, title) rows, sorted — the README/--rules table."""
    return [(r.id, r.severity, r.title)
            for r in sorted(RULES.values(), key=lambda r: r.id)]
