"""Public parallel BLAS-3 and auxiliary drivers — the L5 API.

Reference analogue: the BLAS-3 and Aux rows of the driver inventory (SURVEY.md §2.4):
``src/{gemm,gemmA,gemmC,hemm,symm,herk,her2k,syrk,syr2k,trmm,trsm}.cc`` and
``src/{add,copy,scale,scale_row_col,set,norm,colNorms}.cc``, declared in
``include/slate/slate.hh``.

Drivers accept Matrix wrappers (using their op/uplo/diag flags, like the reference's
typed-matrix dispatch) or raw arrays with explicit keywords.  Each mutates its output
wrapper in place (functional rebind) *and* returns the new array, so both the
reference's in-place style and JAX's functional style work.

Method dispatch: ``select_algo`` mirrors src/gemm.cc:12-24 — on a single device all
stationary variants lower to the same fused XLA matmul (stationarity is a communication
concept), so the choice only matters on a distributed mesh where MethodGemm.SUMMA
routes to the shard_map pipeline (parallel/summa.py).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .core.exceptions import SlateError, slate_assert
from .core.matrix import (BaseBandMatrix, BaseMatrix, BaseTrapezoidMatrix,
                          HermitianMatrix, SymmetricMatrix, as_array, write_back)
from .core.types import (Diag, MethodGemm, MethodTrsm, Norm, NormScope,
                         Options, Side, Uplo)
from .ops import blas3, elementwise, norms as norm_ops


def _uplo_of(A, uplo) -> Uplo:
    if uplo is not None:
        return Uplo.from_string(uplo)
    if isinstance(A, (BaseTrapezoidMatrix, BaseBandMatrix)) and A.uplo != Uplo.General:
        return A.uplo
    raise SlateError("uplo required (pass a triangular/symmetric matrix or uplo=...)")


def _diag_of(A, diag) -> Diag:
    if diag is not None:
        return Diag.from_string(diag)
    return getattr(A, "diag", Diag.NonUnit)


def select_algo_gemm(A, B, C, opts: Options) -> MethodGemm:
    """Pick a gemm variant (src/gemm.cc:12-24 select_algo).

    The reference picks stationary-C when B has >= 2 block columns, else stationary-A.
    On one device both are the same XLA matmul; the distinction is kept so distributed
    callers can follow the same heuristic.
    """
    if opts.method_gemm != MethodGemm.Auto:
        return opts.method_gemm
    B_nt = B.nt if isinstance(B, BaseMatrix) else 2
    return MethodGemm.C if B_nt >= 2 else MethodGemm.A


def gemm(alpha, A, B, beta, C, opts=None):
    """C = alpha op(A) op(B) + beta C (src/gemm.cc:87)."""
    from .core.matrix import distribution_grid

    opts = Options.make(opts)
    grid = distribution_grid(A, B, C)
    if opts.f64_emulation:
        # double-precision-class result on f64-less hardware (exact Ozaki
        # bf16 splitting + double-f32 accumulation, ops/f64emu.py); the
        # whole alpha/beta combination happens inside the compensated
        # accumulator so residual-style calls keep their accuracy
        if grid is not None:
            raise SlateError("f64_emulation gemm is single-device; detach "
                             "the grid or pre-gather the operands")
        from .ops.f64emu import gemm_f64emu

        out = gemm_f64emu(as_array(A), as_array(B), alpha=alpha, beta=beta,
                          C=as_array(C))
        return write_back(C, out)
    if grid is not None:
        # wrappers bound to a >1-device grid run the SUMMA pipeline over it
        # (scalapack_gemm.cc builds on the BLACS grid the same way)
        from .parallel import summa

        return write_back(C, summa.summa_gemm(alpha, A, B, beta, C, opts,
                                              grid=grid))
    method = select_algo_gemm(A, B, C, opts)
    if method == MethodGemm.SUMMA:
        # explicit shard_map pipeline; requires distributed wrappers
        try:
            from .parallel import summa
        except ImportError as e:
            raise SlateError("MethodGemm.SUMMA requires the distributed layer "
                             "(slate_tpu.parallel)") from e
        out = summa.summa_gemm(alpha, A, B, beta, C, opts)
    else:
        # stationary-A/C both lower to one fused MXU matmul on a single array;
        # stationarity is a communication-layout concept handled by the sharding
        out = blas3.gemm(alpha, as_array(A), as_array(B), beta, as_array(C))
    return write_back(C, out)


def gemmA(alpha, A, B, beta, C, opts=None):
    """Stationary-A gemm (src/gemmA.cc): A's tiles stay put, partial C
    products are reduced to C's owners — the reference's pick for a B with
    one block column (select_algo, src/gemm.cc:12-24).  On one device the
    stationarity distinction is a communication layout, not a kernel: both
    variants are the same fused MXU matmul."""
    from dataclasses import replace

    opts = replace(Options.make(opts), method_gemm=MethodGemm.A)
    return gemm(alpha, A, B, beta, C, opts)


def gemmC(alpha, A, B, beta, C, opts=None):
    """Stationary-C gemm (src/gemmC.cc): C never moves, A panels are
    broadcast — the wide-B default."""
    from dataclasses import replace

    opts = replace(Options.make(opts), method_gemm=MethodGemm.C)
    return gemm(alpha, A, B, beta, C, opts)


def symm(side, alpha, A, B, beta, C, opts=None, uplo=None):
    """C = alpha A B + beta C, A symmetric (src/symm.cc)."""
    out = blas3.symm(side, alpha, as_array(A), _uplo_of(A, uplo),
                     as_array(B), beta, as_array(C))
    return write_back(C, out)


def hemm(side, alpha, A, B, beta, C, opts=None, uplo=None):
    """Hermitian symm (src/hemm.cc, hemmA/hemmC variants)."""
    out = blas3.hemm(side, alpha, as_array(A), _uplo_of(A, uplo),
                     as_array(B), beta, as_array(C))
    return write_back(C, out)


def hemmA(side, alpha, A, B, beta, C, opts=None, uplo=None):
    """Stationary-A Hermitian multiply (src/hemmA.cc); see gemmA for the
    stationarity semantics on TPU."""
    return hemm(side, alpha, A, B, beta, C, opts=opts, uplo=uplo)


def hemmC(side, alpha, A, B, beta, C, opts=None, uplo=None):
    """Stationary-C Hermitian multiply (src/hemmC.cc)."""
    return hemm(side, alpha, A, B, beta, C, opts=opts, uplo=uplo)


def syrk(alpha, A, beta, C, opts=None, uplo=None):
    """C = alpha A A^T + beta C on the stored triangle (src/syrk.cc)."""
    out = blas3.syrk(alpha, as_array(A), beta, as_array(C), _uplo_of(C, uplo))
    return write_back(C, out)


def herk(alpha, A, beta, C, opts=None, uplo=None):
    """C = alpha A A^H + beta C, alpha/beta real (src/herk.cc)."""
    out = blas3.herk(alpha, as_array(A), beta, as_array(C), _uplo_of(C, uplo))
    return write_back(C, out)


def syr2k(alpha, A, B, beta, C, opts=None, uplo=None):
    out = blas3.syr2k(alpha, as_array(A), as_array(B), beta, as_array(C),
                      _uplo_of(C, uplo))
    return write_back(C, out)


def her2k(alpha, A, B, beta, C, opts=None, uplo=None):
    out = blas3.her2k(alpha, as_array(A), as_array(B), beta, as_array(C),
                      _uplo_of(C, uplo))
    return write_back(C, out)


def trmm(side, alpha, A, B, opts=None, uplo=None, diag=None):
    """B = alpha op(T) B / alpha B op(T) (src/trmm.cc; work::trmm body)."""
    out = blas3.trmm(side, _uplo_of(A, uplo), _diag_of(A, diag),
                     alpha, as_array(A), as_array(B))
    return write_back(B, out)


def select_algo_trsm(A, B, opts: Options) -> MethodTrsm:
    """Pick a trsm variant (src/trsm.cc:11-23 select_algo).

    The reference picks stationary-A when B has a single block column (a
    narrow right-hand side: moving nb×nrhs X blocks is cheaper than moving
    A's panels), else stationary-B.  On one device both lower to the same
    XLA TriangularSolve; on a grid they are genuinely different dataflows
    (parallel/solvers.py trsmA_distributed vs trsm_distributed)."""
    if opts.method_trsm != MethodTrsm.Auto:
        return opts.method_trsm
    B_nt = B.nt if isinstance(B, BaseMatrix) else 2
    return MethodTrsm.A if B_nt < 2 else MethodTrsm.B


def _trsm_dispatch(method, side, alpha, A, B, opts, uplo, diag):
    from .core.matrix import distribution_grid

    grid = distribution_grid(A, B)
    if grid is None:
        # one device: stationarity is a communication concept; both methods
        # are the same blocked TriangularSolve
        out = blas3.trsm(side, _uplo_of(A, uplo), _diag_of(A, diag),
                         alpha, as_array(A), as_array(B))
        return write_back(B, out)
    from .parallel.solvers import trsmA_distributed, trsm_distributed

    u = _uplo_of(A, uplo)
    d = _diag_of(A, diag)
    a, b = as_array(A), as_array(B)
    s = Side.from_string(side)
    if s == Side.Right:
        # X op(A) = alpha B  <=>  op(A)^T X^T = alpha B^T: reuse the left
        # sweeps on transposed operands (work_trsmA.cc:79-89 does the same)
        a, b = a.T, jnp.swapaxes(b, -1, -2)
        u = Uplo.Upper if u == Uplo.Lower else Uplo.Lower
    lower = u == Uplo.Lower
    if method == MethodTrsm.A:
        out = trsmA_distributed(a, jnp.asarray(alpha, b.dtype) * b, grid,
                                lower=lower, unit_diag=(d == Diag.Unit))
    else:
        if d == Diag.Unit:
            # stationary-B's fused TriangularSolve has no unit flag here:
            # make the implicit unit diagonal explicit instead
            idx = jnp.arange(a.shape[-1])
            a = a.at[idx, idx].set(jnp.asarray(1.0, a.dtype))
        out = trsm_distributed(a, jnp.asarray(alpha, b.dtype) * b, grid,
                               lower=lower)
    if s == Side.Right:
        out = jnp.swapaxes(out, -1, -2)
    return write_back(B, out)


def trsm(side, alpha, A, B, opts=None, uplo=None, diag=None):
    """Solve op(T) X = alpha B in place of B (src/trsm.cc; work::trsm,
    work_trsm.cc:54-387 — the lookahead task DAG collapses into XLA's blocked
    TriangularSolve on TPU).  Grid-bound operands dispatch between the
    stationary-A and stationary-B distributed dataflows via select_algo."""
    opts = Options.make(opts)
    return _trsm_dispatch(select_algo_trsm(A, B, opts), side, alpha, A, B,
                          opts, uplo, diag)


def trsmA(side, alpha, A, B, opts=None, uplo=None, diag=None):
    """Stationary-A triangular solve (src/trsmA.cc): A's tiles stay put, the
    narrow B moves.  Explicit-method entry; trsm's select_algo picks this
    form automatically when B has one block column."""
    opts = Options.make(opts)
    return _trsm_dispatch(MethodTrsm.A, side, alpha, A, B, opts, uplo, diag)


def trsmB(side, alpha, A, B, opts=None, uplo=None, diag=None):
    """Stationary-B triangular solve (src/trsmB.cc): B's tiles stay put, A's
    panels are broadcast — the default for wide right-hand sides."""
    opts = Options.make(opts)
    return _trsm_dispatch(MethodTrsm.B, side, alpha, A, B, opts, uplo, diag)


# ---------------------------------------------------------------------------
# Aux drivers (add/copy/scale/set/norm)
# ---------------------------------------------------------------------------


def add(alpha, A, beta, B, opts=None):
    """B = alpha A + beta B (src/add.cc; tzadd for trapezoid operands)."""
    if isinstance(B, BaseTrapezoidMatrix):
        out = elementwise.tzadd(B.uplo, alpha, as_array(A), beta, as_array(B))
    else:
        out = elementwise.geadd(alpha, as_array(A), beta, as_array(B))
    return write_back(B, out)


def copy(A, B, opts=None):
    """B = A with dtype conversion (src/copy.cc; device_gecopy.cu)."""
    if isinstance(B, BaseTrapezoidMatrix):
        out = elementwise.tzcopy(B.uplo, as_array(A), as_array(B))
    else:
        out = elementwise.gecopy(as_array(A), as_array(B).dtype)
    return write_back(B, out)


def scale(numer, denom, A, opts=None):
    """A *= numer/denom (src/scale.cc)."""
    if isinstance(A, BaseTrapezoidMatrix):
        out = elementwise.tzscale(A.uplo, numer, denom, as_array(A))
    else:
        out = elementwise.gescale(numer, denom, as_array(A))
    return write_back(A, out)


def scale_row_col(R, C, A, opts=None):
    """A = diag(R) A diag(C) equilibration (src/scale_row_col.cc)."""
    out = elementwise.gescale_row_col(jnp.asarray(R), jnp.asarray(C), as_array(A))
    return write_back(A, out)


def set(offdiag_value, diag_value, A, opts=None):  # noqa: A001 - reference name
    """Set entries to constants (src/set.cc; geset/tzset kernels)."""
    if isinstance(A, BaseTrapezoidMatrix):
        out = elementwise.tzset(A.uplo, offdiag_value, diag_value, as_array(A))
    else:
        out = elementwise.geset(offdiag_value, diag_value, as_array(A))
    return write_back(A, out)


def set_from_function(value, A, opts=None):
    """Set entries A[i, j] = value(i, j) (src/set_lambdas.cc).

    TPU re-design: the reference evaluates a per-entry host lambda inside
    each tile task; here ``value`` receives broadcastable global index arrays
    (I of shape (m, 1), J of shape (1, n)) and is evaluated once, vectorized
    — jnp-traceable functions stay on device, numpy functions work too."""
    a = as_array(A)
    m, n = a.shape[-2:]
    I = jnp.arange(m)[:, None]
    J = jnp.arange(n)[None, :]
    vals = jnp.broadcast_to(jnp.asarray(value(I, J), dtype=a.dtype), a.shape)
    if isinstance(A, BaseTrapezoidMatrix):
        # only the stored triangle is set; the off-triangle of shared storage
        # passes through untouched (same contract as set()/tzset)
        from .core.types import Uplo

        mask = (I >= J) if A.uplo == Uplo.Lower else (I <= J)
        vals = jnp.where(mask, vals, a)
    return write_back(A, vals)


set_lambdas = set_from_function   # reference driver name (src/set_lambdas.cc)


def norm(norm_kind, A, opts=None, scope=NormScope.Matrix, uplo=None, diag=None):
    """Matrix norm dispatched on matrix type (src/norm.cc).

    General -> genorm, symmetric/Hermitian -> synorm/henorm, triangular -> trnorm,
    band -> gbnorm/hbnorm (internal_*norm.cc family).
    """
    from .core.matrix import distribution_grid
    from .core.types import Norm

    a = as_array(A)
    grid = distribution_grid(A)
    kind = Norm.from_string(norm_kind)
    the_scope = NormScope.from_string(scope)
    if (grid is not None and a.ndim == 2
            and kind in (Norm.Max, Norm.One, Norm.Inf, Norm.Fro)):
        # wrapper bound to a >1-device grid: sharded masked reduction.
        # Band and unit-diagonal triangles keep the local masked kernels.
        from .parallel import col_norms_distributed, norm_distributed

        general = not isinstance(A, (BaseTrapezoidMatrix, BaseBandMatrix))
        if the_scope == NormScope.Columns and general and kind == Norm.Max:
            return col_norms_distributed(a, grid)
        if the_scope == NormScope.Matrix:
            if isinstance(A, (HermitianMatrix, SymmetricMatrix)):
                return norm_distributed(kind, A.full_array(), grid)
            if (isinstance(A, BaseTrapezoidMatrix)
                    and _diag_of(A, diag) != Diag.Unit):
                return norm_distributed(kind, a, grid, uplo=str(A.uplo.value))
            if general:
                return norm_distributed(kind, a, grid)
    if isinstance(A, HermitianMatrix):
        return norm_ops.henorm(norm_kind, A.uplo, a)
    if isinstance(A, SymmetricMatrix):
        return norm_ops.synorm(norm_kind, A.uplo, a)
    if isinstance(A, BaseTrapezoidMatrix):
        return norm_ops.trnorm(norm_kind, A.uplo, A.diag, a)
    if isinstance(A, BaseBandMatrix):
        from .core.matrix import HermitianBandMatrix
        if isinstance(A, HermitianBandMatrix):
            return norm_ops.hbnorm(norm_kind, A.uplo, A.kd, a)
        # TriangularBandMatrix's (kl, ku) already encode triangle ∩ band exactly
        return norm_ops.gbnorm(norm_kind, A.kl, A.ku, a)
    return norm_ops.genorm(norm_kind, a, scope)


def col_norms(norm_kind, A, opts=None):
    """Per-column max norms (src/colNorms.cc; Norm.Max only, like the reference)."""
    return norm_ops.genorm(norm_kind, as_array(A), NormScope.Columns)
