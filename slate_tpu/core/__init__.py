"""Core runtime: types, tile-grid metadata, matrix hierarchy (reference L2)."""

from .exceptions import (ConvergenceError, DeadlineExceededError,
                         NumericalError, QueueOverloadError,
                         SingularMatrixError, SlateError, slate_assert)
from .types import (Diag, GridOrder, Layout, MethodCholQR, MethodEig, MethodGels,
                    MethodGemm, MethodHemm, MethodLU, MethodSVD, MethodTrsm, Norm,
                    NormScope, Op, Options, Side, Target, TileKind, Uplo)
from .matrix import (BandMatrix, BaseBandMatrix, BaseMatrix, BaseTrapezoidMatrix,
                     HermitianBandMatrix, HermitianMatrix, Matrix, MatrixStorage,
                     SymmetricMatrix, TrapezoidMatrix, TriangularBandMatrix,
                     TriangularMatrix, as_array, distribution_grid, write_back)
from . import grid as func  # reference include/slate/func.hh namespace name
