"""Exceptions (reference: include/slate/Exception.hh:1-126).

The reference wraps MPI errors (`internal/mpi.hh:10-37`); here there is no MPI — JAX/XLA
errors propagate natively — so only the library-level exception and assert helper remain.
"""

from __future__ import annotations


class SlateError(RuntimeError):
    """Library error (reference slate_error / SLATE Exception.hh:1-60)."""


def slate_assert(cond: bool, msg: str = "") -> None:
    """Check a library invariant (reference slate_assert, Exception.hh:100-126)."""
    if not cond:
        raise SlateError(msg or "assertion failed")
