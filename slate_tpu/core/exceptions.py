"""Exceptions (reference: include/slate/Exception.hh:1-126).

The reference wraps MPI errors (`internal/mpi.hh:10-37`); here there is no MPI — JAX/XLA
errors propagate natively — so the library-level exception hierarchy and the assert
helper remain.  The taxonomy below mirrors the *failure classes* the reference's
drivers distinguish through info codes and fallback paths (SURVEY §2.7):

- :class:`NumericalError` — the factorization/solve ran but the numbers broke
  (non-finite values, loss of positive definiteness, breakdown pivots).
- :class:`SingularMatrixError` — a zero/NaN pivot made the matrix numerically
  singular (LAPACK info > 0 from LU/Cholesky-class factorizations).
- :class:`ConvergenceError` — an iterative stage (IR, GMRES-IR, eigensolver
  iteration) stalled and every declared escalation rung was exhausted.
  Raised by ``slate_tpu.robust.run_ladder`` when the caller asks for it
  (``raise_on_exhaust=True``); the built-in drivers keep LAPACK semantics
  instead — best-effort result, nonzero info, ``recovered=False`` report.

The serving tier (``slate_tpu.serve``) adds two *operational* failure
classes — the numbers were fine (or never computed), the service declined
the work:

- :class:`QueueOverloadError` — admission control rejected the request
  (lane queue full, token bucket empty, or SLO-coupled shedding active).
  Carries the lane, the observed queue depth, and a retry-after hint.
- :class:`DeadlineExceededError` — a queued request's deadline budget ran
  out before (or while) it would have been served; the queue expires it
  instead of wasting a batch slot.
"""

from __future__ import annotations


class SlateError(RuntimeError):
    """Library error (reference slate_error / SLATE Exception.hh:1-60)."""


class NumericalError(SlateError):
    """A computation produced numerically invalid results.

    Covers non-finite values, indefinite matrices where SPD was required,
    and breakdown pivots."""


class SingularMatrixError(NumericalError):
    """The matrix is numerically singular (zero/NaN pivot; LAPACK info > 0).

    ``info`` carries the 1-based index of the first failing pivot when known.
    """

    def __init__(self, msg: str = "", info: int = 0):
        super().__init__(msg or f"singular matrix (info={info})")
        self.info = int(info)


class ConvergenceError(NumericalError):
    """An iterative solve failed to converge and no fallback recovered it.

    Raised by ``robust.run_ladder(..., raise_on_exhaust=True)``; the built-in
    drivers return best-effort + nonzero info instead of raising (LAPACK
    convention), so catch this only around ladders you run with that flag.
    ``report`` (when set) is the :class:`slate_tpu.robust.SolveReport` of the
    exhausted escalation ladder.
    """

    def __init__(self, msg: str = "", report=None):
        super().__init__(msg or "iterative solve failed to converge")
        self.report = report


class QueueOverloadError(SlateError):
    """Admission control rejected the request — the serving tier is shedding.

    Structured fields (the load-balancer / retry-loop contract):

    ``lane``          the priority lane the request targeted;
    ``depth``         that lane's queue depth at rejection time;
    ``reason``        what tripped — ``depth`` (lane queue full),
                      ``inflight`` (global in-flight cap), ``rate`` (token
                      bucket empty), ``slo_warning`` / ``slo_breach``
                      (SLO-coupled shedding);
    ``retry_after_s`` hint for when the caller may retry (None = unknown —
                      re-probe, don't hammer).
    """

    def __init__(self, msg: str = "", lane: str = "", depth: int = 0,
                 reason: str = "", retry_after_s: float = None):
        super().__init__(
            msg or f"serve: lane {lane!r} shedding load "
                   f"(reason={reason or '?'}, depth={depth})")
        self.lane = str(lane)
        self.depth = int(depth)
        self.reason = str(reason)
        self.retry_after_s = (None if retry_after_s is None
                              else float(retry_after_s))


class DeadlineExceededError(SlateError):
    """A request's deadline budget expired before it was served.

    ``lane`` / ``deadline_s`` (the submitted budget, seconds) /
    ``elapsed_s`` (time spent queued when the queue expired it)."""

    def __init__(self, msg: str = "", lane: str = "",
                 deadline_s: float = 0.0, elapsed_s: float = 0.0):
        super().__init__(
            msg or f"serve: deadline of {deadline_s:g}s exceeded after "
                   f"{elapsed_s:.3f}s queued (lane {lane!r})")
        self.lane = str(lane)
        self.deadline_s = float(deadline_s)
        self.elapsed_s = float(elapsed_s)


def slate_assert(cond: bool, msg: str = "") -> None:
    """Check a library invariant (reference slate_assert, Exception.hh:100-126)."""
    if not cond:
        raise SlateError(msg or "assertion failed")
