"""Tile-grid distribution functions (reference: include/slate/func.hh, 339 LoC).

The reference makes data distribution a first-class lambda: ``tileRank(i, j)``,
``tileDevice(i, j)``, ``tileMb(i)``, ``tileNb(j)`` are ``std::function`` members of
``MatrixStorage`` (MatrixStorage.hh:339-342), with 2D block-cyclic as the default
(func.hh:100-217).  We keep exactly that design: plain Python callables over tile indices,
with the same factories.  On TPU the "rank" is a flattened (p, q) mesh coordinate — the
device holding the tile under the block-cyclic shard layout (see parallel/distribute.py).

Everything here is host-side metadata — cheap, trace-free, and never jitted.
"""

from __future__ import annotations

import math
from typing import Callable, Sequence, Tuple

from .types import GridOrder

TileIndexFunc = Callable[[int], int]          # i -> mb(i)  (func.hh uniform_blocksize)
TileRankFunc = Callable[[int, int], int]      # (i, j) -> rank


def uniform_blocksize(n: int, nb: int) -> TileIndexFunc:
    """Uniform tile size with ragged last tile (func.hh:39-42)."""

    def mb(i: int) -> int:
        return nb if (i + 1) * nb <= n else max(0, n - i * nb)

    return mb


def num_tiles(n: int, nb: int) -> int:
    """ceil(n / nb), the reference's mt()/nt() computation (BaseMatrix.hh)."""
    return -(-n // nb) if n > 0 else 0


def process_2d_grid(order: GridOrder, p: int, q: int) -> TileRankFunc:
    """2D block-cyclic tile→rank map over a p×q grid (func.hh:178-186).

    Col order: rank = (i%p) + (j%q)*p.  Row order: rank = (i%p)*q + (j%q).
    """
    order = GridOrder.from_string(order)
    if order == GridOrder.Col:
        return lambda i, j: (i % p) + (j % q) * p
    elif order == GridOrder.Row:
        return lambda i, j: (i % p) * q + (j % q)
    raise ValueError(f"unsupported grid order {order}")


def process_1d_grid(order: GridOrder, size: int) -> TileRankFunc:
    """1D block-cyclic map (func.hh process_1d_grid)."""
    order = GridOrder.from_string(order)
    if order == GridOrder.Col:
        return lambda i, j: i % size
    return lambda i, j: j % size


def device_2d_grid(order: GridOrder, p: int, q: int) -> TileRankFunc:
    """Device map analogue (func.hh:100-118). On TPU tileDevice == tileRank."""
    return process_2d_grid(order, p, q)

def device_1d_grid(order: GridOrder, size: int) -> TileRankFunc:
    return process_1d_grid(order, size)


def transpose_grid(func: TileRankFunc) -> TileRankFunc:
    """Swap tile indices (func.hh:229-237); used when transposing a matrix view."""
    return lambda i, j: func(j, i)


def grid_size(nranks: int) -> Tuple[int, int]:
    """Pick the squarest p×q with p*q == nranks (tester's default grid choice)."""
    p = int(math.isqrt(nranks))
    while nranks % p != 0:
        p -= 1
    return p, nranks // p


def is_2d_cyclic_grid(mt: int, nt: int, func: TileRankFunc) -> Tuple[bool, GridOrder, int, int]:
    """Detect whether ``func`` is a 2D block-cyclic grid over the mt×nt tile space and
    recover (order, p, q) (func.hh:265-334).  Returns (ok, order, p, q).
    """
    if mt <= 0 or nt <= 0:
        return True, GridOrder.Col, 1, 1
    # p = number of distinct ranks down the first column before repeating
    p = 1
    while p < mt and func(p, 0) != func(0, 0):
        p += 1
    q = 1
    while q < nt and func(0, q) != func(0, 0):
        q += 1
    for order in (GridOrder.Col, GridOrder.Row):
        cand = process_2d_grid(order, p, q)
        if all(func(i, j) == cand(i, j)
               for i in range(min(mt, 2 * p + 1))
               for j in range(min(nt, 2 * q + 1))):
            return True, order, p, q
    return False, GridOrder.Unknown, p, q
