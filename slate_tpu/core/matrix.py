"""Distributed tiled matrix types — the L2 runtime.

TPU-native re-design of the reference's matrix hierarchy:

- ``BaseMatrix`` (include/slate/BaseMatrix.hh, 3976 LoC) — views, tile access, offsets
- ``Matrix`` / ``TrapezoidMatrix`` / ``TriangularMatrix`` / ``SymmetricMatrix`` /
  ``HermitianMatrix`` / band variants (include/slate/*.hh, ~5400 LoC)
- ``MatrixStorage`` (include/slate/internal/MatrixStorage.hh) — the distributed tile map

Re-design rationale (TPU-first):

* The reference stores a ``std::map<(i,j) -> TileNode>`` of individually-allocated tiles
  with a MOSI host/device coherence protocol (BaseMatrix.hh:2640-2718).  On TPU a matrix
  is **one jax.Array resident in HBM**, optionally sharded over a ``jax.sharding.Mesh``;
  XLA manages placement and there is exactly one device copy per shard, so the entire
  MOSI state machine disappears.  What survives is the *metadata*: the tile grid
  (mb/nb/rank lambdas, MatrixStorage.hh:339-342) and cheap views.

* Views are index arithmetic, exactly like the reference: ``sub`` (BaseMatrix.hh:104-106)
  and ``slice`` (BaseMatrix.hh:110-121) share storage; ``transpose`` is a flag flip
  (Tile.hh:40-52).  Because jax.Arrays are immutable, "mutation" of a view functionally
  rebinds the shared :class:`MatrixStorage` array with an ``.at[].set`` — drivers keep
  their hot loops inside jit over raw arrays and only touch these wrappers at API
  boundaries.

* Distribution: ``MatrixStorage`` carries the tile->rank lambda (2D block-cyclic default,
  func.hh:100-217) and an optional :class:`~slate_tpu.parallel.mesh.ProcessGrid`; the
  actual sharding of the jax.Array is applied by ``parallel/distribute.py``.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from . import grid as grid_funcs
from .exceptions import SlateError, slate_assert
from .types import Diag, GridOrder, Op, TileKind, Uplo


def _expand_tile_sizes(total: int, spec):
    """Materialize a tile-size lambda / vector into an exact-cover tuple."""
    if spec is None:
        return None
    if callable(spec):
        sizes, s, i = [], 0, 0
        while s < total:
            b = int(spec(i))
            slate_assert(b > 0, f"tile size lambda returned {b} at index {i}")
            sizes.append(min(b, total - s))   # ragged last tile, like nb
            s += b
            i += 1
        spec = sizes
    sizes = [int(b) for b in spec]
    slate_assert(all(b > 0 for b in sizes) and sum(sizes) == total,
                 f"tile sizes {sizes} do not exactly cover dimension {total}")
    return tuple(sizes)


def _prefix(sizes):
    if sizes is None:
        return None
    offs = [0]
    for b in sizes:
        offs.append(offs[-1] + b)
    return tuple(offs)


def _offset_index(offs, offset: int, what: str) -> int:
    """Tile index whose boundary is exactly ``offset`` (views of non-uniform
    matrices must stay tile-aligned — same restriction the reference's
    sub/slice tile arithmetic has)."""
    k = _offset_index_or_none(offs, offset)
    slate_assert(k is not None,
                 f"{what}: offset {offset} is not a tile boundary of the "
                 f"non-uniform grid {offs}")
    return k


def _offset_index_or_none(offs, offset: int):
    import bisect

    k = bisect.bisect_left(offs, offset)
    return k if k < len(offs) and offs[k] == offset else None


class MatrixStorage:
    """Shared storage for a family of views (reference MatrixStorage.hh:150-1156).

    Holds the backing jax.Array (global logical matrix, untransposed), the tile-size
    lambdas, the tile->rank distribution lambda, and the optional process grid.  All
    views of one matrix hold a reference to one instance (BaseMatrix.hh:789-790
    ``shared_ptr<MatrixStorage>``).
    """

    __slots__ = ("array", "mb", "nb", "tile_rank", "grid", "kind", "p", "q",
                 "order", "default_rank_map", "pool", "mb_sizes", "nb_sizes",
                 "mb_offs", "nb_offs", "__weakref__")

    def __init__(self, array: jax.Array, mb: int, nb: int,
                 p: int = 1, q: int = 1, order: GridOrder = GridOrder.Col,
                 grid: Any = None, kind: TileKind = TileKind.SlateOwned,
                 tile_rank: Optional[grid_funcs.TileRankFunc] = None,
                 tile_mb=None, tile_nb=None):
        self.array = array
        # first-class per-index tile-size lambdas (MatrixStorage.hh:339-342,
        # func.hh:39-42): ``tile_mb``/``tile_nb`` may be a callable i -> size
        # or an explicit size vector.  They live purely in the METADATA layer
        # — tile accessors, views, owner maps and redistribution honor them,
        # while compiled drivers keep their uniform pad-to-nb blocking
        # (SURVEY §7 hard-part 5's pad-to-uniform boundary).
        self.mb_sizes = _expand_tile_sizes(array.shape[-2], tile_mb)
        self.nb_sizes = _expand_tile_sizes(array.shape[-1], tile_nb)
        self.mb_offs = _prefix(self.mb_sizes)
        self.nb_offs = _prefix(self.nb_sizes)
        self.mb = int(mb) if self.mb_sizes is None else max(self.mb_sizes)
        self.nb = int(nb) if self.nb_sizes is None else max(self.nb_sizes)
        self.p = int(p)
        self.q = int(q)
        self.order = GridOrder.from_string(order)
        # custom lambdas disable the native owner-map fast path (which rebuilds
        # the default 2D block-cyclic map from (order, p, q) only)
        self.default_rank_map = tile_rank is None
        self.tile_rank = tile_rank or grid_funcs.process_2d_grid(self.order, self.p, self.q)
        self.grid = grid          # ProcessGrid (parallel/mesh.py) or None
        self.kind = kind
        # A real (>1 device) grid places the backing array at construction —
        # the reference ties the distribution into every matrix the same way
        # (MatrixStorage.hh:494-511 installs tileRank/tileDevice in the ctor).
        self.place_on_grid()
        if _pool_tracking:
            _register_storage(self)

    def place_on_grid(self) -> None:
        """(Re)place the backing array onto the bound grid's block layout —
        the single definition of "does this storage live on a device grid"."""
        if (self.grid is not None and getattr(self.grid, "size", 1) > 1
                and hasattr(self.grid, "spec")
                and getattr(self.array, "ndim", 0) == 2):
            self.array = jax.device_put(self.array, self.grid.spec())

    @property
    def m(self) -> int:
        return self.array.shape[-2]

    @property
    def n(self) -> int:
        return self.array.shape[-1]

    def update(self, row0: int, col0: int, block: jax.Array) -> None:
        """Functionally write ``block`` into the backing array at (row0, col0)."""
        if row0 == 0 and col0 == 0 and block.shape == self.array.shape:
            self.array = block
        else:
            self.array = self.array.at[row0:row0 + block.shape[-2],
                                       col0:col0 + block.shape[-1]].set(block)


class BaseMatrix:
    """Shared view machinery for all matrix types (BaseMatrix.hh:39-795).

    A view is (storage, ioffset, joffset, m, n, op); ``uplo``/``diag`` live on the typed
    subclasses.  Offsets and extents are in **elements** of the untransposed storage.
    """

    uplo: Uplo = Uplo.General
    diag: Diag = Diag.NonUnit

    def __init__(self, storage: MatrixStorage, ioffset: int, joffset: int,
                 m: int, n: int, op: Op = Op.NoTrans):
        self.storage = storage
        self.ioffset = int(ioffset)
        self.joffset = int(joffset)
        self._m = int(m)   # extent in *storage* coordinates (before op)
        self._n = int(n)
        self.op = op

    # ----- shape ---------------------------------------------------------------
    @property
    def m(self) -> int:
        """Logical row count (after op), BaseMatrix.hh m()."""
        return self._n if self.op != Op.NoTrans else self._m

    @property
    def n(self) -> int:
        return self._m if self.op != Op.NoTrans else self._n

    @property
    def mb(self) -> int:
        return self.storage.nb if self.op != Op.NoTrans else self.storage.mb

    @property
    def nb(self) -> int:
        return self.storage.mb if self.op != Op.NoTrans else self.storage.nb

    def _row_tiles(self):
        """(base, count, sizes, offs) of the view's LOGICAL-row tiling in
        storage terms; sizes is None on the uniform path."""
        st = self.storage
        if self.op == Op.NoTrans:
            sizes, offs, off0, ext, ub = (st.mb_sizes, st.mb_offs,
                                          self.ioffset, self._m, st.mb)
        else:
            sizes, offs, off0, ext, ub = (st.nb_sizes, st.nb_offs,
                                          self.joffset, self._n, st.nb)
        return self._tiles_meta(sizes, offs, off0, ext, ub)

    def _col_tiles(self):
        st = self.storage
        if self.op == Op.NoTrans:
            sizes, offs, off0, ext, ub = (st.nb_sizes, st.nb_offs,
                                          self.joffset, self._n, st.nb)
        else:
            sizes, offs, off0, ext, ub = (st.mb_sizes, st.mb_offs,
                                          self.ioffset, self._m, st.mb)
        return self._tiles_meta(sizes, offs, off0, ext, ub)

    @staticmethod
    def _tiles_meta(sizes, offs, off0, ext, ub):
        if sizes is not None:
            b0 = _offset_index_or_none(offs, off0)
            b1 = _offset_index_or_none(offs, off0 + ext)
            if b0 is not None and b1 is not None:
                return b0, b1 - b0, sizes, offs
            # non-tile-aligned slice of a non-uniform matrix: tile metadata
            # re-bases to the max-block uniform fallback — the same semantics
            # a misaligned slice already has on uniform matrices (tileRank
            # keeps its own hard alignment check)
        return None, grid_funcs.num_tiles(ext, ub), None, None

    @property
    def mt(self) -> int:
        """Row tile count (BaseMatrix.hh mt())."""
        return self._row_tiles()[1]

    @property
    def nt(self) -> int:
        return self._col_tiles()[1]

    def tileMb(self, i: int) -> int:
        b0, _, sizes, _ = self._row_tiles()
        if sizes is None:
            return grid_funcs.uniform_blocksize(self.m, self.mb)(i)
        return sizes[b0 + i]

    def tileNb(self, j: int) -> int:
        b0, _, sizes, _ = self._col_tiles()
        if sizes is None:
            return grid_funcs.uniform_blocksize(self.n, self.nb)(j)
        return sizes[b0 + j]

    def _logical_tile_offset(self, axis: int, t: int) -> int:
        """View-relative element offset of logical tile ``t`` along
        ``axis`` (0 = rows, 1 = cols)."""
        b0, _, sizes, offs = self._row_tiles() if axis == 0 else \
            self._col_tiles()
        if sizes is None:
            return t * (self.mb if axis == 0 else self.nb)
        return offs[b0 + t] - offs[b0]

    def tileRank(self, i: int, j: int) -> int:
        """Tile owner rank in the flattened p×q grid (MatrixStorage.hh:339).

        Only meaningful on tile-aligned views (anything built via ctor/sub/transpose);
        a ``slice`` at a non-tile-aligned offset has no well-defined tile->rank map.
        """
        st = self.storage
        if self.op != Op.NoTrans:
            i, j = j, i
        if st.mb_sizes is None:
            slate_assert(self.ioffset % st.mb == 0,
                         "tileRank on a non-tile-aligned slice view")
            si = self.ioffset // st.mb + i
        else:
            si = _offset_index(st.mb_offs, self.ioffset, "tileRank") + i
        if st.nb_sizes is None:
            slate_assert(self.joffset % st.nb == 0,
                         "tileRank on a non-tile-aligned slice view")
            sj = self.joffset // st.nb + j
        else:
            sj = _offset_index(st.nb_offs, self.joffset, "tileRank") + j
        return st.tile_rank(si, sj)

    def tileIsLocal(self, i: int, j: int) -> bool:
        """Whether tile (i, j) is owned by this process's rank on the grid
        (BaseMatrix::tileIsLocal).  Without a grid everything is local; with
        one, ProcessGrid.rank resolves the controller's flattened position
        (multi-host aware via jax.local_devices)."""
        g = self.storage.grid
        rank = 0 if g is None else getattr(g, "rank", 0)
        return self.tileRank(i, j) == rank

    @property
    def dtype(self):
        return self.storage.array.dtype

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.m, self.n)

    def gridinfo(self) -> Tuple[GridOrder, int, int]:
        """(order, p, q) of the process grid (BaseMatrix.hh:161-164)."""
        return self.storage.order, self.storage.p, self.storage.q

    def owner_map(self):
        """(mt, nt) int32 array of tile owners — the materialized tile directory
        (MatrixStorage.hh's map).  Root views use the native runtime's fast fill
        (slate_tpu/native.py; numpy fallback); transposed/offset views go through
        tileRank so the view semantics stay exact."""
        import numpy as np
        from .. import native
        if (self.op == Op.NoTrans and self.ioffset == 0 and self.joffset == 0
                and self.storage.default_rank_map
                and self.storage.mb_sizes is None
                and self.storage.nb_sizes is None):
            order, p, q = self.gridinfo()
            return native.owner_map(self.mt, self.nt, p, q, order)
        return np.array([[self.tileRank(i, j) for j in range(self.nt)]
                         for i in range(self.mt)], dtype=np.int32)

    def local_tiles(self, rank: int):
        """(k, 2) tile indices owned by ``rank`` (the per-rank directory walk the
        reference does when enumerating local tiles)."""
        import numpy as np
        from .. import native
        if (self.op == Op.NoTrans and self.ioffset == 0 and self.joffset == 0
                and self.storage.default_rank_map
                and self.storage.mb_sizes is None
                and self.storage.nb_sizes is None):
            order, p, q = self.gridinfo()
            return native.local_tiles(self.mt, self.nt, p, q, rank, order)
        ii, jj = np.nonzero(self.owner_map() == rank)
        return np.stack([ii, jj], axis=1).astype(np.int64)

    # ----- data access ---------------------------------------------------------
    @property
    def array(self) -> jax.Array:
        """Materialize the logical view (op applied). Read side of tileGetForReading."""
        a = self.storage.array[..., self.ioffset:self.ioffset + self._m,
                               self.joffset:self.joffset + self._n]
        if self.op == Op.Trans:
            a = jnp.swapaxes(a, -1, -2)
        elif self.op == Op.ConjTrans:
            a = jnp.conj(jnp.swapaxes(a, -1, -2))
        return a

    def set_array(self, value: jax.Array) -> None:
        """Write the logical view back to shared storage (write side of
        tileGetForWriting; functional update under the hood)."""
        value = jnp.asarray(value)
        slate_assert(value.shape[-2:] == (self.m, self.n),
                     f"shape mismatch: view {self.shape}, value {value.shape}")
        if self.op == Op.Trans:
            value = jnp.swapaxes(value, -1, -2)
        elif self.op == Op.ConjTrans:
            value = jnp.conj(jnp.swapaxes(value, -1, -2))
        self.storage.update(self.ioffset, self.joffset, value)

    def __call__(self, i: int, j: int) -> jax.Array:
        """Read tile (i, j) — the reference's ``A(i, j)`` tile accessor."""
        return self.tile(i, j)

    def _tile_storage_coords(self, i: int, j: int):
        """Map logical tile (i, j) to a storage-coordinate slice (op un-applied)."""
        mb_log, nb_log = self.tileMb(i), self.tileNb(j)
        io, jo = self._logical_tile_offset(0, i), self._logical_tile_offset(1, j)
        if self.op != Op.NoTrans:
            io, jo = jo, io
            mb_log, nb_log = nb_log, mb_log
        return (self.ioffset + io, self.joffset + jo, mb_log, nb_log)

    def tile(self, i: int, j: int) -> jax.Array:
        """Slices storage directly and applies op to the single tile — never
        materializes the whole op-applied view."""
        io, jo, mb_s, nb_s = self._tile_storage_coords(i, j)
        t = self.storage.array[..., io:io + mb_s, jo:jo + nb_s]
        if self.op == Op.Trans:
            t = jnp.swapaxes(t, -1, -2)
        elif self.op == Op.ConjTrans:
            t = jnp.conj(jnp.swapaxes(t, -1, -2))
        return t

    def set_tile(self, i: int, j: int, value: jax.Array) -> None:
        io, jo, mb_s, nb_s = self._tile_storage_coords(i, j)
        value = jnp.asarray(value)
        slate_assert(value.shape[-2:] == ((nb_s, mb_s) if self.op != Op.NoTrans
                                          else (mb_s, nb_s)),
                     f"tile shape mismatch at ({i},{j})")
        if self.op == Op.Trans:
            value = jnp.swapaxes(value, -1, -2)
        elif self.op == Op.ConjTrans:
            value = jnp.conj(jnp.swapaxes(value, -1, -2))
        self.storage.update(io, jo, value)

    # ----- views ---------------------------------------------------------------
    def _make_view(self, ioffset, joffset, m, n, op) -> "BaseMatrix":
        view = object.__new__(type(self))
        BaseMatrix.__init__(view, self.storage, ioffset, joffset, m, n, op)
        # carry typed attributes
        view.uplo = getattr(self, "uplo", Uplo.General)
        view.diag = getattr(self, "diag", Diag.NonUnit)
        for attr in ("_kl", "_ku", "kd"):
            if hasattr(self, attr):
                setattr(view, attr, getattr(self, attr))
        return view

    def sub(self, i1: int, i2: int, j1: int, j2: int) -> "BaseMatrix":
        """Sub-matrix over inclusive tile indices [i1..i2] x [j1..j2]
        (BaseMatrix.hh:104-106). Offsets must stay tile-aligned, which they do by
        construction since views are built from tile indices."""
        slate_assert(0 <= i1 and i2 < self.mt and 0 <= j1 and j2 < self.nt,
                     f"sub({i1},{i2},{j1},{j2}) out of range {self.mt}x{self.nt}")
        m = sum(self.tileMb(i) for i in range(i1, i2 + 1))
        n = sum(self.tileNb(j) for j in range(j1, j2 + 1))
        io, jo = self._logical_tile_offset(0, i1), self._logical_tile_offset(1, j1)
        if self.op != Op.NoTrans:
            io, jo, m, n = jo, io, n, m
        return self._make_view(self.ioffset + io, self.joffset + jo, m, n, self.op)

    def slice(self, row1: int, row2: int, col1: int, col2: int) -> "BaseMatrix":
        """Sub-matrix over inclusive element indices (BaseMatrix.hh:110-121)."""
        slate_assert(0 <= row1 <= row2 < self.m and 0 <= col1 <= col2 < self.n,
                     f"slice({row1},{row2},{col1},{col2}) out of range "
                     f"{self.m}x{self.n}")
        m, n = row2 - row1 + 1, col2 - col1 + 1
        io, jo = row1, col1
        if self.op != Op.NoTrans:
            io, jo, m, n = jo, io, n, m
        return self._make_view(self.ioffset + io, self.joffset + jo, m, n, self.op)

    def transpose(self) -> "BaseMatrix":
        """Logical transpose — a flag flip, no data motion (Tile.hh:40-52)."""
        op = {Op.NoTrans: Op.Trans, Op.Trans: Op.NoTrans,
              Op.ConjTrans: Op.ConjTrans}[self.op]
        if self.op == Op.ConjTrans:
            raise SlateError("transpose of conj-transposed view not supported; "
                             "matches reference restriction")
        v = self._make_view(self.ioffset, self.joffset, self._m, self._n, op)
        v.uplo = _flip_uplo(self.uplo)
        return v

    def conj_transpose(self) -> "BaseMatrix":
        op = {Op.NoTrans: Op.ConjTrans, Op.ConjTrans: Op.NoTrans,
              Op.Trans: Op.NoTrans}[self.op]
        if self.op == Op.Trans:
            raise SlateError("conj_transpose of transposed view not supported")
        v = self._make_view(self.ioffset, self.joffset, self._m, self._n, op)
        v.uplo = _flip_uplo(self.uplo)
        return v

    @property
    def T(self):
        return self.transpose()

    @property
    def H(self):
        return self.conj_transpose()

    def __repr__(self) -> str:
        extra = "" if self.uplo == Uplo.General else f", uplo={self.uplo}"
        return (f"{type(self).__name__}({self.m}x{self.n}, mb={self.mb}, nb={self.nb}, "
                f"mt={self.mt}, nt={self.nt}, op={self.op}{extra}, dtype={self.dtype})")


def tri_to_full(a: jax.Array, lower: bool, herm: bool) -> jax.Array:
    """Full symmetric/Hermitian array from the stored triangle (jit-safe,
    batch-dim aware).  The Hermitian case real-casts the diagonal — BLAS
    her* semantics ignore the imaginary part of a Hermitian diagonal."""
    strict = jnp.tril(a, -1) if lower else jnp.triu(a, 1)
    mirror = jnp.swapaxes(strict, -1, -2)
    if herm and jnp.iscomplexobj(a):
        mirror = jnp.conj(mirror)
        diag = jnp.real(jnp.diagonal(a, axis1=-2, axis2=-1)).astype(a.dtype)
    else:
        diag = jnp.diagonal(a, axis1=-2, axis2=-1)
    full = strict + mirror
    idx = jnp.arange(a.shape[-1])
    return full.at[..., idx, idx].set(diag)


def _flip_uplo(uplo: Uplo) -> Uplo:
    if uplo == Uplo.Lower:
        return Uplo.Upper
    if uplo == Uplo.Upper:
        return Uplo.Lower
    return uplo


# ---------------------------------------------------------------------------
# Typed matrices
# ---------------------------------------------------------------------------


class Matrix(BaseMatrix):
    """General m×n matrix (include/slate/Matrix.hh:31-164)."""

    def __init__(self, m: int, n: int, nb: int, p: int = 1, q: int = 1,
                 mb: Optional[int] = None, order: GridOrder = GridOrder.Col,
                 grid: Any = None, dtype=jnp.float32, _storage: MatrixStorage = None):
        if _storage is not None:
            BaseMatrix.__init__(self, _storage, 0, 0, _storage.m, _storage.n)
            return
        mb = mb or nb
        array = jnp.zeros((m, n), dtype=dtype)
        storage = MatrixStorage(array, mb, nb, p, q, order, grid)
        BaseMatrix.__init__(self, storage, 0, 0, m, n)

    @classmethod
    def from_array(cls, a, nb: int = 256, p: int = 1, q: int = 1,
                   mb: Optional[int] = None, order: GridOrder = GridOrder.Col,
                   grid: Any = None, tile_rank=None,
                   tile_mb=None, tile_nb=None) -> "Matrix":
        """Wrap existing data (reference fromLAPACK, Matrix.hh:293; the array is adopted
        as UserOwned origin data).  ``tile_mb``/``tile_nb`` (callable i -> size
        or size vector) install non-uniform per-index tile grids
        (MatrixStorage.hh:339-342, func.hh:39-42); ``tile_rank`` a custom
        tile -> rank lambda."""
        a = jnp.asarray(a)
        slate_assert(a.ndim == 2, "from_array expects a 2-D array")
        storage = MatrixStorage(a, mb or nb, nb, p, q, order, grid,
                                kind=TileKind.UserOwned, tile_rank=tile_rank,
                                tile_mb=tile_mb, tile_nb=tile_nb)
        return cls(0, 0, nb, _storage=storage)

    def empty_like(self, m: Optional[int] = None, n: Optional[int] = None,
                   nb: Optional[int] = None, dtype=None) -> "Matrix":
        """New zeroed matrix with this one's distribution (Matrix.hh emptyLike:117).
        A source non-uniform tile grid is carried over when the shape and
        blocking are unchanged."""
        s = self.storage
        mm = self.m if m is None else m
        nn = self.n if n is None else n
        if (nb is None and (s.mb_sizes is not None or s.nb_sizes is not None)
                and mm == s.m and nn == s.n and self.op == Op.NoTrans):
            arr = jnp.zeros((mm, nn), dtype=dtype or self.dtype)
            storage = MatrixStorage(arr, s.mb, s.nb, s.p, s.q, s.order, s.grid,
                                    tile_rank=(None if s.default_rank_map
                                               else s.tile_rank),
                                    tile_mb=s.mb_sizes, tile_nb=s.nb_sizes)
            return Matrix(0, 0, s.nb, _storage=storage)
        return Matrix(mm, nn,
                      nb or self.nb, s.p, s.q, order=s.order, grid=s.grid,
                      dtype=dtype or self.dtype)


class BaseTrapezoidMatrix(BaseMatrix):
    """Upper/lower trapezoidal storage view (include/slate/BaseTrapezoidMatrix.hh)."""

    def __init__(self, uplo: Uplo, m: int = 0, n: int = 0, nb: int = 256, p: int = 1, q: int = 1,
                 order: GridOrder = GridOrder.Col, grid: Any = None,
                 dtype=jnp.float32, _storage: MatrixStorage = None,
                 diag: Diag = Diag.NonUnit):
        if _storage is not None:
            BaseMatrix.__init__(self, _storage, 0, 0, _storage.m, _storage.n)
        else:
            array = jnp.zeros((m, n), dtype=dtype)
            storage = MatrixStorage(array, nb, nb, p, q, order, grid)
            BaseMatrix.__init__(self, storage, 0, 0, m, n)
        self.uplo = Uplo.from_string(uplo)
        self.diag = Diag.from_string(diag)
        slate_assert(self.uplo in (Uplo.Lower, Uplo.Upper), "uplo must be lower/upper")

    @classmethod
    def from_array(cls, uplo, a, nb: int = 256, p: int = 1, q: int = 1,
                   order: GridOrder = GridOrder.Col, grid: Any = None, **kw):
        a = jnp.asarray(a)
        storage = MatrixStorage(a, nb, nb, p, q, order, grid, kind=TileKind.UserOwned)
        return cls(uplo, _storage=storage, **kw)

    def masked_array(self) -> jax.Array:
        """The logical view with the unreferenced triangle zeroed (and unit diagonal
        substituted if diag == Unit) — the compute-side canonical form."""
        a = self.array
        if self.uplo == Uplo.Lower:
            a = jnp.tril(a)
        else:
            a = jnp.triu(a)
        if self.diag == Diag.Unit:
            eye = jnp.eye(a.shape[-2], a.shape[-1], dtype=jnp.bool_)
            a = jnp.where(eye, jnp.ones((), dtype=a.dtype), a)
        return a


class TrapezoidMatrix(BaseTrapezoidMatrix):
    """include/slate/TrapezoidMatrix.hh."""


class TriangularMatrix(BaseTrapezoidMatrix):
    """Square triangular matrix (include/slate/TriangularMatrix.hh, 684 LoC)."""

    def __init__(self, uplo, n: int = 0, nb: int = 256, *args, **kw):
        super().__init__(uplo, n, n, nb, *args, **kw)


class SymmetricMatrix(BaseTrapezoidMatrix):
    """Symmetric matrix, one triangle stored (include/slate/SymmetricMatrix.hh)."""

    def __init__(self, uplo, n: int = 0, nb: int = 256, *args, **kw):
        super().__init__(uplo, n, n, nb, *args, **kw)

    def full_array(self) -> jax.Array:
        """Symmetrize from the stored triangle: A = tril(A) + tril(A,-1)^T etc."""
        return tri_to_full(self.array, self.uplo == Uplo.Lower, herm=False)


class HermitianMatrix(BaseTrapezoidMatrix):
    """Hermitian matrix (include/slate/HermitianMatrix.hh)."""

    def __init__(self, uplo, n: int = 0, nb: int = 256, *args, **kw):
        super().__init__(uplo, n, n, nb, *args, **kw)

    def full_array(self) -> jax.Array:
        return tri_to_full(self.array, self.uplo == Uplo.Lower, herm=True)


class BaseBandMatrix(BaseMatrix):
    """Band matrix base (include/slate/BaseBandMatrix.hh, 368 LoC).

    TPU note: the reference stores only tiles within the band; here the backing array is
    dense with (kl, ku) metadata — XLA has no ragged storage — but band drivers only
    touch elements inside the band, and packed band storage is provided by
    ``slate_tpu.linalg.band`` for the band factorizations."""

    def __init__(self, m, n, kl, ku, nb, p=1, q=1, order=GridOrder.Col, grid=None,
                 dtype=jnp.float32, _storage=None):
        if _storage is not None:
            BaseMatrix.__init__(self, _storage, 0, 0, _storage.m, _storage.n)
        else:
            array = jnp.zeros((m, n), dtype=dtype)
            storage = MatrixStorage(array, nb, nb, p, q, order, grid)
            BaseMatrix.__init__(self, storage, 0, 0, m, n)
        self._kl = int(kl)   # storage-orientation bandwidths
        self._ku = int(ku)

    @property
    def kl(self) -> int:
        """Logical lower bandwidth (swaps with ku on transposed views)."""
        return self._ku if self.op != Op.NoTrans else self._kl

    @property
    def ku(self) -> int:
        return self._kl if self.op != Op.NoTrans else self._ku

    def band_mask(self) -> jax.Array:
        r = jnp.arange(self.m)[:, None]
        c = jnp.arange(self.n)[None, :]
        return (c - r <= self.ku) & (r - c <= self.kl)

    def masked_array(self) -> jax.Array:
        return jnp.where(self.band_mask(), self.array, 0)


class BandMatrix(BaseBandMatrix):
    """include/slate/BandMatrix.hh (265 LoC)."""


class TriangularBandMatrix(BaseBandMatrix):
    """include/slate/TriangularBandMatrix.hh (374 LoC, incl. ge2tbGather:327)."""

    def __init__(self, uplo, n, kd, nb, **kw):
        uplo = Uplo.from_string(uplo)
        kl, ku = (kd, 0) if uplo == Uplo.Lower else (0, kd)
        super().__init__(n, n, kl, ku, nb, **kw)
        self.uplo = uplo
        self.kd = kd


class HermitianBandMatrix(BaseBandMatrix):
    """include/slate/HermitianBandMatrix.hh (358 LoC, incl. he2hbGather:310)."""

    def __init__(self, uplo, n, kd, nb, **kw):
        uplo = Uplo.from_string(uplo)
        kl, ku = (kd, 0) if uplo == Uplo.Lower else (0, kd)
        super().__init__(n, n, kl, ku, nb, **kw)
        self.uplo = uplo
        self.kd = kd


# ---------------------------------------------------------------------------
# Helpers used across drivers
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# workspace-pool accounting (reference Memory.cc + reserveDeviceWorkspace):
# XLA owns the HBM, so the pool tracks tile-granular budget for the debug
# invariants (Debug::printNumFreeMemBlocks).  Opt-in — zero overhead unless
# enabled — because skins construct wrappers in hot paths.

_pool_tracking = False
_live_storages: "Any" = None


def enable_pool_tracking(on: bool = True) -> None:
    """Track every subsequently-built MatrixStorage in a per-storage native
    pool (one block per tile) plus a process-wide live registry — the data
    path behind utils.debug.check_no_leaks / live_workspace_report."""
    global _pool_tracking, _live_storages
    _pool_tracking = bool(on)
    if on and _live_storages is None:
        import weakref

        _live_storages = weakref.WeakSet()


def _register_storage(s: "MatrixStorage") -> None:
    from .. import native

    arr = s.array
    itemsize = getattr(getattr(arr, "dtype", None), "itemsize", 4)
    mt = -(-arr.shape[-2] // s.mb) if getattr(arr, "ndim", 0) >= 2 else 1
    nt = -(-arr.shape[-1] // s.nb) if getattr(arr, "ndim", 0) >= 2 else 1
    # capacity = the storage's resident tiles; blocks are *allocated* only for
    # transient workspace (drivers may pool.alloc()/free() around scratch),
    # so a healthy storage keeps in_use == 0 and check_no_leaks stays usable
    s.pool = native.MemoryPool(s.mb * s.nb * itemsize, max(mt * nt, 1))
    _live_storages.add(s)


def live_workspace_report():
    """(n_storages, total_resident_bytes) across live tracked storages — the
    Debug::printNumFreeMemBlocks analogue (capacity = resident tiles; any
    nonzero pool.in_use on top is outstanding workspace)."""
    if not _live_storages:
        return 0, 0
    total = 0
    count = 0
    for s in list(_live_storages):
        pool = getattr(s, "pool", None)
        if pool is not None:
            total += pool.capacity * pool.block_bytes
            count += 1
    return count, total


def distribution_grid(*operands):
    """The shared ProcessGrid (size > 1) attached to any wrapper operand, or None.

    Drivers consult this to route to the ``parallel`` implementations — the
    TPU form of the reference consuming ``tileRank``/``tileDevice`` installed
    at matrix construction (MatrixStorage.hh:494-511).  Mixing wrappers bound
    to different grids is an error, like mixing BLACS contexts.
    """
    g = None
    for op in operands:
        if isinstance(op, BaseMatrix):
            og = op.storage.grid
            if og is not None and getattr(og, "size", 1) > 1:
                if g is not None and og is not g:
                    raise SlateError(
                        "operands are distributed on different process grids")
                g = og
    return g


def as_array(A) -> jax.Array:
    """Accept Matrix-likes or raw arrays at API boundaries; return the logical array."""
    if isinstance(A, BaseMatrix):
        return A.array
    return jnp.asarray(A)


def write_back(A, value: jax.Array):
    """Write a driver result back into a Matrix wrapper (no-op passthrough for raw
    arrays — the functional-style API returns the value either way)."""
    if isinstance(A, BaseMatrix):
        A.set_array(value)
    return value
