"""Enums and per-call options.

TPU-native re-design of the reference's enum/option system
(``include/slate/enums.hh:38-498``, ``include/slate/types.hh:32-271``).

The reference passes a ``std::map<Option, OptionValue>`` to every driver; here we use a
frozen dataclass :class:`Options` with typed fields and an ``opts.replace(...)`` /
``Options(**dict)`` interface.  Every enum supports the same string round-trip the
reference provides via ``to_string``/``from_string`` helpers (``enums.hh:61-455``):
``Op.from_string("t") == Op.Trans`` and ``str(Op.Trans) == "trans"``.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, Optional


class _StrEnum(enum.Enum):
    """Enum with case-insensitive string round trip (mirrors enums.hh *2str/str2* pairs)."""

    def __str__(self) -> str:  # noqa: D105
        return self.value

    @classmethod
    def from_string(cls, s: "str | _StrEnum"):
        if isinstance(s, cls):
            return s
        key = str(s).strip().lower()
        for member in cls:
            if member.value == key or member.name.lower() == key:
                return member
        # single-letter shorthands used throughout the reference tester CLI
        short = getattr(cls, "_shorthand", None)
        if short is not None and key in short:
            return short[key]
        raise ValueError(f"no {cls.__name__} named {s!r}")


class Op(_StrEnum):
    """Transposition flag (enums.hh via blaspp; Tile.hh:40-52 makes transpose a flag flip)."""

    NoTrans = "notrans"
    Trans = "trans"
    ConjTrans = "conjtrans"


Op._shorthand = {"n": Op.NoTrans, "t": Op.Trans, "c": Op.ConjTrans}


class Uplo(_StrEnum):
    """Which triangle is referenced (blaspp enum used pervasively in BaseMatrix)."""

    Upper = "upper"
    Lower = "lower"
    General = "general"


Uplo._shorthand = {"u": Uplo.Upper, "l": Uplo.Lower, "g": Uplo.General}


class Diag(_StrEnum):
    NonUnit = "nonunit"
    Unit = "unit"


Diag._shorthand = {"n": Diag.NonUnit, "u": Diag.Unit}


class Side(_StrEnum):
    Left = "left"
    Right = "right"


Side._shorthand = {"l": Side.Left, "r": Side.Right}


class Layout(_StrEnum):
    """Physical tile layout (Tile.hh). On TPU XLA owns layout; kept for API parity only."""

    ColMajor = "colmajor"
    RowMajor = "rowmajor"


class Norm(_StrEnum):
    """Matrix norm kind (matches lapack norms used by internal_genorm.cc etc.)."""

    One = "one"
    Two = "two"
    Inf = "inf"
    Fro = "fro"
    Max = "max"


Norm._shorthand = {"1": Norm.One, "o": Norm.One, "2": Norm.Two, "i": Norm.Inf,
                   "f": Norm.Fro, "e": Norm.Fro, "m": Norm.Max}


class NormScope(_StrEnum):
    """Scope of a norm computation (enums.hh NormScope; Columns used by colNorms)."""

    Columns = "columns"
    Rows = "rows"
    Matrix = "matrix"


class Target(_StrEnum):
    """Execution target (enums.hh:38-44).

    The reference has {HostTask, HostNest, HostBatch, Devices}. On TPU there is a single
    compute fabric, so the meaningful split is how the computation is laid out:

    - ``Auto``: let each driver pick.
    - ``XLA``: whole-matrix XLA primitive (e.g. lax.linalg.cholesky) — the analogue of a
      single fused vendor call.
    - ``Tiled``: our blocked/tiled driver loop (the analogue of the task-DAG drivers);
      required for distributed execution and the path that honors nb/lookahead options.
    """

    Auto = "auto"
    XLA = "xla"
    Tiled = "tiled"
    # accepted aliases for reference CLI parity (`--target t/d` etc.)


Target._shorthand = {"t": Target.Tiled, "d": Target.Tiled, "h": Target.XLA,
                     "x": Target.XLA, "a": Target.Auto}


class TileKind(_StrEnum):
    """Tile provenance (Tile.hh:97-101). Informational on TPU (buffers are jax.Arrays)."""

    Workspace = "workspace"
    SlateOwned = "slateowned"
    UserOwned = "userowned"


class GridOrder(_StrEnum):
    """Process-grid ordering (enums.hh GridOrder; func.hh:178-217)."""

    Col = "col"
    Row = "row"
    Unknown = "unknown"


# ---------------------------------------------------------------------------
# Method enums — algorithmic variant selectors (enums.hh:108-455)
# ---------------------------------------------------------------------------


class MethodGemm(_StrEnum):
    """Stationary-matrix choice for gemm (enums.hh:108-114; src/gemm.cc:12-24)."""

    Auto = "auto"
    A = "a"          # stationary A (gemmA)
    C = "c"          # stationary C (gemmC)
    SUMMA = "summa"  # TPU addition: explicit shard_map SUMMA pipeline


class MethodHemm(_StrEnum):
    Auto = "auto"
    A = "a"
    C = "c"


class MethodTrsm(_StrEnum):
    Auto = "auto"
    A = "a"
    B = "b"


class MethodLU(_StrEnum):
    """LU pivoting variant (enums.hh:302-309)."""

    Auto = "auto"
    PartialPiv = "partialpiv"
    CALU = "calu"        # tournament pivoting (getrf_tntpiv)
    NoPiv = "nopiv"
    RBT = "rbt"          # random butterfly transform + nopiv
    BEAM = "beam"


class MethodEig(_StrEnum):
    """Tridiagonal eigensolver (enums.hh MethodEig:359-365)."""

    Auto = "auto"
    QR = "qr"       # steqr — real implicit-shift QR iteration
    DC = "dc"       # stedc — divide & conquer (the Auto performance path)
    Bisection = "bisection"   # sterf_bisect values + stein vectors — the
                              # reference marks this "not yet implemented"
                              # (enums.hh:363); implemented here
    MRRR = "mrrr"   # unimplemented in the reference too; routes to DC


class MethodSVD(_StrEnum):
    Auto = "auto"
    QR = "qr"         # bdsqr-style (auto: bisect values / dense vectors)
    DC = "dc"         # divide-and-conquer-class dense solve (gesdd/QDWH)
    Bisection = "bisection"   # GK bisection values + stein vectors —
                              # unimplemented in the reference, implemented
                              # here (linalg/svd.py bdsqr method='bisect')


class MethodCholQR(_StrEnum):
    """Inner product method for CholQR (enums.hh MethodCholQR)."""

    Auto = "auto"
    GemmA = "gemma"
    GemmC = "gemmc"
    HerkA = "herka"
    HerkC = "herkc"


class MethodGels(_StrEnum):
    """Least-squares factorization choice (enums.hh MethodGels)."""

    Auto = "auto"
    QR = "qr"
    CholQR = "cholqr"


# ---------------------------------------------------------------------------
# Options
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Options:
    """Per-call options (types.hh:32-81; option keys enums.hh:461-498).

    All drivers accept ``opts: Options | dict | None``. Unknown dict keys raise, like the
    reference's typed ``get_option<Option::X>`` (types.hh:240-271).
    """

    lookahead: int = 1
    block_size: int = 256           # Option::BlockSize (nb)
    inner_blocking: int = 256       # Option::InnerBlocking (ib); 256 keeps the
                                    # CALU tournament panels MXU/lane-aligned
                                    # (the reference's CPU default is far
                                    # smaller; tournament merge flops scale as
                                    # ib^2 so this is the TPU sweet spot).
                                    # NOTE: at the default block_size (256) the
                                    # two-level CALU split degenerates to a
                                    # single-level panel (ib == nb by design —
                                    # two levels only pay off at large nb); the
                                    # inner level engages when callers raise nb
                                    # (bench.py's getrf runs nb=2048, ib=256)
    max_panel_threads: int = 1      # kept for parity; no host thread teams on TPU
    tolerance: Optional[float] = None  # Option::Tolerance (mixed-precision IR)
    max_iterations: int = 30        # Option::MaxIterations (IR)
    use_fallback_solver: bool = True  # Option::UseFallbackSolver (gesv_mixed.cc:93-96)
    pivot_threshold: float = 1.0    # Option::PivotThreshold
    depth: int = 2                  # Option::Depth (RBT butterfly depth, gesv_rbt.cc)
    target: Target = Target.Auto
    trsm_via_inverse: bool = False  # tiled potrf panel: apply Lkk^{-1} as a
                                    # gemm instead of TriangularSolve (pure
                                    # MXU throughput for ~cond(Lkk)^2 local
                                    # error; bench sweep knob, linalg/chol.py)
    hold_local_workspace: bool = False  # parity only
    lu_panel: str = "tournament"    # CALU pivot-selection scheme: "tournament"
                                    # (binary merge tree of batched LUs,
                                    # getrf_tntpiv.cc) or "pp" (one partial-
                                    # pivot LU of the ib-wide subpanel selects
                                    # the pivot rows — ~6x fewer sequential
                                    # elimination steps per panel on TPU, where
                                    # each tournament level is a column-
                                    # sequential batched LU; A/B knob for the
                                    # getrf bench)
    print_verbose: int = 0          # Option::PrintVerbose (enums.hh:477-488)
    print_edgeitems: int = 16
    print_width: int = 10
    print_precision: int = 4
    # method selectors
    method_gemm: MethodGemm = MethodGemm.Auto
    method_hemm: MethodHemm = MethodHemm.Auto
    method_trsm: MethodTrsm = MethodTrsm.Auto
    method_lu: MethodLU = MethodLU.Auto
    method_eig: MethodEig = MethodEig.Auto
    method_svd: MethodSVD = MethodSVD.Auto
    method_cholqr: MethodCholQR = MethodCholQR.Auto
    method_gels: MethodGels = MethodGels.Auto
    # TPU-specific knobs (no reference analogue)
    precision: Optional[Any] = None   # compute dtype override (e.g. jnp.bfloat16)
    factor_precision: Optional[Any] = None  # low precision for *_mixed factor step
    exact_info: bool = False          # host-refine LAPACK info indices (syncs!)
    # resilience knobs (slate_tpu.robust; no reference analogue — the
    # reference's UseFallbackSolver is the only health option it exposes)
    solve_report: bool = False        # append a robust.SolveReport to solver
                                      # returns (opt-in structured health)
    max_retries: int = 0              # host-level same-rung retries before a
                                      # ladder escalates (robust.RetryPolicy)
    retry_backoff: float = 0.0        # seconds between host-level retries
    f64_emulation: bool = False       # gemm via exact Ozaki bf16 splitting —
                                      # true double-precision results on f64-
                                      # less TPUs at ~s(s+1)/2 bf16-gemm cost
                                      # (ops/f64emu.py; SURVEY §7 hard-part 6)

    def replace(self, **kw) -> "Options":
        kw = {k: _coerce_option(k, v) for k, v in kw.items()}
        return dataclasses.replace(self, **kw)

    def cache_key(self) -> "tuple":
        """Canonical, hashable identity of this option set — the Options leg
        of the serving layer's compiled-executable cache key
        (slate_tpu.serve.cache; BLASX keys its software cache the same way:
        routine + shape + the knobs that change the generated code).

        Two option sets that would trace to the same program map to the same
        key: enums collapse to their string values, dtype-likes (``precision``
        / ``factor_precision`` accept ``jnp.float32``, ``np.dtype``, or the
        string name) collapse to the canonical dtype name, and defaulted
        fields equal explicitly-passed identical values.  Fields are emitted
        in declaration order as ``(name, str)`` pairs, so the key is stable
        across processes (no ``hash()`` randomization, no object ids)."""
        parts = []
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            parts.append((f.name, _canon_option_value(v)))
        return tuple(parts)

    @classmethod
    def make(cls, opts: "Options | Dict[str, Any] | None") -> "Options":
        if opts is None:
            return cls()
        if isinstance(opts, Options):
            return opts
        if isinstance(opts, dict):
            return cls().replace(**opts)
        raise TypeError(f"opts must be Options, dict, or None, got {type(opts)}")


_ENUM_FIELDS = {
    "target": Target,
    "method_gemm": MethodGemm,
    "method_hemm": MethodHemm,
    "method_trsm": MethodTrsm,
    "method_lu": MethodLU,
    "method_eig": MethodEig,
    "method_svd": MethodSVD,
    "method_cholqr": MethodCholQR,
    "method_gels": MethodGels,
}


def _coerce_option(key: str, value: Any) -> Any:
    cls = _ENUM_FIELDS.get(key)
    if cls is not None and not isinstance(value, cls):
        return cls.from_string(value)
    return value


def _canon_option_value(v: Any) -> str:
    """One field value -> canonical string (see Options.cache_key)."""
    if v is None:
        return ""
    if isinstance(v, _StrEnum):
        return str(v)
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, (int, float, str)) and not isinstance(v, bool):
        # "float32" the string should canonicalize like the dtype it names
        if isinstance(v, str):
            try:
                import numpy as _np
                return _np.dtype(v).name
            except TypeError:
                return v
        return repr(v)
    # dtype-likes: jnp.float32 (a type), np.dtype, np.float32, ...
    try:
        import numpy as _np
        return _np.dtype(v).name
    except TypeError:
        return str(v)
