"""LAPACK-style compatibility API (≅ lapack_api/, 3.2 kLoC).

The reference exports ``slate_dgesv``-style drop-ins so LAPACK callers can link
against SLATE unchanged (lapack_api/lapack_gesv.cc etc.), tuned through
``SLATE_LAPACK_*`` environment variables.  This module is the Python equivalent:
every routine family the reference's lapack_api covers —

    gemm hemm symm herk syrk her2k syr2k trmm trsm          (BLAS-3)
    lange lansy lanhe lantr                                  (norms)
    gesv gesv_mixed getrf getrs getri gecon                  (LU)
    posv potrf potrs potri pocon                             (Cholesky)
    gels                                                     (least squares)
    heev heevd syev syevd hegv sygv gesvd                    (eig / SVD)
    trcon                                                    (condition)

— is exposed with all four type prefixes (s, d, c, z): ``dgesv(a, b)``,
``spotrf(uplo, a)``, ``zheev(jobz, uplo, a)``, …  numpy in / numpy out, LAPACK
calling shapes simplified to value-returning Python (info returned, not raised).

Env tuning (≅ lapack_slate.hh:34-96): ``SLATE_LAPACK_NB`` sets the block size,
``SLATE_LAPACK_VERBOSE=1`` prints each call.

d/z routines need float64 — enable ``jax.config.update("jax_enable_x64", True)``.
"""

from __future__ import annotations

import os
import sys
from typing import Tuple

import numpy as np

import jax.numpy as jnp

from . import blas as _blas
from . import linalg as _la
from .core.matrix import HermitianMatrix, Matrix, SymmetricMatrix, TriangularMatrix
from .core.types import Norm, Options, Uplo

_TYPES = {"s": np.float32, "d": np.float64, "c": np.complex64, "z": np.complex128}


def _opts() -> Options:
    kw = {}
    nb = os.environ.get("SLATE_LAPACK_NB")
    if nb:
        kw["block_size"] = int(nb)
    return Options.make(kw)


def _verbose(name, *shapes):
    if os.environ.get("SLATE_LAPACK_VERBOSE"):
        print(f"slate_lapack: {name} {shapes}", file=sys.stderr)


def _as(dtype, *arrays):
    return [np.asarray(a, dtype=dtype) for a in arrays]


def _nb(n: int) -> int:
    return min(_opts().block_size, max(8, n))


# ---------------------------------------------------------------------------
# per-routine implementations, parameterized on dtype

def _gemm(dt, transa, transb, alpha, a, b, beta, c):
    a, b, c = _as(dt, a, b, c)
    A = Matrix.from_array(a, nb=_nb(max(a.shape)))
    B = Matrix.from_array(b, nb=_nb(max(b.shape)))
    if transa.lower() in ("t", "c"):
        A = A.H if transa.lower() == "c" else A.T
    if transb.lower() in ("t", "c"):
        B = B.H if transb.lower() == "c" else B.T
    C = Matrix.from_array(c.copy(), nb=_nb(max(c.shape)))
    _blas.gemm(alpha, A, B, beta, C, _opts())
    return np.asarray(C.array)


def _hemm(dt, side, uplo, alpha, a, b, beta, c, *, sy=False):
    a, b, c = _as(dt, a, b, c)
    M = (SymmetricMatrix if sy else HermitianMatrix).from_array(
        Uplo.from_string(uplo), a, nb=_nb(a.shape[0]))
    B = Matrix.from_array(b, nb=_nb(max(b.shape)))
    C = Matrix.from_array(c.copy(), nb=_nb(max(c.shape)))
    (_blas.symm if sy else _blas.hemm)(side, alpha, M, B, beta, C, _opts())
    return np.asarray(C.array)


def _herk(dt, uplo, trans, alpha, a, beta, c, *, sy=False):
    a, c = _as(dt, a, c)
    A = Matrix.from_array(a, nb=_nb(max(a.shape)))
    if trans.lower() in ("t", "c"):
        A = A.H if trans.lower() == "c" else A.T
    C = (SymmetricMatrix if sy else HermitianMatrix).from_array(
        Uplo.from_string(uplo), c.copy(), nb=_nb(c.shape[0]))
    (_blas.syrk if sy else _blas.herk)(alpha, A, beta, C, _opts())
    return np.asarray(C.full_array())


def _her2k(dt, uplo, trans, alpha, a, b, beta, c, *, sy=False):
    a, b, c = _as(dt, a, b, c)
    A = Matrix.from_array(a, nb=_nb(max(a.shape)))
    B = Matrix.from_array(b, nb=_nb(max(b.shape)))
    if trans.lower() in ("t", "c"):
        A, B = (A.H, B.H) if trans.lower() == "c" else (A.T, B.T)
    C = (SymmetricMatrix if sy else HermitianMatrix).from_array(
        Uplo.from_string(uplo), c.copy(), nb=_nb(c.shape[0]))
    (_blas.syr2k if sy else _blas.her2k)(alpha, A, B, beta, C, _opts())
    return np.asarray(C.full_array())


def _trmm(dt, side, uplo, transa, diag, alpha, a, b, *, solve=False):
    a, b = _as(dt, a, b)
    T = TriangularMatrix.from_array(Uplo.from_string(uplo), a,
                                    nb=_nb(a.shape[0]), diag=diag)
    if transa.lower() in ("t", "c"):
        T = T.H if transa.lower() == "c" else T.T
    B = Matrix.from_array(b.copy(), nb=_nb(max(b.shape)))
    (_blas.trsm if solve else _blas.trmm)(side, alpha, T, B, _opts(),
                                          diag=diag)
    return np.asarray(B.array)


def _lange(dt, norm, a):
    (a,) = _as(dt, a)
    return float(_blas.norm(norm, Matrix.from_array(a, nb=_nb(max(a.shape))),
                            _opts()))


def _lanhe(dt, norm, uplo, a, *, sy=False):
    (a,) = _as(dt, a)
    M = (SymmetricMatrix if sy else HermitianMatrix).from_array(
        Uplo.from_string(uplo), a, nb=_nb(a.shape[0]))
    return float(_blas.norm(norm, M, _opts()))


def _lantr(dt, norm, uplo, diag, a):
    (a,) = _as(dt, a)
    T = TriangularMatrix.from_array(Uplo.from_string(uplo), a,
                                    nb=_nb(a.shape[0]), diag=diag)
    return float(_blas.norm(norm, T, _opts(), diag=diag))


def _gesv(dt, a, b):
    a, b = _as(dt, a, b)
    X, perm, info = _la.gesv(a, b, _opts())
    return np.asarray(X), _la.perm_to_pivots(perm), int(info)


def _gesv_mixed(dt, a, b):
    a, b = _as(dt, a, b)
    X, perm, info, iters = _la.gesv_mixed(a, b, _opts())
    return np.asarray(X), _la.perm_to_pivots(perm), int(info), int(iters)


def _getrf(dt, a):
    """Returns (LU, ipiv, info) with 1-based LAPACK ipiv — the same pivot format
    _gesv returns and _getrs/_getri/_gecon consume."""
    (a,) = _as(dt, a)
    lu_, perm, info = _la.getrf(a, _opts())
    return np.asarray(lu_), _la.perm_to_pivots(perm), int(info)


def _perm(ipiv):
    return jnp.asarray(_la.pivots_to_perm(ipiv))


def _getrs(dt, trans, lu_, ipiv, b):
    lu_, b = _as(dt, lu_, b)
    X = _la.getrs(lu_, _perm(ipiv), b, _opts(), trans=trans.lower())
    return np.asarray(X)


def _getri(dt, lu_, ipiv):
    (lu_,) = _as(dt, lu_)
    return np.asarray(_la.getri(lu_, _perm(ipiv), _opts()))


def _gecon(dt, norm, lu_, ipiv, anorm):
    (lu_,) = _as(dt, lu_)
    kind = Norm.Inf if str(norm).lower()[0] == "i" else Norm.One
    return float(_la.gecondest(jnp.asarray(lu_), _perm(ipiv), anorm,
                               _opts(), norm_kind=kind))


def _laset(dt, uplo, m, n, alpha, beta, a=None):
    """dlaset (scalapack_api/scalapack_laset.cc): set the selected region of
    A to alpha off-diagonal / beta on the diagonal.  ``uplo`` 'g' sets the
    whole matrix, 'l'/'u' the triangle (the untouched triangle keeps A's
    entries, which is why A is an optional input)."""
    from .ops import elementwise

    u = str(uplo).lower()[0]
    m, n = int(m), int(n)
    if a is None:
        a = np.zeros((m, n), dtype=dt)
    (a,) = _as(dt, a)
    aj = jnp.asarray(a)
    # LAPACK sets only the leading m x n region of A; the rest is untouched
    sub = aj[:m, :n]
    if u in ("l", "u"):
        out = elementwise.tzset(Uplo.Lower if u == "l" else Uplo.Upper,
                                alpha, beta, sub)
    else:
        out = elementwise.geset(alpha, beta, sub)
    return np.asarray(aj.at[:m, :n].set(out))


def _posv(dt, uplo, a, b):
    a, b = _as(dt, a, b)
    M = HermitianMatrix.from_array(Uplo.from_string(uplo), a.copy(),
                                   nb=_nb(a.shape[0]))
    B = Matrix.from_array(b.copy(), nb=_nb(max(b.shape)))
    X, info = _la.posv(M, B, _opts())
    return np.asarray(B.array), int(info)


def _potrf(dt, uplo, a):
    (a,) = _as(dt, a)
    M = HermitianMatrix.from_array(Uplo.from_string(uplo), a.copy(),
                                   nb=_nb(a.shape[0]))
    L, info = _la.potrf(M, _opts())
    return np.asarray(L.array if hasattr(L, "array") else L), int(info)


def _potrs(dt, uplo, lf, b):
    lf, b = _as(dt, lf, b)
    M = HermitianMatrix.from_array(Uplo.from_string(uplo), lf,
                                   nb=_nb(lf.shape[0]))
    B = Matrix.from_array(b.copy(), nb=_nb(max(b.shape)))
    _la.potrs(M, B, _opts(), uplo=Uplo.from_string(uplo))
    return np.asarray(B.array)


def _potri(dt, uplo, lf):
    (lf,) = _as(dt, lf)
    M = HermitianMatrix.from_array(Uplo.from_string(uplo), lf.copy(),
                                   nb=_nb(lf.shape[0]))
    out = _la.potri(M, _opts(), uplo=Uplo.from_string(uplo))
    return np.asarray(out.array if hasattr(out, "array") else out)


def _pocon(dt, uplo, lf, anorm):
    (lf,) = _as(dt, lf)
    return float(_la.pocondest(jnp.asarray(lf), anorm, _opts(), uplo=uplo))


def _trcon(dt, norm, uplo, diag, a):
    (a,) = _as(dt, a)
    return float(_la.trcondest(jnp.asarray(a), _opts(), uplo=uplo, diag=diag,
                               norm_kind=norm))


def _gels(dt, trans, a, b):
    a, b = _as(dt, a, b)
    A = a.conj().T if trans.lower() in ("t", "c") else a
    return np.asarray(_la.gels(A.copy(), b.copy(), _opts()))


def _heev(dt, jobz, uplo, a, *, sy=False):
    (a,) = _as(dt, a)
    M = (SymmetricMatrix if sy else HermitianMatrix).from_array(
        Uplo.from_string(uplo), a, nb=_nb(a.shape[0]))
    lam, z = _la.heev(M, _opts(), want_vectors=jobz.lower() == "v")
    return ((np.asarray(lam), np.asarray(z)) if jobz.lower() == "v"
            else (np.asarray(lam), None))


def _heevx(dt, jobz, uplo, a, il, iu, *, sy=False):
    """LAPACK heevx/syevx range='I' (1-based INCLUSIVE il..iu, per LAPACK):
    subset eigensolve via index-targeted bisection + inverse iteration —
    a routine family the reference's lapack_api does not cover at all."""
    (a,) = _as(dt, a)
    from .linalg.eig import heev_range

    uplo_e = Uplo.from_string(uplo)
    M = (SymmetricMatrix if sy else HermitianMatrix).from_array(
        uplo_e, a, nb=_nb(a.shape[0]))
    lam, z = heev_range(M, _opts(), want_vectors=jobz.lower() == "v",
                        il=int(il) - 1, iu=int(iu))
    return ((np.asarray(lam), np.asarray(z)) if jobz.lower() == "v"
            else (np.asarray(lam), None))


def _hegvx(dt, itype, jobz, uplo, a, b, il, iu, *, sy=False):
    """LAPACK hegvx/sygvx range='I' (1-based inclusive): generalized subset
    eigensolve — another family the reference's lapack_api lacks."""
    a, b = _as(dt, a, b)
    from .linalg.eig import hegv_range

    lam, z = hegv_range(int(itype), a, b, _opts(), uplo=uplo,
                        il=int(il) - 1, iu=int(iu),
                        want_vectors=jobz.lower() == "v")
    return ((np.asarray(lam), np.asarray(z)) if jobz.lower() == "v"
            else (np.asarray(lam), None))


def _gesvdx(dt, jobu, jobvt, a, il, iu):
    """LAPACK gesvdx range='I' (1-based inclusive il..iu of the DESCENDING
    singular values): subset/top-k SVD — another family the reference's
    lapack_api does not cover."""
    (a,) = _as(dt, a)
    from .linalg.svd import svd_range

    want = jobu.lower() == "v" or jobvt.lower() == "v"
    S, U, VT = svd_range(a, _opts(), il=int(il) - 1, iu=int(iu),
                         want_vectors=want)
    return (np.asarray(S),
            np.asarray(U) if want and jobu.lower() == "v" else None,
            np.asarray(VT) if want and jobvt.lower() == "v" else None)


def _hegv(dt, itype, jobz, uplo, a, b, *, sy=False):
    a, b = _as(dt, a, b)
    lam, z = _la.hegv(int(itype), a, b, _opts(), uplo=uplo,
                      want_vectors=jobz.lower() == "v")
    return ((np.asarray(lam), np.asarray(z)) if jobz.lower() == "v"
            else (np.asarray(lam), None))


def _complete_basis(u: np.ndarray, full: int) -> np.ndarray:
    """Extend orthonormal columns u (m x k) to a full m x m orthogonal basis:
    QR of [u | I] keeps the leading k columns equal to u (up to sign, fixed)."""
    m, k = u.shape
    q, r = np.linalg.qr(np.concatenate([u, np.eye(m, dtype=u.dtype)], axis=1))
    q = q[:, :full]
    d = np.sign(np.real(np.diagonal(r)[:k]))
    d[d == 0] = 1
    q[:, :k] = q[:, :k] * d[None, :]     # undo QR's sign choice so q[:, :k] == u
    return q


def _svd_finish(s, u, vt, jobu, jobvt, m, n):
    """Apply the LAPACK gesvd job semantics to raw SVD outputs — None-filter
    by job flag and complete to a full basis for job 'a'.  Shared by the
    single-device skin and the distributed scalapack route."""
    u = np.asarray(u) if u is not None and jobu.lower() != "n" else None
    vt = np.asarray(vt) if vt is not None and jobvt.lower() != "n" else None
    if u is not None and jobu.lower() == "a" and u.shape[1] < m:
        u = _complete_basis(u, m)        # LAPACK job 'a': full m x m U
    if vt is not None and jobvt.lower() == "a" and vt.shape[0] < n:
        vt = _complete_basis(vt.conj().T, n).conj().T
    return np.asarray(s), u, vt


def _pbsv(dt, uplo, kd, a, b):
    """SPD band solve (lapack_api/lapack_pbsv.cc).  ``a`` is the DENSE banded
    matrix (the skin's simplified shapes); ``kd`` its half-bandwidth.  Returns
    (X, info)."""
    a, b = _as(dt, a, b)
    X, info = _la.pbsv(a.copy(), b.copy(), _opts(), uplo=uplo, kd=int(kd))
    return np.asarray(X), int(info)


def _pbtrf(dt, uplo, kd, a):
    """Band Cholesky factor (lapack_pbtrf.cc): dense banded in, dense lower
    band factor out.  Returns (L, info)."""
    (a,) = _as(dt, a)
    Lb, info = _la.pbtrf(a.copy(), _opts(), uplo=uplo, kd=int(kd))
    return np.asarray(Lb), int(info)


def _pbtrs(dt, uplo, kd, lf, b):
    """Solve from the band Cholesky factor (lapack_pbtrs.cc); ``lf`` is the
    dense LOWER band factor _pbtrf returns (uplo records the original
    storage and is accepted for call-shape parity)."""
    lf, b = _as(dt, lf, b)
    X = _la.pbtrs(lf, b.copy(), _opts(), kd=int(kd))
    return np.asarray(X)


def _gbsv(dt, kl, ku, a, b):
    """General band solve (lapack_gbsv.cc): dense banded in.  Returns
    (X, info)."""
    a, b = _as(dt, a, b)
    X, info = _la.gbsv(a.copy(), b.copy(), _opts(), kl=int(kl), ku=int(ku))
    return np.asarray(X), int(info)


def _hesv(dt, uplo, a, b, *, sy=False):
    """Symmetric/Hermitian-indefinite solve via CA-Aasen (lapack_hesv.cc);
    returns (X, info)."""
    a, b = _as(dt, a, b)
    fn = _la.sysv if sy else _la.hesv
    X, info = fn(a.copy(), b.copy(), _opts(), uplo=uplo)
    return np.asarray(X), int(info)


def _gesvd(dt, jobu, jobvt, a):
    (a,) = _as(dt, a)
    m, n = a.shape
    want_u = jobu.lower() != "n"
    want_vt = jobvt.lower() != "n"
    out = _la.svd(a, _opts(), want_u=want_u, want_vt=want_vt)
    return _svd_finish(out[0], out[1] if want_u else None,
                       out[2] if want_vt and len(out) > 2 else None,
                       jobu, jobvt, m, n)


# ---------------------------------------------------------------------------
# generate the typed entry points: sgemm/dgemm/cgemm/zgemm, ...

_FAMILIES = {
    "gemm": (_gemm, {}),
    "hemm": (_hemm, {}), "symm": (_hemm, {"sy": True}),
    "herk": (_herk, {}), "syrk": (_herk, {"sy": True}),
    "her2k": (_her2k, {}), "syr2k": (_her2k, {"sy": True}),
    "trmm": (_trmm, {}), "trsm": (_trmm, {"solve": True}),
    "lange": (_lange, {}), "lanhe": (_lanhe, {}), "lansy": (_lanhe, {"sy": True}),
    "lantr": (_lantr, {}), "laset": (_laset, {}),
    "gesv": (_gesv, {}), "gesv_mixed": (_gesv_mixed, {}),
    "getrf": (_getrf, {}), "getrs": (_getrs, {}), "getri": (_getri, {}),
    "gecon": (_gecon, {}),
    "posv": (_posv, {}), "potrf": (_potrf, {}), "potrs": (_potrs, {}),
    "potri": (_potri, {}), "pocon": (_pocon, {}), "trcon": (_trcon, {}),
    "gels": (_gels, {}),
    "heev": (_heev, {}), "heevd": (_heev, {}),
    "syev": (_heev, {"sy": True}), "syevd": (_heev, {"sy": True}),
    "heevx": (_heevx, {}), "syevx": (_heevx, {"sy": True}),
    "gesvdx": (_gesvdx, {}),
    "hegv": (_hegv, {}), "sygv": (_hegv, {"sy": True}),
    "hegvx": (_hegvx, {}), "sygvx": (_hegvx, {"sy": True}),
    "gesvd": (_gesvd, {}),
    "pbsv": (_pbsv, {}), "pbtrf": (_pbtrf, {}), "pbtrs": (_pbtrs, {}),
    "gbsv": (_gbsv, {}),
    "hesv": (_hesv, {}), "sysv": (_hesv, {"sy": True}),
}

# complex-only / real-only aliasing like LAPACK: cheev/zheev but ssyev/dsyev
_SKIP = {
    ("s", "hemm"), ("d", "hemm"), ("s", "herk"), ("d", "herk"),
    ("s", "her2k"), ("d", "her2k"), ("s", "lanhe"), ("d", "lanhe"),
    ("s", "heev"), ("d", "heev"), ("s", "heevd"), ("d", "heevd"),
    ("c", "syev"), ("z", "syev"), ("c", "syevd"), ("z", "syevd"),
    ("s", "heevx"), ("d", "heevx"), ("c", "syevx"), ("z", "syevx"),
    ("s", "hegv"), ("d", "hegv"), ("c", "sygv"), ("z", "sygv"),
    ("s", "hegvx"), ("d", "hegvx"), ("c", "sygvx"), ("z", "sygvx"),
    ("s", "hesv"), ("d", "hesv"),   # LAPACK: ssysv/dsysv but chesv/zhesv
    # LAPACK's csysv/zsysv solve complex *symmetric* (A == A.T) systems;
    # the backend's indefinite solver is Hermitian CA-Aasen — exposing the
    # names would silently factor conj-mirrored matrices.  Not offered.
    ("c", "sysv"), ("z", "sysv"),
}

__all__ = []


def _make(letter, name, impl, fixed):
    dt = _TYPES[letter]

    def fn(*args, **kw):
        _verbose(letter + name, *(getattr(a, "shape", a) for a in args))
        return impl(dt, *args, **dict(fixed, **kw))

    fn.__name__ = letter + name
    fn.__qualname__ = letter + name
    fn.__doc__ = (f"slate_{letter}{name} — LAPACK-compatible wrapper over "
                  f"slate_tpu (lapack_api/lapack_{name.split('_')[0]}.cc).")
    return fn


for _letter in _TYPES:
    for _name, (_impl, _fixed) in _FAMILIES.items():
        if (_letter, _name) in _SKIP:
            continue
        _f = _make(_letter, _name, _impl, _fixed)
        globals()[_letter + _name] = _f
        __all__.append(_letter + _name)

# dsgesv — the classic mixed-precision name (f64 system, f32 factor)
dsgesv = globals()["dgesv_mixed"]
zcgesv = globals()["zgesv_mixed"]
__all__ += ["dsgesv", "zcgesv"]
