"""Driver algorithms (reference L4, src/*.cc)."""

from .chol import posv, posv_mixed, potrf, potri, potrs, trtri, trtrm
