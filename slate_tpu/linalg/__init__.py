"""Driver algorithms (reference L4, src/*.cc)."""

from .chol import posv, posv_mixed, potrf, potri, potrs, trtri, trtrm
from .lu import (gerbt, gesv, gesv_mixed, gesv_mixed_gmres, gesv_nopiv, gesv_rbt,
                 getrf, getrf_nopiv, getrf_tntpiv, getri, getrs, perm_to_pivots,
                 rbt_generate)
from .qr import (TriangularFactors, cholqr, gelqf, gels, geqrf, tsqr, unmlq, unmqr)
from .eig import (hb2st, he2hb, heev, hegst, hegv, stedc, steqr, sterf)
from .svd import bdsqr, ge2tb, svd, svd_vals, tb2bd
from .condest import gecondest, norm1est, pocondest, trcondest
from .band import (BandLU, gbmm, gbsv, gbtrf, gbtrs, hbmm, pbsv, pbtrf, pbtrs,
                   tbsm)
from .indefinite import (HermitianFactors, hesv, hetrf, hetrs, sysv, sytrf,
                         sytrs)
