"""Driver algorithms (reference L4, src/*.cc)."""

from .chol import (posv, posv_core, posv_mixed, posv_mixed_gmres, potrf, potri,
                   potrs, trtri, trtrm)
from .lu import (gerbt, gesv, gesv_core, gesv_mixed, gesv_mixed_gmres,
                 gesv_nopiv, gesv_rbt,
                 getrf, getrf_nopiv, getrf_tntpiv, getri, getri_oop, getrs,
                 getrs_nopiv, perm_to_pivots, pivots_to_perm, rbt_generate)
from .qr import (TriangularFactors, cholqr, gelqf, gels, gels_cholqr, gels_core,
                 gels_qr, geqrf, tsqr, unmlq, unmqr)
# the submodule import must come first: importing .stedc binds the module
# object onto the package as attribute "stedc", and the .eig import below
# re-binds that name to the driver *function* (the public contract)
from .stedc import (stedc_deflate, stedc_merge, stedc_secular, stedc_solve,
                    stedc_sort, stedc_z_vector)
from .eig import (eig_count, hb2st, he2hb, he2hb_q, heev, heev_range,
                  hegst, hegv, hegv_range, stedc, steqr,
                  steqr2, sterf, syev, sygst, sygv, unmtr_hb2st, unmtr_he2hb)
from .svd import (svd_range, bdsqr, ge2tb, ge2tb_band, svd, svd_vals, tb2bd,
                  unmbr_ge2tb, unmbr_ge2tb_factors, unmbr_tb2bd)
from .condest import gecondest, norm1est, pocondest, trcondest
from .sturm import stein, sterf_bisect
from .band import (BandLU, gbmm, gbsv, gbtrf, gbtrs, hbmm, pbsv, pbtrf, pbtrs,
                   tbsm, tbsm_pivots, tbsmPivots)
from .indefinite import (HermitianFactors, hesv, hetrf, hetrs, sysv, sytrf,
                         sytrs)
