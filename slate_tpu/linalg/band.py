"""Band matrix drivers: gbmm/hbmm/tbsm multiplies and solves, band LU
(gbtrf/gbtrs/gbsv) and band Cholesky (pbtrf/pbtrs/pbsv).

Reference analogue (SURVEY.md §2.4): ``src/{gbmm,hbmm,tbsm,tbsmPivots}.cc`` (band
BLAS-3) and the band solver drivers ``src/{gbtrf,gbtrs,gbsv,pbtrf,pbtrs,pbsv}.cc``
built over ``BandMatrix``/``TriangularBandMatrix``/``HermitianBandMatrix``
(include/slate/Base\\*Band\\*.hh) — the reference keeps working sets O(n·band) by only
storing/visiting tiles inside the band (SURVEY.md §5.7).

TPU re-design:

* Storage is a dense jax.Array + (kl, ku) metadata (XLA has no ragged tile maps), but
  every driver's *compute* is windowed: a ``lax.fori_loop`` over block columns whose
  body touches only a static-shape window of ``O(band)`` rows/columns around the
  diagonal via ``lax.dynamic_slice`` — so the flop count is the band count
  O(n·band²), not O(n³), and every window op is a fixed-shape MXU matmul /
  triangular-solve that XLA compiles once.
* ``gbmm``/``hbmm`` iterate over *block diagonals*: for each tile offset d in
  [-ceil(kl/nb), ceil(ku/nb)] one batched matmul multiplies all tiles on that
  diagonal — a static loop of uniform MXU batches (the analogue of the reference's
  device_regions_build batched gemm over in-band tiles).
* ``gbtrf`` follows the LAPACK-style band LU contract: partial pivoting within the
  band (pivot row within kl of the diagonal), U's bandwidth grows to kl+ku, and L is
  kept as per-panel permuted elementary transforms — the per-panel permutation is
  applied *inside* the forward solve, exactly like the reference's tbsmPivots path
  (src/tbsm.cc pivot handling).
* Padding: matrices are padded up to whole tiles with an identity diagonal so edge
  windows keep static shapes (SURVEY.md §7 hard-part 5: pad-and-mask edges).
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.exceptions import SlateError, slate_assert
from ..core.matrix import BaseBandMatrix, as_array, write_back
from ..core.types import Diag, Options, Side, Uplo
from ..utils.trace import trace_block
from .lu import _lu_info

__all__ = [
    "gbmm", "hbmm", "tbsm", "gbtrf", "gbtrs", "gbsv", "pbtrf", "pbtrs", "pbsv",
    "BandLU",
]


def _band_meta(A, kl, ku):
    """Resolve (array, kl, ku) from a Band wrapper or explicit keywords."""
    if isinstance(A, BaseBandMatrix):
        return A.array, A.kl, A.ku
    a = as_array(A)
    slate_assert(kl is not None and ku is not None,
                 "band routines need a Band matrix or explicit kl=/ku=")
    return a, int(kl), int(ku)


def _band_mask(m, n, kl, ku, dtype=jnp.bool_):
    r = jnp.arange(m)[:, None]
    c = jnp.arange(n)[None, :]
    return ((c - r <= ku) & (r - c <= kl)).astype(dtype)


def _pad_to(a, rows, cols, diag_val=0.0):
    """Pad a to (rows, cols), optionally writing diag_val on the padded diagonal."""
    m, n = a.shape[-2:]
    out = jnp.pad(a, ((0, rows - m), (0, cols - n)))
    if diag_val != 0.0 and rows > m:
        idx = jnp.arange(m, min(rows, cols))
        out = out.at[idx, idx].set(jnp.asarray(diag_val, a.dtype))
    return out


def _ceil_div(a, b):
    return -(-a // b)


# ---------------------------------------------------------------------------
# band matrix multiply: gbmm / hbmm
# ---------------------------------------------------------------------------


@lru_cache(maxsize=64)
def _gbmm_fn(m: int, k: int, kl: int, ku: int, nb: int, dtype_str: str):
    """C = alpha A_band B + beta C by block diagonals (one batched MXU matmul per
    in-band tile diagonal — ≅ the Devices-target batched gemm over in-band tiles,
    src/gbmm.cc + internal_batch.hh)."""
    mt, kt = _ceil_div(m, nb), _ceil_div(k, nb)
    klt, kut = _ceil_div(kl, nb), _ceil_div(ku, nb)
    mp, kp = mt * nb, kt * nb

    def fn(alpha, a, b, beta, c):
        nrhs = b.shape[-1]
        a = _pad_to(a * _band_mask(m, k, kl, ku, a.dtype), mp, kp)
        bpad = jnp.pad(b, ((0, kp - k), (0, 0)))
        # block views: (mt, nb, kt, nb) -> per-diagonal batched matmul
        abl = a.reshape(mt, nb, kt, nb).transpose(0, 2, 1, 3)
        bbl = bpad.reshape(kt, nb, nrhs)
        acc = jnp.zeros((mt, nb, nrhs), jnp.promote_types(a.dtype, b.dtype))
        for d in range(-klt, kut + 1):
            # tiles (i, i+d) for valid i — gather the diagonal as a batch
            i = jnp.arange(mt)
            j = i + d
            valid = (j >= 0) & (j < kt)
            jc = jnp.clip(j, 0, kt - 1)
            a_diag = abl[i, jc]                       # (mt, nb, nb)
            b_diag = bbl[jc]                          # (mt, nb, nrhs)
            contrib = jnp.einsum("bij,bjr->bir", a_diag, b_diag,
                                 precision=lax.Precision.HIGHEST)
            acc = acc + jnp.where(valid[:, None, None], contrib, 0)
        out = alpha * acc.reshape(mp, nrhs)[:m] + beta * c
        return out

    return jax.jit(fn)


def gbmm(alpha, A, B, beta, C, opts=None, kl=None, ku=None):
    """C = alpha A B + beta C with A a general band matrix (src/gbmm.cc).

    op(A) is expressed through transposed BandMatrix views (``A.T.array`` with
    swapped kl/ku); raw arrays are taken as-is."""
    opts = Options.make(opts)
    a, kl, ku = _band_meta(A, kl, ku)
    b, c = as_array(B), as_array(C)
    m, k = a.shape[-2:]
    squeeze = b.ndim == 1
    if squeeze:
        b = b[:, None]
        c = c[:, None]
    nb = min(opts.block_size, m, k)
    with trace_block("gbmm", m=m, k=k, kl=kl, ku=ku):
        out = _gbmm_fn(m, k, kl, ku, nb, str(a.dtype))(
            jnp.asarray(alpha, a.dtype), a, b, jnp.asarray(beta, c.dtype), c)
    if squeeze:
        out = out[:, 0]
    return write_back(C, out)


def hbmm(side, alpha, A, B, beta, C, opts=None, uplo=None, kd=None):
    """C = alpha A B + beta C with A Hermitian band, one triangle stored
    (src/hbmm.cc). side='left' only, matching the reference's implemented case."""
    opts = Options.make(opts)
    if Side.from_string(side) != Side.Left:
        raise SlateError("hbmm: only side='left' (reference implements left)")
    if isinstance(A, BaseBandMatrix):
        a, u = A.array, A.uplo
        kd_v = getattr(A, "kd", max(A.kl, A.ku))
    else:
        a = as_array(A)
        u = Uplo.from_string(uplo)
        slate_assert(kd is not None, "hbmm on a raw array needs kd=")
        kd_v = int(kd)
    n = a.shape[-1]
    # reconstruct the full Hermitian band from the stored triangle
    tri = jnp.tril(a, 0) if u == Uplo.Lower else jnp.triu(a, 0)
    tri = tri * _band_mask(n, n, kd_v if u == Uplo.Lower else 0,
                           0 if u == Uplo.Lower else kd_v, a.dtype)
    strict = jnp.tril(tri, -1) if u == Uplo.Lower else jnp.triu(tri, 1)
    if jnp.iscomplexobj(tri):
        # Hermitian storage convention: imaginary part of the diagonal is not
        # referenced (matches HermitianMatrix.full_array)
        idx = jnp.arange(n)
        tri = tri.at[idx, idx].set(jnp.real(tri[idx, idx]).astype(tri.dtype))
    full = tri + jnp.conj(jnp.swapaxes(strict, -1, -2))
    return gbmm(alpha, full, B, beta, C, opts, kl=kd_v, ku=kd_v)


# ---------------------------------------------------------------------------
# triangular band solve: tbsm
# ---------------------------------------------------------------------------


@lru_cache(maxsize=64)
def _tbsm_fn(n: int, kd: int, nb: int, nrhs: int, lower: bool, unit: bool,
             trans: bool, dtype_str: str):
    """Blocked band substitution: fori_loop over block rows, each step one
    triangular solve + one windowed matmul update of the next kdt block rows
    (src/tbsm.cc work loop, window = in-band tiles only)."""
    nt = _ceil_div(n, nb)
    kdt = _ceil_div(kd, nb)
    w = kdt * nb  # update window beyond the diagonal block
    np_ = nt * nb

    def fn(a, b):
        a = _pad_to(a, np_ + w, np_ + w, diag_val=1.0)
        mask_kl = kd if lower else 0
        mask_ku = 0 if lower else kd
        a = a * _band_mask(np_ + w, np_ + w, mask_kl, mask_ku, a.dtype)
        if unit:
            idx = jnp.arange(np_ + w)
            a = a.at[idx, idx].set(jnp.asarray(1.0, a.dtype))
        b = jnp.pad(b, ((0, np_ + w - n), (0, 0)))

        fwd = lower != trans  # forward substitution order

        def body(t, b):
            kk = t if fwd else nt - 1 - t
            k0 = kk * nb
            diag = lax.dynamic_slice(a, (k0, k0), (nb, nb))
            if trans:
                diag = jnp.conj(jnp.swapaxes(diag, -1, -2)) if dtype_str.startswith(
                    "complex") else jnp.swapaxes(diag, -1, -2)
            rhs_k = lax.dynamic_slice(b, (k0, 0), (nb, nrhs))
            x_k = lax.linalg.triangular_solve(
                diag, rhs_k, left_side=True, lower=fwd, unit_diagonal=unit)
            b = lax.dynamic_update_slice(b, x_k, (k0, 0))
            # windowed trailing update: the kdt block rows after (before) k
            if fwd:
                if trans:
                    off = lax.dynamic_slice(a, (k0, k0 + nb), (nb, w))
                    off = jnp.conj(jnp.swapaxes(off, -1, -2)) if dtype_str.startswith(
                        "complex") else jnp.swapaxes(off, -1, -2)
                else:
                    off = lax.dynamic_slice(a, (k0 + nb, k0), (w, nb))
                tail = lax.dynamic_slice(b, (k0 + nb, 0), (w, nrhs))
                tail = tail - jnp.matmul(off, x_k, precision=lax.Precision.HIGHEST)
                b = lax.dynamic_update_slice(b, tail, (k0 + nb, 0))
            else:
                # backward: update the kdt block rows above k; shift window so it
                # stays in-bounds (rows [max(k0-w,0) .. k0))
                if trans:
                    a_sl = lax.dynamic_slice(a, (k0, jnp.maximum(k0 - w, 0)), (nb, w))
                    a_sl = jnp.conj(jnp.swapaxes(a_sl, -1, -2)) if dtype_str.startswith(
                        "complex") else jnp.swapaxes(a_sl, -1, -2)
                else:
                    a_sl = lax.dynamic_slice(a, (jnp.maximum(k0 - w, 0), k0), (w, nb))
                head = lax.dynamic_slice(b, (jnp.maximum(k0 - w, 0), 0), (w, nrhs))
                upd = head - jnp.matmul(a_sl, x_k, precision=lax.Precision.HIGHEST)
                # rows that slid past 0 must not be touched: re-mask
                row = jnp.arange(w) + jnp.maximum(k0 - w, 0)
                keep = (row < k0)[:, None]
                upd = jnp.where(keep, upd, head)
                b = lax.dynamic_update_slice(b, upd, (jnp.maximum(k0 - w, 0), 0))
            return b

        b = lax.fori_loop(0, nt, body, b)
        return b[:n]

    return jax.jit(fn)


def tbsm(side, alpha, A, B, opts=None, uplo=None, diag=None, trans=False,
         kd=None, pivots=None):
    """Solve op(A) X = alpha B with A triangular band (src/tbsm.cc); with
    ``pivots`` (a BandLU per-panel permutation array) this is the tbsmPivots path.
    Returns X."""
    opts = Options.make(opts)
    if Side.from_string(side) != Side.Left:
        raise SlateError("tbsm: only side='left' implemented (matches tests usage)")
    if isinstance(A, BaseBandMatrix):
        a, u = A.array, A.uplo
        kd_v = getattr(A, "kd", max(A.kl, A.ku))
        d = getattr(A, "diag", Diag.NonUnit) if diag is None else Diag.from_string(diag)
    else:
        a = as_array(A)
        u = Uplo.from_string(uplo)
        d = Diag.from_string(diag or "nonunit")
        slate_assert(kd is not None or isinstance(pivots, BandLU),
                     "tbsm on a raw array needs kd= (or BandLU pivots, "
                     "which carry their own bandwidth)")
        kd_v = int(kd) if kd is not None else 0   # BandLU overrides below
    b = as_array(B)
    squeeze = b.ndim == 1
    if squeeze:
        b = b[:, None]
    n = a.shape[-1]
    nb = min(opts.block_size, n)
    if pivots is not None:
        slate_assert(u == Uplo.Lower and not trans,
                     "pivots only apply to the forward lower sweep (gbtrs)")
        if isinstance(pivots, BandLU):  # carries its own factor-time nb/kl
            nb, kd_v, pivots = pivots.nb, pivots.kl, pivots.perms
        klt = max(1, _ceil_div(kd_v, nb))
        slate_assert(pivots.shape[-1] == (klt + 1) * nb,
                     f"pivot window {pivots.shape[-1]} does not match "
                     f"kd={kd_v}, nb={nb} (pass the BandLU, or the block_size "
                     "used at factorization time)")
        x = _gbtrs_forward(a, pivots, b, kd_v, nb)
    else:
        x = _tbsm_fn(n, kd_v, nb, b.shape[-1], u == Uplo.Lower,
                     d == Diag.Unit, bool(trans), str(a.dtype))(a, b)
    x = jnp.asarray(alpha, x.dtype) * x
    if squeeze:
        x = x[:, 0]
    return write_back(B, x)


def tbsm_pivots(side, alpha, A, pivots, B, opts=None, **kw):
    """Band triangular solve that applies LU row pivots ahead of each block
    step (src/tbsmPivots.cc; the Pivots overload of slate.hh:302-311's tbsm).
    Standalone driver for the forward sweep gbtrs composes internally."""
    return tbsm(side, alpha, A, B, opts=opts, pivots=pivots, **kw)


tbsmPivots = tbsm_pivots    # the reference's own camelCase spelling


# ---------------------------------------------------------------------------
# band Cholesky: pbtrf / pbtrs / pbsv
# ---------------------------------------------------------------------------


@lru_cache(maxsize=64)
def _pbtrf_fn(n: int, kd: int, nb: int, dtype_str: str):
    """Windowed blocked band Cholesky (src/pbtrf.cc): per block column one
    potrf + panel trsm + windowed herk, all on a static (w+1)nb window."""
    nt = _ceil_div(n, nb)
    kdt = max(1, _ceil_div(kd, nb))
    w = (kdt + 1) * nb  # window: diagonal block + kdt panel blocks
    np_ = nt * nb

    def fn(a):
        # lower-band storage, padded with identity so edge windows stay SPD
        a = _pad_to(a, np_ + w, np_ + w, diag_val=1.0)
        a = a * _band_mask(np_ + w, np_ + w, kd, 0, a.dtype)

        def body(k, a):
            k0 = k * nb
            win = lax.dynamic_slice(a, (k0, k0), (w, w))
            # storage is lower-triangle-only: mirror before factoring (the upper
            # part of the window holds zeros/junk from trailing updates)
            dkk = jnp.tril(win[:nb, :nb])
            dkk = dkk + jnp.conj(jnp.swapaxes(jnp.tril(dkk, -1), -1, -2))
            lkk = lax.linalg.cholesky(dkk, symmetrize_input=False)
            panel = lax.linalg.triangular_solve(
                lkk, win[nb:, :nb], left_side=False, lower=True,
                conjugate_a=dtype_str.startswith("complex"), transpose_a=True)
            trail = win[nb:, nb:] - jnp.matmul(
                panel, jnp.conj(jnp.swapaxes(panel, -1, -2)),
                precision=lax.Precision.HIGHEST)
            win = win.at[:nb, :nb].set(lkk)
            win = win.at[nb:, :nb].set(panel)
            win = win.at[nb:, nb:].set(trail)
            a = lax.dynamic_update_slice(a, win, (k0, k0))
            return a

        a = lax.fori_loop(0, nt, body, a)
        return jnp.tril(a[:n, :n])

    return jax.jit(fn)


def pbtrf(A, opts=None, uplo=None, kd=None):
    """Band Cholesky A = L L^H (src/pbtrf.cc). Input/output in lower band form.
    Returns (L_band, info)."""
    opts = Options.make(opts)
    if isinstance(A, BaseBandMatrix):
        a, u, kd_v = A.array, A.uplo, getattr(A, "kd", max(A.kl, A.ku))
    else:
        a = as_array(A)
        u = Uplo.from_string(uplo or "lower")
        slate_assert(kd is not None, "pbtrf on a raw array needs kd=")
        kd_v = int(kd)
    if u == Uplo.Upper:  # store lower internally (reference restriction is lower too)
        a = jnp.conj(jnp.swapaxes(a, -1, -2))
    n = a.shape[-1]
    nb = min(opts.block_size, n)
    with trace_block("pbtrf", n=n, kd=kd_v):
        L = _pbtrf_fn(n, kd_v, nb, str(a.dtype))(a)
    diag = jnp.real(jnp.diagonal(L, axis1=-2, axis2=-1))
    # shared info kernel (robust.first_bad_index, reduce_info semantics)
    from ..robust import first_bad_index

    info = first_bad_index(~(jnp.isfinite(diag) & (diag > 0)))
    return write_back(A, L), info


def pbtrs(L, B, opts=None, kd=None):
    """Solve L L^H X = B given the band factor (src/pbtrs.cc)."""
    opts = Options.make(opts)
    if isinstance(L, BaseBandMatrix):
        lb, kd_v = L.array, getattr(L, "kd", max(L.kl, L.ku))
    else:
        lb = as_array(L)
        slate_assert(kd is not None, "pbtrs on a raw array needs kd=")
        kd_v = int(kd)
    y = tbsm("left", 1.0, lb, B, opts, uplo="lower", kd=kd_v)
    x = tbsm("left", 1.0, lb, y, opts, uplo="lower", kd=kd_v, trans=True)
    return write_back(B, as_array(x))


def pbsv(A, B, opts=None, uplo=None, kd=None):
    """Solve SPD band system (src/pbsv.cc): pbtrf + pbtrs. Returns (X, info)."""
    from ..core.matrix import distribution_grid

    grid = distribution_grid(A, B)
    slate_assert(isinstance(A, BaseBandMatrix) or kd is not None,
                 "pbsv on a raw array needs kd=")
    kd_v = (getattr(A, "kd", max(A.kl, A.ku)) if isinstance(A, BaseBandMatrix)
            else int(kd))
    if grid is not None:
        # wrapper bound to a >1-device grid: the compact-storage windowed
        # factorization over the mesh (pbsv.cc consumes the construction-time
        # distribution the same way); the factor writes back dense so the
        # in-place contract matches the local path (a later pbtrs on the
        # wrapper sees L, not A)
        from ..parallel.band_dist import (band_lower_to_dense,
                                          dense_to_band_lower,
                                          pbtrf_distributed,
                                          pbtrs_distributed)

        opts_ = Options.make(opts)
        a = as_array(A)
        u = (A.uplo if isinstance(A, BaseBandMatrix)
             else Uplo.from_string(uplo or "lower"))
        if u == Uplo.Upper:
            a = jnp.conj(jnp.swapaxes(a, -1, -2))
        Ab = dense_to_band_lower(a, kd_v)
        Lb, info = pbtrf_distributed(Ab, grid, kd_v, nb=opts_.block_size)
        write_back(A, band_lower_to_dense(Lb, a.shape[-1]))
        x = pbtrs_distributed(Lb, as_array(B), grid, kd_v,
                              nb=opts_.block_size)
        return write_back(B, x), info
    L, info = pbtrf(A, opts, uplo, kd)
    x = pbtrs(as_array(L), B, opts, kd=kd_v)
    return x, info


# ---------------------------------------------------------------------------
# band LU: gbtrf / gbtrs / gbsv
# ---------------------------------------------------------------------------


class BandLU(NamedTuple):
    """Band LU factored form: dense array holding L (unit, within kl band, permuted
    per panel) and U (bandwidth kl+ku), plus the per-panel window permutations —
    the ``Pivots`` analogue (types.hh:84-117) in window-local form."""
    lu: jax.Array        # (n, n) dense with band factors
    perms: jax.Array     # (nt, w) per-panel window permutation
    kl: int
    ku: int
    nb: int


@lru_cache(maxsize=64)
def _gbtrf_fn(n: int, kl: int, ku: int, nb: int, dtype_str: str):
    """Windowed blocked band LU with partial pivoting (src/gbtrf.cc). Pivot rows
    stay within kl of the diagonal, so each panel's window is rows
    [k0, k0+nb+kl) and cols [k0, k0+nb+kl+ku) — all static shapes."""
    nt = _ceil_div(n, nb)
    klt = max(1, _ceil_div(kl, nb))
    kut = max(1, _ceil_div(ku, nb))
    wr = (klt + 1) * nb          # window rows: panel + kl fill
    wc = (klt + kut + 1) * nb    # window cols: U fill-in reaches kl+ku
    np_ = nt * nb

    def fn(a):
        a = _pad_to(a, np_ + wr, np_ + wc, diag_val=1.0)
        a = a * _band_mask(np_ + wr, np_ + wc, kl, ku, a.dtype)

        def body(k, carry):
            a, perms = carry
            k0 = k * nb
            win = lax.dynamic_slice(a, (k0, k0), (wr, wc))
            plu, _, pperm = lax.linalg.lu(win[:, :nb])
            L11 = jnp.tril(plu[:nb], -1) + jnp.eye(nb, dtype=a.dtype)
            win = jnp.take(win, pperm, axis=0)
            win = win.at[:, :nb].set(plu)
            rest = lax.linalg.triangular_solve(
                L11, win[:nb, nb:], left_side=True, lower=True, unit_diagonal=True)
            win = win.at[:nb, nb:].set(rest)
            trail = win[nb:, nb:] - jnp.matmul(
                plu[nb:, :nb], rest, precision=lax.Precision.HIGHEST)
            win = win.at[nb:, nb:].set(trail)
            a = lax.dynamic_update_slice(a, win, (k0, k0))
            perms = perms.at[k].set(pperm)
            return a, perms

        perms0 = jnp.zeros((nt, wr), jnp.int32)
        a, perms = lax.fori_loop(0, nt, body, (a, perms0))
        return a[:n, :n], perms

    return jax.jit(fn)


def _gbtrs_forward(lu, perms, b, kl, nb):
    """Forward sweep with interleaved per-panel pivoting (tbsmPivots semantics:
    apply the panel's window permutation, then eliminate with the panel's L)."""
    n = lu.shape[-1]
    nt = _ceil_div(n, nb)
    klt = max(1, _ceil_div(kl, nb))
    wr = (klt + 1) * nb
    nrhs = b.shape[-1]
    np_ = nt * nb
    lu = _pad_to(lu, np_ + wr, np_ + wr, diag_val=1.0)
    b = jnp.pad(b, ((0, np_ + wr - n), (0, 0)))

    def body(k, b):
        k0 = k * nb
        win_b = lax.dynamic_slice(b, (k0, 0), (wr, nrhs))
        win_b = jnp.take(win_b, perms[k], axis=0)
        Lwin = lax.dynamic_slice(lu, (k0, k0), (wr, nb))
        L11 = jnp.tril(Lwin[:nb], -1) + jnp.eye(nb, dtype=lu.dtype)
        y = lax.linalg.triangular_solve(L11, win_b[:nb], left_side=True,
                                        lower=True, unit_diagonal=True)
        tail = win_b[nb:] - jnp.matmul(Lwin[nb:], y,
                                       precision=lax.Precision.HIGHEST)
        win_b = win_b.at[:nb].set(y).at[nb:].set(tail)
        b = lax.dynamic_update_slice(b, win_b, (k0, 0))
        return b

    b = lax.fori_loop(0, nt, body, b)
    return b[:n]


def gbtrf(A, opts=None, kl=None, ku=None):
    """Band LU with partial pivoting (src/gbtrf.cc). Returns (BandLU, info)."""
    opts = Options.make(opts)
    a, kl, ku = _band_meta(A, kl, ku)
    n = a.shape[-1]
    slate_assert(a.shape[-2] == n, "gbtrf expects square")
    nb = min(opts.block_size, n)
    with trace_block("gbtrf", n=n, kl=kl, ku=ku):
        lu_arr, perms = _gbtrf_fn(n, kl, ku, nb, str(a.dtype))(a)
    info = _lu_info(jnp.diagonal(lu_arr, axis1=-2, axis2=-1))
    fac = BandLU(lu=write_back(A, lu_arr), perms=perms, kl=kl, ku=ku, nb=nb)
    return fac, info


def gbtrs(fac: BandLU, B, opts=None):
    """Solve with a band LU factorization (src/gbtrs.cc): pivoted forward band
    sweep (tbsmPivots) then banded back substitution with U (bandwidth kl+ku)."""
    opts = Options.make(opts)
    b = as_array(B)
    squeeze = b.ndim == 1
    if squeeze:
        b = b[:, None]
    y = _gbtrs_forward(fac.lu, fac.perms, b, fac.kl, fac.nb)
    x = _tbsm_fn(fac.lu.shape[-1], fac.kl + fac.ku, fac.nb, y.shape[-1],
                 False, False, False, str(fac.lu.dtype))(fac.lu, y)
    if squeeze:
        x = x[:, 0]
    return write_back(B, x)


def gbsv(A, B, opts=None, kl=None, ku=None):
    """Solve a general band system (src/gbsv.cc): gbtrf + gbtrs.
    Returns (X, info)."""
    from ..core.matrix import distribution_grid

    grid = distribution_grid(A, B)
    if grid is not None:
        # wrapper bound to a >1-device grid: compact-storage windowed band LU
        # over the mesh.  The factored band writes back dense (the in-place
        # contract); note the window pivots live in the distributed factored
        # form — callers needing repeated solves should use
        # parallel.gbtrf_distributed / gbtrs_distributed directly.
        from ..parallel.band_dist import (band_general_to_dense,
                                          dense_to_band_general,
                                          gbtrf_distributed,
                                          gbtrs_distributed)

        opts_ = Options.make(opts)
        a, kl_v, ku_v = _band_meta(A, kl, ku)
        Gb = dense_to_band_general(a, kl_v, ku_v, extra=kl_v)
        fac, info = gbtrf_distributed(Gb, grid, kl_v, ku_v,
                                      nb=opts_.block_size)
        nd = fac.lub.shape[0]
        wr = nd - kl_v - ku_v
        from ..core.matrix import BaseBandMatrix

        if not (isinstance(A, BaseBandMatrix)
                and getattr(A, "kl", kl_v) < wr - 1):
            # in-place contract: factored form back into A.  Skipped when A
            # is a band wrapper whose storage holds only kl subdiagonals —
            # pivoting widens L's multipliers to wr-1 > kl, and a masked
            # write-back would silently truncate them into a non-factor;
            # solves still ride the returned `fac` either way.
            write_back(A, band_general_to_dense(fac.lub, a.shape[-1],
                                                wr - 1, ku_v, extra=kl_v))
        x = gbtrs_distributed(fac, as_array(B), grid)
        return write_back(B, x), info
    fac, info = gbtrf(A, opts, kl, ku)
    x = gbtrs(fac, B, opts)
    return x, info
