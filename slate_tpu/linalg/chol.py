"""Cholesky family: potrf / potrs / posv / trtri / trtrm / potri / posv_mixed.

Reference analogue: ``src/potrf.cc:22-281`` (the canonical lookahead task-DAG driver,
SURVEY.md §3.1), ``src/{potrs,posv,potri,trtri,trtrm,posv_mixed}.cc`` and the panel
kernel ``src/internal/internal_potrf.cc``.

TPU re-design of the potrf pipeline:

* The reference runs an OpenMP task DAG: factor diagonal tile -> MPI-bcast panel ->
  batched trsm -> batched herk trailing update, with lookahead columns prioritized
  (potrf.cc:84-195).  On TPU the same right-looking blocked recurrence is expressed as
  a *software-pipelined XLA program*: a Python-unrolled loop over block columns (static
  shapes per step, every matmul MXU-shaped), with no dynamic task runtime — XLA's async
  scheduler overlaps the (sharded) panel collectives with the trailing update, which is
  exactly what the lookahead machinery hand-builds in OpenMP.
* The panel factor (internal_potrf.cc -> lapack::potrf on one tile) is
  ``lax.linalg.cholesky`` on the nb x nb diagonal block; the panel trsm is XLA's native
  blocked TriangularSolve; the trailing herk is one fused matmul per step.
* ``Target.XLA`` routes the whole factorization to ``lax.linalg.cholesky`` — the
  analogue of calling the vendor library on a single tile when the matrix fits one
  device.  ``Target.Tiled`` (default for distributed or when nb is specified) runs the
  blocked recurrence above; it is the path that honors Options.block_size and shards
  over a mesh.

Non-SPD detection: the reference reduces an ``info`` code across ranks
(internal_reduce_info.cc, potrf.cc:208).  Here ``info`` is computed functionally from
the factor's diagonal (NaN or <= 0 -> first failing global index + 1, LAPACK-style).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax

from ..core.exceptions import SlateError
from ..core.matrix import (BaseMatrix, HermitianMatrix, SymmetricMatrix, as_array,
                           distribution_grid, write_back)
from ..core.types import Options, Target, Uplo
from ..ops import blas3
from ..robust import (RetryPolicy, Rung, SolveReport, first_bad_index, inject,
                      run_ladder)
from ..utils.trace import trace_block, trace_event
from ..obs import instrument


def _full_spd(A, uplo) -> jax.Array:
    """Materialize the full Hermitian matrix from a half-stored wrapper or array."""
    if isinstance(A, (HermitianMatrix, SymmetricMatrix)):
        return A.full_array()
    a = as_array(A)
    if uplo is None:
        return a  # trust caller: already full
    uplo = Uplo.from_string(uplo)
    if uplo == Uplo.Lower:
        strict = jnp.tril(a, -1)
    else:
        strict = jnp.triu(a, 1)
    other = jnp.conj(jnp.swapaxes(strict, -1, -2)) if jnp.iscomplexobj(a) \
        else jnp.swapaxes(strict, -1, -2)
    idx = jnp.arange(a.shape[-1])
    diag = jnp.diagonal(a, axis1=-2, axis2=-1)
    if jnp.iscomplexobj(a):
        diag = jnp.real(diag).astype(a.dtype)
    return (strict + other).at[..., idx, idx].set(diag)


def _chol_info(L) -> jax.Array:
    """LAPACK-style info from a lower factor: 0 if SPD, else 1-based index of the
    first non-positive/NaN pivot — the shared info kernel
    (robust.first_bad_index, reference reduce_info semantics)."""
    d = jnp.real(jnp.diagonal(L, axis1=-2, axis2=-1))
    return first_bad_index(jnp.isnan(d) | (d <= 0))


def _host_chol_info(a, nb: int = 256) -> int:
    """Exact 1-based first-failing-pivot index, found by a host-side blocked
    factorization.  Runs only on the (exceptional) non-SPD path, because XLA's
    Cholesky NaN-fills the whole output and loses the index the reference reports
    via its per-tile info codes (potrf.cc:208)."""
    import numpy as np

    a = np.array(a, copy=True)
    n = a.shape[-1]
    for k0 in range(0, n, nb):
        k1 = min(k0 + nb, n)
        blk = a[k0:k1, k0:k1]
        try:
            Lkk = np.linalg.cholesky(blk)
        except np.linalg.LinAlgError:
            # scalar scan inside the failing block
            for j in range(k1 - k0):
                d = blk[j, j] - np.real(np.dot(blk[j, :j], np.conj(blk[j, :j])))
                if not (d > 0) or np.isnan(d):
                    return k0 + j + 1
                blk[j, j] = np.sqrt(d)
                if j + 1 < k1 - k0:
                    blk[j+1:, j] = (blk[j+1:, j]
                                    - blk[j+1:, :j] @ np.conj(blk[j, :j])) / blk[j, j]
            return k1  # shouldn't happen
        if k1 < n:
            # pan = A21 · Lkk^{-H}  (pan^H = Lkk^{-1} · A21^H)
            pan = np.linalg.solve(Lkk, a[k1:, k0:k1].conj().T).conj().T
            a[k1:, k1:] -= pan @ np.conj(pan.T)
            a[k1:, k0:k1] = pan
    return 0


_CHOL_BASE = 256


def _chol_blocked(a):
    """Recursive blocked Cholesky of one diagonal block: factor the leading
    half, one triangular solve, one Schur-complement MXU gemm, recurse.  XLA's
    fused Cholesky serializes its internal panel recursion and crawls on large
    blocks (BENCH_NOTES.md); the fused op runs only at the <=256 base."""
    n = a.shape[-1]
    if n <= _CHOL_BASE:
        # lower-triangle-only reference (XLA Cholesky ignores the upper
        # triangle): callers may hand in blocks whose upper triangle is
        # stale because the trailing updates maintain only the lower half
        return lax.linalg.cholesky(a, symmetrize_input=False)
    h = n // 2
    a11, a21, a22 = a[..., :h, :h], a[..., h:, :h], a[..., h:, h:]
    l11 = _chol_blocked(a11)
    l21 = lax.linalg.triangular_solve(l11, a21, left_side=False, lower=True,
                                      conjugate_a=True, transpose_a=True)
    s = a22 - jnp.matmul(l21, jnp.conj(jnp.swapaxes(l21, -1, -2)),
                         precision=lax.Precision.HIGHEST)
    l22 = _chol_blocked(s)
    zeros = jnp.zeros(a.shape[:-2] + (h, n - h), a.dtype)
    return jnp.concatenate(
        [jnp.concatenate([l11, zeros], axis=-1),
         jnp.concatenate([l21, l22], axis=-1)], axis=-2)


@lru_cache(maxsize=32)
def _potrf_tiled_fn(n: int, nb: int, dtype_str: str, inv_trsm: bool = False):
    """Build + jit the blocked right-looking factorization for static (n, nb).

    ``inv_trsm``: replace the panel TriangularSolve with an explicit
    inverse-apply — Linv = Lkk^{-1} once per step (one nb-wide solve), then
    panel = A21 · Linv^H as a full-rate MXU gemm.  TriangularSolve's internal
    blocking serializes against the MXU at large nb; the inverse-apply trades
    ~cond(Lkk)² local error amplification (fine for the f32 bench envelope)
    for pure gemm throughput — the classical GPU-library trsm trick, selected
    via ``Options.trsm_via_inverse`` (bench.py's potrf child maps the
    ``BENCH_POTRF_INVTRSM=1`` sweep env var onto it)."""

    nt = -(-n // nb)

    def fn(Af):
        L = Af
        for k in range(nt):
            k0, k1 = k * nb, min((k + 1) * nb, n)
            # panel factor (≅ internal::potrf on the diagonal tile, potrf.cc:96-102)
            Akk = L[k0:k1, k0:k1]
            Lkk = _chol_blocked(Akk)
            L = L.at[k0:k1, k0:k1].set(Lkk)
            if k1 < n:
                # panel trsm (≅ internal::trsm over the panel, potrf.cc:115-119);
                # the panel "broadcast" (tileBcast, potrf.cc:109) is implicit: XLA
                # inserts the all-gather when the operands are sharded.
                if inv_trsm:
                    eye_b = jnp.eye(k1 - k0, dtype=L.dtype)
                    Linv = lax.linalg.triangular_solve(
                        Lkk, eye_b, left_side=True, lower=True)
                    panel = jnp.matmul(L[k1:n, k0:k1],
                                       jnp.conj(Linv.T),
                                       precision=lax.Precision.HIGHEST)
                else:
                    panel = lax.linalg.triangular_solve(
                        Lkk, L[k1:n, k0:k1], left_side=False, lower=True,
                        conjugate_a=True, transpose_a=True)
                L = L.at[k1:n, k0:k1].set(panel)
                # trailing update (≅ internal::herk, potrf.cc:136-148 — the hot
                # loop).  Blocked herk: one trapezoidal gemm per block-column
                # group on/below the diagonal instead of the full panel·panelᴴ
                # square — flop factor (1 + 1/S)/2 of the square at S groups,
                # i.e. 0.56x at the S=8 cap (exact halving when few columns
                # remain).  S is capped so the unrolled program stays O(8·nt)
                # ops, and beyond the same nt=32 unroll bound solvers.py caps
                # at, S=1 degenerates to the single full-square update (whose
                # Hermitian add keeps both triangles valid, as before).  Only
                # the lower triangle of the trailing block is maintained;
                # every later read (diagonal-block Cholesky, sub-diagonal
                # panels) references the lower half only (_chol_blocked
                # factors with symmetrize_input=False).
                rem = nt - (k + 1)
                S = min(rem, 8) if nt <= 32 else 1
                for i in range(S):
                    jb0 = k + 1 + (i * rem) // S
                    jb1 = k + 1 + ((i + 1) * rem) // S
                    j0, j1 = jb0 * nb, min(jb1 * nb, n)
                    s = j0 - k1
                    upd = jnp.matmul(panel[s:, :],
                                     jnp.conj(panel[s:j1 - k1, :].T),
                                     precision=lax.Precision.HIGHEST)
                    L = L.at[j0:n, j0:j1].add(-upd)
        return jnp.tril(L)

    return jax.jit(fn)


@instrument
def potrf(A, opts=None, uplo=None):
    """Cholesky factorization A = L L^H (src/potrf.cc:262-281 dispatch shape).

    Returns ``(L, info)``; writes the factor back into the stored triangle of ``A`` if
    it is a Matrix wrapper.  ``uplo=Upper`` returns/stores U with A = U^H U.
    """
    opts = Options.make(opts)
    the_uplo = uplo or (A.uplo if isinstance(A, BaseMatrix) and A.uplo != Uplo.General
                        else Uplo.Lower)
    the_uplo = Uplo.from_string(the_uplo)
    Af = _full_spd(A, the_uplo if not isinstance(A, (HermitianMatrix, SymmetricMatrix))
                   else None)
    Af = inject("potrf", Af)
    n = Af.shape[-1]
    target = opts.target
    if target == Target.Auto:
        target = Target.XLA  # single fused factorization; Tiled for distributed runs

    grid = distribution_grid(A)
    with trace_block("potrf", n=n, nb=opts.block_size, target=str(target)):
        if grid is not None:
            # the wrapper carries a >1-device process grid: run the sharded
            # factorization over it (reference: distribution installed at
            # construction is consumed by every driver)
            from ..parallel import potrf_distributed

            L = potrf_distributed(Af, grid, nb=min(opts.block_size, n),
                                  lookahead=opts.lookahead)
        elif target == Target.XLA:
            L = jnp.tril(lax.linalg.cholesky(Af))
        else:
            L = _potrf_tiled_fn(n, min(opts.block_size, n), str(Af.dtype),
                                inv_trsm=opts.trsm_via_inverse)(Af)
    info = _chol_info(L)
    if opts.exact_info and int(info) != 0:
        # opt-in host refinement: XLA's Cholesky NaN-fills the whole factor, so
        # the exact first-failing-pivot index needs a host pass.  Off by
        # default — the int() is a device→host sync on every call (hot-path
        # hazard), and potrf stays fully jittable without it.
        info = jnp.int32(_host_chol_info(Af))

    out = L if the_uplo == Uplo.Lower else jnp.conj(L.T)
    if isinstance(A, BaseMatrix):
        # store only into the stored triangle, leave the rest untouched
        stored = as_array(A)
        mask = jnp.tril(jnp.ones_like(stored, dtype=bool)) if the_uplo == Uplo.Lower \
            else jnp.triu(jnp.ones_like(stored, dtype=bool))
        write_back(A, jnp.where(mask, out, stored))
    return out, info


def posv_core(a, b):
    """Pure single-matrix posv kernel: fused Cholesky + the two triangular
    sweeps — no wrappers, injection, tracing, or host syncs.  Expects the
    *full* Hermitian matrix (the serving layer hands in dense operands, not
    half-stored wrappers).  vmap-compatible: :mod:`slate_tpu.serve` maps this
    over a leading batch axis.  Returns ``(x, info)`` with the per-matrix
    LAPACK info from the factor diagonal."""
    L = lax.linalg.cholesky(a, symmetrize_input=False)
    info = _chol_info(L)
    y = lax.linalg.triangular_solve(L, b, left_side=True, lower=True)
    x = lax.linalg.triangular_solve(L, y, left_side=True, lower=True,
                                    conjugate_a=True, transpose_a=True)
    return x, info


def potrs(A, B, opts=None, uplo=None):
    """Solve A X = B given the Cholesky factor (src/potrs.cc: two work::trsm calls)."""
    opts = Options.make(opts)
    the_uplo = Uplo.from_string(uplo or (A.uplo if isinstance(A, BaseMatrix)
                                         and A.uplo != Uplo.General else Uplo.Lower))
    F = as_array(A)
    L = jnp.tril(F) if the_uplo == Uplo.Lower else jnp.conj(jnp.triu(F).T)
    b = as_array(B)
    with trace_block("potrs"):
        y = lax.linalg.triangular_solve(L, b, left_side=True, lower=True)
        x = lax.linalg.triangular_solve(L, y, left_side=True, lower=True,
                                        conjugate_a=True, transpose_a=True)
    return write_back(B, x)


@instrument
def posv(A, B, opts=None, uplo=None):
    """Solve SPD system A X = B (src/posv.cc = potrf + potrs).

    Returns (X, info); with ``Options(solve_report=True)``,
    (X, info, SolveReport)."""
    opts = Options.make(opts)
    L, info = potrf(A, opts, uplo)
    X = potrs(L if not isinstance(A, BaseMatrix) else A, B, opts,
              uplo=uplo or (A.uplo if isinstance(A, BaseMatrix)
                            and A.uplo != Uplo.General else "lower"))
    if opts.solve_report:
        report = SolveReport(routine="posv", info=int(info),
                             precision_used=str(as_array(L).dtype),
                             fallback_chain=("cholesky",)).finalize()
        report.recovered = report.info == 0
        return X, info, report
    return X, info


def trtri(A, opts=None, uplo=None, diag=None):
    """Triangular inverse (src/trtri.cc).

    The reference runs a blocked in-place algorithm; on TPU a TriangularSolve against
    the identity is the same blocked computation executed by one fused XLA op.
    """
    from ..blas import _diag_of  # local import to avoid cycle
    the_uplo = _default_uplo(A, uplo)
    the_diag = _diag_of(A, diag)
    a = as_array(A)
    n = a.shape[-1]
    eye = jnp.eye(n, dtype=a.dtype)
    with trace_block("trtri", n=n):
        inv = lax.linalg.triangular_solve(
            a, eye, left_side=True, lower=(the_uplo == Uplo.Lower),
            unit_diagonal=(the_diag.value == "unit"))
    tri = jnp.tril if the_uplo == Uplo.Lower else jnp.triu
    return _write_triangle(A, tri(inv), the_uplo)


def trtrm(A, opts=None, uplo=None):
    """Triangular-triangular multiply L^H L (or U U^H) producing a Hermitian result in
    the stored triangle — the second half of potri (src/trtrm.cc)."""
    the_uplo = _default_uplo(A, uplo)
    a = as_array(A)
    if the_uplo == Uplo.Lower:
        L = jnp.tril(a)
        out = jnp.matmul(jnp.conj(L.T), L, precision=lax.Precision.HIGHEST)
        res = jnp.tril(out)
    else:
        U = jnp.triu(a)
        out = jnp.matmul(U, jnp.conj(U.T), precision=lax.Precision.HIGHEST)
        res = jnp.triu(out)
    return _write_triangle(A, res, the_uplo)


def _default_uplo(A, uplo) -> Uplo:
    """Resolve uplo like the sibling drivers: wrapper flag, else Lower."""
    return Uplo.from_string(uplo or (A.uplo if isinstance(A, BaseMatrix)
                                     and A.uplo != Uplo.General else Uplo.Lower))


def _write_triangle(A, tri_result, uplo: Uplo):
    """Write a triangular result into only the stored triangle of a wrapper,
    preserving the unstored triangle (matches potrf's write-back discipline)."""
    if not isinstance(A, BaseMatrix):
        return tri_result
    stored = as_array(A)
    mask = jnp.tril(jnp.ones_like(stored, dtype=bool)) if uplo == Uplo.Lower \
        else jnp.triu(jnp.ones_like(stored, dtype=bool))
    write_back(A, jnp.where(mask, tri_result, stored))
    return tri_result


@instrument
def potri(A, opts=None, uplo=None):
    """SPD inverse from the Cholesky factor: A^{-1} = L^{-H} L^{-1}
    (src/potri.cc = trtri + trtrm)."""
    the_uplo = _default_uplo(A, uplo)
    Linv = trtri(A, opts, uplo=the_uplo, diag="nonunit")
    return trtrm(A if isinstance(A, BaseMatrix) else Linv, opts, uplo=the_uplo)


# ---------------------------------------------------------------------------
# Mixed-precision iterative refinement (src/posv_mixed.cc, gesv_mixed.cc:23-40)
# ---------------------------------------------------------------------------


def _lower_precision(dtype):
    """The reference factors f64 systems in f32 (gesv_mixed): f64->f32, c128->c64.

    f32 has no lower rung: XLA's LU/Cholesky do not accept bfloat16 operands (the
    MXU already uses bf16 multipliers inside f32 matmuls), so f32 inputs fall back
    to the plain full-precision solve."""
    mapping = {
        jnp.dtype(jnp.float64): jnp.float32,
        jnp.dtype(jnp.complex128): jnp.complex64,
    }
    return mapping.get(jnp.dtype(dtype))


def _ir_solve(Af, b, solve_lo, opts: Options):
    """Generic iterative-refinement loop shared by posv_mixed/gesv_mixed
    (gesv_mixed.cc iterative loop): solve in low precision, refine the residual in
    working precision, stop on ||r|| <= ||x|| * ||A|| * sqrt(n) * eps."""
    n = Af.shape[-1]
    eps = jnp.finfo(Af.dtype).eps if jnp.issubdtype(Af.dtype, jnp.floating) else \
        jnp.finfo(jnp.float64 if Af.dtype == jnp.complex128 else jnp.float32).eps
    tol = opts.tolerance if opts.tolerance is not None else float(eps) * (n ** 0.5)
    anorm = jnp.max(jnp.sum(jnp.abs(Af), axis=-1))  # inf-norm

    x0 = solve_lo(b).astype(b.dtype)

    def cond(state):
        x, it, converged = state
        return (~converged) & (it < opts.max_iterations)

    def body(state):
        x, it, _ = state
        r = b - jnp.matmul(Af, x, precision=lax.Precision.HIGHEST)
        dx = solve_lo(r).astype(b.dtype)
        x = x + dx
        rnorm = jnp.max(jnp.abs(b - jnp.matmul(Af, x, precision=lax.Precision.HIGHEST)))
        xnorm = jnp.max(jnp.abs(x))
        converged = rnorm <= tol * anorm * xnorm
        return x, it + 1, converged

    r0 = b - jnp.matmul(Af, x0, precision=lax.Precision.HIGHEST)
    conv0 = jnp.max(jnp.abs(r0)) <= tol * anorm * jnp.max(jnp.abs(x0))
    x, iters, converged = lax.while_loop(cond, body, (x0, jnp.int32(0), conv0))
    return x, iters, converged


@instrument
def posv_mixed(A, B, opts=None, uplo=None):
    """SPD solve: low-precision factor + working-precision refinement
    (src/posv_mixed.cc), run as the declared mixed→full escalation ladder
    (robust.LADDERS["posv_mixed"]; Option::UseFallbackSolver gates the second
    rung, gesv_mixed.cc:93-96).

    Returns (X, info, iters); with ``Options(solve_report=True)``,
    (X, info, iters, SolveReport).
    """
    opts = Options.make(opts)
    the_uplo = uplo or (A.uplo if isinstance(A, BaseMatrix) and A.uplo != Uplo.General
                        else Uplo.Lower)
    Af0 = _full_spd(A, None if isinstance(A, (HermitianMatrix, SymmetricMatrix))
                    else the_uplo)
    # pristine snapshot: each rung re-enters the input injection site, so a
    # call_index=0 input fault is transient under escalation — the full-
    # precision rung recovers from intact data, never a corrupted copy
    b = as_array(B)
    plain = opts.replace(solve_report=False)
    lo = opts.factor_precision or _lower_precision(Af0.dtype)
    report = SolveReport(routine="posv_mixed") if opts.solve_report else None
    if lo is None:
        Af = inject("posv_mixed", Af0)
        if Af is Af0 and isinstance(A, BaseMatrix):
            # no fault fired → original wrapper through posv, keeping its
            # in-place L-factor write-back (pre-ladder contract)
            X, info = posv(A, b, plain, uplo)
        else:
            X, info = posv(Af, b, plain, "lower")
        X = write_back(B, as_array(X))
        if report is not None:
            report.record_rung("full")
            report.info, report.precision_used = int(info), str(Af0.dtype)
            report.recovered = report.info == 0
            return X, info, jnp.int32(0), report.finalize()
        return X, info, jnp.int32(0)

    state = {"iters": jnp.int32(0)}

    def mixed_rung():
        Af = inject("posv_mixed", Af0)
        with trace_block("posv_mixed", lo=str(lo)):
            L_lo = lax.linalg.cholesky(Af.astype(lo))
            L_lo = inject("posv_mixed", L_lo, point="factor")
            info = _chol_info(L_lo)

            def solve_lo(rhs):
                y = lax.linalg.triangular_solve(L_lo, rhs.astype(lo),
                                                left_side=True, lower=True)
                return lax.linalg.triangular_solve(L_lo, y, left_side=True,
                                                   lower=True, conjugate_a=True,
                                                   transpose_a=True)

            x, iters, converged = _ir_solve(Af, b, solve_lo, opts)
        state["iters"] = iters
        return (x, info), bool(converged)

    def full_rung():
        Af = inject("posv_mixed", Af0)
        if Af is Af0 and isinstance(A, BaseMatrix):
            # no fault fired → original wrapper through posv, preserving its
            # in-place L-factor write-back (the mixed rung never touched it)
            X, info = posv(A, b, plain, uplo)
        else:
            X, info = posv(Af, b, plain, "lower")   # full-precision fallback
        return (as_array(X), info), bool(info == 0)

    rungs = [Rung("mixed", mixed_rung)]
    if opts.use_fallback_solver:
        rungs.append(Rung("full", full_rung))
    x, info = run_ladder("posv_mixed", rungs,
                         RetryPolicy.from_options(opts, "posv_mixed"), report)
    X = write_back(B, x)
    if report is not None:
        report.info = int(info)
        report.iters = int(state["iters"])
        report.precision_used = (str(jnp.dtype(lo)) if report.fallback_chain
                                 == ("mixed",) else str(Af0.dtype))
        return X, info, state["iters"], report.finalize()
    return X, info, state["iters"]


@instrument
def posv_mixed_gmres(A, B, opts=None, uplo=None):
    """SPD GMRES-IR: FGMRES in working precision, right-preconditioned by the
    low-precision Cholesky solve (src/posv_mixed_gmres.cc; single RHS like the
    reference). Returns (X, info, iters)."""
    from .lu import _gmres_ir, _require_single_rhs

    opts = Options.make(opts)
    the_uplo = uplo or (A.uplo if isinstance(A, BaseMatrix) and A.uplo != Uplo.General
                        else Uplo.Lower)
    Af = _full_spd(A, None if isinstance(A, (HermitianMatrix, SymmetricMatrix))
                   else the_uplo)
    b = as_array(B)
    _require_single_rhs(b, "posv_mixed_gmres")
    lo = opts.factor_precision or _lower_precision(Af.dtype)
    if lo is None:
        # solve_report stays off here: posv would otherwise append a report
        # and break this 2-way unpack (posv_mixed_gmres has no report form)
        X, info = posv(A, B, opts.replace(solve_report=False), uplo)
        return X, info, jnp.int32(0)

    with trace_block("posv_mixed_gmres", lo=str(lo)):
        L_lo = lax.linalg.cholesky(Af.astype(lo))
        info = _chol_info(L_lo)

        def precond(r):
            y = lax.linalg.triangular_solve(L_lo, r.astype(lo)[:, None],
                                            left_side=True, lower=True)
            z = lax.linalg.triangular_solve(L_lo, y, left_side=True, lower=True,
                                            conjugate_a=True, transpose_a=True)
            return z[:, 0].astype(b.dtype)

        def matvec(x):
            return jnp.matmul(Af, x, precision=lax.Precision.HIGHEST)

        x_out, restarts, converged = _gmres_ir(matvec, precond, b, opts,
                                               "posv_mixed_gmres")

    if opts.use_fallback_solver and not converged:
        # mixed_gmres→full ladder (robust.LADDERS), open-coded like
        # gesv_mixed_gmres; the event keeps the escalation traceable
        trace_event("fallback", routine="posv_mixed_gmres", to="full")
        X, info = posv(A, B, opts.replace(solve_report=False), uplo)
        return X, info, jnp.int32(-1)
    return write_back(B, x_out), info, jnp.int32(restarts)
