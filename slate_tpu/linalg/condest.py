"""Condition number estimation: norm1est power iteration + gecondest / pocondest /
trcondest.

Reference analogue: ``src/norm1est.cc`` (internal one-norm estimator, the Hager/Higham
power iteration used by LAPACK's xLACON), ``src/gecondest.cc``, ``src/pocondest.cc``,
``src/trcondest.cc``.

TPU re-design: the estimator needs only solve callbacks (A^{-1} x and A^{-H} x from an
existing factorization) and elementwise sign/argmax steps — a natural
``lax.while_loop``-shaped iteration; here host-unrolled to the standard <= 5 iteration
bound with static shapes.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..core.exceptions import SlateError
from ..core.matrix import as_array
from ..core.types import Norm, Options, Uplo
from ..ops import norms as norm_ops


def norm1est(solve: Callable, solve_h: Callable, n: int, dtype,
             max_iter: int = 5) -> jax.Array:
    """Estimate ||M||_1 where M is only available through matvec callbacks
    (src/norm1est.cc; Hager-Higham with the classic parity-vector refinement).

    `solve(x)` computes M x, `solve_h(x)` computes M^H x, both on (n,) vectors.
    """
    x = jnp.full((n,), 1.0 / n, dtype=dtype)
    est = jnp.zeros((), jnp.real(x).dtype)
    for _ in range(max_iter):
        y = solve(x)
        est = jnp.sum(jnp.abs(y))
        s = jnp.where(jnp.abs(y) == 0, 1.0, y / jnp.where(jnp.abs(y) == 0, 1.0,
                                                          jnp.abs(y)))
        z = solve_h(s.astype(dtype))
        j = jnp.argmax(jnp.abs(z))
        x = jnp.zeros((n,), dtype=dtype).at[j].set(1.0)
    # refinement with the alternating-parity vector (xLACON's final safeguard)
    i = jnp.arange(n, dtype=jnp.real(x).dtype)
    v = ((-1.0) ** i) * (1.0 + i / jnp.asarray(max(n - 1, 1), i.dtype))
    alt = jnp.sum(jnp.abs(solve(v.astype(dtype)))) * 2.0 / (3.0 * n)
    return jnp.maximum(est, alt)


def gecondest(LU, perm, anorm, opts=None, norm_kind=Norm.One):
    """Reciprocal condition estimate from an LU factorization (src/gecondest.cc):
    rcond = 1 / (||A|| * est(||A^{-1}||)) in the 1- or inf-norm.

    The inf-norm estimate uses ||A^{-1}||_inf == ||A^{-H}||_1 (entries of M^H have
    the same magnitudes), i.e. the same power iteration with the two solves
    swapped — pass anorm measured in the matching norm."""
    lu_ = as_array(LU)
    n = lu_.shape[-1]
    norm_kind = Norm.from_string(norm_kind)
    if norm_kind not in (Norm.One, Norm.Inf):
        raise SlateError("gecondest supports One or Inf norms")

    def solve(x):
        from .lu import lu_factored_solve
        return lu_factored_solve(lu_, perm, x[:, None])[:, 0]

    def solve_h(x):
        y = lax.linalg.triangular_solve(lu_, x[:, None], left_side=True,
                                        lower=False, transpose_a=True,
                                        conjugate_a=True)
        z = lax.linalg.triangular_solve(lu_, y, left_side=True, lower=True,
                                        unit_diagonal=True, transpose_a=True,
                                        conjugate_a=True)[:, 0]
        if perm is not None:
            z = jnp.zeros_like(z).at[perm].set(z)
        return z

    if norm_kind == Norm.Inf:
        inv_norm = norm1est(solve_h, solve, n, lu_.dtype)
    else:
        inv_norm = norm1est(solve, solve_h, n, lu_.dtype)
    rcond = 1.0 / (jnp.asarray(anorm, inv_norm.dtype) * inv_norm)
    return jnp.where(jnp.isfinite(rcond), rcond, 0.0)


def pocondest(L, anorm, opts=None, uplo=None):
    """Reciprocal condition estimate from a Cholesky factor (src/pocondest.cc)."""
    f = as_array(L)
    the_uplo = Uplo.from_string(uplo) if uplo else Uplo.Lower
    Lf = jnp.tril(f) if the_uplo == Uplo.Lower else jnp.conj(jnp.triu(f).T)
    n = f.shape[-1]

    def solve(x):
        y = lax.linalg.triangular_solve(Lf, x[:, None], left_side=True, lower=True)
        return lax.linalg.triangular_solve(Lf, y, left_side=True, lower=True,
                                           conjugate_a=True, transpose_a=True)[:, 0]

    inv_norm = norm1est(solve, solve, n, f.dtype)
    rcond = 1.0 / (jnp.asarray(anorm, inv_norm.dtype) * inv_norm)
    return jnp.where(jnp.isfinite(rcond), rcond, 0.0)


def trcondest(T, opts=None, uplo=None, diag=None, norm_kind=Norm.One):
    """Triangular condition estimate (src/trcondest.cc)."""
    from ..blas import _diag_of
    t = as_array(T)
    the_uplo = Uplo.from_string(uplo) if uplo else getattr(T, "uplo", Uplo.Lower)
    if the_uplo == Uplo.General:
        the_uplo = Uplo.Lower
    the_diag = _diag_of(T, diag)
    n = t.shape[-1]
    lower = the_uplo == Uplo.Lower
    unit = the_diag.value == "unit"
    anorm = norm_ops.trnorm(norm_kind, the_uplo, the_diag, t)

    def solve(x):
        return lax.linalg.triangular_solve(t, x[:, None], left_side=True,
                                           lower=lower, unit_diagonal=unit)[:, 0]

    def solve_h(x):
        return lax.linalg.triangular_solve(t, x[:, None], left_side=True,
                                           lower=lower, unit_diagonal=unit,
                                           transpose_a=True, conjugate_a=True)[:, 0]

    # inf-norm: ||T^{-1}||_inf == ||T^{-H}||_1 — same estimator, solves swapped
    # (the fix mirrors gecondest)
    if Norm.from_string(norm_kind) == Norm.Inf:
        inv_norm = norm1est(solve_h, solve, n, t.dtype)
    else:
        inv_norm = norm1est(solve, solve_h, n, t.dtype)
    rcond = 1.0 / (anorm * inv_norm)
    return jnp.where(jnp.isfinite(rcond), rcond, 0.0)
