"""Hermitian eigensolvers: heev / hegv / hegst, plus the two-stage building blocks
(he2hb band reduction, hb2st tridiagonalization, sterf/steqr/stedc tridiagonal
solvers).

Reference analogue (SURVEY.md §3.4): ``src/heev.cc:68-225`` — the longest pipeline in
the library: scale -> he2hb (full->band, QR-panel based) -> hb2st (band->tridiagonal
bulge chasing on rank 0) -> sterf / steqr / stedc -> back-transforms unmtr_hb2st /
unmtr_he2hb -> rescale.  Generalized: ``src/hegv.cc`` / ``src/hegst.cc``.

TPU re-design:

* The two-stage structure exists in the reference because full tridiagonalization is
  BLAS-2/memory-bound: he2hb keeps the O(n^3) work in BLAS-3 panels, and the
  band->tridiagonal bulge chase is cheap (§5.7).  XLA's ``lax.linalg.eigh`` on TPU
  uses a QDWH-based spectral divide-and-conquer that is *already* all-matmul — the
  MXU-native answer to the same memory-bound problem — so ``Target.XLA`` (default)
  routes the whole solve there.
* The explicit pipeline stages are still provided (``he2hb``/``hb2st`` here, as
  reductions built from ``lax.linalg.tridiagonal``; ``sterf``/``steqr``/``stedc``
  below) for API parity and for the distributed path, which composes them over a
  mesh; the reference's "stage 2 runs on rank 0 only" restriction (heev.cc:137-160)
  corresponds to our single-device tridiagonal solve.
* Scaling: like heev.cc:105-122, matrices with extreme norms are scaled to the
  safe range before factorization and eigenvalues rescaled after.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.exceptions import NumericalError, SlateError, slate_assert
from ..core.matrix import (BaseMatrix, HermitianMatrix, SymmetricMatrix, as_array,
                           distribution_grid, write_back)
from ..core.types import MethodEig, Norm, Options, Target, Uplo
from ..ops import norms as norm_ops
from ..robust import inject
from ..utils.trace import Timers, record_phases, trace_block
from .chol import _full_spd, potrf
from ..obs import instrument


def _full_herm(A, uplo):
    if isinstance(A, (HermitianMatrix, SymmetricMatrix)):
        return A.full_array()
    return _full_spd(A, uplo or Uplo.Lower)


def _safe_scale(a):
    """Pre-scale like heev.cc:105-122: bring ||A||_max into the safe range.
    Returns (scaled, factor) with eigenvalues of `a` = factor * eig(scaled)."""
    anorm = jnp.max(jnp.abs(a))
    eps = jnp.finfo(jnp.real(a).dtype).eps
    sfmin = jnp.finfo(jnp.real(a).dtype).tiny
    rmin = jnp.sqrt(sfmin) / jnp.sqrt(eps)
    rmax = jnp.sqrt(1.0 / sfmin) * jnp.sqrt(eps)
    sigma = jnp.where(anorm > rmax, rmax / anorm,
                      jnp.where((anorm < rmin) & (anorm > 0), rmin / anorm, 1.0))
    return a * sigma.astype(a.dtype), 1.0 / sigma


@instrument
def heev(A, opts=None, uplo=None, want_vectors: bool = True,
         method: str = "fused", chase_pipeline: bool = False,
         chase_distributed: bool = False):
    """Hermitian eigensolve (src/heev.cc). Returns (Lambda ascending, Z or None).

    method:
      - "fused" (default): XLA's eigh — on TPU a QDWH spectral divide & conquer
        that is already all-matmul, the MXU-native answer to the same
        memory-bound problem the reference's two-stage pipeline addresses.
      - "two_stage": the reference pipeline he2hb -> hb2st -> sterf/steqr/stedc
        -> unmtr_hb2st -> unmtr_he2hb (heev.cc:127-205), fully on-device.
        opts.method_eig selects the tridiagonal solver (MethodEig.DC -> stedc).

    timers: phase map like the reference's --timer-level 2 output.
    """
    opts = Options.make(opts)
    timers = Timers()
    a = inject("heev", _full_herm(A, uplo))
    n = a.shape[-1]
    grid = distribution_grid(A)
    if grid is not None:
        # wrapper bound to a >1-device grid: the distributed pipeline
        # (sharded stage 1, replicated chase — parallel/eig_dist.py)
        from ..parallel import heev_distributed

        lam, z = heev_distributed(
            a, grid, nb=default_band_nb(n, opts),
            want_vectors=want_vectors,
            method_eig={MethodEig.QR: "qr",
                        MethodEig.Bisection: "bisection"}.get(
                            opts.method_eig, "dc"),
            chase_pipeline=chase_pipeline,
            chase_distributed=chase_distributed)
        return (lam, z) if want_vectors else (lam, None)
    slate_assert(not chase_distributed,
                 "chase_distributed requires a grid-bound wrapper "
                 "(Matrix.from_array(..., grid=...)); the single-device "
                 "two-stage path has nothing to distribute")
    if method == "two_stage" and n < 8:
        method = "fused"  # no meaningful band structure below one panel
    with trace_block("heev", n=n):
        with timers.time("heev::scale"):
            a, factor = _safe_scale(a)
        if method == "two_stage":
            nb = default_band_nb(n, opts)
            with timers.time("heev::he2hb"):
                band, Vs, Ts = he2hb(a, opts, nb=nb)
            with timers.time("heev::hb2st"):
                out = hb2st(band, kd=nb, want_vectors=want_vectors,
                            pipeline=chase_pipeline)
            with timers.time("heev::stev"):
                if want_vectors:
                    d, e, Q2 = out
                    if opts.method_eig == MethodEig.QR:
                        # explicit QR-iteration request (O(n²)·gemm sweeps —
                        # the compatibility method, like the reference)
                        lam, Zt = steqr(d, e)
                    elif opts.method_eig == MethodEig.Bisection:
                        # bisection values + batched inverse iteration
                        # vectors — the method the reference declares "not
                        # yet implemented" (enums.hh:363), completed here
                        from .sturm import stein, sterf_bisect

                        lam = sterf_bisect(d, e)
                        Zt = stein(d, e, lam)
                    else:
                        # Auto/DC: divide & conquer, the performance path
                        # (MRRR also lands here — unimplemented in the
                        # reference too; D&C is the graceful stand-in)
                        lam, Zt = stedc(d, e)
                    with timers.time("heev::unmtr_hb2st"):
                        z = jnp.matmul(Q2, Zt.astype(Q2.dtype),
                                       precision=lax.Precision.HIGHEST)
                    with timers.time("heev::unmtr_he2hb"):
                        z = unmtr_he2hb("left", "n", Vs, Ts, z)
                else:
                    d, e = out
                    lam = stedc(d, e)[0] if opts.method_eig == MethodEig.DC \
                        else sterf(d, e)
                    z = None
        else:
            with timers.time("heev::solve"):
                if want_vectors:
                    lam, z = jnp.linalg.eigh(a)
                else:
                    lam, z = jnp.linalg.eigvalsh(a), None
        with timers.time("heev::rescale"):
            lam = lam * factor
    heev.timers = timers  # exposed like the reference's driver timers
    record_phases("heev", timers)  # --timer-level-2 map (trace.last_phases)
    return (lam, z) if want_vectors else (lam, None)


@instrument
def heev_range(A, opts=None, uplo=None, *, il: int = 0,
               iu: Optional[int] = None, want_vectors: bool = True,
               chase_pipeline: bool = False):
    """Subset Hermitian eigensolve: ascending eigenvalues with INDICES
    [il, iu) and, optionally, their eigenvectors — LAPACK heevx/syevx
    range='I' semantics, a capability the reference does not provide (its
    heev always computes the full spectrum).

    The bisection representation gives the subset for free: after the
    two-stage reduction (O(n²·nb) gemms), index-targeted Sturm bisection
    brackets only the k = iu-il wanted eigenvalues (O(n·k) lane-parallel
    work), ``stein`` inverse-iterates the k vectors (batched tridiagonal
    solves), and the chase back-transform applies Q2 to the THIN (n, k)
    block via the reverse sweep accumulation — never materializing the
    (n, n) Q2 — followed by the O(n²·k) blocked he2hb back-transform.
    Total vectors cost O(n²·(nb + k)) vs the full solve's O(n³).

    Returns ``(lam, Z)`` with lam shape (k,) ascending, Z (n, k) or None.
    """
    opts = Options.make(opts)
    a = _full_herm(A, uplo)
    n = a.shape[-1]
    if iu is None:
        iu = n
    slate_assert(0 <= il < iu <= n,
                 f"index range [{il}, {iu}) invalid for n={n}")
    grid = distribution_grid(A)
    if grid is not None:
        # wrapper bound to a >1-device grid: route to the distributed subset
        # pipeline like heev does (sharded stage 1, thin back-transforms) —
        # previously this silently gathered the whole matrix to one device
        from ..parallel import heev_range_distributed

        lam, z = heev_range_distributed(
            a, grid, il, iu, nb=default_band_nb(n, opts),
            want_vectors=want_vectors, chase_pipeline=chase_pipeline)
        return (lam, z) if want_vectors else (lam, None)
    if n < 8:
        lam, z = jnp.linalg.eigh(a)
        return (lam[il:iu], z[:, il:iu]) if want_vectors \
            else (lam[il:iu], None)
    from .sturm import stein, sterf_bisect

    with trace_block("heev_range", n=n, k=iu - il):
        a, factor = _safe_scale(a)
        nb = default_band_nb(n, opts)
        band, Vs1, Ts1 = he2hb(a, opts, nb=nb)
        if not want_vectors:
            d, e = hb2st(band, kd=nb, want_vectors=False,
                         pipeline=chase_pipeline)
            lam = sterf_bisect(d, e, il=il, iu=iu)
            return lam * factor, None
        d, e_c, Vcs, tcs = hb2st_reflectors(band, kd=nb,
                                            pipeline=chase_pipeline)
        e = jnp.abs(e_c)
        lam = sterf_bisect(d, e, il=il, iu=iu)
        Zt = stein(d, e, lam).astype(band.dtype)
        # chase back-transform on the thin block: band = Q2 T Q2^H with
        # Q2 = Qraw · diag(phase); Q2 @ Zt = Qraw @ (phase ⊙ Zt), and
        # Qraw @ X comes from the REVERSE sweep accumulation without the
        # (n, n) Qraw (householder.sweep_accumulate(reverse=True))
        from .householder import sweep_accumulate

        ph = _phase_vector(e_c.astype(band.dtype))
        X = ph[:, None] * Zt
        z = jnp.conj(sweep_accumulate(Vcs, tcs, n, nb,
                                      Q0=jnp.conj(X).T, reverse=True)).T
        z = unmtr_he2hb("left", "n", Vs1, Ts1, z)
        return lam * factor, z


def eig_count(A, vl, vu, opts=None, uplo=None):
    """Number of eigenvalues of the Hermitian A in the half-open interval
    [vl, vu) — two-stage reduction + one fused Sturm-count pass per
    endpoint (LAPACK stebz range='V' counting; no reference analogue).
    Endpoints coinciding with an eigenvalue are eps-sensitive (the Sturm
    count is strictly-below) — pick endpoints in spectral gaps."""
    opts = Options.make(opts)
    slate_assert(distribution_grid(A) is None,
                 "eig_count has no distributed pipeline: the Sturm-count "
                 "stage is replicated-only.  Gather the wrapper to a plain "
                 "array explicitly (eig_count(A.array, ...)) to accept the "
                 "single-device cost, or use heev_range for subset spectra.")
    a = _full_herm(A, uplo)
    n = a.shape[-1]
    if n < 8:
        lam = jnp.linalg.eigvalsh(a)
        return jnp.sum((lam >= vl) & (lam < vu)).astype(jnp.int32)
    from .sturm import sturm_count_interval

    a, factor = _safe_scale(a)
    band, _, _ = he2hb(a, opts, nb=default_band_nb(n, opts))
    d, e = hb2st(band, kd=default_band_nb(n, opts), want_vectors=False)
    return sturm_count_interval(d, e, vl / factor, vu / factor)


def hegst(itype: int, A, B_factor, opts=None, uplo=None):
    """Transform the generalized problem to standard form (src/hegst.cc;
    internal::hegst):

    itype=1:  A x = lambda B x  ->  C = L^{-1} A L^{-H}
    itype=2/3: A B x = lambda x ->  C = L^H A L
    where B = L L^H is the Cholesky factor (lower).
    """
    a = _full_herm(A, uplo)
    L = jnp.tril(as_array(B_factor))
    if itype == 1:
        W = lax.linalg.triangular_solve(L, a, left_side=True, lower=True)
        C = lax.linalg.triangular_solve(L, jnp.conj(jnp.swapaxes(W, -1, -2)),
                                        left_side=True, lower=True)
        return jnp.conj(jnp.swapaxes(C, -1, -2))
    elif itype in (2, 3):
        W = jnp.matmul(jnp.conj(jnp.swapaxes(L, -1, -2)), a,
                       precision=lax.Precision.HIGHEST)
        return jnp.matmul(W, L, precision=lax.Precision.HIGHEST)
    raise SlateError(f"hegst itype must be 1, 2, or 3, got {itype}")


def _hegv_pipeline(itype: int, A, B, opts, uplo, want_vectors, solve,
                   label: str):
    """Shared generalized-eigensolve body (src/hegv.cc): potrf(B) -> hegst ->
    ``solve`` on the standard form -> itype-dispatched back-transform.
    ``solve(C)`` returns (lam, z or None)."""
    b = _full_herm(B, uplo)
    with trace_block(label, n=b.shape[-1]):
        L, info = potrf(b, opts)
        if int(info) != 0:
            raise NumericalError(
                f"{label}: B not positive definite (info={int(info)})")
        C = hegst(itype, A, L, opts, uplo)
        lam, z = solve(C)
        if want_vectors:
            if itype in (1, 2):
                # x = L^{-H} y (LAPACK hegv back-transform for itypes 1 and 2)
                z = lax.linalg.triangular_solve(L, z, left_side=True,
                                                lower=True, conjugate_a=True,
                                                transpose_a=True)
            else:
                # itype=3: x = L y
                z = jnp.matmul(jnp.tril(L), z, precision=lax.Precision.HIGHEST)
    return lam, (z if want_vectors else None)


@instrument
def hegv(itype: int, A, B, opts=None, uplo=None, want_vectors: bool = True):
    """Generalized Hermitian eigensolve A x = lambda B x (src/hegv.cc:
    potrf(B) -> hegst -> heev -> back-transform)."""
    opts = Options.make(opts)
    return _hegv_pipeline(
        itype, A, B, opts, uplo, want_vectors,
        lambda C: heev(C, opts, uplo="lower", want_vectors=want_vectors),
        "hegv")


def hegv_range(itype: int, A, B, opts=None, uplo=None, *, il: int = 0,
               iu: Optional[int] = None, want_vectors: bool = True):
    """Generalized subset eigensolve A x = lambda B x for the eigenvalue
    INDICES [il, iu) — LAPACK hegvx/sygvx range='I' (another family the
    reference does not provide).  Same reduction as hegv (potrf(B) ->
    hegst -> standard subset solve -> back-transform), with the standard
    stage going through ``heev_range``'s O(n²(nb+k)) pipeline."""
    opts = Options.make(opts)
    return _hegv_pipeline(
        itype, A, B, opts, uplo, want_vectors,
        lambda C: heev_range(C, opts, uplo="lower", il=il, iu=iu,
                             want_vectors=want_vectors),
        "hegv_range")


# ---------------------------------------------------------------------------
# explicit pipeline stages (two-stage scaffolding + tridiagonal solvers)
# ---------------------------------------------------------------------------


def default_band_nb(n: int, opts: Optional[Options] = None) -> int:
    """Bandwidth for the two-stage reduction: the Options block size capped at
    64 and at n/4 (reference he2hb takes its own band nb, typically much
    smaller than the gemm blocking).  The cap matters for compile time: the
    masked panel QR traces O(nb) ops per panel, so nb=256 inflates the jit
    program ~4x for little chase-side gain.  Pass nb explicitly to he2hb /
    hb2st to override."""
    nb = opts.block_size if opts is not None else 256
    return max(2, min(nb, 64, max(2, n // 4)))


def he2hb(A, opts=None, uplo=None, nb: Optional[int] = None):
    """Stage 1: reduce Hermitian to nb-band form via blocked Householder QR
    panels (src/he2hb.cc — QR panel + ttqrt tree + two-sided trailing update).

    TPU re-design: one ``lax.fori_loop`` over block columns; each step QRs the
    sub-panel below the band (full-height masked panel, dynamic pivot rows —
    no ragged shapes) and applies the compact-WY block reflector two-sided to
    the whole matrix as four MXU gemms.  Program size is O(nb), not O(nt).

    Returns ``(band, Vs, Ts)`` with ``A = Q band Q^H`` where
    ``Q = prod_j (I - Vs[j] Ts[j] Vs[j]^H)``; band has bandwidth nb (both
    triangles kept — the dense Hermitian band).
    """
    opts = Options.make(opts)
    a = _full_herm(A, uplo)
    n = a.shape[-1]
    if nb is None:
        nb = default_band_nb(n, opts)
    if a.ndim > 2:
        fn = lambda x: he2hb(x, opts, nb=nb)
        for _ in range(a.ndim - 2):
            fn = jax.vmap(fn)
        return fn(a)
    nt = -(-n // nb)
    nj = max(nt - 1, 0)
    if nj == 0:
        return a, jnp.zeros((0, n, nb), a.dtype), jnp.zeros((0, nb, nb), a.dtype)
    return _he2hb_core(a, nb)


@partial(jax.jit, static_argnums=(1,))
def _he2hb_core(a, nb: int):
    """Jitted he2hb body.  Module-level jit is load-bearing, not style: the
    panel QR traces O(nb) masked-larfg ops per call, and running the
    fori_loop eagerly re-traced all of it on EVERY call — 56 s of host work
    for a 1.2 s computation at n=1024 (measured round 5; the 'two-stage is
    slow' CPU numbers were mostly this)."""
    from . import householder as hh

    n = a.shape[-1]
    nj = max(-(-n // nb) - 1, 0)

    def body(j, carry):
        Acur, Vs, Ts = carry
        k0 = j * nb
        off = k0 + nb
        P = lax.dynamic_slice(Acur, (0, k0), (n, nb))
        _, V, taus = hh.panel_qr_masked(P, off, nb)
        T = hh.build_T(V, taus)
        Acur = hh.block_apply_left(V, T, Acur, conj_q=True)
        Acur = hh.block_apply_right(V, T, Acur)
        Vs = lax.dynamic_update_slice(Vs, V[None], (j, 0, 0))
        Ts = lax.dynamic_update_slice(Ts, T[None], (j, 0, 0))
        return Acur, Vs, Ts

    Vs0 = jnp.zeros((nj, n, nb), a.dtype)
    Ts0 = jnp.zeros((nj, nb, nb), a.dtype)
    Aout, Vs, Ts = lax.fori_loop(0, nj, body, (a, Vs0, Ts0))
    idx = jnp.arange(n)
    band = jnp.where(jnp.abs(idx[:, None] - idx[None, :]) <= nb, Aout, 0)
    return band, Vs, Ts


def _apply_q(side, op, Q, C):
    """C <- op(Q) C (Side.Left) or C op(Q) (Side.Right) — the shared body of the
    unm* back-transform appliers."""
    from ..core.types import Op, Side

    side = Side.from_string(side) if not isinstance(side, Side) else side
    op = Op.from_string(op) if not isinstance(op, Op) else op
    q = as_array(Q)
    if op == Op.Trans:
        q = jnp.swapaxes(q, -1, -2)
    elif op == Op.ConjTrans:
        q = jnp.conj(jnp.swapaxes(q, -1, -2))
    c = as_array(C)
    out = (jnp.matmul(q, c, precision=lax.Precision.HIGHEST) if side == Side.Left
           else jnp.matmul(c, q, precision=lax.Precision.HIGHEST))
    return write_back(C, out)


def he2hb_q(Vs, Ts) -> jax.Array:
    """Materialize the stage-1 Q from he2hb's stacked block reflectors:
    ``Q = prod_j (I - Vs[j] Ts[j] Vs[j]^H)`` applied to the identity (ungtr
    analogue; each step is two MXU gemms)."""
    from . import householder as hh

    Vs = as_array(Vs)
    nj, n, _ = Vs.shape
    Q = jnp.eye(n, dtype=Vs.dtype)
    if nj == 0:
        return Q

    def body(jj, Q):
        j = nj - 1 - jj
        V = lax.dynamic_index_in_dim(Vs, j, 0, keepdims=False)
        T = lax.dynamic_index_in_dim(Ts, j, 0, keepdims=False)
        return hh.block_apply_left(V, T, Q)

    return lax.fori_loop(0, nj, body, Q)


def unmtr_he2hb(side, op, Vs, Ts, C, opts=None):
    """Apply the stage-1 (full -> band) orthogonal factor to C
    (src/unmtr_he2hb.cc).  ``Vs, Ts`` are he2hb's stacked block reflectors;
    application is a fori_loop of block-reflector gemms — Q is never formed."""
    from ..core.types import Op, Side
    from . import householder as hh

    side = Side.from_string(side) if not isinstance(side, Side) else side
    op = Op.from_string(op) if not isinstance(op, Op) else op
    if op not in (Op.NoTrans, Op.ConjTrans, Op.Trans):
        raise SlateError(f"unmtr_he2hb: bad op {op}")
    Vs, Ts = as_array(Vs), as_array(Ts)
    c = as_array(C)
    nj = Vs.shape[0]
    if nj == 0:
        return C
    conj_q = op != Op.NoTrans
    if op == Op.Trans and jnp.issubdtype(c.dtype, jnp.complexfloating):
        raise SlateError("unmtr_he2hb: Op.Trans unsupported for complex; use 'c'")
    # Q = Q_0 Q_1 ... Q_{nj-1}:  Q C / C Q^H apply blocks descending;
    # Q^H C / C Q apply ascending.
    descending = (side == Side.Left) == (not conj_q)

    def body(jj, acc):
        j = nj - 1 - jj if descending else jj
        V = lax.dynamic_index_in_dim(Vs, j, 0, keepdims=False)
        T = lax.dynamic_index_in_dim(Ts, j, 0, keepdims=False)
        if side == Side.Left:
            return hh.block_apply_left(V, T, acc, conj_q=conj_q)
        return hh.block_apply_right(V, T, acc, conj_q=conj_q)

    out = lax.fori_loop(0, nj, body, c)
    return write_back(C, out)


def unmtr_hb2st(side, op, V, C, opts=None):
    """Apply the stage-2 (band -> tridiagonal) factor to C (src/unmtr_hb2st.cc).
    ``V`` is the dense Q2 returned by ``hb2st(..., want_vectors=True)`` — the
    reference stores bulge-chasing reflectors instead; here stage 2 runs as one
    fused XLA op so Q2 is already materialized."""
    return _apply_q(side, op, V, C)


def _two_sided(tau, v, D):
    """D := H^H D H for H = I - tau v v^H (herf, internal_hebr.cc)."""
    D = D - jnp.conj(tau) * jnp.outer(v, jnp.conj(v) @ D)
    return D - tau * jnp.outer(D @ v, jnp.conj(v))


def _hebr1_window(W):
    """hebr1 on a (b+1, b+1) diagonal window: reflector zeroing col 0 below
    the first subdiagonal + two-sided update.  Returns (W_updated, v, tau)."""
    from . import householder as hh

    x = W[1:, 0]
    v, tau, _ = hh.larfg(x)
    xn = x - jnp.conj(tau) * v * jnp.vdot(v, x)
    W = W.at[1:, 0].set(xn)
    W = W.at[0, 1:].set(jnp.conj(xn))
    W = W.at[1:, 1:].set(_two_sided(tau, v, W[1:, 1:]))
    return W, v, tau


def _chase_extract(Ap, n):
    """(d, e_complex) from the chased padded array."""
    T = Ap[:n, :n]
    idx = jnp.arange(n)
    return jnp.real(jnp.diagonal(T)), T[idx[1:], idx[:-1]]


def _hb2st_chase(Afull: jax.Array, kd: int):
    """The bulge-chasing kernel: full Hermitian band (bandwidth kd >= 2) ->
    complex-subdiagonal tridiagonal, via the reference's three task types
    (src/internal/internal_hebr.cc hebr1/hebr2/hebr3; scheduling
    src/hb2st.cc:44-160) re-expressed as nested lax.fori_loops over static
    kd-by-kd dynamic-slice windows on a zero-padded dense array.

    Per sweep s (eliminating column s to tridiagonal):
      - hebr1: reflector on rows [s+1, s+kd] zeroes A[s+2:, s]; two-sided on the
        diagonal window.
      - for r = 1, 2, ...: hebr2 right-applies the previous reflector to the
        kd-by-kd window at (r*kd+1+s, (r-1)*kd+1+s) (creating the bulge), then a
        new reflector zeroes the window's first column below its band edge;
        hebr3 two-sides the diagonal window.  Inactive steps (past the matrix
        edge) are redirected into the zero padding, where larfg yields tau = 0
        — a structural no-op, no data-dependent branching.

    Returns (d, e_complex, Vs, taus): reflectors stacked (n_sweeps, m_max, kd)
    for the back-transform (disjoint row supports within a sweep, so a sweep's
    reflectors apply as one batched rank-1 sweep in _hb2st_q).
    """
    from . import householder as hh

    n = Afull.shape[-1]
    b = kd
    dt = Afull.dtype
    N = n + 2 * b + 2
    Ap = jnp.zeros((N, N), dt).at[:n, :n].set(Afull)
    n_sweeps = max(n - 2, 0)
    m_max = max(-(-(n - 1) // b), 1)
    Vs0 = jnp.zeros((n_sweeps, m_max, b), dt)
    taus0 = jnp.zeros((n_sweeps, m_max), dt)
    zi, zj = n + b + 1, n + 1  # zero-land window anchors for inactive steps

    def chase_body(r, inner):
        s, Ap, Vs, taus, v_prev, tau_prev = inner
        i = r * b + 1 + s
        j = (r - 1) * b + 1 + s
        active = i < n
        ii = jnp.where(active, i, zi)
        jj = jnp.where(active, j, zj)
        W = lax.dynamic_slice(Ap, (ii, jj), (b, b))
        # hebr2: right-apply previous reflector -> bulge; zero col 0 below edge
        W = W - tau_prev * jnp.outer(W @ v_prev, jnp.conj(v_prev))
        v, tau, _ = hh.larfg(W[:, 0])
        W = W - jnp.conj(tau) * jnp.outer(v, jnp.conj(v) @ W)
        Ap = lax.dynamic_update_slice(Ap, W, (ii, jj))
        Ap = lax.dynamic_update_slice(Ap, jnp.conj(W).T, (jj, ii))
        # hebr3: two-sided on the diagonal window
        D = lax.dynamic_slice(Ap, (ii, ii), (b, b))
        D = _two_sided(tau, v, D)
        Ap = lax.dynamic_update_slice(Ap, D, (ii, ii))
        Vs = Vs.at[s, r].set(v)
        taus = taus.at[s, r].set(tau)
        return s, Ap, Vs, taus, v, tau

    def sweep_body(s, carry):
        Ap, Vs, taus = carry
        # hebr1: first task of the sweep
        W = lax.dynamic_slice(Ap, (s, s), (b + 1, b + 1))
        W, v, tau = _hebr1_window(W)
        Ap = lax.dynamic_update_slice(Ap, W, (s, s))
        Vs = Vs.at[s, 0].set(v)
        taus = taus.at[s, 0].set(tau)
        _, Ap, Vs, taus, _, _ = lax.fori_loop(
            1, m_max, chase_body, (s, Ap, Vs, taus, v, tau))
        return Ap, Vs, taus

    Ap, Vs, taus = lax.fori_loop(0, n_sweeps, sweep_body, (Ap, Vs0, taus0))
    d, e_c = _chase_extract(Ap, n)
    return d, e_c, Vs, taus


def _hb2st_chase_pipelined(Afull: jax.Array, kd: int):
    """Multi-sweep pipelined bulge chase — the reference's pass/step scheduling
    (src/hb2st.cc:147-182: sweep s may run once sweep s-1 is two tasks ahead)
    vectorized into batched rounds.

    Static schedule: sweep s starts at round 2s and advances one chase block
    per round, so concurrent sweeps sit exactly two blocks apart along the
    band — far enough that their window footprints are element-disjoint (the
    corner element of one task's diagonal window is touched by neither the
    next sweep's off-diagonal window nor its mirror).  Each round runs one
    (possibly inactive) hebr1 for the newly-starting sweep plus a *batched*
    hebr2+hebr3 pair across all live fronts: (B, b, b) gathered windows,
    batched reflectors, scattered back.  Rounds total ~2·n versus the
    sequential chase's ~n·m steps — the same reordering of commuting tasks
    the reference's thread scheduler performs, so the arithmetic (and the
    reflector set) is identical up to float reassociation and tau=0 no-op
    entries (inactive slots store zero vectors here, larfg-of-zeros there —
    both mean H = I).

    Returns (d, e_complex, Vs, taus) exactly like ``_hb2st_chase``.
    """
    from . import householder as hh

    n = Afull.shape[-1]
    b = kd
    dt = Afull.dtype
    N = n + 2 * b + 2
    Ap = jnp.zeros((N, N), dt).at[:n, :n].set(Afull)
    n_sweeps = max(n - 2, 0)
    m_max = max(-(-(n - 1) // b), 1)
    B = m_max // 2 + 2                       # slots; 2B >= m_max + 2 so a slot
    #                                          is free before its next sweep
    Vs0 = jnp.zeros((n_sweeps + 1, m_max, b), dt)   # +1 = dead-slot scratch row
    taus0 = jnp.zeros((n_sweeps + 1, m_max), dt)
    zi, zj = n + b + 1, n + 1
    ar_b = jnp.arange(b)

    def round_body(t, carry):
        Ap, Vs, taus, s_st, r_st, vprev, tprev = carry

        # ---- hebr1 for the sweep starting this round (at most one) --------
        s0 = t // 2
        starting = (t % 2 == 0) & (s0 < n_sweeps)
        w0 = jnp.where(starting, s0, zj)     # redirect to zero padding if none
        W = lax.dynamic_slice(Ap, (w0, w0), (b + 1, b + 1))
        W, v0, tau0 = _hebr1_window(W)
        Ap = lax.dynamic_update_slice(Ap, W, (w0, w0))
        s0c = jnp.where(starting, s0, n_sweeps)      # scratch row when idle
        Vs = Vs.at[s0c, 0].set(v0)
        taus = taus.at[s0c, 0].set(tau0)
        q0 = s0 % B
        s_st = s_st.at[q0].set(jnp.where(starting, s0, s_st[q0]))
        r_st = r_st.at[q0].set(jnp.where(starting, 1, r_st[q0]))
        vprev = vprev.at[q0].set(jnp.where(starting, v0, vprev[q0]))
        tprev = tprev.at[q0].set(jnp.where(starting, tau0, tprev[q0]))

        # ---- batched hebr2+hebr3 pairs across all live fronts -------------
        m_s = (n - 1 - s_st + b - 1) // b
        live = (s_st >= 0) & (r_st >= 1) & (r_st < m_s)
        i = r_st * b + 1 + s_st
        j = (r_st - 1) * b + 1 + s_st
        ii = jnp.where(live, i, zi)
        jj = jnp.where(live, j, zj)
        rows = ii[:, None] + ar_b[None, :]            # (B, b)
        cols = jj[:, None] + ar_b[None, :]
        Wb = Ap[rows[:, :, None], cols[:, None, :]]   # (B, b, b) gather
        # right-apply previous reflector (bulge), then new left reflector
        Wv = jnp.einsum("bij,bj->bi", Wb, vprev)
        Wb = Wb - tprev[:, None, None] * Wv[:, :, None] * jnp.conj(vprev)[:, None, :]
        v, tau, _ = hh.larfg(Wb[:, :, 0])
        vW = jnp.einsum("bi,bij->bj", jnp.conj(v), Wb)
        Wb = Wb - jnp.conj(tau)[:, None, None] * v[:, :, None] * vW[:, None, :]
        Ap = Ap.at[rows[:, :, None], cols[:, None, :]].set(Wb)
        Ap = Ap.at[cols[:, :, None], rows[:, None, :]].set(
            jnp.conj(jnp.swapaxes(Wb, -1, -2)))
        Db = Ap[rows[:, :, None], rows[:, None, :]]
        Dv = jnp.einsum("bi,bij->bj", jnp.conj(v), Db)
        Db = Db - jnp.conj(tau)[:, None, None] * v[:, :, None] * Dv[:, None, :]
        Dw = jnp.einsum("bij,bj->bi", Db, v)
        Db = Db - tau[:, None, None] * Dw[:, :, None] * jnp.conj(v)[:, None, :]
        Ap = Ap.at[rows[:, :, None], rows[:, None, :]].set(Db)
        # store reflectors (dead slots target the scratch row)
        s_c = jnp.where(live, s_st, n_sweeps)
        r_c = jnp.where(live, r_st, 0)
        Vs = Vs.at[s_c, r_c].set(jnp.where(live[:, None], v, Vs[s_c, r_c]))
        taus = taus.at[s_c, r_c].set(jnp.where(live, tau, taus[s_c, r_c]))
        r_st = jnp.where(live, r_st + 1, r_st)
        vprev = jnp.where(live[:, None], v, vprev)
        tprev = jnp.where(live, tau, tprev)
        return Ap, Vs, taus, s_st, r_st, vprev, tprev

    T = 2 * n_sweeps + m_max
    s_st0 = jnp.full((B,), -1, jnp.int32)
    r_st0 = jnp.zeros((B,), jnp.int32)
    vprev0 = jnp.zeros((B, b), dt)
    tprev0 = jnp.zeros((B,), dt)
    Ap, Vs, taus, *_ = lax.fori_loop(
        0, T, round_body, (Ap, Vs0, taus0, s_st0, r_st0, vprev0, tprev0))
    d, e_c = _chase_extract(Ap, n)
    return d, e_c, Vs[:n_sweeps], taus[:n_sweeps]


def _hb2st_q(Vs: jax.Array, taus: jax.Array, n: int, b: int) -> jax.Array:
    """Materialize Q2 = prod_{s,r} H_{s,r} (chronological) from the chase
    reflectors — per-sweep batched application (unmtr_hb2st.cc analogue)."""
    from .householder import sweep_accumulate

    return sweep_accumulate(Vs, taus, n, b)


@partial(jax.jit, static_argnums=(1, 2))
def _hb2st_run_chase(b_arr: jax.Array, kd: int, pipeline: bool):
    """Normalize band storage to the full dense Hermitian form and run the
    bulge chase; returns (d, e_c, Vs, taus) — the reflector-level output.
    Jitted at module level: the chase traces thousands of window ops and an
    eager call re-traced them every time (see _he2hb_core)."""
    n = b_arr.shape[-1]
    idx = jnp.arange(n)
    lower = jnp.tril(b_arr, -1)
    upper = jnp.triu(b_arr, 1)
    have_lower = jnp.any(jnp.abs(lower) > 0)
    diag_part = jnp.zeros_like(b_arr).at[idx, idx].set(
        jnp.diagonal(b_arr).real.astype(b_arr.dtype))
    full_from_lower = diag_part + lower + jnp.conj(lower.T)
    full_from_upper = diag_part + upper + jnp.conj(upper.T)
    both = diag_part + lower + upper
    symmetric_already = jnp.any(jnp.abs(lower) > 0) & jnp.any(jnp.abs(upper) > 0)
    full = jnp.where(symmetric_already, both,
                     jnp.where(have_lower, full_from_lower, full_from_upper))
    chase = _hb2st_chase_pipelined if pipeline else _hb2st_chase
    return chase(full, kd)


def hb2st_reflectors(band, kd: Optional[int] = None, pipeline: bool = False):
    """Stage-2 chase returning the REFLECTOR-level output (d, e_c, Vs, taus)
    without materializing Q2.

    The hook the distributed layer uses to shard the Q2 accumulation —
    which dominates the vectors path (~97% profiled) — over mesh rows: the
    scalar chase replays replicated, each device accumulates its own row
    block via ``sweep_accumulate(..., Q0=rows)``, zero collectives (the
    reference redistributes Z to 1-D rows for unmtr_hb2st the same way,
    heev.cc:193-205).  Requires kd > 1 and n > 2 (the band cases with an
    actual chase)."""
    b_arr = as_array(band)
    if kd is None:
        kd = _infer_bandwidth(b_arr)
    n = b_arr.shape[-1]
    slate_assert(kd > 1 and n > 2,
                 "hb2st_reflectors needs kd > 1 and n > 2 (no chase below)")
    return _hb2st_run_chase(b_arr, kd, pipeline)


def _infer_bandwidth(b) -> int:
    """Eagerly infer the bandwidth of a concrete band matrix (numpy; used when
    the caller does not pass kd — requires a concrete array, not a tracer)."""
    import numpy as np

    arr = np.asarray(b)
    n = arr.shape[-1]
    nz = np.nonzero(np.abs(arr).sum(axis=tuple(range(arr.ndim - 2))) > 0)
    if len(nz[0]) == 0:
        return 1
    return max(1, int(np.max(np.abs(nz[0] - nz[1]))))


def hb2st(band, kd: Optional[int] = None, opts=None, want_vectors: bool = False,
          pipeline: bool = False):
    """Stage 2: band -> real symmetric tridiagonal via bulge chasing
    (src/hb2st.cc; task kernels src/internal/internal_hebr.cc).

    ``kd`` is the (static) bandwidth; when omitted it is inferred eagerly from
    the concrete input.  The band may be full (both triangles), lower-stored, or
    upper-stored (HermitianBandMatrix uplos); storage is normalized first.
    Returns (d, e) or (d, e, Q2) with band = Q2 T Q2^H, T = tridiag(d, e).
    Like the reference, the chase runs on one device (heev.cc:137-160 confines
    stage 2 to rank 0).

    ``pipeline=True`` runs the multi-sweep batched chase (the reference's
    pass/step concurrency, hb2st.cc:147-182): ~2n rounds instead of ~n·(n/kd)
    sequential steps.  Worth it when per-step dispatch dominates (large n on
    accelerators); the sequential dynamic-slice windows are faster on CPU,
    where gathers/scatters of batched windows cost more than they save.
    """
    b_arr = as_array(band)
    if kd is None:
        kd = _infer_bandwidth(b_arr)
    if b_arr.ndim > 2:
        fn = lambda x: hb2st(x, kd=kd, opts=opts, want_vectors=want_vectors,
                             pipeline=pipeline)
        for _ in range(b_arr.ndim - 2):
            fn = jax.vmap(fn)
        return fn(b_arr)
    n = b_arr.shape[-1]
    idx = jnp.arange(n)
    if kd > 1 and n > 2:
        d, e_c, Vs, taus = _hb2st_run_chase(b_arr, kd, pipeline)
        e = jnp.abs(e_c)
        if not want_vectors:
            return d, e
        Q2 = _hb2st_q(Vs, taus, n, kd)
        Q2 = Q2 * _phase_vector(e_c.astype(b_arr.dtype))[None, :]
        return d, e, Q2
    # kd == 1 (or trivial n): extraction + phase rotation only
    d = jnp.real(jnp.diagonal(b_arr, axis1=-2, axis2=-1))
    e_c = b_arr[idx[1:], idx[:-1]] if n > 1 else jnp.zeros((0,), b_arr.dtype)
    if n > 1:
        e_up = b_arr[idx[:-1], idx[1:]]
        e_c = jnp.where(jnp.abs(e_c) > 0, e_c, jnp.conj(e_up))
    e = jnp.abs(e_c)
    if not want_vectors:
        return d, e
    Q2 = jnp.zeros(b_arr.shape, b_arr.dtype).at[idx, idx].set(_phase_vector(e_c))
    return d, e, Q2


def _phase_vector(e_c: jax.Array) -> jax.Array:
    """Cumulative phases p (p[0]=1, p[k+1] = p[k]·e_k/|e_k|) such that with
    D = diag(p) the complex tridiagonal T_c = D T_real D^H — the unitary diagonal
    similarity that makes the off-diagonal real nonnegative."""
    mag = jnp.abs(e_c)
    ph = jnp.where(mag > 0, e_c / jnp.where(mag > 0, mag, 1), 1).astype(e_c.dtype)
    return jnp.concatenate([jnp.ones_like(ph[..., :1]),
                            jnp.cumprod(ph, axis=-1)], axis=-1)


def _assemble_tridiag(d, e) -> jax.Array:
    """Dense symmetric tridiagonal from (diag, offdiag) — shared by sterf/steqr."""
    n = d.shape[-1]
    idx = jnp.arange(n)
    T = jnp.zeros((n, n), dtype=d.dtype)
    T = T.at[idx, idx].set(d)
    T = T.at[idx[1:], idx[:-1]].set(e)
    return T.at[idx[:-1], idx[1:]].set(e)


# below this, one fused eigh/eigvalsh call beats the setup cost of the O(n²)
# paths; above it the dense formulations are the wrong complexity class
# (O(n³) flops, O(n²) assembled memory) — VERDICT r2 missing #6
_STEV_DENSE_MAX = 512


def sterf(d, e, opts=None):
    """Eigenvalues of a real symmetric tridiagonal (src/sterf.cc — O(n²) PWK
    QL/QR in LAPACK).  Here: lane-parallel Sturm bisection (linalg/sturm.py),
    the O(n²)-work / O(n)-memory TPU form; tiny problems take one fused
    eigvalsh instead."""
    d = jnp.asarray(d)
    if d.shape[-1] <= _STEV_DENSE_MAX:
        return jnp.linalg.eigvalsh(_assemble_tridiag(d, e))
    from .sturm import sterf_bisect

    return sterf_bisect(d, e)


def steqr(d, e, Z: Optional[jax.Array] = None, opts=None):
    """Tridiagonal QR iteration with optional eigenvector accumulation
    (src/steqr.cc; same (ascending lam, Z @ Q) contract as stedc).

    This is REAL implicit-shift QR iteration at every size — masked-window
    sweeps under one while_loop, each sweep's Givens chain applied to Z as a
    single MXU gemm (``linalg/steqr_qr.py``; the distributed form shards Z's
    rows, ``parallel.steqr_distributed``).  MethodEig.QR therefore means QR
    iteration semantics everywhere; the performance default for large
    vectors problems remains stedc (MethodEig.Auto/DC), the same split the
    reference makes.

    ``opts`` is accepted for driver-signature parity (src/steqr.cc takes
    Options) but the QR iteration has no tunables — it is intentionally
    unused."""
    del opts
    from .steqr_qr import steqr_qr

    return steqr_qr(d, e, Z)


def stedc(d, e, Z: Optional[jax.Array] = None, opts=None):
    """Divide & conquer tridiagonal eigensolver (src/stedc.cc + stedc_* family).
    Real D&C: host-side recursion tree of jitted rank-one merges with
    bracketed-bisection secular solves and Gu-corrected Loewner eigenvectors —
    see ``linalg/stedc.py`` for the TPU-shaped deflation design."""
    from .stedc import stedc as _stedc_impl

    return _stedc_impl(d, e, Z, opts)


steqr2 = steqr   # the reference's steqr2 is a deprecated alias (slate.hh:1295)

# real-symmetric spellings (the reference declares syev/sygv/sygst alongside
# the he* forms, slate.hh; same drivers — Hermitian == symmetric over reals)
syev = heev
sygv = hegv
sygst = hegst
