"""Hermitian eigensolvers: heev / hegv / hegst, plus the two-stage building blocks
(he2hb band reduction, hb2st tridiagonalization, sterf/steqr/stedc tridiagonal
solvers).

Reference analogue (SURVEY.md §3.4): ``src/heev.cc:68-225`` — the longest pipeline in
the library: scale -> he2hb (full->band, QR-panel based) -> hb2st (band->tridiagonal
bulge chasing on rank 0) -> sterf / steqr / stedc -> back-transforms unmtr_hb2st /
unmtr_he2hb -> rescale.  Generalized: ``src/hegv.cc`` / ``src/hegst.cc``.

TPU re-design:

* The two-stage structure exists in the reference because full tridiagonalization is
  BLAS-2/memory-bound: he2hb keeps the O(n^3) work in BLAS-3 panels, and the
  band->tridiagonal bulge chase is cheap (§5.7).  XLA's ``lax.linalg.eigh`` on TPU
  uses a QDWH-based spectral divide-and-conquer that is *already* all-matmul — the
  MXU-native answer to the same memory-bound problem — so ``Target.XLA`` (default)
  routes the whole solve there.
* The explicit pipeline stages are still provided (``he2hb``/``hb2st`` here, as
  reductions built from ``lax.linalg.tridiagonal``; ``sterf``/``steqr``/``stedc``
  below) for API parity and for the distributed path, which composes them over a
  mesh; the reference's "stage 2 runs on rank 0 only" restriction (heev.cc:137-160)
  corresponds to our single-device tridiagonal solve.
* Scaling: like heev.cc:105-122, matrices with extreme norms are scaled to the
  safe range before factorization and eigenvalues rescaled after.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.exceptions import SlateError
from ..core.matrix import (BaseMatrix, HermitianMatrix, SymmetricMatrix, as_array,
                           write_back)
from ..core.types import MethodEig, Norm, Options, Target, Uplo
from ..ops import norms as norm_ops
from ..utils.trace import Timers, trace_block
from .chol import _full_spd, potrf


def _full_herm(A, uplo):
    if isinstance(A, (HermitianMatrix, SymmetricMatrix)):
        return A.full_array()
    return _full_spd(A, uplo or Uplo.Lower)


def _safe_scale(a):
    """Pre-scale like heev.cc:105-122: bring ||A||_max into the safe range.
    Returns (scaled, factor) with eigenvalues of `a` = factor * eig(scaled)."""
    anorm = jnp.max(jnp.abs(a))
    eps = jnp.finfo(jnp.real(a).dtype).eps
    sfmin = jnp.finfo(jnp.real(a).dtype).tiny
    rmin = jnp.sqrt(sfmin) / jnp.sqrt(eps)
    rmax = jnp.sqrt(1.0 / sfmin) * jnp.sqrt(eps)
    sigma = jnp.where(anorm > rmax, rmax / anorm,
                      jnp.where((anorm < rmin) & (anorm > 0), rmin / anorm, 1.0))
    return a * sigma.astype(a.dtype), 1.0 / sigma


def heev(A, opts=None, uplo=None, want_vectors: bool = True):
    """Hermitian eigensolve (src/heev.cc). Returns (Lambda ascending, Z or None).

    timers: phase map like the reference's --timer-level 2 output
    (heev::scale/heev::solve/heev::rescale).
    """
    opts = Options.make(opts)
    timers = Timers()
    a = _full_herm(A, uplo)
    with trace_block("heev", n=a.shape[-1]):
        with timers.time("heev::scale"):
            a, factor = _safe_scale(a)
        with timers.time("heev::solve"):
            if want_vectors:
                lam, z = jnp.linalg.eigh(a)
            else:
                lam, z = jnp.linalg.eigvalsh(a), None
        with timers.time("heev::rescale"):
            lam = lam * factor
    heev.timers = timers  # exposed like the reference's driver timers
    return (lam, z) if want_vectors else (lam, None)


def hegst(itype: int, A, B_factor, opts=None, uplo=None):
    """Transform the generalized problem to standard form (src/hegst.cc;
    internal::hegst):

    itype=1:  A x = lambda B x  ->  C = L^{-1} A L^{-H}
    itype=2/3: A B x = lambda x ->  C = L^H A L
    where B = L L^H is the Cholesky factor (lower).
    """
    a = _full_herm(A, uplo)
    L = jnp.tril(as_array(B_factor))
    if itype == 1:
        W = lax.linalg.triangular_solve(L, a, left_side=True, lower=True)
        C = lax.linalg.triangular_solve(L, jnp.conj(jnp.swapaxes(W, -1, -2)),
                                        left_side=True, lower=True)
        return jnp.conj(jnp.swapaxes(C, -1, -2))
    elif itype in (2, 3):
        W = jnp.matmul(jnp.conj(jnp.swapaxes(L, -1, -2)), a,
                       precision=lax.Precision.HIGHEST)
        return jnp.matmul(W, L, precision=lax.Precision.HIGHEST)
    raise SlateError(f"hegst itype must be 1, 2, or 3, got {itype}")


def hegv(itype: int, A, B, opts=None, uplo=None, want_vectors: bool = True):
    """Generalized Hermitian eigensolve A x = lambda B x (src/hegv.cc:
    potrf(B) -> hegst -> heev -> back-transform)."""
    opts = Options.make(opts)
    b = _full_herm(B, uplo)
    with trace_block("hegv", n=b.shape[-1]):
        L, info = potrf(b, opts)
        if int(info) != 0:
            raise SlateError(f"hegv: B not positive definite (info={int(info)})")
        C = hegst(itype, A, L, opts, uplo)
        lam, z = heev(C, opts, uplo="lower", want_vectors=want_vectors)
        if want_vectors:
            if itype in (1, 2):
                # x = L^{-H} y (LAPACK hegv back-transform for itypes 1 and 2)
                z = lax.linalg.triangular_solve(L, z, left_side=True, lower=True,
                                                conjugate_a=True, transpose_a=True)
            else:
                # itype=3: x = L y
                z = jnp.matmul(jnp.tril(L), z, precision=lax.Precision.HIGHEST)
    return lam, (z if want_vectors else None)


# ---------------------------------------------------------------------------
# explicit pipeline stages (two-stage scaffolding + tridiagonal solvers)
# ---------------------------------------------------------------------------


def he2hb(A, opts=None, uplo=None):
    """Stage 1: reduce Hermitian to band form (src/he2hb.cc, 729 LoC QR-panel
    reduction with ttqrt trees).

    Current TPU form: ``lax.linalg.tridiagonal`` performs the full reduction to
    tridiagonal (band = 1) in one fused XLA op — i.e. both reference stages at once,
    the right granularity for a single device.  Returns (band_matrix, packed_reflectors,
    taus) with band = tridiagonal.  A true nb-band blocked reduction for the
    distributed path is tracked for a later round.
    """
    a = _full_herm(A, uplo)
    arr, d, e, taus = lax.linalg.tridiagonal(a, lower=True)
    n = a.shape[-1]
    band = jnp.zeros_like(a)
    idx = jnp.arange(n)
    band = band.at[..., idx, idx].set(d.astype(a.dtype))
    band = band.at[..., idx[1:], idx[:-1]].set(e.astype(a.dtype))
    band = band.at[..., idx[:-1], idx[1:]].set(jnp.conj(e).astype(a.dtype))
    return band, arr, taus


def _apply_q(side, op, Q, C):
    """C <- op(Q) C (Side.Left) or C op(Q) (Side.Right) — the shared body of the
    unm* back-transform appliers."""
    from ..core.types import Op, Side

    side = Side.from_string(side) if not isinstance(side, Side) else side
    op = Op.from_string(op) if not isinstance(op, Op) else op
    q = as_array(Q)
    if op == Op.Trans:
        q = jnp.swapaxes(q, -1, -2)
    elif op == Op.ConjTrans:
        q = jnp.conj(jnp.swapaxes(q, -1, -2))
    c = as_array(C)
    out = (jnp.matmul(q, c, precision=lax.Precision.HIGHEST) if side == Side.Left
           else jnp.matmul(c, q, precision=lax.Precision.HIGHEST))
    return write_back(C, out)


def he2hb_q(reflectors, taus) -> jax.Array:
    """Materialize the stage-1 Q from he2hb's packed reflectors: Q = diag(1, Q')
    with Q' accumulated from the sub-diagonal Householder vectors (LAPACK unghtr
    convention — the packing lax.linalg.tridiagonal produces)."""
    arr = as_array(reflectors)
    n = arr.shape[-1]
    Qs = lax.linalg.householder_product(arr[..., 1:, : n - 1], taus)
    Q = jnp.zeros_like(arr)
    Q = Q.at[..., 0, 0].set(1.0)
    return Q.at[..., 1:, 1:].set(Qs)


def unmtr_he2hb(side, op, reflectors, taus, C, opts=None):
    """Apply the stage-1 (full -> band) orthogonal factor to C
    (src/unmtr_he2hb.cc).  ``reflectors, taus`` are he2hb's packed outputs."""
    return _apply_q(side, op, he2hb_q(reflectors, taus), C)


def unmtr_hb2st(side, op, V, C, opts=None):
    """Apply the stage-2 (band -> tridiagonal) factor to C (src/unmtr_hb2st.cc).
    ``V`` is the dense Q2 returned by ``hb2st(..., want_vectors=True)`` — the
    reference stores bulge-chasing reflectors instead; here stage 2 runs as one
    fused XLA op so Q2 is already materialized."""
    return _apply_q(side, op, V, C)


def hb2st(band, opts=None, want_vectors: bool = False):
    """Stage 2: band -> real symmetric tridiagonal (src/hb2st.cc bulge chasing).
    With he2hb already producing tridiagonal form, this extracts (d, e); a wider
    band is reduced through the dense Householder tridiagonalization (one fused XLA
    op — the single-device stand-in for the O(n*kd) bulge chase, which the reference
    also confines to one rank, heev.cc:137-160)."""
    b = as_array(band)
    n = b.shape[-1]
    idx = jnp.arange(n)
    # detect content beyond the first sub/superdiagonal in EITHER triangle — the
    # band may be lower- or upper-stored (HermitianBandMatrix supports both uplos)
    wide_lower = n > 2 and bool(jnp.any(jnp.abs(jnp.tril(b, -2)) > 0))
    wide_upper = n > 2 and bool(jnp.any(jnp.abs(jnp.triu(b, 2)) > 0))
    if wide_lower or wide_upper:
        if wide_lower:
            full = jnp.tril(b) + jnp.conj(jnp.swapaxes(jnp.tril(b, -1), -1, -2))
        else:
            full = jnp.triu(b) + jnp.conj(jnp.swapaxes(jnp.triu(b, 1), -1, -2))
        arr, d, e_c, taus = lax.linalg.tridiagonal(full, lower=True)
        if not want_vectors:
            return jnp.real(d), jnp.abs(e_c)
        Q2 = he2hb_q(arr, taus)
        Q2 = Q2 * _phase_vector(e_c.astype(b.dtype))[..., None, :]
        return jnp.real(d), jnp.abs(e_c), Q2
    d = jnp.real(jnp.diagonal(b, axis1=-2, axis2=-1))
    e_c = b[..., idx[1:], idx[:-1]]
    # an upper-stored tridiagonal band keeps its offdiagonal in the superdiagonal
    e_up = b[..., idx[:-1], idx[1:]]
    e_c = jnp.where(jnp.abs(e_c) > 0, e_c, jnp.conj(e_up))
    # rotate away complex phases on the subdiagonal (the unitary diagonal similarity
    # the reference's bulge-chasing accumulates into V)
    e = jnp.abs(e_c)
    if not want_vectors:
        return d, e
    Q2 = jnp.zeros(b.shape, b.dtype).at[..., idx, idx].set(_phase_vector(e_c))
    return d, e, Q2


def _phase_vector(e_c: jax.Array) -> jax.Array:
    """Cumulative phases p (p[0]=1, p[k+1] = p[k]·e_k/|e_k|) such that with
    D = diag(p) the complex tridiagonal T_c = D T_real D^H — the unitary diagonal
    similarity that makes the off-diagonal real nonnegative."""
    mag = jnp.abs(e_c)
    ph = jnp.where(mag > 0, e_c / jnp.where(mag > 0, mag, 1), 1).astype(e_c.dtype)
    return jnp.concatenate([jnp.ones_like(ph[..., :1]),
                            jnp.cumprod(ph, axis=-1)], axis=-1)


def _assemble_tridiag(d, e) -> jax.Array:
    """Dense symmetric tridiagonal from (diag, offdiag) — shared by sterf/steqr."""
    n = d.shape[-1]
    idx = jnp.arange(n)
    T = jnp.zeros((n, n), dtype=d.dtype)
    T = T.at[idx, idx].set(d)
    T = T.at[idx[1:], idx[:-1]].set(e)
    return T.at[idx[:-1], idx[1:]].set(e)


def sterf(d, e, opts=None):
    """Eigenvalues of a real symmetric tridiagonal (src/sterf.cc wraps
    lapack::sterf on rank 0; here: one XLA eigvalsh on the assembled tridiagonal —
    the single-device equivalent)."""
    return jnp.linalg.eigvalsh(_assemble_tridiag(d, e))


def steqr(d, e, Z: Optional[jax.Array] = None, opts=None):
    """Tridiagonal QR iteration with optional eigenvector accumulation
    (src/steqr.cc distributes the Z update; single-device XLA equivalent)."""
    lam, Q = jnp.linalg.eigh(_assemble_tridiag(d, e))
    if Z is not None:
        Q = jnp.matmul(Z.astype(Q.dtype) if Z.dtype != Q.dtype else Z, Q,
                       precision=lax.Precision.HIGHEST)
    return lam, Q


def stedc(d, e, Z: Optional[jax.Array] = None, opts=None):
    """Divide & conquer tridiagonal eigensolver (src/stedc.cc + stedc_* family,
    1.8 kLoC distributed D&C).  Single-device round-1 form routes through the same
    fused path as steqr; the distributed merge/deflate/secular stages are tracked
    for a later round."""
    return steqr(d, e, Z, opts)
