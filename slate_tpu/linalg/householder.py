"""Shared Householder-reflector kernels for the two-stage eig/SVD reductions.

Reference analogue: ``src/internal/internal_householder.hh`` (gerfg/gerf — generate
and apply a single reflector) and the compact-WY panel machinery inside
``src/internal/internal_geqrf.cc`` / ``Tile_geqrf.hh``.

TPU re-design notes:

* Everything here is jittable with static shapes.  ``larfg`` generates a reflector
  for a window whose pivot is element 0 (the bulge-chasing case); ``larfg_masked``
  handles a *dynamic* pivot row inside a full-height column (the blocked panel
  case), replacing the reference's ragged sub-panel views with masks — the XLA-
  friendly alternative to dynamic shapes (SURVEY.md §7 hard part 5).
* Zero-padded tails are free: a zero tail contributes nothing to the norm, the
  reflector components there stay exactly zero, and a fully-zero column yields
  ``tau = 0`` (H = I), so edge/padding windows degenerate to no-ops without any
  data-dependent branching.
* Conventions (LAPACK): ``H = I - tau v v^H`` with ``v[pivot] = 1``.
  Left-apply ``H^H A = A - conj(tau) v (v^H A)``; right-apply
  ``A H = A - tau (A v) v^H``.  Block form ``Q = H_0 H_1 ... = I - V T V^H`` with
  T upper triangular from the forward column recurrence.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _sign_of(alpha_re):
    """sign(x) with sign(0) = 1 (LAPACK larfg convention)."""
    return jnp.where(alpha_re >= 0, 1.0, -1.0).astype(alpha_re.dtype)


def larfg(x: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Generate a Householder reflector with pivot at element 0.

    Returns ``(v, tau, beta)`` with ``v[0] = 1`` such that
    ``H^H x = beta e_0`` where ``H = I - tau v v^H``.  A zero tail (or an
    all-zero x) yields ``tau = 0`` and ``beta = x[0]`` — the no-op case that
    makes padded windows safe.
    """
    alpha = x[..., 0]
    sigma2 = jnp.sum(jnp.abs(x[..., 1:]) ** 2, axis=-1)
    real_dt = jnp.real(x).dtype
    is_cplx = jnp.issubdtype(x.dtype, jnp.complexfloating)
    anorm2 = jnp.abs(alpha) ** 2 + sigma2
    beta_mag = jnp.sqrt(anorm2)
    beta = (-_sign_of(jnp.real(alpha)) * beta_mag).astype(real_dt)
    if is_cplx:
        trivial = (sigma2 == 0) & (jnp.imag(alpha) == 0)
    else:
        trivial = sigma2 == 0
    safe_beta = jnp.where(beta == 0, 1.0, beta)
    tau = jnp.where(trivial, 0.0, ((safe_beta - alpha) / safe_beta)).astype(x.dtype)
    denom = alpha - safe_beta
    safe_denom = jnp.where(denom == 0, 1.0, denom)
    v = jnp.where(trivial[..., None], 0.0, x / safe_denom[..., None])
    v = v.at[..., 0].set(1.0)
    beta_out = jnp.where(trivial, jnp.real(alpha), beta).astype(real_dt)
    return v.astype(x.dtype), tau, beta_out


def larfg_masked(x: jax.Array, pivot) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Reflector for a full-height column with a *dynamic* pivot row.

    Zeroes ``x[pivot+1:]`` into ``x[pivot]``; rows ``< pivot`` are ignored (the
    reflector has zeros there), replacing the reference's sub-panel view
    (``internal_geqrf.cc:79-124`` operates on a trailing sub-column) with a mask.
    Returns ``(v, tau, beta)`` with ``v[pivot] = 1`` and zeros above.
    """
    n = x.shape[-1]
    ar = jnp.arange(n)
    tail = jnp.where(ar > pivot, x, 0)
    alpha = x[pivot]
    sigma2 = jnp.sum(jnp.abs(tail) ** 2)
    real_dt = jnp.real(x).dtype
    is_cplx = jnp.issubdtype(x.dtype, jnp.complexfloating)
    beta_mag = jnp.sqrt(jnp.abs(alpha) ** 2 + sigma2)
    beta = (-_sign_of(jnp.real(alpha)) * beta_mag).astype(real_dt)
    if is_cplx:
        trivial = (sigma2 == 0) & (jnp.imag(alpha) == 0)
    else:
        trivial = sigma2 == 0
    safe_beta = jnp.where(beta == 0, 1.0, beta)
    tau = jnp.where(trivial, 0.0, ((safe_beta - alpha) / safe_beta)).astype(x.dtype)
    denom = alpha - safe_beta
    safe_denom = jnp.where(denom == 0, 1.0, denom)
    v = jnp.where(trivial, 0.0, tail / safe_denom)
    v = jnp.where(ar == pivot, 1.0, v).astype(x.dtype)
    beta_out = jnp.where(trivial, jnp.real(alpha), beta).astype(real_dt)
    return v, tau, beta_out


def apply_left(tau, v: jax.Array, A: jax.Array) -> jax.Array:
    """A := H^H A = A - conj(tau) v (v^H A).  v: (m,), A: (m, n)."""
    w = jnp.einsum("i,i...->...", jnp.conj(v), A)
    return A - jnp.conj(tau) * v[:, None] * w[None, :]


def apply_right(tau, v: jax.Array, A: jax.Array) -> jax.Array:
    """A := A H = A - tau (A v) v^H.  v: (n,), A: (m, n)."""
    w = jnp.einsum("...j,j->...", A, v)
    return A - tau * w[:, None] * jnp.conj(v)[None, :]


def panel_qr_masked(P: jax.Array, off, nb: int):
    """Householder QR of the rows ``off:`` of an (n, nb) panel, in place via masks.

    ``off`` is a traced row offset (the reference slices a trailing sub-panel
    instead; here the panel keeps full height and rows above ``off`` are
    untouched).  Returns ``(R, V, taus)``: R is the transformed panel (entries
    below the per-column pivot explicitly zeroed), V (n, nb) holds the
    reflectors (unit pivot, zeros above), taus (nb,).
    """
    n, nb_ = P.shape
    ar = jnp.arange(n)
    V = jnp.zeros_like(P)
    taus = jnp.zeros((nb_,), P.dtype)
    R = P
    for i in range(nb_):
        p = off + i
        v, tau, beta = larfg_masked(R[:, i], p)
        R = apply_left(tau, v, R)
        # exact zeros below the pivot of column i (the reflector zeroes them
        # analytically; enforce numerically like the reference's panel)
        R = R.at[:, i].set(jnp.where(ar > p, 0.0, R[:, i]))
        V = V.at[:, i].set(v)
        taus = taus.at[i].set(tau)
    return R, V, taus


def panel_lq_masked(P: jax.Array, off, nb: int):
    """Householder LQ of the cols ``off:`` of an (nb, n) row-panel via masks.

    Zeroes, for each row i, the entries right of column ``off + i``.  Returns
    ``(L, V, taus)`` with V of shape (n, nb) in *column* form: column i is the
    reflector v_i (unit pivot at row ``off + i`` of the transposed panel) such
    that right-applying ``Q = H_0 H_1 ... = I - V T V^H`` to the row-panel gives
    ``P Q = L`` — i.e. the same (V, taus) plug into build_T / block_apply_right.

    Implemented as QR of the conjugate transpose, sharing panel_qr_masked.
    """
    R, V, taus = panel_qr_masked(jnp.conj(P).T, off, nb)
    return jnp.conj(R).T, V, taus


def build_T(V: jax.Array, taus: jax.Array, off=None) -> jax.Array:
    """Compact-WY T factor: ``H_0 H_1 ... H_{nb-1} = I - V T V^H``.

    Forward recurrence ``T[:i, i] = -tau_i T[:i, :i] (V[:, :i]^H v_i)``,
    ``T[i, i] = tau_i`` (Tile_geqrf.hh analogue; nb is small and static so the
    Python loop traces to O(nb) fused ops).
    """
    n, nb = V.shape
    T = jnp.zeros((nb, nb), V.dtype)
    G = jnp.matmul(jnp.conj(V).T, V, precision=lax.Precision.HIGHEST)  # (nb, nb)
    for i in range(nb):
        col = -taus[i] * jnp.matmul(T[:, :i], G[:i, i])
        T = T.at[:i, i].set(col[:i])
        T = T.at[i, i].set(taus[i])
    return T


_SWEEP_GROUP = 8


@partial(jax.jit, static_argnums=(2, 3, 4, 6))
def sweep_accumulate(Vs: jax.Array, taus: jax.Array, n: int, b: int,
                     group: int = _SWEEP_GROUP, Q0=None,
                     reverse: bool = False) -> jax.Array:
    """Accumulate Q = prod_s prod_r H_{s,r} (chronological) from bulge-chase
    reflectors whose supports within sweep s are the adjacent length-b blocks
    starting at row/col ``s + 1 + r*b``.

    Because supports within a sweep are disjoint, each sweep is one rank-m
    update applied with a reshape to (slots, b) blocks — batched instead of
    the reference's per-task reflector application (unmtr_hb2st.cc /
    unmbr_tb2bd.cc).  ``group`` sweeps share ONE memory round trip: sweep
    s+g's supports sit g columns to the right of sweep s's, so a window of
    width m_max·b + group − 1 covers the whole group and the g updates run
    back-to-back in registers between one slice and one write — the
    accumulation is bandwidth-bound (profiled at ~97% of the n=2,048
    vectors path), so the traffic drops ~group×.  Returns the dense
    (n, n) Q — or, with ``Q0`` (an (m, n) initial row block replacing the
    identity), the (m, n) product ``Q0 · Q``.  Every update is a pure
    column operation, so rows are embarrassingly parallel: ``Q0`` is the
    hook the distributed layer uses to shard the accumulation over mesh
    rows with zero collectives (the reference's unmtr_hb2st 1-D row
    distribution, heev.cc:193-205).

    ``reverse=True`` applies the CONJUGATE-TRANSPOSED product in reverse
    chronological order — i.e. returns ``Q0 · Q^H`` — so ``Q · X`` for a
    thin X is ``sweep_accumulate(..., Q0=X^H, reverse=True)^H`` without
    materializing the (n, n) Q (the subset-eigenvector back-transform).
    """
    n_sweeps, m_max, _ = Vs.shape
    dt = Vs.dtype
    group = max(1, min(group, n_sweeps))
    ng = -(-n_sweeps // group)            # group count
    pad_s = ng * group - n_sweeps
    if pad_s:
        # tau = 0 ⇒ H = I: padded sweeps are exact no-ops
        Vs = jnp.concatenate(
            [Vs, jnp.zeros((pad_s, m_max, b), dt)], axis=0)
        taus = jnp.concatenate([taus, jnp.zeros((pad_s, m_max), dt)], axis=0)
    if reverse:
        taus = jnp.conj(taus)
    win = m_max * b + group - 1
    ncols = n + win + b + group
    m = n if Q0 is None else Q0.shape[-2]
    Q = jnp.zeros((m, ncols), dt).at[:, :n].set(
        jnp.eye(n, dtype=dt) if Q0 is None else Q0.astype(dt))

    def body(g, Q):
        s0 = (ng - 1 - g) * group if reverse else g * group
        W = lax.dynamic_slice(Q, (0, s0 + 1), (m, win))
        order = range(group - 1, -1, -1) if reverse else range(group)
        for gi in order:                  # in-register: one HBM round trip
            V = lax.dynamic_index_in_dim(Vs, s0 + gi, 0, keepdims=False)
            t = lax.dynamic_index_in_dim(taus, s0 + gi, 0, keepdims=False)
            S = lax.slice_in_dim(W, gi, gi + m_max * b, axis=1)
            S = S.reshape(m, m_max, b)
            y = jnp.einsum("nrb,rb->nr", S, V)
            S = S - jnp.einsum("r,nr,rb->nrb", t, y, jnp.conj(V))
            W = lax.dynamic_update_slice(W, S.reshape(m, m_max * b), (0, gi))
        return lax.dynamic_update_slice(Q, W, (0, s0 + 1))

    Q = lax.fori_loop(0, ng, body, Q)
    return Q[:, :n]


def block_apply_left(V: jax.Array, T: jax.Array, C: jax.Array,
                     conj_q: bool = False) -> jax.Array:
    """C := Q C (or Q^H C with conj_q) for Q = I - V T V^H, all MXU gemms."""
    Tm = jnp.conj(T).T if conj_q else T
    W = jnp.matmul(jnp.conj(V).T, C, precision=lax.Precision.HIGHEST)
    return C - jnp.matmul(V, jnp.matmul(Tm, W, precision=lax.Precision.HIGHEST),
                          precision=lax.Precision.HIGHEST)


def block_apply_right(V: jax.Array, T: jax.Array, C: jax.Array,
                      conj_q: bool = False) -> jax.Array:
    """C := C Q (or C Q^H with conj_q) for Q = I - V T V^H."""
    Tm = jnp.conj(T).T if conj_q else T
    W = jnp.matmul(C, V, precision=lax.Precision.HIGHEST)
    return C - jnp.matmul(jnp.matmul(W, Tm, precision=lax.Precision.HIGHEST),
                          jnp.conj(V).T, precision=lax.Precision.HIGHEST)
