"""Hermitian/symmetric-indefinite solvers: hetrf / hetrs / hesv (+ sy* aliases).

Reference analogue (SURVEY.md §2.4): ``src/{hetrf,hetrs,hesv}.cc`` — SLATE factors
indefinite Hermitian systems with a communication-avoiding **blocked Aasen**
algorithm: P A P^H = L T L^H where L is unit lower triangular (first block column =
identity) and T is a Hermitian **band** matrix of bandwidth nb, which is then solved
with the band LU (the reference routes hetrs through its banded solvers; same here
via :func:`~slate_tpu.linalg.band.gbsv`).

TPU re-design:

* The per-panel work is expressed as a few large gemms: the Aasen H-column
  H[:,j] = T[:, :j+1] @ L[j, :j+1]^H is ONE matmul against the dense-stored band T,
  and the panel residual W = A[j+1:, j] - L @ H - L[:,j] @ H[j,j] is two more — all
  MXU-shaped, no scalar recurrences.
* Panel pivoting uses ``lax.linalg.lu`` on the tall residual panel (the reference's
  multithreaded getrf panel team, SURVEY.md §2.6 "panel parallelism", becomes XLA's
  blocked LU); the permutation is applied two-sidedly to the trailing matrix and to
  the already-computed L rows, giving the standard Aasen P A P^H = L T L^H.
* Ragged n is padded to whole blocks with an identity diagonal (pad-and-mask,
  SURVEY.md §7 hard-part 5) — blockdiag(A, I) factors compatibly and the padded
  solution rows are discarded.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.matrix import as_array, write_back
from ..core.types import Options
from ..robust import inject
from ..utils.trace import trace_block
from .band import BandLU, gbtrf, gbtrs
from .eig import _full_herm

__all__ = ["HermitianFactors", "hetrf", "hetrs", "hesv", "sytrf", "sytrs", "sysv"]


class HermitianFactors(NamedTuple):
    """Aasen factored form P A P^H = L T L^H (the reference's (A, pivots, T, H)
    output bundle of hetrf, slate.hh hetrf signature). T is kept both as the
    dense-stored band (for reconstruction/tests) and pre-factored by band LU so
    repeated hetrs calls don't refactor (factor-once / solve-many contract)."""
    L: jax.Array       # (n, n) unit lower triangular, first block column = identity
    T: jax.Array       # (n, n) dense-stored Hermitian band, bandwidth nb
    T_fac: BandLU      # band LU of T (bandwidths kl = ku = nb)
    perm: jax.Array    # (n,) row permutation: (P A P^H) = A[perm][:, perm]
    inv_perm: jax.Array  # (n,) inverse of perm, precomputed so solves skip the argsort
    nb: int


def _conj_t(x):
    return jnp.conj(jnp.swapaxes(x, -1, -2))


@lru_cache(maxsize=32)
def _hetrf_fn(n: int, nb: int, dtype_str: str):
    """Blocked Aasen, panels unrolled at trace time (N = n/nb static)."""
    N = -(-n // nb)
    np_ = N * nb

    def fn(a):
        # pad with identity: blockdiag(A, I) keeps the factorization exact
        pad = np_ - n
        a = jnp.pad(a, ((0, pad), (0, pad)))
        if pad:
            idx = jnp.arange(n, np_)
            a = a.at[idx, idx].set(jnp.asarray(1.0, a.dtype))
        L = jnp.eye(np_, dtype=a.dtype)
        T = jnp.zeros((np_, np_), a.dtype)
        perm = jnp.arange(np_)

        for j in range(N):
            j0, j1 = j * nb, (j + 1) * nb
            # H[:, j] for block rows 0..j-1: T is Hermitian-banded so only rows
            # 0..j0+nb of columns 0..j1 contribute; one gemm (Aasen H-column)
            if j > 0:
                Hcol = jnp.matmul(T[:j0, :j1 + nb],
                                  _conj_t(L[j0:j1, :j1 + nb]),
                                  precision=lax.Precision.HIGHEST)  # (j0, nb)
            else:
                Hcol = jnp.zeros((0, nb), a.dtype)
            # A-identity: A[j][j] = sum_{k<j} L[j][k] H[k][j] + L[j][j] H[j][j]
            LjjHjj = a[j0:j1, j0:j1] - jnp.matmul(
                L[j0:j1, :j0], Hcol, precision=lax.Precision.HIGHEST)
            Ljj = L[j0:j1, j0:j1]
            Hjj = lax.linalg.triangular_solve(Ljj, LjjHjj, left_side=True,
                                              lower=True, unit_diagonal=True)
            # T[j][j]: H[j][j] = T[j][j-1] L[j][j-1]^H + T[j][j] L[j][j]^H
            rhs = Hjj
            if j > 0:
                rhs = rhs - jnp.matmul(T[j0:j1, j0 - nb:j0],
                                       _conj_t(L[j0:j1, j0 - nb:j0]),
                                       precision=lax.Precision.HIGHEST)
            # right-solve against unit upper triangular L[j][j]^H
            Tjj = lax.linalg.triangular_solve(
                Ljj, rhs, left_side=False, lower=True, unit_diagonal=True,
                conjugate_a=True, transpose_a=True)
            Tjj = (Tjj + _conj_t(Tjj)) / 2  # Hermitian up to roundoff
            T = T.at[j0:j1, j0:j1].set(Tjj)

            if j < N - 1:
                # panel residual W = L[j+1:, j+1] T[j+1][j] L[j][j]^H
                W = a[j1:, j0:j1]
                if j > 0:
                    W = W - jnp.matmul(L[j1:, :j0], Hcol,
                                       precision=lax.Precision.HIGHEST)
                W = W - jnp.matmul(L[j1:, j0:j1], Hjj,
                                   precision=lax.Precision.HIGHEST)
                plu, _, pperm = lax.linalg.lu(W)
                L_panel = jnp.tril(plu, -1)[:, :nb] + jnp.eye(
                    plu.shape[0], nb, dtype=a.dtype)
                Up = jnp.triu(plu[:nb, :nb])
                # T[j+1][j] = U_p (L[j][j]^H)^{-1}  (stays upper triangular)
                Tj1j = lax.linalg.triangular_solve(
                    L[j0:j1, j0:j1], Up, left_side=False, lower=True,
                    unit_diagonal=True, conjugate_a=True, transpose_a=True)
                T = T.at[j1:j1 + nb, j0:j1].set(Tj1j)
                T = T.at[j0:j1, j1:j1 + nb].set(_conj_t(Tj1j))
                # two-sided permutation of the trailing matrix + L rows + perm
                gperm = jnp.concatenate([jnp.arange(j1), j1 + pperm])
                a = jnp.take(jnp.take(a, gperm, axis=0), gperm, axis=1)
                L = L.at[j1:, nb:j1].set(
                    jnp.take(L[j1:, nb:j1], pperm, axis=0))
                perm = jnp.take(perm, gperm)
                L = L.at[j1:, j1:j1 + nb].set(L_panel)

        return L[:n, :n], T[:n, :n], perm[:n]

    return jax.jit(fn)


def hetrf(A, opts=None, uplo=None):
    """Aasen factorization P A P^H = L T L^H with band T (src/hetrf.cc).
    Returns (HermitianFactors, info)."""
    opts = Options.make(opts)
    a = inject("hetrf", _full_herm(A, uplo))
    n = a.shape[-1]
    nb = min(opts.block_size, n)
    with trace_block("hetrf", n=n, nb=nb):
        L, T, perm = _hetrf_fn(n, nb, str(a.dtype))(a)
        # factor the band T once here; its zero-pivot detection is the real
        # singularity signal for the whole factorization
        T_fac, info = gbtrf(T, opts.replace(block_size=nb), kl=nb, ku=nb)
    return HermitianFactors(L=L, T=T, T_fac=T_fac, perm=perm,
                            inv_perm=jnp.argsort(perm), nb=nb), info


def hetrs(fac: HermitianFactors, B, opts=None):
    """Solve with the Aasen factorization (src/hetrs.cc): forward L sweep, band
    solve with T (the reference's banded-T solve), backward L^H sweep, un-permute."""
    opts = Options.make(opts)
    b = as_array(B)
    squeeze = b.ndim == 1
    if squeeze:
        b = b[:, None]
    y = jnp.take(b, fac.perm, axis=0)
    y = lax.linalg.triangular_solve(fac.L, y, left_side=True, lower=True,
                                    unit_diagonal=True)
    z = gbtrs(fac.T_fac, y, opts)
    x = lax.linalg.triangular_solve(fac.L, z, left_side=True, lower=True,
                                    unit_diagonal=True, conjugate_a=True,
                                    transpose_a=True)
    x = jnp.take(x, fac.inv_perm, axis=0)
    if squeeze:
        x = x[:, 0]
    return write_back(B, x)


def hesv(A, B, opts=None, uplo=None):
    """Solve a Hermitian-indefinite system (src/hesv.cc): hetrf + hetrs.
    Returns (X, info); with ``Options(solve_report=True)``,
    (X, info, SolveReport) — on both the single-device and grid paths."""
    from ..core.matrix import distribution_grid

    opts_ = Options.make(opts)
    grid = distribution_grid(A, B)
    if grid is not None:
        # wrapper bound to a >1-device grid: distributed CA-Aasen
        # (hesv.cc consumes the construction-time distribution the same way)
        from ..parallel import hesv_distributed

        a = _full_herm(A, uplo)
        x, info = hesv_distributed(a, as_array(B), grid,
                                   nb=min(opts_.block_size, a.shape[-1]))
        x = write_back(B, x)
    else:
        fac, info = hetrf(A, opts, uplo)
        x = hetrs(fac, B, opts)
    if opts_.solve_report:
        from ..robust import SolveReport

        report = SolveReport(routine="hesv", info=int(info),
                             precision_used=str(as_array(x).dtype),
                             fallback_chain=("aasen",)).finalize()
        report.recovered = report.info == 0
        return x, info, report
    return x, info


# real-symmetric aliases (the reference's sy* names alias he* for real scalars)
sytrf = hetrf
sytrs = hetrs
sysv = hesv
