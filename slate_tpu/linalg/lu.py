"""LU family: getrf (partial-pivot / nopiv / tournament) + getrs / gesv / getri and the
mixed-precision + random-butterfly solver variants.

Reference analogue (SURVEY.md §2.4 LU row): ``src/getrf.cc`` (partial pivoting with the
multithreaded panel of internal_getrf.cc + MPI pivot broadcast), ``src/getrf_nopiv.cc``,
``src/getrf_tntpiv.cc`` (CALU tournament pivoting), ``src/{getrs,gesv,getri,getriOOP}.cc``,
``src/gesv_mixed.cc`` (f32 factor + f64 iterative refinement), ``src/gesv_mixed_gmres.cc``
(GMRES-IR), ``src/gesv_rbt.cc`` + ``src/gerbt.cc`` (random butterfly transform).

TPU re-design:

* **Pivot representation.** The reference keeps per-panel ``Pivots`` (tile index +
  offset, types.hh:84-117) and swaps rows pairwise over MPI (internal_swap.cc).  Row
  swaps are hostile to an SPMD machine; instead every factorization returns a *global
  permutation vector* ``perm`` (PA = LU, perm[i] = source row) and row exchanges become
  one XLA gather — the TPU-native form of permuteRows.  ``perm_to_pivots`` converts to
  LAPACK/reference-style ipiv for API parity.

* **Panel factorization.** The reference panel is a thread-team with an MPI maxloc
  reduction per column (internal_getrf.cc:77-115).  Here the panel is
  ``lax.linalg.lu`` on the tall block column — XLA's native partially-pivoted LU —
  and the blocked driver composes panels exactly like getrf.cc's task loop: panel ->
  permute left/right -> row trsm -> trailing gemm (the hot loop, getrf.cc:173-230).

* **Tournament pivoting (CALU)** maps *better* to TPU than partial pivoting: each
  round is a batched LU over row blocks + a tree reduction that halves the candidate
  set (getrf_tntpiv.cc's panel; SURVEY.md §7 notes this is the better-fit default).
  Implemented with static shapes: candidates are padded to nb rows per block.

* **RBT** (gesv_rbt.cc:94-172): depth-d butterfly transforms are a perfect fit —
  structured +/- mixing expressed as reshapes and elementwise ops, then nopiv LU.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax

from ..core.exceptions import SlateError, slate_assert
from ..core.matrix import BaseMatrix, as_array, distribution_grid, write_back
from ..core.types import MethodLU, Options, Target
from ..robust import (RetryPolicy, Rung, SolveReport, first_bad_index, inject,
                      run_ladder)
from ..utils.trace import trace_block, trace_event
from .chol import _ir_solve
from ..obs import instrument


# ---------------------------------------------------------------------------
# pivots utilities
# ---------------------------------------------------------------------------


def perm_to_pivots(perm):
    """Convert a permutation vector to LAPACK-style sequential ipiv (1-based),
    the reference's Pivots representation (types.hh:84-117).

    O(n) with a position map instead of the O(n²) ``list.index`` scan (round-1
    review: the ipiv path crawled for large n)."""
    import numpy as np

    p = np.asarray(perm)
    n = p.shape[0]
    rows = np.arange(n)            # rows[i] = original row at position i
    pos = np.arange(n)             # pos[r]  = current position of original row r
    ipiv = np.zeros(n, dtype=np.int64)
    for k in range(n):
        j = pos[p[k]]
        ipiv[k] = j + 1
        rk, rj = rows[k], rows[j]
        rows[k], rows[j] = rj, rk
        pos[rj], pos[rk] = k, j
    return ipiv


def pivots_to_perm(ipiv):
    """Inverse of perm_to_pivots: replay the 1-based sequential row interchanges
    into the permutation vector our getrs/getri consume."""
    import numpy as np

    ip = np.asarray(ipiv).tolist()
    rows = list(range(len(ip)))
    for k, one_based in enumerate(ip):
        j = int(one_based) - 1
        rows[k], rows[j] = rows[j], rows[k]
    return np.asarray(rows, dtype=np.int64)


def _compose_perm(outer, inner):
    """perm = outer ∘ inner: result[i] = inner[outer[i]]."""
    return jnp.take(inner, outer)


def _lu_info(U_diag) -> jax.Array:
    """First zero/NaN U pivot, LAPACK-style — the shared info kernel
    (robust.first_bad_index, the reference's reduce_info semantics)."""
    return first_bad_index(jnp.isnan(U_diag) | (U_diag == 0))


# ---------------------------------------------------------------------------
# nopiv panel kernel (used by getrf_nopiv and the RBT solver)
# ---------------------------------------------------------------------------


def _lu_nopiv_unblocked(a):
    """Unblocked LU without pivoting on a square block via rank-1 updates
    (≅ tile-level getrf_nopiv; Tile_getrf_nopiv semantics)."""
    n = a.shape[-1]

    def body(k, m):
        col = m[:, k] / m[k, k]
        col = jnp.where(jnp.arange(n) > k, col, m[:, k])
        m = m.at[:, k].set(col)
        row_mask = (jnp.arange(n)[:, None] > k) & (jnp.arange(n)[None, :] > k)
        update = jnp.outer(col, m[k, :])
        return jnp.where(row_mask, m - update, m)

    return lax.fori_loop(0, n, body, a)


_LU_NOPIV_BASE = 128


def _lu_nopiv_blocked(a):
    """Recursive blocked LU without pivoting: factor the leading half, two
    triangular solves, one Schur-complement MXU gemm, recurse on the trailing
    half.  The unblocked rank-1 loop runs only at the <=128 base — at nb=2048
    the rank-1 form alone moves ~70 GB of HBM per block (2048 sweeps over a
    16 MB tile) and dominated the whole CALU factorization."""
    n = a.shape[-1]
    if n <= _LU_NOPIV_BASE:
        return _lu_nopiv_unblocked(a)
    h = n // 2
    a11, a12 = a[..., :h, :h], a[..., :h, h:]
    a21, a22 = a[..., h:, :h], a[..., h:, h:]
    f11 = _lu_nopiv_blocked(a11)
    u12 = lax.linalg.triangular_solve(f11, a12, left_side=True, lower=True,
                                      unit_diagonal=True)
    l21 = lax.linalg.triangular_solve(f11, a21, left_side=False, lower=False)
    s = a22 - jnp.matmul(l21, u12, precision=lax.Precision.HIGHEST)
    f22 = _lu_nopiv_blocked(s)
    return jnp.concatenate(
        [jnp.concatenate([f11, u12], axis=-1),
         jnp.concatenate([l21, f22], axis=-1)], axis=-2)


@lru_cache(maxsize=32)
def _getrf_nopiv_fn(m: int, n: int, nb: int, dtype_str: str):
    nt = -(-min(m, n) // nb)

    def fn(A):
        for k in range(nt):
            k0, k1 = k * nb, min((k + 1) * nb, min(m, n))
            blk = _lu_nopiv_blocked(A[k0:k1, k0:k1])
            A = A.at[k0:k1, k0:k1].set(blk)
            if k1 < m:
                L21 = lax.linalg.triangular_solve(
                    blk, A[k1:m, k0:k1], left_side=False, lower=False)  # X U = B
                A = A.at[k1:m, k0:k1].set(L21)
            if k1 < n:
                U12 = lax.linalg.triangular_solve(
                    blk, A[k0:k1, k1:n], left_side=True, lower=True,
                    unit_diagonal=True)
                A = A.at[k0:k1, k1:n].set(U12)
            if k1 < m and k1 < n:
                A = A.at[k1:m, k1:n].add(
                    -jnp.matmul(A[k1:m, k0:k1], A[k0:k1, k1:n],
                                precision=lax.Precision.HIGHEST))
        return A

    return jax.jit(fn)


def getrf_nopiv(A, opts=None):
    """LU without pivoting (src/getrf_nopiv.cc). Returns (LU, info)."""
    opts = Options.make(opts)
    a = inject("getrf_nopiv", as_array(A))
    m, n = a.shape[-2:]
    with trace_block("getrf_nopiv", m=m, n=n):
        out = _getrf_nopiv_fn(m, n, min(opts.block_size, m, n), str(a.dtype))(a)
    info = _lu_info(jnp.diagonal(out, axis1=-2, axis2=-1))
    return write_back(A, out), info


# ---------------------------------------------------------------------------
# partial-pivot getrf
# ---------------------------------------------------------------------------


@lru_cache(maxsize=32)
def _getrf_tiled_fn(m: int, n: int, nb: int, dtype_str: str):
    """Blocked right-looking partially-pivoted LU (getrf.cc task loop, software-
    pipelined for XLA)."""
    kmax = min(m, n)
    nt = -(-kmax // nb)

    def fn(A):
        perm = jnp.arange(m)
        for k in range(nt):
            k0, k1 = k * nb, min((k + 1) * nb, kmax)
            # --- panel (≅ internal::getrf_panel, getrf.cc:92-120) ---
            panel = A[k0:m, k0:k1]
            plu, _, pperm = lax.linalg.lu(panel)
            L_pan = jnp.tril(plu[:, : k1 - k0], -1)
            U_pan = jnp.triu(plu[: k1 - k0, :])
            # permute trailing + left columns and the global perm (row gather —
            # TPU-native permuteRows, internal_swap.cc analogue)
            gperm = jnp.concatenate([jnp.arange(k0), k0 + pperm])
            A = jnp.take(A, gperm, axis=0)
            perm = jnp.take(perm, gperm)
            A = A.at[k0:m, k0:k1].set(L_pan + jnp.pad(
                U_pan, ((0, m - k0 - (k1 - k0)), (0, 0))))
            if k1 < n:
                # row trsm (≅ lookahead/trailing trsm, getrf.cc:121-155)
                L11 = jnp.tril(plu[: k1 - k0, :], -1) + jnp.eye(
                    k1 - k0, dtype=A.dtype)
                U12 = lax.linalg.triangular_solve(
                    L11, A[k0:k1, k1:n], left_side=True, lower=True,
                    unit_diagonal=True)
                A = A.at[k0:k1, k1:n].set(U12)
                if k1 < m:
                    # trailing gemm — the hot loop (getrf.cc:173-230)
                    A = A.at[k1:m, k1:n].add(
                        -jnp.matmul(A[k1:m, k0:k1], U12,
                                    precision=lax.Precision.HIGHEST))
        return A, perm

    return jax.jit(fn)


@instrument
def getrf(A, opts=None):
    """Partially-pivoted LU: returns (LU, perm, info) with A[perm] = L U
    (src/getrf.cc:22-260; dispatch over MethodLU like gesv's select_algo).

    MethodLU.CALU routes to tournament pivoting (getrf_tntpiv), NoPiv to getrf_nopiv
    (perm = identity), RBT is reserved for gesv_rbt.
    """
    opts = Options.make(opts)
    # validated up front, on EVERY path: a typo'd lu_panel must raise, never
    # silently run the other panel scheme (parity-audit behavior contract)
    slate_assert(opts.lu_panel in ("tournament", "pp"),
                 f"lu_panel must be 'tournament' or 'pp', got {opts.lu_panel!r}")
    method = opts.method_lu
    if method == MethodLU.Auto:
        method = MethodLU.PartialPiv
    if method == MethodLU.NoPiv:
        lu_, info = getrf_nopiv(A, opts)
        return lu_, jnp.arange(as_array(A).shape[-2]), info
    if method == MethodLU.CALU:
        return getrf_tntpiv(A, opts)
    if method != MethodLU.PartialPiv:
        raise SlateError(f"unsupported MethodLU {method}")

    grid = distribution_grid(A)
    a_chk = inject("getrf", as_array(A))
    if grid is not None:
        # wrapper bound to a >1-device grid: tournament-pivoted distributed LU
        # (the mesh form of getrf_tntpiv; reference getrf.cc consumes the
        # construction-time distribution the same way).  Wide inputs factor the
        # leading square block + one sharded trsm; tall inputs ride the 1-D
        # TSLU (O(m n²/P); the round-2 square embedding and its m <= 2n
        # caller guard are gone).  Options.lu_panel reaches the mesh panel
        # too ("pp" = gathered partial-pivot panel, pivot.partialpiv_piv).
        from ..parallel import getrf_distributed

        lu_, perm, info = getrf_distributed(a_chk, grid, nb=opts.block_size,
                                            lu_panel=opts.lu_panel)
        write_back(A, lu_)
        return lu_, perm, info

    a = a_chk
    m, n = a.shape[-2:]
    target = opts.target
    if target == Target.Auto:
        target = Target.XLA
    with trace_block("getrf", m=m, n=n, target=str(target)):
        if target == Target.XLA:
            plu, _, perm = lax.linalg.lu(a)
            out = plu
        else:
            out, perm = _getrf_tiled_fn(m, n, min(opts.block_size, m, n),
                                        str(a.dtype))(a)
    info = _lu_info(jnp.diagonal(out, axis1=-2, axis2=-1))
    return write_back(A, out), perm, info


# ---------------------------------------------------------------------------
# tournament pivoting (CALU)
# ---------------------------------------------------------------------------


def _tournament_panel(panel, nb):
    """Select nb pivot rows of a tall panel by tournament (getrf_tntpiv.cc panel:
    block-local partially-pivoted LUs, then a binary reduction tree over winners;
    internal_getrf_tntpiv.cc / Tile_getrf_tntpiv.hh semantics, re-expressed as a
    static tree of lax.linalg.lu calls).

    Returns the winning global row indices (length min(nb, mp)).
    """
    mp, w = panel.shape
    k = min(nb, mp)
    nfull = mp // nb
    # uniform leaves (nb rows each) reduce as ONE batched LU per tree level —
    # TPU executes ops sequentially, so the reference's independent per-pair
    # merges must be a batch, not a Python loop of separate lu calls (this
    # halved the measured CALU time at the n=16384 bench config)
    if nfull >= 2:
        V = panel[: nfull * nb].reshape(nfull, nb, w)
        I = jnp.arange(nfull * nb).reshape(nfull, nb)
        while V.shape[0] > 1:
            nblk = V.shape[0]
            half = nblk // 2
            V2 = jnp.concatenate([V[0:2 * half:2], V[1:2 * half:2]], axis=1)
            I2 = jnp.concatenate([I[0:2 * half:2], I[1:2 * half:2]], axis=1)
            _, _, perm = lax.linalg.lu(V2)          # batched pair merges
            take = perm[:, :k]
            V2 = jnp.take_along_axis(V2, take[:, :, None], axis=1)
            I2 = jnp.take_along_axis(I2, take, axis=1)
            if nblk % 2:
                V2 = jnp.concatenate([V2, V[2 * half:][:, :k]], axis=0)
                I2 = jnp.concatenate([I2, I[2 * half:][:, :k]], axis=0)
            V, I = V2, I2
        sub, idx = V[0], I[0]
        ordered = True     # the last pair merge emitted winners in pivot order
    elif nfull == 1:
        sub, idx = panel[:nb], jnp.arange(nb)
        ordered = False
    else:
        sub, idx = panel, jnp.arange(mp)
        ordered = False
    rest = nfull * nb
    if rest and rest < mp:      # ragged tail block joins the final merge
        sub = jnp.concatenate([sub, panel[rest:]], axis=0)
        idx = jnp.concatenate([idx, jnp.arange(rest, mp)])
        ordered = False
    if not ordered:
        # root LU orders the winners (pivot order, reference's root merge);
        # redundant — and skipped — when the tree already ordered them
        _, _, perm = lax.linalg.lu(sub)
        idx = jnp.take(idx, perm[: min(k, sub.shape[0])])
    return idx[:k]


@lru_cache(maxsize=32)
def _getrf_tntpiv_fn(m: int, n: int, nb: int, ib: int, dtype_str: str,
                     panel_scheme: str = "tournament"):
    """Two-level CALU (getrf_tntpiv.cc:161-230 + its ib inner blocking).

    Tournament merge flops scale as (panel width)² per candidate row, so
    pivot selection runs on narrow ib-wide subpanels while the trailing
    update stays an nb-wide MXU gemm — the same nb/ib split the reference
    uses (Option::InnerBlocking), which took the n=16384 bench config from
    ~6.5 to the flat-panel tournament's missing third of peak.

    ``panel_scheme="pp"`` selects pivots with ONE partial-pivot LU of the
    ib-wide subpanel instead of the merge tree: the tournament's log2(m/ib)
    levels are each a column-sequential batched LU (~6 x ib sequential
    elimination steps per panel at the bench shape), while a single panel
    LU is ib steps — the selection quality of classic partial pivoting at a
    sixth of the sequential depth.  (The round-2 finding that fused
    lax.linalg.lu "does not finish" was for the FULL n-wide matrix, not an
    ib-wide panel.)"""
    kmax = min(m, n)
    nt = -(-kmax // nb)

    def inner_step(A, perm, c0, c1, upto):
        """Factor subpanel cols [c0,c1): tournament + dirty-row swap + nopiv
        block factor + L21, then update outer-panel cols [c1,upto) only."""
        w = c1 - c0
        panel = A[c0:m, c0:c1]
        if panel_scheme == "pp":
            # classic partial pivoting on the subpanel: the permutation's
            # first w entries are the rows the elimination promoted to the
            # top — exactly the pivot rows, discarding the factor
            _, _, pperm = lax.linalg.lu(panel)
            winners = pperm[:w]
        else:
            winners = _tournament_panel(panel, w)      # local indices into panel
        # dirty-rows-only exchange (permuteRows analogue): winners move to
        # the top w window slots and the displaced occupants fill the
        # vacated winner slots — ≤ 2w rows move, vs the full-matrix
        # compaction gather (4x the HBM traffic at the n=16384 bench)
        mw = m - c0
        ar = jnp.arange(mw)
        is_w = jnp.zeros(mw, dtype=bool).at[winners].set(True)
        big = mw + w                                   # OOB sentinel
        disp = jnp.sort(jnp.where(~is_w[:w], jnp.arange(w), big))
        vac = jnp.sort(jnp.where(is_w & (ar >= w), ar, big))[:w]
        # window permutation: identity, winners into [:w], displaced into
        # the vacated slots (slot i of vac pairs with slot i of disp —
        # their valid counts match by construction)
        gwin = ar.at[:w].set(winners).at[vac].set(disp, mode="drop")
        S = jnp.concatenate([c0 + jnp.arange(w), c0 + vac])      # dirty dst
        src = c0 + jnp.concatenate([winners, disp])              # their rows
        rows = A[jnp.clip(src, 0, m - 1)]
        A = A.at[S].set(rows, mode="drop")
        perm = jnp.take(perm, jnp.concatenate([jnp.arange(c0), c0 + gwin]))
        # nopiv factor of the permuted subpanel (pivots already chosen)
        blk = _lu_nopiv_blocked(A[c0:c1, c0:c1])
        A = A.at[c0:c1, c0:c1].set(blk)
        if c1 < m:
            L21 = lax.linalg.triangular_solve(
                blk, A[c1:m, c0:c1], left_side=False, lower=False)
            A = A.at[c1:m, c0:c1].set(L21)
        if c1 < upto:
            U12 = lax.linalg.triangular_solve(
                blk, A[c0:c1, c1:upto], left_side=True, lower=True,
                unit_diagonal=True)
            A = A.at[c0:c1, c1:upto].set(U12)
            if c1 < m:
                A = A.at[c1:m, c1:upto].add(
                    -jnp.matmul(A[c1:m, c0:c1], U12,
                                precision=lax.Precision.HIGHEST))
        return A, perm

    def fn(A):
        perm = jnp.arange(m)
        for k in range(nt):
            k0, k1 = k * nb, min((k + 1) * nb, kmax)
            # inner ib-wide tournament panels, updates confined to the outer
            # panel's columns
            for c0 in range(k0, k1, ib):
                c1 = min(c0 + ib, k1)
                A, perm = inner_step(A, perm, c0, c1, k1)
            if k1 < n:
                # outer row trsm against the panel's unit-lower factor (the
                # solve reads only the strict lower triangle) + the big
                # trailing MXU gemm (the hot loop, getrf.cc:173-230)
                U12 = lax.linalg.triangular_solve(
                    A[k0:k1, k0:k1], A[k0:k1, k1:n], left_side=True,
                    lower=True, unit_diagonal=True)
                A = A.at[k0:k1, k1:n].set(U12)
                if k1 < m:
                    A = A.at[k1:m, k1:n].add(
                        -jnp.matmul(A[k1:m, k0:k1], U12,
                                    precision=lax.Precision.HIGHEST))
        return A, perm

    return jax.jit(fn)


@instrument
def getrf_tntpiv(A, opts=None):
    """Tournament-pivoted (CALU) LU (src/getrf_tntpiv.cc:161-230).
    Returns (LU, perm, info)."""
    opts = Options.make(opts)
    a = inject("getrf_tntpiv", as_array(A))
    m, n = a.shape[-2:]
    nb = min(opts.block_size, m, n)
    ib = max(1, min(opts.inner_blocking, nb))
    slate_assert(opts.lu_panel in ("tournament", "pp"),
                 f"lu_panel must be 'tournament' or 'pp', got {opts.lu_panel!r}")
    with trace_block("getrf_tntpiv", m=m, n=n):
        out, perm = _getrf_tntpiv_fn(m, n, nb, ib, str(a.dtype),
                                     opts.lu_panel)(a)
    info = _lu_info(jnp.diagonal(out, axis1=-2, axis2=-1))
    return write_back(A, out), perm, info


# ---------------------------------------------------------------------------
# solves
# ---------------------------------------------------------------------------


def lu_factored_solve(plu, perm, rhs):
    """Permute rows + unit-lower solve + upper solve from a packed LU factor —
    the shared kernel of getrs, the *_mixed preconditioners, and gecondest."""
    pb = jnp.take(rhs, perm, axis=0) if perm is not None else rhs
    y = lax.linalg.triangular_solve(plu, pb, left_side=True, lower=True,
                                    unit_diagonal=True)
    return lax.linalg.triangular_solve(plu, y, left_side=True, lower=False)


def gesv_core(a, b):
    """Pure single-matrix gesv kernel: partially-pivoted LU + the two
    triangular sweeps, nothing else — no wrappers, no fault injection, no
    trace blocks, no host syncs.  This is the vmap-first core the batched
    serving layer (:mod:`slate_tpu.serve`) maps over a leading batch axis
    (``lax.linalg.lu`` batches natively, so ``jax.vmap(gesv_core)`` is one
    fused batched program).  Returns ``(x, perm, info)`` with a per-matrix
    LAPACK info from the U diagonal."""
    plu, _, perm = lax.linalg.lu(a)
    info = _lu_info(jnp.diagonal(plu, axis1=-2, axis2=-1))
    x = lu_factored_solve(plu, perm, b)
    return x, perm, info


def getrs(LU, perm, B, opts=None, trans=False):
    """Solve op(A) X = B from the LU factor (src/getrs.cc: permuteRows(Forward) +
    work::trsm(L) + work::trsm(U); here: one gather + two TriangularSolves).

    ``trans``: False/'n' solves A X = B; True/'t' solves A^T X = B; 'c' solves
    A^H X = B (the LAPACK trans codes)."""
    lu_ = as_array(LU)
    b = as_array(B)
    code = ({False: "n", True: "t"}.get(trans, trans) or "n")
    code = str(code).lower()[0]
    if code in ("t", "c"):
        conj = code == "c"
        # op(A) x = b  =>  U^op y = b; L^op z = y; x = perm^{-1} scatter
        y = lax.linalg.triangular_solve(lu_, b, left_side=True, lower=False,
                                        transpose_a=True, conjugate_a=conj)
        z = lax.linalg.triangular_solve(lu_, y, left_side=True, lower=True,
                                        unit_diagonal=True, transpose_a=True,
                                        conjugate_a=conj)
        x = jnp.zeros_like(z).at[perm].set(z) if perm is not None else z
        return write_back(B, x)
    return write_back(B, lu_factored_solve(lu_, perm, b))


def getrs_nopiv(LU, B, opts=None, trans=False):
    """Solve from a pivot-free LU factor (src/getrs_nopiv.cc): the two triangular
    sweeps with no row permutation."""
    return getrs(LU, None, B, opts, trans=trans)


@instrument
def gesv(A, B, opts=None):
    """Solve A X = B (src/gesv.cc = getrf + getrs).

    Returns (X, perm, info); with ``Options(solve_report=True)``,
    (X, perm, info, SolveReport)."""
    opts = Options.make(opts)
    lu_, perm, info = getrf(A, opts if not opts.solve_report
                            else opts.replace(solve_report=False))
    X = getrs(lu_, perm, B, opts)
    if opts.solve_report:
        report = SolveReport(routine="gesv", info=int(info),
                             precision_used=str(as_array(lu_).dtype),
                             fallback_chain=(str(opts.method_lu),)).finalize()
        report.recovered = report.info == 0
        return X, perm, info, report
    return X, perm, info


def gesv_nopiv(A, B, opts=None):
    """Solve A X = B without pivoting, escalating to partial pivoting on breakdown.

    The declared ladder (src/gesv_nopiv.cc +
    robust.LADDERS["gesv_nopiv"]): a nopiv breakdown (zero pivot, info > 0,
    or non-finite X) re-solves with partial pivoting from the *pristine*
    operand when Option::UseFallbackSolver holds — the recovery the reference
    leaves to the caller.  Detecting the breakdown costs one host sync (a
    fused info+isfinite verdict, trivial next to the O(n³) factor); pipelined
    callers who want the old zero-sync alias pass
    ``Options(use_fallback_solver=False)``.  Returns (X, perm, info); with
    ``Options(solve_report=True)``, (X, perm, info, SolveReport)."""
    opts = Options.make(opts)
    base = opts.replace(method_lu="nopiv", solve_report=False)
    from ..robust import active

    if (not opts.use_fallback_solver and not opts.solve_report
            and opts.max_retries <= 0 and active() is None):
        # single-rung ladder with nothing to observe it: the ok verdict could
        # never trigger an escalation, so skip the ladder machinery and its
        # host sync + isfinite reduction — the original zero-sync alias
        return gesv(A, B, base)
    a0, b0 = as_array(A), as_array(B)   # immutable snapshots: rungs re-solve
    #                                     from intact inputs, never a half-
    #                                     written factor
    report = SolveReport(routine="gesv_nopiv") if opts.solve_report else None
    policy = RetryPolicy.from_options(opts, "gesv_nopiv")

    def _operand():
        # a Matrix wrapper keeps its in-place factor write-back: restore the
        # pristine operand first (a prior rung left ITS factor in the
        # wrapper), then let gesv factor the wrapper itself.  Plain arrays
        # just use the snapshot.
        if isinstance(A, BaseMatrix):
            write_back(A, a0)
            return A
        return a0

    def nopiv_rung():
        out = gesv(_operand(), b0, base)
        ok = bool((out[2] == 0) & jnp.all(jnp.isfinite(as_array(out[0]))))
        return out, ok

    def pp_rung():
        out = gesv(_operand(), b0, base.replace(method_lu="partialpiv"))
        return out, bool(out[2] == 0)

    rungs = [Rung("nopiv", nopiv_rung)]
    if opts.use_fallback_solver:
        rungs.append(Rung("partialpiv", pp_rung))
    X, perm, info = run_ladder("gesv_nopiv", rungs, policy, report)
    X = write_back(B, as_array(X))
    if report is not None:
        report.info = int(info)
        report.precision_used = str(a0.dtype)
        return X, perm, info, report.finalize()
    return X, perm, info


def getri(LU, perm, opts=None):
    """Inverse from the LU factor (src/getri.cc): solves A X = I against the
    factored (LU, perm) pair from getrf, writing the inverse back over the
    factor — the reference's in-place contract."""
    lu_ = as_array(LU)
    n = lu_.shape[-1]
    X = getrs(lu_, perm, jnp.eye(n, dtype=lu_.dtype), opts)
    return write_back(LU, X)


def getri_oop(LU, perm, B, opts=None):
    """Out-of-place inverse (src/getriOOP.cc): writes A^{-1} into B from the
    factored (LU, perm) pair, leaving the factor intact for reuse."""
    lu_ = as_array(LU)
    n = lu_.shape[-1]
    X = getrs(lu_, perm, jnp.eye(n, dtype=lu_.dtype), opts)
    return write_back(B, X)


# ---------------------------------------------------------------------------
# mixed precision + GMRES-IR
# ---------------------------------------------------------------------------


@instrument
def gesv_mixed(A, B, opts=None):
    """Low-precision LU factor + working-precision iterative refinement
    (src/gesv_mixed.cc:23-40,106+), run as the declared mixed→full escalation
    ladder (robust.LADDERS["gesv_mixed"]; Option::UseFallbackSolver gates the
    second rung, gesv_mixed.cc:93-96).  Returns (X, perm, info, iters); with
    ``Options(solve_report=True)``, (..., SolveReport)."""
    from .chol import _lower_precision

    opts = Options.make(opts)
    a0 = as_array(A)        # pristine snapshot: each rung re-enters the input
    #                         injection site, so a call_index=0 input fault is
    #                         transient under escalation (the ladder recovers
    #                         from intact data, never a corrupted copy)
    b = as_array(B)
    plain = opts.replace(solve_report=False)
    lo = opts.factor_precision or _lower_precision(a0.dtype)
    report = SolveReport(routine="gesv_mixed") if opts.solve_report else None
    if lo is None:
        a_in = inject("gesv_mixed", a0)
        # no fault fired → pass the original operand through, so a Matrix
        # wrapper keeps its in-place factor write-back (pre-ladder contract)
        src = A if (a_in is a0 and isinstance(A, BaseMatrix)) else a_in
        X, perm, info = gesv(src, b, plain)
        X = write_back(B, as_array(X))
        if report is not None:
            report.record_rung("full")
            report.info, report.precision_used = int(info), str(a0.dtype)
            report.recovered = report.info == 0
            return X, perm, info, jnp.int32(0), report.finalize()
        return X, perm, info, jnp.int32(0)

    state = {"iters": jnp.int32(0)}

    def mixed_rung():
        a = inject("gesv_mixed", a0)
        with trace_block("gesv_mixed", lo=str(lo)):
            plu, _, perm = lax.linalg.lu(a.astype(lo))
            plu = inject("gesv_mixed", plu, point="factor")
            info = _lu_info(jnp.diagonal(plu, axis1=-2, axis2=-1))

            def solve_lo(rhs):
                return lu_factored_solve(plu, perm, rhs.astype(lo))

            x, iters, converged = _ir_solve(a, b, solve_lo, opts)
        state["iters"] = iters
        return (x, perm, info), bool(converged)

    def full_rung():
        a_in = inject("gesv_mixed", a0)
        # no fault fired → original wrapper through, preserving its in-place
        # factor write-back (the mixed rung never touched its storage)
        src = A if (a_in is a0 and isinstance(A, BaseMatrix)) else a_in
        X, perm, info = gesv(src, b, plain)
        return (as_array(X), perm, info), bool(info == 0)

    rungs = [Rung("mixed", mixed_rung)]
    if opts.use_fallback_solver:
        rungs.append(Rung("full", full_rung))
    x, perm, info = run_ladder("gesv_mixed", rungs,
                               RetryPolicy.from_options(opts, "gesv_mixed"),
                               report)
    X = write_back(B, x)
    if report is not None:
        report.info = int(info)
        report.iters = int(state["iters"])
        report.precision_used = (str(jnp.dtype(lo)) if report.fallback_chain
                                 == ("mixed",) else str(a0.dtype))
        return X, perm, info, state["iters"], report.finalize()
    return X, perm, info, state["iters"]


def _fgmres(matvec, precond, b, x0, restart, tol, max_restarts):
    """Restarted FGMRES with right preconditioning (src/gesv_mixed_gmres.cc uses
    GMRES-IR the same way).  The restart loop is a ``lax.while_loop`` with an
    on-device convergence test — no per-restart host sync (round-1 review: the
    ``float()`` in the old loop blocked dispatch every cycle); a NaN residual
    fails the ``resid > tol`` predicate and exits, preserving the NaN-safe
    fallback verdict."""

    def cycle(x):
        r = b - matvec(x)
        beta = jnp.linalg.norm(r)
        V = jnp.zeros((restart + 1,) + b.shape, dtype=b.dtype)
        Z = jnp.zeros((restart,) + b.shape, dtype=b.dtype)
        H = jnp.zeros((restart + 1, restart), dtype=b.dtype)
        V = V.at[0].set(r / jnp.where(beta == 0, 1, beta))
        for j in range(restart):       # static unroll: Krylov dim is small
            z = precond(V[j])
            w = matvec(z)
            # modified Gram-Schmidt
            for i in range(j + 1):
                hij = jnp.vdot(V[i], w)
                H = H.at[i, j].set(hij)
                w = w - hij * V[i]
            hn = jnp.linalg.norm(w)
            H = H.at[j + 1, j].set(hn)
            V = V.at[j + 1].set(w / jnp.where(hn == 0, 1, hn))
            Z = Z.at[j].set(z)
        # least squares min ||beta e1 - H y||
        e1 = jnp.zeros(restart + 1, dtype=b.dtype).at[0].set(beta)
        y, *_ = jnp.linalg.lstsq(H, e1)
        return x + jnp.tensordot(y, Z, axes=1)

    tol = jnp.asarray(tol, jnp.real(b).dtype)

    def cond(carry):
        x, restarts, resid = carry
        return (resid > tol) & (restarts < max_restarts)

    def body(carry):
        x, restarts, _ = carry
        x = cycle(x)
        return x, restarts + 1, jnp.linalg.norm(b - matvec(x))

    x, restarts, _ = lax.while_loop(
        cond, body, (x0, jnp.int32(0), jnp.linalg.norm(b - matvec(x0))))
    return x, restarts


def _require_single_rhs(b, routine: str):
    """GMRES-IR drivers take one RHS like the reference — enforced up front, for
    every dtype, so the contract doesn't depend on whether a lower precision
    exists."""
    if b.ndim != 1 and b.shape[-1] != 1:
        raise SlateError(f"{routine} supports a single RHS (matches reference)")


def _gmres_ir(matvec, precond, b, opts, routine: str):
    """Shared GMRES-IR body for gesv_mixed_gmres / posv_mixed_gmres: tolerance,
    restarted FGMRES, NaN-safe convergence verdict.
    Returns (x shaped like b, restarts, converged)."""
    squeeze = b.ndim == 1
    _require_single_rhs(b, routine)
    bv = b.reshape(-1) if not squeeze else b
    n = bv.shape[0]
    eps = jnp.finfo(jnp.real(bv).dtype).eps
    # tolerance stays traced: the whole GMRES-IR (restart loop included — it
    # is a lax.while_loop in _fgmres) dispatches with zero device→host round
    # trips; callers sync exactly once on the returned verdict
    tol = jnp.asarray(
        opts.tolerance if opts.tolerance is not None
        else float(eps) * (n ** 0.5),
        jnp.real(bv).dtype) * jnp.linalg.norm(bv)
    x, restarts = _fgmres(matvec, precond, bv, precond(bv), restart=min(30, n),
                          tol=tol, max_restarts=opts.max_iterations // 10 + 1)
    resid = jnp.linalg.norm(bv - matvec(x))
    converged = resid <= tol * 10        # NaN residual fails this, forcing fallback
    return (x if squeeze else x[:, None]), restarts, converged


@instrument
def gesv_mixed_gmres(A, B, opts=None):
    """GMRES-IR: FGMRES in working precision, right-preconditioned by the
    low-precision LU solve (src/gesv_mixed_gmres.cc). Single-RHS path like the
    reference (it restricts to nrhs == 1). Returns (X, perm, info, iters)."""
    from .chol import _lower_precision

    opts = Options.make(opts)
    a = as_array(A)
    b = as_array(B)
    _require_single_rhs(b, "gesv_mixed_gmres")
    lo = opts.factor_precision or _lower_precision(a.dtype)
    if lo is None:
        # solve_report stays off here: gesv would otherwise append a report
        # and break this 3-way unpack (gesv_mixed_gmres has no report form)
        X, perm, info = gesv(A, B, opts.replace(solve_report=False))
        return X, perm, info, jnp.int32(0)

    with trace_block("gesv_mixed_gmres", lo=str(lo)):
        plu, _, perm = lax.linalg.lu(a.astype(lo))
        info = _lu_info(jnp.diagonal(plu, axis1=-2, axis2=-1))

        def precond(r):
            z = lu_factored_solve(plu, perm, r.astype(lo)[:, None])
            return z[:, 0].astype(b.dtype)

        def matvec(x):
            return jnp.matmul(a, x, precision=lax.Precision.HIGHEST)

        x_out, restarts, converged = _gmres_ir(matvec, precond, b, opts,
                                               "gesv_mixed_gmres")

    if opts.use_fallback_solver and not converged:
        # mixed_gmres→full ladder (robust.LADDERS) — open-coded because the
        # GMRES machinery already returned its verdict; event keeps the
        # escalation visible in the chrome trace
        trace_event("fallback", routine="gesv_mixed_gmres", to="full")
        X, perm, info = gesv(A, B, opts.replace(solve_report=False))
        return X, perm, info, jnp.int32(-1)
    return write_back(B, x_out), perm, info, jnp.int32(restarts)


# ---------------------------------------------------------------------------
# random butterfly transform (RBT)
# ---------------------------------------------------------------------------


def rbt_generate(key, n, depth, dtype):
    """Generate the diagonals of a depth-d recursive butterfly transform
    (src/internal/internal_gerbt.cc rbt_generate; matgen random signs).

    Each level d has a diagonal of exp(r/10)-distributed entries like the classic
    RBT construction; returns [depth, n] array of diagonal values.
    """
    r = jax.random.uniform(key, (depth, n), minval=-0.5, maxval=0.5)
    return jnp.exp(r / 10.0).astype(dtype)


def _butterfly_apply(W, x, transpose=False):
    """Apply the depth-d butterfly U (or U^T) to the leading axis of x.

    One level on a vector v of length 2h: with diagonals (r1, r2):
        B v = [r1*v1 + r2*v2, r1*v1 - r2*v2] / sqrt(2)
    Levels nest recursively on halves (gerbt.cc applies tile-wise; here the
    recursion is expressed with reshapes so XLA fuses it into a few elementwise ops).
    """
    depth, n = W.shape
    levels = range(depth - 1, -1, -1) if transpose else range(depth)
    y = x
    for d in levels:
        nblk = 2 ** (depth - 1 - d)
        h = n // (2 * nblk)
        r = W[d] / jnp.sqrt(jnp.asarray(2.0, x.dtype))
        shape = (nblk, 2, h) + x.shape[1:]
        yv = y.reshape(shape)
        rv = r.reshape(nblk, 2, h)
        rv = rv.reshape(rv.shape + (1,) * (x.ndim - 1))
        if not transpose:
            a = rv[:, 0] * yv[:, 0]
            bpart = rv[:, 1] * yv[:, 1]
            top, bot = a + bpart, a - bpart
        else:
            # B^T w: v1 = r1*(w1 + w2), v2 = r2*(w1 - w2)
            top = rv[:, 0] * (yv[:, 0] + yv[:, 1])
            bot = rv[:, 1] * (yv[:, 0] - yv[:, 1])
        y = jnp.stack([top, bot], axis=1).reshape(x.shape)
    return y


def gerbt(Wu, Wv, A):
    """Two-sided butterfly transform A' = U^T A V (src/gerbt.cc)."""
    a = as_array(A)
    a1 = _butterfly_apply(Wu, a, transpose=True)
    a2 = _butterfly_apply(Wv, a1.T, transpose=True).T
    return write_back(A, a2)


@instrument
def gesv_rbt(A, B, opts=None, key=None):
    """Solve via random butterfly transform + nopiv LU + refinement
    (src/gesv_rbt.cc:94-172), run as the declared RBT→partial-pivot
    escalation ladder (robust.LADDERS["gesv_rbt"]): when the butterfly fails
    to tame the matrix (nopiv breakdown or IR stall) the pivoted solve takes
    over from the pristine operand.  Returns (X, info, iters); with
    ``Options(solve_report=True)``, (X, info, iters, SolveReport)."""
    opts = Options.make(opts)
    a0 = as_array(A)        # pristine snapshot: each rung re-enters the input
    #                         injection site (transient-fault contract; the
    #                         pivoted escalation really does take over from
    #                         intact data, as the docstring promises)
    b = as_array(B)
    grid = distribution_grid(A)
    if grid is not None:
        # construction-time grid: the sharded butterfly + nopiv-LU + IR path
        # (parallel/rbt.py), like every other driver's grid dispatch
        from ..parallel.rbt import gesv_rbt_distributed

        X, info, iters, via_rbt = gesv_rbt_distributed(
            inject("gesv_rbt", a0), b, grid, depth=opts.depth,
            nb=min(opts.block_size, a0.shape[-1]), key=key,
            max_iterations=opts.max_iterations,
            use_fallback=opts.use_fallback_solver, tol=opts.tolerance)
        X = write_back(B, X)
        if opts.solve_report:
            chain = ("rbt",) if via_rbt else ("rbt", "partialpiv")
            report = SolveReport(routine="gesv_rbt", info=int(info),
                                 iters=int(iters),
                                 precision_used=str(a0.dtype),
                                 fallback_chain=chain).finalize()
            report.recovered = report.info == 0 and (
                via_rbt or opts.use_fallback_solver)
            return X, info, iters, report
        return X, info, iters
    n = a0.shape[-1]
    depth = opts.depth
    # pad n to a multiple of 2^depth for the butterfly recursion
    pad = (-n) % (2 ** depth)
    key = key if key is not None else jax.random.PRNGKey(42)
    ku, kv = jax.random.split(key)
    np_ = n + pad
    plain = opts.replace(solve_report=False)
    report = SolveReport(routine="gesv_rbt") if opts.solve_report else None
    state = {"iters": jnp.int32(0)}

    def rbt_rung():
        a = inject("gesv_rbt", a0)
        Wu = rbt_generate(ku, np_, depth, a.dtype)
        Wv = rbt_generate(kv, np_, depth, a.dtype)
        ap = jnp.pad(a, ((0, pad), (0, pad)))
        if pad:
            ap = ap.at[jnp.arange(n, np_), jnp.arange(n, np_)].set(1)
        with trace_block("gesv_rbt", n=n, depth=depth):
            at = _butterfly_apply(Wu, ap, transpose=True)
            at = _butterfly_apply(Wv, at.T, transpose=True).T
            lu_p, info = getrf_nopiv(at, plain)
            lu_p = inject("gesv_rbt", lu_p, point="factor")

            def solve_rbt(rhs):
                rp = jnp.pad(rhs, ((0, pad),) + ((0, 0),) * (rhs.ndim - 1))
                y = _butterfly_apply(Wu, rp, transpose=True)
                z = lax.linalg.triangular_solve(lu_p, y, left_side=True,
                                                lower=True, unit_diagonal=True)
                w = lax.linalg.triangular_solve(lu_p, z, left_side=True,
                                                lower=False)
                x = _butterfly_apply(Wv, w, transpose=False)
                return x[:n]

            x, iters, converged = _ir_solve(a, b, solve_rbt, opts)
        state["iters"] = iters
        return (x, info), bool(converged)

    def pp_rung():
        a_in = inject("gesv_rbt", a0)
        # no fault fired → original wrapper through, preserving its in-place
        # factor write-back (the rbt rung factors a transformed copy only)
        src = A if (a_in is a0 and isinstance(A, BaseMatrix)) else a_in
        X, _, info = gesv(src, b, plain)
        return (as_array(X), info), bool(info == 0)

    rungs = [Rung("rbt", rbt_rung)]
    if opts.use_fallback_solver:
        rungs.append(Rung("partialpiv", pp_rung))
    x, info = run_ladder("gesv_rbt", rungs,
                         RetryPolicy.from_options(opts, "gesv_rbt"), report)
    X = write_back(B, x)
    if report is not None:
        report.info = int(info)
        report.iters = int(state["iters"])
        report.precision_used = str(a0.dtype)
        return X, info, state["iters"], report.finalize()
    return X, info, state["iters"]
