"""QR/LQ factorizations and least squares: geqrf / gelqf / unmqr / unmlq / tsqr (CAQR)
/ cholqr / gels.

Reference analogue (SURVEY.md §2.4 QR/LS row): ``src/geqrf.cc`` (CAQR: multithreaded
Householder panel internal_geqrf.cc + triangle-triangle tree reduction
internal_ttqrt.cc), ``src/gelqf.cc``, ``src/{unmqr,unmlq}.cc``, ``src/cholqr.cc``,
``src/{gels,gels_qr,gels_cholqr}.cc``; ``TriangularFactors`` is the reference's
``vector<Matrix>`` of block-reflector T factors (slate.hh:857).

TPU re-design:

* **Panel QR** is ``jnp.linalg.qr(mode='raw')`` — XLA's native Householder
  factorization returning the packed V + tau form (the per-tile geqrf of
  Tile_geqrf.hh).
* **Block reflector T** (the reference accumulates it column-by-column in the panel
  loop, internal_geqrf.cc:79-124) is computed *in closed form*: with V the unit lower
  trapezoid and S = V^H V, orthogonality of Q = I - V T V^H forces
  T^{-1} + T^{-H} = S, so ``T = inv(triu(S, 1) + diag(1/tau))`` — one gemm plus one
  k x k triangular solve, fully MXU-parallel instead of a length-k recurrence.
* **Applying Q** (unmqr/unmlq; reference replays the panel+tree tasks in reverse,
  unmqr.cc + internal_ttmqr.cc) is three gemms: Q^H C = C - V (T^H (V^H C)).
* **TSQR/CAQR tree** (ttqrt's triangle-triangle reduction over mesh rows) is
  ``tsqr``: leaf QRs over row blocks + a binary tree of stacked-R QRs; the Q factor
  is reconstructed down the tree.  This is the communication-avoiding shape that
  rides a mesh axis all-gather (distributed form lives in parallel/).
* **CholQR** (cholqr.cc; MethodCholQR Herk/Gemm variants for the Gram matrix) with
  the CholeskyQR2 re-orthogonalization pass and a shifted retry when the Gram matrix
  is numerically indefinite (the reference falls back to QR inside gels_cholqr).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..core.exceptions import SlateError
from ..core.matrix import BaseMatrix, as_array, distribution_grid, write_back
from ..core.types import MethodGels, Op, Options, Side
from ..robust import inject
from ..utils.trace import trace_block
from ..ops.blas3 import gram
from .chol import _chol_blocked, _chol_info
from ..obs import instrument


@dataclasses.dataclass
class TriangularFactors:
    """Block-Householder factors (reference TriangularFactors, slate.hh:857):
    ``packed`` holds R in the upper triangle and the reflector columns V below the
    diagonal (LAPACK geqrf layout); ``tau`` the reflector scalars; ``T`` the k x k
    block-reflector triangle."""

    packed: jax.Array   # (m, k)
    tau: jax.Array      # (k,)
    T: jax.Array        # (k, k) upper triangular

    @property
    def m(self):
        return self.packed.shape[-2]

    @property
    def k(self):
        return self.tau.shape[-1]

    def V(self) -> jax.Array:
        """Unit lower-trapezoid reflector matrix."""
        k = self.k
        V = jnp.tril(self.packed, -1)[..., :, :k]
        idx = jnp.arange(k)
        return V.at[..., idx, idx].set(jnp.ones((), self.packed.dtype))

    def Q(self, full: bool = False) -> jax.Array:
        """Materialize the (reduced) orthogonal factor via householder_product."""
        if not full:
            return lax.linalg.householder_product(self.packed, self.tau)
        m, k = self.m, self.k
        pad = jnp.zeros((m, m - k), dtype=self.packed.dtype)
        packed_f = jnp.concatenate([self.packed, pad], axis=-1)
        tau_f = jnp.concatenate([self.tau, jnp.zeros((m - k,), self.tau.dtype)])
        return lax.linalg.householder_product(packed_f, tau_f)

    def R(self) -> jax.Array:
        return jnp.triu(self.packed[..., : self.k, :])


def _block_T(V, tau):
    """Closed-form block-reflector triangle: T = inv(triu(S,1) + diag(1/tau)),
    S = V^H V (see module docstring)."""
    S = jnp.matmul(jnp.conj(jnp.swapaxes(V, -1, -2)), V,
                   precision=lax.Precision.HIGHEST)
    k = tau.shape[-1]
    inv_tau = jnp.where(tau == 0, jnp.inf, 1.0 / tau)
    Tinv = jnp.triu(S, 1) + jnp.zeros_like(S).at[..., jnp.arange(k), jnp.arange(k)
                                                 ].set(inv_tau)
    eye = jnp.eye(k, dtype=V.dtype)
    T = lax.linalg.triangular_solve(Tinv, eye, left_side=True, lower=False)
    # zero columns where tau == 0 (identity reflectors contribute nothing)
    return jnp.where(tau[..., None, :] == 0, 0, T)


@instrument
def geqrf(A, opts=None):
    """QR factorization A = Q R (src/geqrf.cc). Returns TriangularFactors; writes the
    packed factor back into a Matrix wrapper (R in the upper triangle, V below)."""
    opts = Options.make(opts)
    a = inject("geqrf", as_array(A))
    m, n = a.shape[-2:]
    k = min(m, n)
    with trace_block("geqrf", m=m, n=n):
        h, tau = jnp.linalg.qr(a, mode="raw")
        packed = jnp.swapaxes(h, -1, -2)  # numpy raw convention is transposed
        fac = TriangularFactors(packed=packed[..., :, :], tau=tau,
                                T=None)  # type: ignore[arg-type]
        V = jnp.tril(packed[..., :, :k], -1).at[..., jnp.arange(k), jnp.arange(k)
                                                ].set(jnp.ones((), a.dtype))
        fac.T = _block_T(V, tau)
    write_back(A, packed) if isinstance(A, BaseMatrix) else None
    return fac


@instrument
def gelqf(A, opts=None):
    """LQ factorization A = L Q (src/gelqf.cc) via QR of A^H: A^H = Q1 R1 =>
    A = R1^H Q1^H. Returns TriangularFactors of A^H."""
    a = as_array(A)
    fac = geqrf(jnp.conj(jnp.swapaxes(a, -1, -2)), opts)
    if isinstance(A, BaseMatrix):
        write_back(A, jnp.conj(jnp.swapaxes(fac.packed, -1, -2)))
    return fac


def unmqr(side, op, factors: TriangularFactors, C, opts=None):
    """Multiply by Q from geqrf (src/unmqr.cc): C := op(Q) C or C op(Q) using the
    compact WY form, Q = I - V T V^H."""
    side = Side.from_string(side)
    op = Op.from_string(op)
    V = factors.V()
    T = factors.T
    c = as_array(C)
    if op == Op.Trans and jnp.iscomplexobj(c):
        # LAPACK unmqr likewise rejects plain transpose for complex factors
        raise SlateError("unmqr: Op.Trans unsupported for complex; use ConjTrans")
    Tm = T if op == Op.NoTrans else jnp.conj(jnp.swapaxes(T, -1, -2))
    with trace_block("unmqr"):
        if side == Side.Left:
            # op(Q) C = C - V op(T) (V^H C)
            W = jnp.matmul(jnp.conj(jnp.swapaxes(V, -1, -2)), c,
                           precision=lax.Precision.HIGHEST)
            out = c - jnp.matmul(V, jnp.matmul(Tm, W),
                                 precision=lax.Precision.HIGHEST)
        else:
            # C op(Q) = C - (C V) op(T) V^H
            W = jnp.matmul(c, V, precision=lax.Precision.HIGHEST)
            out = c - jnp.matmul(jnp.matmul(W, Tm),
                                 jnp.conj(jnp.swapaxes(V, -1, -2)),
                                 precision=lax.Precision.HIGHEST)
    return write_back(C, out)


def unmlq(side, op, factors: TriangularFactors, C, opts=None):
    """Multiply by Q from gelqf (src/unmlq.cc). With A = L Q, Q = Q1^H where Q1 is
    the QR factor of A^H, so op(Q) flips the op on Q1."""
    op = Op.from_string(op)
    if op == Op.Trans and jnp.iscomplexobj(factors.packed):
        raise SlateError("unmlq: Op.Trans unsupported for complex; use ConjTrans")
    flip = {Op.NoTrans: Op.ConjTrans, Op.ConjTrans: Op.NoTrans,
            Op.Trans: Op.NoTrans}[op]
    return unmqr(side, flip, factors, C, opts)


# ---------------------------------------------------------------------------
# TSQR / CAQR tree
# ---------------------------------------------------------------------------


def tsqr(a, row_blocks: int = 0, nb: int = 1024):
    """Tall-skinny QR by binary tree reduction (the CAQR pattern of
    internal_ttqrt.cc: leaf QRs + pairwise triangle-triangle QRs up the tree).

    Returns (Q, R) with Q explicit reduced (m x n).  The distributed version runs the
    same tree over a mesh axis (parallel/).
    """
    m, n = a.shape[-2:]
    if row_blocks <= 0:
        row_blocks = max(1, min(m // max(n, 1), -(-m // nb)))
    if row_blocks <= 1 or m < 2 * n:
        return lax.linalg.qr(a, full_matrices=False)

    # split into row blocks (pad to equal size)
    bs = -(-m // row_blocks)
    pad = bs * row_blocks - m
    ap = jnp.pad(a, ((0, pad), (0, 0)))
    blocks = ap.reshape(row_blocks, bs, n)
    # leaf QRs, batched
    Qs, Rs = lax.linalg.qr(blocks, full_matrices=False)
    levels = [Qs]  # per-level Q stacks
    while Rs.shape[0] > 1:
        nblk = Rs.shape[0]
        if nblk % 2 == 1:
            Rs = jnp.concatenate([Rs, jnp.zeros((1, n, n), Rs.dtype)], axis=0)
            nblk += 1
        paired = Rs.reshape(nblk // 2, 2 * n, n)
        Qp, Rs = lax.linalg.qr(paired, full_matrices=False)
        levels.append(Qp)
    R = Rs[0]
    # reconstruct Q down the tree: start from the root's identity coupling
    Qacc = jnp.eye(n, dtype=a.dtype)[None]          # (1, n, n)
    for Qp in reversed(levels[1:]):
        npair = Qp.shape[0]
        # each pair contributes two n-row slices of Q
        Qfull = jnp.matmul(Qp, Qacc[:npair])        # (npair, 2n, n)
        Qacc = Qfull.reshape(npair * 2, n, n)
    Qacc = Qacc[: levels[0].shape[0]]
    Q = jnp.matmul(levels[0], Qacc).reshape(row_blocks * bs, n)[:m]
    return Q, R


@instrument
def cholqr(A, opts=None):
    """Cholesky QR (src/cholqr.cc): R = chol(A^H A)^H upper, Q = A R^{-1}, with a
    CholeskyQR2 second pass for orthogonality and a shifted retry if the Gram matrix
    is numerically indefinite. Returns (Q, R).

    The cholqr→shifted→Householder escalation is an IN-TRACE ladder
    (``lax.cond`` chain, declared in robust.LADDERS["cholqr"]): hoisting it
    to the host would cost a sync per call, so unlike the mixed-precision
    ladders it stays inside the jitted program."""
    opts = Options.make(opts)
    a = inject("cholqr", as_array(A))
    m, n = a.shape[-2:]

    def q_from_chol(L, x):
        # Q = x · L^{-H} via inverting the small n×n triangle and one MXU gemm.
        # A right-side blocked TriangularSolve over the tall x materializes
        # O(m·n) temps per column block inside XLA — it OOMs a single chip at
        # the BASELINE 131072×4096 config — while the inverse is n×n and the
        # product is a single (m,n)·(n,n) matmul (the trtri+gemm trsm shape).
        # CholeskyQR2's second pass absorbs the extra rounding of the explicit
        # inverse.
        eye = jnp.broadcast_to(jnp.eye(n, dtype=L.dtype), L.shape)
        Linv = lax.linalg.triangular_solve(L, eye, left_side=True, lower=True)
        W = jnp.conj(jnp.swapaxes(Linv, -1, -2))    # L^{-H}, upper
        return jnp.matmul(x, W, precision=lax.Precision.HIGHEST)

    def one_pass(x):
        # herk-halved Gram + recursive blocked factor of the n x n result
        # (the fused XLA Cholesky serializes at large n, BENCH_NOTES.md)
        G = gram(x)
        L = _chol_blocked(G)
        info = _chol_info(L)
        return q_from_chol(L, x), jnp.conj(jnp.swapaxes(L, -1, -2)), info

    def shifted_pass(x):
        # shifted retry (stabilized CholeskyQR): shift Gram by ~11(mn+n^2) eps ||A||^2
        eps = jnp.finfo(x.dtype).eps
        shift = 11.0 * (m * n + n * (n + 1)) * eps * (jnp.linalg.norm(x) ** 2)
        G = gram(x) + shift * jnp.eye(n, dtype=x.dtype)
        L = _chol_blocked(G)
        return q_from_chol(L, x), jnp.conj(L.T)

    with trace_block("cholqr", m=m, n=n):
        # fully traceable (no host syncs): failure branches route through
        # lax.cond, so cholqr composes under jit/vmap and never blocks dispatch
        Q1, R1, info = one_pass(a)
        Q1, R1 = lax.cond(info != 0, lambda _: shifted_pass(a),
                          lambda _: (Q1, R1), None)
        # CholeskyQR2: re-orthogonalize
        Q2, R2, info2 = one_pass(Q1)
        R = jnp.matmul(R2, R1, precision=lax.Precision.HIGHEST)
        # rank-deficient input: the Gram route cannot recover — fall back to
        # Householder QR (the reference's MethodCholQR -> MethodGels::QR
        # fallback); lax.cond executes only the taken branch
        Q, R = lax.cond(info2 != 0,
                        lambda _: lax.linalg.qr(a, full_matrices=False),
                        lambda _: (Q2, R), None)
    return Q, R


def _gels_csne(a, b):
    """Overdetermined least squares by corrected semi-normal equations
    (Björck's CSNE — the TPU-fit form of the reference's CholQR least squares,
    src/gels_cholqr.cc): R^H R x = A^H b with R from Cholesky of the Gram
    matrix, plus one refinement step x += (R^H R)^{-1} A^H (b - A x).

    Redesign note: the reference materializes the tall Q = A R^{-1} and
    applies Q^H to B.  On TPU that right-side triangular solve over the tall
    operand is the memory hot spot (XLA materializes O(m·n) temps per column
    block — it OOMs one chip at the BASELINE 131072×4096 config), and Q is
    never needed again.  CSNE keeps the whole job as one Gram matmul plus thin
    mat-vecs — pure MXU work, O(n²) extra memory — and the corrected step
    restores the accuracy the squared condition number costs, to the same
    envelope as the reference's CholQR path (which squares cond(A) in R too).
    Rank-deficient or borderline-conditioned inputs (Cholesky of the Gram
    fails, or the solve produces non-finite values) fall back to Householder
    QR inside the jitted program (lax.cond), mirroring the MethodCholQR -> QR
    fallback — and Householder is the accurate choice exactly when the
    squared-Gram route is in trouble, so no shifted retry is attempted here.
    """
    ah = jnp.conj(jnp.swapaxes(a, -1, -2))
    # herk-halved Gram (the dominant 2mn^2 of the whole job) + recursive
    # blocked factor (the fused XLA Cholesky serializes at large n)
    G = gram(a)
    w = jnp.matmul(ah, b, precision=lax.Precision.HIGHEST)
    L = _chol_blocked(G)
    info = _chol_info(L)

    def normal_solve(rhs):
        y = lax.linalg.triangular_solve(L, rhs, left_side=True, lower=True)
        return lax.linalg.triangular_solve(L, y, left_side=True, lower=True,
                                           conjugate_a=True, transpose_a=True)

    x = normal_solve(w)
    # one corrected step (the "C" in CSNE)
    r = b - jnp.matmul(a, x, precision=lax.Precision.HIGHEST)
    x = x + normal_solve(jnp.matmul(ah, r, precision=lax.Precision.HIGHEST))

    def qr_path(_):
        Q, R = lax.linalg.qr(a, full_matrices=False)
        # this branch only runs when the Gram route failed, i.e. A may be
        # numerically rank-deficient: clamp vanishing R diagonals at
        # sqrt(eps)·max|d| so the null directions get negligible (not
        # catastrophic) weight — full-rank borderline cases (|d| ratio down
        # to ~1/cond > sqrt(eps)) are untouched
        n = R.shape[-1]
        d = jnp.diagonal(R, axis1=-2, axis2=-1)
        tol = jnp.sqrt(jnp.finfo(R.real.dtype).eps) * jnp.max(jnp.abs(d))
        small = jnp.abs(d) < tol
        dc = jnp.where(small, jnp.where(jnp.real(d) < 0, -tol, tol)
                       .astype(R.dtype), d)
        idx = jnp.arange(n)
        R = R.at[..., idx, idx].set(dc)
        y = jnp.matmul(jnp.conj(jnp.swapaxes(Q, -1, -2)), b,
                       precision=lax.Precision.HIGHEST)
        return lax.linalg.triangular_solve(R, y, left_side=True, lower=False)

    bad = (info != 0) | ~jnp.all(jnp.isfinite(x))
    return lax.cond(bad, qr_path, lambda _: x, None)


def gels_core(a, b):
    """Pure least-squares kernel — no wrappers, injection, tracing, or host
    syncs; the vmap-first core the batched serving layer maps over a leading
    batch axis.  The tall/square path is *raw* CSNE — deliberately WITHOUT
    :func:`_gels_csne`'s in-trace Householder escape: under ``vmap`` a
    ``lax.cond`` lowers to a select that executes BOTH branches for every
    batch element, so the escape would make every healthy batch pay a full
    batched Householder QR.  The escape lives in the serving layer's
    element-granular ladder instead (a failed element re-runs alone through
    the full :func:`gels` driver, escape included).  The wide path is the LQ
    minimum-norm solve expressed through QR of ``a^H``.  The branch is
    static on shape, so every element of a shape bucket traces one program.

    Returns ``(x, info)`` with x ``(n, nrhs)`` and info 0 on success,
    nonzero when the Gram Cholesky broke (its 1-based pivot index) or the
    solution is non-finite — the health verdict the escalation ladder keys
    on (least squares has no LAPACK pivot semantics beyond that).
    """
    from ..ops.blas3 import gram as _gram
    from .chol import _chol_blocked as _cb, _chol_info as _ci

    m, n = a.shape[-2:]
    if m >= n:
        ah = jnp.conj(jnp.swapaxes(a, -1, -2))
        G = _gram(a)
        L = _cb(G)
        ginfo = _ci(L)

        def normal_solve(rhs):
            y = lax.linalg.triangular_solve(L, rhs, left_side=True,
                                            lower=True)
            return lax.linalg.triangular_solve(L, y, left_side=True,
                                               lower=True, conjugate_a=True,
                                               transpose_a=True)

        x = normal_solve(jnp.matmul(ah, b, precision=lax.Precision.HIGHEST))
        r = b - jnp.matmul(a, x, precision=lax.Precision.HIGHEST)
        x = x + normal_solve(jnp.matmul(ah, r,
                                        precision=lax.Precision.HIGHEST))
    else:
        # minimum-norm via QR of a^H: a = R^H Q^H, x = Q R^{-H} b
        q, r = lax.linalg.qr(jnp.conj(jnp.swapaxes(a, -1, -2)),
                             full_matrices=False)
        y = lax.linalg.triangular_solve(r, b, left_side=True, lower=False,
                                        transpose_a=True, conjugate_a=True)
        x = jnp.matmul(q, y, precision=lax.Precision.HIGHEST)
        ginfo = jnp.int32(0)
    info = jnp.where(jnp.all(jnp.isfinite(x)), ginfo,
                     jnp.maximum(ginfo, jnp.int32(1)))
    return x, info


@instrument
def gels(A, BX, opts=None):
    """Least squares min ||A X - B|| / minimum-norm solve (src/gels.cc dispatch:
    MethodGels QR vs CholQR; src/gels_qr.cc, src/gels_cholqr.cc).

    Overdetermined (m >= n): X = R^{-1} Q^H B.  Underdetermined: minimum-norm via LQ.
    Returns the n x nrhs solution.

    Rank-deficiency note (differs from the reference): when the CholQR/CSNE
    route detects trouble (Gram Cholesky fails or the solve goes non-finite)
    it falls back to Householder QR *and clamps vanishing R diagonals* at
    sqrt(eps)·max|diag(R)|, i.e. numerically rank-deficient systems are
    regularized (null directions get negligible weight) rather than erroring.
    The reference's gels_qr/gels_cholqr make no such substitution.  Callers
    who must detect rank deficiency should check ``jnp.abs(jnp.diagonal(R))``
    from ``geqrf`` directly.
    """
    opts = Options.make(opts)
    a = as_array(A)
    b = as_array(BX)
    m, n = a.shape[-2:]
    grid = distribution_grid(A, BX)
    if grid is not None:
        # wrapper bound to a >1-device grid: ride the mesh least-squares
        # pipelines (gels.cc consumes the construction-time distribution the
        # same way).  An explicit MethodGels is honored; Auto takes the same
        # CholQR-when-very-tall heuristic as the local path.
        from ..parallel import (gels_caqr_distributed, gels_cholqr_distributed,
                                gels_lq_distributed)

        if m < n:
            X = gels_lq_distributed(a, b, grid, nb=opts.block_size)
        else:
            gmethod = opts.method_gels
            if gmethod == MethodGels.Auto:
                gmethod = MethodGels.CholQR if m >= 4 * n else MethodGels.QR
            if gmethod == MethodGels.CholQR:
                X = gels_cholqr_distributed(a, b, grid)
            else:
                X = gels_caqr_distributed(a, b, grid, nb=opts.block_size)
        return write_back(BX, X) if X.shape == b.shape else X
    method = opts.method_gels
    if method == MethodGels.Auto:
        # cholqr for very tall well-shaped panels (the reference's heuristic picks
        # cholqr when tall-skinny), qr otherwise
        method = MethodGels.CholQR if m >= 4 * n else MethodGels.QR

    with trace_block("gels", m=m, n=n, method=str(method)):
        if m >= n:
            if method == MethodGels.CholQR:
                x = _gels_csne(a, b)
            else:
                fac = geqrf(a, opts)
                y = unmqr("left", "c", fac, b)[..., :n, :]
                R = fac.R()
                x = lax.linalg.triangular_solve(R, y, left_side=True,
                                                lower=False)
        else:
            # minimum-norm: A = L Q, x = Q^H L^{-1} b
            fac = gelqf(a, opts)
            L = jnp.conj(jnp.swapaxes(fac.R(), -1, -2))   # m x m lower
            y = lax.linalg.triangular_solve(L, b, left_side=True, lower=True)
            ypad = jnp.concatenate(
                [y, jnp.zeros((n - m,) + y.shape[1:], y.dtype)], axis=0)
            x = unmqr("left", "n", fac, ypad)  # Q1 ypad = Q^H ypad
    return write_back(BX, x) if (isinstance(BX, BaseMatrix)
                                 and as_array(BX).shape == x.shape) else x


def gels_qr(A, BX, opts=None):
    """Least squares via Householder QR explicitly (src/gels_qr.cc)."""
    return gels(A, BX, Options.make(opts).replace(method_gels=MethodGels.QR))


def gels_cholqr(A, BX, opts=None):
    """Least squares via CholeskyQR explicitly (src/gels_cholqr.cc).

    See :func:`gels` for the rank-deficient fallback-and-clamp behavior of
    this path (the QR fallback regularizes vanishing R diagonals)."""
    return gels(A, BX, Options.make(opts).replace(method_gels=MethodGels.CholQR))
