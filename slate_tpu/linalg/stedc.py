"""Tridiagonal divide & conquer eigensolver (stedc).

Reference analogue: ``src/stedc.cc`` + ``stedc_{sort,deflate,z_vector,secular,
merge,solve}.cc`` (~1.8 kLoC distributed D&C).  The reference pipeline per merge
is: sort -> deflate (Givens rotations on equal diagonal entries + tiny-z
drops) -> secular equation solve -> Loewner-formula eigenvectors -> gemm the
block eigenbasis.

TPU re-design (all static shapes, no data-dependent control flow):

* The recursion tree is host-side Python (sizes are static); every merge is a
  jitted function of its two halves.  This mirrors the reference's task tree
  without a task runtime.
* **Deflation as structure, not shape change.**  LAPACK shrinks the secular
  problem; XLA cannot.  Instead the merge solves a bracketed bisection for all
  m roots at once: the secular function f is strictly increasing on each
  interval (d_j, d_{j+1}); where the coupling z_j is (near-)zero, f has no sign
  change in the bracket and the bisection converges to the bracket endpoint —
  which is exactly the deflated eigenvalue.  No mask bookkeeping for values;
  only the eigenvector formula needs an endpoint guard.
* **Equal-diagonal deflation as spacing.**  The reference rotates duplicate
  d's together (stedc_deflate); here sorted d's are nudged apart to a minimal
  gap of O(eps * ||T||) by a monotone cumulative-max pass, perturbing the
  spectrum within backward error while keeping every Loewner denominator
  nonzero.
* **Gu's corrected z** (log-space products) replaces the raw Loewner vector so
  eigenvectors stay orthogonal through clustered roots.
* The secular solve runs in the gap variable t = lambda - d_j so subtraction
  cancellation never amplifies (d_i - d_j are exact-ish differences of sorted
  values).

Precision envelope: at working precision the eigenvalues are accurate to
O(eps * ||T||) everywhere; eigenvector orthogonality is O(eps * m) for
well-separated and deflation-heavy spectra.  Inside pathological many-fold
clusters the raw Loewner columns degrade to ~1e-3 (f32); two gated
Newton–Schulz polar sweeps per merge (Löwdin orthogonalization) restore
~100·eps orthogonality there, at the cost of two extra m^3 gemms only on
the merges that trip the gate.

``stedc(d, e, Z)`` matches steqr's contract: (ascending eigenvalues, Z @ Q).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

_BASE_N = 32       # below this, one fused eigh is faster than a merge
_BISECT_ITERS = 90  # geometric descent to tiny roots + full mantissa refinement


def _secular_f(d, z2, rho, pole, off):
    """f(lam_j = pole_j + off_j) for a (chunk of) brackets — pole-relative
    evaluation, fused: the (m, chunk) denominator is built inside the
    reduction as (d_i - pole_j) - off_j — the two-term form keeps the laed4
    relative precision of the gap (pole subtracted exactly first), while XLA
    fuses broadcast→divide→reduce so no m×m buffer survives a sweep (the
    round-2 version cached Dlo/Dup/D_sel: 3 m² arrays that made the n=20,000
    merge memory-infeasible).  The single shared implementation keeps prep
    and bisection evaluating f identically at the same point."""
    den = (d[:, None] - pole[None, :]) - off[None, :]
    return 1.0 + rho * jnp.sum(z2[:, None] / den, axis=0)


def _secular_prep(d: jax.Array, z2: jax.Array, rho: jax.Array):
    """Per-bracket setup of the secular solve: bracket widths and closer-pole
    selection (one f sweep).  Separated from the bisection loop so the
    distributed path can shard the loop over brackets (parallel/secular.py).
    Returns (pole, sigma, gaps, use_lower)."""
    eps = jnp.finfo(d.dtype).eps
    width = rho * jnp.sum(z2) + eps * (jnp.abs(d[-1]) + 1)
    gaps = jnp.concatenate([d[1:] - d[:-1], width[None]])
    d_up = jnp.concatenate([d[1:], (d[-1] + width)[None]])  # upper pole per bracket

    # closer-pole selection: f increasing per bracket; f(mid) >= 0 -> root in
    # the lower half (solve in u = lam - d_j), else upper (u = d_{j+1} - lam)
    use_lower = _secular_f(d, z2, rho, d, 0.5 * gaps) >= 0
    sigma = jnp.where(use_lower, 1.0, -1.0).astype(d.dtype)
    pole = jnp.where(use_lower, d, d_up)
    return pole, sigma, gaps, use_lower


def _secular_bisect(d, z2, rho, pole, sigma, gaps, use_lower):
    """The O(m_chunk · m · iters) bisection loop for a (chunk of) brackets,
    given the full pole set (d, z2 — replicated) and per-bracket prep.
    Pure elementwise-over-brackets: the distributed path maps it over
    bracket shards with no collectives (parallel/secular.py)."""
    def body(_, lohi):
        lo, hi = lohi
        u = 0.5 * (lo + hi)
        f = _secular_f(d, z2, rho, pole, sigma * u)
        bigger = sigma * f < 0               # root at larger u
        lo = jnp.where(bigger, u, lo)
        hi = jnp.where(bigger, hi, u)
        return lo, hi

    z0 = jnp.zeros(pole.shape, d.dtype)
    lo, hi = lax.fori_loop(0, _BISECT_ITERS, body, (z0, 0.5 * gaps))
    u = 0.5 * (lo + hi)
    t = jnp.where(use_lower, u, gaps - u)
    s = jnp.where(use_lower, gaps - u, u)
    lam = pole + sigma * u
    return t, s, lam


def _secular_roots(d: jax.Array, z2: jax.Array, rho: jax.Array):
    """All m roots of 1 + rho * sum_i z2_i / (d_i - lam) = 0 (stedc_secular /
    laed4 analogue), vectorized over brackets (d_j, d_{j+1}).

    Like laed4, each root is solved in the gap variable of its *closer* pole
    (chosen by the sign of f at the bracket midpoint) so near-pole roots carry
    full relative precision: pure bisection from 0 descends geometrically, so
    ~90 iterations resolve t ~ 1e-14 * gap to the last mantissa bit.  Returns
    (t, s, lam): t = lam - d_j and s = d_{j+1} - lam, both accurate near their
    respective poles.
    """
    pole, sigma, gaps, use_lower = _secular_prep(d, z2, rho)
    return _secular_bisect(d, z2, rho, pole, sigma, gaps, use_lower)


def _deflate(d_sorted, z_sorted, rho):
    """Structural deflation on the sorted union (the stedc_deflate analogue;
    see module docstring): minimal spacing for equal diagonals, z^2 floor for
    tiny couplings.  Returns (d_spaced, z2_floored, scale, eps)."""
    dt = d_sorted.dtype
    m = d_sorted.shape[0]
    scale = jnp.maximum(jnp.abs(d_sorted[0]), jnp.abs(d_sorted[-1])) + rho
    eps = jnp.finfo(dt).eps
    # minimal spacing (equal-diagonal deflation as perturbation)
    gap_min = 8 * eps * scale
    ar = jnp.arange(m, dtype=dt)
    # lax.cummax, not jnp.maximum.accumulate: the ufunc .accumulate method
    # only exists on newer jax; cummax is the same scan on every version
    d = lax.cummax(d_sorted - gap_min * ar, axis=0) + gap_min * ar
    # z-floor deflation: LAPACK drops tiny-z entries from the secular problem;
    # with static shapes we instead *floor* z^2 so every bracket keeps a pole
    # on each side and a strictly interior root.  Strict interlacing is what
    # Gu's product formula needs for globally orthogonal vectors; the floor
    # perturbs T by ~m * eps^2 * scale, far below one ulp of the spectrum.
    z2 = z_sorted * z_sorted + (eps * scale) ** 2 / jnp.maximum(rho, eps)
    return d, z2, scale, eps


def _merge(d1, Q1, d2, Q2, rho_raw, grid=None):
    """One D&C merge (stedc_merge + stedc_z_vector + stedc_secular +
    stedc_solve): rank-one update D + rho z z^T in the blkdiag(Q1, Q2) basis.

    With ``grid`` (a ProcessGrid), the two basis-update gemms — the O(m³)
    flops of the merge — run sharded over the mesh (src/stedc_merge.cc keeps
    Q distributed the same way), and the secular bisection — the O(m²·iters)
    stage — shards over brackets (parallel/secular.py; the reference splits
    the same loop across ranks, src/stedc_secular.cc).  Only the O(m²)
    Loewner build stays replicated."""
    dt = d1.dtype
    n1 = d1.shape[0]
    n2 = d2.shape[0]
    m = n1 + n2
    rho = jnp.abs(rho_raw)  # e is sign-normalized by the driver; guard anyway
    d = jnp.concatenate([d1, d2])
    z = jnp.concatenate([Q1[-1, :], Q2[0, :]])
    # sort the union (stedc_sort)
    order = jnp.argsort(d)
    d = d[order]
    z = z[order]
    d, z2, scale, eps = _deflate(d, z, rho)

    if grid is not None:
        from ..parallel.secular import secular_roots_sharded

        t, s, lam = secular_roots_sharded(d, z2, rho, grid)
    else:
        t, s, lam = _secular_roots(d, z2, rho)

    # Gu's corrected |z~_i|^2 = prod_j (lam_j - d_i) / prod_{j != i} (d_j - d_i)
    M = lam[None, :] - d[:, None]                     # (i, j): lam_j - d_i
    # patch the two near-pole entries with the exactly-solved gap offsets so
    # they carry relative (not just absolute) precision — the laed4 payoff
    idx = jnp.arange(m)
    M = M.at[idx, idx].set(t)
    if m > 1:
        M = M.at[idx[1:], idx[:-1]].set(-s[:-1])
    absM = jnp.abs(M)
    num = jnp.sum(jnp.log(jnp.where(absM > 0, absM, 1.0)), axis=1)
    zero_num = jnp.any(absM == 0, axis=1)
    # denominator log-sum over |d_j - d_i| (i≠j), fused broadcast reduction —
    # no m×m Dabs buffer (memory diet, same reason as in _secular_roots)
    same = idx[:, None] == idx[None, :]
    den = jnp.sum(jnp.log(jnp.where(same, 1.0,
                                    jnp.abs(d[:, None] - d[None, :]))), axis=1)
    sign_z = jnp.where(z >= 0, 1.0, -1.0).astype(dt)  # sign(0) must be 1, not 0
    ztilde = jnp.where(zero_num, 0.0, sign_z * jnp.exp(0.5 * (num - den)))

    # Loewner eigenvectors v_j[i] = z~_i / (d_i - lam_j).  The z-floor keeps
    # every root strictly interior to its bracket, so denominators never vanish
    # and near-pole roots resolve to ~e_i columns through the formula itself
    # (no endpoint special-casing, which would collide duplicate columns).
    denomV = -M                                       # (i, j): d_i - lam_j
    safe = jnp.where(jnp.abs(denomV) > 0, denomV, eps * scale)
    V = ztilde[:, None] / safe
    # exact pole hits (t or s underflowed to 0 — only reachable when rho ~ 0
    # decouples the problem): the eigenpair is exactly (d_i, e_i)
    pin_lo = t == 0
    pin_up = (~pin_lo) & (s == 0)
    eye_m = jnp.eye(m, dtype=dt)
    up_shift = jnp.concatenate([eye_m[:, 1:], eye_m[:, :1]], axis=1)
    V = jnp.where(pin_lo[None, :], eye_m,
                  jnp.where(pin_up[None, :], up_shift, V))
    V = V / jnp.linalg.norm(V, axis=0, keepdims=True)

    # Cluster repair: inside many-fold clusters the Loewner columns lose
    # orthogonality (the documented envelope — LAPACK's rotation deflation
    # needs dynamic shapes).  Up to two *gated* Newton–Schulz sweeps toward
    # the polar factor (Löwdin orthogonalization — the nearest orthogonal
    # matrix, so within-cluster mixing is the only change and residuals are
    # preserved) restore it quadratically: 1e-3 -> ~1e-6 -> below eps.
    # Healthy merges pay only the gate's one Gram product: the whole repair —
    # second sweep and its Gram included — nests inside the first cond (if
    # sweep 1 did not trip, sweep 2 cannot).
    ns_tol = 64 * eps * jnp.sqrt(jnp.asarray(float(m), dt))

    def _ns(Vc, Gc):
        return 1.5 * Vc - 0.5 * jnp.matmul(Vc, Gc,
                                           precision=lax.Precision.HIGHEST)

    def repair(VG):
        V1 = _ns(*VG)
        G1 = jnp.matmul(V1.T, V1, precision=lax.Precision.HIGHEST)
        return lax.cond(jnp.max(jnp.abs(G1 - eye_m)) > ns_tol,
                        lambda vg: _ns(*vg), lambda vg: vg[0], (V1, G1))

    G0 = jnp.matmul(V.T, V, precision=lax.Precision.HIGHEST)
    V = lax.cond(jnp.max(jnp.abs(G0 - eye_m)) > ns_tol,
                 repair, lambda vg: vg[0], (V, G0))

    # back to the original basis: Z = blkdiag(Q1, Q2)[:, order] @ V.  Undo the
    # sort on V's rows, then apply the two diagonal blocks separately (the
    # laed3 structure) — two (n_i x n_i x m) gemms, half the flops of one
    # dense m^3 product against materialized zero blocks.  On a grid these
    # two products (the merge's O(m³) mass) ride the mesh.
    Vp = jnp.zeros_like(V).at[order].set(V)
    if grid is not None:
        from ..parallel.summa import gemm_padded

        Ztop = gemm_padded(Q1, Vp[:n1], grid)
        Zbot = gemm_padded(Q2, Vp[n1:], grid)
    else:
        Ztop = jnp.matmul(Q1, Vp[:n1], precision=lax.Precision.HIGHEST)
        Zbot = jnp.matmul(Q2, Vp[n1:], precision=lax.Precision.HIGHEST)
    return lam, jnp.concatenate([Ztop, Zbot], axis=0)


_merge_jit = jax.jit(_merge)  # caches per input shape/dtype (grid=None path)


# merges below this size gain nothing from the mesh (collective latency
# dwarfs the gemm); the top log2(n/threshold) merges carry ~all the flops
_DIST_MERGE_MIN = 1024


def _stedc_rec(d, e, grid=None) -> Tuple[jax.Array, jax.Array]:
    n = d.shape[0]
    if n <= _BASE_N:
        from .eig import _assemble_tridiag

        return jnp.linalg.eigh(_assemble_tridiag(d, e))
    mid = n // 2
    rho = e[mid - 1]
    d1 = jnp.concatenate([d[: mid - 1], (d[mid - 1] - rho)[None]])
    d2 = jnp.concatenate([(d[mid] - rho)[None], d[mid + 1:]])
    lam1, Z1 = _stedc_rec(d1, e[: mid - 1], grid)
    lam2, Z2 = _stedc_rec(d2, e[mid:], grid)
    if grid is not None and n >= _DIST_MERGE_MIN:
        # eager composition: the O(m³) gemms and the O(m²·iters) secular
        # bisection inside are themselves jitted sharded programs
        # (parallel/summa, parallel/secular); only the O(m²) Loewner build
        # runs as replicated fused lax ops
        return _merge(lam1, Z1, lam2, Z2, rho, grid)
    return _merge_jit(lam1, Z1, lam2, Z2, rho)


def stedc(d, e, Z: Optional[jax.Array] = None, opts=None, grid=None):
    """Divide & conquer tridiagonal eigensolver (src/stedc.cc family).

    Same contract as steqr: returns (ascending eigenvalues, Q), premultiplied
    by ``Z`` when given.  The off-diagonal may be signed; a diagonal similarity
    normalizes it nonnegative first (signs folded into Q).

    ``grid``: a ProcessGrid — merges at and above ``_DIST_MERGE_MIN`` run
    their basis-update gemms sharded over the mesh (the distributed form of
    src/stedc.cc, whose Q stays a distributed matrix throughout), as does the
    final Z @ Q product.
    """
    d = jnp.asarray(d)
    e = jnp.asarray(e)
    n = d.shape[-1]
    if n == 0:
        Q = jnp.zeros((0, 0), d.dtype)
        return d, (Q if Z is None else Z)
    # sign-normalize e >= 0: T(d, e) = S T(d, |e|) S, S = diag of sign prefix
    if n > 1:
        sgn = jnp.where(e < 0, -1.0, 1.0).astype(d.dtype)
        S = jnp.concatenate([jnp.ones((1,), d.dtype), jnp.cumprod(sgn)])
        lam, Q = _stedc_rec(d, jnp.abs(e), grid)
        Q = S[:, None] * Q
    else:
        lam, Q = d, jnp.ones((1, 1), d.dtype)
    if Z is not None:
        Zc = Z.astype(Q.dtype) if Z.dtype != Q.dtype else Z
        if grid is not None and n >= _DIST_MERGE_MIN:
            from ..parallel.summa import gemm_padded

            Q = gemm_padded(Zc, Q, grid)
        else:
            Q = jnp.matmul(Zc, Q, precision=lax.Precision.HIGHEST)
    return lam, Q


# ---------------------------------------------------------------------------
# Stage entry points (the reference exposes each D&C stage publicly,
# slate.hh:1210-1264; these are the TPU-idiomatic functional forms)
# ---------------------------------------------------------------------------


def stedc_z_vector(Q1, Q2):
    """Coupling vector of a merge: last row of Q1 over first row of Q2
    (src/stedc_z_vector.cc — there, gathered over the distributed Q)."""
    return jnp.concatenate([jnp.asarray(Q1)[-1, :], jnp.asarray(Q2)[0, :]])


def stedc_sort(d, Q):
    """Ascending eigenvalue sort with matching column permutation of Q
    (src/stedc_sort.cc).  Returns (d_sorted, Q_sorted)."""
    d = jnp.asarray(d)
    order = jnp.argsort(d)
    return d[order], jnp.asarray(Q)[:, order]


def stedc_deflate(rho, d, z):
    """Deflation stage on the sorted union (src/stedc_deflate.cc).

    The reference rotates equal diagonals together and drops tiny couplings,
    shrinking the secular problem; with static shapes the same effect is a
    backward-error perturbation — minimal diagonal spacing plus a z^2 floor
    (module docstring).  Returns (d_hat, z2_hat): the spaced diagonal and the
    floored squared couplings that feed stedc_secular.
    """
    d = jnp.asarray(d)
    rho = jnp.abs(jnp.asarray(rho))
    d_hat, z2_hat, _, _ = _deflate(d, jnp.asarray(z), rho)
    return d_hat, z2_hat


def stedc_secular(rho, d, z2):
    """Secular equation stage (src/stedc_secular.cc / laed4): all m roots of
    1 + rho * sum_i z2_i / (d_i - lam) = 0 by closer-pole bisection.
    Returns the ascending eigenvalues."""
    _, _, lam = _secular_roots(jnp.asarray(d), jnp.asarray(z2),
                               jnp.abs(jnp.asarray(rho)))
    return lam


def stedc_merge(d1, Q1, d2, Q2, rho):
    """One full merge of two solved halves (src/stedc_merge.cc).
    Returns (eigenvalues, blkdiag(Q1, Q2) @ U)."""
    return _merge_jit(jnp.asarray(d1), jnp.asarray(Q1), jnp.asarray(d2),
                      jnp.asarray(Q2), jnp.asarray(rho))


def stedc_solve(d, e):
    """The recursive D&C solve without a pre-multiplied Z
    (src/stedc_solve.cc).  Returns (ascending eigenvalues, Q)."""
    return stedc(d, e)
