"""Implicit-shift tridiagonal QR iteration with eigenvector accumulation.

Reference analogue: ``src/steqr.cc`` — SLATE redistributes Z into a 1-D row
layout, every rank runs the same host QR iteration on the replicated (D, E)
scalars, and each rank applies the resulting plane rotations to its *local
rows* of Z only (rotations act columnwise, so rows are embarrassingly
parallel); a final redistribute restores the 2-D layout.

TPU re-design (this module):

- The sweep recurrence (Givens generation + bulge chase) is strictly
  sequential in k, so it runs as ONE ``lax.scan`` over the full index range
  with the active window [l, m] expressed by masking — no dynamic shapes,
  one compiled program for every window the iteration visits.
- The Z update is where the flops are, and it is *batched*: a whole sweep's
  rotation chain G_l···G_{m-1} is materialized in closed form as its dense
  orthogonal product (an upper-Hessenberg matrix — entry (i, j>=i) is
  c_{i-1}·(∏_{t=i..j-1} s_t)·c_j, subdiagonal -s_i), and Z absorbs the whole
  sweep as ONE MXU gemm instead of n-1 sequential column updates.  The
  cumulative s-products are evaluated in log space with explicit zero/sign
  tracking so long chains underflow to exact zeros instead of NaNs.
- Distributed Z (``parallel.steqr_distributed``) shard_maps the same program
  over row blocks of Z: the scalar iteration is replicated per device
  (exactly the reference's design point), the per-sweep gemm touches local
  rows only, and the compiled module contains zero collectives.

Complexity note, stated honestly: with vectors a sweep costs rows·W² MXU
flops, where W is the smallest power-of-two bucket covering the active
window [l, m] (vs LAPACK's O(rows·W) scalar rotation applies) — the gemm
runs over the active columns only, so the late small windows of a
deflating iteration cost W², not n².  Summed over a full solve this is
O(n³)-class with an extra bucket-width factor; the price of keeping the
update on the systolic array.  The performance path at scale remains
stedc (divide & conquer, ``linalg/stedc.py``) — the same split the
reference makes (steqr is its compatibility/QR-method path, used at top
level only when MethodEig::QR is requested).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["steqr_qr"]


def _sweep(d, e, l, m, shift):
    """One implicit-shift QR sweep on the window [l, m] (rotations at
    k = l..m-1).  Entries outside the window are untouched (identity
    rotations).  Returns updated (d, e) and the rotation vectors (c, s),
    with c=1, s=0 outside the window.

    Update formulas are the symmetric similarity T' = G T Gᵀ written out on
    the tridiagonal entries (Golub & Van Loan alg. 8.3.2 shape), with
    G = [[c, s], [-s, c]] in the (k, k+1) plane, c = x/r, s = z/r chosen to
    zero the bulge z against x.
    """
    n = d.shape[0]
    dt = d.dtype
    tiny = jnp.finfo(dt).tiny

    def step(carry, k):
        d, e, x, z = carry
        active = (k >= l) & (k < m)
        r = jnp.hypot(x, z)          # scaled: no overflow at |x|,|z| ~ huge
        safe = r > tiny
        c = jnp.where(active & safe, x / jnp.where(safe, r, 1), 1.0)
        s = jnp.where(active & safe, z / jnp.where(safe, r, 1), 0.0)
        # the rotated previous off-diagonal: e[k-1] <- r  (k > l only)
        e = e.at[k - 1].set(jnp.where(active & (k > l), r, e[k - 1]))
        dk = d[k]
        dk1 = d[jnp.minimum(k + 1, n - 1)]
        ek = e[jnp.minimum(k, n - 2)]
        new_dk = c * c * dk + 2 * c * s * ek + s * s * dk1
        new_dk1 = s * s * dk - 2 * c * s * ek + c * c * dk1
        new_ek = c * s * (dk1 - dk) + (c * c - s * s) * ek
        d = d.at[k].set(jnp.where(active, new_dk, dk))
        d = d.at[jnp.minimum(k + 1, n - 1)].set(
            jnp.where(active, new_dk1, dk1))
        e = e.at[jnp.minimum(k, n - 2)].set(jnp.where(active, new_ek, ek))
        # next pair: x = e[k] (post-update), z = s·e[k+1]; the (k+1, k+2)
        # coupling shrinks to c·e[k+1].  Only while the chase stays inside
        # the window (k < m-1) — e[m] belongs to the next deflated block and
        # must not be touched.
        inner = active & (k < m - 1)
        ek1 = e[jnp.minimum(k + 1, n - 2)]
        # pre-window steps (k < l) must PASS the pending bulge through to
        # step l, not zero it — only a finished chase (k = m-1) kills z
        z_next = jnp.where(inner, s * ek1, jnp.where(active, 0.0, z))
        e = e.at[jnp.minimum(k + 1, n - 2)].set(
            jnp.where(inner, c * ek1, ek1))
        x_next = jnp.where(inner, new_ek, x)
        return (d, e, x_next, z_next), (c, s)

    x0 = d[l] - shift
    z0 = e[jnp.minimum(l, n - 2)]
    (d, e, _, _), (cs, ss) = lax.scan(
        step, (d, e, x0, z0), jnp.arange(n - 1))
    return d, e, cs, ss


def _sweep_q(cs, ss):
    """Dense orthogonal Q̃ = G_lᵀ·G_{l+1}ᵀ···G_{m-1}ᵀ for the sweep's rotation
    chain, as Z's per-sweep right factor (Z ← Z·Q̃ accumulates T = Q Λ Qᵀ).

    For an ascending right-chain of P_k = [[c, s], [-s, c]] planes the product
    is upper Hessenberg: P[i, j>=i] = ĉ_{i-1}·(∏_{t=i..j-1} ŝ_t)·ĉ_j and
    P[i+1, i] = -ŝ_i (ĉ padded with 1 at both ends).  G_kᵀ is P_k with
    s → -s.  The cumulative products run in log space with zero- and
    sign-count tracking: a chain segment containing any exact zero is an
    exact zero (not a NaN), and long decaying chains underflow cleanly.
    """
    n1 = cs.shape[0]          # n-1 rotations
    n = n1 + 1
    dt = cs.dtype
    s = -ss                   # the transpose chain
    zero = jnp.abs(s) <= 0
    la = jnp.log(jnp.where(zero, 1.0, jnp.abs(s)))
    # prefix sums with a leading 0 so segment(i..j-1) = pref[j] - pref[i]
    pref = jnp.concatenate([jnp.zeros((1,), dt), jnp.cumsum(la)])
    zc = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                          jnp.cumsum(zero.astype(jnp.int32))])
    neg = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                           jnp.cumsum((s < 0).astype(jnp.int32))])
    chat = jnp.concatenate([jnp.ones((1,), dt), cs, jnp.ones((1,), dt)])
    i = jnp.arange(n)[:, None]
    j = jnp.arange(n)[None, :]
    seg = pref[j] - pref[i]                     # log ∏ s_t over t=i..j-1
    seg_zero = (zc[j] - zc[i]) > 0
    seg_sign = 1.0 - 2.0 * ((neg[j] - neg[i]) % 2).astype(dt)
    prod = jnp.where(seg_zero, 0.0, seg_sign * jnp.exp(seg))
    upper = chat[i] * prod * chat[j + 1]        # ĉ_{i-1}=chat[i], ĉ_j=chat[j+1]
    Q = jnp.where(j >= i, upper, 0.0)
    sub = jnp.concatenate([s, jnp.zeros((1,), dt)])   # Q[i+1, i] = -(-ss) = ss
    return Q + jnp.zeros((n, n), dt).at[jnp.arange(1, n),
                                        jnp.arange(n - 1)].set(-sub[:-1])


def _deflate(d, e):
    """Zero off-diagonals that satisfy the LAPACK smallness test."""
    eps = jnp.finfo(d.dtype).eps
    tiny = jnp.finfo(d.dtype).tiny
    thresh = eps * (jnp.abs(d[:-1]) + jnp.abs(d[1:])) + tiny
    return jnp.where(jnp.abs(e) <= thresh, 0.0, e)


def _window(e):
    """Bottom-most maximal unreduced window [l, m]: m is one past the highest
    nonzero off-diagonal, l the start of its contiguous nonzero run."""
    n1 = e.shape[0]
    idx = jnp.arange(n1)
    act = e != 0
    m_rot = jnp.max(jnp.where(act, idx, -1))          # -1 when fully deflated
    m = jnp.maximum(m_rot, 0) + 1
    # l = 1 + highest j < m_rot with e[j] == 0 (0 if the run reaches the top)
    brk = jnp.max(jnp.where((~act) & (idx < m_rot), idx, -1))
    l = brk + 1
    return l, m, m_rot >= 0


def _wilkinson(d, e, m):
    delta = (d[m - 1] - d[m]) * 0.5
    em = e[m - 1]
    sgn = jnp.where(delta >= 0, 1.0, -1.0).astype(d.dtype)
    denom = delta + sgn * jnp.hypot(delta, em)
    return d[m] - em * em / jnp.where(jnp.abs(denom) > 0, denom, 1.0)


@partial(jax.jit,
         static_argnames=("want_vectors", "max_sweeps", "return_info"))
def steqr_qr(d, e, Z: Optional[jax.Array] = None, *,
             want_vectors: bool = True, max_sweeps: Optional[int] = None,
             return_info: bool = False):
    """Eigen-decomposition of a symmetric tridiagonal T(d, e) by implicit-shift
    QR iteration (``src/steqr.cc`` semantics; LAPACK ``steqr`` shape).

    Returns ``(lam, Zout)`` with lam ascending; ``Zout = Z·Q`` (or ``Q`` when
    ``Z is None``) when vectors are requested, else ``lam`` alone.  Jittable:
    fixed iteration bound, masked windows, one gemm per sweep.

    Failure semantics: if the iteration exhausts its 30·n sweep budget with
    undeflated off-diagonals (LAPACK steqr's info > 0 case), the eigenvalues
    come back as NaN — the package's functional poison verdict — so an
    unconverged solve can never masquerade as a successful one.  Pass
    ``return_info=True`` to additionally receive the LAPACK-style info count
    (number of off-diagonals that failed to converge; 0 on success).
    """
    d = jnp.asarray(d)
    e = jnp.asarray(e)
    rdt = jnp.real(d).dtype
    d = jnp.real(d).astype(rdt)
    e = jnp.real(e).astype(rdt) if e.size else jnp.zeros((0,), rdt)
    n = d.shape[0]
    if n == 1:
        info0 = jnp.zeros((), jnp.int32)
        if not want_vectors:
            return (d, info0) if return_info else d
        Zout = jnp.ones((1, 1), rdt) if Z is None else jnp.asarray(Z)
        return (d, Zout, info0) if return_info else (d, Zout)
    # global pre-scale to O(1): keeps the sweep arithmetic (products of
    # entries in the similarity updates, shift denominators) inside the
    # representable range for inputs near overflow/underflow — the dense
    # steqr gets this from lascl, we take one multiply each way
    anorm = jnp.maximum(jnp.max(jnp.abs(d)), jnp.max(jnp.abs(e)))
    scale = jnp.where(anorm > 0, anorm, 1.0)
    d = d / scale
    e = e / scale
    if max_sweeps is None:
        max_sweeps = 30 * n                    # LAPACK's nmaxit = 30·n
    accumulate = want_vectors
    if accumulate:
        Z0 = jnp.eye(n, dtype=rdt) if Z is None else jnp.asarray(Z)
    else:
        Z0 = jnp.zeros((1, 1), rdt)

    # power-of-two window buckets for the Z update: a sweep only rotates
    # columns [l, m], so the gemm runs over the smallest bucket covering the
    # active window instead of all n columns — the late, small windows of a
    # deflating iteration cost W² instead of n² (the same blocking idea as
    # LAPACK's lasr applying rotations to the active columns only)
    buckets = []
    w = 64
    while w < n:
        buckets.append(w)
        w *= 2
    buckets.append(n)

    def cond(state):
        d, e, Zc, it = state
        return (it < max_sweeps) & jnp.any(_deflate(d, e) != 0)

    def body(state):
        d, e, Zc, it = state
        e = _deflate(d, e)
        l, m, any_active = _window(e)
        shift = _wilkinson(d, e, m)
        d2, e2, cs, ss = _sweep(d, e, l, m, shift)
        if accumulate:
            wsize = m + 1 - l              # columns touched: [l, m]
            bidx = jnp.int32(0)
            for i, W in enumerate(buckets[1:], start=1):
                bidx = jnp.where(wsize > buckets[i - 1], jnp.int32(i), bidx)

            def make_branch(W):
                def branch(Zc, cs, ss, l):
                    s0 = jnp.minimum(l, n - W)
                    csw = lax.dynamic_slice(cs, (s0,), (W - 1,))
                    ssw = lax.dynamic_slice(ss, (s0,), (W - 1,))
                    Qw = _sweep_q(csw, ssw)
                    Zw = lax.dynamic_slice(Zc, (0, s0), (Zc.shape[0], W))
                    Zw = jnp.matmul(Zw, Qw.astype(Zc.dtype),
                                    precision=lax.Precision.HIGHEST)
                    return lax.dynamic_update_slice(Zc, Zw, (0, s0))
                return branch

            Zc = lax.switch(bidx, [make_branch(W) for W in buckets],
                            Zc, cs, ss, l)
        d = jnp.where(any_active, d2, d)
        e = jnp.where(any_active, e2, e)
        return d, e, Zc, it + 1

    d, e, Zacc, _ = lax.while_loop(cond, body, (d, e, Z0, jnp.int32(0)))
    # LAPACK info: number of off-diagonals still undeflated at exit.  On the
    # default path an unconverged solve poisons lam with NaN (the package's
    # functional failure verdict) instead of returning silent garbage.
    info = jnp.sum(_deflate(d, e) != 0).astype(jnp.int32)
    order = jnp.argsort(d)
    lam = d[order] * scale
    lam = jnp.where(info == 0, lam, jnp.full_like(lam, jnp.nan))
    if not want_vectors:
        return (lam, info) if return_info else lam
    Zout = Zacc[:, order]
    return (lam, Zout, info) if return_info else (lam, Zout)
