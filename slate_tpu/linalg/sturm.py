"""Sturm-count bisection for symmetric tridiagonal eigenvalues.

Reference analogue: ``src/sterf.cc`` (wraps LAPACK sterf — O(n²) Pal–Walker–
Kahan QL/QR with no Z accumulation) and the bisection stage of LAPACK's
``stebz`` that the reference reaches through lapack::sterf's callers.

TPU re-design: PWK rotations are a scalar recurrence per eigenvalue step —
hostile to a vector machine.  Bisection inverts the parallelism: ONE length-n
``lax.scan`` evaluates the Sturm count at *all n shifts simultaneously*
(the carry is the n-vector of LDL pivots), so each scan step is a fused
elementwise op over n lanes and a full bisection sweep costs one pass of
O(n²) lane-parallel work with O(n) memory.  ~(mantissa+4) sweeps pin every
eigenvalue to absolute accuracy O(eps·||T||) — the same envelope as sterf.
No O(n³) eigh, no O(n²) memory: this is the right complexity class for the
n=20,000 BASELINE config.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _sturm_counts(d: jax.Array, e2: jax.Array, x: jax.Array) -> jax.Array:
    """Number of eigenvalues of T(d, e) strictly below each shift in ``x``.

    LDL^T pivot recurrence q_i = (d_i - x) - e²_{i-1}/q_{i-1}; the count is
    #{i : q_i < 0} (Sturm's theorem).  ``stebz``-style pivmin guard keeps the
    recurrence defined when a pivot underflows.  Vectorized over shifts: one
    scan step updates every lane at once.
    """
    dt = d.dtype
    n = d.shape[0]
    tiny = jnp.finfo(dt).tiny
    pivmin = tiny * jnp.maximum(jnp.max(e2), 1.0) if n > 1 else jnp.asarray(
        tiny, dt)
    e2x = jnp.concatenate([jnp.zeros((1,), dt), e2])   # e2x[0] unused

    def step(carry, de):
        q, cnt = carry
        di, e2i = de
        q = (di - x) - e2i / q
        q = jnp.where(jnp.abs(q) < pivmin, -pivmin, q)
        return (q, cnt + (q < 0)), None

    q0 = jnp.full(x.shape, 1.0, dt)   # q_{-1} sentinel: e2x[0] = 0 ignores it
    (_, cnt), _ = lax.scan(step, (q0, jnp.zeros(x.shape, jnp.int32)),
                           (d, e2x))
    return cnt


def _prescale(d, e):
    """Scale (d, e) by s so e*e cannot overflow/underflow (shared by the
    bisection entry points; drivers' _safe_scale does not reach here)."""
    dt = d.dtype
    emax = jnp.max(jnp.abs(e)) if e.size else jnp.zeros((), dt)
    s = jnp.maximum(jnp.maximum(jnp.max(jnp.abs(d)), emax),
                    jnp.finfo(dt).tiny).astype(dt)
    e2 = ((e / s) * (e / s)).astype(dt) if e.size else jnp.zeros((0,), dt)
    return d / s, e / s, e2, s


@partial(jax.jit, static_argnames=("iters", "il", "iu"))
def sterf_bisect(d: jax.Array, e: jax.Array, iters: int | None = None,
                 il: int = 0, iu: int | None = None):
    """Eigenvalues (ascending) of the symmetric tridiagonal T(d, e) by
    index-targeted bisection — every targeted eigenvalue's bracket halves in
    the same fused sweep.  O(n·k·iters/n) lane-parallel work, O(k) memory.

    ``il``/``iu`` select the half-open INDEX range [il, iu) of the ascending
    spectrum (static, LAPACK stebz's range='I' — the subset feature the
    bisection representation gives for free: the count predicate
    ``cnt >= k+1`` targets any index vector).  Default: all n."""
    d = jnp.asarray(d)
    e = jnp.asarray(e)
    dt = d.dtype
    n = d.shape[0]
    if n == 0:
        return d
    if iu is None:
        iu = n
    if not (0 <= il < iu <= n):
        raise ValueError(f"index range [{il}, {iu}) invalid for n={n}")
    if n == 1:
        return d[il:iu]
    if iters is None:
        # enough sweeps to shrink the Gershgorin span to ~4 ulp of ||T||
        iters = jnp.finfo(dt).nmant + 4
    d, e, e2, s = _prescale(d, e)
    # Gershgorin bounds
    r = jnp.abs(jnp.concatenate([e, jnp.zeros((1,), dt)])) + jnp.abs(
        jnp.concatenate([jnp.zeros((1,), dt), e]))
    lo0 = jnp.min(d - r)
    hi0 = jnp.max(d + r)
    span = hi0 - lo0
    k = jnp.arange(il, iu)
    lo = jnp.full((iu - il,), lo0, dt)
    hi = jnp.full((iu - il,), hi0 + jnp.finfo(dt).eps * span, dt)

    def sweep(_, lohi):
        lo, hi = lohi
        mid = 0.5 * (lo + hi)
        cnt = _sturm_counts(d, e2, mid)      # eigenvalues strictly below mid
        below = cnt >= k + 1                 # lambda_k < mid
        return jnp.where(below, lo, mid), jnp.where(below, mid, hi)

    lo, hi = lax.fori_loop(0, int(iters), sweep, (lo, hi))
    return 0.5 * (lo + hi) * s


@jax.jit
def sturm_count_interval(d: jax.Array, e: jax.Array, vl, vu) -> jax.Array:
    """Number of eigenvalues of T(d, e) in the half-open interval [vl, vu) —
    one fused Sturm-count pass per endpoint (LAPACK stebz range='V''s
    counting step; the reference has no interval-counting API at all).
    The Sturm count is strictly-below, so endpoints that coincide with an
    eigenvalue to rounding are eps-sensitive — pick endpoints in gaps."""
    d = jnp.asarray(d)
    e = jnp.asarray(e)
    dt = d.dtype
    ds, _, e2, s = _prescale(d, e)
    x = jnp.stack([jnp.asarray(vl, dt) / s, jnp.asarray(vu, dt) / s])
    cnt = _sturm_counts(ds, e2, x)
    # inverted intervals count zero (not negative) — matches the dense path
    return jnp.maximum(cnt[1] - cnt[0], 0).astype(jnp.int32)


@partial(jax.jit, static_argnames=("iters",))
def stein(d: jax.Array, e: jax.Array, lam: jax.Array,
          iters: int = 3) -> jax.Array:
    """Eigenvectors of the symmetric tridiagonal T(d, e) for precomputed
    eigenvalues ``lam`` by batched inverse iteration (LAPACK ``stein``).

    The reference declares MethodEig::Bisection "not yet implemented"
    (enums.hh:363); this is the TPU-native completion of that method:
    ``sterf_bisect`` brackets every eigenvalue in fused lane-parallel
    sweeps, and this routine turns them into vectors with ONE vmapped
    ``lax.linalg.tridiagonal_solve`` per iteration — all n shifted systems
    factor simultaneously, no per-eigenvalue loop.  LAPACK's per-cluster
    Gram-Schmidt reorthogonalization becomes one QR polish of the whole
    vector block (an MXU gemm tree): mixing is O(overlap) across separated
    eigenvalues and harmless inside clusters, where any basis of the
    invariant subspace is a valid answer.

    Returns V (n, k) with columns ordered like ``lam``; T V ≈ V diag(lam)
    and VᵀV ≈ I to O(n·eps·‖T‖).
    """
    d = jnp.asarray(d)
    e = jnp.asarray(e)
    lam = jnp.asarray(lam)
    dt = d.dtype
    n = d.shape[0]
    k = lam.shape[0]
    if n == 1:
        return jnp.ones((1, k), dt)
    anorm = jnp.maximum(jnp.max(jnp.abs(d)) + 2 * jnp.max(jnp.abs(e)),
                        jnp.finfo(dt).tiny)
    # LAPACK-style perturbation: keep T - λI invertible without moving the
    # shift past the eigenvalue's own ulp neighbourhood
    sep = jnp.finfo(dt).eps * anorm
    dl = jnp.concatenate([jnp.zeros((1,), dt), e])
    du = jnp.concatenate([e, jnp.zeros((1,), dt)])

    def solve_one(shift, rhs):
        return lax.linalg.tridiagonal_solve(dl, d - shift, du,
                                            rhs[:, None])[:, 0]

    batched = jax.vmap(solve_one, in_axes=(0, 1), out_axes=1)

    # deterministic start: uniform + an index-dependent perturbation so no
    # start vector is orthogonal to its target eigenvector by symmetry
    ii = jnp.arange(n, dtype=dt)[:, None]
    V = jnp.ones((n, k), dt) + 1e-3 * jnp.sin(ii * (jnp.arange(k, dtype=dt)[None, :] + 1.0))

    def body(_, carry):
        V, fails = carry
        # a column whose factorization hit an exact zero pivot re-solves
        # with a GROWN perturbation next sweep (LAPACK stein re-perturbs on
        # every failed factorization; a fixed shift would fail identically
        # forever and return the start vector as a fake eigenvector)
        V = batched(lam + sep * (1.0 + fails), V)
        nrm = jnp.linalg.norm(V, axis=0, keepdims=True)
        V = V / jnp.where(nrm > 0, nrm, 1.0)
        bad = ~jnp.isfinite(V).all(axis=0, keepdims=True)
        fails = fails + bad[0].astype(dt)
        V = jnp.where(bad, 1.0 / jnp.sqrt(jnp.asarray(n, dt)), V)
        # re-orthogonalize EVERY sweep (inverse subspace iteration): inside
        # a cluster all columns converge to the same dominant direction, so
        # a normalize-only loop leaves an exponentially ill-conditioned
        # span for the final polish to unscramble (measured: residual
        # degrades ~10x per extra normalize-only sweep on a 40-fold
        # cluster); the per-sweep QR keeps every cluster span orthonormal
        Q, R = jnp.linalg.qr(V)
        sgn = jnp.sign(jnp.diagonal(R))
        return Q * jnp.where(sgn == 0, 1.0, sgn)[None, :], fails

    V, _ = lax.fori_loop(0, iters, body, (V, jnp.zeros((k,), dt)))
    return V
