"""Sturm-count bisection for symmetric tridiagonal eigenvalues.

Reference analogue: ``src/sterf.cc`` (wraps LAPACK sterf — O(n²) Pal–Walker–
Kahan QL/QR with no Z accumulation) and the bisection stage of LAPACK's
``stebz`` that the reference reaches through lapack::sterf's callers.

TPU re-design: PWK rotations are a scalar recurrence per eigenvalue step —
hostile to a vector machine.  Bisection inverts the parallelism: ONE length-n
``lax.scan`` evaluates the Sturm count at *all n shifts simultaneously*
(the carry is the n-vector of LDL pivots), so each scan step is a fused
elementwise op over n lanes and a full bisection sweep costs one pass of
O(n²) lane-parallel work with O(n) memory.  ~(mantissa+4) sweeps pin every
eigenvalue to absolute accuracy O(eps·||T||) — the same envelope as sterf.
No O(n³) eigh, no O(n²) memory: this is the right complexity class for the
n=20,000 BASELINE config.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _sturm_counts(d: jax.Array, e2: jax.Array, x: jax.Array) -> jax.Array:
    """Number of eigenvalues of T(d, e) strictly below each shift in ``x``.

    LDL^T pivot recurrence q_i = (d_i - x) - e²_{i-1}/q_{i-1}; the count is
    #{i : q_i < 0} (Sturm's theorem).  ``stebz``-style pivmin guard keeps the
    recurrence defined when a pivot underflows.  Vectorized over shifts: one
    scan step updates every lane at once.
    """
    dt = d.dtype
    n = d.shape[0]
    tiny = jnp.finfo(dt).tiny
    pivmin = tiny * jnp.maximum(jnp.max(e2), 1.0) if n > 1 else jnp.asarray(
        tiny, dt)
    e2x = jnp.concatenate([jnp.zeros((1,), dt), e2])   # e2x[0] unused

    def step(carry, de):
        q, cnt = carry
        di, e2i = de
        q = (di - x) - e2i / q
        q = jnp.where(jnp.abs(q) < pivmin, -pivmin, q)
        return (q, cnt + (q < 0)), None

    q0 = jnp.full(x.shape, 1.0, dt)   # q_{-1} sentinel: e2x[0] = 0 ignores it
    (_, cnt), _ = lax.scan(step, (q0, jnp.zeros(x.shape, jnp.int32)),
                           (d, e2x))
    return cnt


def sterf_bisect(d: jax.Array, e: jax.Array, iters: int | None = None):
    """All eigenvalues (ascending) of the symmetric tridiagonal T(d, e) by
    index-targeted bisection — every eigenvalue's bracket halves in the same
    fused sweep.  O(n²·iters/n) lane-parallel work, O(n) memory."""
    d = jnp.asarray(d)
    e = jnp.asarray(e)
    dt = d.dtype
    n = d.shape[0]
    if n == 0:
        return d
    if n == 1:
        return d
    if iters is None:
        # enough sweeps to shrink the Gershgorin span to ~4 ulp of ||T||
        iters = jnp.finfo(dt).nmant + 4
    # pre-scale so e*e cannot overflow/underflow (the public entry points do
    # not pass through the drivers' _safe_scale)
    s = jnp.maximum(jnp.maximum(jnp.max(jnp.abs(d)), jnp.max(jnp.abs(e))),
                    jnp.finfo(dt).tiny)
    d = d / s
    e = e / s
    e2 = (e * e).astype(dt)
    # Gershgorin bounds
    r = jnp.abs(jnp.concatenate([e, jnp.zeros((1,), dt)])) + jnp.abs(
        jnp.concatenate([jnp.zeros((1,), dt), e]))
    lo0 = jnp.min(d - r)
    hi0 = jnp.max(d + r)
    span = hi0 - lo0
    lo = jnp.full((n,), lo0, dt)
    hi = jnp.full((n,), hi0 + jnp.finfo(dt).eps * span, dt)
    k = jnp.arange(n)

    def sweep(_, lohi):
        lo, hi = lohi
        mid = 0.5 * (lo + hi)
        cnt = _sturm_counts(d, e2, mid)      # eigenvalues strictly below mid
        below = cnt >= k + 1                 # lambda_k < mid
        return jnp.where(below, lo, mid), jnp.where(below, mid, hi)

    lo, hi = lax.fori_loop(0, int(iters), sweep, (lo, hi))
    return 0.5 * (lo + hi) * s
