"""SVD drivers: svd / svd_vals and the two-stage building blocks ge2tb / tb2bd / bdsqr.

Reference analogue: ``src/svd.cc:99-141`` pipeline — scale -> [QR pre-step for tall
matrices, svd.cc:224+] -> ge2tb (full->band, src/ge2tb.cc 586 LoC) -> tb2bd
(band->bidiagonal bulge chasing, src/tb2bd.cc) -> lapack::bdsqr (svd.cc:354-359) ->
back-transforms unmbr_tb2bd / unmbr_ge2tb.

TPU re-design mirrors heev's: XLA's ``lax.linalg.svd`` (QDWH-SVD on TPU — all-matmul,
MXU-native) replaces the two-stage reduction for a single device; the QR pre-step for
tall matrices is kept because it is a genuine flop-saver on any hardware; the explicit
stages are provided for parity and future distributed composition.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.matrix import BaseMatrix, as_array
from ..core.types import Options
from ..utils.trace import Timers, trace_block
from .eig import _safe_scale
from .qr import geqrf, unmqr


def svd(A, opts=None, want_u: bool = True, want_vt: bool = True):
    """Singular value decomposition A = U S V^H (src/svd.cc).

    Returns (S descending, U or None, VT or None).  Tall/wide matrices take the QR/LQ
    pre-step like the reference (svd.cc:224+): for m >> n factor A = QR first and run
    the SVD on the small R, then U = Q U_R.
    """
    opts = Options.make(opts)
    timers = Timers()
    a = as_array(A)
    m, n = a.shape[-2:]
    want_vectors = want_u or want_vt
    with trace_block("svd", m=m, n=n):
        with timers.time("svd::scale"):
            a, factor = _safe_scale(a)
        qr_pre = m >= 2 * n   # the reference's tall threshold for the QR pre-step
        lq_pre = n >= 2 * m
        if qr_pre:
            with timers.time("svd::geqrf"):
                fac = geqrf(a, opts)
                core = fac.R()
        elif lq_pre:
            with timers.time("svd::gelqf"):
                fac = geqrf(jnp.conj(jnp.swapaxes(a, -1, -2)), opts)
                core = jnp.conj(jnp.swapaxes(fac.R(), -1, -2))
        else:
            core = a
        with timers.time("svd::bdsqr"):
            if want_vectors:
                U, S, VT = jnp.linalg.svd(core, full_matrices=False)
            else:
                S = jnp.linalg.svd(core, compute_uv=False)
                U = VT = None
        if want_vectors and qr_pre:
            with timers.time("svd::unmbr"):
                # U = Q U_R: apply implicit Q to U padded to m rows
                Upad = jnp.concatenate(
                    [U, jnp.zeros((m - U.shape[-2],) + U.shape[-1:], U.dtype)],
                    axis=-2)
                U = unmqr("left", "n", fac, Upad)
        if want_vectors and lq_pre:
            with timers.time("svd::unmbr"):
                VTpad = jnp.concatenate(
                    [jnp.conj(jnp.swapaxes(VT, -1, -2)),
                     jnp.zeros((n - VT.shape[-2],) + (VT.shape[-2],), VT.dtype)],
                    axis=-2)
                V = unmqr("left", "n", fac, VTpad)
                VT = jnp.conj(jnp.swapaxes(V, -1, -2))
        S = S * factor
    svd.timers = timers
    return S, (U if want_u else None), (VT if want_vt else None)


def svd_vals(A, opts=None):
    """Singular values only (src/svd.cc svd_vals entry)."""
    S, _, _ = svd(A, opts, want_u=False, want_vt=False)
    return S


# ---------------------------------------------------------------------------
# explicit pipeline stages
# ---------------------------------------------------------------------------


def ge2tb(A, opts=None):
    """Stage 1: general -> bidiagonal via alternating left/right Householder
    reflections (src/ge2tb.cc reduces to *band*; the single-device XLA granularity
    goes directly to bidiagonal).  Returns (d, e, U, VT) with A = U B V^H where B is
    upper bidiagonal: diag d, superdiag e."""
    a = as_array(A)
    m, n = a.shape[-2:]
    k = min(m, n)
    # Golub-Kahan via QR sweeps expressed with XLA householder kernels:
    # round 1 uses the fused SVD path to produce an exactly-bidiagonal equivalent:
    # B = U1^H A V1. Here: QR of A gives R; LQ of R gives bidiagonal-ish core.
    # For exact parity we compute the bidiagonal through jnp's internal
    # tridiagonalization of the Jordan-Wielandt form later; current form returns
    # the Golub-Kahan result computed by alternating Householder passes.
    # alternating reflections, one column/row at a time (host-unrolled; stage is
    # O(mn^2) — parity scaffolding, the fused svd() path is the fast route)
    import numpy as np

    Bh = np.array(a)
    Uh = np.eye(m, dtype=Bh.dtype)
    Vh = np.eye(n, dtype=Bh.dtype)
    for j in range(k):
        # left reflector to zero column j below diagonal
        x = Bh[j:, j]
        v = x.copy()
        alpha = -np.exp(1j * np.angle(x[0])) * np.linalg.norm(x) if \
            np.iscomplexobj(x) else -np.sign(x[0] if x[0] != 0 else 1.0) * np.linalg.norm(x)
        v[0] -= alpha
        nv = np.linalg.norm(v)
        if nv > 0:
            v = v / nv
            Bh[j:, :] -= 2.0 * np.outer(v, v.conj() @ Bh[j:, :])
            Uh[:, j:] -= 2.0 * np.outer(Uh[:, j:] @ v, v.conj())
        if j < n - 2:
            x = Bh[j, j + 1:]
            v = x.copy().conj()
            alpha = -np.exp(1j * np.angle(v[0])) * np.linalg.norm(v) if \
                np.iscomplexobj(v) else -np.sign(v[0] if v[0] != 0 else 1.0) * np.linalg.norm(v)
            v[0] -= alpha
            nv = np.linalg.norm(v)
            if nv > 0:
                v = v / nv
                Bh[:, j + 1:] -= 2.0 * np.outer(Bh[:, j + 1:] @ v, v.conj())
                Vh[:, j + 1:] -= 2.0 * np.outer(Vh[:, j + 1:] @ v, v.conj())
    if np.iscomplexobj(Bh):
        # absorb the diagonal/superdiagonal phases into U and V (the LAPACK-style
        # unitary diagonal similarity) so (d, e) are exactly real
        for j in range(k):
            cur = Bh[j, j]
            if cur != 0:
                ph = cur / abs(cur)
                Bh[j, :] *= np.conj(ph)
                Uh[:, j] *= ph
            if j < k - 1:
                ej = Bh[j, j + 1]
                if ej != 0:
                    ph2 = ej / abs(ej)
                    Bh[:, j + 1] *= np.conj(ph2)
                    Vh[:, j + 1] *= np.conj(ph2)
    d = jnp.asarray(np.real(np.diagonal(Bh))[:k])
    e = jnp.asarray(np.real(np.diagonal(Bh, offset=1))[: max(k - 1, 0)])
    return d, e, jnp.asarray(Uh[:, :k]), jnp.asarray(Vh.conj().T[:k, :])


def tb2bd(band, kd, opts=None, want_vectors: bool = False):
    """Stage 2: band -> bidiagonal bulge chasing (src/tb2bd.cc).  For the kd=1
    output of ge2tb this is the identity extraction of (d, e); a wider band (kd > 1)
    is re-bidiagonalized through the ge2tb Householder pass — correct for any kd,
    with the O(n*kd) bulge chase tracked for a later round.

    With want_vectors, returns (d, e, U2, VT2) such that band = U2 B VT2."""
    b = as_array(band)
    if kd > 1:
        d, e, U2, VT2 = ge2tb(b, opts)
        return (d, e, U2, VT2) if want_vectors else (d, e)
    k = min(b.shape[-2:])
    d_c = jnp.diagonal(b, axis1=-2, axis2=-1)[:k]
    e_c = jnp.diagonal(b, offset=1, axis1=-2, axis2=-1)[: k - 1]
    if not jnp.issubdtype(b.dtype, jnp.complexfloating):
        if not want_vectors:
            return jnp.real(d_c), jnp.real(e_c)
        m, n = b.shape[-2:]
        return (jnp.real(d_c), jnp.real(e_c), jnp.eye(m, k, dtype=b.dtype),
                jnp.eye(k, n, dtype=b.dtype))
    # complex band: absorb diagonal/superdiagonal phases into unitary diagonals
    # u, w with  B_c = diag(u) B_real diag(w)^T  (the LAPACK-style similarity):
    #   u_j w_j = phase(d_j),  u_j w_{j+1} = phase(e_j)
    # solved by  w_0 = 1,  u_j = pd_j / w_j,  w_{j+1} = w_j pd_j^* pe_j
    def phase(x):
        mag = jnp.abs(x)
        return jnp.where(mag > 0, x / jnp.where(mag > 0, mag, 1), 1).astype(b.dtype)

    pd, pe = phase(d_c), phase(e_c)
    w = jnp.concatenate([jnp.ones_like(pd[:1]),
                         jnp.cumprod(jnp.conj(pd[:-1]) * pe)])
    u = pd / w
    d, e = jnp.abs(d_c), jnp.abs(e_c)
    if not want_vectors:
        return d, e
    m, n = b.shape[-2:]
    U2 = jnp.eye(m, k, dtype=b.dtype) * u[None, :]
    VT2 = jnp.eye(k, n, dtype=b.dtype) * w[:, None]
    return d, e, U2, VT2


def unmbr_ge2tb(side, op, Q, C, opts=None):
    """Apply the stage-1 bidiagonalization factor (U or V^H from ge2tb) to C
    (src/unmbr_ge2tb.cc).  Here ge2tb returns U/VT materialized, so application is
    one MXU matmul."""
    from .eig import _apply_q
    return _apply_q(side, op, Q, C)


def unmbr_tb2bd(side, op, Q, C, opts=None):
    """Apply the stage-2 (band -> bidiagonal) factor from
    ``tb2bd(..., want_vectors=True)`` to C (src/unmbr_tb2bd.cc)."""
    from .eig import _apply_q
    return _apply_q(side, op, Q, C)


def bdsqr(d, e, opts=None, want_vectors: bool = False):
    """Bidiagonal SVD (src/bdsqr.cc wraps lapack::bdsqr, svd.cc:354-359).
    Assembles the bidiagonal and runs the fused XLA SVD."""
    k = d.shape[-1]
    B = jnp.zeros((k, k), dtype=d.dtype)
    idx = jnp.arange(k)
    B = B.at[idx, idx].set(d)
    if k > 1:
        B = B.at[idx[:-1], idx[1:]].set(e)
    if want_vectors:
        U, S, VT = jnp.linalg.svd(B)
        return S, U, VT
    return jnp.linalg.svd(B, compute_uv=False), None, None
