"""SVD drivers: svd / svd_vals and the two-stage building blocks ge2tb / tb2bd / bdsqr.

Reference analogue: ``src/svd.cc:99-141`` pipeline — scale -> [QR pre-step for tall
matrices, svd.cc:224+] -> ge2tb (full->band, src/ge2tb.cc 586 LoC) -> tb2bd
(band->bidiagonal bulge chasing, src/tb2bd.cc) -> lapack::bdsqr (svd.cc:354-359) ->
back-transforms unmbr_tb2bd / unmbr_ge2tb.

TPU re-design mirrors heev's: XLA's ``lax.linalg.svd`` (QDWH-SVD on TPU — all-matmul,
MXU-native) replaces the two-stage reduction for a single device; the QR pre-step for
tall matrices is kept because it is a genuine flop-saver on any hardware; the explicit
stages are provided for parity and future distributed composition.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.exceptions import slate_assert
from ..core.matrix import BaseMatrix, as_array
from ..core.types import MethodSVD, Options
from ..robust import inject
from ..utils.trace import Timers, record_phases, trace_block
from .eig import _safe_scale
from .qr import geqrf, unmqr
from ..obs import instrument


@instrument
def svd(A, opts=None, want_u: bool = True, want_vt: bool = True,
        method: str = "fused", chase_pipeline: bool = False,
        chase_distributed: bool = False):
    """Singular value decomposition A = U S V^H (src/svd.cc).

    Returns (S descending, U or None, VT or None).  Tall/wide matrices take the QR/LQ
    pre-step like the reference (svd.cc:224+): for m >> n factor A = QR first and run
    the SVD on the small R, then U = Q U_R.

    method="two_stage" runs the reference pipeline ge2tb -> tb2bd -> bdsqr ->
    back-transforms (svd.cc:99-141) fully on-device; the default "fused" uses
    XLA's all-matmul QDWH-SVD, the MXU-native equivalent.
    """
    opts = Options.make(opts)
    timers = Timers()
    a = inject("svd", as_array(A))
    m, n = a.shape[-2:]
    want_vectors = want_u or want_vt
    if opts.method_svd == MethodSVD.Bisection and method == "fused":
        # the bisection method needs a bidiagonal stage to bisect — honor
        # the option on the default path by taking the two-stage pipeline
        # (review pin: silently running QDWH would ignore the request)
        method = "two_stage"
    from ..core.matrix import distribution_grid

    grid = distribution_grid(A)
    if grid is not None:
        # wrapper bound to a >1-device grid: distributed pipeline
        from ..linalg.eig import default_band_nb
        from ..parallel import svd_distributed

        S, U, VT = svd_distributed(a, grid, nb=default_band_nb(min(m, n), opts),
                                   want_vectors=want_vectors,
                                   chase_pipeline=chase_pipeline,
                                   method_svd=str(opts.method_svd),
                                   chase_distributed=chase_distributed)
        return S, (U if want_u else None), (VT if want_vt else None)
    slate_assert(not chase_distributed,
                 "chase_distributed requires a grid-bound wrapper "
                 "(Matrix.from_array(..., grid=...)); the single-device "
                 "two-stage path has nothing to distribute")
    if method == "two_stage":
        with trace_block("svd_two_stage", m=m, n=n):
            with timers.time("svd::scale"):
                a, factor = _safe_scale(a)
            k = min(m, n)
            with timers.time("svd::ge2tb"):
                d, e, U1, VT1 = ge2tb(a, opts, chase_pipeline=chase_pipeline)
            with timers.time("svd::bdsqr"):
                # MethodSVD.Bisection -> GK bisection values + stein
                # inverse-iteration vectors (implemented here; the
                # reference leaves the method unimplemented).  DC -> the
                # dense divide-and-conquer-class solve at any size.
                bd_method = {MethodSVD.Bisection: "bisect",
                             MethodSVD.DC: "dense"}.get(
                                 opts.method_svd, "auto")
                Sv, Ub, VTb = bdsqr(d, e, opts, want_vectors=want_vectors,
                                    method=bd_method)
            if want_vectors:
                with timers.time("svd::unmbr"):
                    U = jnp.matmul(U1, Ub.astype(U1.dtype),
                                   precision=lax.Precision.HIGHEST)
                    VT = jnp.matmul(VTb.astype(VT1.dtype), VT1,
                                    precision=lax.Precision.HIGHEST)
            else:
                U = VT = None
            Sv = Sv * factor
        svd.timers = timers
        record_phases("svd", timers)
        return Sv, (U if want_u else None), (VT if want_vt else None)
    with trace_block("svd", m=m, n=n):
        with timers.time("svd::scale"):
            a, factor = _safe_scale(a)
        qr_pre = m >= 2 * n   # the reference's tall threshold for the QR pre-step
        lq_pre = n >= 2 * m
        if qr_pre:
            with timers.time("svd::geqrf"):
                fac = geqrf(a, opts)
                core = fac.R()
        elif lq_pre:
            with timers.time("svd::gelqf"):
                fac = geqrf(jnp.conj(jnp.swapaxes(a, -1, -2)), opts)
                core = jnp.conj(jnp.swapaxes(fac.R(), -1, -2))
        else:
            core = a
        with timers.time("svd::bdsqr"):
            if want_vectors:
                U, S, VT = jnp.linalg.svd(core, full_matrices=False)
            else:
                S = jnp.linalg.svd(core, compute_uv=False)
                U = VT = None
        if want_vectors and qr_pre:
            with timers.time("svd::unmbr"):
                # U = Q U_R: apply implicit Q to U padded to m rows
                Upad = jnp.concatenate(
                    [U, jnp.zeros((m - U.shape[-2],) + U.shape[-1:], U.dtype)],
                    axis=-2)
                U = unmqr("left", "n", fac, Upad)
        if want_vectors and lq_pre:
            with timers.time("svd::unmbr"):
                VTpad = jnp.concatenate(
                    [jnp.conj(jnp.swapaxes(VT, -1, -2)),
                     jnp.zeros((n - VT.shape[-2],) + (VT.shape[-2],), VT.dtype)],
                    axis=-2)
                V = unmqr("left", "n", fac, VTpad)
                VT = jnp.conj(jnp.swapaxes(V, -1, -2))
        S = S * factor
    svd.timers = timers
    record_phases("svd", timers)
    return S, (U if want_u else None), (VT if want_vt else None)


def _gk_form(d, e):
    """Golub–Kahan form of the bidiagonal B(d, e): the 2k symmetric
    tridiagonal with zero diagonal and interleaved (d_0, e_0, d_1, …)
    off-diagonal, whose eigenvalues are ±σ_i (the bdsvdx/stebz route)."""
    k = d.shape[0]
    tgk_off = jnp.zeros((2 * k - 1,), d.dtype)
    tgk_off = tgk_off.at[0::2].set(d)
    if k > 1:
        tgk_off = tgk_off.at[1::2].set(e)
    return jnp.zeros((2 * k,), d.dtype), tgk_off


def _gk_split(Z, dtype):
    """Split TGK eigenvectors for +σ into the (U, V) singular-vector pair:
    z[0::2] = v/√2, z[1::2] = u/√2; renormalize (near-degenerate ±σ pairs
    can leak norm between the halves)."""
    root2 = jnp.asarray(jnp.sqrt(2.0), jnp.real(Z).dtype)
    V = root2 * Z[0::2, :]
    U = root2 * Z[1::2, :]

    def renorm(M):
        nrm = jnp.linalg.norm(M, axis=0, keepdims=True)
        return (M / jnp.where(nrm > 0, nrm, 1.0)).astype(dtype)

    return renorm(U), renorm(V)


def svd_range(A, opts=None, *, il: int = 0, iu: Optional[int] = None,
              want_vectors: bool = True, chase_pipeline: bool = False):
    """Subset SVD: the singular values with DESCENDING indices [il, iu)
    (il=0 is the largest) and, optionally, their U/V columns — the top-k
    SVD as a first-class driver (no reference analogue; SLATE's svd always
    computes the full spectrum).

    Route: two-stage reduction (ge2tb O(mn·nb) gemms) -> bidiagonal chase
    -> index-targeted Sturm bisection on the Golub–Kahan form (only the
    2j target indices of the ±σ spectrum bracket, O(n·j) work) -> ``stein``
    inverse iteration for the j interleaved TGK vectors -> both chase
    back-transforms applied to the THIN (n, j) blocks via the reverse
    sweep accumulation -> thin stage-1 back-transforms.  Vectors cost
    O(mn·(nb + j)) vs the full solve's O(mn²).

    Returns ``(S, U, VT)`` with S (j,) descending, U (m, j), VT (j, n)
    (None when ``want_vectors=False``).  Accuracy is bisection's ABSOLUTE
    envelope O(eps·σ_max) — exactly right for top-k use.
    """
    opts = Options.make(opts)
    a = as_array(A)
    m, n = a.shape[-2:]
    from ..core.matrix import distribution_grid

    grid = distribution_grid(A)
    if grid is not None:
        # wrapper bound to a >1-device grid: route to the distributed subset
        # pipeline like svd does (sharded ge2tb, thin back-transforms) —
        # previously this silently gathered the whole matrix to one device
        from .eig import default_band_nb
        from ..parallel import svd_range_distributed

        kmin = min(m, n)
        return svd_range_distributed(
            a, grid, il, kmin if iu is None else iu,
            nb=default_band_nb(kmin, opts), want_vectors=want_vectors,
            chase_pipeline=chase_pipeline)
    if m < n:
        S, V, UT = svd_range(jnp.conj(a).T, opts, il=il, iu=iu,
                             want_vectors=want_vectors,
                             chase_pipeline=chase_pipeline)
        if not want_vectors:
            return S, None, None
        return S, jnp.conj(UT).T, jnp.conj(V).T
    k = n
    if iu is None:
        iu = k
    slate_assert(0 <= il < iu <= k,
                 f"index range [{il}, {iu}) invalid for min(m,n)={k}")
    j = iu - il
    if k < 8:
        if want_vectors:
            out = jnp.linalg.svd(a, full_matrices=False)
            return out[1][il:iu], out[0][:, il:iu], out[2][il:iu, :]
        return jnp.linalg.svd(a, compute_uv=False)[il:iu], None, None
    from .eig import default_band_nb
    from .sturm import stein, sterf_bisect

    with trace_block("svd_range", m=m, n=n, k=j):
        a, factor = _safe_scale(a)
        nb = default_band_nb(k, opts)
        nb = int(max(2, min(nb, max(2, k - 1))))
        band, Uf, Vf = ge2tb_band(a, opts, nb=nb)
        sq = band[:k, :k]
        if want_vectors:
            d_c, e_c, Us, tauus, Vcs, tauvs = tb2bd_reflectors(
                sq, nb, pipeline=chase_pipeline)
        else:
            d_c, e_c, *_ = _tb2bd_run_chase(sq, nb, chase_pipeline)
        d, e = jnp.abs(d_c), jnp.abs(e_c)
        # Golub–Kahan form: eigenvalues are ±σ ascending; descending σ
        # indices [il, iu) are TGK ascending indices [2k-iu, 2k-il)
        zero_d, tgk_off = _gk_form(d, e)
        lam_desc = sterf_bisect(zero_d, tgk_off,
                                il=2 * k - iu, iu=2 * k - il)[::-1]
        sig = jnp.maximum(lam_desc, 0.0)
        if not want_vectors:
            return sig * factor, None, None
        Z = stein(zero_d, tgk_off, lam_desc)       # (2k, j), +σ descending
        U2t, V2t = _gk_split(Z, sq.dtype)
        # chase back-transforms on the thin blocks: U2 = Qu_raw · diag(pu),
        # so U2 @ X = Qu_raw @ (pu ⊙ X) via the reverse sweep accumulation
        from .householder import sweep_accumulate

        pu, pw = _bidiag_phases(d_c, e_c, sq.dtype)
        Xu = pu[:, None] * U2t
        Xv = pw[:, None] * V2t
        Uu = jnp.conj(sweep_accumulate(Us, tauus, k, nb,
                                       Q0=jnp.conj(Xu).T, reverse=True)).T
        Vv = jnp.conj(sweep_accumulate(Vcs, tauvs, k, nb,
                                       Q0=jnp.conj(Xv).T, reverse=True)).T
        # thin stage-1 back-transforms
        U = jnp.zeros((m, j), sq.dtype).at[:k, :].set(Uu)
        U = unmbr_ge2tb_factors("left", "n", Uf, U)
        Vfull = jnp.zeros((n, j), sq.dtype).at[:k, :].set(Vv)
        Vfull = unmbr_ge2tb_factors("left", "n", Vf, Vfull)
        return sig * factor, U, jnp.conj(Vfull).T


def svd_vals(A, opts=None):
    """Singular values only (src/svd.cc svd_vals entry)."""
    S, _, _ = svd(A, opts, want_u=False, want_vt=False)
    return S


# ---------------------------------------------------------------------------
# explicit pipeline stages
# ---------------------------------------------------------------------------


def ge2tb(A, opts=None, nb: Optional[int] = None,
          chase_pipeline: bool = False):
    """Full bidiagonalization: general -> real bidiagonal, as the composition of
    the reference's two stages (src/ge2tb.cc blocked band reduction, then
    src/tb2bd.cc bulge chasing) — fully jitted, no host loops (the round-1 numpy
    loop is gone).  Returns (d, e, U, VT) with A = U B V^H, B upper bidiagonal,
    U (m, k), VT (k, n), k = min(m, n).

    Wide inputs (m < n) take an LQ pre-step (A = L Q, bidiagonalize square L)
    like the reference svd driver's pre-factor (svd.cc:224+).
    """
    from . import householder as hh

    opts = Options.make(opts)
    a = as_array(A)
    m, n = a.shape[-2:]
    k = min(m, n)
    if m < n:
        # LQ pre-step: A^H = Q_l R  =>  A = R^H Q_l^H; bidiagonalize L = R^H
        Ql, R = jnp.linalg.qr(jnp.conj(a).T, mode="reduced")  # (n, m), (m, m)
        L = jnp.conj(R).T
        d, e, U, VT_L = ge2tb(L, opts, nb=nb, chase_pipeline=chase_pipeline)
        VT = jnp.matmul(VT_L, jnp.conj(Ql).T, precision=lax.Precision.HIGHEST)
        return d, e, U, VT
    from .eig import default_band_nb

    nb_eff = default_band_nb(k, opts) if nb is None else nb
    nb_eff = int(max(2, min(nb_eff, max(2, k - 1))))
    band, Uf, Vf = ge2tb_band(a, opts, nb=nb_eff)
    if k > 2:
        d, e, U2, VT2 = tb2bd(band[..., :k, :k], nb_eff, opts,
                              want_vectors=True, pipeline=chase_pipeline)
    else:
        # k <= 2: the band already is the bidiagonal; just normalize phases
        sq = band[:k, :k]
        d_c = jnp.diagonal(sq)
        e_c = jnp.diagonal(sq, offset=1)
        pu, pw = _bidiag_phases(d_c, e_c, a.dtype)
        d, e = jnp.abs(d_c), jnp.abs(e_c)
        U2 = jnp.diag(pu)
        VT2 = jnp.conj(jnp.diag(pw)).T
    # U = (prod Qu)[:, :k] @ U2 ; VT = VT2 @ (prod Qv)^H[:k, :]
    U = jnp.zeros((m, k), a.dtype).at[:k, :k].set(U2.astype(a.dtype))
    U = unmbr_ge2tb_factors("left", "n", Uf, U)
    Vh = jnp.zeros((n, k), a.dtype).at[:k, :k].set(
        jnp.conj(VT2.astype(a.dtype)).T)
    Vfull = unmbr_ge2tb_factors("left", "n", Vf, Vh)
    VT = jnp.conj(Vfull).T
    return d, e, U, VT


def ge2tb_band(A, opts=None, nb: Optional[int] = None):
    """Stage 1 proper: general -> *upper band* (bandwidth nb) via alternating
    blocked QR column panels and LQ row panels (src/ge2tb.cc — the reference
    stops at the band exactly like this; tb2bd chases it to bidiagonal).

    One ``lax.fori_loop`` over block indices; each step QRs the diagonal-pivot
    column panel (masked dynamic pivots, no ragged shapes), left-applies the
    compact-WY reflector to the whole matrix, then LQs the row panel with
    pivots one block to the right and right-applies — all MXU gemms, program
    size O(nb).  Requires m >= n (the svd driver LQ-pre-steps wide inputs).

    Returns ``(band, (Vu, Tu), (Vv, Tv))`` with ``A = U band V^H``,
    ``U = prod_j (I - Vu[j] Tu[j] Vu[j]^H)``, ``V = prod_j (I - Vv[j] Tv[j] Vv[j]^H)``.
    """
    from .eig import default_band_nb

    opts = Options.make(opts)
    a = as_array(A)
    m, n = a.shape[-2:]
    if m < n:
        raise ValueError("ge2tb_band requires m >= n; LQ-pre-step wide inputs")
    k = n
    if nb is None:
        nb = default_band_nb(k, opts)
    return _ge2tb_band_core(a, nb)


@partial(jax.jit, static_argnums=(1,))
def _ge2tb_band_core(a, nb: int):
    """Jitted ge2tb_band body (module-level jit is load-bearing: the panel
    QR/LQ pair traces O(nb) masked-larfg ops and an eager fori_loop re-traced
    them on every call — see eig._he2hb_core)."""
    from . import householder as hh

    m, n = a.shape[-2:]
    k = n
    nt = max(-(-k // nb), 1)
    # pad so the last panel's slice never clamps (dynamic_slice clamps
    # out-of-bounds starts, which would silently grab shifted columns)
    mp, np_ = m + nb, n + nb
    Apad = jnp.zeros((mp, np_), a.dtype).at[:m, :n].set(a)

    def body(j, carry):
        Acur, Vu, Tu, Vv, Tv = carry
        k0 = j * nb
        # QR panel: pivots on the diagonal, zero below it
        P = lax.dynamic_slice(Acur, (0, k0), (mp, nb))
        _, V, taus = hh.panel_qr_masked(P, k0, nb)
        T = hh.build_T(V, taus)
        Acur = hh.block_apply_left(V, T, Acur, conj_q=True)
        Vu = lax.dynamic_update_slice(Vu, V[None], (j, 0, 0))
        Tu = lax.dynamic_update_slice(Tu, T[None], (j, 0, 0))
        # LQ panel: pivots one block right of the diagonal, zero beyond them
        Prow = lax.dynamic_slice(Acur, (k0, 0), (nb, np_))
        _, Vr, tausr = hh.panel_lq_masked(Prow, k0 + nb, nb)
        Tr = hh.build_T(Vr, tausr)
        Acur = hh.block_apply_right(Vr, Tr, Acur)
        Vv = lax.dynamic_update_slice(Vv, Vr[None], (j, 0, 0))
        Tv = lax.dynamic_update_slice(Tv, Tr[None], (j, 0, 0))
        return Acur, Vu, Tu, Vv, Tv

    Vu0 = jnp.zeros((nt, mp, nb), a.dtype)
    Tu0 = jnp.zeros((nt, nb, nb), a.dtype)
    Vv0 = jnp.zeros((nt, np_, nb), a.dtype)
    Tv0 = jnp.zeros((nt, nb, nb), a.dtype)
    Aout, Vu, Tu, Vv, Tv = lax.fori_loop(0, nt, body,
                                         (Apad, Vu0, Tu0, Vv0, Tv0))
    ri = jnp.arange(m)[:, None]
    ci = jnp.arange(n)[None, :]
    band = jnp.where((ci >= ri) & (ci - ri <= nb), Aout[:m, :n], 0)
    return band, (Vu[:, :m, :], Tu), (Vv[:, :n, :], Tv)


def unmbr_ge2tb_factors(side, op, factors, C):
    """Apply a stacked block-reflector factor from ge2tb_band ((Vu,Tu) for U,
    (Vv,Tv) for V) to C without materializing Q (src/unmbr_ge2tb.cc)."""
    from .eig import unmtr_he2hb

    Vs, Ts = factors
    return unmtr_he2hb(side, op, Vs, Ts, C)


def _tb2bd_chase(Bfull: jax.Array, kd: int):
    """Bidiagonal bulge chasing: square upper band (bandwidth kd >= 2) ->
    complex bidiagonal, via the reference's three task types
    (src/internal/internal_gebr.cc gebr1/gebr2/gebr3; windows src/tb2bd.cc:77-131)
    as nested lax.fori_loops over static dynamic-slice windows on a padded array.

    Per sweep s:
      - gebr1 on the (kd+1)-by-kd window at (s, s+1): a right reflector zeroes
        row s beyond the superdiagonal, then a left reflector zeroes column s+1
        below its first subdiagonal row.
      - per block r >= 1: gebr2 on the kd-by-kd superdiagonal window at
        ((r-1)kd+1+s, r*kd+1+s) left-applies the previous u (bulge), then a new
        right reflector zeroes its first row; gebr3 on the diagonal window at
        (r*kd+1+s) right-applies that v and generates a left u zeroing its
        first column.  Inactive steps land in zero padding (tau = 0 no-ops).

    Returns (d_c, e_c, Us, tauus, Vsr, tauvs): complex bi-diagonal plus both
    reflector families for the back-transforms (disjoint supports per sweep).
    """
    from . import householder as hh

    n = Bfull.shape[-1]
    b = kd
    dt = Bfull.dtype
    N = n + 2 * b + 2
    Bp = jnp.zeros((N, N), dt).at[:n, :n].set(Bfull)
    n_sweeps = max(n - 1, 0)
    m_max = max(-(-(n - 1) // b), 1)
    Us0 = jnp.zeros((n_sweeps, m_max, b), dt)
    tauus0 = jnp.zeros((n_sweeps, m_max), dt)
    Vs0 = jnp.zeros((n_sweeps, m_max, b), dt)
    tauvs0 = jnp.zeros((n_sweeps, m_max), dt)
    zi, zj = n + b + 1, n + 1

    def chase_body(r, inner):
        s, Bp, Us, tauus, Vs, tauvs, u_prev, tauu_prev = inner
        i = (r - 1) * b + 1 + s
        j = r * b + 1 + s
        active = j < n
        ii = jnp.where(active, i, zj)
        jj = jnp.where(active, j, zi)
        # gebr2: superdiagonal window — left-apply previous u, new right v
        W = lax.dynamic_slice(Bp, (ii, jj), (b, b))
        W = hh.apply_left(tauu_prev, u_prev, W)
        v, tauv, _ = hh.larfg(jnp.conj(W[0, :]))
        W = hh.apply_right(tauv, v, W)
        Bp = lax.dynamic_update_slice(Bp, W, (ii, jj))
        # gebr3: diagonal window — right-apply v, new left u
        D = lax.dynamic_slice(Bp, (jj, jj), (b, b))
        D = hh.apply_right(tauv, v, D)
        u, tauu, _ = hh.larfg(D[:, 0])
        D = hh.apply_left(tauu, u, D)
        Bp = lax.dynamic_update_slice(Bp, D, (jj, jj))
        Vs = Vs.at[s, r].set(v)
        tauvs = tauvs.at[s, r].set(tauv)
        Us = Us.at[s, r].set(u)
        tauus = tauus.at[s, r].set(tauu)
        return s, Bp, Us, tauus, Vs, tauvs, u, tauu

    def sweep_body(s, carry):
        Bp, Us, tauus, Vs, tauvs = carry
        # gebr1: (b+1, b) window at (s, s+1)
        W = lax.dynamic_slice(Bp, (s, s + 1), (b + 1, b))
        v, tauv, _ = hh.larfg(jnp.conj(W[0, :]))
        W = hh.apply_right(tauv, v, W)
        y = W[1:, 0]
        u, tauu, _ = hh.larfg(y)
        W = W.at[1:, :].set(hh.apply_left(tauu, u, W[1:, :]))
        Bp = lax.dynamic_update_slice(Bp, W, (s, s + 1))
        Vs = Vs.at[s, 0].set(v)
        tauvs = tauvs.at[s, 0].set(tauv)
        Us = Us.at[s, 0].set(u)
        tauus = tauus.at[s, 0].set(tauu)
        _, Bp, Us, tauus, Vs, tauvs, _, _ = lax.fori_loop(
            1, m_max, chase_body, (s, Bp, Us, tauus, Vs, tauvs, u, tauu))
        return Bp, Us, tauus, Vs, tauvs

    Bp, Us, tauus, Vs, tauvs = lax.fori_loop(
        0, n_sweeps, sweep_body, (Bp, Us0, tauus0, Vs0, tauvs0))
    B = Bp[:n, :n]
    idx = jnp.arange(n)
    d_c = B[idx, idx]
    e_c = B[idx[:-1], idx[1:]] if n > 1 else jnp.zeros((0,), dt)
    return d_c, e_c, Us, tauus, Vs, tauvs


def _tb2bd_chase_pipelined(Bfull: jax.Array, kd: int):
    """Multi-sweep pipelined bidiagonal chase — the reference's pass/step
    scheduling (src/tb2bd.cc:163-196, same dependency rule as hb2st)
    vectorized into batched rounds, mirroring ``eig._hb2st_chase_pipelined``.

    Sweep s starts at round 2s and advances one chase block per round, so
    concurrent sweeps sit two blocks apart — element-disjoint window
    footprints (the nonsymmetric band has no mirror writes, so only the
    gebr2/gebr3 windows themselves need checking).  Each round: one scalar
    gebr1 for the starting sweep, then batched gebr2+gebr3 pairs across all
    live fronts.  Results match the sequential chase up to float
    reassociation and tau=0 no-op entries.
    """
    from . import householder as hh

    n = Bfull.shape[-1]
    b = kd
    dt = Bfull.dtype
    N = n + 2 * b + 2
    Bp = jnp.zeros((N, N), dt).at[:n, :n].set(Bfull)
    n_sweeps = max(n - 1, 0)
    m_max = max(-(-(n - 1) // b), 1)
    B_slots = m_max // 2 + 2
    Us0 = jnp.zeros((n_sweeps + 1, m_max, b), dt)    # +1 = dead-slot scratch
    tauus0 = jnp.zeros((n_sweeps + 1, m_max), dt)
    Vs0 = jnp.zeros((n_sweeps + 1, m_max, b), dt)
    tauvs0 = jnp.zeros((n_sweeps + 1, m_max), dt)
    zi, zj = n + b + 1, n + 1
    ar_b = jnp.arange(b)

    def round_body(t, carry):
        Bp, Us, tauus, Vs, tauvs, s_st, r_st, uprev, tuprev = carry

        # ---- gebr1 for the sweep starting this round (at most one) --------
        s0 = t // 2
        starting = (t % 2 == 0) & (s0 < n_sweeps)
        w0 = jnp.where(starting, s0, zj)
        W = lax.dynamic_slice(Bp, (w0, w0 + 1), (b + 1, b))
        v0, tauv0, _ = hh.larfg(jnp.conj(W[0, :]))
        W = hh.apply_right(tauv0, v0, W)
        u0, tauu0, _ = hh.larfg(W[1:, 0])
        W = W.at[1:, :].set(hh.apply_left(tauu0, u0, W[1:, :]))
        Bp = lax.dynamic_update_slice(Bp, W, (w0, w0 + 1))
        s0c = jnp.where(starting, s0, n_sweeps)
        Vs = Vs.at[s0c, 0].set(v0)
        tauvs = tauvs.at[s0c, 0].set(tauv0)
        Us = Us.at[s0c, 0].set(u0)
        tauus = tauus.at[s0c, 0].set(tauu0)
        q0 = s0 % B_slots
        s_st = s_st.at[q0].set(jnp.where(starting, s0, s_st[q0]))
        r_st = r_st.at[q0].set(jnp.where(starting, 1, r_st[q0]))
        uprev = uprev.at[q0].set(jnp.where(starting, u0, uprev[q0]))
        tuprev = tuprev.at[q0].set(jnp.where(starting, tauu0, tuprev[q0]))

        # ---- batched gebr2+gebr3 pairs across all live fronts -------------
        j = r_st * b + 1 + s_st
        i = (r_st - 1) * b + 1 + s_st
        live = (s_st >= 0) & (r_st >= 1) & (j < n)
        ii = jnp.where(live, i, zj)
        jj = jnp.where(live, j, zi)
        rows_i = ii[:, None] + ar_b[None, :]
        cols_j = jj[:, None] + ar_b[None, :]
        # gebr2: left-apply previous u, then new right v zeroing row 0
        Wb = Bp[rows_i[:, :, None], cols_j[:, None, :]]   # (B, b, b)
        uW = jnp.einsum("bi,bij->bj", jnp.conj(uprev), Wb)
        Wb = Wb - jnp.conj(tuprev)[:, None, None] * uprev[:, :, None] * uW[:, None, :]
        v, tauv, _ = hh.larfg(jnp.conj(Wb[:, 0, :]))
        Wv = jnp.einsum("bij,bj->bi", Wb, v)
        Wb = Wb - tauv[:, None, None] * Wv[:, :, None] * jnp.conj(v)[:, None, :]
        Bp = Bp.at[rows_i[:, :, None], cols_j[:, None, :]].set(Wb)
        # gebr3: right-apply v on the diagonal window, new left u zeroing col 0
        Db = Bp[cols_j[:, :, None], cols_j[:, None, :]]
        Dv = jnp.einsum("bij,bj->bi", Db, v)
        Db = Db - tauv[:, None, None] * Dv[:, :, None] * jnp.conj(v)[:, None, :]
        u, tauu, _ = hh.larfg(Db[:, :, 0])
        uD = jnp.einsum("bi,bij->bj", jnp.conj(u), Db)
        Db = Db - jnp.conj(tauu)[:, None, None] * u[:, :, None] * uD[:, None, :]
        Bp = Bp.at[cols_j[:, :, None], cols_j[:, None, :]].set(Db)
        # store reflectors (dead slots target the scratch row)
        s_c = jnp.where(live, s_st, n_sweeps)
        r_c = jnp.where(live, r_st, 0)
        Vs = Vs.at[s_c, r_c].set(jnp.where(live[:, None], v, Vs[s_c, r_c]))
        tauvs = tauvs.at[s_c, r_c].set(jnp.where(live, tauv, tauvs[s_c, r_c]))
        Us = Us.at[s_c, r_c].set(jnp.where(live[:, None], u, Us[s_c, r_c]))
        tauus = tauus.at[s_c, r_c].set(jnp.where(live, tauu, tauus[s_c, r_c]))
        r_st = jnp.where(live, r_st + 1, r_st)
        uprev = jnp.where(live[:, None], u, uprev)
        tuprev = jnp.where(live, tauu, tuprev)
        return Bp, Us, tauus, Vs, tauvs, s_st, r_st, uprev, tuprev

    T = 2 * n_sweeps + m_max
    s_st0 = jnp.full((B_slots,), -1, jnp.int32)
    r_st0 = jnp.zeros((B_slots,), jnp.int32)
    uprev0 = jnp.zeros((B_slots, b), dt)
    tuprev0 = jnp.zeros((B_slots,), dt)
    Bp, Us, tauus, Vs, tauvs, *_ = lax.fori_loop(
        0, T, round_body,
        (Bp, Us0, tauus0, Vs0, tauvs0, s_st0, r_st0, uprev0, tuprev0))
    Bm = Bp[:n, :n]
    idx = jnp.arange(n)
    d_c = Bm[idx, idx]
    e_c = Bm[idx[:-1], idx[1:]] if n > 1 else jnp.zeros((0,), dt)
    return d_c, e_c, Us[:n_sweeps], tauus[:n_sweeps], Vs[:n_sweeps], tauvs[:n_sweeps]


def _bidiag_phases(d_c, e_c, dt):
    """Unitary diagonal phases (pu, pw) with B_c = diag(pu) B_real diag(pw)^H:
    pu_j conj(pw_j) = phase(d_j), pu_j conj(pw_{j+1}) = phase(e_j)."""
    def phase(x):
        mag = jnp.abs(x)
        return jnp.where(mag > 0, x / jnp.where(mag > 0, mag, 1), 1).astype(dt)

    pd, pe = phase(d_c), phase(e_c)
    # w_0 = 1; u_j = pd_j w_j; w_{j+1} = conj(pe_j) u_j
    pw = jnp.concatenate([jnp.ones((1,), dt),
                          jnp.cumprod(jnp.conj(pe) * pd[:-1])]) \
        if d_c.shape[-1] > 1 else jnp.ones(d_c.shape, dt)
    pu = pd * pw
    return pu, pw


def tb2bd_reflectors(band, kd, pipeline: bool = False):
    """Stage-2 bidiagonal chase at the REFLECTOR level:
    (d_c, e_c, Us, tauus, Vs, tauvs) without materializing U2/VT2.

    Hook for the distributed layer's row-sharded vectors accumulation
    (``parallel.eig_dist``): the two sweep_accumulate calls dominate the
    vectors path and every update is a column operation, so each device
    builds its own row block with zero collectives.  Requires kd > 1."""
    b = as_array(band)
    slate_assert(kd > 1, "tb2bd_reflectors needs kd > 1 (no chase below)")
    kb = min(b.shape[-2:])
    sq = b[..., :kb, :kb]
    return _tb2bd_run_chase(sq, kd, pipeline)


@partial(jax.jit, static_argnums=(1, 2))
def _tb2bd_run_chase(sq, kd: int, pipeline: bool):
    """Jitted chase dispatch (module-level jit is load-bearing — see
    eig._he2hb_core)."""
    chase = _tb2bd_chase_pipelined if pipeline else _tb2bd_chase
    return chase(sq, kd)


def tb2bd(band, kd, opts=None, want_vectors: bool = False,
          pipeline: bool = False):
    """Stage 2: band -> bidiagonal bulge chasing (src/tb2bd.cc; kernels
    src/internal/internal_gebr.cc).  For kd=1 this is the (phase-normalized)
    identity extraction; kd >= 2 runs the real windowed chase.

    With want_vectors, returns (d, e, U2, VT2) such that band = U2 B VT2.
    ``pipeline=True`` runs the multi-sweep batched chase (~2n rounds instead
    of ~n*(n/kd) steps — same trade-off as ``hb2st(pipeline=True)``: wins on
    accelerators where per-step dispatch dominates, loses to the sequential
    dynamic-slice windows on CPU)."""
    from . import householder as hh

    b = as_array(band)
    if kd > 1:
        kb = min(b.shape[-2:])
        d_c, e_c, Us, tauus, Vs, tauvs = tb2bd_reflectors(b, kd,
                                                          pipeline=pipeline)
        pu, pw = _bidiag_phases(d_c, e_c, b.dtype)
        d, e = jnp.abs(d_c), jnp.abs(e_c)
        if not want_vectors:
            return d, e
        U2 = hh.sweep_accumulate(Us, tauus, kb, kd) * pu[None, :]
        V2 = hh.sweep_accumulate(Vs, tauvs, kb, kd) * pw[None, :]
        VT2 = jnp.conj(V2).T
        return d, e, U2, VT2
    k = min(b.shape[-2:])
    d_c = jnp.diagonal(b, axis1=-2, axis2=-1)[:k]
    e_c = jnp.diagonal(b, offset=1, axis1=-2, axis2=-1)[: k - 1]
    if not jnp.issubdtype(b.dtype, jnp.complexfloating):
        if not want_vectors:
            return jnp.real(d_c), jnp.real(e_c)
        m, n = b.shape[-2:]
        return (jnp.real(d_c), jnp.real(e_c), jnp.eye(m, k, dtype=b.dtype),
                jnp.eye(k, n, dtype=b.dtype))
    # complex band: absorb diagonal/superdiagonal phases into unitary diagonals
    # u, w with  B_c = diag(u) B_real diag(w)^T  (the LAPACK-style similarity):
    #   u_j w_j = phase(d_j),  u_j w_{j+1} = phase(e_j)
    # solved by  w_0 = 1,  u_j = pd_j / w_j,  w_{j+1} = w_j pd_j^* pe_j
    def phase(x):
        mag = jnp.abs(x)
        return jnp.where(mag > 0, x / jnp.where(mag > 0, mag, 1), 1).astype(b.dtype)

    pd, pe = phase(d_c), phase(e_c)
    w = jnp.concatenate([jnp.ones_like(pd[:1]),
                         jnp.cumprod(jnp.conj(pd[:-1]) * pe)])
    u = pd / w
    d, e = jnp.abs(d_c), jnp.abs(e_c)
    if not want_vectors:
        return d, e
    m, n = b.shape[-2:]
    U2 = jnp.eye(m, k, dtype=b.dtype) * u[None, :]
    VT2 = jnp.eye(k, n, dtype=b.dtype) * w[:, None]
    return d, e, U2, VT2


def unmbr_ge2tb(side, op, Q, C, opts=None):
    """Apply the stage-1 bidiagonalization factor (U or V^H from ge2tb) to C
    (src/unmbr_ge2tb.cc).  Here ge2tb returns U/VT materialized, so application is
    one MXU matmul."""
    from .eig import _apply_q
    return _apply_q(side, op, Q, C)


def unmbr_tb2bd(side, op, Q, C, opts=None):
    """Apply the stage-2 (band -> bidiagonal) factor from
    ``tb2bd(..., want_vectors=True)`` to C (src/unmbr_tb2bd.cc)."""
    from .eig import _apply_q
    return _apply_q(side, op, Q, C)


def bdsqr(d, e, opts=None, want_vectors: bool = False, method: str = "auto"):
    """Bidiagonal SVD (src/bdsqr.cc wraps lapack::bdsqr, svd.cc:354-359).

    Values-only at scale: Sturm bisection on the Golub–Kahan form — the
    2k×2k symmetric tridiagonal with zero diagonal and interleaved
    (d_0, e_0, d_1, e_1, …) off-diagonal, whose eigenvalues are ±σ_i (the
    bdsvdx/stebz route in LAPACK).  O(k²) lane-parallel work, O(k) memory,
    and no squaring of the condition number (unlike the B^T B normal form).

    With ``method="bisect"`` and ``want_vectors``, singular vectors come
    from batched inverse iteration on the same GK form (``sturm.stein`` —
    the bdsvdx route): the TGK eigenvector for +σ interleaves the pair as
    z[0::2] = v/√2, z[1::2] = u/√2.  Cost is O(k³)-class like the dense
    path (the per-sweep orthogonalization is a QR of the (2k, k) block),
    but structured as batched tridiagonal solves + QR gemms rather than
    one fused SVD; values-only bisection stays O(k²).

    Accuracy envelope: like LAPACK's bisection (stebz/bdsvdx), the
    bisection path delivers *absolute* accuracy O(eps·σ_max); singular
    values near σ_max·eps carry no relative digits and their u/v split
    degrades (the ±σ TGK pair merges).  ``method`` controls the trade:
    "auto" (default) bisects above _STEV_DENSE_MAX for values-only,
    "dense" forces the fused XLA SVD at any size (full relative accuracy
    of tiny σ, O(k³)), "bisect" forces the Golub–Kahan bisection.
    """
    from .eig import _STEV_DENSE_MAX
    from ..core.exceptions import slate_assert

    slate_assert(method in ("auto", "dense", "bisect"),
                 f"bdsqr: unknown method '{method}'")
    d = jnp.asarray(d)
    e = jnp.asarray(e)
    k = d.shape[-1]
    use_bisect = (method == "bisect"
                  or (method == "auto" and k > _STEV_DENSE_MAX
                      and not want_vectors))
    if use_bisect:
        from .sturm import stein, sterf_bisect

        zero_d, tgk_off = _gk_form(d, e)
        lam = sterf_bisect(zero_d, tgk_off)
        # +σ branch, descending; clamp the ~eps·||B|| bisection noise at σ≈0
        sig = jnp.maximum(lam[k:][::-1], 0.0)
        if not want_vectors:
            return sig, None, None
        # vectors by batched inverse iteration on the Golub–Kahan form (the
        # bdsvdx route): the TGK eigenvector for +σ_i interleaves the
        # singular pair as z[0::2] = v_i/√2, z[1::2] = u_i/√2 — verified
        # against the dense SVD in tests.  Shares bisection's ABSOLUTE
        # accuracy envelope: σ within O(eps·σ_max) of zero have no relative
        # digits and their u/v split degrades (the ±σ TGK pair merges).
        Z = stein(zero_d, tgk_off, lam[k:][::-1])
        U, V = _gk_split(Z, Z.dtype)
        return sig, U, jnp.swapaxes(V, -1, -2)
    B = jnp.zeros((k, k), dtype=d.dtype)
    idx = jnp.arange(k)
    B = B.at[idx, idx].set(d)
    if k > 1:
        B = B.at[idx[:-1], idx[1:]].set(e)
    if want_vectors:
        U, S, VT = jnp.linalg.svd(B)
        return S, U, VT
    return jnp.linalg.svd(B, compute_uv=False), None, None
