"""Test-matrix generation library (``slate_matgen`` analogue).

Reference analogue: ``matgen/`` (2659 LoC) — ``slate::generate_matrix`` with ~40 named
matrix kinds, singular-/eigen-spectrum control via ``--cond`` and distribution
suffixes, scaling and modifier suffixes, and a counter-based RNG so that any tile can
be generated independently on any rank (matgen/random.cc, matgen/generate_matrix_utils.cc:70-95,
matgen/generate_type_{rand,svd,heev}.hh, public API matgen/generate_matrix.hh:30-71).

TPU re-design: entries are pure functions of the *global* index, built with jnp index
grids (deterministic kinds) or with JAX's threefry counter-based RNG keyed per
canonical 256x256 block (random kinds) — the same independence property as the
reference's Philox-like generator: ``generate_tile`` produces any aligned sub-block
without generating the rest of the matrix, so each mesh device can materialize its own
shard. Spectrum-controlled kinds (svd/heev/poev/diag) build A = U.Sigma.V^H from the
requested sigma distribution exactly as the reference does.

Kind grammar (matching the reference's ``--matrix`` strings)::

    <base>[_<dist>][_<scale>][_dominant][_zerocol<N|frac>]

base: zeros ones identity ij jordan jordanT chebspec circul fiedler gfpp kms orthog
      riemann ris zielkeNS minij hilb frank lehmer lotkin redheff triw pei tridiag
      toeppen parter moler cauchy chow clement gcdmat
      rand rands randn randb randr
      diag svd poev spd heev syev
dist (for diag/svd/poev/heev): logrand (default) arith geo cluster0 cluster1
      rarith rgeo rcluster0 rcluster1 specified rand rands randn
scale: ufl ofl small large
"""

from __future__ import annotations

import math
import re
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .core.exceptions import SlateError

__all__ = [
    "generate_matrix", "generate_sigma", "generate_tile", "matrix_kinds",
    "generate_matrix_usage",
]

# canonical random-generation block: random kinds are generated per aligned
# (_GEN_NB x _GEN_NB) block with a key folded by the block index, so any block is
# reproducible in isolation (the reference's counter-based-RNG property)
_GEN_NB = 256

_DETERMINISTIC = (
    "zeros ones identity ij jordan jordanT chebspec circul fiedler gfpp kms orthog "
    "riemann ris zielkeNS minij hilb frank lehmer lotkin redheff triw pei tridiag "
    "toeppen parter moler cauchy chow clement gcdmat"
).split()
_RANDOM = "rand rands randn randb randr".split()
_SPECTRUM = "diag svd poev spd heev syev".split()
_DISTS = ("logrand arith geo cluster0 cluster1 rarith rgeo rcluster0 rcluster1 "
          "specified rand rands randn").split()
_SCALES = "ufl ofl small large".split()


def matrix_kinds() -> list:
    """All base kind names (suffixes excluded)."""
    return _DETERMINISTIC + _RANDOM + _SPECTRUM


def generate_matrix_usage() -> str:
    """Human-readable kind list (≅ generate_matrix_usage, generate_matrix_utils.cc:61-143)."""
    return __doc__.split("Kind grammar")[1]


def _real_dtype(dtype):
    return jnp.zeros((), dtype).real.dtype


def _limits(dtype):
    info = jnp.finfo(_real_dtype(dtype))
    ufl = float(info.tiny)
    ofl = 1.0 / ufl
    return ufl, ofl, float(info.eps)


def _parse_kind(kind: str, dtype, cond: Optional[float], condD: Optional[float]):
    """Decode base kind + dist + scaling + modifiers (≅ decode_matrix,
    generate_matrix_utils.cc:166+)."""
    tokens = re.split(r"[-_]", kind)
    if not tokens or not tokens[0]:
        raise SlateError("empty matrix kind")
    base = tokens[0]
    if base == "spd":
        base = "poev"
    if base == "syev":
        base = "heev"
    if base not in matrix_kinds() and base != "poev" and base != "heev":
        raise SlateError(f"unknown matrix kind base '{tokens[0]}' in '{kind}'")

    ufl, ofl, eps = _limits(dtype)
    dist = "logrand"
    sigma_max = 1.0
    dominant = False
    zero_col = None
    for tok in tokens[1:]:
        if tok in _DISTS:
            dist = tok
        elif tok == "ufl":
            sigma_max = ufl * (1 / eps)    # representable but near underflow
        elif tok == "ofl":
            sigma_max = ofl * eps
        elif tok == "small":
            sigma_max = math.sqrt(ufl)
        elif tok == "large":
            sigma_max = math.sqrt(ofl)
        elif tok == "dominant":
            dominant = True
        elif tok.startswith("zerocol"):
            frac_or_n = tok[len("zerocol"):]
            zero_col = float(frac_or_n) if "." in frac_or_n else int(frac_or_n)
        elif tok == "":
            continue
        else:
            raise SlateError(f"unknown suffix '_{tok}' in matrix kind '{kind}'")

    cond = (1.0 / math.sqrt(eps)) if cond is None else float(cond)
    condD = 1.0 if condD is None else float(condD)
    return base, dist, cond, condD, sigma_max, dominant, zero_col


# ---------------------------------------------------------------------------
# deterministic kinds: entry(i, j) formulas on global 0-based index grids
# (≅ the entry_type lambdas, generate_matrix_ge.cc:100-460)

def _entries(base: str, I, J, m: int, n: int, rdtype):
    one = jnp.ones((), rdtype)
    mx = max(m, n)
    if base == "zeros":
        return jnp.zeros(I.shape, rdtype)
    if base == "ones":
        return jnp.ones(I.shape, rdtype)
    if base == "identity":
        return (I == J).astype(rdtype)
    if base == "ij":
        s = 1.0 / 10 ** math.ceil(math.log10(n)) if n > 1 else 0.1
        return I.astype(rdtype) + J.astype(rdtype) * s
    if base == "jordan":
        return ((I == J) | (I + 1 == J)).astype(rdtype)
    if base == "jordanT":
        return ((I == J) | (I - 1 == J)).astype(rdtype)
    if base == "chebspec":
        x = lambda K: jnp.cos(jnp.pi * (K + 1) / mx).astype(rdtype)
        xi, xj = x(I), x(J)
        ci = jnp.where(I == mx - 1, 2.0, 1.0).astype(rdtype)
        cj = jnp.where(J == mx - 1, 2.0, 1.0).astype(rdtype)
        sgn = jnp.where((I + J) % 2 == 0, 1.0, -1.0).astype(rdtype)
        off = sgn * ci / (cj * (xj - xi + jnp.where(I == J, one, 0)))
        last = (2.0 * mx * mx + 1) / -6.0
        diag = jnp.where(J + 1 == mx, last, -0.5 * xi / (1 - xi * xi))
        return jnp.where(I == J, diag, off)
    if base == "circul":
        d = J - I
        return (d + jnp.where(d < 0, mx, 0) + 1).astype(rdtype)
    if base == "fiedler":
        return jnp.abs(J - I).astype(rdtype)
    if base == "gfpp":
        return jnp.where(J == n - 1, one,
                         jnp.where(I > J, -one, jnp.where(I == J, 0.5 * one, 0.0)))
    if base == "kms":
        return jnp.power(jnp.asarray(0.5, rdtype), jnp.abs(J - I).astype(rdtype))
    if base == "orthog":
        outer = math.sqrt(2.0 / (mx + 1))
        return (outer * jnp.sin((I + 1) * (J + 1) * (jnp.pi / (mx + 1)))).astype(rdtype)
    if base == "riemann":
        # entry = i+1 when (i+2) divides (j+2), else -1 (gallery('riemann'): the
        # reference's lambda transposes its own help text; we follow the documented
        # matrix, generate_matrix_utils.cc:88)
        return jnp.where((J + 2) % (I + 2) == 0, (I + 1).astype(rdtype), -one)
    if base == "ris":
        return 0.5 / (mx - J - I - 0.5).astype(rdtype)
    if base == "zielkeNS":
        return jnp.where(J < I, one, jnp.where((J + 1 == mx) & (I == 0), -one, 0.0))
    if base == "minij":
        return (jnp.minimum(I, J) + 1).astype(rdtype)
    if base == "hilb":
        return 1.0 / (I + J + 1).astype(rdtype)
    if base == "frank":
        return jnp.where(I - J > 1, 0.0,
                         jnp.where(I - J == 1, (mx - J - 1).astype(rdtype),
                                   (mx - J).astype(rdtype)))
    if base == "lehmer":
        return (jnp.minimum(I, J) + 1).astype(rdtype) / (jnp.maximum(I, J) + 1)
    if base == "lotkin":
        return jnp.where(I == 0, one, 1.0 / (I + J + 1).astype(rdtype))
    if base == "redheff":
        return (((J + 1) % (I + 1) == 0) | (J == 0)).astype(rdtype)
    if base == "triw":
        return jnp.where(I == J, one, jnp.where(I > J, 0.0, -one))
    if base == "pei":
        return jnp.where(I == J, 2 * one, one)
    if base == "tridiag":
        return jnp.where(I == J, 2 * one, jnp.where(jnp.abs(I - J) == 1, -one, 0.0))
    if base == "toeppen":
        return jnp.where(jnp.abs(J - I) == 1, (J - I).astype(rdtype) * 10,
                         jnp.where(jnp.abs(I - J) == 2, one, 0.0))
    if base == "parter":
        return 1.0 / (I - J + 0.5).astype(rdtype)
    if base == "moler":
        return jnp.where(I == J, (I + 1).astype(rdtype),
                         (jnp.minimum(I, J) - 1).astype(rdtype))
    if base == "cauchy":
        return 1.0 / (I + J + 2).astype(rdtype)
    if base == "chow":
        return jnp.where(I - J < -1, 0.0, 1.0).astype(rdtype)
    if base == "clement":
        return jnp.where(I - J == 1, (mx - J - 1).astype(rdtype),
                         jnp.where(I - J == -1, J.astype(rdtype), 0.0))
    if base == "gcdmat":
        return jnp.gcd(I + 1, J + 1).astype(rdtype)
    raise SlateError(f"unhandled deterministic kind '{base}'")


# ---------------------------------------------------------------------------
# random kinds: counter-based per canonical block

def _rand_block(base: str, key, bi: int, bj: int, shape, dtype):
    """One canonical block; key folded with the block's grid index, so blocks are
    independent and reproducible (≅ random::generate taking (i_global, j_global),
    generate_type_rand.hh:65-68)."""
    k = jax.random.fold_in(jax.random.fold_in(key, bi), bj)
    if jnp.issubdtype(dtype, jnp.complexfloating):
        kr, ki = jax.random.split(k)
        re = _rand_block(base, kr, 0, 0, shape, _real_dtype(dtype))
        im = _rand_block(base, ki, 0, 0, shape, _real_dtype(dtype))
        return (re + 1j * im).astype(dtype)
    if base == "rand":
        return jax.random.uniform(k, shape, dtype)
    if base == "rands":
        return jax.random.uniform(k, shape, dtype, minval=-1.0, maxval=1.0)
    if base == "randn":
        return jax.random.normal(k, shape, dtype)
    if base == "randb":
        return jax.random.bernoulli(k, 0.5, shape).astype(dtype)
    if base == "randr":
        return jax.random.rademacher(k, shape).astype(dtype)
    raise SlateError(f"unhandled random kind '{base}'")


def _rand_full(base: str, key, m: int, n: int, dtype):
    """Assemble the full matrix from canonical blocks (vmapped fold_in keeps it one
    XLA program)."""
    bm = -(-m // _GEN_NB)
    bn = -(-n // _GEN_NB)
    # always draw whole canonical blocks (even when one covers the matrix) so the
    # threefry counters — and hence the values — agree with generate_tile

    def block(bi, bj):
        return _rand_block(base, key, bi, bj, (_GEN_NB, _GEN_NB), dtype)

    grid = jax.vmap(lambda bi: jax.vmap(lambda bj: block(bi, bj))(jnp.arange(bn)))(
        jnp.arange(bm))                       # (bm, bn, NB, NB)
    full = grid.transpose(0, 2, 1, 3).reshape(bm * _GEN_NB, bn * _GEN_NB)
    return full[:m, :n]


# ---------------------------------------------------------------------------
# sigma distributions (≅ generate_sigma.hh)

def generate_sigma(dist: str, n: int, cond: float, *, rand_sign: bool = False,
                   sigma_max: float = 1.0, seed: int = 0,
                   sigma: Optional[jax.Array] = None, dtype=jnp.float32) -> jax.Array:
    """Singular/eigen value vector for the requested distribution (≅
    matgen/generate_sigma.hh; suffix table generate_matrix_utils.cc:120-137)."""
    rdtype = _real_dtype(dtype)
    key = jax.random.PRNGKey(seed)
    i = jnp.arange(n, dtype=rdtype)
    denom = max(n - 1, 1)
    if dist == "specified":
        if sigma is None:
            raise SlateError("dist 'specified' requires sigma=")
        s = jnp.asarray(sigma, rdtype)
    elif dist in ("logrand",):
        lo = math.log(1.0 / cond)
        s = jnp.exp(jax.random.uniform(key, (n,), rdtype, minval=lo, maxval=0.0))
    elif dist in ("arith", "rarith"):
        s = 1 - i / denom * (1 - 1 / cond)
    elif dist in ("geo", "rgeo"):
        s = jnp.power(jnp.asarray(cond, rdtype), -i / denom)
    elif dist in ("cluster0", "rcluster0"):
        s = jnp.where(i == 0, 1.0, 1.0 / cond).astype(rdtype)
    elif dist in ("cluster1", "rcluster1"):
        s = jnp.where(i == n - 1, 1.0 / cond, 1.0).astype(rdtype)
    elif dist == "rand":
        s = jax.random.uniform(key, (n,), rdtype)
    elif dist == "rands":
        s = jax.random.uniform(key, (n,), rdtype, minval=-1.0, maxval=1.0)
    elif dist == "randn":
        s = jax.random.normal(key, (n,), rdtype)
    else:
        raise SlateError(f"unknown sigma distribution '{dist}'")
    if dist.startswith("r") and dist in ("rarith", "rgeo", "rcluster0", "rcluster1"):
        s = s[::-1]
    if rand_sign and dist not in ("rands", "randn"):
        # heev: eigenvalues of mixed sign (poev keeps them positive)
        signs = jax.random.rademacher(jax.random.fold_in(key, 17), (n,)).astype(rdtype)
        s = s * signs
    return s * sigma_max


def _haar_q(key, rows: int, cols: int, dtype):
    """Random orthonormal (rows x cols) factor: QR of a Gaussian block (the
    reference forms Q the same way — geqrf of a rand matrix, generate_type_heev.hh:60-75)."""
    g = _rand_full("randn", key, rows, cols, dtype)
    q, r = jnp.linalg.qr(g)
    # fix the sign convention so Q is Haar-distributed
    d = jnp.sign(jnp.diagonal(r).real)
    d = jnp.where(d == 0, 1.0, d).astype(dtype)
    return q * d[None, :]


def _cond_diag(key, n: int, condD: float, rdtype):
    """Diagonal scaling with condition condD: log-uniform on [log(1/condD), 0]
    (generate_type_svd.hh:159-170)."""
    lo = math.log(1.0 / condD)
    return jnp.exp(jax.random.uniform(key, (n,), rdtype, minval=lo, maxval=0.0))


# ---------------------------------------------------------------------------
# public API

def generate_matrix(kind: str, m: int, n: Optional[int] = None, *,
                    dtype=jnp.float32, seed: int = 0, cond: Optional[float] = None,
                    condD: Optional[float] = None,
                    sigma: Optional[jax.Array] = None
                    ) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Generate an m x n test matrix of the named kind.

    Returns ``(A, Sigma)`` where Sigma is the generated singular/eigenvalue vector
    for spectrum-controlled kinds (diag/svd/poev/heev) and None otherwise.
    ≅ ``slate::generate_matrix`` (matgen/generate_matrix.hh:30-71).
    """
    n = m if n is None else n
    base, dist, cond, condD, sigma_max, dominant, zero_col = _parse_kind(
        kind, dtype, cond, condD)
    rdtype = _real_dtype(dtype)
    key = jax.random.PRNGKey(seed)
    S = None

    if base in _DETERMINISTIC:
        I, J = jnp.meshgrid(jnp.arange(m), jnp.arange(n), indexing="ij")
        A = _entries(base, I, J, m, n, rdtype).astype(dtype)
        if sigma_max != 1:
            A = A * sigma_max
    elif base in _RANDOM:
        A = _rand_full(base, key, m, n, dtype)
        if sigma_max != 1:
            A = A * sigma_max
    elif base == "diag":
        S = generate_sigma(dist, min(m, n), cond, sigma_max=sigma_max, seed=seed,
                           sigma=sigma, dtype=dtype)
        A = jnp.zeros((m, n), dtype).at[jnp.arange(min(m, n)),
                                        jnp.arange(min(m, n))].set(S.astype(dtype))
    elif base == "svd":
        mn = min(m, n)
        S = generate_sigma(dist, mn, cond, sigma_max=sigma_max, seed=seed,
                           sigma=sigma, dtype=dtype)
        kU, kV, kD = jax.random.split(jax.random.fold_in(key, 1), 3)
        U = _haar_q(kU, m, mn, dtype)
        V = _haar_q(kV, n, mn, dtype)
        A = (U * S.astype(dtype)[None, :]) @ V.conj().T
        if condD != 1:
            A = A * _cond_diag(kD, n, condD, rdtype).astype(dtype)[None, :]
    elif base in ("poev", "heev"):
        if m != n:
            raise SlateError(f"kind '{kind}' requires a square matrix")
        S = generate_sigma(dist, n, cond, rand_sign=(base == "heev"),
                           sigma_max=sigma_max, seed=seed, sigma=sigma, dtype=dtype)
        kU, kD = jax.random.split(jax.random.fold_in(key, 1))
        U = _haar_q(kU, n, n, dtype)
        A = (U * S.astype(dtype)[None, :]) @ U.conj().T
        A = (A + A.conj().T) / 2
        if condD != 1:
            d = _cond_diag(kD, n, condD, rdtype).astype(dtype)
            A = A * d[None, :] * d[:, None]      # two-sided D A D
            A = (A + A.conj().T) / 2
    else:  # pragma: no cover
        raise SlateError(f"unhandled kind '{kind}'")

    if dominant:
        # the reference bumps the diagonal by n BEFORE the sigma_max scaling
        # (generate_type_rand.hh:70-83), so the bump scales with the matrix
        mn = min(m, n)
        idx = jnp.arange(mn)
        A = A.at[idx, idx].add(jnp.asarray(n * sigma_max, dtype))
    if zero_col is not None:
        col = int(round(zero_col * (n - 1))) if isinstance(zero_col, float) else zero_col
        if not 0 <= col < n:
            raise SlateError(f"zerocol index {col} out of range [0, {n})")
        A = A.at[:, col].set(0)
        if base in ("poev", "heev") or (m == n and base in ("hilb", "minij", "pei")):
            A = A.at[col, :].set(0)
    return A, S


def generate_tile(kind: str, i0: int, j0: int, mb: int, nb: int, m: int, n: int, *,
                  dtype=jnp.float32, seed: int = 0) -> jax.Array:
    """Generate just the (mb x nb) sub-block at global offset (i0, j0) without
    materializing the rest — the counter-based-RNG property that lets every mesh
    device build its own shard independently (≅ random::generate with global
    offsets, generate_type_rand.hh:65-68).

    Supported for deterministic and random kinds (spectrum-controlled kinds need
    the global factors, use generate_matrix).
    """
    base, dist, cond, condD, sigma_max, dominant, zero_col = _parse_kind(
        kind, dtype, None, None)
    rdtype = _real_dtype(dtype)
    if base in _DETERMINISTIC:
        I, J = jnp.meshgrid(jnp.arange(i0, i0 + mb), jnp.arange(j0, j0 + nb),
                            indexing="ij")
        tile = _entries(base, I, J, m, n, rdtype).astype(dtype)
    elif base in _RANDOM:
        key = jax.random.PRNGKey(seed)
        # cover with canonical aligned blocks, then slice
        b0, b1 = i0 // _GEN_NB, (i0 + mb - 1) // _GEN_NB
        c0, c1 = j0 // _GEN_NB, (j0 + nb - 1) // _GEN_NB
        rows = []
        for bi in range(b0, b1 + 1):
            row = [_rand_block(base, key, bi, bj, (_GEN_NB, _GEN_NB), dtype)
                   for bj in range(c0, c1 + 1)]
            rows.append(jnp.concatenate(row, axis=1))
        cover = jnp.concatenate(rows, axis=0)
        tile = cover[i0 - b0 * _GEN_NB: i0 - b0 * _GEN_NB + mb,
                     j0 - c0 * _GEN_NB: j0 - c0 * _GEN_NB + nb]
    else:
        raise SlateError(
            f"generate_tile supports deterministic/random kinds, not '{kind}'")
    if sigma_max != 1:
        tile = tile * sigma_max
    if dominant or zero_col is not None:
        I, J = jnp.meshgrid(jnp.arange(i0, i0 + mb), jnp.arange(j0, j0 + nb),
                            indexing="ij")
        if dominant:
            # bump scaled by sigma_max to match the reference's pre-scale order
            tile = jnp.where((I == J) & (I < min(m, n)),
                             tile + n * sigma_max, tile)
        if zero_col is not None:
            col = (int(round(zero_col * (n - 1))) if isinstance(zero_col, float)
                   else zero_col)
            tile = jnp.where(J == col, 0, tile)
            if m == n and base in ("hilb", "minij", "pei"):  # symmetric kinds zero the row too
                tile = jnp.where(I == col, 0, tile)
    return tile
