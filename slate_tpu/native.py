"""ctypes bindings for the native host runtime (native/slate_rt.cpp), with pure
Python fallbacks.

Reference analogue: the reference's C++ runtime layer — block-cyclic tile maps
(func.hh), the tile directory (MatrixStorage.hh), the fixed-block memory pool
(src/core/Memory.cc) and trace capture (src/auxiliary/Trace.cc).  The TPU compute
path is XLA/Pallas; this is the *host* side: integer-heavy owner-map/plan
computation, workspace accounting, and low-overhead event capture.

``backend()`` reports which implementation is active.  The shared library is built
on demand with ``make`` in ``native/`` (no pip deps); every entry point falls back
to Python when the build is unavailable, and the test suite covers both paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Tuple

import numpy as np

from .core.types import GridOrder

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                           "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libslate_rt.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _order_code(order) -> int:
    return 0 if GridOrder.from_string(order) == GridOrder.Col else 1


_FAIL_STAMP = os.path.join(_NATIVE_DIR, ".build_failed")


def _src_fingerprint() -> str:
    """Newest mtime over the native sources; keys the fail stamp so a stamp
    from an older (or transiently broken) tree doesn't suppress builds of a
    changed one."""
    try:
        ms = [os.path.getmtime(os.path.join(_NATIVE_DIR, f))
              for f in os.listdir(_NATIVE_DIR)
              if f.endswith((".cpp", ".cc", ".c", ".h", ".hpp")) or f == "Makefile"]
        return repr(max(ms)) if ms else "0"
    except OSError:
        return "0"


def _stamp_suppresses() -> bool:
    try:
        with open(_FAIL_STAMP) as f:
            return f.read().strip() == _src_fingerprint()
    except OSError:
        return False


def build() -> bool:
    """Compile native/libslate_rt.so with make.  Runs lazily on the first
    native call (never at import — an import must not spawn a compiler);
    callers can also invoke it explicitly after a clean.  A failed attempt is
    stamped with the source fingerprint so later sessions don't re-pay a
    doomed compile, but any source change invalidates the stamp; explicit
    build() always retries."""
    global _tried
    try:
        proc = subprocess.run(["make", "-C", _NATIVE_DIR], capture_output=True,
                              timeout=120)
        _tried = False            # allow _load to pick up the fresh build
        ok = proc.returncode == 0
    # slate-lint: disable=SLT501 -- `make` subprocess probe: only
    # subprocess errors can arise; a failed build is recorded in the stamp
    except Exception:
        ok = False
    try:
        if ok:
            if os.path.exists(_FAIL_STAMP):
                os.unlink(_FAIL_STAMP)
        else:
            with open(_FAIL_STAMP, "w") as f:
                f.write(_src_fingerprint())
    except OSError:
        pass
    return ok


def _should_autobuild() -> bool:
    import shutil
    if (os.environ.get("SLATE_TPU_NATIVE", "1") == "0"
            or os.path.exists(_LIB_PATH)
            or not os.path.isdir(_NATIVE_DIR)
            or not os.access(_NATIVE_DIR, os.W_OK)
            or shutil.which("make") is None
            or shutil.which(os.environ.get("CXX", "g++")) is None):
        return False
    if _stamp_suppresses():
        global _warned_stamp
        if not _warned_stamp:
            _warned_stamp = True
            import warnings
            warnings.warn(
                "slate_tpu native build previously failed for these sources "
                f"({_FAIL_STAMP} present); using pure-Python fallbacks. "
                "Call slate_tpu.native.build() to retry.")
        return False
    return True


_warned_stamp = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH) and _should_autobuild():
        build()           # lazy first-use build (ADVICE: not at import time)
        _tried = True     # build() cleared it so a fresh .so is picked up here
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.srt_owner_map.argtypes = [ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
                                  ctypes.c_int32, ctypes.c_int32, i32p]
    lib.srt_local_tiles.argtypes = [ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
                                    ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                                    i64p]
    lib.srt_local_tiles.restype = ctypes.c_int64
    lib.srt_redist_plan.argtypes = [ctypes.c_int64, ctypes.c_int64,
                                    ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                                    ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                                    i32p, i32p]
    lib.srt_redist_plan.restype = ctypes.c_int64
    lib.srt_pool_new.argtypes = [ctypes.c_int64, ctypes.c_int64]
    lib.srt_pool_new.restype = ctypes.c_void_p
    lib.srt_pool_delete.argtypes = [ctypes.c_void_p]
    lib.srt_pool_alloc.argtypes = [ctypes.c_void_p]
    lib.srt_pool_alloc.restype = ctypes.c_int64
    lib.srt_pool_free.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.srt_pool_free.restype = ctypes.c_int32
    for fn in ("srt_pool_in_use", "srt_pool_capacity", "srt_pool_peak"):
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
        getattr(lib, fn).restype = ctypes.c_int64
    lib.srt_trace_enable.argtypes = [ctypes.c_int32]
    lib.srt_trace_begin.argtypes = [ctypes.c_char_p]
    lib.srt_trace_end.argtypes = []
    lib.srt_trace_count.restype = ctypes.c_int64
    lib.srt_trace_dump.argtypes = [ctypes.c_char_p]
    lib.srt_trace_dump.restype = ctypes.c_int32
    _lib = lib
    return _lib


def backend() -> str:
    """'native' when libslate_rt.so is loaded, else 'python'."""
    return "native" if _load() is not None else "python"


# ---------------------------------------------------------------------------
# block-cyclic maps

def owner_map(mt: int, nt: int, p: int, q: int,
              order=GridOrder.Col) -> np.ndarray:
    """Full (mt, nt) int32 tile->rank map for a 2D block-cyclic grid
    (func.hh:178-186 applied over the whole tile space)."""
    code = _order_code(order)
    lib = _load()
    out = np.empty((mt, nt), dtype=np.int32)
    if lib is not None and mt * nt > 0:
        lib.srt_owner_map(mt, nt, p, q, code,
                          out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return out
    i = np.arange(mt)[:, None] % p
    j = np.arange(nt)[None, :] % q
    return (i + j * p if code == 0 else i * q + j).astype(np.int32)


def local_tiles(mt: int, nt: int, p: int, q: int, rank: int,
                order=GridOrder.Col) -> np.ndarray:
    """(k, 2) array of the (i, j) tile indices owned by ``rank`` (the reference's
    per-rank tile-directory iteration, MatrixStorage.hh)."""
    code = _order_code(order)
    lib = _load()
    if lib is not None:
        count = lib.srt_local_tiles(mt, nt, p, q, code, rank, None)
        out = np.empty((count, 2), dtype=np.int64)
        if count:
            lib.srt_local_tiles(mt, nt, p, q, code, rank,
                                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return out
    om = owner_map(mt, nt, p, q, order)
    ii, jj = np.nonzero(om == rank)
    return np.stack([ii, jj], axis=1).astype(np.int64)


def redist_plan(mt: int, nt: int,
                src_grid: Tuple[int, int], dst_grid: Tuple[int, int],
                src_order=GridOrder.Col, dst_order=GridOrder.Col):
    """Per-tile (src_rank, dst_rank) maps between two block-cyclic layouts and the
    count of tiles that move (src/redistribute.cc's send/recv planning loop).

    Returns (src_map, dst_map, n_moved)."""
    c1, c2 = _order_code(src_order), _order_code(dst_order)
    lib = _load()
    if lib is not None:
        src = np.empty((mt, nt), dtype=np.int32)
        dst = np.empty((mt, nt), dtype=np.int32)
        moved = lib.srt_redist_plan(
            mt, nt, src_grid[0], src_grid[1], c1, dst_grid[0], dst_grid[1], c2,
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return src, dst, int(moved)
    src = owner_map(mt, nt, src_grid[0], src_grid[1], src_order)
    dst = owner_map(mt, nt, dst_grid[0], dst_grid[1], dst_order)
    return src, dst, int(np.count_nonzero(src != dst))


# ---------------------------------------------------------------------------
# memory-pool accounting

class MemoryPool:
    """Fixed-block workspace accounting (src/core/Memory.cc free list).

    XLA owns the actual HBM; this tracks tile-granular workspace budget so
    drivers can reason about fit/spill (the reference's reserveDeviceWorkspace
    planning).  alloc() returns a block id or -1 when exhausted; free() returns
    False on double-free (the Debug.cc leak check).
    """

    def __init__(self, block_bytes: int, nblocks: int):
        self.block_bytes = int(block_bytes)
        self._lib = _load()
        if self._lib is not None:
            self._pool = self._lib.srt_pool_new(block_bytes, nblocks)
            self._free: Optional[List[int]] = None
        else:
            self._pool = None
            self._free = list(range(nblocks - 1, -1, -1))
            self._used = set()
            self._peak = 0
            self._cap = nblocks

    def alloc(self) -> int:
        if self._pool is not None:
            return int(self._lib.srt_pool_alloc(self._pool))
        if not self._free:
            return -1
        bid = self._free.pop()
        self._used.add(bid)
        self._peak = max(self._peak, len(self._used))
        return bid

    def free(self, block_id: int) -> bool:
        if self._pool is not None:
            return int(self._lib.srt_pool_free(self._pool, block_id)) == 0
        if block_id not in self._used:
            return False
        self._used.discard(block_id)
        self._free.append(block_id)
        return True

    @property
    def in_use(self) -> int:
        if self._pool is not None:
            return int(self._lib.srt_pool_in_use(self._pool))
        return len(self._used)

    @property
    def capacity(self) -> int:
        if self._pool is not None:
            return int(self._lib.srt_pool_capacity(self._pool))
        return self._cap

    @property
    def peak(self) -> int:
        if self._pool is not None:
            return int(self._lib.srt_pool_peak(self._pool))
        return self._peak

    def __del__(self):
        if getattr(self, "_pool", None) is not None and self._lib is not None:
            self._lib.srt_pool_delete(self._pool)
            self._pool = None


# ---------------------------------------------------------------------------
# native trace capture

def trace_enable(on: bool = True) -> None:
    lib = _load()
    if lib is not None:
        lib.srt_trace_enable(1 if on else 0)


def trace_begin(name: str) -> None:
    lib = _load()
    if lib is not None:
        lib.srt_trace_begin(name.encode())


def trace_end() -> None:
    lib = _load()
    if lib is not None:
        lib.srt_trace_end()


def trace_count() -> int:
    lib = _load()
    return int(lib.srt_trace_count()) if lib is not None else 0


def trace_clear() -> None:
    lib = _load()
    if lib is not None:
        lib.srt_trace_clear()


def trace_dump(path: str) -> bool:
    """Write captured events as chrome://tracing JSON (Trace.cc:330-448's SVG
    writer, modernized). Returns False when native capture is unavailable."""
    lib = _load()
    if lib is None:
        return False
    return int(lib.srt_trace_dump(path.encode())) == 0


# NOTE: no import-time build — the native library compiles lazily on the first
# native call (_load), so `import slate_tpu` never spawns a compiler.  Opt out
# entirely with SLATE_TPU_NATIVE=0 (pure-Python fallbacks remain functional);
# a failed attempt is stamped keyed to the source fingerprint, so only the
# same broken tree is suppressed and a warning is emitted once.
