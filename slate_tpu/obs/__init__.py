"""Unified observability layer (round-8 tentpole).

Three pillars, one import:

* **Metrics registry** (:mod:`.registry`) — process-wide counters, gauges,
  and histograms with labels (routine, dtype, shape_bucket, mesh, nb,
  method, lu_panel ...), exported as one ``metrics.json`` document
  (schema ``slate_tpu.metrics/v1``) shared by bench, tester, and chaos
  runs.
* **Span API** (:mod:`.spans`) — ``obs.scope(routine, **labels)`` wraps a
  driver invocation: chrome-trace region (via ``utils.trace.trace_block``)
  plus registry counters/histograms.  ``obs.instrument`` is the decorator
  every public distributed driver wears.
* **Compiled-cost audit** (:mod:`.costaudit` / :mod:`.scaling`) — harvest
  ``cost_analysis`` + compiled-HLO collective volume for every
  ``parallel/`` routine on a P-device mesh; ``tools/gen_scaling.py``
  renders SCALING.md and pins the P=2 envelopes for CI.

Reference analogue: none — SLATE's observability is printed tester columns
and trace SVGs; the registry/audit unification is this reproduction's
addition (FlatAttention's collective-volume accounting and BLASX's
throughput telemetry are the exemplars, PAPERS.md).
"""

from .registry import (REGISTRY, SCHEMA, Counter, Gauge, Histogram,
                       MetricsRegistry, quantile_from_counts,
                       validate_metrics)
from .spans import (INSTRUMENT_ATTR, SpanHandle, current_span, instrument,
                    on_phases, scope, span_depth)
from .costaudit import COLLECTIVE_OPS, collective_volume, harvest, harvest_many
from .scaling import (AUDIT_N, AUDIT_NB, RoutineSpec, audit_all,
                      audit_routine, make_grid, spec_names, specs)
from .timeseries import (TIMESERIES_SCHEMA, TimeSeriesSampler,
                         validate_timeseries)
from .slo import (SLO, SLOMonitor, SLOVerdict, STATUS_CODES,
                  default_serve_slos)


def counter(name: str, help: str = "") -> Counter:
    """Get-or-create a counter on the process registry."""
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    """Get-or-create a gauge (last-write-wins sample) on the process registry."""
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "", **kw) -> Histogram:
    """Get-or-create a histogram (bucketed distribution) on the process registry."""
    return REGISTRY.histogram(name, help, **kw)


def metrics_doc(source: str = "unknown") -> dict:
    """The current ``metrics.json`` document (validated shape)."""
    return REGISTRY.collect(source=source)


def export_metrics(path: str, source: str = "unknown") -> str:
    """Write ``metrics.json`` for this run; returns the path."""
    return REGISTRY.export(path, source=source)


def reset() -> None:
    """Drop all metrics (test isolation / fresh-run boundary)."""
    REGISTRY.reset()


__all__ = [
    "REGISTRY", "SCHEMA", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "quantile_from_counts", "validate_metrics", "INSTRUMENT_ATTR",
    "SpanHandle", "current_span", "instrument",
    "on_phases", "scope", "span_depth", "COLLECTIVE_OPS", "collective_volume",
    "harvest", "harvest_many", "AUDIT_N", "AUDIT_NB", "RoutineSpec",
    "audit_all", "audit_routine", "make_grid", "spec_names", "specs",
    "TIMESERIES_SCHEMA", "TimeSeriesSampler", "validate_timeseries",
    "SLO", "SLOMonitor", "SLOVerdict", "STATUS_CODES", "default_serve_slos",
    "counter", "gauge", "histogram", "metrics_doc", "export_metrics", "reset",
]
