"""Compiled-cost audit: collective volume + per-device flops/bytes from XLA.

This generalizes the PR-2 ``kernel_plan``-vs-``traced_plan`` pattern (one
Pallas launch audited against its traced index maps) to *whole distributed
programs*: for a compiled SPMD executable, harvest

* ``cost_analysis()`` — per-device flops and bytes accessed (GSPMD partitions
  before backend compilation, so the compiled module IS the per-device
  program), and
* the compiled HLO text — every collective op (all-reduce / all-gather /
  reduce-scatter / all-to-all / collective-permute / collective-broadcast,
  sync or async ``-start`` form) with its output shape, summed into bytes.

The byte accounting is **static-site** volume: each collective instruction
counts once with its compiled shape.  Collectives inside a ``while`` loop
execute once per iteration at run time, so absolute numbers are a lower
bound there — but the number is *deterministic for a given program*, which
is what a CI envelope needs: a schedule change that doubles the gathered
panel or swaps a psum for an all-gather moves the static volume immediately.
SCALING.md records the caveat next to the numbers.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

#: collective opcodes audited (HLO spellings); ``-start`` async variants are
#: folded into their base op, ``-done`` halves are skipped (no double count)
COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
                  "collective-permute", "collective-broadcast")

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# one typed shape: f32[128,256]{1,0:T(8,128)} / u32[] / pred[4]
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
# an HLO instruction: %name = <shape-or-tuple> opcode(
# the tuple alternative tolerates one paren-nesting level so tiled-layout
# annotations inside tuple shapes — `(f32[128,128]{1,0:T(8,128)}, ...)` on
# TPU-compiled modules — don't truncate the match and drop the opcode
_INSTR_RE = re.compile(
    r"=\s*((?:\((?:[^()]|\([^()]*\))*\))|(?:[a-z0-9]+\[[^\]]*\]\S*))"
    r"\s+([a-z0-9-]+)\(")


def _shape_bytes(shape_text: str) -> int:
    """Bytes of one HLO shape string; tuple shapes sum their elements."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:           # token[] / opaque[] / unknown — no bytes
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * size
    return total


def collective_volume(hlo_text: str) -> Dict[str, Any]:
    """Parse compiled HLO text into the collective-op bill of materials.

    Returns ``{"total_bytes": int, "total_count": int,
    "ops": {op: {"count": n, "bytes": b}}}`` — bytes are the collective's
    output shape (the data each device materializes from the fabric at that
    site), per device, static sites only (module docstring caveat).
    """
    ops: Dict[str, Dict[str, int]] = {}
    for m in _INSTR_RE.finditer(hlo_text):
        shape_text, opcode = m.group(1), m.group(2)
        if opcode.endswith("-done"):
            continue
        is_start = opcode.endswith("-start")
        base = opcode[:-6] if is_start else opcode
        if base not in COLLECTIVE_OPS:
            continue
        if is_start:
            # an async start's tuple shape is (operand-alias, result, ...,
            # context): bill only the result — element 1 when the tuple has
            # one (trailing u32[] scheduling contexts would undercount a
            # shapes[-1] pick) — so the async form measures the same bytes
            # as its sync spelling (no double count)
            shapes = _SHAPE_RE.findall(shape_text)
            size = 0
            if shapes:
                dtype, dims = shapes[1] if len(shapes) >= 2 else shapes[0]
                per = _DTYPE_BYTES.get(dtype, 0)
                n = 1
                for d in dims.split(","):
                    if d:
                        n *= int(d)
                size = n * per
        else:
            size = _shape_bytes(shape_text)
        entry = ops.setdefault(base, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += size
    return {"total_bytes": sum(o["bytes"] for o in ops.values()),
            "total_count": sum(o["count"] for o in ops.values()),
            "ops": ops}


# ---------------------------------------------------------------------------
# computation-aware HLO parsing (shared with slate_tpu.analysis's collective
# race auditor, which needs *ordering* and call structure, not just counts)

# computation header: `%name (params) -> type {` or `ENTRY %name (...) ... {`
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->.*\{\s*$")
# one instruction line: `  [ROOT ]%name = <shape> opcode(...), attrs`
_LINE_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"
    r"((?:\((?:[^()]|\([^()]*\))*\))|(?:[a-z0-9]+\[[^\]]*\]\S*))"
    r"\s+([a-z0-9\-]+)\((.*)$")
# computation references inside an instruction's attribute tail
_CALLEE_ATTRS = ("to_apply", "body", "condition", "true_computation",
                 "false_computation", "calls")
_CALLEE_RE = re.compile(
    r"\b(" + "|".join(_CALLEE_ATTRS) + r")=%?([\w.\-]+)")
_BRANCHES_RE = re.compile(r"\bbranch_computations=\{([^}]*)\}")
_CHANNEL_RE = re.compile(r"\bchannel_id=(\d+)")
_GROUPS_RE = re.compile(r"\breplica_groups=\{(.*?)\}\}|"
                        r"\breplica_groups=\{\}")
_GROUPS_IOTA_RE = re.compile(
    r"\breplica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_PAIRS_RE = re.compile(r"\bsource_target_pairs=\{(.*?)\}\}")
_PAIR_RE = re.compile(r"\{(\d+),(\d+)\}")


@dataclasses.dataclass(frozen=True)
class Instr:
    """One parsed HLO instruction (name/shape/opcode + raw attribute tail)."""

    name: str
    shape: str
    opcode: str
    tail: str          #: operands + attributes text after the opening paren
    is_root: bool = False   #: carried the ``ROOT`` marker (computation output)

    def base_opcode(self) -> str:
        """Opcode with the async ``-start`` suffix folded (an
        ``all-reduce-start`` is the same rendezvous as its sync spelling);
        ``-done`` halves are left distinct so walkers can skip them."""
        return self.opcode[:-6] if self.opcode.endswith("-start") \
            else self.opcode

    def channel_id(self) -> Optional[int]:
        m = _CHANNEL_RE.search(self.tail)
        return int(m.group(1)) if m else None

    def replica_groups(self) -> Optional[Tuple[Tuple[int, ...], ...]]:
        """Explicit or iota-form replica groups; ``()`` means "all devices in
        one group" (HLO's ``replica_groups={}``), None when absent."""
        m = _GROUPS_IOTA_RE.search(self.tail)
        if m:
            ngroups, gsize = int(m.group(1)), int(m.group(2))
            dims = [int(d) for d in m.group(3).split(",")]
            ids = _iota_ids(dims, m.group(4))
            return tuple(tuple(ids[g * gsize:(g + 1) * gsize])
                         for g in range(ngroups))
        if "replica_groups={}" in self.tail:
            return ()
        m = _GROUPS_RE.search(self.tail)
        if m and m.group(1) is not None:
            groups = []
            for part in re.finditer(r"\{([\d,\s]*)\}", "{" + m.group(1) + "}}"):
                ids = [int(t) for t in part.group(1).split(",") if t.strip()]
                groups.append(tuple(ids))
            return tuple(g for g in groups if g)
        return None

    def source_target_pairs(self) -> Optional[Tuple[Tuple[int, int], ...]]:
        m = _PAIRS_RE.search(self.tail)
        if not m:
            return None
        return tuple((int(a), int(b))
                     for a, b in _PAIR_RE.findall("{" + m.group(1) + "}}"))

    def operand_text(self) -> str:
        """The operand section of the tail — text up to the close paren that
        matches the opcode's open paren (tuple-shape annotations inside
        operands nest parens; attrs follow the close)."""
        depth = 1
        for i, ch in enumerate(self.tail):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    return self.tail[:i]
        return self.tail

    def operand_refs(self) -> List[str]:
        """Names of the instructions this one consumes (in operand order)."""
        return [m.group(1) for m in
                re.finditer(r"%([\w.\-]+)", self.operand_text())]

    def callees(self) -> Dict[str, List[str]]:
        """attr -> called computation names (``branch_computations`` folded
        in as an ordered list)."""
        out: Dict[str, List[str]] = {}
        for attr, name in _CALLEE_RE.findall(self.tail):
            out.setdefault(attr, []).append(name)
        m = _BRANCHES_RE.search(self.tail)
        if m:
            out["branch_computations"] = [
                t.strip().lstrip("%") for t in m.group(1).split(",")
                if t.strip()]
        return out


def _iota_ids(dims: List[int], perm_text: Optional[str]) -> List[int]:
    """Decode HLO's iota replica-group list: iota over prod(dims), reshaped
    to ``dims``, transposed by ``perm``, flattened."""
    n = 1
    for d in dims:
        n *= d
    ids = list(range(n))
    if perm_text:
        perm = [int(p) for p in perm_text.split(",")]
        # row-major reshape + transpose without numpy (jax-free module)
        strides = [1] * len(dims)
        for i in range(len(dims) - 2, -1, -1):
            strides[i] = strides[i + 1] * dims[i + 1]
        tdims = [dims[p] for p in perm]
        tstrides = [strides[p] for p in perm]
        out = []

        def rec(depth, off):
            if depth == len(tdims):
                out.append(ids[off])
                return
            for i in range(tdims[depth]):
                rec(depth + 1, off + i * tstrides[depth])

        rec(0, 0)
        ids = out
    return ids


_NUM_PARTITIONS_RE = re.compile(r"\bnum_partitions=(\d+)")


def module_num_partitions(hlo_text: str) -> Optional[int]:
    """The SPMD partition count from the HloModule header (None if absent)."""
    m = _NUM_PARTITIONS_RE.search(hlo_text)
    return int(m.group(1)) if m else None


def parse_computations(hlo_text: str
                       ) -> Tuple[Dict[str, List[Instr]], Optional[str]]:
    """Split compiled HLO text into per-computation instruction lists.

    Returns ``(computations, entry_name)`` — instruction order within each
    computation is the printed order, which for ``is_scheduled=true`` modules
    (every ``Compiled.as_text()``) is the execution schedule."""
    comps: Dict[str, List[Instr]] = {}
    entry: Optional[str] = None
    current: Optional[str] = None
    for line in hlo_text.splitlines():
        m = _COMP_RE.match(line.strip())
        if m and not line.lstrip().startswith("%param") \
                and "=" not in line.split("(")[0]:
            current = m.group(2)
            comps[current] = []
            if m.group(1):
                entry = current
            continue
        if line.strip() == "}":
            current = None
            continue
        if current is None:
            continue
        im = _LINE_RE.match(line)
        if im:
            comps[current].append(Instr(name=im.group(1), shape=im.group(2),
                                        opcode=im.group(3), tail=im.group(4),
                                        is_root=line.lstrip()
                                        .startswith("ROOT ")))
    return comps, entry


def _cost_analysis(compiled) -> Dict[str, float]:
    """``Compiled.cost_analysis()`` across jax versions (same shim as
    ``slate_tpu.testing.cost_analysis_dict`` — duplicated here so obs does
    not import the tester)."""
    try:
        ca = compiled.cost_analysis()
    # slate-lint: disable=SLT501 -- version shim: cost_analysis raises
    # different errors across jax releases; nothing numerical executes here
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def harvest(compiled) -> Dict[str, Any]:
    """Audit one compiled executable: per-device flops/bytes + collectives.

    ``compiled`` is a ``jax.stages.Compiled`` (``jit(f).lower(...).compile()``).
    Returns::

        {"flops": float, "bytes_accessed": float,
         "collective_bytes": int, "collective_count": int,
         "collectives": {op: {count, bytes}},
         "comm_compute_ratio": float | None}   # collective bytes per flop
    """
    ca = _cost_analysis(compiled)
    try:
        hlo = compiled.as_text()
    # slate-lint: disable=SLT501 -- HLO rendering shim: as_text availability
    # varies by backend/version; nothing numerical executes here
    except Exception:
        hlo = ""
    vol = collective_volume(hlo)
    flops = float(ca.get("flops", 0.0))
    out = {
        "flops": flops,
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "collective_bytes": int(vol["total_bytes"]),
        "collective_count": int(vol["total_count"]),
        "collectives": vol["ops"],
        "comm_compute_ratio": (vol["total_bytes"] / flops) if flops > 0
        else None,
    }
    return out


def harvest_many(compiled_list) -> Dict[str, Any]:
    """Sum :func:`harvest` across several compiled programs.

    Host-composed drivers lower to more than one executable."""
    agg: Dict[str, Any] = {"flops": 0.0, "bytes_accessed": 0.0,
                           "collective_bytes": 0, "collective_count": 0,
                           "collectives": {}, "programs": 0}
    for compiled in compiled_list:
        h = harvest(compiled)
        agg["flops"] += h["flops"]
        agg["bytes_accessed"] += h["bytes_accessed"]
        agg["collective_bytes"] += h["collective_bytes"]
        agg["collective_count"] += h["collective_count"]
        agg["programs"] += 1
        for op, e in h["collectives"].items():
            dst = agg["collectives"].setdefault(op, {"count": 0, "bytes": 0})
            dst["count"] += e["count"]
            dst["bytes"] += e["bytes"]
    agg["comm_compute_ratio"] = (agg["collective_bytes"] / agg["flops"]
                                 if agg["flops"] > 0 else None)
    return agg
