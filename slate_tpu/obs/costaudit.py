"""Compiled-cost audit: collective volume + per-device flops/bytes from XLA.

This generalizes the PR-2 ``kernel_plan``-vs-``traced_plan`` pattern (one
Pallas launch audited against its traced index maps) to *whole distributed
programs*: for a compiled SPMD executable, harvest

* ``cost_analysis()`` — per-device flops and bytes accessed (GSPMD partitions
  before backend compilation, so the compiled module IS the per-device
  program), and
* the compiled HLO text — every collective op (all-reduce / all-gather /
  reduce-scatter / all-to-all / collective-permute / collective-broadcast,
  sync or async ``-start`` form) with its output shape, summed into bytes.

The byte accounting is **static-site** volume: each collective instruction
counts once with its compiled shape.  Collectives inside a ``while`` loop
execute once per iteration at run time, so absolute numbers are a lower
bound there — but the number is *deterministic for a given program*, which
is what a CI envelope needs: a schedule change that doubles the gathered
panel or swaps a psum for an all-gather moves the static volume immediately.
SCALING.md records the caveat next to the numbers.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

#: collective opcodes audited (HLO spellings); ``-start`` async variants are
#: folded into their base op, ``-done`` halves are skipped (no double count)
COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
                  "collective-permute", "collective-broadcast")

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# one typed shape: f32[128,256]{1,0:T(8,128)} / u32[] / pred[4]
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
# an HLO instruction: %name = <shape-or-tuple> opcode(
# the tuple alternative tolerates one paren-nesting level so tiled-layout
# annotations inside tuple shapes — `(f32[128,128]{1,0:T(8,128)}, ...)` on
# TPU-compiled modules — don't truncate the match and drop the opcode
_INSTR_RE = re.compile(
    r"=\s*((?:\((?:[^()]|\([^()]*\))*\))|(?:[a-z0-9]+\[[^\]]*\]\S*))"
    r"\s+([a-z0-9-]+)\(")


def _shape_bytes(shape_text: str) -> int:
    """Bytes of one HLO shape string; tuple shapes sum their elements."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:           # token[] / opaque[] / unknown — no bytes
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * size
    return total


def collective_volume(hlo_text: str) -> Dict[str, Any]:
    """Parse compiled HLO text into the collective-op bill of materials.

    Returns ``{"total_bytes": int, "total_count": int,
    "ops": {op: {"count": n, "bytes": b}}}`` — bytes are the collective's
    output shape (the data each device materializes from the fabric at that
    site), per device, static sites only (module docstring caveat).
    """
    ops: Dict[str, Dict[str, int]] = {}
    for m in _INSTR_RE.finditer(hlo_text):
        shape_text, opcode = m.group(1), m.group(2)
        if opcode.endswith("-done"):
            continue
        is_start = opcode.endswith("-start")
        base = opcode[:-6] if is_start else opcode
        if base not in COLLECTIVE_OPS:
            continue
        if is_start:
            # an async start's tuple shape is (operand-alias, result, ...,
            # context): bill only the result — element 1 when the tuple has
            # one (trailing u32[] scheduling contexts would undercount a
            # shapes[-1] pick) — so the async form measures the same bytes
            # as its sync spelling (no double count)
            shapes = _SHAPE_RE.findall(shape_text)
            size = 0
            if shapes:
                dtype, dims = shapes[1] if len(shapes) >= 2 else shapes[0]
                per = _DTYPE_BYTES.get(dtype, 0)
                n = 1
                for d in dims.split(","):
                    if d:
                        n *= int(d)
                size = n * per
        else:
            size = _shape_bytes(shape_text)
        entry = ops.setdefault(base, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += size
    return {"total_bytes": sum(o["bytes"] for o in ops.values()),
            "total_count": sum(o["count"] for o in ops.values()),
            "ops": ops}


def _cost_analysis(compiled) -> Dict[str, float]:
    """``Compiled.cost_analysis()`` across jax versions (same shim as
    ``slate_tpu.testing.cost_analysis_dict`` — duplicated here so obs does
    not import the tester)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def harvest(compiled) -> Dict[str, Any]:
    """Audit one compiled executable: per-device flops/bytes + collectives.

    ``compiled`` is a ``jax.stages.Compiled`` (``jit(f).lower(...).compile()``).
    Returns::

        {"flops": float, "bytes_accessed": float,
         "collective_bytes": int, "collective_count": int,
         "collectives": {op: {count, bytes}},
         "comm_compute_ratio": float | None}   # collective bytes per flop
    """
    ca = _cost_analysis(compiled)
    try:
        hlo = compiled.as_text()
    except Exception:
        hlo = ""
    vol = collective_volume(hlo)
    flops = float(ca.get("flops", 0.0))
    out = {
        "flops": flops,
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "collective_bytes": int(vol["total_bytes"]),
        "collective_count": int(vol["total_count"]),
        "collectives": vol["ops"],
        "comm_compute_ratio": (vol["total_bytes"] / flops) if flops > 0
        else None,
    }
    return out


def harvest_many(compiled_list) -> Dict[str, Any]:
    """Sum :func:`harvest` across several compiled programs.

    Host-composed drivers lower to more than one executable."""
    agg: Dict[str, Any] = {"flops": 0.0, "bytes_accessed": 0.0,
                           "collective_bytes": 0, "collective_count": 0,
                           "collectives": {}, "programs": 0}
    for compiled in compiled_list:
        h = harvest(compiled)
        agg["flops"] += h["flops"]
        agg["bytes_accessed"] += h["bytes_accessed"]
        agg["collective_bytes"] += h["collective_bytes"]
        agg["collective_count"] += h["collective_count"]
        agg["programs"] += 1
        for op, e in h["collectives"].items():
            dst = agg["collectives"].setdefault(op, {"count": 0, "bytes": 0})
            dst["count"] += e["count"]
            dst["bytes"] += e["bytes"]
    agg["comm_compute_ratio"] = (agg["collective_bytes"] / agg["flops"]
                                 if agg["flops"] > 0 else None)
    return agg
