"""Metrics registry: counters, gauges, histograms with labels.

Reference analogue: none — SLATE's observability is the tester's printed
columns plus trace SVGs.  This registry is the unification point the round-8
issue asks for: the phase timers (utils/trace.py), the resilience layer's
retry/fallback/fault events (robust/), the tester's ``TestResult.details``
side-channel, and the bench children all report here, and one
``metrics.json`` document (schema ``slate_tpu.metrics/v1``) serializes the
lot for CI and offline diffing.

Design points:

* **Label model** — every sample carries a flat ``{str: str}`` label map
  (routine, dtype, shape_bucket, mesh, lu_panel, method, ...).  Label sets
  are canonicalized to sorted tuples so ``inc(a=1, b=2)`` and
  ``inc(b=2, a=1)`` hit the same series.
* **Cardinality cap** — a metric holds at most :data:`MAX_SERIES` distinct
  label sets; past the cap new series fold into one ``{"overflow": "true"}``
  series instead of growing without bound (a sweep over thousands of shapes
  must not turn the registry into the memory leak it is meant to audit).
* **Histograms** — fixed upper-bound buckets (default: log-spaced seconds);
  counts has one extra slot for the overflow bucket, plus sum/count for
  mean-rate queries.
* **Thread safety** — one lock around every mutation; the tester and bench
  both run host threads.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

SCHEMA = "slate_tpu.metrics/v1"

#: per-metric label-set cap (see module docstring)
MAX_SERIES = 512

#: default histogram upper bounds — log-spaced around solver wall times
#: (sub-ms dispatches up to multi-minute distributed factorizations)
DEFAULT_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                   1.0, 5.0, 10.0, 30.0, 60.0, 300.0)

_OVERFLOW_KEY = (("overflow", "true"),)

LabelKey = Tuple[Tuple[str, str], ...]


def _canon(labels: Dict[str, Any]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def quantile_from_counts(buckets: Sequence[float], counts: Sequence[int],
                         q: float) -> Optional[float]:
    """Estimate the ``q``-quantile (0 <= q <= 1) of a bucketed distribution.

    Prometheus-style: find the bucket holding the target rank and linearly
    interpolate within it (the first bucket interpolates from 0, assuming
    non-negative observations — true of every duration/count histogram
    here).  Observations in the overflow slot clamp to the largest bound —
    the estimator cannot see past its bucket table, so a p99 that lands
    there reads as ">= last bound", not a fabricated value.  Returns None
    on an empty distribution.  Shared by :meth:`Histogram.quantile` and the
    per-window delta estimation in :mod:`.timeseries`."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    seen = 0.0
    for i, ub in enumerate(buckets):
        c = counts[i]
        if seen + c >= rank and c > 0:
            lo = buckets[i - 1] if i > 0 else 0.0
            frac = (rank - seen) / c
            return lo + (ub - lo) * min(max(frac, 0.0), 1.0)
        seen += c
    return float(buckets[-1])     # rank fell in the overflow slot


class _Metric:
    """Base: a named family of label-keyed series."""

    kind = "abstract"

    def __init__(self, name: str, help: str = "",
                 registry: "MetricsRegistry" = None):
        self.name = name
        self.help = help
        self._registry = registry
        self._series: Dict[LabelKey, Any] = {}
        self._lock = registry._lock if registry is not None \
            else threading.Lock()

    def _key(self, labels: Dict[str, Any]) -> LabelKey:
        key = _canon(labels)
        if key not in self._series and len(self._series) >= MAX_SERIES:
            return _OVERFLOW_KEY
        return key

    def series(self) -> Dict[LabelKey, Any]:
        with self._lock:
            return dict(self._series)

    def labeled(self, **labels):
        """The sample value for one exact label set (None when absent)."""
        with self._lock:
            return self._series.get(_canon(labels))


class Counter(_Metric):
    """Monotonic accumulator (retries, faults, spans, test rows)."""

    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        if value < 0:
            raise ValueError(f"counter {self.name}: negative increment {value}")
        with self._lock:
            key = self._key(labels)
            self._series[key] = self._series.get(key, 0.0) + float(value)

    def value(self, **labels) -> float:
        return float(self.labeled(**labels) or 0.0)


class Gauge(_Metric):
    """Last-write-wins sample (mesh size, HBM footprint, queue depth)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[self._key(labels)] = float(value)

    def value(self, **labels) -> Optional[float]:
        return self.labeled(**labels)


class Histogram(_Metric):
    """Bucketed distribution (span durations, IR iteration counts)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 registry: "MetricsRegistry" = None):
        super().__init__(name, help, registry)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"histogram {self.name}: empty bucket list")
        self.buckets = bs

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        with self._lock:
            key = self._key(labels)
            state = self._series.get(key)
            if state is None:
                state = {"counts": [0] * (len(self.buckets) + 1),
                         "sum": 0.0, "count": 0}
                self._series[key] = state
            idx = len(self.buckets)            # overflow slot by default
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    idx = i
                    break
            state["counts"][idx] += 1
            state["sum"] += value
            state["count"] += 1

    def snapshot(self, **labels) -> Optional[Dict[str, Any]]:
        state = self.labeled(**labels)
        if state is None:
            return None
        return {"buckets": list(self.buckets),
                "counts": list(state["counts"]),
                "sum": state["sum"], "count": state["count"]}

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Bucket-interpolated ``q``-quantile (0 <= q <= 1) of one series —
        p50/p99 derivable live, not just end-of-run (see
        :func:`quantile_from_counts` for the estimator and its clamping at
        the overflow slot).  None when the series has no observations."""
        state = self.labeled(**labels)
        if state is None:
            return None
        return quantile_from_counts(self.buckets, state["counts"], q)


class MetricsRegistry:
    """The process-wide metric family table.

    ``counter/gauge/histogram`` are get-or-create: repeated calls with the
    same name return the same object; a name reused across kinds raises (the
    one schema must stay coherent across bench, tester, and chaos runs).
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}

    def _get(self, cls, name: str, help: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, registry=self, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{m.kind}, requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        h = self._get(Histogram, name, help, buckets=buckets)
        want = tuple(sorted(float(b) for b in buckets))
        if want != h.buckets and want != tuple(DEFAULT_BUCKETS):
            # a get with explicit non-default bounds against a family created
            # with different ones would silently mis-bucket its observations;
            # passing the default means "whatever exists" and stays a lookup
            raise ValueError(
                f"histogram {name!r} exists with buckets {h.buckets}, "
                f"requested {want}")
        return h

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def reset(self) -> None:
        """Drop every metric family (tests; a fresh run's clean slate)."""
        with self._lock:
            self._metrics.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._metrics)

    # -- serialization ------------------------------------------------------
    def collect(self, source: str = "unknown") -> Dict[str, Any]:
        """The ``metrics.json`` document (schema ``slate_tpu.metrics/v1``) —
        the one shape bench, tester, and chaos-suite runs all emit."""
        with self._lock:
            metrics: List[Dict[str, Any]] = []
            for name in sorted(self._metrics):
                m = self._metrics[name]
                samples = []
                for key in sorted(m._series):
                    val = m._series[key]
                    sample: Dict[str, Any] = {"labels": dict(key)}
                    if m.kind == "histogram":
                        sample.update(buckets=list(m.buckets),
                                      counts=list(val["counts"]),
                                      sum=val["sum"], count=val["count"])
                    else:
                        sample["value"] = val
                    samples.append(sample)
                metrics.append({"name": name, "kind": m.kind,
                                "help": m.help, "samples": samples})
        return {"schema": SCHEMA, "source": str(source),
                "created_unix": round(time.time(), 3), "metrics": metrics}

    def export(self, path: str, source: str = "unknown") -> str:
        doc = self.collect(source=source)
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=False)
            f.write("\n")
        return path


def validate_metrics(doc: Any) -> None:
    """Schema-check a ``metrics.json`` document, raising on the first violation.

    The schema test runs bench/tester/chaos documents through this, so the
    three producers cannot drift apart silently."""
    if not isinstance(doc, dict):
        raise ValueError(f"metrics doc must be a dict, got {type(doc)}")
    if doc.get("schema") != SCHEMA:
        raise ValueError(f"schema must be {SCHEMA!r}, got {doc.get('schema')!r}")
    if not isinstance(doc.get("source"), str):
        raise ValueError("source must be a string")
    if not isinstance(doc.get("created_unix"), (int, float)):
        raise ValueError("created_unix must be a number")
    metrics = doc.get("metrics")
    if not isinstance(metrics, list):
        raise ValueError("metrics must be a list")
    for m in metrics:
        name = m.get("name")
        if not isinstance(name, str) or not name:
            raise ValueError(f"metric name missing/empty: {m!r}")
        kind = m.get("kind")
        if kind not in ("counter", "gauge", "histogram"):
            raise ValueError(f"{name}: bad kind {kind!r}")
        if not isinstance(m.get("samples"), list):
            raise ValueError(f"{name}: samples must be a list")
        for s in m["samples"]:
            labels = s.get("labels")
            if not isinstance(labels, dict) or not all(
                    isinstance(k, str) and isinstance(v, str)
                    for k, v in labels.items()):
                raise ValueError(f"{name}: labels must be str->str")
            if kind == "histogram":
                bs, cs = s.get("buckets"), s.get("counts")
                if not isinstance(bs, list) or not isinstance(cs, list):
                    raise ValueError(f"{name}: histogram needs buckets+counts")
                if len(cs) != len(bs) + 1:
                    raise ValueError(
                        f"{name}: counts must have len(buckets)+1 slots "
                        f"(got {len(cs)} for {len(bs)} buckets)")
                if not isinstance(s.get("sum"), (int, float)) \
                        or not isinstance(s.get("count"), int):
                    raise ValueError(f"{name}: histogram needs sum+count")
            else:
                if not isinstance(s.get("value"), (int, float)):
                    raise ValueError(f"{name}: sample value must be numeric")


#: the process-wide registry every subsystem reports into
REGISTRY = MetricsRegistry()
