"""Per-routine scaling audit: compile every distributed routine on a P-device
mesh and harvest its collective volume + per-device flops/bytes.

This is the in-env evidence layer behind SCALING.md (ROADMAP item 4): each
:class:`RoutineSpec` knows how to AOT-compile one ``parallel/`` routine at a
fixed audit shape on a CPU mesh (``jit(...).lower(...).compile()`` — nothing
executes, same discipline as tools/twostage_scale.py), and
:func:`audit_routine` runs the compiled module through
:mod:`slate_tpu.obs.costaudit`.  ``tools/gen_scaling.py`` renders the table
at P ∈ {2, 4, 8} and pins the P=2 collective volumes for CI
(tests/test_perf_pins.py).

Audit shapes are deliberately small (n=128-class): the *shape* of the
compiled program — which collectives, how many, what they carry relative to
the problem — is what regresses when a schedule changes, and it shows at any
size.  Absolute volumes at BASELINE scale follow from the same program by
the documented per-site shapes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .costaudit import harvest

#: the default audit problem edge (divisible by every grid in P ∈ {2,4,8}
#: and by the nb=32 blocking the specs use)
AUDIT_N = 128
AUDIT_NB = 32
#: band audits: half-bandwidth small enough for the chase's seg >= 2kd+2
#: constraint at P=8 (seg = 128/8 = 16 >= 2*4+2)
AUDIT_KD = 4

_DTYPE = np.float32


@dataclasses.dataclass(frozen=True)
class RoutineSpec:
    """One audited distributed routine.

    name:     row label (the public driver's name).
    module:   owning ``slate_tpu.parallel`` module (table grouping).
    build:    ``build(grid) -> jax.stages.Compiled`` for the audit shape.
    model_flops: whole-problem flop model at the audit shape (the table's
              "model" column; per-device flops come from cost_analysis).
    requires: optional grid predicate (e.g. Cannon's square-grid-only ring).
    """

    name: str
    module: str
    build: Callable[[Any], Any]
    model_flops: float = 0.0
    requires: Optional[Callable[[Any], bool]] = None


def _rng(seed: int = 0):
    return np.random.default_rng(seed)


def _randn(m: int, n: int):
    import jax.numpy as jnp

    return jnp.asarray(_rng(m * 131 + n).standard_normal((m, n)),
                       dtype=_DTYPE)


def _spd(n: int):
    import jax.numpy as jnp

    g = _rng(n).standard_normal((n, n))
    return jnp.asarray(g @ g.T + n * np.eye(n), dtype=_DTYPE)


def _randn_batch(b: int, m: int, n: int):
    import jax.numpy as jnp

    a = _rng(b * 17 + m).standard_normal((b, m, n))
    if m == n:
        a = a + m * np.eye(m)      # diagonally dominant: well-posed solves
    return jnp.asarray(a, dtype=_DTYPE)


def _spd_batch(b: int, n: int):
    import jax.numpy as jnp

    g = _rng(b * 31 + n).standard_normal((b, n, n))
    return jnp.asarray(g @ np.swapaxes(g, -1, -2) + n * np.eye(n),
                       dtype=_DTYPE)


def _aot(fn, *args):
    """AOT-compile ``fn(*args)`` (compile-only: nothing executes)."""
    import jax

    return jax.jit(fn).lower(*args).compile()


def _square_grid(grid) -> bool:
    return grid.p == grid.q


def _build_specs() -> List[RoutineSpec]:
    """The audit table.  Imports live inside the builders so ``import
    slate_tpu.obs`` stays jax-light; every builder closes over nothing but
    the grid handed to it."""
    from ..parallel import (band_dist, batched, blas3_dist, chase_dist,
                            eig_dist, indefinite_dist, inverse, lu_dist,
                            pipeline, qr_dist, rbt, secular, solvers, summa)

    n, nb, kd = AUDIT_N, AUDIT_NB, AUDIT_KD
    mt = 4 * n                     # tall-panel audit height
    nrhs = 16

    specs = [
        # -- summa ----------------------------------------------------------
        RoutineSpec(
            "gemm_allgather", "summa",
            lambda g: _aot(lambda a, b: summa.gemm_allgather(a, b, g),
                           _randn(n, n), _randn(n, n)),
            model_flops=2 * n**3),
        RoutineSpec(
            "gemm_ring", "summa",
            lambda g: _aot(lambda a, b: summa.gemm_ring(a, b, g),
                           _randn(n, n), _randn(n, n)),
            model_flops=2 * n**3, requires=_square_grid),
        # -- blas3_dist ------------------------------------------------------
        RoutineSpec(
            "herk_distributed", "blas3_dist",
            lambda g: _aot(lambda a, c: blas3_dist.herk_distributed(
                1.0, a, 0.0, c, g), _randn(n, n), _spd(n)),
            model_flops=n**3),
        RoutineSpec(
            "trmm_distributed", "blas3_dist",
            lambda g: _aot(lambda a, b: blas3_dist.trmm_distributed(
                "left", 1.0, a, b, g), _spd(n), _randn(n, n)),
            model_flops=n**3),
        # -- solvers ---------------------------------------------------------
        RoutineSpec(
            "potrf_distributed", "solvers",
            lambda g: _aot(lambda a: solvers.potrf_distributed(a, g, nb=nb),
                           _spd(n)),
            model_flops=n**3 / 3),
        RoutineSpec(
            "trsm_distributed", "solvers",
            lambda g: _aot(lambda l, b: solvers.trsm_distributed(l, b, g),
                           _spd(n), _randn(n, nrhs)),
            model_flops=n * n * nrhs),
        RoutineSpec(
            "trsmA_distributed", "solvers",
            lambda g: _aot(lambda a, b: solvers.trsmA_distributed(a, b, g),
                           _spd(n), _randn(n, nrhs)),
            model_flops=n * n * nrhs),
        RoutineSpec(
            "posv_distributed", "solvers",
            lambda g: _aot(lambda a, b: solvers.posv_distributed(
                a, b, g, nb=nb), _spd(n), _randn(n, nrhs)),
            model_flops=n**3 / 3 + 2 * n * n * nrhs),
        RoutineSpec(
            "cholqr_distributed", "solvers",
            lambda g: _aot(lambda a: solvers.cholqr_distributed(a, g),
                           _randn(mt, nb)),
            model_flops=2 * mt * nb * nb),
        RoutineSpec(
            "gels_cholqr_distributed", "solvers",
            lambda g: _aot(lambda a, b: solvers.gels_cholqr_distributed(
                a, b, g), _randn(mt, nb), _randn(mt, nrhs)),
            model_flops=2 * mt * nb * nb + 2 * mt * nb * nrhs),
        # -- lu_dist ---------------------------------------------------------
        RoutineSpec(
            "getrf_distributed", "lu_dist",
            lambda g: _aot(lambda a: lu_dist.getrf_distributed(a, g, nb=nb),
                           _randn(n, n)),
            model_flops=2 * n**3 / 3),
        RoutineSpec(
            "getrf_tall_distributed", "lu_dist",
            lambda g: _aot(lambda a: lu_dist.getrf_tall_distributed(
                a, g, nb=nb), _randn(mt, nb)),
            model_flops=mt * nb * nb),
        RoutineSpec(
            "gesv_distributed", "lu_dist",
            lambda g: _aot(lambda a, b: lu_dist.gesv_distributed(
                a, b, g, nb=nb), _randn(n, n), _randn(n, nrhs)),
            model_flops=2 * n**3 / 3 + 2 * n * n * nrhs),
        # -- rbt -------------------------------------------------------------
        RoutineSpec(
            "getrf_nopiv_distributed", "rbt",
            lambda g: _aot(lambda a: rbt.getrf_nopiv_distributed(
                a, g, nb=nb), _spd(n)),
            model_flops=2 * n**3 / 3),
        # -- qr_dist ---------------------------------------------------------
        RoutineSpec(
            "tsqr_distributed", "qr_dist",
            lambda g: _aot(lambda a: qr_dist.tsqr_distributed(a, g),
                           _randn(mt, nb)),
            model_flops=2 * mt * nb * nb),
        RoutineSpec(
            "geqrf_distributed", "qr_dist",
            lambda g: _aot(lambda a: qr_dist.geqrf_distributed(a, g, nb=nb),
                           _randn(n, n)),
            model_flops=4 * n**3 / 3),
        # -- eig_dist --------------------------------------------------------
        RoutineSpec(
            "he2hb_distributed", "eig_dist",
            lambda g: _aot(lambda a: eig_dist.he2hb_distributed(a, g, nb=nb),
                           _spd(n)),
            model_flops=4 * n**3 / 3),
        RoutineSpec(
            "ge2tb_distributed", "eig_dist",
            lambda g: _aot(lambda a: eig_dist.ge2tb_distributed(a, g, nb=nb),
                           _randn(n, n)),
            model_flops=8 * n**3 / 3),
        RoutineSpec(
            "norm_distributed", "eig_dist",
            lambda g: _aot(lambda a: eig_dist.norm_distributed("fro", a, g),
                           _randn(n, n)),
            model_flops=2 * n * n),
        RoutineSpec(
            "steqr_distributed", "eig_dist",
            lambda g: _aot(lambda d, e: eig_dist.steqr_distributed(d, e, g),
                           _randn(n, 1)[:, 0],
                           _randn(n - 1, 1)[:, 0]),
            model_flops=6 * n**3),
        # -- secular ---------------------------------------------------------
        RoutineSpec(
            "secular_roots_sharded", "secular",
            lambda g: _aot(
                lambda d, z2: secular.secular_roots_sharded(
                    d, z2, np.float32(1.0), g),
                np.sort(np.abs(_rng(3).standard_normal(n))).astype(_DTYPE)
                + np.arange(n, dtype=_DTYPE),
                (np.abs(_rng(5).standard_normal(n)) + 0.1).astype(_DTYPE)),
            model_flops=90 * n * n),
        # -- chase_dist ------------------------------------------------------
        RoutineSpec(
            "hb2st_chase_distributed", "chase_dist",
            lambda g: _aot(lambda a: chase_dist.hb2st_chase_distributed(
                a, kd, g), _band_sym(n, kd)),
            model_flops=6 * n * n * kd),
        RoutineSpec(
            "tb2bd_chase_distributed", "chase_dist",
            lambda g: _aot(lambda b: chase_dist.tb2bd_chase_distributed(
                b, kd, g), _band_upper(n, kd)),
            model_flops=6 * n * n * kd),
        # -- band_dist -------------------------------------------------------
        RoutineSpec(
            "pbtrf_distributed", "band_dist",
            lambda g: _aot(lambda ab: band_dist.pbtrf_distributed(
                ab, g, kd=kd, nb=nb),
                band_dist.dense_to_band_lower(_spd(n), kd)),
            model_flops=n * kd * kd),
        RoutineSpec(
            "gbtrf_distributed", "band_dist",
            lambda g: _aot(lambda gb: band_dist.gbtrf_distributed(
                gb, g, kl=kd, ku=kd, nb=nb),
                band_dist.dense_to_band_general(_spd(n), kd, kd, extra=kd)),
            model_flops=2 * n * kd * kd),
        # -- indefinite_dist -------------------------------------------------
        RoutineSpec(
            "hetrf_distributed", "indefinite_dist",
            lambda g: _aot(lambda a: indefinite_dist.hetrf_distributed(
                a, g, nb=nb), _spd(n)),
            model_flops=n**3 / 3),
        # -- inverse ---------------------------------------------------------
        RoutineSpec(
            "trtri_distributed", "inverse",
            lambda g: _aot(lambda t: inverse.trtri_distributed(t, g),
                           _spd(n)),
            model_flops=n**3 / 3),
        RoutineSpec(
            "potri_distributed", "inverse",
            lambda g: _aot(lambda l: inverse.potri_distributed(l, g),
                           _spd(n)),
            model_flops=2 * n**3 / 3),
        # -- pipeline --------------------------------------------------------
        RoutineSpec(
            "potrf_pipelined", "pipeline",
            lambda g: _aot(lambda a: pipeline.potrf_pipelined(a, g, nb=nb),
                           _spd(n)),
            model_flops=n**3 / 3),
        # -- batched (serving tier) ------------------------------------------
        # batch=16 divides every grid in P ∈ {2,4,8}; the audited fact is
        # that the batch tier compiles with ZERO collectives — independent
        # problems shard perfectly, the one routine whose communication
        # envelope is identically nothing
        RoutineSpec(
            "gesv_batched_distributed", "batched",
            lambda g: _aot(lambda a, b: batched.gesv_batched_distributed(
                a, b, g), _randn_batch(16, nb, nb), _randn_batch(16, nb, 4)),
            model_flops=16 * (2 * nb**3 / 3 + 2 * nb * nb * 4)),
        RoutineSpec(
            "posv_batched_distributed", "batched",
            lambda g: _aot(lambda a, b: batched.posv_batched_distributed(
                a, b, g), _spd_batch(16, nb), _randn_batch(16, nb, 4)),
            model_flops=16 * (nb**3 / 3 + 2 * nb * nb * 4)),
    ]
    return specs


def _band_sym(n: int, kd: int):
    """Dense-storage Hermitian band matrix (the chase's input shape)."""
    import jax.numpy as jnp

    a = np.asarray(_spd(n))
    mask = np.abs(np.arange(n)[:, None] - np.arange(n)[None, :]) <= kd
    return jnp.asarray(a * mask, dtype=_DTYPE)


def _band_upper(n: int, kd: int):
    """Dense-storage upper-band matrix (tb2bd's input shape)."""
    import jax.numpy as jnp

    a = np.asarray(_randn(n, n))
    off = np.arange(n)[None, :] - np.arange(n)[:, None]
    mask = (off >= 0) & (off <= kd)
    return jnp.asarray(a * mask + np.eye(n) * n, dtype=_DTYPE)


_SPECS_CACHE: Optional[List[RoutineSpec]] = None


def specs() -> List[RoutineSpec]:
    """The audit registry: one RoutineSpec per audited distributed routine."""
    global _SPECS_CACHE
    if _SPECS_CACHE is None:
        _SPECS_CACHE = _build_specs()
    return _SPECS_CACHE


def spec_names() -> List[str]:
    """Names of every routine in the audit registry (SCALING.md row labels)."""
    return [s.name for s in specs()]


def make_grid(nproc: int):
    """Build a ProcessGrid over the first ``nproc`` visible devices.

    The audit's "mpirun -np P" analogue on the virtual CPU mesh."""
    import jax

    from ..parallel import ProcessGrid

    devs = jax.devices()
    if len(devs) < nproc:
        raise RuntimeError(
            f"audit at P={nproc} needs {nproc} devices, have {len(devs)} "
            "(set --xla_force_host_platform_device_count)")
    return ProcessGrid(devices=devs[:nproc])


def compile_spec(spec: RoutineSpec, grid):
    """AOT-compile one audit spec on ``grid``.

    Returns ``(compiled, None)`` on success, else ``(None, problem)`` where
    ``problem`` is a ``{"skipped": ...}`` or ``{"error": ...}`` dict — the
    shared front half of :func:`audit_routine` and the collective race
    auditor (``slate_tpu.analysis.collective_audit``), so both gates compile
    each routine exactly the same way."""
    if spec.requires is not None and not spec.requires(grid):
        return None, {"skipped": "grid constraint "
                      "(e.g. square-grid-only algorithm)"}
    try:
        return spec.build(grid), None
    # slate-lint: disable=SLT501 -- the audit table renders per-row compile
    # failures as data; nothing executes in AOT lower/compile, so the
    # NumericalError taxonomy cannot arise here
    except Exception as e:   # surface, don't die: the table shows the reason
        return None, {"error": f"{type(e).__name__}: {e}"}


def audit_routine(spec: RoutineSpec, grid) -> Dict[str, Any]:
    """Compile one routine on ``grid`` and harvest its compiled costs.

    Returns the :func:`costaudit.harvest` dict extended with routine/mesh
    metadata, or ``{"error": ...}`` when the spec does not apply or fails to
    compile (the table renders the reason instead of dying)."""
    meta = {"routine": spec.name, "module": spec.module,
            "P": grid.size, "grid": f"{grid.p}x{grid.q}",
            "model_flops": spec.model_flops}
    compiled, problem = compile_spec(spec, grid)
    if problem is not None:
        return dict(meta, **problem)
    out = harvest(compiled)
    out.update(meta)
    return out


def check_pins(rows: Sequence[Dict[str, Any]], pins: Dict[str, Any]
               ) -> List[str]:
    """Diff audited rows against a SCALING_PINS.json document; returns the
    list of regressions (empty = gate passes).

    One implementation serves both gates — ``tools/gen_scaling.py --check``
    (the CI scaling-audit step) and ``tests/test_perf_pins.py::
    TestCollectivePins`` — so the envelope semantics cannot drift.  A routine
    that is audited-but-unpinned is itself a failure: a shrunk or partially
    regenerated pin file must not let the gate pass vacuously."""
    bad: List[str] = []
    nproc = int(pins.get("P", 2))
    slack = float(pins.get("bytes_slack", 1.25))
    cslack = int(pins.get("count_slack", 2))
    pinned = pins.get("routines", {})
    fresh = {r["routine"]: r for r in rows if r.get("P") == nproc}
    for name, pin in sorted(pinned.items()):
        row = fresh.get(name)
        if row is None:
            bad.append(f"{name}: pinned but missing from the audit registry")
            continue
        if row.get("error") or row.get("skipped"):
            bad.append(f"{name}: audit failed: "
                       f"{row.get('error') or row.get('skipped')}")
            continue
        if row["collective_bytes"] > slack * pin["collective_bytes"]:
            bad.append(f"{name}: collective bytes {row['collective_bytes']} "
                       f"> {slack} x pinned {pin['collective_bytes']}")
        if row["collective_count"] > pin["collective_count"] + cslack:
            bad.append(f"{name}: collective sites {row['collective_count']} "
                       f"> pinned {pin['collective_count']} + {cslack}")
    for name in sorted(set(fresh) - set(pinned)):
        row = fresh[name]
        if row.get("skipped"):
            continue          # grid-constrained at this P — nothing to pin
        if row.get("error"):
            # --update-pins drops error rows, so "unpinned" would point at
            # the wrong remedy: surface the compile failure itself
            bad.append(f"{name}: audit failed: {row['error']}")
            continue
        bad.append(f"{name}: audited but unpinned "
                   "(run tools/gen_scaling.py --update-pins)")
    return bad


def audit_all(nprocs: Sequence[int] = (2, 4, 8),
              names: Optional[Sequence[str]] = None,
              progress: Optional[Callable[[Dict[str, Any]], None]] = None
              ) -> List[Dict[str, Any]]:
    """Audit every routine spec at every requested device count.

    This is the full SCALING.md table.  Rows carrying ``error``/``skipped``
    keys mark non-applicable combinations."""
    rows = []
    wanted = set(names) if names else None
    for nproc in nprocs:
        grid = make_grid(nproc)
        for spec in specs():
            if wanted is not None and spec.name not in wanted:
                continue
            row = audit_routine(spec, grid)
            rows.append(row)
            if progress is not None:
                progress(row)
    return rows
