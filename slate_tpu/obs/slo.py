"""SLO monitors: declared objectives evaluated over the window ring.

The serving tier's admission control (ROADMAP item 2(c)) needs a *verdict*,
not a dashboard: "is gesv p99 latency inside its objective right now, and
how fast is the error budget burning?".  This module turns the
:mod:`.timeseries` ring into exactly that signal:

* an :class:`SLO` **declares** one objective — a per-routine p99 latency
  bound, a maximum error rate, or a minimum cache hit rate after warm-up;
* an :class:`SLOMonitor` **evaluates** the declared set over the last N
  windows of a :class:`~.timeseries.TimeSeriesSampler`, computing the
  classic error-budget burn rate (observed bad fraction / allowed bad
  fraction) and mapping it to a verdict: ``ok`` (burn < 1 — inside budget),
  ``warning`` (budget burning faster than sustainable), ``breach`` (burn
  past the breach multiplier), or ``no_data``;
* every verdict lands in the registry as gauges —
  ``slate_slo_status{slo=...}`` (0 ok / 1 warning / 2 breach / -1 no data)
  and ``slate_slo_burn_rate{slo=...}`` — which is the form
  :class:`~slate_tpu.serve.queue.ServeQueue` consumes
  (``ServeQueue.slo_status()``), so a later admission-control PR can shed
  load on ``breach`` without new plumbing.

Burn-rate semantics (the SRE-workbook form, windowed): for a latency SLO
"p99 < objective" the budget is the 1% of requests allowed over the bound;
the observed bad fraction is estimated from the window's histogram delta
counts (observations in buckets above the threshold, interpolated within
the straddling bucket).  For rate SLOs the budget is the declared maximum
bad fraction directly.  ``burn = bad_fraction / budget``: 1.0 means burning
exactly the budget, sustained; 2.0 means the budget is gone in half the
period.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .registry import REGISTRY
from .timeseries import TimeSeriesSampler

VERDICT_OK = "ok"
VERDICT_WARNING = "warning"
VERDICT_BREACH = "breach"
VERDICT_NO_DATA = "no_data"

#: verdict -> the gauge code ``slate_slo_status`` carries
STATUS_CODES = {VERDICT_OK: 0, VERDICT_WARNING: 1, VERDICT_BREACH: 2,
                VERDICT_NO_DATA: -1}

KINDS = ("latency", "error_rate", "hit_rate")


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declared objective.

    kind:        ``latency`` — p(``target``) of histogram ``metric`` must be
                 under ``objective`` seconds; ``error_rate`` — counter
                 ``metric`` over counter ``total_metric`` must stay under
                 ``objective``; ``hit_rate`` — counter ``metric`` (good)
                 over good + ``total_metric`` (bad) must stay over
                 ``objective``.
    labels:      series filter — a sample matches when its labels contain
                 every (k, v) pair here (subset match, so one SLO can cover
                 a routine across buckets).
    windows:     evaluate over the newest N windows of the ring.
    warmup_windows: ignore the oldest K windows of the *run* (hit-rate SLOs
                 exempt the warm-up compiles this way).
    warn_burn / breach_burn: burn-rate thresholds for the verdict ladder.
    """

    name: str
    kind: str
    metric: str
    objective: float
    total_metric: str = ""
    labels: Tuple[Tuple[str, str], ...] = ()
    target: float = 0.99
    windows: int = 10
    warmup_windows: int = 0
    warn_burn: float = 1.0
    breach_burn: float = 2.0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"SLO {self.name}: kind must be one of {KINDS}, "
                             f"got {self.kind!r}")
        if self.kind in ("error_rate", "hit_rate") and not self.total_metric:
            raise ValueError(f"SLO {self.name}: {self.kind} needs "
                             "total_metric")
        if self.kind == "latency" and not 0.0 < self.target < 1.0:
            raise ValueError(f"SLO {self.name}: latency target must be in "
                             f"(0, 1), got {self.target}")

    def budget(self) -> float:
        """The allowed bad fraction."""
        if self.kind == "latency":
            return 1.0 - self.target
        if self.kind == "error_rate":
            return self.objective
        return 1.0 - self.objective         # hit_rate


@dataclasses.dataclass
class SLOVerdict:
    """One evaluation: the verdict plus the numbers behind it."""

    name: str
    kind: str
    verdict: str
    burn_rate: Optional[float]
    value: Optional[float]       # observed p-quantile / error rate / hit rate
    objective: float
    bad: float                   # observations over the bound (est.)
    total: float                 # observations considered
    windows_evaluated: int
    detail: str = ""

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        for k in ("burn_rate", "value", "bad", "total"):
            if d[k] is not None:
                d[k] = round(float(d[k]), 6)
        return d


def _match(labels: Dict[str, str], flt: Tuple[Tuple[str, str], ...]) -> bool:
    return all(labels.get(k) == v for k, v in flt)


def _frac_above(buckets: Sequence[float], counts: Sequence[int],
                threshold: float) -> Tuple[float, float]:
    """(observations above ``threshold``, total) for one histogram window —
    full buckets above the bound, plus the straddling bucket's interpolated
    share.  The overflow slot is entirely above any *in-range* threshold;
    for a threshold past the top bound the overflow observations are
    indeterminate (they may still be under it), so they are NOT counted —
    the estimator under-reports rather than fabricating violations."""
    total = float(sum(counts))
    if total <= 0:
        return 0.0, 0.0
    bad = float(counts[len(buckets)]) if threshold <= buckets[-1] else 0.0
    for i, ub in enumerate(buckets):
        lo = buckets[i - 1] if i > 0 else 0.0
        if threshold <= lo:
            bad += counts[i]
        elif threshold < ub:
            bad += counts[i] * (ub - threshold) / (ub - lo)
    return bad, total


class SLOMonitor:
    """Evaluate declared SLOs over a sampler's window ring.

    ::

        mon = obs.SLOMonitor(obs.default_serve_slos(), sampler)
        verdicts = mon.evaluate()        # also sets slate_slo_* gauges
    """

    def __init__(self, slos: Sequence[SLO], sampler: TimeSeriesSampler,
                 registry=None):
        self.slos = tuple(slos)
        self.sampler = sampler
        self.registry = REGISTRY if registry is None else registry

    # -- aggregation over the ring -------------------------------------------
    def _windows_for(self, slo: SLO) -> List[Dict[str, Any]]:
        ws = self.sampler.windows()
        if slo.warmup_windows:
            ws = [w for w in ws if w["index"] >= slo.warmup_windows]
        return ws[-slo.windows:]

    @staticmethod
    def _sum_counter(ws, name, flt) -> float:
        return sum(c["delta"] for w in ws for c in w["counters"]
                   if c["name"] == name and _match(c["labels"], flt))

    def _eval_latency(self, slo: SLO, ws) -> SLOVerdict:
        from .registry import quantile_from_counts

        buckets: Optional[List[float]] = None
        counts: Optional[List[float]] = None
        for w in ws:
            for h in w["histograms"]:
                if h["name"] != slo.metric or not _match(h["labels"],
                                                         slo.labels):
                    continue
                if counts is None:
                    buckets, counts = list(h["buckets"]), [0.0] * len(
                        h["counts"])
                if list(h["buckets"]) == buckets:
                    counts = [a + b for a, b in zip(counts, h["counts"])]
        if counts is None or sum(counts) <= 0:
            return self._verdict(slo, None, None, 0.0, 0.0, len(ws),
                                 "no observations in evaluated windows")
        bad, total = _frac_above(buckets, counts, slo.objective)
        q = quantile_from_counts(buckets, counts, slo.target)
        burn = (bad / total) / slo.budget()
        return self._verdict(slo, burn, q, bad, total, len(ws),
                             f"p{slo.target * 100:g}={q:.4g}s vs "
                             f"objective {slo.objective:g}s")

    def _eval_rate(self, slo: SLO, ws) -> SLOVerdict:
        good_is_metric = slo.kind == "hit_rate"
        a = self._sum_counter(ws, slo.metric, slo.labels)
        b = self._sum_counter(ws, slo.total_metric, slo.labels)
        if good_is_metric:
            total, bad = a + b, b               # metric=hits, total=misses
            value = a / total if total else None
        else:
            total, bad = b, min(a, b)           # metric=errors, total=requests
            value = bad / total if total else None
        if total <= 0:
            return self._verdict(slo, None, None, 0.0, 0.0, len(ws),
                                 "no traffic in evaluated windows")
        burn = (bad / total) / slo.budget() if slo.budget() > 0 else (
            0.0 if bad == 0 else float("inf"))
        what = "hit rate" if good_is_metric else "error rate"
        return self._verdict(slo, burn, value, bad, total, len(ws),
                             f"{what} {value:.4f} vs objective "
                             f"{slo.objective:g}")

    def _verdict(self, slo: SLO, burn, value, bad, total, nwin,
                 detail) -> SLOVerdict:
        if burn is None:
            verdict = VERDICT_NO_DATA
        elif burn < slo.warn_burn:
            verdict = VERDICT_OK
        elif burn < slo.breach_burn:
            verdict = VERDICT_WARNING
        else:
            verdict = VERDICT_BREACH
        return SLOVerdict(name=slo.name, kind=slo.kind, verdict=verdict,
                          burn_rate=burn, value=value,
                          objective=slo.objective, bad=bad, total=total,
                          windows_evaluated=nwin, detail=detail)

    # -- the monitor ---------------------------------------------------------
    def evaluate(self) -> List[SLOVerdict]:
        """Evaluate every declared SLO; publish the verdicts as
        ``slate_slo_status`` / ``slate_slo_burn_rate`` gauges (the signal
        :class:`~slate_tpu.serve.queue.ServeQueue` reads)."""
        verdicts = []
        status = self.registry.gauge(
            "slate_slo_status",
            "SLO verdict per objective: 0 ok, 1 warning, 2 breach, "
            "-1 no data")
        burn_g = self.registry.gauge(
            "slate_slo_burn_rate", "error-budget burn rate per objective")
        for slo in self.slos:
            ws = self._windows_for(slo)
            if slo.kind == "latency":
                v = self._eval_latency(slo, ws)
            else:
                v = self._eval_rate(slo, ws)
            status.set(STATUS_CODES[v.verdict], slo=slo.name)
            if v.burn_rate is not None:
                burn_g.set(v.burn_rate, slo=slo.name)
            verdicts.append(v)
        return verdicts


def default_serve_slos(routines: Sequence[str] = ("gesv", "posv", "gels"),
                       p99_latency_s: float = 1.0,
                       max_error_rate: float = 0.01,
                       min_hit_rate: float = 0.95,
                       warmup_windows: int = 1,
                       windows: int = 20) -> List[SLO]:
    """The serving stack's standard objectives: per-routine p99 submit-to-
    result latency, worker error rate, and executable-cache hit rate after
    warm-up — the three signals ROADMAP item 2(c)'s admission control needs.
    Thresholds are keyword-tunable (the CI smoke loosens latency on the CPU
    backend; a TPU deployment tightens it)."""
    slos = [SLO(name=f"{r}_p99_latency", kind="latency",
                metric="slate_serve_latency_seconds",
                labels=(("routine", r),), objective=p99_latency_s,
                target=0.99, windows=windows)
            for r in routines]
    slos.append(SLO(name="serve_error_rate", kind="error_rate",
                    metric="slate_serve_worker_errors_total",
                    total_metric="slate_serve_requests_total",
                    objective=max_error_rate, windows=windows))
    slos.append(SLO(name="serve_cache_hit_rate", kind="hit_rate",
                    metric="slate_serve_cache_hits_total",
                    total_metric="slate_serve_cache_misses_total",
                    objective=min_hit_rate, windows=windows,
                    warmup_windows=warmup_windows))
    return slos
