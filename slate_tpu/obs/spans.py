"""Span API: ``obs.scope(routine=...)`` — the one instrumentation surface.

A *span* is a host-side named region that simultaneously

* opens a :func:`slate_tpu.utils.trace.trace_block` region (so spans land in
  the chrome-trace timeline next to the existing phase timers and the
  resilience layer's retry/fault instants), and
* records into the metrics registry on close: ``slate_spans_total`` (counter)
  and ``slate_span_seconds`` (histogram), labeled with the routine plus
  whatever labels the caller attached (dtype, shape_bucket, mesh, nb,
  method, ...).

Spans nest; a child records its parent's routine under the ``parent`` label
so nested driver compositions (gesv -> getrf -> trsm) remain attributable.

:func:`instrument` is the decorator the distributed drivers wear: it derives
the standard labels (dtype + shape bucket from the first array argument,
``pxq`` mesh from a ``ProcessGrid`` argument, ``nb``/``method`` keyword
options) and wraps the call in a scope.  Host-side overhead is a few dict
writes per *driver call* — noise against any distributed solve, and the
counters need no enable switch (unlike the trace timeline, which stays
opt-in via ``trace.on()``).
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
from typing import Any, Dict, Optional

from ..utils.trace import trace_block
from .registry import REGISTRY

_stack = threading.local()

#: attribute stamped on instrumented callables (the meta-test in
#: tests/test_obs.py asserts every public parallel/ driver carries it)
INSTRUMENT_ATTR = "__obs_routine__"


def current_span() -> Optional[str]:
    """Routine name of the innermost open span on this thread (None outside)."""
    stack = getattr(_stack, "spans", None)
    return stack[-1] if stack else None


def span_depth() -> int:
    """Nesting depth of open spans on this thread (0 outside any scope)."""
    return len(getattr(_stack, "spans", ()))


class SpanHandle:
    """The object a :func:`scope` yields: a slot for the span's result.

    With ``device_sync=True`` on the scope, the recorded duration includes a
    ``block_until_ready()`` on whatever was handed to :meth:`set_result` —
    without it, an async-dispatch backend would close the span at *dispatch*
    time and the execute histogram would measure queue depth, not compute.
    """

    __slots__ = ("_result",)

    def __init__(self):
        self._result = None

    def set_result(self, value) -> None:
        """Attach the span's device result (blocked on at close when the
        scope was opened with ``device_sync=True``)."""
        self._result = value


@contextlib.contextmanager
def scope(routine: str, device_sync: bool = False, **labels):
    """Open an observability span around a routine invocation.

    ::

        with obs.scope("getrf_distributed", mesh="2x4", dtype="float32"):
            ...

    Labels are stringified; the span's duration lands in the
    ``slate_span_seconds`` histogram and its count in ``slate_spans_total``.

    ``device_sync=True`` (opt-in; the serve execute stage is the intended
    caller) makes the span block on the result attached via the yielded
    :class:`SpanHandle` before closing, so the duration is dispatch+compute
    rather than dispatch alone, and stamps a ``device_sync="true"`` label so
    synced and unsynced timings never mix in one series::

        with obs.scope("serve.execute", device_sync=True) as sp:
            sp.set_result(driver(A, B))
    """
    labels = {k: str(v) for k, v in labels.items() if v is not None}
    if device_sync:
        labels["device_sync"] = "true"
    parent = current_span()
    if parent is not None:
        labels.setdefault("parent", parent)
    stack = getattr(_stack, "spans", None)
    if stack is None:
        stack = _stack.spans = []
    stack.append(routine)
    handle = SpanHandle()
    t0 = time.perf_counter()
    try:
        with trace_block(routine, **labels):
            yield handle
            if device_sync and hasattr(handle._result, "block_until_ready"):
                handle._result.block_until_ready()
    finally:
        dur = time.perf_counter() - t0
        stack.pop()
        REGISTRY.counter(
            "slate_spans_total",
            "driver invocations, by routine and labels").inc(
                routine=routine, **labels)
        REGISTRY.histogram(
            "slate_span_seconds",
            "host wall time per driver invocation").observe(
                dur, routine=routine, **labels)


def _shape_bucket(shape) -> str:
    """Pow-2 bucket of the largest dim: the sweep label that keeps histogram
    cardinality bounded while separating 64-class from 16384-class rows."""
    try:
        top = max(int(d) for d in shape) if len(shape) else 1
    except (TypeError, ValueError):
        return "unknown"
    b = 1
    while b < top:
        b <<= 1
    return f"<={b}"


_LABEL_KWARGS = ("nb", "method", "lu_panel", "kind", "uplo", "lookahead",
                 "batch", "bucket")


def _derive_labels(args, kwargs) -> Dict[str, Any]:
    """Standard label extraction for :func:`instrument`: best-effort and
    exception-free — a driver call must never fail because of telemetry."""
    labels: Dict[str, Any] = {}
    try:
        for a in args:
            if labels.get("dtype") is None and hasattr(a, "dtype") \
                    and hasattr(a, "shape"):
                labels["dtype"] = str(a.dtype)
                labels["shape_bucket"] = _shape_bucket(a.shape)
            elif "mesh" not in labels and hasattr(a, "p") and hasattr(a, "q") \
                    and hasattr(a, "mesh"):
                labels["mesh"] = f"{a.p}x{a.q}"
        g = kwargs.get("grid")
        if g is not None and hasattr(g, "p") and hasattr(g, "q"):
            labels["mesh"] = f"{g.p}x{g.q}"
        for k in _LABEL_KWARGS:
            v = kwargs.get(k)
            if v is not None and not hasattr(v, "shape"):
                labels[k] = v
    # slate-lint: disable=SLT501 -- label derivation is best-effort shape/
    # attr inspection of the call's arguments; no computation runs here, and
    # a driver call must never fail because of telemetry
    except Exception:
        pass
    return labels


def instrument(fn=None, *, routine: Optional[str] = None):
    """Decorator: wrap a driver in an observability scope.

    ::

        @instrument
        def getrf_distributed(A, grid, nb=256, ...): ...

    The routine label defaults to the function name.  Works bare or with the
    ``routine=`` override; idempotent on already-instrumented callables.
    """
    def deco(f):
        if getattr(f, INSTRUMENT_ATTR, None):
            return f
        name = routine or f.__name__

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            with scope(name, **_derive_labels(args, kwargs)):
                return f(*args, **kwargs)

        setattr(wrapper, INSTRUMENT_ATTR, name)
        return wrapper

    return deco(fn) if fn is not None else deco


def on_phases(routine: str, phases: Dict[str, float],
              attempt: Optional[int] = None) -> None:
    """Absorb a driver's phase-timer map into the metrics registry.

    Called lazily by ``utils.trace.record_phases`` so the trace layer stays
    importable without obs.  Each phase becomes one ``slate_phase_seconds``
    histogram sample."""
    hist = REGISTRY.histogram("slate_phase_seconds",
                              "per-phase host wall time (trace.record_phases)")
    for phase, sec in dict(phases).items():
        try:
            labels = {"routine": routine, "phase": str(phase)}
            if attempt is not None:
                labels["attempt"] = str(attempt)
            hist.observe(float(sec), **labels)
        except (TypeError, ValueError):
            continue
