"""Windowed time-series over the metrics registry.

The registry (:mod:`.registry`) answers "what did this process do overall" —
cumulative counters and end-of-run histograms.  ROADMAP item 2(c)/(d)
(SLO-aware admission control, traffic-derived bucket tables) needs *runtime*
signals: rates and quantiles **over time windows**, so a monitor can tell a
steady 1% error rate from a burst that burned the week's budget in a minute.

:class:`TimeSeriesSampler` snapshots the registry on an interval into a ring
of fixed-width windows.  Each window carries, per labeled series:

* **counter deltas and rates** — ``delta = cur - prev``, ``rate = delta /
  duration`` (a counter reset mid-flight clamps to 0 rather than reporting a
  negative rate);
* **histogram deltas** — per-slot count deltas plus delta sum/count, with
  p50/p99 estimated from the delta counts via
  :func:`~.registry.quantile_from_counts` — per-window quantiles, not
  since-process-start ones;
* **gauge values** — last write as of the window close.

The ring is bounded (``max_windows``); old windows fall off, so a sampler
left running for hours costs a fixed few hundred KB.  ``export`` writes the
``metrics_timeseries.json`` document (schema ``slate_tpu.timeseries/v1``,
checked by :func:`validate_timeseries` — the same producer/validator pattern
as ``metrics.json``/``validate_metrics``); SLO verdicts evaluated over the
ring (:mod:`.slo`) ride along in the document's ``slos`` section so one
artifact answers both "what happened" and "was it acceptable".

Sampling is registry-read-only and lock-cheap (one ``collect()`` per tick);
the background thread is optional — tests and the CI smoke drive
``sample()`` manually with explicit timestamps for deterministic rate math.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .registry import REGISTRY, MetricsRegistry, quantile_from_counts

SCHEMA = "slate_tpu.timeseries/v1"
#: package-level alias (obs.SCHEMA is the metrics.json schema id)
TIMESERIES_SCHEMA = SCHEMA

#: default ring size — at the default 1 s interval, two minutes of history
DEFAULT_MAX_WINDOWS = 120


def _series_map(doc: Dict[str, Any]) -> Dict[tuple, Dict[str, Any]]:
    """metrics.json document -> {(name, canonical labels): sample}."""
    out: Dict[tuple, Dict[str, Any]] = {}
    for m in doc.get("metrics", ()):
        for s in m.get("samples", ()):
            key = (m["name"], m["kind"],
                   tuple(sorted(s.get("labels", {}).items())))
            out[key] = s
    return out


class TimeSeriesSampler:
    """Interval snapshots of the registry, diffed into a window ring.

    ::

        ts = obs.TimeSeriesSampler(interval_s=1.0)
        ts.start()                       # background thread; or call
        ...                              # ts.sample() manually
        ts.stop()
        ts.export("metrics_timeseries.json", source="serving-smoke")

    ``sample(now=...)`` accepts an explicit ``time.time()`` stamp so rate
    math is exactly testable; the background thread passes real time.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 interval_s: float = 1.0,
                 max_windows: int = DEFAULT_MAX_WINDOWS):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be positive, got {interval_s}")
        self.registry = REGISTRY if registry is None else registry
        self.interval_s = float(interval_s)
        self.max_windows = int(max_windows)
        self._lock = threading.Lock()
        self._windows: "deque[Dict[str, Any]]" = deque(maxlen=self.max_windows)
        self._prev: Optional[Dict[tuple, Dict[str, Any]]] = None
        self._prev_t: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- sampling ------------------------------------------------------------
    def sample(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Take one snapshot; returns the new window (None on the baseline
        call — the first snapshot has nothing to diff against)."""
        now = time.time() if now is None else float(now)
        cur = _series_map(self.registry.collect(source="timeseries"))
        with self._lock:
            prev, prev_t = self._prev, self._prev_t
            self._prev, self._prev_t = cur, now
            if prev is None or now <= prev_t:
                return None
            window = self._diff(prev, cur, prev_t, now)
            window["index"] = (self._windows[-1]["index"] + 1
                               if self._windows else 0)
            self._windows.append(window)
            return window

    @staticmethod
    def _diff(prev: Dict[tuple, Dict[str, Any]],
              cur: Dict[tuple, Dict[str, Any]],
              t0: float, t1: float) -> Dict[str, Any]:
        dur = t1 - t0
        counters: List[Dict[str, Any]] = []
        histograms: List[Dict[str, Any]] = []
        gauges: List[Dict[str, Any]] = []
        for key in sorted(cur):
            name, kind, lkey = key
            s = cur[key]
            p = prev.get(key)
            if kind == "counter":
                delta = s["value"] - (p["value"] if p else 0.0)
                if delta < 0:          # registry reset mid-flight
                    delta = 0.0
                if delta == 0.0:
                    continue           # quiet series stay out of the window
                counters.append({"name": name, "labels": dict(lkey),
                                 "delta": delta,
                                 "rate": delta / dur})
            elif kind == "gauge":
                gauges.append({"name": name, "labels": dict(lkey),
                               "value": s["value"]})
            else:
                pc = p["counts"] if p else [0] * len(s["counts"])
                dcounts = [c - q for c, q in zip(s["counts"], pc)]
                dcount = s["count"] - (p["count"] if p else 0)
                if dcount <= 0 or any(d < 0 for d in dcounts):
                    continue           # quiet, or reset mid-flight
                buckets = s["buckets"]
                histograms.append({
                    "name": name, "labels": dict(lkey),
                    "buckets": list(buckets), "counts": dcounts,
                    "sum": s["sum"] - (p["sum"] if p else 0.0),
                    "count": dcount,
                    "rate": dcount / dur,
                    "p50": quantile_from_counts(buckets, dcounts, 0.50),
                    "p99": quantile_from_counts(buckets, dcounts, 0.99),
                })
        return {"t_start": round(t0, 6), "t_end": round(t1, 6),
                "duration_s": round(dur, 6), "counters": counters,
                "histograms": histograms, "gauges": gauges}

    def windows(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        """The ring's windows, oldest first (``last`` trims to the newest N)."""
        with self._lock:
            ws = list(self._windows)
        return ws if last is None else ws[-int(last):]

    # -- background thread ---------------------------------------------------
    def start(self) -> "TimeSeriesSampler":
        """Begin interval sampling on a daemon thread (idempotent); the
        construction-time baseline is the first ``sample()`` call."""
        if self._thread is not None:
            return self
        self.sample()                    # baseline snapshot
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="slate-obs-sampler")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample()

    def stop(self, final_sample: bool = True) -> None:
        """Stop the thread; by default take one last window so activity since
        the final tick is not dropped on the floor."""
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=max(5.0, 2 * self.interval_s))
            self._thread = None
        if final_sample:
            self.sample()

    def __enter__(self) -> "TimeSeriesSampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- serialization -------------------------------------------------------
    def collect(self, source: str = "unknown",
                slos: Optional[List[Dict[str, Any]]] = None
                ) -> Dict[str, Any]:
        """The ``metrics_timeseries.json`` document (schema
        ``slate_tpu.timeseries/v1``); ``slos`` attaches SLO verdicts
        (:meth:`~slate_tpu.obs.slo.SLOVerdict.to_dict` dicts)."""
        doc = {"schema": SCHEMA, "source": str(source),
               "created_unix": round(time.time(), 3),
               "interval_s": self.interval_s,
               "max_windows": self.max_windows,
               "windows": self.windows()}
        if slos is not None:
            doc["slos"] = list(slos)
        return doc

    def export(self, path: str, source: str = "unknown",
               slos: Optional[List[Dict[str, Any]]] = None) -> str:
        doc = self.collect(source=source, slos=slos)
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        return path


def validate_timeseries(doc: Any) -> None:
    """Schema-check a ``metrics_timeseries.json`` document, raising
    ``ValueError`` on the first violation (the CI serving-smoke gate runs
    its exported document through this — same pattern as
    :func:`~.registry.validate_metrics`)."""
    if not isinstance(doc, dict):
        raise ValueError(f"timeseries doc must be a dict, got {type(doc)}")
    if doc.get("schema") != SCHEMA:
        raise ValueError(f"schema must be {SCHEMA!r}, got {doc.get('schema')!r}")
    if not isinstance(doc.get("source"), str):
        raise ValueError("source must be a string")
    if not isinstance(doc.get("created_unix"), (int, float)):
        raise ValueError("created_unix must be a number")
    if not isinstance(doc.get("interval_s"), (int, float)) \
            or doc["interval_s"] <= 0:
        raise ValueError("interval_s must be a positive number")
    windows = doc.get("windows")
    if not isinstance(windows, list):
        raise ValueError("windows must be a list")
    for w in windows:
        if not isinstance(w, dict):
            raise ValueError(f"window must be a dict, got {type(w)}")
        for k in ("t_start", "t_end", "duration_s"):
            if not isinstance(w.get(k), (int, float)):
                raise ValueError(f"window.{k} must be a number")
        if w["duration_s"] <= 0:
            raise ValueError("window.duration_s must be positive")
        for sec, need_num in (("counters", ("delta", "rate")),
                              ("gauges", ("value",)),
                              ("histograms", ("sum", "rate"))):
            entries = w.get(sec)
            if not isinstance(entries, list):
                raise ValueError(f"window.{sec} must be a list")
            for e in entries:
                if not isinstance(e.get("name"), str) or not e["name"]:
                    raise ValueError(f"window.{sec} entry missing name")
                if not isinstance(e.get("labels"), dict):
                    raise ValueError(f"{e['name']}: labels must be a dict")
                for k in need_num:
                    if not isinstance(e.get(k), (int, float)):
                        raise ValueError(f"{e['name']}: {k} must be a number")
        for h in w["histograms"]:
            bs, cs = h.get("buckets"), h.get("counts")
            if not isinstance(bs, list) or not isinstance(cs, list) \
                    or len(cs) != len(bs) + 1:
                raise ValueError(f"{h['name']}: histogram window needs "
                                 "buckets + len(buckets)+1 counts")
            if not isinstance(h.get("count"), int) or h["count"] <= 0:
                raise ValueError(f"{h['name']}: window count must be a "
                                 "positive int")
            for k in ("p50", "p99"):
                if h.get(k) is not None \
                        and not isinstance(h[k], (int, float)):
                    raise ValueError(f"{h['name']}: {k} must be numeric or "
                                     "null")
    slos = doc.get("slos")
    if slos is not None:
        if not isinstance(slos, list):
            raise ValueError("slos must be a list")
        for v in slos:
            if not isinstance(v.get("name"), str) or not v["name"]:
                raise ValueError("slo verdict missing name")
            if v.get("verdict") not in ("ok", "warning", "breach",
                                        "no_data"):
                raise ValueError(f"{v.get('name')}: bad verdict "
                                 f"{v.get('verdict')!r}")
            if not isinstance(v.get("burn_rate"), (int, float)) \
                    and v.get("burn_rate") is not None:
                raise ValueError(f"{v['name']}: burn_rate must be numeric "
                                 "or null")
