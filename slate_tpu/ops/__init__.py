"""Internal compute kernels (reference L3, src/internal/) as pure XLA functions."""

from . import blas3, elementwise, norms
