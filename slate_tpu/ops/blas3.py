"""Pure-array BLAS-3 kernels — the L3 "internal" layer.

Reference analogue: ``src/internal/internal_{gemm,hemm,herk,her2k,symm,syrk,syr2k,trmm,
trsm}.cc`` (one parallel step per op, specialized per Target) and the per-tile BLAS in
``include/slate/Tile_blas.hh``.

TPU re-design: the reference decomposes each op into per-tile batched vendor-BLAS calls
grouped by ``device_regions_build`` (internal_batch.hh:198-391).  On TPU the *whole
operand* is one HBM-resident array and XLA tiles the matmul onto the MXU itself, so the
"internal" layer collapses to single fused XLA ops: ``jnp.matmul`` drives the MXU
directly, ``lax.linalg.triangular_solve`` is the native blocked TRSM, and masking
(tril/triu) expresses the triangular/symmetric structure that the reference encodes in
its typed tile loops.  The tiled/distributed decompositions live one level up
(slate_tpu/blas.py drivers and slate_tpu/parallel/ for the SUMMA pipeline).

All functions are pure (array in, array out) and jit-friendly; structure flags are
static Python values so XLA sees a fixed program.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..core.types import Diag, Side, Uplo


def _c(alpha, ref):
    """Cast a scalar to the result dtype."""
    return jnp.asarray(alpha, dtype=ref.dtype)


def gemm(alpha, A: jax.Array, B: jax.Array, beta, C: jax.Array) -> jax.Array:
    """C = alpha A B + beta C (internal_gemm.cc; MXU-native via jnp.matmul)."""
    ab = jnp.matmul(A, B, precision=lax.Precision.HIGHEST)
    return _c(alpha, ab) * ab + _c(beta, C) * C


def _symmetrize(A, uplo: Uplo, conj: bool):
    uplo = Uplo.from_string(uplo)
    if uplo == Uplo.Lower:
        strict = jnp.tril(A, -1)
    else:
        strict = jnp.triu(A, 1)
    other = jnp.swapaxes(strict, -1, -2)
    if conj and jnp.iscomplexobj(A):
        other = jnp.conj(other)
    diag = jnp.diagonal(A, axis1=-2, axis2=-1)
    if conj and jnp.iscomplexobj(A):
        diag = jnp.real(diag).astype(A.dtype)
    n = A.shape[-1]
    idx = jnp.arange(n)
    return (strict + other).at[..., idx, idx].set(diag)


def symm(side, alpha, A, uplo, B, beta, C):
    """C = alpha A B + beta C with A symmetric stored in `uplo` (internal_symm)."""
    Af = _symmetrize(A, uplo, conj=False)
    side = Side.from_string(side)
    prod = jnp.matmul(Af, B) if side == Side.Left else jnp.matmul(B, Af)
    return _c(alpha, prod) * prod + _c(beta, C) * C


def hemm(side, alpha, A, uplo, B, beta, C):
    """Hermitian counterpart of symm (internal_hemm)."""
    Af = _symmetrize(A, uplo, conj=True)
    side = Side.from_string(side)
    prod = jnp.matmul(Af, B) if side == Side.Left else jnp.matmul(B, Af)
    return _c(alpha, prod) * prod + _c(beta, C) * C


def _real_diag(G):
    """Zero the imaginary residue on the diagonal of a complex Gram/Hermitian
    product (the diagonal is mathematically real: sum |x|^2)."""
    idx = jnp.arange(G.shape[-1])
    return G.at[..., idx, idx].set(
        jnp.real(jnp.diagonal(G, axis1=-2, axis2=-1)).astype(G.dtype))


def _rank_k_update(update, beta, C, uplo: Uplo, real_diag: bool):
    """Apply a rank-k update to the stored triangle only, leaving the other triangle of
    the backing array untouched (the reference updates only local tiles of the stored
    triangle)."""
    uplo = Uplo.from_string(uplo)
    n = C.shape[-1]
    r = jnp.arange(n)
    mask = (r[:, None] >= r[None, :]) if uplo == Uplo.Lower else (r[:, None] <= r[None, :])
    new = update + _c(beta, C) * C
    if real_diag and jnp.iscomplexobj(new):
        idx = jnp.arange(n)
        new = new.at[..., idx, idx].set(
            jnp.real(jnp.diagonal(new, axis1=-2, axis2=-1)).astype(new.dtype))
    return jnp.where(mask, new, C)


def syrk(alpha, A, beta, C, uplo):
    """C(uplo) = alpha A A^T + beta C (internal_syrk)."""
    up = jnp.matmul(A, jnp.swapaxes(A, -1, -2))
    return _rank_k_update(_c(alpha, up) * up, beta, C, uplo, real_diag=False)


def herk(alpha, A, beta, C, uplo):
    """C(uplo) = alpha A A^H + beta C, alpha/beta real (internal_herk) — the hot op of
    the Cholesky trailing update (potrf.cc:136-148)."""
    up = jnp.matmul(A, jnp.conj(jnp.swapaxes(A, -1, -2)))
    return _rank_k_update(_c(alpha, up) * up, beta, C, uplo, real_diag=True)


def gram(x, strips: int = 8, precision=None):
    """x^H x as block-column strips on/below the diagonal, mirrored to the
    full Hermitian result — flop factor (1 + 1/S)/2 of the naive square
    matmul (the herk halving; reference internal_herk's triangle scope).
    Each strip product keeps the full contraction dim, so MXU utilization
    stays gemm-class; the mirror assembly is O(n^2) copies.  The mirror makes
    the off-diagonal exactly Hermitian by construction; the diagonal needs
    its imaginary residue forced to zero for complex inputs (the naive
    matmul leaves rounding residue in both)."""
    if precision is None:
        precision = lax.Precision.HIGHEST
    n = x.shape[-1]
    xh = jnp.conj(jnp.swapaxes(x, -1, -2))
    # keep strips at least 128 columns so the per-strip gemms stay
    # lane-aligned; S=1 degenerates to the plain full product
    S = max(1, min(strips, n // 128))
    if S <= 1:
        G = jnp.matmul(xh, x, precision=precision)
        return _real_diag(G) if jnp.iscomplexobj(G) else G
    G = jnp.zeros(x.shape[:-2] + (n, n), dtype=x.dtype)
    for i in range(S):
        j0, j1 = (i * n) // S, ((i + 1) * n) // S
        blk = jnp.matmul(xh[..., j0:, :], x[..., :, j0:j1],
                         precision=precision)
        G = G.at[..., j0:, j0:j1].set(blk)
    if jnp.iscomplexobj(G):
        G = _real_diag(G)
    low = jnp.tril(G)
    return low + jnp.conj(jnp.swapaxes(jnp.tril(G, -1), -1, -2))


def syr2k(alpha, A, B, beta, C, uplo):
    up = jnp.matmul(A, jnp.swapaxes(B, -1, -2))
    up = _c(alpha, up) * up + _c(alpha, up) * jnp.matmul(B, jnp.swapaxes(A, -1, -2))
    return _rank_k_update(up, beta, C, uplo, real_diag=False)


def her2k(alpha, A, B, beta, C, uplo):
    up1 = jnp.matmul(A, jnp.conj(jnp.swapaxes(B, -1, -2)))
    up2 = jnp.matmul(B, jnp.conj(jnp.swapaxes(A, -1, -2)))
    up = _c(alpha, up1) * up1 + jnp.conj(_c(alpha, up1)) * up2
    return _rank_k_update(up, beta, C, uplo, real_diag=True)


def _triangle(A, uplo: Uplo, diag: Diag):
    uplo = Uplo.from_string(uplo)
    diag = Diag.from_string(diag)
    T = jnp.tril(A) if uplo == Uplo.Lower else jnp.triu(A)
    if diag == Diag.Unit:
        n = A.shape[-1]
        idx = jnp.arange(n)
        T = T.at[..., idx, idx].set(jnp.ones((), dtype=A.dtype))
    return T


def trmm(side, uplo, diag, alpha, A, B):
    """B = alpha op(T) B or alpha B op(T), T triangular (internal_trmm)."""
    T = _triangle(A, uplo, diag)
    side = Side.from_string(side)
    prod = jnp.matmul(T, B) if side == Side.Left else jnp.matmul(B, T)
    return _c(alpha, prod) * prod


def trsm(side, uplo, diag, alpha, A, B):
    """Solve op(T) X = alpha B (Left) or X op(T) = alpha B (Right).

    Reference: internal_trsm.cc -> blas::batch::trsm.  TPU-native: XLA's
    TriangularSolve is itself a blocked MXU algorithm, so one lax call replaces the
    tile loop."""
    side = Side.from_string(side)
    uplo = Uplo.from_string(uplo)
    diag = Diag.from_string(diag)
    X = lax.linalg.triangular_solve(
        A, _c(alpha, B) * B,
        left_side=(side == Side.Left),
        lower=(uplo == Uplo.Lower),
        unit_diagonal=(diag == Diag.Unit),
        transpose_a=False, conjugate_a=False)
    return X
