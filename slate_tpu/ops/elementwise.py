"""Elementwise / copy / set kernels.

Reference analogue: the device kernels in ``src/cuda/device_{geadd,gecopy,gescale,
gescale_row_col,geset,tzadd,tzcopy,tzscale,tzset}.cu`` and their internal wrappers
(``src/internal/internal_{geadd,gecopy,...}.cc``).

TPU re-design: every one of these is a fused XLA elementwise op; the trapezoid (tz*)
variants become tril/triu masks.  No Pallas needed — XLA fuses these into neighboring
matmuls, which is precisely why the reference needed hand-written CUDA and we don't.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.types import Uplo


def _mask(shape, uplo: Uplo, dtype=jnp.bool_):
    """Trapezoid mask including the diagonal."""
    m, n = shape[-2], shape[-1]
    r = jnp.arange(m)[:, None]
    c = jnp.arange(n)[None, :]
    if Uplo.from_string(uplo) == Uplo.Lower:
        return r >= c
    return r <= c


def geadd(alpha, A, beta, B):
    """B = alpha A + beta B (device_geadd.cu)."""
    a = jnp.asarray(alpha, B.dtype)
    b = jnp.asarray(beta, B.dtype)
    return a * A + b * B


def tzadd(uplo, alpha, A, beta, B):
    """Trapezoid add: only the `uplo` triangle is updated (device_tzadd.cu)."""
    return jnp.where(_mask(B.shape, uplo), geadd(alpha, A, beta, B), B)


def gecopy(A, out_dtype=None):
    """Copy with optional precision conversion (device_gecopy.cu — used by the
    mixed-precision solvers to round f64->f32; here any dtype pair)."""
    return A.astype(out_dtype) if out_dtype is not None else A


def tzcopy(uplo, A, B, out_dtype=None):
    """Copy the `uplo` trapezoid of A over B (device_tzcopy.cu)."""
    src = gecopy(A, out_dtype or B.dtype)
    return jnp.where(_mask(B.shape, uplo), src, B)


def gescale(numer, denom, A):
    """A *= numer/denom (device_gescale.cu; two-scalar form avoids overflow)."""
    s = jnp.asarray(numer, A.dtype) / jnp.asarray(denom, A.dtype)
    return A * s


def tzscale(uplo, numer, denom, A):
    return jnp.where(_mask(A.shape, uplo), gescale(numer, denom, A), A)


def gescale_row_col(R, C, A):
    """A = diag(R) A diag(C) — row/col equilibration (device_gescale_row_col.cu)."""
    return A * R[..., :, None] * C[..., None, :]


def geset(offdiag_value, diag_value, A):
    """Set off-diagonal and diagonal entries to constants (device_geset.cu)."""
    m, n = A.shape[-2], A.shape[-1]
    out = jnp.full_like(A, offdiag_value)
    idx = jnp.arange(min(m, n))
    return out.at[..., idx, idx].set(jnp.asarray(diag_value, A.dtype))


def tzset(uplo, offdiag_value, diag_value, A):
    """Set the `uplo` trapezoid (device_tzset.cu); the other triangle is untouched."""
    return jnp.where(_mask(A.shape, uplo), geset(offdiag_value, diag_value, A), A)
