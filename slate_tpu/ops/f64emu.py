"""Software f64 matmul on bf16 hardware (SURVEY §7 hard-part 6).

TPU v5e has no f64 ALUs; the d/z routine families run as f32 with
``Precision.HIGHEST`` (bf16-pass accumulation), whose envelope is
O(eps_f32·√k) per dot product.  This module supplies the *emulation flag*
the survey plans for — double-precision-class gemm semantics built from MXU
bf16 passes, for callers whose refinement loops or residual checks need
f64-class accuracy on chip.

**Ozaki-scheme splitting, made exact.**  After a per-row power-of-two
scale, each operand decomposes on a fixed-point grid:

    a = 2^e_row · Σ_i c_i · 2^(-7-8i),   c_i integer, |c_i| ≤ 256.

Integers up to 256 are exactly representable in bf16, so the slice
matrices ship to the MXU losslessly; every product c_i·c_j is an integer
with |c_i·c_j| ≤ 2^16, exact in the f32 accumulator; and a 256-length
chunk of such products sums to an integer of magnitude ≤ 2^24 — still
exactly representable in f32.  The contraction is therefore chunked at
2^(24-16) = 256, each chunk sum is EXACT, and chunk results (scaled by
their power of two, which is also exact) accumulate in double-f32
(hi, lo) via the 2Sum error-free transformation.  The only rounding in
the whole pipeline is the compensated cross-chunk accumulation and the
final read-out: measured ~1e-14 relative error at n=512 (vs ~1e-5 for
plain f32-HIGHEST), i.e. genuine double-precision-class results.

Cost: slice pairs with i+j ≥ s contribute below 2^(-8s) and are skipped,
so the flop multiplier is s(s+1)/2 ≈ 28 bf16 gemms per dgemm with the
default s=7 (56 mantissa bits ≥ f64's 53) — the classical software-f64
trade.  This is a capability/envelope layer, not the bench path (BASELINE
comparisons stay f32-HIGHEST, documented in bench.py's precision note).

Reference context: the reference's d/z tests (test/run_tests.py --type d,z)
assume hardware f64; this flag is how a TPU deployment meets those
tolerances when it must.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax

_CHUNK = 256             # 2^(24 - 16): exact f32 accumulation length


def _exact_pow2(e, dtype):
    """2^e as EXACT floats via exponent-field bit construction — XLA's
    ``exp2`` is a polynomial approximation whose f32 result can miss the
    exact power of two (observed: exp2(23.0f) = 8388612 != 2^23), which
    would silently break the error-free scaling this module depends on.
    ``e`` is clamped to the normal-exponent range: beyond it the true scale
    is not a representable normal float, and an unclamped shift corrupts
    the sign bit (rows whose magnitudes sit outside ~[2^-126, 2^127] in the
    f32 path saturate, matching what any f32 result could express)."""
    e = jnp.asarray(e)
    if jnp.dtype(dtype) == jnp.dtype(jnp.float64):
        ec = jnp.clip(e.astype(jnp.int64), -1022, 1023)
        return lax.bitcast_convert_type((ec + 1023) << 52, jnp.float64)
    ec = jnp.clip(e.astype(jnp.int32), -126, 127)
    return lax.bitcast_convert_type((ec + 127) << 23, jnp.float32)


def split_fixed_slices(x: jax.Array, s: int):
    """Error-free fixed-grid split: returns (slices, e_row) with
    ``x[i, :] = 2^e_row[i] · Σ_j slices[j][i, :] · 2^(-7-8j)`` and every
    slice an integer-valued bf16 matrix with entries in [-256, 256]."""
    x = jnp.asarray(x)
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    e = jnp.where(amax > 0, jnp.floor(jnp.log2(amax)) + 1, 0.0)
    # keep both e and -e inside the normal range of the compute dtype
    lim = 1000.0 if jnp.dtype(x.dtype) == jnp.dtype(jnp.float64) else 120.0
    e = jnp.clip(e, -lim, lim)
    u = x * _exact_pow2(-e, x.dtype)     # |u| < 1 (row-normalized; exact)
    slices = []
    for _ in range(s):
        c = jnp.round(u * 128.0)         # integer in [-128, 128]... plus
        # carry headroom: after the first step |u| <= 0.5 ulp => |c| <= 64;
        # first step |u| < 1 => |c| <= 128.  Both within bf16's exact range.
        slices.append(lax.convert_element_type(c, jnp.bfloat16))
        u = (u - c / 128.0) * 256.0
    return slices, e[..., 0]


def _two_sum(a, b):
    """Knuth 2Sum: s + t == a + b exactly, s = fl(a + b)."""
    s = a + b
    bb = s - a
    t = (a - (s - bb)) + (b - bb)
    return s, t


@lru_cache(maxsize=16)
def _gemm_f64emu_fn(m: int, k: int, n: int, s: int):
    kc = -(-k // _CHUNK)
    kpad = kc * _CHUNK

    def fn(A_slices, B_slices):
        # A_slices: s × (m, k) bf16 integer grids; B_slices: s × (k, n)
        hi = jnp.zeros((m, n), jnp.float32)
        lo = jnp.zeros((m, n), jnp.float32)
        for i in range(s):
            Ai = jnp.pad(A_slices[i], ((0, 0), (0, kpad - k)))
            Ac = Ai.reshape(m, kc, _CHUNK).swapaxes(0, 1)   # (kc, m, CHUNK)
            for j in range(s - i):      # i + j >= s: below target precision
                Bj = jnp.pad(B_slices[j], ((0, kpad - k), (0, 0)))
                Bc = Bj.reshape(kc, _CHUNK, n)
                parts = jax.vmap(lambda a, b: jnp.matmul(
                    a, b, preferred_element_type=jnp.float32))(Ac, Bc)
                # exact integer chunk sums, scaled by their exact power of 2
                scale = jnp.float32(2.0 ** (-14 - 8 * (i + j)))

                def add_chunk(c, hilo, parts=parts, scale=scale):
                    h, l = hilo
                    h2, t = _two_sum(h, parts[c] * scale)
                    return h2, l + t

                hi, lo = lax.fori_loop(0, kc, add_chunk, (hi, lo))
        return hi, lo

    return jax.jit(fn)


def _gemm_f64emu_real(A, B, slices: int):
    """(hi, lo) double-f32 pair for real A @ B, exponents folded back in
    (power-of-two multiplies — exact)."""
    m, k = A.shape
    n = B.shape[-1]
    As, ea = split_fixed_slices(A, slices)
    Bs_t, eb = split_fixed_slices(B.T, slices)
    Bs = tuple(b.T for b in Bs_t)
    hi, lo = _gemm_f64emu_fn(m, k, n, slices)(tuple(As), Bs)
    # scale in the widest dtype available: under x64 the exponent SUM ea+eb
    # (up to ±2000 after clamping) still fits f64's normal range; on the
    # f32-only target the sum clamps — saturating exactly like any f32
    # representation of the true product would
    sdt = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    esum = ea.astype(sdt)[:, None] + eb.astype(sdt)[None, :]
    sc = _exact_pow2(esum, sdt)
    return (hi.astype(sdt) * sc).astype(sdt), (lo.astype(sdt) * sc).astype(sdt)


def _hilo_add(h, l, x):
    """Fold x into the (hi, lo) accumulator error-free (2Sum)."""
    h2, t = _two_sum(h, x)
    return h2, l + t


def gemm_f64emu(A, B, alpha=1.0, beta=0.0, C=None, slices: int = 7,
                return_hilo: bool = False):
    """Double-precision-class ``alpha·A@B + beta·C`` on bf16 hardware via the
    exact Ozaki-style splitting above (2-D operands; complex handled as four
    real products).

    The whole combination — including ``beta·C`` — happens inside the
    double-f32 (hi, lo) accumulator, so residual-style calls
    (``alpha=1, beta=-1``) keep their accuracy even when the result is tiny
    against ``A@B`` (the catastrophic-cancellation case plain f32 loses).
    alpha/beta that are signed powers of two (the residual case) fold in
    exactly; general scalars round once in f32.

    Returns f64 where available (CPU testing), else the collapsed f32 —
    already carrying the compensated accumulation; pass ``return_hilo=True``
    for the raw (hi, lo) pair.  ``slices=7`` covers 56 mantissa bits
    (≥ f64's 53); smaller values trade accuracy for speed.
    """
    from ..core.exceptions import slate_assert

    A = jnp.asarray(A)
    B = jnp.asarray(B)
    slate_assert(A.ndim == 2 and B.ndim == 2,
                 "gemm_f64emu takes 2-D operands (vmap/batch outside)")
    if jnp.iscomplexobj(A) or jnp.iscomplexobj(B):
        Ar, Ai = jnp.real(A), jnp.imag(A)
        Br, Bi = jnp.real(B), jnp.imag(B)
        rr = gemm_f64emu(Ar, Br, slices=slices, return_hilo=True)
        ii = gemm_f64emu(Ai, Bi, slices=slices, return_hilo=True)
        ri = gemm_f64emu(Ar, Bi, slices=slices, return_hilo=True)
        ir = gemm_f64emu(Ai, Br, slices=slices, return_hilo=True)
        reh, rel = _hilo_add(rr[0], rr[1] - ii[1], -ii[0])
        imh, iml = _hilo_add(ri[0], ri[1] + ir[1], ir[0])
        cdt = jnp.complex128 if jax.config.jax_enable_x64 else jnp.complex64
        prod_h = reh.astype(cdt) + 1j * imh.astype(cdt)
        prod_l = rel.astype(cdt) + 1j * iml.astype(cdt)
        prod_h, prod_l = prod_h * alpha, prod_l * alpha
        if C is not None and beta != 0:
            prod_h, prod_l = _hilo_add(prod_h, prod_l,
                                       beta * jnp.asarray(C).astype(cdt))
        if return_hilo:
            return prod_h, prod_l
        return prod_h + prod_l
    hi, lo = _gemm_f64emu_real(A, B, slices)
    af = jnp.float32(alpha)
    hi, lo = hi * af, lo * af            # exact for signed powers of two
    if C is not None and beta != 0 and jnp.iscomplexobj(C):
        # real A·B with a complex C: the product contributes only to the real
        # part, but C's imaginary part must survive (previously it was
        # silently discarded by the f32 cast).  Fold beta·Re(C) into the real
        # accumulator and carry beta·Im(C) as its own split pair.
        Cf = jnp.asarray(C)
        bf = jnp.float32(beta)
        cr_hi = jnp.real(Cf).astype(jnp.float32)
        hi, lo = _hilo_add(hi, lo, bf * cr_hi)
        ci_hi = jnp.imag(Cf).astype(jnp.float32)
        im_h, im_l = bf * ci_hi, jnp.zeros_like(ci_hi)
        if Cf.dtype == jnp.dtype(jnp.complex128):
            cr = jnp.real(Cf)
            ci = jnp.imag(Cf)
            lo = lo + bf * (cr - cr_hi.astype(cr.dtype)).astype(jnp.float32)
            im_l = im_l + bf * (ci - ci_hi.astype(ci.dtype)).astype(jnp.float32)
        cdt = jnp.complex128 if jax.config.jax_enable_x64 else jnp.complex64
        prod_h = hi.astype(cdt) + 1j * im_h.astype(cdt)
        prod_l = lo.astype(cdt) + 1j * im_l.astype(cdt)
        if return_hilo:
            return prod_h, prod_l
        return prod_h + prod_l
    if C is not None and beta != 0:
        # fold C in as its own double-f32 split, so an f64 C (CPU testing /
        # a caller-carried hilo pair collapsed to f64) loses nothing; an f32
        # C bounds the result by its own storage precision, unavoidably
        Cf = jnp.asarray(C)
        bf = jnp.float32(beta)
        c_hi = Cf.astype(jnp.float32)
        hi, lo = _hilo_add(hi, lo, bf * c_hi)
        if Cf.dtype in (jnp.float64, jnp.dtype("float64")):
            c_lo = (Cf - c_hi.astype(Cf.dtype)).astype(jnp.float32)
            lo = lo + bf * c_lo
    if return_hilo:
        return hi, lo
    out_dt = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    return hi.astype(out_dt) + lo.astype(out_dt)


def _f64ir_refine(A, B2, Xh, solve32, max_iterations: int,
                  tol_factor: float):
    """Shared refinement core of gesv_f64ir / posv_f64ir: double-f32 iterate,
    residuals through the compensated gemm, stagnation-aware stop.  Returns
    (Xh, Xl, iters, info): info = 1 when the f32 factor produced non-finite
    values (singular / not SPD) — the LAPACK-style signal the *_mixed
    drivers carry — in which case the loop never runs.

    Device-side throughout: the convergence test rides a ``lax.while_loop``
    carry, so the whole solve is jittable and costs ONE host sync at the
    caller's read-out — on the TPU tunnel (~70 ms round-trip) the previous
    per-round ``float()`` checks dominated the solve itself."""
    Xl = jnp.zeros_like(Xh)
    finite = jnp.all(jnp.isfinite(Xh))
    eps32 = float(jnp.finfo(jnp.float32).eps)
    rdt = jnp.zeros((), Xh.dtype).real.dtype
    b_hi = B2.astype(Xh.dtype)
    bnorm = jnp.max(jnp.abs(b_hi))
    bnorm = jnp.where(bnorm == 0, 1.0, bnorm).astype(rdt)
    anorm = jnp.max(jnp.abs(A)).astype(rdt)
    xnorm = jnp.max(jnp.abs(Xh))
    xnorm = jnp.where(xnorm == 0, 1.0, xnorm).astype(rdt)
    tol = tol_factor * (eps32 ** 2) * jnp.maximum(bnorm, anorm * xnorm)

    def cond(c):
        _, _, _, it, stop = c
        return (~stop) & (it < max_iterations)

    def body(c):
        Xh, Xl, prev, it, _ = c
        rh, rl = gemm_f64emu(A, Xh.astype(A.dtype), alpha=-1.0, beta=1.0,
                             C=B2, return_hilo=True)
        rh2, rl2 = gemm_f64emu(A, Xl.astype(A.dtype), alpha=-1.0,
                               return_hilo=True)
        rh, t = _two_sum(rh, rh2)
        rl = rl + rl2 + t
        rfull = rh + rl
        rmax = jnp.max(jnp.abs(rfull)).astype(rdt)
        stop = (rmax <= tol) | (rmax > 0.9 * prev)

        def refine(_):
            D = solve32(rfull.astype(Xh.dtype))
            Xh2, tt = _two_sum(Xh, D)
            return Xh2, Xl + tt

        Xh3, Xl3 = lax.cond(stop, lambda _: (Xh, Xl), refine, None)
        return Xh3, Xl3, rmax, it + 1, stop

    init = (Xh, Xl, jnp.asarray(jnp.inf, rdt), jnp.int32(0), ~finite)
    Xh, Xl, _, iters, _ = lax.while_loop(cond, body, init)
    info = jnp.where(finite, 0, 1).astype(jnp.int32)
    return Xh, Xl, iters, info


def gesv_f64ir(A, B, max_iterations: int = 20, tol_factor: float = 4.0):
    """Solve A X = B to double-precision-class accuracy on f32 hardware:
    f32 LU factor + iterative refinement whose residuals run through the
    exact-splitting gemm — SURVEY §7's "bf16/f32 factor, f64-emulated
    refine" made concrete (the reference's gesv_mixed with the refinement
    precision EMULATED instead of assumed in hardware).

    The iterate is carried as a double-f32 (Xh, Xl) pair; each round
    computes R = B - A·(Xh + Xl) inside the compensated accumulator (both
    halves through ``gemm_f64emu``'s hilo path), solves the f32 correction
    against the cached LU, and folds it in error-free.  Standard IR theory
    then gives forward error ~ eps_emu · cond(A), i.e. ~1e-13-class
    solutions for well-conditioned systems — on hardware whose native
    solve stops at ~1e-6.

    Returns ``(Xh, Xl, iterations, info)``: the solution is ``Xh + Xl``
    evaluated in f64 (or consumed as a pair on f64-less backends); info = 1
    means the f32 factor was singular (non-finite) and no refinement ran.
    Complex inputs factor in c64 and refine through the four-real-products
    gemm path.
    """
    A = jnp.asarray(A)
    B = jnp.asarray(B)
    vec = B.ndim == 1
    B2 = B[:, None] if vec else B
    lo_dt = jnp.complex64 if jnp.iscomplexobj(A) else jnp.float32
    Af = A.astype(lo_dt)
    plu, _, perm = lax.linalg.lu(Af)

    def solve32(R):
        pb = jnp.take(R, perm, axis=0)
        y = lax.linalg.triangular_solve(plu, pb, left_side=True, lower=True,
                                        unit_diagonal=True)
        return lax.linalg.triangular_solve(plu, y, left_side=True,
                                           lower=False)

    Xh = solve32(B2.astype(lo_dt))
    Xh, Xl, iters, info = _f64ir_refine(A, B2, Xh, solve32, max_iterations,
                                        tol_factor)
    return ((Xh[:, 0], Xl[:, 0], iters, info) if vec
            else (Xh, Xl, iters, info))


def posv_f64ir(A, B, max_iterations: int = 20, tol_factor: float = 4.0):
    """SPD/HPD sibling of ``gesv_f64ir`` (the posv_mixed counterpart): f32
    Cholesky factor + f64-emulated-residual refinement.  Same double-f32
    iterate and convergence policy; returns ``(Xh, Xl, iterations, info)``
    with info = 1 when A is not (numerically) positive definite."""
    A = jnp.asarray(A)
    B = jnp.asarray(B)
    vec = B.ndim == 1
    B2 = B[:, None] if vec else B
    lo_dt = jnp.complex64 if jnp.iscomplexobj(A) else jnp.float32
    Af = A.astype(lo_dt)
    L = lax.linalg.cholesky(Af)

    def solve32(R):
        y = lax.linalg.triangular_solve(L, R, left_side=True, lower=True)
        return lax.linalg.triangular_solve(L, y, left_side=True, lower=True,
                                           conjugate_a=True, transpose_a=True)

    Xh = solve32(B2.astype(lo_dt))
    Xh, Xl, iters, info = _f64ir_refine(A, B2, Xh, solve32, max_iterations,
                                        tol_factor)
    return ((Xh[:, 0], Xl[:, 0], iters, info) if vec
            else (Xh, Xl, iters, info))
