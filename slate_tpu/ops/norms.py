"""Matrix norms over general / symmetric / triangular / band structures.

Reference analogue: ``src/internal/internal_{ge,he,sy,tr,gb,hb}norm.cc`` plus the CUDA
reductions ``src/cuda/device_{ge,he,sy,tr}norm.cu`` and the drivers ``src/norm.cc`` /
``src/colNorms.cc``.

TPU re-design: each norm is one masked XLA reduction over the HBM-resident array —
the per-tile partial-norm + host-combine structure of the reference exists only to
span GPUs and ranks, which the sharded reduction handles natively (psum over the mesh
when the array is sharded).  One-norm of a symmetric matrix uses the
half-stored form directly, like synorm/henorm do: col_sums(full) =
col_sums(stored triangle) + row_sums(strict stored triangle) transposed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.exceptions import SlateError
from ..core.types import Diag, Norm, NormScope, Uplo
from .elementwise import _mask
from . import pallas_norms as _pk

#: route 2-D unbatched norms through the Pallas streaming kernels on TPU
#: (set False to force the plain XLA reductions; tests cover both paths)
USE_PALLAS = True

_PK_WHICH = {Norm.Max: "max", Norm.One: "one", Norm.Inf: "inf", Norm.Fro: "fro"}


def _pallas_ok(A) -> bool:
    # complex dtypes stay on the XLA path: Mosaic has no complex lowering, so
    # the kernel's jnp.abs would fail to compile on the real TPU backend
    return (USE_PALLAS and _pk.available() and getattr(A, "ndim", 0) == 2
            and not jnp.issubdtype(getattr(A, "dtype", jnp.float32),
                                   jnp.complexfloating)
            and jax.default_backend() == "tpu")


def _abs(A):
    return jnp.abs(A)


def genorm(norm, A, scope=NormScope.Matrix):
    """General-matrix norm (internal_genorm.cc, device_genorm.cu).

    scope=Columns returns the vector of column norms (the colNorms driver,
    src/colNorms.cc — only Max is supported there, like the reference).
    """
    norm = Norm.from_string(norm)
    scope = NormScope.from_string(scope) if not isinstance(scope, NormScope) else scope
    if scope == NormScope.Columns:
        if norm != Norm.Max:
            raise SlateError("colNorms supports Norm.Max only (matches reference)")
        if _pallas_ok(A):
            return _pk.col_norms_max(A)
        return jnp.max(_abs(A), axis=-2)
    if _pallas_ok(A) and norm in _PK_WHICH:
        return _pk.genorm(A, _PK_WHICH[norm])
    a = _abs(A)
    if norm == Norm.Max:
        return jnp.max(a)
    if norm == Norm.One:
        return jnp.max(jnp.sum(a, axis=-2))
    if norm == Norm.Inf:
        return jnp.max(jnp.sum(a, axis=-1))
    if norm == Norm.Fro:
        return jnp.sqrt(jnp.sum(jnp.square(a)))
    raise SlateError(f"unsupported norm {norm}")


def _masked(A, uplo, diag=Diag.NonUnit):
    mask = _mask(A.shape, uplo)
    a = jnp.where(mask, A, 0)
    if Diag.from_string(diag) == Diag.Unit:
        idx = jnp.arange(min(A.shape[-2:]))
        a = a.at[..., idx, idx].set(jnp.ones((), A.dtype))
    return a


def trnorm(norm, uplo, diag, A):
    """Trapezoid/triangular norm (internal_trnorm.cc, device_trnorm.cu).

    On TPU the triangle mask is applied in-register inside the Pallas kernel
    instead of materializing the masked matrix in HBM."""
    which = _PK_WHICH.get(Norm.from_string(norm))
    if _pallas_ok(A) and which is not None:
        lower = Uplo.from_string(uplo) == Uplo.Lower
        mode = _pk._MODE_LOWER if lower else _pk._MODE_UPPER
        return _pk.genorm(A, which, mode=mode,
                          unit_diag=Diag.from_string(diag) == Diag.Unit)
    return genorm(norm, _masked(A, uplo, diag))


def synorm(norm, uplo, A):
    """Symmetric norm from the stored triangle (internal_synorm.cc).

    One == Inf by symmetry; column sums combine the stored triangle's columns with its
    strict rows (synormOffdiag device kernel, device.hh:234-240).
    """
    norm = Norm.from_string(norm)
    lower = Uplo.from_string(uplo) == Uplo.Lower
    absA = jnp.abs(A)
    tri = jnp.tril(absA) if lower else jnp.triu(absA)          # stored triangle
    strict = jnp.tril(absA, -1) if lower else jnp.triu(absA, 1)  # excl. diagonal
    if norm == Norm.Max:
        return jnp.max(tri)
    if norm in (Norm.One, Norm.Inf):
        col = jnp.sum(tri, axis=-2) + jnp.sum(strict, axis=-1)
        return jnp.max(col)
    if norm == Norm.Fro:
        diag_sq = jnp.sum(jnp.square(jnp.abs(jnp.diagonal(A, axis1=-2, axis2=-1))))
        off_sq = jnp.sum(jnp.square(strict))
        return jnp.sqrt(2.0 * off_sq + diag_sq)
    raise SlateError(f"unsupported norm {norm}")


def henorm(norm, uplo, A):
    """Hermitian norm (internal_henorm.cc) — same combine as synorm; |.| removes the
    conjugation difference."""
    return synorm(norm, uplo, A)


def gbnorm(norm, kl, ku, A):
    """Band norm (internal_gbnorm.cc): mask outside the band then reduce."""
    m, n = A.shape[-2], A.shape[-1]
    r = jnp.arange(m)[:, None]
    c = jnp.arange(n)[None, :]
    band = (c - r <= ku) & (r - c <= kl)
    return genorm(norm, jnp.where(band, A, 0))


def hbnorm(norm, uplo, kd, A):
    """Hermitian band norm (internal_hbnorm.cc)."""
    n = A.shape[-1]
    r = jnp.arange(n)[:, None]
    c = jnp.arange(n)[None, :]
    if Uplo.from_string(uplo) == Uplo.Lower:
        band = (r - c <= kd) & (r >= c)
    else:
        band = (c - r <= kd) & (c >= r)
    return synorm(norm, uplo, jnp.where(band, A, 0))
