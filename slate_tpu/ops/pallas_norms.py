"""Pallas TPU kernels for the norm family (max / one / inf / fro, with scopes and
triangle masks).

Reference analogue: the hand-written CUDA reductions ``src/cuda/device_genorm.cu``,
``device_{he,sy,tr}norm.cu`` and their batch wrappers — the one kernel family the
survey marks as deserving real custom kernels on TPU (SURVEY.md §2.5): a norm is a
pure reduction, so XLA materializes |A| (an extra HBM round-trip) unless fused;
the Pallas kernel streams each (block_rows x block_cols) tile through VMEM once,
computing |.|, triangle masking, and the partial reduction in registers, and
accumulates across the sequential TPU grid — the same structure as the reference's
per-tile partial-norm kernels plus host combine.

The grid is 2-D (row blocks x col blocks) so VMEM stays bounded (~2 MB/block) for
any matrix shape; TPU executes the grid sequentially with the last dimension
innermost, which the accumulation predicates rely on.  Zero padding is safe for
every reduction here (|0| contributes nothing to max of abs, sums, or squares).

On non-TPU backends the same kernels run through the Pallas interpreter
(``interpret=True``) so CPU tests exercise the identical code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # both pallas and its TPU backend are optional: a jax build without
    # pallas must not break `import slate_tpu` (the XLA norm path needs none)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover - environment-specific
    pl = None
    pltpu = None
    _HAS_PALLAS = False

_LANE = 128          # TPU lane width: last dim must be a multiple
_SUBLANE = 8         # f32 sublane count: the native vreg tile is (8, 128), so
                     # every in-kernel partial is kept (8, lanes)-shaped — a
                     # 1-row partial would leave 7 of 8 sublanes idle on every
                     # accumulate and force a masked store per grid step
_BM = 512            # row-block
_BN = 2048           # col-block: 512x2048 f32 = 4 MB of VMEM per buffer
                     # (8 MB double-buffered, inside the ~16 MB VMEM budget;
                     # deeper blocks halve the grid-step count vs round 3)

# mask modes (static kernel parameter)
_MODE_GE = 0         # no mask
_MODE_LOWER = 1      # keep r >= c
_MODE_UPPER = 2      # keep r <= c
_MODE_LOWER_STRICT = 3   # keep r > c
_MODE_UPPER_STRICT = 4   # keep r < c


def available() -> bool:
    return _HAS_PALLAS


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _ceil_mult(x: int, m: int) -> int:
    return -(-x // m) * m


def _launch(m: int, n: int, dtype, kind: str):
    """Launch geometry of the streaming reductions — the ONE source of truth
    consumed by ``col_reduce``/``row_sums`` AND reported by ``kernel_plan``,
    so the committed evidence cannot drift from the kernels it describes
    (tests cross-check it against the traced ``pallas_call`` params).

    Returns (bm, bn, pm, pn, grid): block shape, padded shape, and the grid
    with the reduced dimension INNERMOST (kind='col' reduces rows, 'row'
    reduces cols)."""
    bm, bn = _blocks(m, n, dtype)
    pm = _ceil_mult(m, bm)
    pn = _ceil_mult(max(n, _LANE), bn)
    grid = (pn // bn, pm // bm) if kind == "col" else (pm // bm, pn // bn)
    return bm, bn, pm, pn, grid


def _pad_to(a: jax.Array, pm: int, pn: int):
    """Zero-pad up to the launch shape (zero is neutral for every reduction
    here)."""
    m, n = a.shape
    if (pm, pn) != (m, n):
        a = jnp.pad(a, ((0, pm - m), (0, pn - n)))
    return a


def _block_abs(ref, mode: int, unit_diag: bool, i, j, bm: int, bn: int,
               m_valid: int, n_valid: int):
    """|block| with the triangle mask applied in-register (device_trnorm.cu's
    masked read). Row/col ids are global via the block offsets; the valid extents
    keep zero padding out of upper-triangle and unit-diagonal fills."""
    x = jnp.abs(ref[...])
    rows = i * bm + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    cols = j * bn + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    if mode == _MODE_LOWER:
        keep = rows >= cols
    elif mode == _MODE_UPPER:
        keep = (rows <= cols) & (cols < n_valid)
    elif mode == _MODE_LOWER_STRICT:
        keep = rows > cols
    elif mode == _MODE_UPPER_STRICT:
        keep = (rows < cols) & (cols < n_valid)
    else:
        keep = None
    if keep is not None:
        x = jnp.where(keep, x, 0)
    if unit_diag:
        x = jnp.where((rows == cols) & (rows < min(m_valid, n_valid)), 1.0, x)
    return x


def _real(dtype):
    return jnp.zeros((), dtype).real.dtype


def _blocks(bm, bn, dtype=None):
    """Block shape capped in BYTES, not elements: _BM/_BN are sized for f32
    (4 MB/buffer, 8 MB double-buffered inside the ~16 MB VMEM); wider dtypes
    (f64 under x64, complex) scale the row block down so the budget holds.
    Both dims come out (8, 128)-tile aligned: rows a _SUBLANE multiple (the
    in-kernel sublane fold reshapes (bm, bn) -> (bm/8, 8, bn)), cols a _LANE
    multiple."""
    itemsize = jnp.dtype(dtype or jnp.float32).itemsize
    bm_cap = max(_SUBLANE, (_BM * 4) // max(itemsize, 4))
    return (_ceil_mult(max(_SUBLANE, min(bm, bm_cap)), _SUBLANE),
            max(_LANE, min(_ceil_mult(bn, _LANE), _BN)))


@functools.partial(jax.jit, static_argnames=("mode", "unit_diag"))
def max_norm(a: jax.Array, mode: int = _MODE_GE,
             unit_diag: bool = False) -> jax.Array:
    """max |a_ij| over the (masked) matrix — one streaming pass.

    Rides the per-column kernel: the in-kernel reduction folds row blocks to
    an (8, bn) sublane-partial tile per lane column, with the final fold left
    to XLA on the tiny (8, pn) output.  The round-3 form reduced every block
    to an SMEM scalar in-kernel; the cross-lane shuffles serialized the VPU
    against the DMA stream (VERDICT r3 #5: 0.255x baseline, ~230 GB/s
    effective)."""
    return jnp.max(col_reduce(a, mode, unit_diag, op="max"))


@functools.partial(jax.jit, static_argnames=("mode", "unit_diag"))
def sumsq(a: jax.Array, mode: int = _MODE_GE,
          unit_diag: bool = False) -> jax.Array:
    """sum |a_ij|^2 (fro-norm partial) — per-column partials in-kernel
    (lane-parallel), final length-pn sum in XLA (same rationale as
    ``max_norm``)."""
    return jnp.sum(col_reduce(a, mode, unit_diag, op="sumsq"))


@functools.partial(jax.jit, static_argnames=("mode", "unit_diag", "op"))
def col_reduce(a: jax.Array, mode: int = _MODE_GE, unit_diag: bool = False,
               op: str = "sum") -> jax.Array:
    """Per-column reduction over row blocks: op='sum' -> column sums of |a|
    (one-norm partials); 'max' -> column maxes (colNorms); 'sumsq' -> sums of
    |a|^2 (fro partials).  Returns the length-n vector.

    (8, 128)-tile alignment: the in-kernel fold reshapes the (bm, bn) block to
    (bm/8, 8, bn) and reduces over the leading axis only, so every add/max is
    an elementwise op between full (8, bn) vreg tiles — row r lands in sublane
    r % 8 and never crosses sublanes.  The output block is the (8, bn) partial
    tile itself (native-tile store, all sublanes live); the 8-row fold runs in
    XLA on the tiny (8, pn) result.  The round-5 form accumulated a (1, bn)
    row — 1 of 8 sublanes active in every accumulate and a sub-tile masked
    store per grid step."""
    rdt = _real(a.dtype)
    m, n = a.shape
    bm, bn, pm, pn, grid = _launch(m, n, a.dtype, "col")
    a_p = _pad_to(a, pm, pn)

    # the reduced (row) dimension must be the INNERMOST grid dim so consecutive
    # grid steps keep revisiting the same output block (TPU pipelining flushes an
    # output block when its index changes — the standard K-innermost accumulation
    # rule)
    def kernel(in_ref, out_ref):
        j, i = pl.program_id(0), pl.program_id(1)
        x = _block_abs(in_ref, mode, unit_diag, i, j, bm, bn, m, n).astype(rdt)
        if op == "sumsq":
            x = x * x
        xg = x.reshape(bm // _SUBLANE, _SUBLANE, bn)
        part = (jnp.max(xg, axis=0) if op == "max" else jnp.sum(xg, axis=0))

        @pl.when(i == 0)
        def _():
            out_ref[...] = part

        @pl.when(i > 0)
        def _():
            if op == "max":
                out_ref[...] = jnp.maximum(out_ref[...], part)
            else:
                out_ref[...] = out_ref[...] + part

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((bm, bn), lambda j, i: (i, j))],
        out_specs=pl.BlockSpec((_SUBLANE, bn), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((_SUBLANE, pn), rdt),
        interpret=_interpret(),
    )(a_p)
    folded = (jnp.max(out, axis=0) if op == "max" else jnp.sum(out, axis=0))
    return folded[:n]


@functools.partial(jax.jit, static_argnames=("mode", "unit_diag"))
def row_sums(a: jax.Array, mode: int = _MODE_GE,
             unit_diag: bool = False) -> jax.Array:
    """Per-row sums of |a| (inf-norm partials), accumulated across col blocks.

    The in-kernel reduction folds the bn columns down to _LANE lane-partials
    per row — ``reshape(bm, bn/_LANE, _LANE)`` keeps every add lane-aligned
    (element (r, c) lands in lane c % 128), so the VPU never shuffles across
    lanes; the final 128-wide fold runs in XLA on the (m, 128) partials.
    The round-3 form summed axis=1 to a (bm, 1) column in-kernel — a full
    cross-lane reduction per block that serialized against the DMA stream."""
    rdt = _real(a.dtype)
    m, n = a.shape
    bm, bn, pm, pn, grid = _launch(m, n, a.dtype, "row")
    a_p = _pad_to(a, pm, pn)

    def kernel(in_ref, out_ref):
        i, j = pl.program_id(0), pl.program_id(1)
        x = _block_abs(in_ref, mode, unit_diag, i, j, bm, bn, m, n).astype(rdt)
        part = jnp.sum(x.reshape(bm, bn // _LANE, _LANE), axis=1)

        @pl.when(j == 0)
        def _():
            out_ref[...] = part

        @pl.when(j > 0)
        def _():
            out_ref[...] = out_ref[...] + part

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((bm, _LANE), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((pm, _LANE), rdt),
        interpret=_interpret(),
    )(a_p)
    return jnp.sum(out[:m], axis=1)


def genorm(a: jax.Array, which: str, mode: int = _MODE_GE,
           unit_diag: bool = False) -> jax.Array:
    """Full norm via the streaming kernels (general or triangle-masked).

    which: max | one | inf | fro.  Scalar result.
    """
    if which == "max":
        return max_norm(a, mode, unit_diag)
    if which == "one":
        return jnp.max(col_reduce(a, mode, unit_diag, op="sum"))
    if which == "inf":
        return jnp.max(row_sums(a, mode, unit_diag))
    if which == "fro":
        return jnp.sqrt(sumsq(a, mode, unit_diag))
    raise ValueError(f"unknown norm '{which}'")


def col_norms_max(a: jax.Array) -> jax.Array:
    """colNorms(Max) — vector of column max-norms (src/colNorms.cc)."""
    return col_reduce(a, op="max")


def kernel_plan(m: int, n: int, dtype=jnp.float32, kind: str = "col") -> dict:
    """Static launch plan of the streaming reduction at (m, n) — committable
    kernel-shape evidence (the CI perf pin asserts on this, and a capture
    window can confirm the same numbers on chip).

    kind='col' describes ``col_reduce`` (one/fro/max partials), kind='row'
    describes ``row_sums`` (inf partials).  The geometry comes from the SAME
    ``_launch`` helper the kernels consume (and the tests cross-check against
    the traced ``pallas_call`` params), so the plan cannot drift from the
    code.  Returns grid, block shapes, the padded array shape, and the HBM
    traffic model: ``bytes_in`` is the padded input read exactly ONCE (grid
    steps x input-block bytes == padded bytes — the single-streaming-pass
    invariant), ``bytes_out`` the partial tile written back, ``pad_ratio``
    the padding overhead vs the logical array.
    """
    dt = jnp.dtype(dtype)
    rdt = jnp.zeros((), dt).real.dtype
    bm, bn, pm, pn, grid = _launch(m, n, dt, kind)
    in_block = (bm, bn)
    out_block = (_SUBLANE, bn) if kind == "col" else (bm, _LANE)
    out_shape = (_SUBLANE, pn) if kind == "col" else (pm, _LANE)
    steps = grid[0] * grid[1]
    bytes_in = steps * bm * bn * dt.itemsize
    return {
        "grid": grid,
        "in_block": in_block,
        "out_block": out_block,
        "out_shape": out_shape,
        "padded_shape": (pm, pn),
        "bytes_in": bytes_in,
        "bytes_out": out_shape[0] * out_shape[1] * jnp.dtype(rdt).itemsize,
        "single_pass": bytes_in == pm * pn * dt.itemsize,
        "pad_ratio": (pm * pn) / float(max(m, 1) * max(n, 1)),
        "sublane_aligned": out_block[0] % _SUBLANE == 0
                           and in_block[0] % _SUBLANE == 0,
        "lane_aligned": out_block[1] % _LANE == 0 and in_block[1] % _LANE == 0,
    }


def traced_plan(m: int, n: int, dtype=jnp.float32, kind: str = "col") -> dict:
    """The TRACED launch evidence: grid, block shapes, and input-block
    coverage extracted from the actual ``pallas_call`` jaxpr of
    ``col_reduce``/``row_sums`` — the non-tautological half of the perf pin
    (``kernel_plan`` is the static model; this is what the kernel really
    does).

    ``single_pass`` here means the input index_map, evaluated over EVERY
    grid point, visits each input block exactly once — a revisiting
    index_map (a genuine multi-pass traffic regression) fails it even when
    the grid is unchanged.  Raises loudly on jax-internals drift so the CI
    pin cannot rot into a silent pass.
    """
    import itertools

    fn = (lambda x: col_reduce(x)) if kind == "col" else (lambda x: row_sums(x))
    jaxpr = jax.make_jaxpr(fn)(jnp.zeros((m, n), dtype))

    def find(j):
        for eqn in j.eqns:
            if eqn.primitive.name == "pallas_call":
                return eqn
            for v in eqn.params.values():
                inner = getattr(v, "jaxpr", None)
                if inner is not None:
                    found = find(getattr(inner, "jaxpr", inner))
                    if found is not None:
                        return found
        return None

    eqn = find(jaxpr.jaxpr)
    if eqn is None:
        raise RuntimeError("no pallas_call in traced norm kernel")
    gm = eqn.params["grid_mapping"]
    grid = tuple(gm.grid)
    blocks = [tuple(b.block_shape) for b in gm.block_mappings]
    # evaluate the INPUT block index_map over the whole grid: bijective
    # coverage == one streaming pass over HBM
    cj = gm.block_mappings[0].index_map_jaxpr
    visited = []
    for idx in itertools.product(*(range(g) for g in grid)):
        out = jax.core.eval_jaxpr(cj.jaxpr, cj.consts, *map(jnp.int32, idx))
        visited.append(tuple(int(v) for v in out))
    steps = len(visited)
    operand_shapes = {tuple(v.aval.shape) for v in eqn.invars}
    return {
        "grid": grid,
        "blocks": blocks,
        "operand_shapes": operand_shapes,
        "steps": steps,
        "unique_input_blocks": len(set(visited)),
        "single_pass": len(set(visited)) == steps,
    }
