"""Pallas TPU kernels for the norm family (max / one / inf / fro, with scopes and
triangle masks).

Reference analogue: the hand-written CUDA reductions ``src/cuda/device_genorm.cu``,
``device_{he,sy,tr}norm.cu`` and their batch wrappers — the one kernel family the
survey marks as deserving real custom kernels on TPU (SURVEY.md §2.5): a norm is a
pure reduction, so XLA materializes |A| (an extra HBM round-trip) unless fused;
the Pallas kernel streams each (block_rows x block_cols) tile through VMEM once,
computing |.|, triangle masking, and the partial reduction in registers, and
accumulates across the sequential TPU grid — the same structure as the reference's
per-tile partial-norm kernels plus host combine.

The grid is 2-D (row blocks x col blocks) so VMEM stays bounded (~2 MB/block) for
any matrix shape; TPU executes the grid sequentially with the last dimension
innermost, which the accumulation predicates rely on.  Zero padding is safe for
every reduction here (|0| contributes nothing to max of abs, sums, or squares).

On non-TPU backends the same kernels run through the Pallas interpreter
(``interpret=True``) so CPU tests exercise the identical code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # both pallas and its TPU backend are optional: a jax build without
    # pallas must not break `import slate_tpu` (the XLA norm path needs none)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover - environment-specific
    pl = None
    pltpu = None
    _HAS_PALLAS = False

_LANE = 128          # TPU lane width: last dim must be a multiple
_BM = 512            # row-block
_BN = 2048           # col-block: 512x2048 f32 = 4 MB of VMEM per buffer
                     # (8 MB double-buffered, inside the ~16 MB VMEM budget;
                     # deeper blocks halve the grid-step count vs round 3)

# mask modes (static kernel parameter)
_MODE_GE = 0         # no mask
_MODE_LOWER = 1      # keep r >= c
_MODE_UPPER = 2      # keep r <= c
_MODE_LOWER_STRICT = 3   # keep r > c
_MODE_UPPER_STRICT = 4   # keep r < c


def available() -> bool:
    return _HAS_PALLAS


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _ceil_mult(x: int, m: int) -> int:
    return -(-x // m) * m


def _pad2(a: jax.Array, bm: int, bn: int):
    """Zero-pad both dims up to block multiples (last dim also lane-aligned)."""
    m, n = a.shape
    pm = _ceil_mult(m, bm)
    pn = _ceil_mult(max(n, _LANE), bn if bn % _LANE == 0 else _ceil_mult(bn, _LANE))
    if (pm, pn) != (m, n):
        a = jnp.pad(a, ((0, pm - m), (0, pn - n)))
    return a, pm, pn


def _block_abs(ref, mode: int, unit_diag: bool, i, j, bm: int, bn: int,
               m_valid: int, n_valid: int):
    """|block| with the triangle mask applied in-register (device_trnorm.cu's
    masked read). Row/col ids are global via the block offsets; the valid extents
    keep zero padding out of upper-triangle and unit-diagonal fills."""
    x = jnp.abs(ref[...])
    rows = i * bm + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    cols = j * bn + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    if mode == _MODE_LOWER:
        keep = rows >= cols
    elif mode == _MODE_UPPER:
        keep = (rows <= cols) & (cols < n_valid)
    elif mode == _MODE_LOWER_STRICT:
        keep = rows > cols
    elif mode == _MODE_UPPER_STRICT:
        keep = (rows < cols) & (cols < n_valid)
    else:
        keep = None
    if keep is not None:
        x = jnp.where(keep, x, 0)
    if unit_diag:
        x = jnp.where((rows == cols) & (rows < min(m_valid, n_valid)), 1.0, x)
    return x


def _real(dtype):
    return jnp.zeros((), dtype).real.dtype


def _blocks(bm, bn, dtype=None):
    """Block shape capped in BYTES, not elements: _BM/_BN are sized for f32
    (4 MB/buffer, 8 MB double-buffered inside the ~16 MB VMEM); wider dtypes
    (f64 under x64, complex) scale the row block down so the budget holds."""
    itemsize = jnp.dtype(dtype or jnp.float32).itemsize
    bm_cap = max(8, (_BM * 4) // max(itemsize, 4))
    return (max(8, min(bm, bm_cap)),
            max(_LANE, min(_ceil_mult(bn, _LANE), _BN)))


@functools.partial(jax.jit, static_argnames=("mode", "unit_diag"))
def max_norm(a: jax.Array, mode: int = _MODE_GE,
             unit_diag: bool = False) -> jax.Array:
    """max |a_ij| over the (masked) matrix — one streaming pass.

    Rides the per-column kernel: the in-kernel reduction is a sublane
    (cross-vreg elementwise) max per lane column, with the final 1-D lane
    reduction left to XLA on the tiny (pn,) vector.  The round-3 form
    reduced every block to an SMEM scalar in-kernel; the cross-lane
    shuffles serialized the VPU against the DMA stream (VERDICT r3 #5:
    0.255x baseline, ~230 GB/s effective)."""
    return jnp.max(col_reduce(a, mode, unit_diag, op="max"))


@functools.partial(jax.jit, static_argnames=("mode", "unit_diag"))
def sumsq(a: jax.Array, mode: int = _MODE_GE,
          unit_diag: bool = False) -> jax.Array:
    """sum |a_ij|^2 (fro-norm partial) — per-column partials in-kernel
    (lane-parallel), final length-pn sum in XLA (same rationale as
    ``max_norm``)."""
    return jnp.sum(col_reduce(a, mode, unit_diag, op="sumsq"))


@functools.partial(jax.jit, static_argnames=("mode", "unit_diag", "op"))
def col_reduce(a: jax.Array, mode: int = _MODE_GE, unit_diag: bool = False,
               op: str = "sum") -> jax.Array:
    """Per-column reduction over row blocks: op='sum' -> column sums of |a|
    (one-norm partials); 'max' -> column maxes (colNorms); 'sumsq' -> sums of
    |a|^2 (fro partials).  Returns the length-n vector."""
    rdt = _real(a.dtype)
    m, n = a.shape
    bm, bn = _blocks(m, n, a.dtype)
    a_p, pm, pn = _pad2(a, bm, bn)

    # the reduced (row) dimension must be the INNERMOST grid dim so consecutive
    # grid steps keep revisiting the same output block (TPU pipelining flushes an
    # output block when its index changes — the standard K-innermost accumulation
    # rule)
    def kernel(in_ref, out_ref):
        j, i = pl.program_id(0), pl.program_id(1)
        x = _block_abs(in_ref, mode, unit_diag, i, j, bm, bn, m, n).astype(rdt)
        if op == "sumsq":
            x = x * x
        part = (jnp.max(x, axis=0, keepdims=True) if op == "max"
                else jnp.sum(x, axis=0, keepdims=True))

        @pl.when(i == 0)
        def _():
            out_ref[...] = part

        @pl.when(i > 0)
        def _():
            if op == "max":
                out_ref[...] = jnp.maximum(out_ref[...], part)
            else:
                out_ref[...] = out_ref[...] + part

    out = pl.pallas_call(
        kernel,
        grid=(pn // bn, pm // bm),
        in_specs=[pl.BlockSpec((bm, bn), lambda j, i: (i, j))],
        out_specs=pl.BlockSpec((1, bn), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((1, pn), rdt),
        interpret=_interpret(),
    )(a_p)
    return out[0, :n]


@functools.partial(jax.jit, static_argnames=("mode", "unit_diag"))
def row_sums(a: jax.Array, mode: int = _MODE_GE,
             unit_diag: bool = False) -> jax.Array:
    """Per-row sums of |a| (inf-norm partials), accumulated across col blocks.

    The in-kernel reduction folds the bn columns down to _LANE lane-partials
    per row — ``reshape(bm, bn/_LANE, _LANE)`` keeps every add lane-aligned
    (element (r, c) lands in lane c % 128), so the VPU never shuffles across
    lanes; the final 128-wide fold runs in XLA on the (m, 128) partials.
    The round-3 form summed axis=1 to a (bm, 1) column in-kernel — a full
    cross-lane reduction per block that serialized against the DMA stream."""
    rdt = _real(a.dtype)
    m, n = a.shape
    bm, bn = _blocks(m, n, a.dtype)
    a_p, pm, pn = _pad2(a, bm, bn)

    def kernel(in_ref, out_ref):
        i, j = pl.program_id(0), pl.program_id(1)
        x = _block_abs(in_ref, mode, unit_diag, i, j, bm, bn, m, n).astype(rdt)
        part = jnp.sum(x.reshape(bm, bn // _LANE, _LANE), axis=1)

        @pl.when(j == 0)
        def _():
            out_ref[...] = part

        @pl.when(j > 0)
        def _():
            out_ref[...] = out_ref[...] + part

    out = pl.pallas_call(
        kernel,
        grid=(pm // bm, pn // bn),
        in_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((bm, _LANE), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((pm, _LANE), rdt),
        interpret=_interpret(),
    )(a_p)
    return jnp.sum(out[:m], axis=1)


def genorm(a: jax.Array, which: str, mode: int = _MODE_GE,
           unit_diag: bool = False) -> jax.Array:
    """Full norm via the streaming kernels (general or triangle-masked).

    which: max | one | inf | fro.  Scalar result.
    """
    if which == "max":
        return max_norm(a, mode, unit_diag)
    if which == "one":
        return jnp.max(col_reduce(a, mode, unit_diag, op="sum"))
    if which == "inf":
        return jnp.max(row_sums(a, mode, unit_diag))
    if which == "fro":
        return jnp.sqrt(sumsq(a, mode, unit_diag))
    raise ValueError(f"unknown norm '{which}'")


def col_norms_max(a: jax.Array) -> jax.Array:
    """colNorms(Max) — vector of column max-norms (src/colNorms.cc)."""
    return col_reduce(a, op="max")
