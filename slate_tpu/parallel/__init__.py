"""Distributed execution layer — the TPU-native replacement for MPI + process grids.

Reference analogue (SURVEY.md §2.6, §5.8): SLATE distributes tiles over a p×q MPI grid
(func.hh:100-217) and moves them with hypercube tile broadcasts/reductions
(BaseMatrix.hh:1999-2452, internal_comm.cc:72-123).  Here the process grid is a
``jax.sharding.Mesh`` over the TPU slice, tile ownership is a ``NamedSharding``, and
the tile collectives are XLA ICI collectives (`all_gather`, `psum`, `ppermute`,
`psum_scatter`) — either inserted automatically by GSPMD when drivers run under ``jit``
with sharded operands, or issued explicitly inside ``shard_map`` for the pipelined
algorithms (SUMMA ring gemm, tall-skinny CholQR trees).
"""

from .mesh import ProcessGrid
from .collectives import (axis_bcast, axis_allreduce, axis_reduce_scatter, ring_shift,
                          axis_index)
from .distribute import (block_spec, distribute, replicate, redistribute,
                         redistribute_matrix, cyclic_to_blocked,
                         blocked_to_cyclic, cyclic_permutation)
from .summa import gemm_distributed, gemm_allgather, gemm_ring, summa_gemm
from .blas3_dist import (herk_distributed, syrk_distributed, her2k_distributed,
                         syr2k_distributed, hemm_distributed, symm_distributed,
                         trmm_distributed, gbmm_distributed, hbmm_distributed)
from .solvers import (potrf_distributed, trsm_distributed, trsmA_distributed,
                      posv_distributed, posv_mixed_distributed,
                      posv_mixed_gmres_distributed, cholqr_distributed,
                      gels_cholqr_distributed)
from .lu_dist import (getrf_distributed, getrf_tall_distributed,
                      getrs_distributed, gesv_distributed,
                      gesv_mixed_distributed, gesv_mixed_gmres_distributed)
from .qr_dist import (tsqr_distributed, unmqr_distributed, gels_qr_distributed,
                      geqrf_distributed, gels_caqr_distributed,
                      gelqf_distributed, unmlq_distributed,
                      gels_lq_distributed)
from .eig_dist import (heev_distributed, hegv_distributed, svd_distributed,
                       norm_distributed, col_norms_distributed,
                       he2hb_distributed, ge2tb_distributed,
                       unmtr_he2hb_distributed, steqr_distributed,
                       heev_range_distributed, svd_range_distributed)
from .chase_dist import (hb2st_chase_distributed,
                         tb2bd_chase_distributed)
from .inverse import (trtri_distributed, trtrm_distributed, potri_distributed,
                      getri_distributed, gecondest_distributed,
                      pocondest_distributed, trcondest_distributed)
from .band_dist import (pbtrf_distributed, pbtrs_distributed, pbsv_distributed,
                        tbsm_distributed, gbtrf_distributed, gbtrs_distributed,
                        gbsv_distributed, dense_to_band_lower,
                        band_lower_to_dense, dense_to_band_general,
                        band_general_to_dense)
from .indefinite_dist import (hetrf_distributed, hetrs_distributed,
                              hesv_distributed, HermitianFactorsDist)
from .rbt import getrf_nopiv_distributed, gesv_rbt_distributed
from .pipeline import potrf_pipelined
from .batched import gesv_batched_distributed, posv_batched_distributed
