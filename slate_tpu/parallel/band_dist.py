"""Distributed band factorizations and solves over the process grid.

Reference analogues: ``src/pbtrf.cc:22-200`` (distributed band Cholesky:
per-block-column potrf + panel trsm + windowed herk over grid tiles),
``src/gbtrf.cc`` (distributed band LU, pivoting confined to the kl window),
``src/tbsm.cc`` (distributed banded triangular solve, with and without
pivot replay), ``src/pbtrs.cc`` / ``src/gbtrs.cc`` / ``src/pbsv.cc`` /
``src/gbsv.cc``.

TPU re-design (not a translation):

- **Compact band storage, sharded along n.**  The reference distributes the
  band's *tiles* over the 2-D grid; a band's natural TPU layout is the
  LAPACK-style compact form — ``Ab[j, i] = A[i+j, i]`` for the lower band —
  block-sharded along the column axis over the *flattened* mesh, so memory
  is O((kd+1)·n/P) per device (the single-device path's dense masked array
  would defeat the point of distributing a band).
- **Windows ride one psum.**  A band factorization's critical path is the
  sequential chain of diagonal windows (SURVEY §2.4 band row); per window the
  owning shards contribute their columns via one masked ``psum``, every
  device factors the small (w×w) window redundantly (cheaper than shipping
  factors around — w ≪ n), and writes back only its owned columns.  This is
  the replicated-panel trade the dense drivers use for their diagonal
  blocks, applied to the whole window.
- **Pivoting stays in-window** (gbtrf): partial pivoting of a band matrix
  cannot leave the kl window, so the per-window permutation is a *local*
  (wr,)-vector carried in a static (nt, wr) array — no global permutation
  machinery, exactly the locality the reference exploits.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.exceptions import slate_assert
from .distribute import ceil_mult
from .mesh import COL_AXIS, ProcessGrid, ROW_AXIS, shard_map
from ..obs import instrument

AX = (ROW_AXIS, COL_AXIS)


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def _band_lu_geometry(n: int, kl: int, ku: int, nb: int, nprocs: int):
    """Window/padding geometry shared by the band-LU factor AND its solves —
    one source of truth (round-3 review: gbtrs recomputed npad from the same
    formula and relied on a comment to keep them in lock-step).

    Returns (wr, wc, nd, npad): window rows/cols, factored-form storage
    depth, and the padded problem size."""
    klt = max(1, _ceil_div(kl, nb))
    kut = max(1, _ceil_div(ku, nb))
    wr = (klt + 1) * nb
    wc = (klt + kut + 1) * nb
    nd = wr + kl + ku
    unit = nb * nprocs
    npad = ceil_mult(max(n + wc, unit), unit)
    return wr, wc, nd, npad


def dense_to_band_lower(A: jax.Array, kd: int) -> jax.Array:
    """Compact lower band: Ab[j, i] = A[i+j, i], zero beyond the edge."""
    n = A.shape[-1]
    j = jnp.arange(kd + 1)[:, None]
    i = jnp.arange(n)[None, :]
    r = jnp.clip(i + j, 0, n - 1)
    vals = A[r, i]
    return jnp.where(i + j < n, vals, jnp.zeros_like(vals))


def band_lower_to_dense(Ab: jax.Array, n: int) -> jax.Array:
    """Inverse of dense_to_band_lower (for tests and write-back)."""
    kd = Ab.shape[0] - 1
    r = jnp.arange(n)[:, None]
    c = jnp.arange(n)[None, :]
    j = r - c
    ok = (j >= 0) & (j <= kd)
    return jnp.where(ok, Ab[jnp.clip(j, 0, kd), c], 0)


def _expand_window(win: jax.Array, w: int, kd: int) -> jax.Array:
    """Dense (w, w) lower-band window from compact (kd+1, w) columns."""
    r = jnp.arange(w)[:, None]
    c = jnp.arange(w)[None, :]
    j = r - c
    ok = (j >= 0) & (j <= kd)
    return jnp.where(ok, win[jnp.clip(j, 0, kd), c], 0)


def _compress_window(dense: jax.Array, win_old: jax.Array, w: int,
                     kd: int) -> jax.Array:
    """Compact (kd+1, w) from a dense (w, w) window; band entries whose row
    falls below the window (c + j >= w) are later windows' territory and
    keep their old values."""
    jj = jnp.arange(kd + 1)[:, None]
    cc = jnp.arange(w)[None, :]
    rr = jj + cc
    inside = rr < w
    vals = dense[jnp.clip(rr, 0, w - 1), cc]
    return jnp.where(inside, vals, win_old)


def _window_ops(gcol):
    """Masked-psum window extraction/write-back over the column-sharded
    compact storage — ONE implementation shared by every windowed sweep
    (factor, forward, backward), so the slot/sentinel logic cannot drift."""

    def extract_cols(X_loc, k0, width):
        """Replicated (rows, width) block of columns [k0, k0+width)."""
        inw = (gcol >= k0) & (gcol < k0 + width)
        slot = jnp.where(inw, gcol - k0, width)      # width = discard slot
        win = jnp.zeros((X_loc.shape[0], width + 1), X_loc.dtype)
        win = win.at[:, slot].set(jnp.where(inw[None, :], X_loc,
                                            jnp.zeros_like(X_loc)))
        return lax.psum(win[:, :width], AX)

    def extract_rows(B_loc, k0, width):
        """Replicated (width, nrhs) block of rows [k0, k0+width)."""
        inw = (gcol >= k0) & (gcol < k0 + width)
        slot = jnp.where(inw, gcol - k0, width)
        bw = jnp.zeros((width + 1,) + B_loc.shape[1:], B_loc.dtype)
        bw = bw.at[slot].set(jnp.where(inw[:, None], B_loc,
                                       jnp.zeros_like(B_loc)))
        return lax.psum(bw[:width], AX)

    def put_rows(B_loc, vals, k0, width):
        """Write my owned slice of rows [k0, k0+width) from replicated vals."""
        inw = (gcol >= k0) & (gcol < k0 + width)
        mine = vals[jnp.clip(gcol - k0, 0, width - 1)]
        return jnp.where(inw[:, None], mine, B_loc)

    def put_cols(X_loc, vals, k0, width):
        """Write my owned columns of [k0, k0+width) from replicated vals."""
        inw = (gcol >= k0) & (gcol < k0 + width)
        mine = vals[:, jnp.clip(gcol - k0, 0, width - 1)]
        return jnp.where(inw[None, :], mine, X_loc)

    return extract_cols, extract_rows, put_rows, put_cols



@lru_cache(maxsize=32)
def _pbtrf_dist_fn(mesh, npad: int, kd: int, nb: int, dtype_str: str):
    """Jitted shard_map windowed band Cholesky on compact storage."""
    nprocs = mesh.shape[ROW_AXIS] * mesh.shape[COL_AXIS]
    nc = npad // nprocs                     # local columns
    kdt = max(1, _ceil_div(kd, nb))
    w = (kdt + 1) * nb
    nt = npad // nb
    cplx = dtype_str.startswith("complex")

    def local_fn(Ab_loc):                   # (kd+1, nc)
        ri = lax.axis_index(AX)
        gcol = ri * nc + jnp.arange(nc, dtype=jnp.int32)
        extract_cols, _, _, put_cols = _window_ops(gcol)

        def body(k, Ab_loc):
            k0 = (k * nb).astype(jnp.int32) if hasattr(k, "astype") else k * nb
            win = extract_cols(Ab_loc, k0, w)
            dense = _expand_window(win, w, kd)
            dkk = dense[:nb, :nb]
            lkk = lax.linalg.cholesky(
                dkk + jnp.conj(jnp.swapaxes(jnp.tril(dkk, -1), -1, -2)),
                symmetrize_input=False)
            panel = lax.linalg.triangular_solve(
                lkk, dense[nb:, :nb], left_side=False, lower=True,
                conjugate_a=cplx, transpose_a=True)
            trail = dense[nb:, nb:] - jnp.matmul(
                panel, jnp.conj(jnp.swapaxes(panel, -1, -2)),
                precision=lax.Precision.HIGHEST)
            dense = dense.at[:nb, :nb].set(lkk)
            dense = dense.at[nb:, :nb].set(panel)
            dense = dense.at[nb:, nb:].set(jnp.tril(trail))
            win_new = _compress_window(dense, win, w, kd)
            return put_cols(Ab_loc, win_new, k0, w)

        return lax.fori_loop(0, nt, body, Ab_loc)

    spec = P(None, AX)
    fn = shard_map(local_fn, mesh=mesh, in_specs=spec, out_specs=spec,
                       check_vma=False)
    return jax.jit(fn)


@instrument
def pbtrf_distributed(Ab: jax.Array, grid: ProcessGrid, kd: int,
                      nb: int = 256):
    """Distributed band Cholesky on compact lower storage (src/pbtrf.cc).

    ``Ab`` is (kd+1, n) with ``Ab[j, i] = A[i+j, i]``.  Returns
    ``(Lb, info)`` in the same compact form.  Memory O((kd+1)·n/P) per
    device; one masked psum of (kd+1, w) per diagonal window.
    """
    slate_assert(Ab.ndim == 2 and Ab.shape[0] == kd + 1,
                 "pbtrf_distributed expects compact (kd+1, n) lower band")
    n = Ab.shape[1]
    nb = max(1, min(nb, n))
    nprocs = grid.p * grid.q
    unit = nb * nprocs
    kdt = max(1, _ceil_div(kd, nb))
    w = (kdt + 1) * nb
    npad = ceil_mult(max(n + w, unit), unit)   # room for the last window
    if npad > n:
        pad = jnp.zeros((kd + 1, npad - n), Ab.dtype)
        pad = pad.at[0, :].set(1)              # identity tail keeps windows SPD
        Abp = jnp.concatenate([Ab, pad], axis=1)
    else:
        Abp = Ab
    Abp = jax.device_put(Abp, jax.sharding.NamedSharding(
        grid.mesh, P(None, AX)))
    Lb = _pbtrf_dist_fn(grid.mesh, npad, kd, nb, str(Abp.dtype))(Abp)
    Lb = Lb[:, :n]
    diag = jnp.real(Lb[0])
    bad = ~(jnp.isfinite(diag) & (diag > 0))
    info = jnp.where(bad.any(), jnp.argmax(bad) + 1, 0).astype(jnp.int32)
    return Lb, info


@lru_cache(maxsize=32)
def _tbsm_dist_fn(mesh, npad: int, kd: int, nb: int, nrhs: int,
                  trans: bool, unit: bool, dtype_str: str):
    """Jitted windowed banded triangular solve: forward (L x = b) or
    backward (L^H x = b) block substitution; B block-row-sharded."""
    nprocs = mesh.shape[ROW_AXIS] * mesh.shape[COL_AXIS]
    nc = npad // nprocs
    kdt = max(1, _ceil_div(kd, nb))
    w = (kdt + 1) * nb
    nt = npad // nb
    cplx = dtype_str.startswith("complex")

    def local_fn(Ab_loc, B_loc):            # (kd+1, nc), (nc, nrhs)
        ri = lax.axis_index(AX)
        gcol = ri * nc + jnp.arange(nc, dtype=jnp.int32)
        extract_cols, extract_b, put_b, _ = _window_ops(gcol)

        def extract_band(k0):
            return extract_cols(Ab_loc, k0, w)

        if not trans:
            def body(k, B_loc):
                k0 = (k * nb).astype(jnp.int32) if hasattr(k, "astype") \
                    else k * nb
                win = extract_band(k0)
                dense = _expand_window(win, w, kd)
                bwin = extract_b(B_loc, k0, w)
                xk = lax.linalg.triangular_solve(
                    dense[:nb, :nb], bwin[:nb], left_side=True, lower=True,
                    unit_diagonal=unit)
                rest = bwin[nb:] - jnp.matmul(dense[nb:, :nb], xk,
                                              precision=lax.Precision.HIGHEST)
                bnew = jnp.concatenate([xk, rest], axis=0)
                return put_b(B_loc, bnew, k0, w)

            return lax.fori_loop(0, nt, body, B_loc)

        def body(t, B_loc):
            k = nt - 1 - t
            k0 = (k * nb).astype(jnp.int32) if hasattr(k, "astype") else k * nb
            win = extract_band(k0)
            dense = _expand_window(win, w, kd)
            bwin = extract_b(B_loc, k0, w)      # rows [k0, k0+w): x below known
            rhs = bwin[:nb] - jnp.matmul(
                jnp.conj(jnp.swapaxes(dense[nb:, :nb], -1, -2)) if cplx
                else jnp.swapaxes(dense[nb:, :nb], -1, -2),
                bwin[nb:], precision=lax.Precision.HIGHEST)
            xk = lax.linalg.triangular_solve(
                dense[:nb, :nb], rhs, left_side=True, lower=True,
                unit_diagonal=unit, transpose_a=True, conjugate_a=cplx)
            return put_b(B_loc, xk, k0, nb)

        return lax.fori_loop(0, nt, body, B_loc)

    fn = shard_map(local_fn, mesh=mesh,
                       in_specs=(P(None, AX), P(AX, None)),
                       out_specs=P(AX, None), check_vma=False)
    return jax.jit(fn)


@instrument
def tbsm_distributed(Lb: jax.Array, B: jax.Array, grid: ProcessGrid, kd: int,
                     nb: int = 256, trans: bool = False,
                     unit_diagonal: bool = False) -> jax.Array:
    """Distributed banded triangular solve (src/tbsm.cc): L x = b, or
    L^H x = b with ``trans=True``, on compact lower band storage."""
    slate_assert(Lb.ndim == 2 and Lb.shape[0] == kd + 1,
                 "tbsm_distributed expects compact (kd+1, n) lower band")
    n = Lb.shape[1]
    vec = B.ndim == 1
    B2 = B[:, None] if vec else B
    nrhs = B2.shape[1]
    nb = max(1, min(nb, n))
    nprocs = grid.p * grid.q
    unit = nb * nprocs
    kdt = max(1, _ceil_div(kd, nb))
    w = (kdt + 1) * nb
    npad = ceil_mult(max(n + w, unit), unit)
    if npad > n:
        pad = jnp.zeros((kd + 1, npad - n), Lb.dtype)
        pad = pad.at[0, :].set(1)
        Lbp = jnp.concatenate([Lb, pad], axis=1)
        B2p = jnp.pad(B2, ((0, npad - n), (0, 0)))
    else:
        Lbp, B2p = Lb, B2
    Lbp = jax.device_put(Lbp, jax.sharding.NamedSharding(
        grid.mesh, P(None, AX)))
    B2p = jax.device_put(B2p, jax.sharding.NamedSharding(
        grid.mesh, P(AX, None)))
    X = _tbsm_dist_fn(grid.mesh, npad, kd, nb, nrhs, bool(trans),
                      bool(unit_diagonal), str(Lbp.dtype))(Lbp, B2p)
    X = X[:n]
    return X[:, 0] if vec else X


@instrument
def pbtrs_distributed(Lb: jax.Array, B: jax.Array, grid: ProcessGrid, kd: int,
                      nb: int = 256) -> jax.Array:
    """Solve L L^H X = B from the distributed band factor (src/pbtrs.cc)."""
    Y = tbsm_distributed(Lb, B, grid, kd, nb=nb, trans=False)
    return tbsm_distributed(Lb, Y, grid, kd, nb=nb, trans=True)


@instrument
def pbsv_distributed(Ab: jax.Array, B: jax.Array, grid: ProcessGrid, kd: int,
                     nb: int = 256):
    """Distributed SPD band solve (src/pbsv.cc = pbtrf + pbtrs)."""
    Lb, info = pbtrf_distributed(Ab, grid, kd, nb=nb)
    return pbtrs_distributed(Lb, B, grid, kd, nb=nb), info


# ---------------------------------------------------------------------------
# band LU (gbtrf / gbtrs / gbsv)
# ---------------------------------------------------------------------------


class BandLUDist(NamedTuple):
    """Distributed band LU factored form: compact factored storage (row j =
    diagonal j - kl - ku; depth wr-1 below the diagonal for the dense-form
    window multipliers), plus per-window permutations — the window-local
    Pivots analogue.  ``npad`` records the padded problem size the factor
    ran at, so the solves replay the exact same window schedule."""
    lub: jax.Array       # (wr + kl + ku, n) compact factored form
    perms: jax.Array     # (nt, wr) window permutations
    kl: int
    ku: int
    nb: int
    npad: int


def dense_to_band_general(A: jax.Array, kl: int, ku: int,
                          extra: int = 0) -> jax.Array:
    """Compact general band with ``extra`` superdiagonal fill rows:
    row j holds diagonal (j - ku - extra): Gb[j, i] = A[i + j - ku - extra, i].
    """
    n = A.shape[-1]
    nd = kl + ku + extra + 1
    j = jnp.arange(nd)[:, None]
    i = jnp.arange(n)[None, :]
    r = i + j - ku - extra
    ok = (r >= 0) & (r < n)
    return jnp.where(ok, A[jnp.clip(r, 0, n - 1), i], 0)


def band_general_to_dense(Gb: jax.Array, n: int, kl: int, ku: int,
                          extra: int = 0) -> jax.Array:
    nd = Gb.shape[0]
    assert nd == kl + ku + extra + 1
    r = jnp.arange(n)[:, None]
    c = jnp.arange(n)[None, :]
    j = r - c + ku + extra
    ok = (j >= 0) & (j < nd)
    return jnp.where(ok, Gb[jnp.clip(j, 0, nd - 1), c], 0)


def _expand_general(win: jax.Array, wr: int, wc: int,
                    fill: int) -> jax.Array:
    """Dense (wr, wc) window from compact columns: row r, col c maps to
    diagonal j = r - c + fill (fill = ku + extra offset of the storage)."""
    nd = win.shape[0]
    r = jnp.arange(wr)[:, None]
    c = jnp.arange(wc)[None, :]
    j = r - c + fill
    ok = (j >= 0) & (j < nd)
    return jnp.where(ok, win[jnp.clip(j, 0, nd - 1), c], 0)


def _compress_general(dense: jax.Array, win_old: jax.Array, wr: int, wc: int,
                      fill: int) -> jax.Array:
    nd = win_old.shape[0]
    jj = jnp.arange(nd)[:, None]
    cc = jnp.arange(wc)[None, :]
    rr = jj + cc - fill
    inside = (rr >= 0) & (rr < wr)
    vals = dense[jnp.clip(rr, 0, wr - 1), cc]
    return jnp.where(inside, vals, win_old)


@lru_cache(maxsize=32)
def _gbtrf_dist_fn(mesh, npad: int, kl: int, ku: int, nb: int,
                   dtype_str: str):
    """Windowed band LU with in-window partial pivoting on compact storage
    (src/gbtrf.cc): per block column one window LU + row trsm + trailing
    gemm; the permutation never leaves the kl window."""
    nprocs = mesh.shape[ROW_AXIS] * mesh.shape[COL_AXIS]
    nc = npad // nprocs
    klt = max(1, _ceil_div(kl, nb))
    kut = max(1, _ceil_div(ku, nb))
    wr = (klt + 1) * nb
    wc = (klt + kut + 1) * nb
    fill = ku + kl                      # storage offset of the diagonal
    # the window LU returns the panel in fully-swapped dense form, so L
    # multipliers can land up to wr-1 rows below their column (not kl: the
    # in-window permutation scrambles the band adjacency).  The factored
    # storage therefore carries wr-1 subdiagonals — the price of batching a
    # whole window's pivoting into one fused LU instead of the reference's
    # column-at-a-time product form.
    nd = wr + kl + ku
    nt = npad // nb

    def local_fn(Gb_loc):               # (nd, nc)
        ri = lax.axis_index(AX)
        gcol = ri * nc + jnp.arange(nc, dtype=jnp.int32)
        extract_cols, _, _, put_cols = _window_ops(gcol)

        def body(k, carry):
            Gb_loc, perms = carry
            k0 = (k * nb).astype(jnp.int32) if hasattr(k, "astype") else k * nb
            win = extract_cols(Gb_loc, k0, wc)
            # dense window rows [k0, k0+wr), cols [k0, k0+wc): row r of the
            # window is diagonal (r - c) => storage row r - c + fill
            dense = _expand_general(win, wr, wc, fill)
            plu, _, pperm = lax.linalg.lu(dense[:, :nb])
            L11 = jnp.tril(plu[:nb], -1) + jnp.eye(nb, dtype=dense.dtype)
            dense = jnp.take(dense, pperm, axis=0)
            dense = dense.at[:, :nb].set(plu)
            rest = lax.linalg.triangular_solve(
                L11, dense[:nb, nb:], left_side=True, lower=True,
                unit_diagonal=True)
            dense = dense.at[:nb, nb:].set(rest)
            trail = dense[nb:, nb:] - jnp.matmul(
                plu[nb:, :nb], rest, precision=lax.Precision.HIGHEST)
            dense = dense.at[nb:, nb:].set(trail)
            win_new = _compress_general(dense, win, wr, wc, fill)
            Gb_loc = put_cols(Gb_loc, win_new, k0, wc)
            perms = perms.at[k].set(pperm)
            return Gb_loc, perms

        perms0 = jnp.zeros((nt, wr), jnp.int32)
        Gb_loc, perms = lax.fori_loop(0, nt, body, (Gb_loc, perms0))
        return Gb_loc, perms

    fn = shard_map(local_fn, mesh=mesh, in_specs=P(None, AX),
                       out_specs=(P(None, AX), P(None, None)),
                       check_vma=False)
    return jax.jit(fn)


@instrument
def gbtrf_distributed(Gb: jax.Array, grid: ProcessGrid, kl: int, ku: int,
                      nb: int = 256):
    """Distributed band LU (src/gbtrf.cc) on compact storage with kl fill
    rows: input (2kl+ku+1, n) where row j holds diagonal j - kl - ku (the
    LAPACK gb layout; build it with ``dense_to_band_general(A, kl, ku,
    extra=kl)``).  Returns ``(BandLUDist, info)``."""
    nd_in = 2 * kl + ku + 1
    slate_assert(Gb.ndim == 2 and Gb.shape[0] == nd_in,
                 "gbtrf_distributed expects compact (2kl+ku+1, n) storage")
    n = Gb.shape[1]
    nb = max(1, min(nb, n))
    nprocs = grid.p * grid.q
    wr, wc, nd, npad = _band_lu_geometry(n, kl, ku, nb, nprocs)
    Gb = jnp.concatenate(
        [Gb, jnp.zeros((nd - nd_in, n), Gb.dtype)], axis=0)
    if npad > n:
        pad = jnp.zeros((nd, npad - n), Gb.dtype)
        pad = pad.at[kl + ku, :].set(1)      # unit diagonal tail
        Gbp = jnp.concatenate([Gb, pad], axis=1)
    else:
        Gbp = Gb
    Gbp = jax.device_put(Gbp, jax.sharding.NamedSharding(
        grid.mesh, P(None, AX)))
    lub, perms = _gbtrf_dist_fn(grid.mesh, npad, kl, ku, nb,
                                str(Gbp.dtype))(Gbp)
    lub = lub[:, :n]
    diag = lub[kl + ku]
    bad = ~jnp.isfinite(diag) | (diag == 0)
    info = jnp.where(bad.any(), jnp.argmax(bad) + 1, 0).astype(jnp.int32)
    return BandLUDist(lub, perms, kl, ku, nb, npad), info


@lru_cache(maxsize=32)
def _gbtrs_fwd_dist_fn(mesh, npad: int, kl: int, ku: int, nb: int, nrhs: int,
                       dtype_str: str):
    """Forward sweep with interleaved window pivoting (tbsm with Pivots,
    src/tbsm.cc): per window apply the stored permutation to the RHS rows,
    eliminate with the unit-lower window panel."""
    nprocs = mesh.shape[ROW_AXIS] * mesh.shape[COL_AXIS]
    nc = npad // nprocs
    klt = max(1, _ceil_div(kl, nb))
    wr = (klt + 1) * nb
    fill = ku + kl
    nt = npad // nb

    def local_fn(Gb_loc, perms, B_loc):
        ri = lax.axis_index(AX)
        gcol = ri * nc + jnp.arange(nc, dtype=jnp.int32)
        extract_cols, extract_b, put_b, _ = _window_ops(gcol)

        def body(k, B_loc):
            k0 = (k * nb).astype(jnp.int32) if hasattr(k, "astype") else k * nb
            win = extract_cols(Gb_loc, k0, nb)        # panel cols only
            Lpan = _expand_general(win, wr, nb, fill)
            bwin = extract_b(B_loc, k0, wr)
            bwin = jnp.take(bwin, perms[k], axis=0)   # window pivot replay
            xk = lax.linalg.triangular_solve(
                jnp.tril(Lpan[:nb], -1) + jnp.eye(nb, dtype=Lpan.dtype),
                bwin[:nb], left_side=True, lower=True, unit_diagonal=True)
            rest = bwin[nb:] - jnp.matmul(Lpan[nb:, :nb], xk,
                                          precision=lax.Precision.HIGHEST)
            return put_b(B_loc, jnp.concatenate([xk, rest], axis=0), k0, wr)

        return lax.fori_loop(0, nt, body, B_loc)

    fn = shard_map(local_fn, mesh=mesh,
                       in_specs=(P(None, AX), P(None, None), P(AX, None)),
                       out_specs=P(AX, None), check_vma=False)
    return jax.jit(fn)


@lru_cache(maxsize=32)
def _gbtrs_bwd_dist_fn(mesh, npad: int, kl: int, ku: int, nb: int, nrhs: int,
                       dtype_str: str):
    """Backward sweep: U X = Y where U is upper-banded with bandwidth kl+ku
    (fill-in), windowed block substitution from the bottom."""
    nprocs = mesh.shape[ROW_AXIS] * mesh.shape[COL_AXIS]
    nc = npad // nprocs
    klt = max(1, _ceil_div(kl, nb))
    kut = max(1, _ceil_div(ku, nb))
    wc = (klt + kut + 1) * nb
    fill = ku + kl
    nt = npad // nb

    def local_fn(Gb_loc, B_loc):
        ri = lax.axis_index(AX)
        gcol = ri * nc + jnp.arange(nc, dtype=jnp.int32)
        extract_cols, extract_b, put_b, _ = _window_ops(gcol)

        def body(t, B_loc):
            k = nt - 1 - t
            k0 = (k * nb).astype(jnp.int32) if hasattr(k, "astype") else k * nb
            win = extract_cols(Gb_loc, k0, wc)
            # dense rows [k0, k0+nb) of U across the window columns
            Urows = _expand_general(win, nb, wc, fill)
            bwin = extract_b(B_loc, k0, wc)       # x beyond k0+nb already solved
            rhs = bwin[:nb] - jnp.matmul(Urows[:, nb:], bwin[nb:],
                                         precision=lax.Precision.HIGHEST)
            xk = lax.linalg.triangular_solve(Urows[:nb, :nb], rhs,
                                             left_side=True, lower=False)
            return put_b(B_loc, xk, k0, nb)

        return lax.fori_loop(0, nt, body, B_loc)

    fn = shard_map(local_fn, mesh=mesh,
                       in_specs=(P(None, AX), P(AX, None)),
                       out_specs=P(AX, None), check_vma=False)
    return jax.jit(fn)


@instrument
def gbtrs_distributed(fac: BandLUDist, B: jax.Array,
                      grid: ProcessGrid) -> jax.Array:
    """Solve from the distributed band LU (src/gbtrs.cc): pivoted forward
    sweep + banded backward sweep, both windowed over the mesh."""
    lub, perms, kl, ku, nb, npad = fac
    n = lub.shape[1]
    vec = B.ndim == 1
    B2 = B[:, None] if vec else B
    nrhs = B2.shape[1]
    nprocs = grid.p * grid.q
    wr, wc, nd, npad_geom = _band_lu_geometry(n, kl, ku, nb, nprocs)
    slate_assert(npad == npad_geom,
                 "band LU factor was built on a different grid size; "
                 "re-factor on this grid")
    if npad > n:
        pad = jnp.zeros((nd, npad - n), lub.dtype)
        pad = pad.at[kl + ku, :].set(1)
        lubp = jnp.concatenate([lub, pad], axis=1)
        B2p = jnp.pad(B2, ((0, npad - n), (0, 0)))
    else:
        lubp, B2p = lub, B2
    # gbtrf computed npad from the same (n, kl, ku, nb), so perms already
    # covers every window including the padded tail
    sh = jax.sharding.NamedSharding(grid.mesh, P(None, AX))
    lubp = jax.device_put(lubp, sh)
    B2p = jax.device_put(B2p, jax.sharding.NamedSharding(
        grid.mesh, P(AX, None)))
    Y = _gbtrs_fwd_dist_fn(grid.mesh, npad, kl, ku, nb, nrhs,
                           str(lubp.dtype))(lubp, perms, B2p)
    X = _gbtrs_bwd_dist_fn(grid.mesh, npad, kl, ku, nb, nrhs,
                           str(lubp.dtype))(lubp, Y)
    X = X[:n]
    return X[:, 0] if vec else X


@instrument
def gbsv_distributed(Gb: jax.Array, B: jax.Array, grid: ProcessGrid, kl: int,
                     ku: int, nb: int = 256):
    """Distributed general band solve (src/gbsv.cc = gbtrf + gbtrs)."""
    fac, info = gbtrf_distributed(Gb, grid, kl, ku, nb=nb)
    return gbtrs_distributed(fac, B, grid), info
