"""Batch-parallel solves: the leading batch axis sharded over the mesh.

Reference analogue: SLATE's batch-BLAS tier (PAPER.md L1) distributes
*independent* problems, not tiles of one problem — on TPU that means the
batch axis is the natural mesh axis.  Each device vmap-solves its local
shard of the stack with the same pure cores the serving layer compiles
(:func:`slate_tpu.linalg.gesv_core`), and the program contains **zero
collectives**: the batch tier is embarrassingly parallel, which is exactly
what the SCALING.md audit row for this module documents (collective bytes
= 0 at every P — the one distributed routine whose communication budget is
identically nothing).

The serving queue stays single-device (its buckets are small); this entry
is for bulk offline batches — thousands of same-bucket solves in one
sharded call (``slate_tpu.serve`` handles the mixed-traffic front end).
"""

from __future__ import annotations

import jax
from jax.sharding import PartitionSpec

from ..core.exceptions import slate_assert
from ..linalg.chol import posv_core
from ..linalg.lu import gesv_core
from ..obs import instrument
from .mesh import COL_AXIS, ROW_AXIS, ProcessGrid, shard_map


def _batch_sharded(core, grid: ProcessGrid, a, b, n_out: int):
    """shard_map the vmapped core over the batch axis (both mesh axes
    flattened — P = p*q shards, no collectives)."""
    P = grid.p * grid.q
    slate_assert(a.ndim == 3 and b.ndim == 3,
                 f"batched distributed solve needs (batch, m, n) operands, "
                 f"got {a.shape} / {b.shape}")
    slate_assert(a.shape[0] % P == 0,
                 f"batch {a.shape[0]} must divide the grid size {P} evenly "
                 f"(pad the batch to a multiple — serve.BucketPolicy's "
                 f"batch rounding does)")
    spec = PartitionSpec((ROW_AXIS, COL_AXIS))
    fn = shard_map(lambda al, bl: jax.vmap(core)(al, bl),
                   mesh=grid.mesh,
                   in_specs=(spec, spec),
                   out_specs=tuple([spec] * n_out),
                   check_vma=False)
    return jax.jit(fn)(a, b)


@instrument
def gesv_batched_distributed(a, b, grid: ProcessGrid):
    """Batched gesv with the batch axis sharded over the grid's devices.

    ``a`` (batch, n, n), ``b`` (batch, n, nrhs); batch must be a multiple of
    ``grid.p * grid.q``.  Returns ``(x, perm, info)`` with per-request perm
    and info, exactly like :func:`slate_tpu.serve.gesv_batched` (which
    handles the escalation ladder; this entry is the raw sharded kernel)."""
    return _batch_sharded(gesv_core, grid, a, b, 3)


@instrument
def posv_batched_distributed(a, b, grid: ProcessGrid):
    """Batched SPD solve with the batch axis sharded over the grid (full
    Hermitian operands).  Returns ``(x, info)`` per request."""
    return _batch_sharded(posv_core, grid, a, b, 2)
