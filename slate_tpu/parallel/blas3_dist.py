"""Distributed symmetric/Hermitian/triangular BLAS-3 over the process grid.

Reference analogues (SURVEY.md §2.2, §2.4): the distributed BLAS-3 drivers
``src/herk.cc`` / ``src/her2k.cc`` / ``src/syrk.cc`` / ``src/syr2k.cc`` (rank-k
updates of one stored triangle), ``src/hemm*.cc`` / ``src/symm.cc`` (symmetric
multiply), and ``src/trmm.cc`` (triangular multiply), each a task DAG of panel
broadcasts + batched tile gemms.

TPU re-design, two shapes:

* **Rank-k updates** (herk/her2k/syrk/syr2k) are written with *explicit*
  collectives inside ``shard_map``: the k-panel is all-gathered along both mesh
  axes — the reference's ``listBcastMT`` of the panel to its row *and* column
  owners (potrf.cc:122-132) collapsed into two ICI all-gathers — and every
  device then updates its local C block with one dense MXU matmul.  The
  triangle is enforced with an index mask on the local block (global row/col
  indices reconstructed from the mesh coordinates), so the untouched triangle
  passes through exactly as the reference's one-triangle update does.

* **hemm/symm/trmm** reconstruct the implied full operand from the stored
  triangle under ``jit`` with sharded operands (a masked add + transpose, which
  GSPMD turns into the mesh all-to-all) and run one sharded matmul — the
  structure lives in masks, the FLOPs stay on the MXU (SURVEY.md §2.5 mapping).

All entry points accept ragged shapes: operands are zero-padded to
grid-divisible sizes (zero rows/cols leave every product unchanged) and the
result is sliced back.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.exceptions import slate_assert
from .distribute import lcm, pad2d
from .mesh import COL_AXIS, ProcessGrid, ROW_AXIS, shard_map
from ..obs import instrument

_PREC = lax.Precision.HIGHEST


def _tri_mask(n_loc_p, n_loc_q, lower: bool, strict: bool = False):
    """Local-block mask of the stored triangle, from global indices."""
    i = lax.axis_index(ROW_AXIS)
    j = lax.axis_index(COL_AXIS)
    rows = i * n_loc_p + jnp.arange(n_loc_p)[:, None]
    cols = j * n_loc_q + jnp.arange(n_loc_q)[None, :]
    if lower:
        return rows > cols if strict else rows >= cols
    return rows < cols if strict else rows <= cols


def _col_block(a_row, n, q):
    """From the row-gathered panel (n/p, k), produce this device's *column*
    block (n/q, k): gather the rest of the rows along p, slice at the q
    coordinate.  Two all-gathers total = the reference's panel bcast to row and
    column owners."""
    a_all = lax.all_gather(a_row, ROW_AXIS, axis=0, tiled=True)  # (n, k)
    j = lax.axis_index(COL_AXIS)
    return lax.dynamic_slice_in_dim(a_all, j * (n // q), n // q, axis=0)


@lru_cache(maxsize=8)
def _axpby_fn(mesh):
    spec = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))

    def fn(alpha, x, beta, y):
        return lax.with_sharding_constraint(alpha * x + beta * y, spec)

    return jax.jit(fn, in_shardings=(None, spec, None, spec),
                   out_shardings=spec)


@lru_cache(maxsize=64)
def _rank_k_fn(mesh, n: int, lower: bool, herm: bool, two: bool):
    p = mesh.shape[ROW_AXIS]
    q = mesh.shape[COL_AXIS]

    def ct(x):
        return jnp.conj(x.T) if herm else x.T

    def local(a, b, c, alpha, beta):
        a_row = lax.all_gather(a, COL_AXIS, axis=1, tiled=True)   # (n/p, k)
        b_row = lax.all_gather(b, COL_AXIS, axis=1, tiled=True)
        b_col = _col_block(b_row, n, q)                            # (n/q, k)
        upd = jnp.matmul(a_row, ct(b_col), precision=_PREC)
        if two:
            a_col = _col_block(a_row, n, q)
            alpha2 = jnp.conj(alpha) if herm else alpha
            upd = alpha * upd + alpha2 * jnp.matmul(
                b_row, ct(a_col), precision=_PREC)
        else:
            upd = alpha * upd
        if herm and jnp.issubdtype(c.dtype, jnp.complexfloating):
            # her*k semantics: the Hermitian diagonal is real — drop any
            # imaginary part of C's diagonal before beta scales it (the
            # reference's herk does the same on the diagonal tiles)
            i = lax.axis_index(ROW_AXIS)
            j = lax.axis_index(COL_AXIS)
            rows = i * (n // p) + jnp.arange(n // p)[:, None]
            cols = j * (n // q) + jnp.arange(n // q)[None, :]
            c = jnp.where(rows == cols, c.real.astype(c.dtype), c)
        mask = _tri_mask(n // p, n // q, lower)
        return jnp.where(mask, upd + beta * c, c)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(ROW_AXIS, COL_AXIS), P(ROW_AXIS, COL_AXIS),
                  P(ROW_AXIS, COL_AXIS), P(), P()),
        out_specs=P(ROW_AXIS, COL_AXIS))
    return jax.jit(fn)


def _run_rank_k(alpha, A, B, beta, C, grid, lower, herm, two):
    n, k = A.shape[-2:]
    slate_assert(B.shape == A.shape, "rank-k operands must have equal shapes")
    slate_assert(C.shape[-2:] == (n, n), f"C must be {n}x{n}")
    unit = lcm(grid.p, grid.q)
    Ap = pad2d(A, unit, grid.q)
    Bp = Ap if B is A else pad2d(B, unit, grid.q)
    Cp = pad2d(C, unit, unit)
    npad = Cp.shape[-1]
    spec = grid.spec()
    Ap = jax.device_put(Ap, spec)
    Bp = Ap if B is A else jax.device_put(Bp, spec)
    Cp = jax.device_put(Cp, spec)
    dt = Cp.dtype
    out = _rank_k_fn(grid.mesh, npad, lower, herm, two)(
        Ap, Bp, Cp, jnp.asarray(alpha, dt), jnp.asarray(beta, dt))
    return out[:n, :n] if npad != n else out


@instrument
def herk_distributed(alpha, A, beta, C, grid: ProcessGrid,
                     uplo: str = "lower") -> jax.Array:
    """C_uplo = alpha A A^H + beta C_uplo, C sharded (p, q) (src/herk.cc).
    The opposite triangle of C passes through untouched."""
    return _run_rank_k(alpha, A, A, beta, C, grid, uplo == "lower",
                       herm=True, two=False)


@instrument
def syrk_distributed(alpha, A, beta, C, grid: ProcessGrid,
                     uplo: str = "lower") -> jax.Array:
    """C_uplo = alpha A A^T + beta C_uplo (src/syrk.cc)."""
    return _run_rank_k(alpha, A, A, beta, C, grid, uplo == "lower",
                       herm=False, two=False)


@instrument
def her2k_distributed(alpha, A, B, beta, C, grid: ProcessGrid,
                      uplo: str = "lower") -> jax.Array:
    """C_uplo = alpha A B^H + conj(alpha) B A^H + beta C_uplo (src/her2k.cc)."""
    return _run_rank_k(alpha, A, B, beta, C, grid, uplo == "lower",
                       herm=True, two=True)


@instrument
def syr2k_distributed(alpha, A, B, beta, C, grid: ProcessGrid,
                      uplo: str = "lower") -> jax.Array:
    """C_uplo = alpha (A B^T + B A^T) + beta C_uplo (src/syr2k.cc)."""
    return _run_rank_k(alpha, A, B, beta, C, grid, uplo == "lower",
                       herm=False, two=True)


# ---------------------------------------------------------------------------
# hemm / symm / trmm — masked sharded matmuls
# ---------------------------------------------------------------------------


@lru_cache(maxsize=64)
def _hemm_fn(mesh, left: bool, lower: bool, herm: bool):
    spec = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))

    def fn(a, b, c, alpha, beta):
        from ..core.matrix import tri_to_full

        full = tri_to_full(a, lower, herm)
        prod = (jnp.matmul(full, b, precision=_PREC) if left
                else jnp.matmul(b, full, precision=_PREC))
        out = alpha * prod + beta * c
        return lax.with_sharding_constraint(out, spec)

    return jax.jit(fn, in_shardings=(spec, spec, spec, None, None),
                   out_shardings=spec)


@instrument
def hemm_distributed(side, alpha, A, B, beta, C, grid: ProcessGrid,
                     uplo: str = "lower", herm: bool = True) -> jax.Array:
    """C = alpha A B + beta C (side=left) or alpha B A + beta C (side=right),
    with A Hermitian/symmetric stored in one triangle (src/hemm.cc, src/symm.cc)."""
    left = str(side).lower().startswith("l")
    slate_assert(A.shape[-1] == A.shape[-2], "hemm operand A must be square")
    slate_assert(A.shape[-1] == (C.shape[-2] if left else C.shape[-1]),
                 f"side={side!r} needs A of order "
                 f"{C.shape[-2] if left else C.shape[-1]}, got {A.shape[-1]}")
    m, n = C.shape[-2:]
    unit = lcm(grid.p, grid.q)
    Ap = pad2d(A, unit, unit)
    Bp = pad2d(B, unit, unit)
    Cp = pad2d(C, unit, unit)
    spec = grid.spec()
    Ap, Bp, Cp = (jax.device_put(x, spec) for x in (Ap, Bp, Cp))
    dt = Cp.dtype
    out = _hemm_fn(grid.mesh, left, uplo == "lower", herm)(
        Ap, Bp, Cp, jnp.asarray(alpha, dt), jnp.asarray(beta, dt))
    return out[:m, :n] if out.shape[-2:] != (m, n) else out


@instrument
def symm_distributed(side, alpha, A, B, beta, C, grid: ProcessGrid,
                     uplo: str = "lower") -> jax.Array:
    return hemm_distributed(side, alpha, A, B, beta, C, grid, uplo, herm=False)


@lru_cache(maxsize=64)
def _trmm_fn(mesh, left: bool, lower: bool, trans: bool, unit_diag: bool):
    spec = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))

    def fn(a, b, alpha):
        tri = jnp.tril(a) if lower else jnp.triu(a)
        if unit_diag:
            idx = jnp.arange(a.shape[0])
            tri = tri.at[idx, idx].set(1)
        if trans:
            tri = jnp.conj(tri.T)
        prod = (jnp.matmul(tri, b, precision=_PREC) if left
                else jnp.matmul(b, tri, precision=_PREC))
        return lax.with_sharding_constraint(alpha * prod, spec)

    return jax.jit(fn, in_shardings=(spec, spec, None), out_shardings=spec)


@instrument
def gbmm_distributed(alpha, A, B, beta, C, grid: ProcessGrid,
                     kl: int, ku: int) -> jax.Array:
    """C = alpha A B + beta C with A a general band matrix (src/gbmm.cc over
    the grid).  The band structure is a mask — zeros outside the band keep
    every shard's matmul dense on the MXU (SURVEY.md §2.5 mapping) — and the
    product rides the SUMMA all-gather gemm."""
    from ..linalg.band import _band_mask
    from .summa import gemm_allgather

    m, k = A.shape[-2:]
    n = B.shape[-1]
    slate_assert(B.shape[-2] == k, f"gbmm inner dims {k} != {B.shape[-2]}")
    slate_assert(C.shape[-2:] == (m, n), f"gbmm C must be {m}x{n}")
    Am = A * _band_mask(m, k, kl, ku, A.dtype)
    kmult = lcm(grid.p, grid.q)
    Ap = pad2d(Am, grid.p, kmult)
    Bp = pad2d(B, kmult, grid.q)
    prod = gemm_allgather(Ap, Bp, grid)          # sharded, padded (mp, np)
    # fold the axpy into a sharded program so the result keeps the grid
    # sharding like every other *_distributed entry point (C is padded to the
    # product's shape and placed on the grid first)
    Cp = jax.device_put(
        jnp.pad(C, ((0, prod.shape[-2] - m), (0, prod.shape[-1] - n))),
        grid.spec())
    dt = Cp.dtype
    out = _axpby_fn(grid.mesh)(jnp.asarray(alpha, dt), prod,
                               jnp.asarray(beta, dt), Cp)
    return out[:m, :n] if out.shape[-2:] != (m, n) else out


@instrument
def hbmm_distributed(alpha, A, B, beta, C, grid: ProcessGrid,
                     kd: int, uplo: str = "lower",
                     side: str = "left") -> jax.Array:
    """C = alpha A B + beta C (side=left) or alpha B A + beta C (side=right)
    with A Hermitian band, one triangle stored (src/hbmm.cc over the grid;
    the reference's Side parameter, slate.hh:215)."""
    from ..linalg.band import _band_mask

    n = A.shape[-1]
    lower = uplo == "lower"
    tri = A * _band_mask(n, n, kd if lower else 0, 0 if lower else kd, A.dtype)
    # the hemm kernel reconstructs the full Hermitian operand from the stored
    # (band-masked) triangle in-trace
    return hemm_distributed(side, alpha, tri, B, beta, C, grid, uplo=uplo)


@instrument
def trmm_distributed(side, alpha, A, B, grid: ProcessGrid,
                     uplo: str = "lower", conj_trans: bool = False,
                     unit_diag: bool = False) -> jax.Array:
    """B = alpha op(A) B (side=left) or alpha B op(A) (side=right) with A
    triangular (src/trmm.cc).  Zero-padding keeps the padded triangle inert."""
    left = str(side).lower().startswith("l")
    m, n = B.shape[-2:]
    unit = lcm(grid.p, grid.q)
    Ap = pad2d(A, unit, unit)
    Bp = pad2d(B, unit, unit)
    spec = grid.spec()
    Ap = jax.device_put(Ap, spec)
    Bp = jax.device_put(Bp, spec)
    out = _trmm_fn(grid.mesh, left, uplo == "lower", conj_trans, unit_diag)(
        Ap, Bp, jnp.asarray(alpha, Bp.dtype))
    return out[:m, :n] if out.shape[-2:] != (m, n) else out
