"""Distributed bulge chase: the hb2st pipelined schedule sharded over a mesh.

The reference confines stage 2 to rank 0 (src/hb2st.cc scheduling consumed on
one process; src/heev.cc:137-160 gathers the band there), and rounds 1-4 of
this repo mirrored that: ``heev_distributed`` replicated the band and every
device replayed the same chase.  This module goes past the reference: the
band's column range is partitioned into P contiguous segments, each device
runs only the chase fronts whose window anchor falls in its segment, and
neighbors reconcile through two tiny ``ppermute`` exchanges per round:

- a (2b+1)x(2b+1) boundary-square DELTA in each direction.  Concurrent
  fronts write element-disjoint footprints (the schedule spaces live fronts
  2b-1 apart - the same commutativity the reference's thread scheduler and
  our batched single-device rounds rely on), so neighbor copies of the
  overlap reconcile by pure addition;
- at most one CROSSING reflector (v, tau, s): a front advances b columns
  per round and fronts are 2b-1 apart, so per boundary per round at most
  one front hops segments, carrying its v_prev to the next owner.

Collective volume is O(b^2 + b) per round - independent of n - versus the
O(n * b) band replication the rank-0 design ships once.  Per-device window
work drops from the full front set (~n/2b batched windows per round) to
~n/(2bP).

Schedule (identical to linalg/eig.py:_hb2st_chase_pipelined): sweep s runs
hebr1 at round t=2s and its hebr2/hebr3 step r (window anchor
j = (t-2s)b+1+s, i = j+b) at round t = 2s+r-1; front ownership is by the
anchor column j.  hebr1 ownership is by the sweep's r=1 anchor j = s+1, so
the hebr1 -> first-hebr2 handoff (same round, shared v0) never crosses a
boundary; the window's one-column reach below s+1 is why tiles carry a
single extra left column.

Results match the single-device pipelined chase bit-for-bit in the same
XLA configuration: same windows, same reflectors, same order per front
(pinned by tests/test_chase_dist.py against _hb2st_chase_pipelined).

The two kernels here (hb2st and tb2bd) share the segmentation idea but are
kept as separate builders on purpose: they differ in left margin (1 vs
b+1), exchange-square anchor (boundary-1 vs boundary-b-1), mirror writes
(Hermitian only), carried reflector family (v vs u), and per-window math —
a parameterized common scaffold was tried and read worse than the ~80
shared lines it saved.  Both are pinned output-for-output against their
single-device schedules, which is what keeps the pair honest.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.exceptions import slate_assert
from .mesh import COL_AXIS, ProcessGrid, ROW_AXIS, shard_map
from ..obs import instrument

AX = (ROW_AXIS, COL_AXIS)                  # flattened device axis


def _shift_right(x, P_):
    """Each device receives its LEFT neighbor's value (device 0: zeros)."""
    return lax.ppermute(x, AX, [(i, i + 1) for i in range(P_ - 1)])


def _shift_left(x, P_):
    """Each device receives its RIGHT neighbor's value (device P-1: zeros)."""
    return lax.ppermute(x, AX, [(i + 1, i) for i in range(P_ - 1)])


@lru_cache(maxsize=16)
def _chase_dist_fn(mesh, n: int, b: int, seg: int, want_vectors: bool,
                   dtype_str: str):
    """Build the jitted shard_map chase for static (mesh, n, b, seg)."""
    from ..linalg.eig import _hebr1_window
    from ..linalg import householder as hh

    P_ = mesh.shape[ROW_AXIS] * mesh.shape[COL_AXIS]
    dt = jnp.dtype(dtype_str)
    n_sweeps = max(n - 2, 0)
    m_max = max(-(-(n - 1) // b), 1)
    T = 2 * n_sweeps + m_max
    B_loc = seg // (2 * b - 1) + 1          # max co-resident fronts/segment
    S_cap = B_loc + 2                        # v_prev store keys (mod-S_cap)
    M = seg + 4 * b + 4                      # local tile (real+halo+zero-land)
    lz = seg + 2 * b + 2                     # zero-land anchor (local)
    W_pad = P_ * seg + 4 * b + 4             # strip width (cols never sharded)
    sq = 2 * b + 1                           # boundary-square edge
    ar_b = jnp.arange(b)

    def local_fn(strip):                     # (seg, W_pad): rows [c0, c0+seg)
        p = lax.axis_index(AX)
        c0 = p * seg
        c1 = c0 + seg
        g0 = jnp.maximum(c0 - 1, 0)          # tile origin (global)
        # overlapping tile: left neighbor's tail row + my strip + the 2b-row
        # right halo (one neighbor suffices: seg >= 2b+2), zero-padded up to
        # the tile height (the tail rows are zero-land, zeroed below anyway)
        prev_tail = _shift_right(strip[-1:], P_)
        next_head = _shift_left(strip[: 2 * b], P_)
        zpad = jnp.zeros((M + 1 - (1 + seg + 2 * b), W_pad), dt)
        rows_ext = jnp.concatenate([prev_tail, strip, next_head, zpad], 0)
        off = g0 - (c0 - 1)                  # 1 on device 0, else 0
        tile = lax.dynamic_slice(rows_ext, (off, jnp.zeros_like(off)),
                         (M, W_pad))
        tile = lax.dynamic_slice(tile, (jnp.zeros_like(g0), g0), (M, M))
        # zero everything past real+halo: the slice drags neighbor data into
        # what must be this device's zero-land
        re = c1 + 2 * b - g0
        arM = jnp.arange(M)
        keep = (arM < re)[:, None] & (arM < re)[None, :]
        tile = jnp.where(keep, tile, jnp.zeros((), dt))
        lL = 0                               # the tile origin IS the left
        #                                      boundary square (g0 = c0-1,
        #                                      clamped with c0 on device 0)
        lR = c1 - 1 - g0                     # right boundary square (local)

        stv0 = jnp.zeros((S_cap, b), dt)
        stt0 = jnp.zeros((S_cap,), dt)
        nvs = n_sweeps + 1 if want_vectors else 1
        Vs0 = jnp.zeros((nvs, m_max, b), dt)
        taus0 = jnp.zeros((nvs, m_max), dt)

        def round_body(t, carry):
            tile, stv, stt, Vs, taus = carry
            snapL = lax.dynamic_slice(tile, (lL, lL), (sq, sq))
            snapR = lax.dynamic_slice(tile, (lR, lR), (sq, sq))

            # ---- hebr1: owned by the device of its r=1 anchor s0+1 -------
            s0 = t // 2
            start = (2 * s0 == t) & (s0 < n_sweeps)
            own1 = start & (s0 + 1 >= c0) & (s0 + 1 < c1)
            a1 = jnp.where(own1, s0 - g0, lz)
            W1 = lax.dynamic_slice(tile, (a1, a1), (b + 1, b + 1))
            W1, v0, tau0 = _hebr1_window(W1)
            tile = lax.dynamic_update_slice(tile, W1, (a1, a1))
            k0 = jnp.where(own1, s0 % S_cap, S_cap)      # OOB -> dropped
            stv = stv.at[k0].set(v0, mode="drop")
            stt = stt.at[k0].set(tau0, mode="drop")
            if want_vectors:
                sv = jnp.where(own1, s0, n_sweeps)
                Vs = Vs.at[sv, 0].set(jnp.where(own1, v0, Vs[sv, 0]))
                taus = taus.at[sv, 0].set(jnp.where(own1, tau0, taus[sv, 0]))

            # ---- batched hebr2+hebr3 over my live fronts -----------------
            # fronts at round t: sweep s at anchor j = t*b+1 - s*(2b-1),
            # step r = t-2s+1; mine are the (<= B_loc) consecutive s with
            # j in [c0, c1)
            s_start = -((c1 - t * b - 2) // (2 * b - 1))
            s_q = s_start + jnp.arange(B_loc)
            j_q = t * b + 1 - s_q * (2 * b - 1)
            r_q = t - 2 * s_q + 1
            m_s = -(-(n - 1 - s_q) // b)
            active = ((s_q >= 0) & (s_q < n_sweeps) & (r_q >= 1)
                      & (r_q < m_s) & (j_q >= c0) & (j_q < c1))
            li = jnp.where(active, j_q + b - g0, lz + b)
            ljj = jnp.where(active, j_q - g0, lz)
            vp = stv[s_q % S_cap]
            tp = stt[s_q % S_cap]
            rows = li[:, None] + ar_b[None, :]           # (B_loc, b)
            cols = ljj[:, None] + ar_b[None, :]
            Wb = tile[rows[:, :, None], cols[:, None, :]]
            Wv = jnp.einsum("bij,bj->bi", Wb, vp)
            Wb = Wb - tp[:, None, None] * Wv[:, :, None] * jnp.conj(vp)[:, None, :]
            v, tau, _ = hh.larfg(Wb[:, :, 0])
            vW = jnp.einsum("bi,bij->bj", jnp.conj(v), Wb)
            Wb = Wb - jnp.conj(tau)[:, None, None] * v[:, :, None] * vW[:, None, :]
            tile = tile.at[rows[:, :, None], cols[:, None, :]].set(Wb)
            tile = tile.at[cols[:, :, None], rows[:, None, :]].set(
                jnp.conj(jnp.swapaxes(Wb, -1, -2)))
            Db = tile[rows[:, :, None], rows[:, None, :]]
            Dv = jnp.einsum("bi,bij->bj", jnp.conj(v), Db)
            Db = Db - jnp.conj(tau)[:, None, None] * v[:, :, None] * Dv[:, None, :]
            Dw = jnp.einsum("bij,bj->bi", Db, v)
            Db = Db - tau[:, None, None] * Dw[:, :, None] * jnp.conj(v)[:, None, :]
            tile = tile.at[rows[:, :, None], rows[:, None, :]].set(Db)
            kq = jnp.where(active, s_q % S_cap, S_cap)
            stv = stv.at[kq].set(v, mode="drop")
            stt = stt.at[kq].set(tau, mode="drop")
            if want_vectors:
                s_c = jnp.where(active, s_q, n_sweeps)
                r_c = jnp.where(active, r_q, 0)
                Vs = Vs.at[s_c, r_c].set(
                    jnp.where(active[:, None], v, Vs[s_c, r_c]))
                taus = taus.at[s_c, r_c].set(
                    jnp.where(active, tau, taus[s_c, r_c]))

            # ---- neighbor reconciliation ---------------------------------
            dL = lax.dynamic_slice(tile, (lL, lL), (sq, sq)) - snapL
            dR = lax.dynamic_slice(tile, (lR, lR), (sq, sq)) - snapR
            crossing = active & (j_q >= c1 - b)          # at most one
            cvalid = jnp.any(crossing).astype(jnp.int32)
            cs = jnp.sum(jnp.where(crossing, s_q, 0))
            cv = jnp.sum(jnp.where(crossing[:, None], v, 0), axis=0)
            ct = jnp.sum(jnp.where(crossing, tau, 0))
            # rightward: my dR + crossing reflector -> right neighbor
            rdelta = _shift_right(dR, P_)
            rv = _shift_right(cv, P_)
            rt = _shift_right(ct, P_)
            rs = _shift_right(cs, P_)
            rvalid = _shift_right(cvalid, P_)
            # leftward: my dL -> left neighbor
            ldelta = _shift_left(dL, P_)
            tile = lax.dynamic_update_slice(
                tile, lax.dynamic_slice(tile, (lL, lL), (sq, sq)) + rdelta,
                (lL, lL))
            tile = lax.dynamic_update_slice(
                tile, lax.dynamic_slice(tile, (lR, lR), (sq, sq)) + ldelta,
                (lR, lR))
            kin = jnp.where(rvalid == 1, rs % S_cap, S_cap)
            stv = stv.at[kin].set(rv, mode="drop")
            stt = stt.at[kin].set(rt, mode="drop")
            return tile, stv, stt, Vs, taus

        tile, stv, stt, Vs, taus = lax.fori_loop(
            0, T, round_body, (tile, stv0, stt0, Vs0, taus0))

        # owned diagonal + subdiagonal segments (global x in [c0, c1))
        lx = jnp.arange(seg) + (c0 - g0)
        d_loc = jnp.real(tile[lx, lx])
        e_loc = tile[lx + 1, lx]             # e[x] = T[x+1, x]
        if want_vectors:
            Vs = lax.psum(Vs, AX)
            taus = lax.psum(taus, AX)
        return d_loc, e_loc, Vs, taus

    out_specs = (P(AX), P(AX), P(None), P(None))
    fn = shard_map(local_fn, mesh=mesh, in_specs=(P(AX, None),),
                       out_specs=out_specs, check_vma=False)
    return jax.jit(fn)


@lru_cache(maxsize=16)
def _tb2bd_dist_fn(mesh, n: int, b: int, seg: int, want_vectors: bool,
                   dtype_str: str):
    """shard_map bidiagonal chase (tb2bd) for static (mesh, n, b, seg).

    Same segmentation as the Hermitian kernel with three differences that
    follow from the upper-band geometry (svd.py:_tb2bd_chase_pipelined):
    - the gebr1 window at (s, s+1) reaches b+1 columns left of its sweep's
      r=1 anchor j = s+b+1, so tiles carry a b+1 left margin (vs 1);
    - no mirror writes (the band is not Hermitian), and the exchange square
      sits at [boundary-b-1, boundary+b): gebr2 rows dip b below the
      anchor, gebr1 a further 1;
    - TWO reflector families: v (right) is generated fresh per step, u
      (left) is the carried one — the crossing payload ships (u, tauu, s).
    """
    from ..linalg import householder as hh

    P_ = mesh.shape[ROW_AXIS] * mesh.shape[COL_AXIS]
    dt = jnp.dtype(dtype_str)
    n_sweeps = max(n - 1, 0)
    m_max = max(-(-(n - 1) // b), 1)
    T = 2 * n_sweeps + m_max
    B_loc = seg // (2 * b - 1) + 1
    S_cap = B_loc + 2
    lm = b + 1                               # left margin (gebr1 reach)
    M = seg + 2 * b + lm + 2 * b + 3         # real+halo + zero-land
    lz = seg + 2 * b + lm + 1                # zero-land i-anchor (local)
    W_pad = P_ * seg + M                     # strip width (cols never sharded)
    sq = 2 * b + 1                           # exchange-square edge
    ar_b = jnp.arange(b)

    def local_fn(strip):                     # (seg, W_pad): rows [c0, c0+seg)
        p = lax.axis_index(AX)
        c0 = p * seg
        c1 = c0 + seg
        g0 = jnp.maximum(c0 - lm, 0)         # tile origin (global)
        prev_tail = _shift_right(strip[-lm:], P_)
        next_head = _shift_left(strip[: 2 * b], P_)
        zpad = jnp.zeros((M + lm - (lm + seg + 2 * b), W_pad), dt)
        rows_ext = jnp.concatenate([prev_tail, strip, next_head, zpad], 0)
        off = g0 - (c0 - lm)                 # lm on device 0, else 0
        tile = lax.dynamic_slice(rows_ext, (off, jnp.zeros_like(off)),
                                 (M, W_pad))
        tile = lax.dynamic_slice(tile, (jnp.zeros_like(g0), g0), (M, M))
        re = c1 + 2 * b - g0
        arM = jnp.arange(M)
        keep = (arM < re)[:, None] & (arM < re)[None, :]
        tile = jnp.where(keep, tile, jnp.zeros((), dt))
        lL = jnp.maximum(c0 - b - 1, 0) - g0  # left exchange square (local)
        lR = c1 - b - 1 - g0                  # right exchange square (local)

        stu0 = jnp.zeros((S_cap, b), dt)
        stt0 = jnp.zeros((S_cap,), dt)
        nvs = n_sweeps + 1 if want_vectors else 1
        Us0 = jnp.zeros((nvs, m_max, b), dt)
        tauus0 = jnp.zeros((nvs, m_max), dt)
        Vs0 = jnp.zeros((nvs, m_max, b), dt)
        tauvs0 = jnp.zeros((nvs, m_max), dt)

        def round_body(t, carry):
            tile, stu, stt, Us, tauus, Vs, tauvs = carry
            snapL = lax.dynamic_slice(tile, (lL, lL), (sq, sq))
            snapR = lax.dynamic_slice(tile, (lR, lR), (sq, sq))

            # ---- gebr1: owned by the device of its r=1 anchor s0+b+1 -----
            s0 = t // 2
            start = (2 * s0 == t) & (s0 < n_sweeps)
            # ownership anchor: the r=1 front's column for the same-round u0
            # handoff; tail sweeps (s0+b+1 >= n) have no r=1 front, so their
            # anchor clamps to the last real column (the last device's tile
            # still contains the whole (s0, s0+1) window)
            jown = jnp.minimum(s0 + b + 1, n - 1)
            own1 = start & (jown >= c0) & (jown < c1)
            a1 = jnp.where(own1, s0 - g0, lz)
            W = lax.dynamic_slice(tile, (a1, a1 + 1), (b + 1, b))
            v0, tauv0, _ = hh.larfg(jnp.conj(W[0, :]))
            W = hh.apply_right(tauv0, v0, W)
            u0, tauu0, _ = hh.larfg(W[1:, 0])
            W = W.at[1:, :].set(hh.apply_left(tauu0, u0, W[1:, :]))
            tile = lax.dynamic_update_slice(tile, W, (a1, a1 + 1))
            k0 = jnp.where(own1, s0 % S_cap, S_cap)
            stu = stu.at[k0].set(u0, mode="drop")
            stt = stt.at[k0].set(tauu0, mode="drop")
            if want_vectors:
                sv = jnp.where(own1, s0, n_sweeps)
                Vs = Vs.at[sv, 0].set(jnp.where(own1, v0, Vs[sv, 0]))
                tauvs = tauvs.at[sv, 0].set(
                    jnp.where(own1, tauv0, tauvs[sv, 0]))
                Us = Us.at[sv, 0].set(jnp.where(own1, u0, Us[sv, 0]))
                tauus = tauus.at[sv, 0].set(
                    jnp.where(own1, tauu0, tauus[sv, 0]))

            # ---- batched gebr2+gebr3 over my live fronts -----------------
            # front (s, r=t-2s+1) at diagonal anchor j = (t+1)b+1 - s(2b-1)
            s_start = -((c1 - (t + 1) * b - 2) // (2 * b - 1))
            s_q = s_start + jnp.arange(B_loc)
            j_q = (t + 1) * b + 1 - s_q * (2 * b - 1)
            r_q = t - 2 * s_q + 1
            active = ((s_q >= 0) & (s_q < n_sweeps) & (r_q >= 1)
                      & (j_q < n) & (j_q >= c0) & (j_q < c1))
            li = jnp.where(active, j_q - b - g0, lz)       # gebr2 row anchor
            ljj = jnp.where(active, j_q - g0, lz + b)      # col/diag anchor
            up = stu[s_q % S_cap]
            tp = stt[s_q % S_cap]
            rows_i = li[:, None] + ar_b[None, :]
            cols_j = ljj[:, None] + ar_b[None, :]
            # gebr2: left-apply previous u, then new right v zeroing row 0
            Wb = tile[rows_i[:, :, None], cols_j[:, None, :]]
            uW = jnp.einsum("bi,bij->bj", jnp.conj(up), Wb)
            Wb = Wb - jnp.conj(tp)[:, None, None] * up[:, :, None] * uW[:, None, :]
            v, tauv, _ = hh.larfg(jnp.conj(Wb[:, 0, :]))
            Wv = jnp.einsum("bij,bj->bi", Wb, v)
            Wb = Wb - tauv[:, None, None] * Wv[:, :, None] * jnp.conj(v)[:, None, :]
            tile = tile.at[rows_i[:, :, None], cols_j[:, None, :]].set(Wb)
            # gebr3: right-apply v on the diagonal window, new left u
            Db = tile[cols_j[:, :, None], cols_j[:, None, :]]
            Dv = jnp.einsum("bij,bj->bi", Db, v)
            Db = Db - tauv[:, None, None] * Dv[:, :, None] * jnp.conj(v)[:, None, :]
            u, tauu, _ = hh.larfg(Db[:, :, 0])
            uD = jnp.einsum("bi,bij->bj", jnp.conj(u), Db)
            Db = Db - jnp.conj(tauu)[:, None, None] * u[:, :, None] * uD[:, None, :]
            tile = tile.at[cols_j[:, :, None], cols_j[:, None, :]].set(Db)
            kq = jnp.where(active, s_q % S_cap, S_cap)
            stu = stu.at[kq].set(u, mode="drop")
            stt = stt.at[kq].set(tauu, mode="drop")
            if want_vectors:
                s_c = jnp.where(active, s_q, n_sweeps)
                r_c = jnp.where(active, r_q, 0)
                Vs = Vs.at[s_c, r_c].set(
                    jnp.where(active[:, None], v, Vs[s_c, r_c]))
                tauvs = tauvs.at[s_c, r_c].set(
                    jnp.where(active, tauv, tauvs[s_c, r_c]))
                Us = Us.at[s_c, r_c].set(
                    jnp.where(active[:, None], u, Us[s_c, r_c]))
                tauus = tauus.at[s_c, r_c].set(
                    jnp.where(active, tauu, tauus[s_c, r_c]))

            # ---- neighbor reconciliation ---------------------------------
            dL = lax.dynamic_slice(tile, (lL, lL), (sq, sq)) - snapL
            dR = lax.dynamic_slice(tile, (lR, lR), (sq, sq)) - snapR
            crossing = active & (j_q >= c1 - b)
            cvalid = jnp.any(crossing).astype(jnp.int32)
            cs = jnp.sum(jnp.where(crossing, s_q, 0))
            cu = jnp.sum(jnp.where(crossing[:, None], u, 0), axis=0)
            ct = jnp.sum(jnp.where(crossing, tauu, 0))
            rdelta = _shift_right(dR, P_)
            ru = _shift_right(cu, P_)
            rt = _shift_right(ct, P_)
            rs = _shift_right(cs, P_)
            rvalid = _shift_right(cvalid, P_)
            ldelta = _shift_left(dL, P_)
            tile = lax.dynamic_update_slice(
                tile, lax.dynamic_slice(tile, (lL, lL), (sq, sq)) + rdelta,
                (lL, lL))
            tile = lax.dynamic_update_slice(
                tile, lax.dynamic_slice(tile, (lR, lR), (sq, sq)) + ldelta,
                (lR, lR))
            kin = jnp.where(rvalid == 1, rs % S_cap, S_cap)
            stu = stu.at[kin].set(ru, mode="drop")
            stt = stt.at[kin].set(rt, mode="drop")
            return tile, stu, stt, Us, tauus, Vs, tauvs

        tile, stu, stt, Us, tauus, Vs, tauvs = lax.fori_loop(
            0, T, round_body,
            (tile, stu0, stt0, Us0, tauus0, Vs0, tauvs0))

        lx = jnp.arange(seg) + (c0 - g0)
        d_loc = tile[lx, lx]
        e_loc = tile[lx, lx + 1]             # e[x] = B[x, x+1]
        if want_vectors:
            Us = lax.psum(Us, AX)
            tauus = lax.psum(tauus, AX)
            Vs = lax.psum(Vs, AX)
            tauvs = lax.psum(tauvs, AX)
        return d_loc, e_loc, Us, tauus, Vs, tauvs

    out_specs = (P(AX), P(AX), P(None), P(None), P(None), P(None))
    fn = shard_map(local_fn, mesh=mesh, in_specs=(P(AX, None),),
                       out_specs=out_specs, check_vma=False)
    return jax.jit(fn)


@instrument
def tb2bd_chase_distributed(Bfull: jax.Array, kd: int, grid: ProcessGrid,
                            want_vectors: bool = False):
    """Segment-parallel bidiagonal chase (the SVD stage 2) over ``grid``.

    ``Bfull``: square upper band (bandwidth ``kd``), dense storage.  Returns
    ``(d_c, e_c, Us, tauus, Vs, tauvs)`` matching
    ``linalg.svd._tb2bd_chase_pipelined`` (reflector stacks are zeros when
    ``want_vectors=False``).
    """
    n = Bfull.shape[-1]
    b = int(kd)
    P_ = grid.size
    slate_assert(b >= 2 and n > 1, "tb2bd chase needs kd >= 2 and n > 1")
    seg = -(-n // P_)
    slate_assert(seg >= 2 * b + 2,
                 f"segment {seg} too narrow for bandwidth {b} on {P_} devices"
                 " (need n/P >= 2*kd+2); use the replicated chase")
    M = seg + 2 * b + (b + 1) + 2 * b + 3
    W_pad = P_ * seg + M
    Bp = jnp.zeros((P_ * seg, W_pad), Bfull.dtype)
    Bp = Bp.at[:n, :n].set(Bfull)
    fn = _tb2bd_dist_fn(grid.mesh, n, b, seg, bool(want_vectors),
                        str(Bfull.dtype))
    d_all, e_all, Us, tauus, Vs, tauvs = fn(Bp)
    d_c = d_all[:n]
    e_c = e_all[: n - 1]
    n_sweeps = max(n - 1, 0)
    return (d_c, e_c, Us[:n_sweeps], tauus[:n_sweeps],
            Vs[:n_sweeps], tauvs[:n_sweeps])


@instrument
def hb2st_chase_distributed(Afull: jax.Array, kd: int, grid: ProcessGrid,
                            want_vectors: bool = False):
    """Segment-parallel bulge chase over ``grid``'s flattened device list.

    ``Afull``: the full Hermitian band matrix (dense storage, bandwidth
    ``kd``), replicated on the host side like the rank-0 design's input.
    Returns ``(d, e_complex, Vs, taus)`` matching
    ``linalg.eig._hb2st_chase_pipelined`` (``Vs``/``taus`` are zeros when
    ``want_vectors=False``).
    """
    n = Afull.shape[-1]
    b = int(kd)
    P_ = grid.size
    slate_assert(b >= 2 and n > 2, "chase needs kd >= 2 and n > 2")
    seg = -(-n // P_)
    slate_assert(seg >= 2 * b + 2,
                 f"segment {seg} too narrow for bandwidth {b} on {P_} devices"
                 " (need n/P >= 2*kd+2); use the replicated chase")
    W_pad = P_ * seg + 4 * b + 4
    Ap = jnp.zeros((P_ * seg, W_pad), Afull.dtype)
    Ap = Ap.at[:n, :n].set(Afull)
    fn = _chase_dist_fn(grid.mesh, n, b, seg, bool(want_vectors),
                        str(Afull.dtype))
    d_all, e_all, Vs, taus = fn(Ap)
    d = d_all[:n]
    e_c = e_all[: n - 1]
    n_sweeps = max(n - 2, 0)
    return d, e_c, Vs[:n_sweeps], taus[:n_sweeps]
