"""Distributed bulge chase: the hb2st pipelined schedule sharded over a mesh.

The reference confines stage 2 to rank 0 (src/hb2st.cc scheduling consumed on
one process; src/heev.cc:137-160 gathers the band there), and rounds 1-4 of
this repo mirrored that: ``heev_distributed`` replicated the band and every
device replayed the same chase.  This module goes past the reference: the
band's column range is partitioned into P contiguous segments, each device
runs only the chase fronts whose window anchor falls in its segment, and
neighbors reconcile through two tiny ``ppermute`` exchanges per round:

- a (2b+1)x(2b+1) boundary-square DELTA in each direction.  Concurrent
  fronts write element-disjoint footprints (the schedule spaces live fronts
  2b-1 apart - the same commutativity the reference's thread scheduler and
  our batched single-device rounds rely on), so neighbor copies of the
  overlap reconcile by pure addition;
- at most one CROSSING reflector (v, tau, s): a front advances b columns
  per round and fronts are 2b-1 apart, so per boundary per round at most
  one front hops segments, carrying its v_prev to the next owner.

Collective volume is O(b^2 + b) per round - independent of n - versus the
O(n * b) band replication the rank-0 design ships once.  Per-device window
work drops from the full front set (~n/2b batched windows per round) to
~n/(2bP).

Schedule (identical to linalg/eig.py:_hb2st_chase_pipelined): sweep s runs
hebr1 at round t=2s and its hebr2/hebr3 step r (window anchor
j = (t-2s)b+1+s, i = j+b) at round t = 2s+r-1; front ownership is by the
anchor column j.  hebr1 ownership is by the sweep's r=1 anchor j = s+1, so
the hebr1 -> first-hebr2 handoff (same round, shared v0) never crosses a
boundary; the window's one-column reach below s+1 is why tiles carry a
single extra left column.

Results match the single-device pipelined chase bit-for-bit in the same
XLA configuration: same windows, same reflectors, same order per front
(pinned by tests/test_chase_dist.py against _hb2st_chase_pipelined).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.exceptions import slate_assert
from .mesh import COL_AXIS, ROW_AXIS, ProcessGrid

AX = (ROW_AXIS, COL_AXIS)                  # flattened device axis


def _shift_right(x, P_):
    """Each device receives its LEFT neighbor's value (device 0: zeros)."""
    return lax.ppermute(x, AX, [(i, i + 1) for i in range(P_ - 1)])


def _shift_left(x, P_):
    """Each device receives its RIGHT neighbor's value (device P-1: zeros)."""
    return lax.ppermute(x, AX, [(i + 1, i) for i in range(P_ - 1)])


@lru_cache(maxsize=16)
def _chase_dist_fn(mesh, n: int, b: int, seg: int, want_vectors: bool,
                   dtype_str: str):
    """Build the jitted shard_map chase for static (mesh, n, b, seg)."""
    from ..linalg.eig import _hebr1_window
    from ..linalg import householder as hh

    P_ = mesh.shape[ROW_AXIS] * mesh.shape[COL_AXIS]
    dt = jnp.dtype(dtype_str)
    n_sweeps = max(n - 2, 0)
    m_max = max(-(-(n - 1) // b), 1)
    T = 2 * n_sweeps + m_max
    B_loc = seg // (2 * b - 1) + 1          # max co-resident fronts/segment
    S_cap = B_loc + 2                        # v_prev store keys (mod-S_cap)
    M = seg + 4 * b + 4                      # local tile (real+halo+zero-land)
    lz = seg + 2 * b + 2                     # zero-land anchor (local)
    W_pad = P_ * seg + 4 * b + 4             # strip width (cols never sharded)
    sq = 2 * b + 1                           # boundary-square edge
    ar_b = jnp.arange(b)

    def local_fn(strip):                     # (seg, W_pad): rows [c0, c0+seg)
        p = lax.axis_index(AX)
        c0 = p * seg
        c1 = c0 + seg
        g0 = jnp.maximum(c0 - 1, 0)          # tile origin (global)
        # overlapping tile: left neighbor's tail row + my strip + the 2b-row
        # right halo (one neighbor suffices: seg >= 2b+2), zero-padded up to
        # the tile height (the tail rows are zero-land, zeroed below anyway)
        prev_tail = _shift_right(strip[-1:], P_)
        next_head = _shift_left(strip[: 2 * b], P_)
        zpad = jnp.zeros((M + 1 - (1 + seg + 2 * b), W_pad), dt)
        rows_ext = jnp.concatenate([prev_tail, strip, next_head, zpad], 0)
        off = g0 - (c0 - 1)                  # 1 on device 0, else 0
        tile = lax.dynamic_slice(rows_ext, (off, jnp.zeros_like(off)),
                         (M, W_pad))
        tile = lax.dynamic_slice(tile, (jnp.zeros_like(g0), g0), (M, M))
        # zero everything past real+halo: the slice drags neighbor data into
        # what must be this device's zero-land
        re = c1 + 2 * b - g0
        arM = jnp.arange(M)
        keep = (arM < re)[:, None] & (arM < re)[None, :]
        tile = jnp.where(keep, tile, jnp.zeros((), dt))
        lL = 0                               # the tile origin IS the left
        #                                      boundary square (g0 = c0-1,
        #                                      clamped with c0 on device 0)
        lR = c1 - 1 - g0                     # right boundary square (local)

        stv0 = jnp.zeros((S_cap, b), dt)
        stt0 = jnp.zeros((S_cap,), dt)
        nvs = n_sweeps + 1 if want_vectors else 1
        Vs0 = jnp.zeros((nvs, m_max, b), dt)
        taus0 = jnp.zeros((nvs, m_max), dt)

        def round_body(t, carry):
            tile, stv, stt, Vs, taus = carry
            snapL = lax.dynamic_slice(tile, (lL, lL), (sq, sq))
            snapR = lax.dynamic_slice(tile, (lR, lR), (sq, sq))

            # ---- hebr1: owned by the device of its r=1 anchor s0+1 -------
            s0 = t // 2
            start = (2 * s0 == t) & (s0 < n_sweeps)
            own1 = start & (s0 + 1 >= c0) & (s0 + 1 < c1)
            a1 = jnp.where(own1, s0 - g0, lz)
            W1 = lax.dynamic_slice(tile, (a1, a1), (b + 1, b + 1))
            W1, v0, tau0 = _hebr1_window(W1)
            tile = lax.dynamic_update_slice(tile, W1, (a1, a1))
            k0 = jnp.where(own1, s0 % S_cap, S_cap)      # OOB -> dropped
            stv = stv.at[k0].set(v0, mode="drop")
            stt = stt.at[k0].set(tau0, mode="drop")
            if want_vectors:
                sv = jnp.where(own1, s0, n_sweeps)
                Vs = Vs.at[sv, 0].set(jnp.where(own1, v0, Vs[sv, 0]))
                taus = taus.at[sv, 0].set(jnp.where(own1, tau0, taus[sv, 0]))

            # ---- batched hebr2+hebr3 over my live fronts -----------------
            # fronts at round t: sweep s at anchor j = t*b+1 - s*(2b-1),
            # step r = t-2s+1; mine are the (<= B_loc) consecutive s with
            # j in [c0, c1)
            s_start = -((c1 - t * b - 2) // (2 * b - 1))
            s_q = s_start + jnp.arange(B_loc)
            j_q = t * b + 1 - s_q * (2 * b - 1)
            r_q = t - 2 * s_q + 1
            m_s = -(-(n - 1 - s_q) // b)
            active = ((s_q >= 0) & (s_q < n_sweeps) & (r_q >= 1)
                      & (r_q < m_s) & (j_q >= c0) & (j_q < c1))
            li = jnp.where(active, j_q + b - g0, lz + b)
            ljj = jnp.where(active, j_q - g0, lz)
            vp = stv[s_q % S_cap]
            tp = stt[s_q % S_cap]
            rows = li[:, None] + ar_b[None, :]           # (B_loc, b)
            cols = ljj[:, None] + ar_b[None, :]
            Wb = tile[rows[:, :, None], cols[:, None, :]]
            Wv = jnp.einsum("bij,bj->bi", Wb, vp)
            Wb = Wb - tp[:, None, None] * Wv[:, :, None] * jnp.conj(vp)[:, None, :]
            v, tau, _ = hh.larfg(Wb[:, :, 0])
            vW = jnp.einsum("bi,bij->bj", jnp.conj(v), Wb)
            Wb = Wb - jnp.conj(tau)[:, None, None] * v[:, :, None] * vW[:, None, :]
            tile = tile.at[rows[:, :, None], cols[:, None, :]].set(Wb)
            tile = tile.at[cols[:, :, None], rows[:, None, :]].set(
                jnp.conj(jnp.swapaxes(Wb, -1, -2)))
            Db = tile[rows[:, :, None], rows[:, None, :]]
            Dv = jnp.einsum("bi,bij->bj", jnp.conj(v), Db)
            Db = Db - jnp.conj(tau)[:, None, None] * v[:, :, None] * Dv[:, None, :]
            Dw = jnp.einsum("bij,bj->bi", Db, v)
            Db = Db - tau[:, None, None] * Dw[:, :, None] * jnp.conj(v)[:, None, :]
            tile = tile.at[rows[:, :, None], rows[:, None, :]].set(Db)
            kq = jnp.where(active, s_q % S_cap, S_cap)
            stv = stv.at[kq].set(v, mode="drop")
            stt = stt.at[kq].set(tau, mode="drop")
            if want_vectors:
                s_c = jnp.where(active, s_q, n_sweeps)
                r_c = jnp.where(active, r_q, 0)
                Vs = Vs.at[s_c, r_c].set(
                    jnp.where(active[:, None], v, Vs[s_c, r_c]))
                taus = taus.at[s_c, r_c].set(
                    jnp.where(active, tau, taus[s_c, r_c]))

            # ---- neighbor reconciliation ---------------------------------
            dL = lax.dynamic_slice(tile, (lL, lL), (sq, sq)) - snapL
            dR = lax.dynamic_slice(tile, (lR, lR), (sq, sq)) - snapR
            crossing = active & (j_q >= c1 - b)          # at most one
            cvalid = jnp.any(crossing).astype(jnp.int32)
            cs = jnp.sum(jnp.where(crossing, s_q, 0))
            cv = jnp.sum(jnp.where(crossing[:, None], v, 0), axis=0)
            ct = jnp.sum(jnp.where(crossing, tau, 0))
            # rightward: my dR + crossing reflector -> right neighbor
            rdelta = _shift_right(dR, P_)
            rv = _shift_right(cv, P_)
            rt = _shift_right(ct, P_)
            rs = _shift_right(cs, P_)
            rvalid = _shift_right(cvalid, P_)
            # leftward: my dL -> left neighbor
            ldelta = _shift_left(dL, P_)
            tile = lax.dynamic_update_slice(
                tile, lax.dynamic_slice(tile, (lL, lL), (sq, sq)) + rdelta,
                (lL, lL))
            tile = lax.dynamic_update_slice(
                tile, lax.dynamic_slice(tile, (lR, lR), (sq, sq)) + ldelta,
                (lR, lR))
            kin = jnp.where(rvalid == 1, rs % S_cap, S_cap)
            stv = stv.at[kin].set(rv, mode="drop")
            stt = stt.at[kin].set(rt, mode="drop")
            return tile, stv, stt, Vs, taus

        tile, stv, stt, Vs, taus = lax.fori_loop(
            0, T, round_body, (tile, stv0, stt0, Vs0, taus0))

        # owned diagonal + subdiagonal segments (global x in [c0, c1))
        lx = jnp.arange(seg) + (c0 - g0)
        d_loc = jnp.real(tile[lx, lx])
        e_loc = tile[lx + 1, lx]             # e[x] = T[x+1, x]
        if want_vectors:
            Vs = lax.psum(Vs, AX)
            taus = lax.psum(taus, AX)
        return d_loc, e_loc, Vs, taus

    out_specs = (P(AX), P(AX), P(None), P(None))
    fn = jax.shard_map(local_fn, mesh=mesh, in_specs=(P(AX, None),),
                       out_specs=out_specs, check_vma=False)
    return jax.jit(fn)


def hb2st_chase_distributed(Afull: jax.Array, kd: int, grid: ProcessGrid,
                            want_vectors: bool = False):
    """Segment-parallel bulge chase over ``grid``'s flattened device list.

    ``Afull``: the full Hermitian band matrix (dense storage, bandwidth
    ``kd``), replicated on the host side like the rank-0 design's input.
    Returns ``(d, e_complex, Vs, taus)`` matching
    ``linalg.eig._hb2st_chase_pipelined`` (``Vs``/``taus`` are zeros when
    ``want_vectors=False``).
    """
    n = Afull.shape[-1]
    b = int(kd)
    P_ = grid.size
    slate_assert(b >= 2 and n > 2, "chase needs kd >= 2 and n > 2")
    seg = -(-n // P_)
    slate_assert(seg >= 2 * b + 2,
                 f"segment {seg} too narrow for bandwidth {b} on {P_} devices"
                 " (need n/P >= 2*kd+2); use the replicated chase")
    W_pad = P_ * seg + 4 * b + 4
    Ap = jnp.zeros((P_ * seg, W_pad), Afull.dtype)
    Ap = Ap.at[:n, :n].set(Afull)
    fn = _chase_dist_fn(grid.mesh, n, b, seg, bool(want_vectors),
                        str(Afull.dtype))
    d_all, e_all, Vs, taus = fn(Ap)
    d = d_all[:n]
    e_c = e_all[: n - 1]
    n_sweeps = max(n - 2, 0)
    return d, e_c, Vs[:n_sweeps], taus[:n_sweeps]
