"""Mesh-axis collective primitives used inside ``shard_map`` bodies.

Reference analogue (SURVEY.md §5.8): SLATE's tile collectives — ``listBcast``
(hypercube broadcast tree, BaseMatrix.hh:1999-2100 + internal_comm.cc:72-117),
``listReduce`` (BaseMatrix.hh:2219-2258), pivot ``MPI_Bcast`` (getrf.cc:113-119) and
maxloc allreduces (types.hh:161-175).

On TPU the hand-built hypercube trees are unnecessary: ICI collectives are
hardware-scheduled ring/torus algorithms, so each reference pattern maps to a single
XLA collective:

=====================  ==============================================
reference pattern      TPU-native primitive
=====================  ==============================================
listBcast (root tile)  ``axis_bcast`` (psum of masked contribution)
panel gather           ``lax.all_gather`` along the mesh axis
listReduce             ``axis_allreduce`` / ``axis_reduce_scatter``
pivot maxloc           ``lax.pmax`` + index arithmetic (see lu)
ring/lookahead bcast   ``ring_shift`` (ppermute)
=====================  ==============================================

These helpers are *SPMD-internal*: they must be called inside ``shard_map`` (or pmap)
with the named axis in scope.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def axis_index(axis_name: str) -> jax.Array:
    """This shard's coordinate along the axis (the reference's rank-in-communicator)."""
    return lax.axis_index(axis_name)


def axis_bcast(x: jax.Array, axis_name: str, root: int = 0) -> jax.Array:
    """Broadcast ``x`` from the shard at ``root`` to every shard along ``axis_name``.

    The listBcast analogue.  Implemented as a masked psum — one ICI all-reduce, which
    on TPU is as fast as a tree broadcast and needs no per-tile tag bookkeeping
    (BaseMatrix.hh:2129-2216's multithreaded tags disappear in SPMD program order).
    """
    idx = lax.axis_index(axis_name)
    contrib = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis_name)


def axis_allreduce(x: jax.Array, axis_name: str, op: str = "sum") -> jax.Array:
    """listReduce analogue: elementwise reduce across the axis, result everywhere."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unsupported reduce op {op!r}")


def axis_reduce_scatter(x: jax.Array, axis_name: str, scatter_dim: int = 0) -> jax.Array:
    """Reduce across the axis, scattering the result (listReduce where each rank keeps
    its own destination tiles)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dim, tiled=True)


def ring_shift(x: jax.Array, axis_name: str, shift: int = 1, size: int | None = None):
    """Rotate shards along the axis by ``shift`` (SUMMA/Cannon pipeline step;
    the TPU-native form of the reference's lookahead panel sends).

    ``size`` is the axis size; required because ppermute needs a static permutation.
    """
    if size is None:
        size = lax.axis_size(axis_name)
    perm = [(i, (i - shift) % size) for i in range(size)]
    return lax.ppermute(x, axis_name, perm)
