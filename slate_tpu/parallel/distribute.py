"""Distributing matrices over a ProcessGrid, and moving them between distributions.

Reference analogue: the tile→rank block-cyclic maps (func.hh:100-217) applied at
matrix construction (MatrixStorage.hh:494-499), plus ``slate::redistribute``
(src/redistribute.cc:1-154) which migrates a matrix tile-by-tile between two
distributions with send/recv.

TPU re-design: XLA's ``NamedSharding`` gives *block-contiguous* layouts natively.
2D **block-cyclic** ownership (tile (i,j) → rank (i%p, j%q)) is realized by composing a
block layout with a tile permutation: permuting block-rows so that rows owned by mesh
row r become contiguous turns cyclic ownership into a plain block sharding.  The
permutation is itself a gather executed on-device, so `cyclic_to_blocked` +
``distribute`` is the constructor path and ``redistribute`` between any two layouts is
a single ``device_put`` (XLA emits the minimal ICI all-to-all — the reference's
tile-by-tile isend/irecv loop collapses into one collective).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

from ..core.exceptions import slate_assert
from .mesh import ProcessGrid


def ceil_mult(x: int, mult: int) -> int:
    """Round up to a multiple — the shared edge policy (pad-and-mask, SURVEY.md §7)."""
    return -(-x // mult) * mult


def lcm(a: int, b: int) -> int:
    """Least common multiple (shard-alignment unit for (p, q) grids)."""
    import math

    return a * b // math.gcd(a, b)


def pad2d(a: jax.Array, row_mult: int = 1, col_mult: int = 1) -> jax.Array:
    """Zero-pad the trailing 2-D dims up to multiples (no-op when already aligned)."""
    m, n = a.shape[-2:]
    pm, pn = ceil_mult(m, row_mult), ceil_mult(n, col_mult)
    if (pm, pn) == (m, n):
        return a
    return jnp.pad(a, [(0, 0)] * (a.ndim - 2) + [(0, pm - m), (0, pn - n)])


def block_spec(grid: ProcessGrid, row_shard: bool = True,
               col_shard: bool = True) -> NamedSharding:
    """Plain 2-D block sharding: rows over p, cols over q."""
    return grid.spec(row_shard, col_shard)


def distribute(a: jax.Array, grid: ProcessGrid, row_shard: bool = True,
               col_shard: bool = True) -> jax.Array:
    """Place ``a`` on the grid with a block layout (the default compiled-path layout)."""
    return jax.device_put(a, grid.spec(row_shard, col_shard))


def replicate(a: jax.Array, grid: ProcessGrid) -> jax.Array:
    return jax.device_put(a, grid.replicated())


def redistribute(a: jax.Array, dst: NamedSharding) -> jax.Array:
    """Move an array (however currently sharded) to ``dst``
    (src/redistribute.cc — one device_put instead of a send/recv loop)."""
    return jax.device_put(a, dst)


def redistribute_matrix(src, dst) -> None:
    """``slate::redistribute(A, B)`` on wrappers (src/redistribute.cc:1-154):
    copy ``src``'s logical content into ``dst``, honoring both wrappers'
    tile grids — including NON-UNIFORM per-index grids — and ``dst``'s
    device placement.

    When the two tile grids agree the copy walks tiles exactly like the
    reference's send/recv loop (each dst tile filled from the matching src
    tile); differing grids fall back to one whole-view assignment, which on
    functional global arrays is the same data motion without the per-tile
    bookkeeping.  Grid-bound destinations get a device_put to the dst
    placement (the XLA resharding that replaces MPI messages)."""
    from ..core.matrix import BaseMatrix

    slate_assert(isinstance(src, BaseMatrix) and isinstance(dst, BaseMatrix),
                 "redistribute_matrix expects matrix wrappers")
    slate_assert(src.shape == dst.shape,
                 f"shape mismatch: {src.shape} vs {dst.shape}")
    # on functional global arrays the whole tile-by-tile send/recv loop is
    # ONE logical assignment (tile()/set_tile() would produce byte-identical
    # results, mt·nt times slower); the per-tile plan survives as metadata
    # (native.redist_plan / owner_map diffs)
    dst.set_array(src.array)
    dst.storage.place_on_grid()


def cyclic_permutation(n: int, nb: int, nparts: int) -> np.ndarray:
    """Element permutation turning block-cyclic tile ownership into contiguous blocks.

    Returns ``perm`` such that ``a[perm]`` groups all rows of tiles owned by part 0
    first, then part 1, …  With ragged edges the parts are *unequal*, so callers pad
    to ``num_tiles`` divisible shapes before sharding (the compiled drivers already
    pad to uniform nb — SURVEY.md §7 hard-part 5).
    """
    slate_assert(n % nb == 0, "cyclic_permutation requires tile-aligned n (pad first)")
    nt = n // nb
    order = []
    for part in range(nparts):
        for t in range(part, nt, nparts):
            order.extend(range(t * nb, (t + 1) * nb))
    return np.array(order, dtype=np.int64)


def cyclic_to_blocked(a: jax.Array, grid: ProcessGrid, nb: int) -> jax.Array:
    """Permute a matrix so 2D block-cyclic (nb-tile) ownership becomes the block
    layout of ``grid.spec()`` — the bridge from ScaLAPACK-style cyclic semantics to
    XLA shardings (the ``fromScaLAPACK`` constructor path, Matrix.hh:347)."""
    m, n = a.shape[-2:]
    rp = jnp.asarray(cyclic_permutation(m, nb, grid.p))
    cp = jnp.asarray(cyclic_permutation(n, nb, grid.q))
    return a[..., rp, :][..., :, cp]


def blocked_to_cyclic(a: jax.Array, grid: ProcessGrid, nb: int) -> jax.Array:
    """Inverse of :func:`cyclic_to_blocked`."""
    m, n = a.shape[-2:]
    rp = cyclic_permutation(m, nb, grid.p)
    cp = cyclic_permutation(n, nb, grid.q)
    rinv = jnp.asarray(np.argsort(rp))
    cinv = jnp.asarray(np.argsort(cp))
    return a[..., rinv, :][..., :, cinv]
