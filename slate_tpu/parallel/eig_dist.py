"""Distributed eigenvalue / SVD / norm drivers over the process grid.

Reference analogues: ``src/heev.cc:68-225`` (the longest distributed pipeline:
scale -> he2hb on the grid -> he2hbGather to rank 0 -> hb2st on rank 0 ->
sterf/steqr/stedc -> redistribute -> back-transforms), ``src/svd.cc:99-141``
(same shape via ge2tb/tb2bd/bdsqr), and the ``internal::norm`` reductions the
``norm`` driver runs over distributed tiles.

TPU re-design:

* **Stage 1 is where the flops are** (O(n^2 nb) gemms per panel, O(n^3)
  total) — it runs *sharded*: the blocked he2hb / ge2tb_band loops are jitted
  with the operand placed on the (p, q) mesh and GSPMD partitions the
  two-sided block-reflector gemms, inserting the panel all-gathers the
  reference does with listBcast (SURVEY.md §5.8 mapping).
* **Stage 2 is sequential by nature** (bulge chasing) and cheap (O(n^2 kd));
  the band is *replicated* across the mesh — the exact analogue of
  ``he2hbGather`` pulling the band to rank 0 (heev.cc:133-135) — and chased
  locally, like the reference runs hb2st on rank 0 only (heev.cc:137-160).
* **Back-transforms are gemms** and run sharded again (the reference
  redistributes Z to 1-D for unmtr_hb2st then back, heev.cc:193-205; here the
  resharding is one device_put).
* Norms are one jitted masked reduction with sharded input — XLA lowers the
  reduction to per-shard partials + a psum, which is ``internal::norm``'s
  partial-tile reduction + MPI allreduce.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import COL_AXIS, ROW_AXIS, ProcessGrid


@lru_cache(maxsize=32)
def _constrain_fn(mesh, row_shard: bool, col_shard: bool):
    spec = NamedSharding(mesh, P(ROW_AXIS if row_shard else None,
                                 COL_AXIS if col_shard else None))
    return jax.jit(lambda a: lax.with_sharding_constraint(a, spec))


def _shard(x, grid: ProcessGrid, row: bool = True, col: bool = True):
    """Place x block-sharded on the grid via a sharding constraint —
    unlike device_put this tolerates non-divisible shapes (GSPMD pads)."""
    return _constrain_fn(grid.mesh, row, col)(x)


@lru_cache(maxsize=32)
def _he2hb_dist_fn(mesh, n: int, nb: int, dtype_str: str):
    from ..linalg.eig import he2hb

    spec = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))

    def fn(Af):
        Af = lax.with_sharding_constraint(Af, spec)
        return he2hb(Af, nb=nb)

    return jax.jit(fn)


@lru_cache(maxsize=32)
def _ge2tb_dist_fn(mesh, m: int, n: int, nb: int, dtype_str: str):
    from ..linalg.svd import ge2tb_band

    spec = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))

    def fn(Af):
        Af = lax.with_sharding_constraint(Af, spec)
        return ge2tb_band(Af, nb=nb)

    return jax.jit(fn)


def heev_distributed(A: jax.Array, grid: ProcessGrid, nb: int = 64,
                     want_vectors: bool = True, method_eig: str = "qr",
                     chase_pipeline: bool = False):
    """Distributed Hermitian eigensolve over the (p, q) mesh (src/heev.cc).

    Returns (ascending eigenvalues, Z or None); Z comes back sharded on the
    grid.  ``method_eig='dc'`` solves the tridiagonal with stedc.
    """
    from ..linalg.eig import _safe_scale, hb2st, sterf, unmtr_he2hb
    from ..linalg.stedc import stedc as _stedc
    from ..linalg.eig import steqr

    n = A.shape[-1]
    if n < 8:
        # no meaningful band structure below one panel — local fused solve
        # (the single-device heev makes the same switch)
        lam, z = (jnp.linalg.eigh(A) if want_vectors
                  else (jnp.linalg.eigvalsh(A), None))
        return lam, z
    nb = max(2, min(nb, max(2, n // 2)))
    a, factor = _safe_scale(A)
    a = _shard(a, grid)
    # stage 1 on the mesh: GSPMD shards the two-sided panel gemms
    band, Vs, Ts = _he2hb_dist_fn(grid.mesh, n, nb, str(a.dtype))(a)
    # he2hbGather analogue: replicate the (cheap) band for the local chase
    band = jax.device_put(band, grid.replicated())
    out = hb2st(band, kd=nb, want_vectors=want_vectors,
                pipeline=chase_pipeline)
    if not want_vectors:
        d, e = out
        # values-only always takes sterf — D&C inherently carries vectors
        # (merge z-couplings ARE eigenvector rows), exactly why the reference
        # routes no-vector solves to sterf too (heev.cc:208-215)
        lam = sterf(d, e)
        return lam * factor, None
    d, e, Q2 = out
    lam, Zt = (_stedc if method_eig == "dc" else steqr)(d, e)
    Z = jnp.matmul(Q2, Zt.astype(Q2.dtype), precision=lax.Precision.HIGHEST)
    # redistribute + stage-1 back-transform (sharded gemms)
    Z = _shard(Z, grid)
    Z = unmtr_he2hb("left", "n", Vs, Ts, Z)
    return lam * factor, Z


def hegv_distributed(itype: int, A: jax.Array, B: jax.Array,
                     grid: ProcessGrid, nb: int = 64,
                     want_vectors: bool = True):
    """Distributed generalized Hermitian eigensolve A x = lambda B x
    (src/hegv.cc over the mesh): sharded potrf(B) -> hegst transform (sharded
    triangular solves / gemms) -> heev_distributed -> sharded back-transform.

    Returns (ascending eigenvalues, X or None).
    """
    from ..core.exceptions import SlateError
    from ..linalg.eig import hegst
    from .solvers import potrf_distributed, trsm_distributed

    L = potrf_distributed(B, grid, nb=max(nb, 32))
    # SPD verdict stays traced until the END: the whole pipeline (transform,
    # eigensolve, back-transform — all bounded loops, NaN-safe) dispatches
    # with a single host sync, instead of blocking on L's diagonal up front
    spd_ok = jnp.all(jnp.isfinite(jnp.diagonal(L)))
    C = hegst(itype, _shard(A, grid), L)
    lam, Z = heev_distributed(C, grid, nb=nb, want_vectors=want_vectors)
    if want_vectors:
        if itype in (1, 2):
            X = trsm_distributed(L, Z, grid, lower=True, conj_trans=True)
        else:
            X = jnp.matmul(jnp.tril(L), Z, precision=lax.Precision.HIGHEST)
    else:
        X = None
    if not bool(spd_ok):                  # the solve's single host sync
        raise SlateError("hegv_distributed: B not positive definite")
    return lam, X


def svd_distributed(A: jax.Array, grid: ProcessGrid, nb: int = 64,
                    want_vectors: bool = True, chase_pipeline: bool = False):
    """Distributed SVD over the (p, q) mesh (src/svd.cc pipeline).

    Returns (S descending, U or None, VT or None); U/VT come back sharded.
    Wide inputs run on the conjugate transpose (U/VT swap), like the
    reference's LQ pre-step (svd.cc:224+).
    """
    from ..linalg.eig import _safe_scale
    from ..linalg.svd import _bidiag_phases, bdsqr, tb2bd, unmbr_ge2tb_factors

    m, n = A.shape[-2:]
    if min(m, n) < 8:
        out = jnp.linalg.svd(A, full_matrices=False) if want_vectors else \
            (jnp.linalg.svd(A, compute_uv=False), None, None)
        if want_vectors:
            U, S, VT = out[0], out[1], out[2]
            return S, U, VT
        return out[0], None, None
    if m < n:
        S, V, UT = svd_distributed(jnp.conj(A).T, grid, nb=nb,
                                   want_vectors=want_vectors,
                                   chase_pipeline=chase_pipeline)
        if not want_vectors:
            return S, None, None
        return S, jnp.conj(UT).T, jnp.conj(V).T
    if m >= 2 * n:
        # tall pre-step (svd.cc:224+): QR first — the reference QRs very tall
        # inputs so the bidiagonalization runs on the square R.  With vectors,
        # the 2-D CAQR tree over the mesh supplies Q, R and U = Q @ U_R is one
        # sharded gemm; values-only skips Q entirely (singular values of R ==
        # singular values of A for any QR), taking R from the sharded
        # CholeskyQR2 Gram tree.
        if not want_vectors:
            # Householder-quality R from the 1-D TSQR tree (no Gram squaring,
            # no 2-D CAQR Q accumulation)
            from .qr_dist import tsqr_distributed

            _, R = tsqr_distributed(A, grid)
            S, _, _ = svd_distributed(R[:n, :n], grid, nb=nb,
                                      want_vectors=False,
                                      chase_pipeline=chase_pipeline)
            return S, None, None
        from .qr_dist import geqrf_distributed

        Q, R = geqrf_distributed(A, grid, nb=max(nb, 32))
        S, UR, VT = svd_distributed(R[:n, :n], grid, nb=nb,
                                    want_vectors=True,
                                    chase_pipeline=chase_pipeline)
        U = jnp.matmul(Q[:, :n], UR, precision=lax.Precision.HIGHEST)
        return S, _shard(U, grid), VT
    k = n
    nb = max(2, min(nb, max(2, k - 1)))
    a, factor = _safe_scale(A)
    a = _shard(a, grid)
    band, Uf, Vf = _ge2tb_dist_fn(grid.mesh, m, n, nb, str(a.dtype))(a)
    band = jax.device_put(band, grid.replicated())
    sq = band[:k, :k]
    if k > 2:
        out = tb2bd(sq, nb, want_vectors=want_vectors,
                    pipeline=chase_pipeline)
        d, e = out[0], out[1]
        U2, VT2 = (out[2], out[3]) if want_vectors else (None, None)
    else:
        d_c = jnp.diagonal(sq)
        e_c = jnp.diagonal(sq, offset=1)
        pu, pw = _bidiag_phases(d_c, e_c, a.dtype)
        d, e = jnp.abs(d_c), jnp.abs(e_c)
        U2, VT2 = jnp.diag(pu), jnp.conj(jnp.diag(pw)).T
    S, Ub, VTb = bdsqr(d, e, want_vectors=want_vectors)
    if not want_vectors:
        return S * factor, None, None
    # U = Q_u [U2 Ub; 0],  VT = (VTb VT2) Q_v^H — sharded block-reflector gemms
    Uin = jnp.zeros((m, k), a.dtype).at[:k, :k].set(
        jnp.matmul(U2, Ub.astype(U2.dtype), precision=lax.Precision.HIGHEST))
    U = unmbr_ge2tb_factors("left", "n", Uf, _shard(Uin, grid))
    Vin = jnp.conj(jnp.matmul(VTb.astype(VT2.dtype), VT2,
                              precision=lax.Precision.HIGHEST)).T
    Vfull = unmbr_ge2tb_factors("left", "n", Vf,
                                _shard(Vin, grid, col=False))
    return S * factor, U, jnp.conj(Vfull).T


@lru_cache(maxsize=64)
def _norm_dist_fn(mesh, kind: str, uplo: str, dtype_str: str):
    spec = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))

    def fn(a):
        x = lax.with_sharding_constraint(a, spec)
        if uplo == "lower":
            x = jnp.tril(x)
        elif uplo == "upper":
            x = jnp.triu(x)
        ax = jnp.abs(x)
        if kind == "max":
            return jnp.max(ax)
        if kind == "one":
            return jnp.max(jnp.sum(ax, axis=-2))
        if kind == "inf":
            return jnp.max(jnp.sum(ax, axis=-1))
        # fro
        return jnp.sqrt(jnp.sum(ax * ax))

    return jax.jit(fn)


def norm_distributed(kind, A: jax.Array, grid: ProcessGrid,
                     uplo: str = "general"):
    """Distributed matrix norm (src/norm.cc over internal::genorm partials +
    MPI allreduce; here one sharded masked reduction — XLA emits the per-shard
    partials and the psum).  kind: max | one | inf | fro."""
    from ..core.types import Norm

    k = Norm.from_string(kind) if not isinstance(kind, Norm) else kind
    name = {Norm.Max: "max", Norm.One: "one", Norm.Inf: "inf",
            Norm.Fro: "fro"}[k]
    return _norm_dist_fn(grid.mesh, name, uplo, str(jnp.asarray(A).dtype))(A)


@lru_cache(maxsize=8)
def _col_norms_fn(mesh):
    spec = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))

    def fn(a):
        a = lax.with_sharding_constraint(a, spec)
        return jnp.max(jnp.abs(a), axis=-2)

    return jax.jit(fn)


def col_norms_distributed(A: jax.Array, grid: ProcessGrid) -> jax.Array:
    """Distributed column max-norms (internal::colNorms analogue)."""
    return _col_norms_fn(grid.mesh)(A)
