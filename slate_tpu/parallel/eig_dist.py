"""Distributed eigenvalue / SVD / norm drivers over the process grid.

Reference analogues: ``src/heev.cc:68-225`` (the longest distributed pipeline:
scale -> he2hb on the grid -> he2hbGather to rank 0 -> hb2st on rank 0 ->
sterf/steqr/stedc -> redistribute -> back-transforms), ``src/svd.cc:99-141``
(same shape via ge2tb/tb2bd/bdsqr), and the ``internal::norm`` reductions the
``norm`` driver runs over distributed tiles.

TPU re-design:

* **Stage 1 is where the flops are** (O(n^2 nb) gemms per panel, O(n^3)
  total) — it runs as an *explicit shard_map* pipeline (round-3 rewrite:
  the round-2 GSPMD form compiled sharded but replicated the loop state —
  see ``_he2hb_shard_fn``): 1-D block rows, one panel all-gather + one
  W-psum per step, the reference's listBcast collapsed into the mesh
  collectives (SURVEY.md §5.8 mapping).
* **Stage 2 is sequential by nature** (bulge chasing) and cheap (O(n^2 kd));
  the band is *replicated* across the mesh — the exact analogue of
  ``he2hbGather`` pulling the band to rank 0 (heev.cc:133-135) — and chased
  locally, like the reference runs hb2st on rank 0 only (heev.cc:137-160).
* **Back-transforms are gemms** and run sharded again (the reference
  redistributes Z to 1-D for unmtr_hb2st then back, heev.cc:193-205; here the
  resharding is one device_put).
* Norms are one jitted masked reduction with sharded input — XLA lowers the
  reduction to per-shard partials + a psum, which is ``internal::norm``'s
  partial-tile reduction + MPI allreduce.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import COL_AXIS, ProcessGrid, ROW_AXIS, shard_map
from ..obs import instrument


@lru_cache(maxsize=32)
def _constrain_fn(mesh, row_shard: bool, col_shard: bool):
    spec = NamedSharding(mesh, P(ROW_AXIS if row_shard else None,
                                 COL_AXIS if col_shard else None))
    return jax.jit(lambda a: lax.with_sharding_constraint(a, spec))


def _shard(x, grid: ProcessGrid, row: bool = True, col: bool = True):
    """Place x block-sharded on the grid via a sharding constraint —
    unlike device_put this tolerates non-divisible shapes (GSPMD pads)."""
    return _constrain_fn(grid.mesh, row, col)(x)


AX = (ROW_AXIS, COL_AXIS)                  # flattened device axis


@lru_cache(maxsize=32)
def _he2hb_shard_fn(mesh, npad: int, nb: int, dtype_str: str):
    """Explicit shard_map he2hb over the flattened mesh (src/he2hb.cc, 729
    LoC of grid QR panels + ttqrt trees + two-sided updates).

    Round-2 review finding: the old GSPMD form (`with_sharding_constraint` +
    jit around the sequential fori_loop) compiled with sharded operands but
    ran 7x *slower* on a 2x4 mesh than one device — the partitioner inserted
    per-panel resharding instead of the algorithm's natural collectives.
    This version owns the layout: 1-D block rows (columns local), the panel
    gathered once per step (O(n·nb) bytes), the replicated O(n·nb²) panel QR
    recomputed on every device (far cheaper than shipping factors), and the
    two-sided O(n²·nb) block-reflector gemms fully local except ONE psum for
    W = V^H A.  Two collectives per panel, total O(n²) bytes.
    """
    from ..linalg import householder as hh

    nprocs = mesh.shape[ROW_AXIS] * mesh.shape[COL_AXIS]
    mr = npad // nprocs
    nt = npad // nb
    nj = max(nt - 1, 0)
    prec = lax.Precision.HIGHEST

    def local_fn(A_loc):                   # (mr, npad)
        ri = lax.axis_index(AX)
        r0 = (ri * mr).astype(jnp.int32)
        grow = r0 + jnp.arange(mr, dtype=jnp.int32)
        gcol = jnp.arange(npad, dtype=jnp.int32)

        def body(j, carry):
            A_loc, Vs_loc, Ts = carry
            k0 = (j * nb).astype(jnp.int32) if hasattr(j, "astype") else j * nb
            off = k0 + nb
            P_loc = lax.dynamic_slice(A_loc, (jnp.int32(0), k0), (mr, nb))
            P_full = lax.all_gather(P_loc, AX).reshape(npad, nb)
            _, V, taus = hh.panel_qr_masked(P_full, off, nb)
            T = hh.build_T(V, taus)
            V_loc = lax.dynamic_slice(V, (r0, jnp.int32(0)), (mr, nb))
            # left apply Q^H A: W = V^H A rides the mesh's one psum
            W = lax.psum(jnp.matmul(jnp.conj(V_loc).T, A_loc, precision=prec),
                         AX)                                     # (nb, npad)
            A_loc = A_loc - jnp.matmul(
                V_loc, jnp.matmul(jnp.conj(T).T, W, precision=prec),
                precision=prec)
            # right apply (Q^H A) Q: V replicated => fully local gemms
            Y = jnp.matmul(A_loc, V, precision=prec)             # (mr, nb)
            A_loc = A_loc - jnp.matmul(jnp.matmul(Y, T, precision=prec),
                                       jnp.conj(V).T, precision=prec)
            Vs_loc = lax.dynamic_update_slice(Vs_loc, V_loc[None], (j, 0, 0))
            Ts = lax.dynamic_update_slice(Ts, T[None], (j, 0, 0))
            return A_loc, Vs_loc, Ts

        Vs0 = jnp.zeros((max(nj, 1), mr, nb), A_loc.dtype)
        Ts0 = jnp.zeros((max(nj, 1), nb, nb), A_loc.dtype)
        A_loc, Vs_loc, Ts = lax.fori_loop(0, nj, body, (A_loc, Vs0, Ts0))
        band_loc = jnp.where(
            jnp.abs(grow[:, None] - gcol[None, :]) <= nb, A_loc,
            jnp.zeros_like(A_loc))
        return band_loc, Vs_loc, Ts

    fn = shard_map(local_fn, mesh=mesh, in_specs=P(AX, None),
                       out_specs=(P(AX, None), P(None, AX, None), P(None)),
                       check_vma=False)
    return jax.jit(fn)


@lru_cache(maxsize=32)
def _unmtr_he2hb_shard_fn(mesh, npad: int, ncols: int, nb: int, nj: int,
                          descending: bool, conj_q: bool, dtype_str: str):
    """Left-side stage-1 back-transform on the sharded reflector stack
    (src/unmtr_he2hb.cc): per block one psum for W = V^H C, the rest local."""
    nprocs = mesh.shape[ROW_AXIS] * mesh.shape[COL_AXIS]
    mr = npad // nprocs
    prec = lax.Precision.HIGHEST

    def local_fn(Vs_loc, Ts, C_loc):       # (nj, mr, nb), (nj, nb, nb), (mr, ncols)
        def body(jj, C_loc):
            j = nj - 1 - jj if descending else jj
            V_loc = lax.dynamic_index_in_dim(Vs_loc, j, 0, keepdims=False)
            T = lax.dynamic_index_in_dim(Ts, j, 0, keepdims=False)
            Tm = jnp.conj(T).T if conj_q else T
            W = lax.psum(jnp.matmul(jnp.conj(V_loc).T, C_loc, precision=prec),
                         AX)
            return C_loc - jnp.matmul(V_loc,
                                      jnp.matmul(Tm, W, precision=prec),
                                      precision=prec)

        return lax.fori_loop(0, nj, body, C_loc)

    fn = shard_map(local_fn, mesh=mesh,
                       in_specs=(P(None, AX, None), P(None), P(AX, None)),
                       out_specs=P(AX, None), check_vma=False)
    return jax.jit(fn)


@instrument
def he2hb_distributed(A: jax.Array, grid: ProcessGrid, nb: int = 64):
    """Distributed stage-1 band reduction A = Q band Q^H over the flattened
    mesh.  Returns ``(band, Vs, Ts)``: band (n, n) bandwidth-nb, Vs sharded
    (nj, n, nb) reflector rows, Ts (nj, nb, nb) replicated."""
    from .distribute import ceil_mult

    n = A.shape[-1]
    nprocs = grid.p * grid.q
    npad = ceil_mult(n, nb * nprocs)
    if npad > n:
        Ap = jnp.zeros((npad, npad), A.dtype)
        Ap = Ap.at[:n, :n].set(A)
        idx = jnp.arange(n, npad)
        Ap = Ap.at[idx, idx].set(1)
    else:
        Ap = A
    Ap = jax.device_put(Ap, NamedSharding(grid.mesh, P(AX, None)))
    band, Vs, Ts = _he2hb_shard_fn(grid.mesh, npad, nb, str(Ap.dtype))(Ap)
    return band[:n, :n], Vs, Ts


@instrument
def unmtr_he2hb_distributed(Vs: jax.Array, Ts: jax.Array, C: jax.Array,
                            grid: ProcessGrid, conj_q: bool = False):
    """Apply the stage-1 Q (NoTrans, left) from the sharded reflector stack to
    a row-sharded C: Q C = H_0 ... H_{nj-1} C applied descending (conj_q
    flips to ascending Q^H C)."""
    nj, npad, nb = Vs.shape
    n, ncols = C.shape[-2:]
    if npad > n:
        Cp = jnp.zeros((npad, ncols), C.dtype).at[:n].set(C)
    else:
        Cp = C
    Cp = jax.device_put(Cp, NamedSharding(grid.mesh, P(AX, None)))
    out = _unmtr_he2hb_shard_fn(grid.mesh, npad, ncols, nb, nj,
                                not conj_q, conj_q, str(Cp.dtype))(Vs, Ts, Cp)
    return out[:n]


def _twostage_stage12(A, grid: ProcessGrid, nb: int,
                      chase_pipeline: bool, chase_distributed: bool,
                      want_tape: bool):
    """Shared two-stage prologue for the distributed eig drivers: nb clamps,
    safe scaling, sharded stage 1, band replication, and the chase (with the
    segment-parallel eligibility floor applied in ONE place so the full and
    subset drivers cannot diverge).

    Returns the 8-tuple ``(d, e_c, Vcs, tcs, Vs1, Ts1, factor, nb_eff)`` —
    ``nb_eff`` is the clamped bandwidth the caller must reuse for the
    back-transforms.  With ``want_tape=False`` the reflector tape entries
    (``Vcs``, ``tcs``) are None and ``e_c`` is already the real ``|e|``."""
    from ..linalg.eig import _safe_scale, hb2st, hb2st_reflectors

    n = A.shape[-1]
    nb = max(2, min(nb, max(2, n // 2)))
    # clamp against the nb·nprocs padding granularity: pad stays ≤ ~n/4, so
    # the O(n²·nb) stage-1 gemms never run on a matrix 2× the real linear
    # size for unaligned n (the chase below uses the same clamped kd)
    nprocs = grid.p * grid.q
    if n >= 8 * nprocs:
        nb = max(2, min(nb, -(-n // (4 * nprocs))))
    a, factor = _safe_scale(A)
    # stage 1 on the mesh: explicit shard_map panel pipeline (he2hb.cc)
    band, Vs1, Ts1 = he2hb_distributed(a, grid, nb=nb)
    # he2hbGather analogue: replicate the (cheap) band for the local chase
    band = jax.device_put(band, grid.replicated())
    nband = band.shape[-1]
    use_dist_chase = (chase_distributed and nb >= 2 and nband > 2
                      and -(-nband // nprocs) >= 2 * nb + 2)
    if use_dist_chase:
        from .chase_dist import hb2st_chase_distributed

        d, e_c, Vcs, tcs = hb2st_chase_distributed(band, nb, grid,
                                                   want_vectors=want_tape)
    elif want_tape:
        d, e_c, Vcs, tcs = hb2st_reflectors(band, kd=nb,
                                            pipeline=chase_pipeline)
    else:
        # hb2st already returns the real |e|; jnp.abs below is a no-op
        d, e_c = hb2st(band, kd=nb, want_vectors=False,
                       pipeline=chase_pipeline)
        Vcs = tcs = None
    # single exit: the tape-less form drops the reflectors and realizes |e|
    if not want_tape:
        return d, jnp.abs(e_c), None, None, Vs1, Ts1, factor, nb
    return d, e_c, Vcs, tcs, Vs1, Ts1, factor, nb


@instrument
def heev_range_distributed(A: jax.Array, grid: ProcessGrid, il: int, iu: int,
                           nb: int = 64, want_vectors: bool = True,
                           chase_pipeline: bool = False,
                           chase_distributed: bool = False):
    """Distributed subset eigensolve: the k = iu-il eigenpairs with ascending
    indices [il, iu) over the mesh (no reference analogue at any scale).

    Stage 1 (the O(n²·nb) flops) runs sharded (he2hb_distributed); the
    chase runs replicated or segment-parallel per ``chase_distributed``;
    the subset tridiagonal work is O(n·k) bisection + stein; the chase
    back-transform applies Q2 to the THIN (n, k) block via the reverse
    sweep accumulation (replicated — O(n²·k/b) total, small next to stage
    1); and the stage-1 back-transform rides the mesh
    (unmtr_he2hb_distributed on k columns, one psum per block).
    Returns (lam (k,), Z (n, k) row-sharded or None).
    """
    from ..core.exceptions import slate_assert
    from ..linalg.eig import _phase_vector
    from ..linalg.householder import sweep_accumulate
    from ..linalg.sturm import stein, sterf_bisect

    n = A.shape[-1]
    slate_assert(0 <= il < iu <= n,
                 f"index range [{il}, {iu}) invalid for n={n}")
    if n < 8:
        lam, z = jnp.linalg.eigh(A)
        return (lam[il:iu], z[:, il:iu]) if want_vectors \
            else (lam[il:iu], None)
    if not want_vectors:
        d, e, _, _, _, _, factor, _ = _twostage_stage12(
            A, grid, nb, chase_pipeline, chase_distributed, want_tape=False)
        lam = sterf_bisect(d, e, il=il, iu=iu)
        return lam * factor, None
    d, e_c, Vcs, tcs, Vs1, Ts1, factor, nb_eff = _twostage_stage12(
        A, grid, nb, chase_pipeline, chase_distributed, want_tape=True)
    e = jnp.abs(e_c)
    lam = sterf_bisect(d, e, il=il, iu=iu)
    dt = Vcs.dtype
    Zt = stein(d, e, lam).astype(dt)
    ph = _phase_vector(e_c.astype(dt))
    X = ph[:, None] * Zt
    nband = d.shape[0]
    z = jnp.conj(sweep_accumulate(Vcs, tcs, nband, nb_eff,
                                  Q0=jnp.conj(X).T, reverse=True)).T
    z = unmtr_he2hb_distributed(Vs1, Ts1, z[:n], grid, conj_q=False)
    return lam * factor, z


@lru_cache(maxsize=32)
def _ge2tb_shard_fn(mesh, mpad: int, npc: int, nreal: int, nb: int,
                    dtype_str: str):
    """Explicit shard_map ge2tb band reduction (src/ge2tb.cc): alternating
    QR column panels (left apply — one all-gather + one psum, like
    ``_he2hb_shard_fn``) and LQ row panels, whose right applies are FULLY
    local in the 1-D row layout (columns resident; only the nb-row panel
    extraction psums).  Three O(n·nb)-byte collectives per panel."""
    from ..linalg import householder as hh

    nprocs = mesh.shape[ROW_AXIS] * mesh.shape[COL_AXIS]
    mr = mpad // nprocs
    ncv = npc // nprocs                    # Vv rows live sharded too
    nt = max(-(-nreal // nb), 1)
    prec = lax.Precision.HIGHEST

    def local_fn(A_loc):                   # (mr, npc)
        ri = lax.axis_index(AX)
        r0 = (ri * mr).astype(jnp.int32)
        grow = r0 + jnp.arange(mr, dtype=jnp.int32)

        def body(j, carry):
            A_loc, Vu_loc, Tu, Vv, Tv = carry
            k0 = (j * nb).astype(jnp.int32) if hasattr(j, "astype") else j * nb
            # --- QR column panel (pivots on the diagonal)
            P_loc = lax.dynamic_slice(A_loc, (jnp.int32(0), k0), (mr, nb))
            P_full = lax.all_gather(P_loc, AX).reshape(mpad, nb)
            _, V, taus = hh.panel_qr_masked(P_full, k0, nb)
            T = hh.build_T(V, taus)
            V_loc = lax.dynamic_slice(V, (r0, jnp.int32(0)), (mr, nb))
            W = lax.psum(jnp.matmul(jnp.conj(V_loc).T, A_loc, precision=prec),
                         AX)                                     # (nb, npc)
            A_loc = A_loc - jnp.matmul(
                V_loc, jnp.matmul(jnp.conj(T).T, W, precision=prec),
                precision=prec)
            Vu_loc = lax.dynamic_update_slice(Vu_loc, V_loc[None], (j, 0, 0))
            Tu = lax.dynamic_update_slice(Tu, T[None], (j, 0, 0))
            # --- LQ row panel (pivots one block right): extract nb rows
            S = k0 + jnp.arange(nb, dtype=jnp.int32)
            loc = S - r0
            own = (loc >= 0) & (loc < mr)
            Prow = A_loc[jnp.clip(loc, 0, mr - 1)]
            Prow = jnp.where(own[:, None], Prow, jnp.zeros_like(Prow))
            Prow = lax.psum(Prow, AX)                            # (nb, npc)
            _, Vr, tausr = hh.panel_lq_masked(Prow, k0 + nb, nb)
            Tr = hh.build_T(Vr, tausr)
            # right apply: columns are local => zero collectives
            Y = jnp.matmul(A_loc, Vr, precision=prec)            # (mr, nb)
            A_loc = A_loc - jnp.matmul(jnp.matmul(Y, Tr, precision=prec),
                                       jnp.conj(Vr).T, precision=prec)
            Vr_loc = lax.dynamic_slice(Vr, ((ri * ncv).astype(jnp.int32),
                                            jnp.int32(0)), (ncv, nb))
            Vv = lax.dynamic_update_slice(Vv, Vr_loc[None], (j, 0, 0))
            Tv = lax.dynamic_update_slice(Tv, Tr[None], (j, 0, 0))
            return A_loc, Vu_loc, Tu, Vv, Tv

        Vu0 = jnp.zeros((nt, mr, nb), A_loc.dtype)
        Tu0 = jnp.zeros((nt, nb, nb), A_loc.dtype)
        Vv0 = jnp.zeros((nt, ncv, nb), A_loc.dtype)
        Tv0 = jnp.zeros((nt, nb, nb), A_loc.dtype)
        A_loc, Vu_loc, Tu, Vv, Tv = lax.fori_loop(
            0, nt, body, (A_loc, Vu0, Tu0, Vv0, Tv0))
        gcol = jnp.arange(npc, dtype=jnp.int32)
        band_loc = jnp.where(
            (gcol[None, :] >= grow[:, None])
            & (gcol[None, :] - grow[:, None] <= nb), A_loc,
            jnp.zeros_like(A_loc))
        return band_loc, Vu_loc, Tu, Vv, Tv

    fn = shard_map(
        local_fn, mesh=mesh, in_specs=P(AX, None),
        out_specs=(P(AX, None), P(None, AX, None), P(None),
                   P(None, AX, None), P(None)),
        check_vma=False)
    return jax.jit(fn)


def _apply_stacked_left(Vs: jax.Array, Ts: jax.Array, C: jax.Array,
                        grid: ProcessGrid, conj_q: bool = False):
    """Left-apply a stacked block-reflector factor through the sharded unmtr
    sweep regardless of how Vs arrived (sharded from he2hb/ge2tb, or
    replicated like the right-side Vv): rows pad to a mesh-divisible count
    (zero reflector rows act as identity) and reshard in one device_put."""
    from .distribute import ceil_mult

    nj, nv, nb = Vs.shape
    nprocs = grid.p * grid.q
    nvp = ceil_mult(nv, nprocs)
    if nvp > nv:
        Vs = jnp.concatenate(
            [Vs, jnp.zeros((nj, nvp - nv, nb), Vs.dtype)], axis=1)
    Vs = jax.device_put(Vs, NamedSharding(grid.mesh, P(None, AX, None)))
    return unmtr_he2hb_distributed(Vs, Ts, C, grid, conj_q=conj_q)


@instrument
def ge2tb_distributed(A: jax.Array, grid: ProcessGrid, nb: int = 64):
    """Distributed stage-1 general->band reduction A = U band V^H over the
    flattened mesh.  Returns ``(band, (Vu, Tu), (Vv, Tv))``: band (m, n)
    upper-bandwidth nb, Vu sharded reflector rows, Vv replicated (applied
    from the right — columns are local in this layout)."""
    from ..core.exceptions import slate_assert
    from .distribute import ceil_mult

    m, n = A.shape[-2:]
    slate_assert(m >= n, "ge2tb_distributed requires m >= n")
    nprocs = grid.p * grid.q
    mpad = ceil_mult(m + nb, nb * nprocs)
    # pad so the last panel never clamps AND Vv rows shard evenly; reflector
    # entries on pad columns are exactly zero (the padded A columns are), so
    # keeping the full npc rows loses nothing and stays sharded
    npc = ceil_mult(n + nb, nprocs)
    Ap = jnp.zeros((mpad, npc), A.dtype).at[:m, :n].set(A)
    Ap = jax.device_put(Ap, NamedSharding(grid.mesh, P(AX, None)))
    band, Vu, Tu, Vv, Tv = _ge2tb_shard_fn(grid.mesh, mpad, npc, n, nb,
                                           str(Ap.dtype))(Ap)
    return band[:m, :n], (Vu, Tu), (Vv, Tv)


@instrument
def heev_distributed(A: jax.Array, grid: ProcessGrid, nb: int = 64,
                     want_vectors: bool = True, method_eig: str = "dc",
                     chase_pipeline: bool = False,
                     chase_distributed: bool = False):
    """Distributed Hermitian eigensolve over the (p, q) mesh (src/heev.cc).

    Returns (ascending eigenvalues, Z or None); Z comes back sharded on the
    grid.  ``method_eig='dc'`` solves the tridiagonal with stedc.

    ``chase_distributed=True`` runs stage 2 segment-parallel over the mesh
    (parallel/chase_dist.py) instead of replicating the band chase on every
    device — past the reference, which confines hb2st to rank 0
    (heev.cc:137-160).  Requires n/P >= 2*nb+2 (falls back to the
    replicated chase below that floor).
    """
    from ..linalg.eig import sterf
    from ..linalg.stedc import stedc as _stedc

    n = A.shape[-1]
    if n < 8:
        # no meaningful band structure below one panel — local fused solve
        # (the single-device heev makes the same switch)
        lam, z = (jnp.linalg.eigh(A) if want_vectors
                  else (jnp.linalg.eigvalsh(A), None))
        return lam, z
    if not want_vectors:
        d, e, _, _, _, _, factor, _ = _twostage_stage12(
            A, grid, nb, chase_pipeline, chase_distributed, want_tape=False)
        # values-only always takes sterf — D&C inherently carries vectors
        # (merge z-couplings ARE eigenvector rows), exactly why the reference
        # routes no-vector solves to sterf too (heev.cc:208-215)
        lam = sterf(d, e)
        return lam * factor, None
    # vectors: the chase tape is the cheap O(n² kd) part and replays
    # replicated; the Q2 accumulation — 97% of the profiled vectors time —
    # shards over mesh rows with zero collectives (round-5; was replicated)
    d, e_c, Vcs, tcs, Vs, Ts, factor, nb = _twostage_stage12(
        A, grid, nb, chase_pipeline, chase_distributed, want_tape=True)
    e = jnp.abs(e_c)
    Q2 = hb2st_q_distributed(Vcs, tcs, e_c, d.shape[0], grid)
    if method_eig == "bisection":
        # bisection values + batched inverse-iteration vectors (the method
        # the reference leaves unimplemented, enums.hh:363); the vmapped
        # tridiagonal solves replay replicated — they are O(n²) like the
        # chase — and the back-transforms below ride the mesh
        from ..linalg.sturm import stein, sterf_bisect

        lam = sterf_bisect(d, e)
        Zt = stein(d, e, lam)
    elif method_eig == "dc":
        # distributed D&C: the merge basis-update gemms ride the mesh
        lam, Zt = _stedc(d, e, grid=grid)
    else:
        # MethodEig.QR: real QR iteration with the Z update sharded over
        # mesh rows (steqr.cc's 1-D redistribute + local-row rotations)
        lam, Zt = steqr_distributed(d, e, grid)
    # chase back-transform is the same O(n³) order as the merges — it rides
    # the mesh too rather than replicating on every device
    from .summa import gemm_padded

    Z = gemm_padded(Q2, Zt.astype(Q2.dtype), grid)
    # stage-1 back-transform on the sharded reflector stack (one psum per
    # block; unmtr_he2hb.cc)
    Z = unmtr_he2hb_distributed(Vs, Ts, Z, grid, conj_q=False)
    return lam * factor, Z


@instrument
def svd_range_distributed(A: jax.Array, grid: ProcessGrid, il: int, iu: int,
                          nb: int = 64, want_vectors: bool = True,
                          chase_pipeline: bool = False,
                          chase_distributed: bool = False):
    """Distributed top-k/subset SVD: the singular triplets with DESCENDING
    indices [il, iu) over the mesh (no reference analogue at any scale).

    Sharded ge2tb stage 1, tb2bd chase (replicated or segment-parallel),
    index-targeted GK bisection (only the 2j target indices of the ±σ
    spectrum), stein vectors, thin reverse-accumulated chase
    back-transforms, and thin mesh stage-1 back-transforms.  Returns
    (S (j,) descending, U (m, j) or None, VT (j, n) or None).
    """
    from ..core.exceptions import slate_assert
    from ..linalg.eig import _safe_scale
    from ..linalg.householder import sweep_accumulate
    from ..linalg.sturm import stein, sterf_bisect
    from ..linalg.svd import (_bidiag_phases, _gk_form, _gk_split,
                              _tb2bd_run_chase, tb2bd_reflectors)

    m, n = A.shape[-2:]
    if m < n:
        S, V, UT = svd_range_distributed(jnp.conj(A).T, grid, il, iu, nb=nb,
                                         want_vectors=want_vectors,
                                         chase_pipeline=chase_pipeline,
                                         chase_distributed=chase_distributed)
        if not want_vectors:
            return S, None, None
        return S, jnp.conj(UT).T, jnp.conj(V).T
    k = n
    slate_assert(0 <= il < iu <= k,
                 f"index range [{il}, {iu}) invalid for min(m,n)={k}")
    j = iu - il
    if k < 8:
        if want_vectors:
            out = jnp.linalg.svd(A, full_matrices=False)
            return out[1][il:iu], out[0][:, il:iu], out[2][il:iu, :]
        return jnp.linalg.svd(A, compute_uv=False)[il:iu], None, None
    nb = max(2, min(nb, max(2, k - 1)))
    nprocs = grid.p * grid.q
    if k >= 8 * nprocs:
        nb = max(2, min(nb, -(-k // (4 * nprocs))))
    a, factor = _safe_scale(A)
    band, Uf, Vf = ge2tb_distributed(a, grid, nb=nb)
    band = jax.device_put(band, grid.replicated())
    sq = band[:k, :k]
    use_dist_chase = (chase_distributed and nb >= 2 and k > 2
                      and -(-k // nprocs) >= 2 * nb + 2)
    if want_vectors:
        if use_dist_chase:
            from .chase_dist import tb2bd_chase_distributed

            d_c, e_c, Us, tauus, Vcs, tauvs = tb2bd_chase_distributed(
                sq, nb, grid, want_vectors=True)
        else:
            d_c, e_c, Us, tauus, Vcs, tauvs = tb2bd_reflectors(
                sq, nb, pipeline=chase_pipeline)
    else:
        if use_dist_chase:
            from .chase_dist import tb2bd_chase_distributed

            d_c, e_c, *_ = tb2bd_chase_distributed(sq, nb, grid,
                                                   want_vectors=False)
        else:
            d_c, e_c, *_ = _tb2bd_run_chase(sq, nb, chase_pipeline)
    d, e = jnp.abs(d_c), jnp.abs(e_c)
    zero_d, tgk_off = _gk_form(d, e)
    lam_desc = sterf_bisect(zero_d, tgk_off,
                            il=2 * k - iu, iu=2 * k - il)[::-1]
    sig = jnp.maximum(lam_desc, 0.0)
    if not want_vectors:
        return sig * factor, None, None
    Z = stein(zero_d, tgk_off, lam_desc)
    U2t, V2t = _gk_split(Z, sq.dtype)
    pu, pw = _bidiag_phases(d_c, e_c, sq.dtype)
    Xu = pu[:, None] * U2t
    Xv = pw[:, None] * V2t
    Uu = jnp.conj(sweep_accumulate(Us, tauus, k, nb,
                                   Q0=jnp.conj(Xu).T, reverse=True)).T
    Vv = jnp.conj(sweep_accumulate(Vcs, tauvs, k, nb,
                                   Q0=jnp.conj(Xv).T, reverse=True)).T
    U = jnp.zeros((m, j), sq.dtype).at[:k, :].set(Uu)
    U = _apply_stacked_left(Uf[0], Uf[1], U, grid)
    Vfull = jnp.zeros((n, j), sq.dtype).at[:k, :].set(Vv)
    Vfull = _apply_stacked_left(Vf[0], Vf[1], Vfull, grid)
    return sig * factor, U, jnp.conj(Vfull).T


@lru_cache(maxsize=16)
def _hb2st_q_shard_fn(mesh, n: int, npad: int):
    """Row-sharded chase-vectors accumulation (the ~97%-of-time phase of the
    distributed two-stage vectors path, PERF_CPU.md): the reflector tape
    (Vs, taus) is replicated — it is the cheap O(n²) part — and each device
    accumulates its own row block of Q2 via ``sweep_accumulate(Q0=rows)``,
    building its identity block locally from iota (no host-side O(n²) eye
    is ever materialized).  Every update is a column operation, so the
    module contains ZERO collectives; the reference reaches the same shape
    by redistributing Z to 1-D rows for unmtr_hb2st (heev.cc:193-205)."""
    from ..linalg.householder import sweep_accumulate

    nproc = mesh.size
    rl = npad // nproc

    def local_fn(Vs, taus, phase):
        row0 = lax.axis_index(AX).astype(jnp.int32) * rl
        rows = row0 + lax.broadcasted_iota(jnp.int32, (rl, n), 0)
        cols = lax.broadcasted_iota(jnp.int32, (rl, n), 1)
        q0 = (rows == cols).astype(Vs.dtype)
        q = sweep_accumulate(Vs, taus, n, Vs.shape[-1], Q0=q0)
        return q * phase[None, :]

    fn = shard_map(local_fn, mesh=mesh,
                       in_specs=(P(None), P(None), P(None)),
                       out_specs=P(AX, None), check_vma=False)
    return jax.jit(fn)


def _sweep_q_distributed(Vs, taus, phase, n: int, grid: ProcessGrid):
    """Row-sharded sweep accumulation with a column-phase postmultiply —
    shared by the hb2st Q2 and the tb2bd U2/V2 builds."""
    nproc = grid.p * grid.q
    npad = -(-n // nproc) * nproc
    Q = _hb2st_q_shard_fn(grid.mesh, n, npad)(Vs, taus,
                                              phase.astype(Vs.dtype))
    return Q[:n]


@instrument
def hb2st_q_distributed(Vs, taus, e_c, n: int, grid: ProcessGrid):
    """Q2 of the hb2st chase, rows sharded on the flattened mesh."""
    from ..linalg.eig import _phase_vector

    return _sweep_q_distributed(Vs, taus, _phase_vector(e_c.astype(Vs.dtype)),
                                n, grid)


@lru_cache(maxsize=16)
def _steqr_shard_fn(mesh):
    """Row-sharded tridiagonal QR iteration (src/steqr.cc:52-82).

    The reference redistributes Z into a 1-D row layout, every rank runs the
    identical host QR iteration on the replicated (D, E) scalars, and each
    rank applies the plane rotations to its local rows only.  Here: the
    (d, e) while_loop replays identically on every device inside shard_map
    (deterministic, so every shard sees the same rotation chain) and each
    device absorbs each sweep into its (npad/nproc, n) row block with a
    local MXU gemm.  The compiled module contains ZERO collectives — row
    parallelism is the whole story, exactly the reference's design point.
    """
    from ..linalg.steqr_qr import steqr_qr

    def local_fn(d, e, z_loc):
        return steqr_qr(d, e, z_loc)

    fn = shard_map(local_fn, mesh=mesh,
                       in_specs=(P(None), P(None), P(AX, None)),
                       out_specs=(P(None), P(AX, None)), check_vma=False)
    return jax.jit(fn)


@instrument
def steqr_distributed(d, e, grid: ProcessGrid, Z=None):
    """Distributed steqr: eigenvalues replicated, eigenvector matrix returned
    row-sharded on the flattened mesh.  ``Z`` (optional) is the matrix to
    accumulate into (defaults to identity, yielding Q itself)."""
    d = jnp.asarray(d)
    n = d.shape[0]
    nproc = grid.p * grid.q
    Z0 = jnp.eye(n, dtype=d.dtype) if Z is None else jnp.asarray(Z)
    m = Z0.shape[0]
    npad = -(-m // nproc) * nproc
    if npad != m:
        Z0 = jnp.pad(Z0, ((0, npad - m), (0, 0)))
    lam, Zo = _steqr_shard_fn(grid.mesh)(d, jnp.asarray(e), Z0)
    return lam, Zo[:m]


@instrument
def hegv_distributed(itype: int, A: jax.Array, B: jax.Array,
                     grid: ProcessGrid, nb: int = 64,
                     want_vectors: bool = True):
    """Distributed generalized Hermitian eigensolve A x = lambda B x
    (src/hegv.cc over the mesh): sharded potrf(B) -> hegst transform (sharded
    triangular solves / gemms) -> heev_distributed -> sharded back-transform.

    Returns (ascending eigenvalues, X or None).
    """
    from ..core.exceptions import SlateError
    from ..linalg.eig import hegst
    from .solvers import potrf_distributed, trsm_distributed

    L = potrf_distributed(B, grid, nb=max(nb, 32))
    # SPD verdict stays traced until the END: the whole pipeline (transform,
    # eigensolve, back-transform — all bounded loops, NaN-safe) dispatches
    # with a single host sync, instead of blocking on L's diagonal up front
    spd_ok = jnp.all(jnp.isfinite(jnp.diagonal(L)))
    C = hegst(itype, _shard(A, grid), L)
    lam, Z = heev_distributed(C, grid, nb=nb, want_vectors=want_vectors)
    if want_vectors:
        if itype in (1, 2):
            X = trsm_distributed(L, Z, grid, lower=True, conj_trans=True)
        else:
            X = jnp.matmul(jnp.tril(L), Z, precision=lax.Precision.HIGHEST)
    else:
        X = None
    if not bool(spd_ok):                  # the solve's single host sync
        raise SlateError("hegv_distributed: B not positive definite")
    return lam, X


@instrument
def svd_distributed(A: jax.Array, grid: ProcessGrid, nb: int = 64,
                    want_vectors: bool = True, chase_pipeline: bool = False,
                    method_svd: str = "auto",
                    chase_distributed: bool = False):
    """Distributed SVD over the (p, q) mesh (src/svd.cc pipeline).

    Returns (S descending, U or None, VT or None); U/VT come back sharded.
    Wide inputs run on the conjugate transpose (U/VT swap), like the
    reference's LQ pre-step (svd.cc:224+).  ``method_svd='bisection'``
    solves the bidiagonal stage by GK bisection (+ stein vectors).
    ``chase_distributed=True`` runs the tb2bd stage segment-parallel over
    the mesh (parallel/chase_dist.py) instead of replicating it; requires
    n/P >= 2*nb+2 (falls back to the replicated chase below that floor).
    """
    from ..linalg.eig import _safe_scale
    from ..linalg.svd import _bidiag_phases, bdsqr, tb2bd

    m, n = A.shape[-2:]
    if min(m, n) < 8:
        out = jnp.linalg.svd(A, full_matrices=False) if want_vectors else \
            (jnp.linalg.svd(A, compute_uv=False), None, None)
        if want_vectors:
            U, S, VT = out[0], out[1], out[2]
            return S, U, VT
        return out[0], None, None
    if m < n:
        S, V, UT = svd_distributed(jnp.conj(A).T, grid, nb=nb,
                                   want_vectors=want_vectors,
                                   chase_pipeline=chase_pipeline,
                                   method_svd=method_svd,
                                   chase_distributed=chase_distributed)
        if not want_vectors:
            return S, None, None
        return S, jnp.conj(UT).T, jnp.conj(V).T
    if m >= 2 * n:
        # tall pre-step (svd.cc:224+): QR first — the reference QRs very tall
        # inputs so the bidiagonalization runs on the square R.  With vectors,
        # the 2-D CAQR tree over the mesh supplies Q, R and U = Q @ U_R is one
        # sharded gemm; values-only skips Q entirely (singular values of R ==
        # singular values of A for any QR), taking R from the sharded
        # CholeskyQR2 Gram tree.
        if not want_vectors:
            # Householder-quality R from the 1-D TSQR tree (no Gram squaring,
            # no 2-D CAQR Q accumulation)
            from .qr_dist import tsqr_distributed

            _, R = tsqr_distributed(A, grid)
            S, _, _ = svd_distributed(R[:n, :n], grid, nb=nb,
                                      want_vectors=False,
                                      chase_pipeline=chase_pipeline,
                                      method_svd=method_svd,
                                      chase_distributed=chase_distributed)
            return S, None, None
        from .qr_dist import geqrf_distributed

        Q, R = geqrf_distributed(A, grid, nb=max(nb, 32))
        S, UR, VT = svd_distributed(R[:n, :n], grid, nb=nb,
                                    want_vectors=True,
                                    chase_pipeline=chase_pipeline,
                                    method_svd=method_svd,
                                    chase_distributed=chase_distributed)
        U = jnp.matmul(Q[:, :n], UR, precision=lax.Precision.HIGHEST)
        return S, _shard(U, grid), VT
    k = n
    nb = max(2, min(nb, max(2, k - 1)))
    # same padding-granularity clamp as heev_distributed
    nprocs = grid.p * grid.q
    if k >= 8 * nprocs:
        nb = max(2, min(nb, -(-k // (4 * nprocs))))
    a, factor = _safe_scale(A)
    # stage 1 on the mesh: explicit shard_map panel pipeline (ge2tb.cc)
    band, Uf, Vf = ge2tb_distributed(a, grid, nb=nb)
    band = jax.device_put(band, grid.replicated())
    sq = band[:k, :k]
    use_dist_chase = (chase_distributed and nb >= 2 and k > 2
                      and -(-k // (grid.p * grid.q)) >= 2 * nb + 2)
    if use_dist_chase:
        from .chase_dist import tb2bd_chase_distributed
    if k > 2 and want_vectors:
        # reflector-level chase (replicated, the cheap part) + BOTH vector
        # accumulations sharded over mesh rows with zero collectives
        # (round 5 — the same 97%-phase split as the heev chase)
        from ..linalg.svd import _bidiag_phases as _phases
        from ..linalg.svd import tb2bd_reflectors

        if use_dist_chase:
            d_c, e_c, Us, tauus, Vcs, tauvs = tb2bd_chase_distributed(
                sq, nb, grid, want_vectors=True)
        else:
            d_c, e_c, Us, tauus, Vcs, tauvs = tb2bd_reflectors(
                sq, nb, pipeline=chase_pipeline)
        pu, pw = _phases(d_c, e_c, a.dtype)
        d, e = jnp.abs(d_c), jnp.abs(e_c)
        U2 = _sweep_q_distributed(Us, tauus, pu, k, grid)
        V2 = _sweep_q_distributed(Vcs, tauvs, pw, k, grid)
        VT2 = jnp.conj(V2).T
    elif k > 2:
        if use_dist_chase:
            d_c, e_c, _, _, _, _ = tb2bd_chase_distributed(
                sq, nb, grid, want_vectors=False)
            d, e = jnp.abs(d_c), jnp.abs(e_c)
        else:
            out = tb2bd(sq, nb, want_vectors=False,
                        pipeline=chase_pipeline)
            d, e = out[0], out[1]
        U2, VT2 = None, None
    else:
        d_c = jnp.diagonal(sq)
        e_c = jnp.diagonal(sq, offset=1)
        pu, pw = _bidiag_phases(d_c, e_c, a.dtype)
        d, e = jnp.abs(d_c), jnp.abs(e_c)
        U2, VT2 = jnp.diag(pu), jnp.conj(jnp.diag(pw)).T
    bd_method = {"bisection": "bisect", "dc": "dense"}.get(method_svd, "auto")
    S, Ub, VTb = bdsqr(d, e, want_vectors=want_vectors, method=bd_method)
    if not want_vectors:
        return S * factor, None, None
    # U = Q_u [U2 Ub; 0],  VT = (VTb VT2) Q_v^H — sharded reflector sweeps
    # (one psum per block, _unmtr_he2hb_shard_fn)
    Uin = jnp.zeros((m, k), a.dtype).at[:k, :k].set(
        jnp.matmul(U2, Ub.astype(U2.dtype), precision=lax.Precision.HIGHEST))
    U = _apply_stacked_left(Uf[0], Uf[1], Uin, grid)
    Vin = jnp.conj(jnp.matmul(VTb.astype(VT2.dtype), VT2,
                              precision=lax.Precision.HIGHEST)).T
    Vfull = _apply_stacked_left(Vf[0], Vf[1], Vin, grid)
    return S * factor, U, jnp.conj(Vfull).T


@lru_cache(maxsize=64)
def _norm_dist_fn(mesh, kind: str, uplo: str, dtype_str: str):
    spec = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))

    def fn(a):
        x = lax.with_sharding_constraint(a, spec)
        if uplo == "lower":
            x = jnp.tril(x)
        elif uplo == "upper":
            x = jnp.triu(x)
        ax = jnp.abs(x)
        if kind == "max":
            return jnp.max(ax)
        if kind == "one":
            return jnp.max(jnp.sum(ax, axis=-2))
        if kind == "inf":
            return jnp.max(jnp.sum(ax, axis=-1))
        # fro
        return jnp.sqrt(jnp.sum(ax * ax))

    return jax.jit(fn)


@instrument
def norm_distributed(kind, A: jax.Array, grid: ProcessGrid,
                     uplo: str = "general"):
    """Distributed matrix norm (src/norm.cc over internal::genorm partials +
    MPI allreduce; here one sharded masked reduction — XLA emits the per-shard
    partials and the psum).  kind: max | one | inf | fro."""
    from ..core.types import Norm

    k = Norm.from_string(kind) if not isinstance(kind, Norm) else kind
    name = {Norm.Max: "max", Norm.One: "one", Norm.Inf: "inf",
            Norm.Fro: "fro"}[k]
    return _norm_dist_fn(grid.mesh, name, uplo, str(jnp.asarray(A).dtype))(A)


@lru_cache(maxsize=8)
def _col_norms_fn(mesh):
    spec = NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))

    def fn(a):
        a = lax.with_sharding_constraint(a, spec)
        return jnp.max(jnp.abs(a), axis=-2)

    return jax.jit(fn)


@instrument
def col_norms_distributed(A: jax.Array, grid: ProcessGrid) -> jax.Array:
    """Distributed column max-norms (internal::colNorms analogue)."""
    return _col_norms_fn(grid.mesh)(A)
