"""Distributed Hermitian-indefinite (Aasen) factorization over the mesh.

Reference analogues: ``src/hetrf.cc`` (642 LoC: communication-avoiding Aasen
over the grid — panel LU on the Schur-complement column, band T assembly,
two-sided pivoting), ``src/hetrs.cc`` (L sweep + banded-T solve + L^H sweep),
``src/hesv.cc``.

TPU re-design (not a translation):

- **1-D row-block layout over the flattened mesh** (the TSLU layout,
  ``lu_dist._getrf_tall_fn``): every device owns all columns of its row
  block, so Aasen's H-column gemm — the flops-dominant step — is a fully
  local (n/P × n)·(n × nb) MXU gemm with *zero* communication; only the
  nb-row block extractions (masked psum), the H-column all-gather, and the
  tournament candidate all-gather touch the interconnect per panel.
- **Tournament panel pivoting.**  The reference's hetrf panel is a
  partial-pivoted LU over grid tiles; here the Schur panel reuses the CALU
  tournament (one candidate all-gather + one stacked LU — the
  communication-avoiding shape, SURVEY §7 hard-part 1).
- **Two-sided dirty exchange.**  The symmetric permutation moves ≤ 2nb rows
  (one masked psum) and ≤ 2nb columns (purely local gathers — columns are
  resident), instead of the reference's MPI pairwise row+column swaps.
- **ONE ``lax.fori_loop``** over panels: O(1) program size (the
  single-device path unrolls panels at trace time; the reference unrolls an
  OpenMP task graph).

T is returned in compact lower band form (bandwidth nb) and factored by the
distributed band LU, so ``hetrs_distributed`` solves ride
``band_dist.gbtrs_distributed`` + the sharded unit-lower sweeps.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.exceptions import slate_assert
from .band_dist import (BandLUDist, dense_to_band_general, gbtrf_distributed,
                        gbtrs_distributed)
from .distribute import ceil_mult
from .mesh import COL_AXIS, ProcessGrid, ROW_AXIS, shard_map
from .pivot import (exchange_rows as _exchange_rows,
                    extract_rows as _extract_rows,
                    step_permutation, tournament_piv)
from ..obs import instrument

AX = (ROW_AXIS, COL_AXIS)


class HermitianFactorsDist(NamedTuple):
    """Distributed Aasen bundle P A P^H = L T L^H (hetrf.cc output shape)."""
    L: jax.Array         # (n, n) unit lower triangular (sharded rows)
    Tband: jax.Array     # T in LAPACK-gb layout (3nb+1, n): row j holds
                         # diagonal j - 2nb, i.e. dense_to_band_general(
                         # T, nb, nb, extra=nb); the diagonal is row 2nb
    T_fac: BandLUDist    # distributed band LU of T
    perm: jax.Array      # (n,)
    nb: int


@lru_cache(maxsize=32)
def _hetrf_dist_fn(mesh, npad: int, nb: int, dtype_str: str):
    nprocs = mesh.shape[ROW_AXIS] * mesh.shape[COL_AXIS]
    mr = npad // nprocs
    N = npad // nb
    cplx = dtype_str.startswith("complex")

    def conj_t(x):
        return jnp.conj(jnp.swapaxes(x, -1, -2)) if cplx else \
            jnp.swapaxes(x, -1, -2)

    def local_fn(A_loc):                     # (mr, npad)
        ri = lax.axis_index(AX)
        grow = ri * mr + jnp.arange(mr, dtype=jnp.int32)
        gcol = jnp.arange(npad, dtype=jnp.int32)

        def extract_rows(X_loc, r0, cnt):
            """Replicated (cnt, npad) block of rows [r0, r0+cnt)."""
            S = r0 + jnp.arange(cnt, dtype=jnp.int32)
            return _extract_rows(X_loc, S, ri, mr, AX)

        def step(j, carry):
            A_loc, L_loc, T_loc, perm = carry
            j0 = (j * nb).astype(jnp.int32) if hasattr(j, "astype") else j * nb
            j1 = j0 + nb

            # ---- H-column: Hcol = T[:, :j1+nb] @ L[j0:j1, :j1+nb]^H,
            # rows < j0 meaningful.  T band => cols beyond j1+nb are zero in
            # the needed rows; local gemm over my rows, then gather.
            Lj = extract_rows(L_loc, j0, nb)             # (nb, npad)
            cmask = (gcol < j1 + nb)
            Hcol_loc = jnp.matmul(
                jnp.where(cmask[None, :], T_loc, jnp.zeros_like(T_loc)),
                conj_t(jnp.where(cmask[None, :], Lj, jnp.zeros_like(Lj))),
                precision=lax.Precision.HIGHEST)         # (mr, nb)
            Hcol_loc = jnp.where((grow < j0)[:, None], Hcol_loc,
                                 jnp.zeros_like(Hcol_loc))
            Hcol = lax.all_gather(Hcol_loc, AX).reshape(npad, nb)

            # ---- diagonal identities (replicated small blocks)
            Arow = extract_rows(A_loc, j0, nb)           # (nb, npad)
            Ajj = lax.dynamic_slice(Arow, (jnp.int32(0), j0), (nb, nb))
            Ljj = lax.dynamic_slice(Lj, (jnp.int32(0), j0), (nb, nb))
            pmask = (gcol < j0)
            LH = jnp.matmul(jnp.where(pmask[None, :], Lj, jnp.zeros_like(Lj)),
                            Hcol, precision=lax.Precision.HIGHEST)
            LjjHjj = Ajj - LH
            Hjj = lax.linalg.triangular_solve(Ljj, LjjHjj, left_side=True,
                                              lower=True, unit_diagonal=True)
            Trow = extract_rows(T_loc, j0, nb)           # (nb, npad)
            start_prev = jnp.maximum(j0 - nb, 0)
            Tprev = lax.dynamic_slice(Trow, (jnp.int32(0), start_prev),
                                      (nb, nb))
            Lprev = lax.dynamic_slice(Lj, (jnp.int32(0), start_prev), (nb, nb))
            rhs = Hjj - jnp.where(j0 > 0, jnp.matmul(
                Tprev, conj_t(Lprev), precision=lax.Precision.HIGHEST),
                jnp.zeros((nb, nb), Hjj.dtype))
            Tjj = lax.linalg.triangular_solve(
                Ljj, rhs, left_side=False, lower=True, unit_diagonal=True,
                conjugate_a=cplx, transpose_a=True)
            Tjj = (Tjj + jnp.conj(Tjj.T)) / 2 if cplx else (Tjj + Tjj.T) / 2
            # write T[j0:j1, j0:j1]
            dstT = j0 + jnp.arange(nb, dtype=jnp.int32) - ri * mr
            dstT = jnp.where((dstT >= 0) & (dstT < mr), dstT, mr)
            Tnew = jnp.zeros((nb, npad), T_loc.dtype)
            Tnew = lax.dynamic_update_slice(Tnew, Tjj, (jnp.int32(0), j0))
            keep = lax.dynamic_update_slice(
                jnp.zeros((nb, npad), jnp.bool_),
                jnp.ones((nb, nb), jnp.bool_), (jnp.int32(0), j0))
            Trows_cur = T_loc[jnp.clip(dstT, 0, mr - 1)]
            T_loc = T_loc.at[dstT].set(
                jnp.where(keep, Tnew, Trows_cur), mode="drop")

            # ---- Schur panel W = A[:, j0:j1] - L[:, :j0] Hcol - L[:, j0:j1] Hjj
            # (rows >= j1 meaningful)
            Acol = lax.dynamic_slice(A_loc, (jnp.int32(0), j0), (mr, nb))
            Lpre = jnp.where(pmask[None, :], L_loc, jnp.zeros_like(L_loc))
            W = Acol - jnp.matmul(Lpre, Hcol, precision=lax.Precision.HIGHEST)
            Lcur = lax.dynamic_slice(L_loc, (jnp.int32(0), j0), (mr, nb))
            W = W - jnp.matmul(Lcur, Hjj, precision=lax.Precision.HIGHEST)

            # ---- tournament panel LU over rows >= j1 (shared machinery,
            # pivot.py; CALU round)
            piv = tournament_piv(W, grow, j1, nb, nprocs, AX)
            safe = j1 < npad        # final iteration has no trailing panel
            iota = jnp.arange(npad, dtype=jnp.int32)
            stepperm = jnp.where(safe, step_permutation(piv, j1, npad, nb),
                                 iota)
            perm = perm[stepperm]

            # dirty sets
            S = jnp.concatenate([j1 + jnp.arange(nb, dtype=jnp.int32), piv])
            src = stepperm[jnp.clip(S, 0, npad - 1)]

            def exchange_rows(X_loc):
                return _exchange_rows(X_loc, S, src, ri, mr, AX)

            # two-sided on A: rows (psum) then columns (local gather)
            A_loc = exchange_rows(A_loc)
            A_loc = A_loc.at[:, S].set(A_loc[:, jnp.clip(src, 0, npad - 1)],
                                       mode="drop")
            # L rows move only inside cols [nb, j1) — swap then re-mask
            Lsw = exchange_rows(L_loc)
            lmask = (gcol >= nb) & (gcol < j1)
            L_loc = jnp.where(lmask[None, :], Lsw, L_loc)
            # W rows follow the same permutation
            W = exchange_rows(W)

            # ---- factor the swapped panel block
            blk = extract_rows(W, j1, nb)
            blk = lax.dynamic_slice(blk, (jnp.int32(0), jnp.int32(0)),
                                    (nb, nb))
            LUkk, _, blkperm = lax.linalg.lu(blk)
            # guard the final iteration (j1 >= npad): identity block
            LUkk = jnp.where(safe, LUkk, jnp.eye(nb, dtype=LUkk.dtype))
            blkperm = jnp.where(safe, blkperm,
                                jnp.arange(nb, dtype=blkperm.dtype))
            # fold intra-block pivots (rows j1..j1+nb): perm, A rows+cols,
            # L masked cols, W rows
            seg = jnp.take(perm, jnp.clip(j1 + blkperm, 0, npad - 1))
            perm = lax.dynamic_update_slice(
                perm, jnp.where(safe, seg,
                                lax.dynamic_slice(perm, (jnp.int32(
                                    jnp.minimum(j1, npad - nb)),), (nb,))),
                (jnp.minimum(j1, npad - nb),))

            Sb = j1 + jnp.arange(nb, dtype=jnp.int32)
            srcb = jnp.clip(j1 + blkperm, 0, npad - 1)

            def reorder_block_rows(X_loc):
                return _exchange_rows(X_loc, Sb, srcb, ri, mr, AX)

            A_loc = reorder_block_rows(A_loc)
            A_loc = A_loc.at[:, Sb].set(A_loc[:, srcb], mode="drop")
            Lsw = reorder_block_rows(L_loc)
            L_loc = jnp.where(lmask[None, :], Lsw, L_loc)
            W = reorder_block_rows(W)

            # ---- L panel and T sub/super blocks
            Up = jnp.triu(LUkk)
            Lblock = jnp.tril(LUkk, -1) + jnp.eye(nb, dtype=LUkk.dtype)
            # rows below j1+nb: X = W · Up^{-1}
            # guard singular Up (pad tail): unit diagonal floor
            dU = jnp.abs(jnp.diagonal(Up))
            Up_safe = Up + jnp.diag(jnp.where(dU > 0, 0.0, 1.0).astype(
                Up.dtype))
            X = lax.linalg.triangular_solve(Up_safe, W, left_side=False,
                                            lower=False)
            belowb = grow >= (j1 + nb)
            in_blk = (grow >= j1) & (grow < j1 + nb)
            Lpan_loc = jnp.where(belowb[:, None], X,
                                 jnp.zeros_like(X))
            # block rows get the unit-lower Lblock
            Lblk_rows = lax.dynamic_update_slice(
                jnp.zeros((mr, nb), X.dtype), Lblock,
                (jnp.clip(j1 - ri * mr, 0, mr), jnp.int32(0)))
            Lblk_rows = jnp.where(in_blk[:, None], Lblk_rows,
                                  jnp.zeros_like(Lblk_rows))
            Lpan_loc = Lpan_loc + Lblk_rows
            # write L[:, j1:j1+nb] where rows >= j1 (cond: only if safe)
            cur = lax.dynamic_slice(
                L_loc, (jnp.int32(0), jnp.minimum(j1, npad - nb)), (mr, nb))
            put = jnp.where(jnp.logical_and(safe, in_blk | belowb)[:, None],
                            Lpan_loc, cur)
            L_loc = lax.dynamic_update_slice(
                L_loc, put, (jnp.int32(0), jnp.minimum(j1, npad - nb)))

            # T[j1][j0] = Up (L[j0:j1,j0:j1]^H)^{-1}; Ljj unchanged by swaps
            Tj1j = lax.linalg.triangular_solve(
                Ljj, Up, left_side=False, lower=True, unit_diagonal=True,
                conjugate_a=cplx, transpose_a=True)
            Tj1j = jnp.where(safe, Tj1j, jnp.zeros_like(Tj1j))
            # write T[j1:j1+nb, j0:j1] and its Hermitian mirror
            dstT2 = Sb - ri * mr
            dstT2 = jnp.where((dstT2 >= 0) & (dstT2 < mr), dstT2, mr)
            rows_cur = T_loc[jnp.clip(dstT2, 0, mr - 1)]
            block_row = lax.dynamic_update_slice(
                jnp.zeros((nb, npad), T_loc.dtype), Tj1j, (jnp.int32(0), j0))
            keep2 = lax.dynamic_update_slice(
                jnp.zeros((nb, npad), jnp.bool_),
                jnp.ones((nb, nb), jnp.bool_), (jnp.int32(0), j0))
            T_loc = T_loc.at[dstT2].set(
                jnp.where(keep2, block_row, rows_cur), mode="drop")
            # mirror: T[j0:j1, j1:j1+nb] = Tj1j^H
            mirror = lax.dynamic_update_slice(
                jnp.zeros((nb, npad), T_loc.dtype), conj_t(Tj1j),
                (jnp.int32(0), jnp.minimum(j1, npad - nb)))
            keep3 = lax.dynamic_update_slice(
                jnp.zeros((nb, npad), jnp.bool_),
                jnp.ones((nb, nb), jnp.bool_),
                (jnp.int32(0), jnp.minimum(j1, npad - nb)))
            keep3 = keep3 & safe
            rows_cur2 = T_loc[jnp.clip(dstT, 0, mr - 1)]
            T_loc = T_loc.at[dstT].set(
                jnp.where(keep3, mirror, rows_cur2), mode="drop")

            return A_loc, L_loc, T_loc, perm

        eyer = (grow[:, None] == gcol[None, :]).astype(A_loc.dtype)
        L0 = eyer
        T0 = jnp.zeros_like(A_loc)
        perm0 = jnp.arange(npad, dtype=jnp.int32)
        A_loc, L_loc, T_loc, perm = lax.fori_loop(
            0, N, step, (A_loc, L0, T0, perm0))
        return L_loc, T_loc, perm

    spec = P(AX, None)
    fn = shard_map(local_fn, mesh=mesh, in_specs=spec,
                       out_specs=(spec, spec, P(None)), check_vma=False)
    return jax.jit(fn)


@instrument
def hetrf_distributed(A: jax.Array, grid: ProcessGrid, nb: int = 256):
    """Distributed Aasen factorization P A P^H = L T L^H (src/hetrf.cc).

    Returns ``(HermitianFactorsDist, info)``; T comes back as a compact
    general band (bandwidth nb each side) already factored by the
    distributed band LU, so solves never refactor.
    """
    slate_assert(A.ndim == 2 and A.shape[-1] == A.shape[-2],
                 "hetrf_distributed expects a square Hermitian matrix")
    n = A.shape[-1]
    nb = max(1, min(nb, n))
    nprocs = grid.p * grid.q
    unit = nb * nprocs
    npad = ceil_mult(n, unit)
    if npad > n:
        Ap = jnp.zeros((npad, npad), A.dtype)
        Ap = Ap.at[:n, :n].set(A)
        idx = jnp.arange(n, npad)
        Ap = Ap.at[idx, idx].set(1)
    else:
        Ap = A
    Ap = jax.device_put(Ap, jax.sharding.NamedSharding(grid.mesh,
                                                       P(AX, None)))
    L, T, perm = _hetrf_dist_fn(grid.mesh, npad, nb, str(Ap.dtype))(Ap)
    L = L[:n, :n]
    T = T[:n, :n]
    perm = perm[:n]
    Tband = dense_to_band_general(T, nb, nb, extra=nb)
    T_fac, info = gbtrf_distributed(Tband, grid, nb, nb, nb=nb)
    return HermitianFactorsDist(L=L, Tband=Tband, T_fac=T_fac, perm=perm,
                                nb=nb), info


@instrument
def hetrs_distributed(fac: HermitianFactorsDist, B: jax.Array,
                      grid: ProcessGrid) -> jax.Array:
    """Distributed Aasen solve (src/hetrs.cc): permute, unit-lower sweep,
    banded-T solve, unit-lower^H sweep, un-permute — all on mesh kernels."""
    from .solvers import trsm_distributed

    vec = B.ndim == 1
    b = B[:, None] if vec else B
    y = jnp.take(b, fac.perm, axis=0)
    n = fac.L.shape[-1]
    idx = jnp.arange(n)
    Lu = jnp.tril(fac.L, -1).at[idx, idx].set(1)
    y = trsm_distributed(Lu, y, grid, lower=True, conj_trans=False)
    z = gbtrs_distributed(fac.T_fac, y, grid)
    x = trsm_distributed(Lu, z, grid, lower=True, conj_trans=True)
    x = jnp.zeros_like(x).at[fac.perm].set(x)
    return x[:, 0] if vec else x


@instrument
def hesv_distributed(A: jax.Array, B: jax.Array, grid: ProcessGrid,
                     nb: int = 256):
    """Distributed Hermitian-indefinite solve (src/hesv.cc = hetrf + hetrs)."""
    fac, info = hetrf_distributed(A, grid, nb=nb)
    return hetrs_distributed(fac, B, grid), info
