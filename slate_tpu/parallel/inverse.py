"""Distributed matrix inversion: trtri / trtrm / potri / getri over the mesh.

Reference analogues: ``src/trtri.cc`` (blocked in-place triangular inverse over
the grid), ``src/trtrm.cc`` (L^H·L triangular-triangular multiply, the second
half of potri), ``src/potri.cc`` (trtri + trtrm), ``src/getri.cc:242`` and
``src/getriOOP.cc`` (LU inverse: solve against the identity with pivot
replay).

TPU re-design: each of these is a composition of kernels the mesh already
runs — the blocked recurrences the reference schedules tile-by-tile collapse
into the sharded TriangularSolve / SUMMA / getrs building blocks, which GSPMD
partitions over the same (p, q) grid the reference distributes on.  No new
communication pattern is needed: that is the point of building on the
established distributed verbs (the reference's potri.cc likewise just calls
its trtri + trtrm work routines).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .mesh import ProcessGrid
from .solvers import trsm_distributed
from .summa import gemm_padded


def trtri_distributed(T: jax.Array, grid: ProcessGrid, lower: bool = True,
                      unit_diagonal: bool = False) -> jax.Array:
    """Distributed triangular inverse (src/trtri.cc): the blocked in-place
    recurrence is one sharded TriangularSolve against the identity."""
    n = T.shape[-1]
    eye = jnp.eye(n, dtype=T.dtype)
    if unit_diagonal:
        idx = jnp.arange(n)
        T = T.at[idx, idx].set(1)
    X = trsm_distributed(jnp.tril(T) if lower else jnp.triu(T), eye, grid,
                         lower=lower)
    return jnp.tril(X) if lower else jnp.triu(X)


def trtrm_distributed(T: jax.Array, grid: ProcessGrid,
                      lower: bool = True) -> jax.Array:
    """Distributed L^H L (or U U^H) producing the stored triangle — the
    second half of potri (src/trtrm.cc), as one SUMMA gemm over the grid."""
    if lower:
        L = jnp.tril(T)
        out = gemm_padded(jnp.conj(L.T), L, grid)
        return jnp.tril(out)
    U = jnp.triu(T)
    out = gemm_padded(U, jnp.conj(U.T), grid)
    return jnp.triu(out)


def potri_distributed(L: jax.Array, grid: ProcessGrid,
                      lower: bool = True) -> jax.Array:
    """Distributed SPD inverse from the Cholesky factor: A^{-1} = L^{-H} L^{-1}
    (src/potri.cc = trtri + trtrm, both riding the mesh kernels)."""
    Linv = trtri_distributed(L, grid, lower=lower)
    return trtrm_distributed(Linv, grid, lower=lower)


def getri_distributed(LU: jax.Array, perm: jax.Array,
                      grid: ProcessGrid) -> jax.Array:
    """Distributed inverse from the tournament-LU factor (src/getri.cc:242 /
    getriOOP.cc): solve A X = I through the sharded getrs sweeps."""
    from .lu_dist import getrs_distributed

    n = LU.shape[-1]
    return getrs_distributed(LU, perm, jnp.eye(n, dtype=LU.dtype), grid)
