"""Distributed matrix inversion: trtri / trtrm / potri / getri over the mesh.

Reference analogues: ``src/trtri.cc`` (blocked in-place triangular inverse over
the grid), ``src/trtrm.cc`` (L^H·L triangular-triangular multiply, the second
half of potri), ``src/potri.cc`` (trtri + trtrm), ``src/getri.cc:242`` and
``src/getriOOP.cc`` (LU inverse: solve against the identity with pivot
replay).

TPU re-design: each of these is a composition of kernels the mesh already
runs — the blocked recurrences the reference schedules tile-by-tile collapse
into the sharded TriangularSolve / SUMMA / getrs building blocks, which GSPMD
partitions over the same (p, q) grid the reference distributes on.  No new
communication pattern is needed: that is the point of building on the
established distributed verbs (the reference's potri.cc likewise just calls
its trtri + trtrm work routines).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .mesh import ProcessGrid
from .solvers import trsm_distributed
from .summa import gemm_padded
from ..obs import instrument


@instrument
def trtri_distributed(T: jax.Array, grid: ProcessGrid, lower: bool = True,
                      unit_diagonal: bool = False) -> jax.Array:
    """Distributed triangular inverse (src/trtri.cc): the blocked in-place
    recurrence is one sharded TriangularSolve against the identity."""
    n = T.shape[-1]
    eye = jnp.eye(n, dtype=T.dtype)
    if unit_diagonal:
        idx = jnp.arange(n)
        T = T.at[idx, idx].set(1)
    X = trsm_distributed(jnp.tril(T) if lower else jnp.triu(T), eye, grid,
                         lower=lower)
    return jnp.tril(X) if lower else jnp.triu(X)


@instrument
def trtrm_distributed(T: jax.Array, grid: ProcessGrid,
                      lower: bool = True) -> jax.Array:
    """Distributed L^H L (or U U^H) producing the stored triangle — the
    second half of potri (src/trtrm.cc), as one SUMMA gemm over the grid."""
    if lower:
        L = jnp.tril(T)
        out = gemm_padded(jnp.conj(L.T), L, grid)
        return jnp.tril(out)
    U = jnp.triu(T)
    out = gemm_padded(U, jnp.conj(U.T), grid)
    return jnp.triu(out)


@instrument
def potri_distributed(L: jax.Array, grid: ProcessGrid,
                      lower: bool = True) -> jax.Array:
    """Distributed SPD inverse from the Cholesky factor: A^{-1} = L^{-H} L^{-1}
    (src/potri.cc = trtri + trtrm, both riding the mesh kernels)."""
    Linv = trtri_distributed(L, grid, lower=lower)
    return trtrm_distributed(Linv, grid, lower=lower)


@instrument
def getri_distributed(LU: jax.Array, perm: jax.Array,
                      grid: ProcessGrid) -> jax.Array:
    """Distributed inverse from the tournament-LU factor (src/getri.cc:242 /
    getriOOP.cc): solve A X = I through the sharded getrs sweeps."""
    from .lu_dist import getrs_distributed

    n = LU.shape[-1]
    return getrs_distributed(LU, perm, jnp.eye(n, dtype=LU.dtype), grid)


@instrument
def gecondest_distributed(LU, perm, anorm, grid: ProcessGrid,
                          norm_kind=None):
    """Distributed 1-norm condition estimate from the tournament-LU factor
    (src/gecondest.cc over the mesh): the Hager/Higham power iteration of
    ``linalg.condest.norm1est`` with both solve directions riding the
    sharded triangular sweeps."""
    from ..core.exceptions import SlateError
    from ..core.types import Norm
    from ..linalg.condest import norm1est
    from .lu_dist import getrs_distributed

    norm_kind = (Norm.One if norm_kind is None
                 else Norm.from_string(norm_kind)
                 if not isinstance(norm_kind, Norm) else norm_kind)
    if norm_kind not in (Norm.One, Norm.Inf):
        raise SlateError("gecondest_distributed supports One or Inf norms")
    LU = jnp.asarray(LU)
    n = LU.shape[-1]
    L = jnp.tril(LU, -1) + jnp.eye(n, dtype=LU.dtype)
    U = jnp.triu(LU)

    def solve(x):                      # A^{-1} x: the shared sharded sweeps
        return getrs_distributed(LU, perm, x[:, None], grid)[:, 0]

    def solve_h(x):                    # A^{-H} x
        y = trsm_distributed(U, x[:, None], grid, lower=False,
                             conj_trans=True)
        z = trsm_distributed(L, y, grid, lower=True, conj_trans=True)
        return jnp.zeros_like(z).at[perm].set(z)[:, 0]

    if norm_kind == Norm.Inf:
        inv_norm = norm1est(solve_h, solve, n, LU.dtype)
    else:
        inv_norm = norm1est(solve, solve_h, n, LU.dtype)
    rcond = 1.0 / (jnp.asarray(anorm, jnp.real(inv_norm).dtype) * inv_norm)
    # singular factor / zero norm -> rcond 0, like the single-device API
    return jnp.where(jnp.isfinite(rcond), rcond, 0.0)


@instrument
def pocondest_distributed(L: jax.Array, anorm, grid: ProcessGrid):
    """Distributed SPD condition estimate from the Cholesky factor
    (src/pocondest.cc over the mesh)."""
    from ..linalg.condest import norm1est

    Lf = jnp.tril(jnp.asarray(L))
    n = Lf.shape[-1]

    def solve(x):                      # A^{-1} x = L^{-H} L^{-1} x
        y = trsm_distributed(Lf, x[:, None], grid, lower=True)
        return trsm_distributed(Lf, y, grid, lower=True, conj_trans=True)[:, 0]

    inv_norm = norm1est(solve, solve, n, Lf.dtype)
    rcond = 1.0 / (jnp.asarray(anorm, jnp.real(inv_norm).dtype) * inv_norm)
    return jnp.where(jnp.isfinite(rcond), rcond, 0.0)


@instrument
def trcondest_distributed(T: jax.Array, grid: ProcessGrid, lower: bool = True,
                          unit_diagonal: bool = False, norm_kind=None):
    """Distributed triangular condition estimate (src/trcondest.cc over the
    mesh): anorm from the sharded triangle norm, the inverse norm from the
    Hager/Higham estimator with both solve directions riding the sharded
    triangular sweeps.  Inf-norm uses ||T^{-1}||_inf == ||T^{-H}||_1 — the
    same estimator with the two solves swapped (mirrors gecondest)."""
    from ..core.exceptions import SlateError
    from ..core.types import Norm
    from ..linalg.condest import norm1est
    from .eig_dist import norm_distributed

    norm_kind = (Norm.One if norm_kind is None
                 else norm_kind if isinstance(norm_kind, Norm)
                 else Norm.from_string(norm_kind))
    if norm_kind not in (Norm.One, Norm.Inf):
        raise SlateError("trcondest_distributed supports One or Inf norms")
    Tf = jnp.asarray(T)
    n = Tf.shape[-1]
    if unit_diagonal:
        idx = jnp.arange(n)
        Tf = Tf.at[idx, idx].set(1)
    Tf = jnp.tril(Tf) if lower else jnp.triu(Tf)
    anorm = norm_distributed(norm_kind, Tf, grid,
                             uplo="lower" if lower else "upper")

    def solve(x):                      # T^{-1} x
        return trsm_distributed(Tf, x[:, None], grid, lower=lower)[:, 0]

    def solve_h(x):                    # T^{-H} x
        return trsm_distributed(Tf, x[:, None], grid, lower=lower,
                                conj_trans=True)[:, 0]

    if norm_kind == Norm.Inf:
        inv_norm = norm1est(solve_h, solve, n, Tf.dtype)
    else:
        inv_norm = norm1est(solve, solve_h, n, Tf.dtype)
    rcond = 1.0 / (jnp.asarray(anorm, jnp.real(inv_norm).dtype) * inv_norm)
    return jnp.where(jnp.isfinite(rcond), rcond, 0.0)
