"""Distributed LU with tournament pivoting over the process grid.

Reference analogues:

* ``src/getrf.cc:22-260`` — partial-pivot LU: panel factor + pivot MPI_Bcast +
  row swaps + trailing update, with lookahead.
* ``src/getrf_tntpiv.cc:161-230`` + ``src/internal/internal_getrf_tntpiv.cc`` —
  CALU tournament pivoting: block-local partially-pivoted panel LUs, then a
  reduction tree over candidate pivot rows.
* ``src/internal/internal_swap.cc`` — permuteRows MPI row exchanges.
* ``src/gesv.cc`` — getrf + getrs.

TPU re-design (not a translation):

- **Tournament pivoting is the default** (SURVEY.md §7 hard-part 1): the
  reference's partial-pivot panel needs one maxloc allreduce per column; the
  tournament needs one candidate all-gather per *panel*, which is the
  communication-avoiding shape that fits ICI collectives.  Each mesh row
  factors its local panel chunk with ``lax.linalg.lu`` (one batched XLA op),
  winners are reduced in a single stacked LU over the gathered candidates —
  the reference's binary tree collapsed into one round, optimal for the
  p ≤ 64 mesh rows a pod slice has.
- **Row swaps are gathers**: only the ≤ 2·nb "dirty" rows move, fetched with a
  masked ``psum`` along the p axis and scattered locally — the reference's
  pairwise MPI row exchanges (internal_swap.cc) become two collectives of
  O(nb · n/q) bytes per panel.
- **Fixed-shape pipeline**: the whole factorization is ONE ``lax.fori_loop``
  over panels with full-width masked updates — O(1) program size and compile
  time regardless of nt (the reference's O(nt) OpenMP task unroll, and the
  compile-time hazard of Python-unrolled drivers, both disappear).  The masked
  full-width trailing gemm trades ~3× the minimal flop count for perfectly
  static MXU-shaped matmuls; on TPU the large fused (n/p × nb)·(nb × n/q)
  updates run at near-peak, which is the right end of that trade
  (pallas_guide.md: prefer static shapes + big matmuls over tight flop counts).
- Layout is the (p, q) block sharding of the process grid; the matrix is
  padded with an identity tail to align panels to shard boundaries
  (pad-and-mask edge policy, SURVEY.md §7 hard-part 5).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.exceptions import slate_assert
from ..robust import RetryPolicy, first_bad_index, guard_shards, inject
from ..utils.trace import trace_event
from .distribute import ceil_mult, lcm as _lcm
from .mesh import COL_AXIS, ProcessGrid, ROW_AXIS, shard_map
from .pivot import (exchange_rows as _exchange_rows,
                    select_pivots, step_permutation)
from ..obs import instrument


def _panel_tail(A_loc, pan, LUkk, k0, grow, gcol, pi, qi, mr, mc, nb):
    """Shared post-factor panel pipeline of the 2-D LU variants (tournament
    and nopiv — parallel/rbt.py): panel L via trsm against Ukk, packed
    L\\U write-back on the owner mesh column, U row band psum-bcast along p,
    masked full-width trailing gemm.  One implementation so the two
    factorizations cannot drift."""
    po = k0 // mr
    roff = k0 - po * mr
    qo = k0 // mc
    off = k0 - qo * mc

    Ukk = jnp.triu(LUkk)
    # L below the block: X = pan · Ukk^{-1}, valid for rows ≥ k0+nb
    X = lax.linalg.triangular_solve(Ukk, pan, left_side=False, lower=False)
    below = grow >= (k0 + nb)
    Lmask = jnp.where(below[:, None], X, jnp.zeros_like(X))

    # write the packed panel column back (owner mesh column only): rows < k0
    # keep U history; block rows get packed L\U; rows below get L.  Every
    # device knows LUkk (replicated by the psum before the factor).
    in_blk = (grow >= k0) & (grow < k0 + nb)
    packed = jnp.where(in_blk[:, None],
                       lax.dynamic_update_slice(
                           jnp.zeros((mr, nb), pan.dtype), LUkk,
                           (roff, jnp.int32(0))),
                       jnp.where(below[:, None], Lmask, pan))
    newA = lax.dynamic_update_slice(A_loc, packed, (jnp.int32(0), off))
    A_loc = jnp.where(qi == qo, newA, A_loc)

    # U row band: U = Lkk^{-1} · A[k0:k0+nb, :], bcast along p
    rb = lax.dynamic_slice(A_loc, (roff, jnp.int32(0)), (nb, mc))
    rb = jnp.where(pi == po, rb, jnp.zeros_like(rb))
    rb = lax.psum(rb, ROW_AXIS)                # (nb, mc) everywhere
    U_loc = lax.linalg.triangular_solve(jnp.tril(LUkk), rb,
                                        left_side=True, lower=True,
                                        unit_diagonal=True)
    ucols = gcol >= (k0 + nb)
    Umask = jnp.where(ucols[None, :], U_loc, jnp.zeros_like(U_loc))
    new_rows = jnp.where(ucols[None, :], U_loc, rb)
    rowband = lax.dynamic_update_slice(A_loc, new_rows, (roff, jnp.int32(0)))
    A_loc = jnp.where(pi == po, rowband, A_loc)

    # trailing update: full-width masked MXU gemm
    return A_loc - jnp.matmul(Lmask, Umask, precision=lax.Precision.HIGHEST)


def _lu_diag_info(A_loc, grow, gcol, npad):
    """First bad U diagonal (0 or non-finite), psum-assembled — the
    reduce_info analogue shared by the 2-D LU variants."""
    dmask = grow[:, None] == gcol[None, :]
    drow = jnp.sum(jnp.where(dmask, A_loc, jnp.zeros_like(A_loc)), axis=1)
    diag = jnp.zeros((npad,), A_loc.dtype).at[grow].set(drow)
    diag = lax.psum(lax.psum(diag, ROW_AXIS), COL_AXIS)
    # shared info kernel (robust.first_bad_index, reduce_info semantics)
    return first_bad_index((diag == 0) | ~jnp.isfinite(diag))


@lru_cache(maxsize=32)
def _getrf_dist_fn(mesh, npad: int, nb: int, dtype_str: str,
                   lu_panel: str = "tournament"):
    """Build the jitted shard_map tournament-LU over an npad×npad matrix.
    ``lu_panel`` selects the panel pivot scheme (Options.lu_panel: CALU
    tournament rounds or one gathered partial-pivot LU, pivot.py)."""
    p, q = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]
    mr, mc = npad // p, npad // q          # local shard shape
    nt = npad // nb                        # panel count (static)
    assert mr % nb == 0 and mc % nb == 0

    def local_fn(A_loc):
        pi = lax.axis_index(ROW_AXIS)
        qi = lax.axis_index(COL_AXIS)
        grow = pi * mr + jnp.arange(mr, dtype=jnp.int32)   # global row of my rows
        gcol = qi * mc + jnp.arange(mc, dtype=jnp.int32)

        def extract_panel(A_loc, k0):
            """My rows of panel columns [k0, k0+nb): owner mesh column
            contributes, psum along q = the reference's panel listBcast."""
            qo = k0 // mc
            off = k0 - qo * mc
            pan = lax.dynamic_slice(A_loc, (jnp.int32(0), off), (mr, nb))
            pan = jnp.where(qi == qo, pan, jnp.zeros_like(pan))
            return lax.psum(pan, COL_AXIS)

        def step(k, carry):
            A_loc, perm = carry
            k0 = (k * nb).astype(jnp.int32) if hasattr(k, "astype") else k * nb
            pan = extract_panel(A_loc, k0)

            # ---- panel pivot selection + ipiv-compatible step permutation
            # (shared machinery, pivot.py; internal_getrf_tntpiv analogue)
            piv = select_pivots(lu_panel, pan, grow, k0, nb, p, ROW_AXIS)
            stepperm = step_permutation(piv, k0, npad, nb)
            perm = perm[stepperm]

            # ---- apply the row permutation: only dirty rows move
            # (shared machinery, pivot.py); dirty positions are within
            # {k0..k0+nb-1} ∪ piv
            S = jnp.concatenate([k0 + jnp.arange(nb, dtype=jnp.int32), piv])
            A_loc = _exchange_rows(A_loc, S, stepperm[S], pi, mr, ROW_AXIS)

            # ---- panel factorization on the permuted panel
            pan = extract_panel(A_loc, k0)
            po = k0 // mr
            roff = k0 - po * mr
            blk = lax.dynamic_slice(pan, (roff, jnp.int32(0)), (nb, nb))
            blk = jnp.where(pi == po, blk, jnp.zeros_like(blk))
            blk = lax.psum(blk, ROW_AXIS)              # diag block everywhere
            LUkk, _, blkperm = lax.linalg.lu(blk)
            # fold the intra-block pivoting into the global permutation and
            # physically reorder rows [k0, k0+nb) (they live on mesh row po)
            seg = jnp.take(perm, k0 + blkperm)
            perm = lax.dynamic_update_slice(perm, seg, (k0,))
            blk_rows = A_loc[jnp.clip(roff + blkperm, 0, mr - 1)]
            A_perm = lax.dynamic_update_slice(A_loc, blk_rows, (roff, jnp.int32(0)))
            A_loc = jnp.where(pi == po, A_perm, A_loc)
            pan_blk = pan[jnp.clip(roff + blkperm, 0, mr - 1)]
            pan = jnp.where(pi == po,
                            lax.dynamic_update_slice(pan, pan_blk, (roff, jnp.int32(0))),
                            pan)

            # ---- shared post-factor pipeline (panel L, packed write, U row
            # band, trailing gemm — one source of truth with the nopiv
            # variant, parallel/rbt.py)
            A_loc = _panel_tail(A_loc, pan, LUkk, k0, grow, gcol, pi, qi,
                                mr, mc, nb)
            return A_loc, perm

        perm0 = jnp.arange(npad, dtype=jnp.int32)
        A_loc, perm = lax.fori_loop(0, nt, step, (A_loc, perm0))

        # info: first bad diagonal of U (functional, reduce_info analogue)
        info = _lu_diag_info(A_loc, grow, gcol, npad)
        return A_loc, perm, info

    spec = P(ROW_AXIS, COL_AXIS)
    # perm/info are computed identically on every shard (their inputs are all
    # psum/all_gather results), but the vma system cannot prove replication
    # through the swap fori_loops — the unsharded out_specs assert it.
    fn = shard_map(local_fn, mesh=mesh, in_specs=spec,
                       out_specs=(spec, P(None), P()), check_vma=False)
    return jax.jit(fn)


@lru_cache(maxsize=32)
def _getrf_tall_fn(mesh, mpad: int, npc: int, nb: int, dtype_str: str,
                   lu_panel: str = "tournament"):
    """Jitted 1-D TSLU over an mpad×npc tall matrix: rows block-sharded over
    the *flattened* mesh (every device owns all columns), tournament panels
    over the flat axis, trailing updates as fully local MXU gemms.

    The reference's ``src/getrf.cc:22-260`` factors any m×n over the grid;
    this is its tall regime re-shaped for TPU: with columns local, the panel
    needs no column broadcast at all, and the only collectives per panel are
    the candidate all-gather (tournament, getrf_tntpiv.cc) and two masked
    psums (dirty-row exchange + U row-band broadcast) — O(nb·(P·nb + npc))
    bytes each.  Work is O(m n²/P): the square-embedding detour (round 2) and
    its O(m³) flops are gone.
    """
    AX = (ROW_AXIS, COL_AXIS)                  # flattened device axis
    nprocs = mesh.shape[ROW_AXIS] * mesh.shape[COL_AXIS]
    mr = mpad // nprocs
    nt = npc // nb
    assert mr % nb == 0

    def local_fn(A_loc):                       # (mr, npc) per device
        ri = lax.axis_index(AX)
        grow = ri * mr + jnp.arange(mr, dtype=jnp.int32)
        gcol = jnp.arange(npc, dtype=jnp.int32)

        def step(k, carry):
            A_loc, perm = carry
            k0 = (k * nb).astype(jnp.int32) if hasattr(k, "astype") else k * nb

            # ---- panel pivot selection + ipiv-compatible step permutation
            # (shared machinery, pivot.py)
            pan = lax.dynamic_slice(A_loc, (jnp.int32(0), k0), (mr, nb))
            piv = select_pivots(lu_panel, pan, grow, k0, nb, nprocs, AX)
            stepperm = step_permutation(piv, k0, mpad, nb)
            perm = perm[stepperm]

            # ---- dirty-row exchange (≤ 2nb rows move, full local width;
            # shared machinery, pivot.py)
            S = jnp.concatenate([k0 + jnp.arange(nb, dtype=jnp.int32), piv])
            A_loc = _exchange_rows(A_loc, S, stepperm[S], ri, mr, AX)

            # ---- diagonal block factor (rows [k0,k0+nb) live on device po)
            po = k0 // mr
            roff = k0 - po * mr
            pan2 = lax.dynamic_slice(A_loc, (jnp.int32(0), k0), (mr, nb))
            blk = lax.dynamic_slice(pan2, (roff, jnp.int32(0)), (nb, nb))
            blk = jnp.where(ri == po, blk, jnp.zeros_like(blk))
            blk = lax.psum(blk, AX)
            LUkk, _, blkperm = lax.linalg.lu(blk)
            # fold intra-block pivots into the global permutation + reorder
            seg = jnp.take(perm, k0 + blkperm)
            perm = lax.dynamic_update_slice(perm, seg, (k0,))
            blk_rows = A_loc[jnp.clip(roff + blkperm, 0, mr - 1)]
            A_perm = lax.dynamic_update_slice(A_loc, blk_rows,
                                              (roff, jnp.int32(0)))
            A_loc = jnp.where(ri == po, A_perm, A_loc)
            pan2 = lax.dynamic_slice(A_loc, (jnp.int32(0), k0), (mr, nb))

            # ---- panel L: X = pan · Ukk^{-1} for rows below the block
            Ukk = jnp.triu(LUkk)
            X = lax.linalg.triangular_solve(Ukk, pan2, left_side=False,
                                            lower=False)
            below = grow >= (k0 + nb)
            Lmask = jnp.where(below[:, None], X, jnp.zeros_like(X))
            in_blk = (grow >= k0) & (grow < k0 + nb)
            packed = jnp.where(in_blk[:, None],
                               lax.dynamic_update_slice(
                                   jnp.zeros((mr, nb), pan2.dtype), LUkk,
                                   (roff, jnp.int32(0))),
                               jnp.where(below[:, None], Lmask, pan2))
            A_loc = lax.dynamic_update_slice(A_loc, packed, (jnp.int32(0), k0))

            # ---- U row band (owner bcast) + masked trailing columns
            rb = lax.dynamic_slice(A_loc, (roff, jnp.int32(0)), (nb, npc))
            rb = jnp.where(ri == po, rb, jnp.zeros_like(rb))
            rb = lax.psum(rb, AX)              # (nb, npc) everywhere
            U_band = lax.linalg.triangular_solve(jnp.tril(LUkk), rb,
                                                 left_side=True, lower=True,
                                                 unit_diagonal=True)
            ucols = gcol >= (k0 + nb)
            Umask = jnp.where(ucols[None, :], U_band, jnp.zeros_like(U_band))
            new_rows = jnp.where(ucols[None, :], U_band, rb)
            rowband = lax.dynamic_update_slice(A_loc, new_rows,
                                               (roff, jnp.int32(0)))
            A_loc = jnp.where(ri == po, rowband, A_loc)

            # ---- trailing update: one fully local MXU gemm
            A_loc = A_loc - jnp.matmul(Lmask, Umask,
                                       precision=lax.Precision.HIGHEST)
            return A_loc, perm

        perm0 = jnp.arange(mpad, dtype=jnp.int32)
        A_loc, perm = lax.fori_loop(0, nt, step, (A_loc, perm0))

        # info: first zero diagonal of U (cols ∩ my rows, psum-assembled;
        # shared kernel robust.first_bad_index)
        on_diag = (grow[:, None] == gcol[None, :])
        drow = jnp.sum(jnp.where(on_diag, A_loc, jnp.zeros_like(A_loc)),
                       axis=1)
        in_range = grow < npc
        diag = jnp.zeros((npc,), A_loc.dtype).at[
            jnp.where(in_range, grow, npc)].add(
                jnp.where(in_range, drow, jnp.zeros_like(drow)), mode="drop")
        diag = lax.psum(diag, AX)
        info = first_bad_index(diag == 0)
        return A_loc, perm, info

    spec = P(AX, None)
    fn = shard_map(local_fn, mesh=mesh, in_specs=spec,
                       out_specs=(spec, P(None), P()), check_vma=False)
    return jax.jit(fn)


@instrument
def getrf_tall_distributed(A: jax.Array, grid: ProcessGrid, nb: int = 256,
                           lu_panel: str = "tournament"):
    """1-D TSLU for tall matrices (m > n) over the flattened mesh.

    Returns ``(LU, perm, info)`` with ``A[perm] = L @ U`` in O(m n²/P) work —
    the mesh form of the reference's tall ``getrf.cc`` regime, replacing
    round 2's O(m³) square embedding.  Rows are padded to P·nb blocks and
    columns to nb multiples; pad columns carry unit pivots on pad rows so
    they never disturb the real factorization.
    """
    m, n = A.shape[-2:]
    slate_assert(m >= n, "getrf_tall_distributed expects m >= n")
    slate_assert(lu_panel in ("tournament", "pp"),
                 f"lu_panel must be 'tournament' or 'pp', got {lu_panel!r}")
    nb = max(1, min(nb, n))
    unit = nb * grid.p * grid.q
    npc = ceil_mult(n, nb)
    mpad = ceil_mult(m, unit)
    if mpad - m < npc - n:      # need a pad row per pad column
        mpad += unit
    if (mpad, npc) != (m, n):
        Ap = jnp.zeros((mpad, npc), A.dtype)
        Ap = Ap.at[:m, :n].set(A)
        if npc > n:             # unit pivots for pad columns, on pad rows
            Ap = Ap.at[m + jnp.arange(npc - n), n + jnp.arange(npc - n)].set(1)
    else:
        Ap = A
    mesh = grid.mesh
    Ap = jax.device_put(Ap, jax.sharding.NamedSharding(
        mesh, P((ROW_AXIS, COL_AXIS), None)))
    LU, perm, info = _getrf_tall_fn(mesh, mpad, npc, nb, str(Ap.dtype),
                                    lu_panel)(Ap)
    if mpad > m:
        # pad columns carry their unit pivot on a PAD row, so each pad column
        # deterministically swaps one pad row into the head — positions
        # [n, npc) of the head hold pad rows and their displaced real rows sit
        # in the tail.  (Unlike the square embedding, this is the *generic*
        # case, not a singularity signal.)  Repair both halves of the
        # truncation: the perm entry AND the L row, gathered from the padded
        # position where the displaced real row actually resides — valid
        # because row r of P·A_pad satisfies A[r] = L_pad[pos(r), :n] @ U for
        # every real row wherever it sits.
        head = perm[:m]
        bad = head >= m
        tail = perm[m:]
        key = jnp.where(tail < m, tail, mpad)
        order = jnp.argsort(key)             # tail slots sorted by row value
        cum = jnp.cumsum(bad) - 1            # index among bad slots
        repl = jnp.sort(key)[jnp.clip(cum, 0, key.shape[0] - 1)]
        srcpos = (m + order)[jnp.clip(cum, 0, order.shape[0] - 1)]
        perm = jnp.where(bad, repl, head)
        LUm = jnp.where(bad[:, None],
                        LU[jnp.clip(srcpos, 0, mpad - 1)], LU[:m])
        # a pad row inside the first n positions means a REAL column went
        # singular (its zero U diagonal already set info <= n); pad-column
        # info (> n) is the benign embedding diagonal
        info = jnp.where(info > n, jnp.int32(0), info)
        return LUm[:, :n], perm, info
    perm = perm[:m]
    info = jnp.where(info > n, jnp.int32(0), info)
    return LU[:m, :n], perm, info


@instrument
def getrf_distributed(A: jax.Array, grid: ProcessGrid, nb: int = 256,
                      lu_panel: str = "tournament"):
    """Distributed tournament-pivoted LU over the process grid.

    Returns ``(LU, perm, info)`` with ``A[perm] = L @ U`` (L unit-lower, U
    upper, packed into one sharded array) — the distributed form of
    ``linalg.lu.getrf_tntpiv`` and the analogue of ``src/getrf_tntpiv.cc``.

    ``lu_panel`` (Options.lu_panel) selects panel pivoting: "tournament"
    (CALU candidate rounds, the communication-avoiding default) or "pp"
    (one gathered partial-pivot panel LU — exact LAPACK selection at
    O(m·nb) gather bytes per panel; the first-class A/B of the single-chip
    ``_getrf_tntpiv_fn`` schemes).

    Tall inputs (m > n) route to ``getrf_tall_distributed`` — 1-D TSLU over
    the flattened mesh with O(m n²/P) work (round 2's O(m³) square embedding
    is gone; the reference's getrf.cc handles the same regime on its 2-D
    grid, but with columns local the tall panel needs no broadcast at all).

    Wide inputs (m < n) factor the leading m×m block — partial pivoting never
    looks past column m — and finish the trailing columns with one sharded
    unit-lower solve, U[:, m:] = L^{-1} (P A)[:, m:] (the same split the
    reference's getrf uses once the diagonal runs out).
    """
    m, n = A.shape[-2:]
    slate_assert(A.ndim == 2, "getrf_distributed expects a 2-D matrix")
    slate_assert(lu_panel in ("tournament", "pp"),
                 f"lu_panel must be 'tournament' or 'pp', got {lu_panel!r}")
    if m > n:
        return getrf_tall_distributed(A, grid, nb=nb, lu_panel=lu_panel)
    if m < n:
        from .solvers import trsm_distributed

        LU1, perm, info = getrf_distributed(A[:, :m], grid, nb=nb,
                                            lu_panel=lu_panel)
        L = jnp.tril(LU1, -1) + jnp.eye(m, dtype=LU1.dtype)
        U2 = trsm_distributed(L, jnp.take(A[:, m:], perm, axis=0), grid,
                              lower=True, conj_trans=False)
        return jnp.concatenate([LU1, U2], axis=1), perm, info
    # clamp the block size so the padding unit never dwarfs the problem
    # (default nb=256 on a small matrix would otherwise pad to nb*lcm(p,q))
    nb = max(1, min(nb, n))
    unit = nb * _lcm(grid.p, grid.q)
    npad = ceil_mult(m, unit)
    if npad > n:
        # one allocation covers both the tall embedding (cols n..m) and the
        # divisibility padding (rows/cols m..npad): unit diagonal throughout
        Ap = jnp.zeros((npad, npad), A.dtype)
        Ap = Ap.at[:m, :n].set(A)
        idx = jnp.arange(n, npad)
        Ap = Ap.at[idx, idx].set(1)
    else:
        Ap = A
    Ap = jax.device_put(Ap, grid.spec())
    LU, perm, info = _getrf_dist_fn(grid.mesh, npad, min(nb, npad),
                                    str(Ap.dtype), lu_panel)(Ap)
    if npad > m:
        # pad rows never win a tournament against real rows (their entries in
        # real columns are zero) — except when a trailing block is exactly
        # singular, where a zero pad row can tie and be selected.  Repair the
        # truncated perm so it remains a permutation of [0,m): out-of-range
        # entries are replaced, in position order, by the unused values that
        # were displaced past position m (only reachable when info != 0).
        head = perm[:m]
        bad = head >= m
        tail = perm[m:]
        repl = jnp.sort(jnp.where(tail < m, tail, npad))   # unused values first
        perm = jnp.where(bad, repl[jnp.cumsum(bad) - 1], head)
        # a repaired position means a pad row's (zero) L entries landed inside
        # the leading m rows — the factorization there is NOT a clean LU of A,
        # so a pad-column info must not be silenced into success
        fallback = jnp.where(jnp.any(bad), jnp.argmax(bad).astype(jnp.int32) + 1,
                             jnp.int32(0))
        info = jnp.where(info > n, fallback, info)
    else:
        perm = perm[:m]
        # rows n..m of the embedding columns are real rows, so pivoting there
        # cannot corrupt the leading n columns: pad-column info is benign
        info = jnp.where(info > n, jnp.int32(0), info)
    LU = LU[:m, :n]
    return LU, perm, info


@instrument
def getrs_distributed(LU: jax.Array, perm: jax.Array, B: jax.Array,
                      grid: ProcessGrid):
    """Solve A X = B given the distributed LU: X = U^{-1} L^{-1} B[perm]
    (src/getrs.cc: permuteRows + two work::trsm sweeps)."""
    from .solvers import trsm_distributed

    Bp = jnp.take(B, perm, axis=0)
    n = LU.shape[-1]
    eye = jnp.eye(n, dtype=LU.dtype)
    L = jnp.tril(LU, -1) + eye
    U = jnp.triu(LU)
    Y = trsm_distributed(L, Bp, grid, lower=True, conj_trans=False)
    return trsm_distributed(U, Y, grid, lower=False, conj_trans=False)


@instrument
def gesv_distributed(A: jax.Array, B: jax.Array, grid: ProcessGrid,
                     nb: int = 256, lu_panel: str = "tournament"):
    """Distributed general solve A X = B (src/gesv.cc = getrf + getrs).

    Runs under the failed-shard guard (robust.guard_shards): when a fault
    plan simulates a dead device (shard_fail at the "output" point), a
    non-finite result re-runs factor AND solve from the intact input — the
    honest recovery.  Zero extra host syncs when no chaos is active.

    Returns ``(X, info)``.
    """
    state = {}

    def run():
        LU, perm, info = getrf_distributed(inject("gesv_distributed", A),
                                           grid, nb=nb, lu_panel=lu_panel)
        state["info"] = info
        return getrs_distributed(LU, perm, B, grid)

    X, _ = guard_shards("gesv_distributed", run, RetryPolicy(max_retries=1))
    return X, state["info"]


@instrument
def gesv_mixed_distributed(A: jax.Array, B: jax.Array, grid: ProcessGrid,
                           nb: int = 256, max_iterations: int = 30):
    """Distributed mixed-precision solve (src/gesv_mixed.cc over the mesh):
    tournament-LU factor in the next precision down (f64->f32, c128->c64;
    f32 has no lower rung — XLA's LU rejects bf16), working-precision
    iterative refinement, full-precision sharded fallback when IR stalls.

    Returns (X, perm, info, iters, converged_via_ir).
    """
    from .solvers import _ir_refine_distributed, _lower_dtype

    lo = _lower_dtype(A.dtype)
    if lo is None:
        LU, perm, info = getrf_distributed(A, grid, nb=nb)
        return getrs_distributed(LU, perm, B, grid), perm, info, 0, True
    LU, perm, info = getrf_distributed(A.astype(lo), grid, nb=nb)

    def solve_lo(R):
        return getrs_distributed(LU, perm, R.astype(lo), grid)

    X, iters, ok = _ir_refine_distributed(A, B, solve_lo, grid,
                                          max_iterations)
    if not bool(ok):                      # the solve's single host sync
        # mixed→full ladder (robust.LADDERS["gesv_mixed_distributed"])
        trace_event("fallback", routine="gesv_mixed_distributed", to="full")
        LU, perm, info = getrf_distributed(A, grid, nb=nb)
        return (getrs_distributed(LU, perm, B, grid), perm, info, int(iters),
                False)
    return X, perm, info, int(iters), True


@instrument
def gesv_mixed_gmres_distributed(A: jax.Array, B: jax.Array,
                                 grid: ProcessGrid, nb: int = 256, opts=None):
    """Distributed GMRES-IR (src/gesv_mixed_gmres.cc over the mesh): FGMRES in
    working precision with sharded matvecs, right-preconditioned by the
    low-precision tournament-LU solve (factor sharded, solves in-trace).
    Single-RHS like the reference.  Returns (X, perm, info, restarts,
    converged); falls back to the full-precision sharded solve on stall.
    """
    from ..core.types import Options
    from ..linalg.lu import _gmres_ir, _require_single_rhs, lu_factored_solve
    from .eig_dist import _shard
    from .solvers import _lower_dtype

    opts = Options.make(opts)
    _require_single_rhs(B, "gesv_mixed_gmres_distributed")
    vec = B.ndim == 1
    B2 = B[:, None] if vec else B       # the sharded solves need 2-D RHS

    def fallback():
        LUf, permf, infof = getrf_distributed(A, grid, nb=nb)
        Xf = getrs_distributed(LUf, permf, B2, grid)
        return (Xf[:, 0] if vec else Xf), permf, infof

    lo = opts.factor_precision or _lower_dtype(A.dtype)
    if lo is None:
        Xf, permf, infof = fallback()
        return Xf, permf, infof, 0, True
    LU, perm, info = getrf_distributed(A.astype(lo), grid, nb=nb)
    # sharding *constraints*, not device_put: GSPMD pads grid-indivisible n
    LUs = _shard(LU, grid)
    As = _shard(A, grid)

    def matvec(x):
        return jnp.matmul(As, x, precision=lax.Precision.HIGHEST)

    def precond(r):
        z = lu_factored_solve(LUs, perm, r.astype(lo)[:, None])
        return z[:, 0].astype(B.dtype)

    X, restarts, converged = _gmres_ir(matvec, precond, B, opts,
                                       "gesv_mixed_gmres_distributed")
    if not converged:
        if not opts.use_fallback_solver:
            return X, perm, info, int(restarts), False
        trace_event("fallback", routine="gesv_mixed_gmres_distributed",
                    to="full")
        Xf, permf, infof = fallback()
        return Xf, permf, infof, int(restarts), False
    return X, perm, info, int(restarts), True
