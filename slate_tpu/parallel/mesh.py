"""Process grid over a TPU device mesh.

Reference analogue: the p×q MPI/BLACS process grid every SLATE matrix carries
(``BaseMatrix.hh:161-164`` ``gridinfo()``, ``func.hh:178-186`` 2D block-cyclic maps,
``MatrixStorage.hh:494-499``).  The reference asks MPI for a communicator and computes
each rank's (p, q) coordinate; here the grid *is* a ``jax.sharding.Mesh`` with axes
``("p", "q")`` over the slice's devices, and a "rank" is the flattened mesh coordinate.

Multi-host note: a ``Mesh`` built from ``jax.devices()`` spans all hosts of a pod slice
automatically (ICI for intra-slice axes, DCN across slices) — there is no separate
multi-node code path, which is the core simplification over MPI.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core import grid as grid_funcs
from ..core.exceptions import slate_assert
from ..core.types import GridOrder

ROW_AXIS = "p"
COL_AXIS = "q"

# --- environment resilience: the distributed layer is written against the
# modern ``jax.shard_map`` spelling (jax >= 0.5).  Older jax ships it at
# ``jax.experimental.shard_map`` with ``check_rep`` instead of ``check_vma``;
# without this adapter every shard_map driver dies with AttributeError at
# first call on such environments.  The adapter is a module-local binding
# (``from .mesh import shard_map``), NOT a patch of the global jax namespace —
# mutating ``jax.shard_map`` would change what third-party feature detection
# sees after ``import slate_tpu``.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - jax-version-specific
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=True, **kw):
        kw.pop("check_rep", None)   # accept either spelling, pass one
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma,
                                 **kw)


class ProcessGrid:
    """A p×q grid of devices playing the role of the reference's MPI process grid.

    ``order`` mirrors the reference's ``GridOrder`` (func.hh): Col means ranks run down
    columns first (rank = i%p + (j%q)*p), the ScaLAPACK default.
    """

    def __init__(self, p: Optional[int] = None, q: Optional[int] = None,
                 devices: Optional[Sequence] = None,
                 order: GridOrder = GridOrder.Col):
        devices = list(devices if devices is not None else jax.devices())
        if p is None and q is None:
            p, q = grid_funcs.grid_size(len(devices))
        elif p is None:
            p = len(devices) // q
        elif q is None:
            q = len(devices) // p
        slate_assert(p >= 1 and q >= 1 and p * q <= len(devices),
                     f"grid {p}x{q} needs p, q >= 1 and p*q <= {len(devices)} devices")
        self.p, self.q = int(p), int(q)
        self.order = GridOrder.from_string(order)
        dev_grid = np.array(devices[:p * q])
        # Mesh axes are (p, q); Col order lays ranks down columns, so the flattened
        # device index runs fastest over p — transpose the reshape accordingly.
        if self.order == GridOrder.Col:
            dev_grid = dev_grid.reshape(self.q, self.p).T
        else:
            dev_grid = dev_grid.reshape(self.p, self.q)
        self.mesh = Mesh(dev_grid, (ROW_AXIS, COL_AXIS))
        self.tile_rank = grid_funcs.process_2d_grid(self.order, self.p, self.q)

    # -- reference gridinfo() ------------------------------------------------
    @property
    def size(self) -> int:
        return self.p * self.q

    def gridinfo(self) -> Tuple[GridOrder, int, int]:
        return self.order, self.p, self.q

    def coords(self, rank: int) -> Tuple[int, int]:
        """(row, col) coordinate of a flattened rank (BLACS pcoord analogue)."""
        if self.order == GridOrder.Col:
            return rank % self.p, rank // self.p
        return rank // self.q, rank % self.q

    @property
    def rank(self) -> int:
        """This process's flattened grid rank (Cblacs_pcoord's myrow/mycol
        inverse).  Under single-controller SPMD every device is addressable,
        so the controller's rank is the first grid position owned by one of
        this process's local devices — 0 in single-process runs, and the
        process's first device slot under jax.distributed (multi-host).
        Cached: the mesh is fixed at construction and tileIsLocal reads this
        per tile."""
        cached = getattr(self, "_rank", None)
        if cached is not None:
            return cached
        local = set(jax.local_devices())
        flat = (self.mesh.devices.T if self.order == GridOrder.Col
                else self.mesh.devices).ravel()
        rank = -1   # no local device on this grid -> this process owns nothing
        for r, d in enumerate(flat):
            if d in local:
                rank = r
                break
        self._rank = rank
        return rank

    # -- shardings -----------------------------------------------------------
    def spec(self, row_shard: bool = True, col_shard: bool = True,
             extra_leading: int = 0) -> NamedSharding:
        """NamedSharding for a 2-D array: rows over p, cols over q (either optional)."""
        parts = [None] * extra_leading
        parts += [ROW_AXIS if row_shard else None, COL_AXIS if col_shard else None]
        return NamedSharding(self.mesh, PartitionSpec(*parts))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def row_spec(self) -> NamedSharding:
        """1-D row distribution (rows over the whole flattened grid) for tall panels —
        the reference's 1D grids (func.hh process_1d_grid)."""
        return NamedSharding(self.mesh, PartitionSpec((ROW_AXIS, COL_AXIS)))

    def __repr__(self) -> str:
        return (f"ProcessGrid({self.p}x{self.q}, order={self.order}, "
                f"devices={self.size})")
