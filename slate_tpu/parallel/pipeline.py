"""Software-pipelined (lookahead) distributed Cholesky over an explicit
shard_map — the reference's lookahead task pipeline in SPMD form.

This is a production path: ``potrf_distributed(..., lookahead >= 2)`` — and
through it the ``slate.potrf`` driver's ``Option::Lookahead`` — routes here
(round-2 review: "lookahead is a demo no production driver calls").

Reference analogue: ``src/potrf.cc:84-195`` — the OpenMP task DAG gives the
next panel column a *high-priority* update task so its factorization and
broadcast overlap the bulk trailing update (``potrf.cc:136-177`` lookahead
columns; SURVEY.md §2.6 "pipeline lookahead").

TPU re-design: there is no task runtime — the same overlap is expressed as a
*dependency structure*.  Each fori_loop step, in trace order:

1. **prioritized column update**: the owner of panel k+1 applies panel k to
   that one block column only (cheap);
2. **next-panel factor + broadcast**: the updated column is psum-broadcast
   (masked-contribution trick ≅ tileBcast, BaseMatrix.hh:1999) and factored
   redundantly on every device (replicated O(n·nb²) work — cheaper than a
   second broadcast);
3. **bulk trailing update**: all remaining local columns get the rank-nb
   gemm update from panel k.

Step 3 has no data dependency on step 2's collective, so XLA's latency-hiding
scheduler can run the ICI broadcast for panel k+1 *under* the trailing-update
gemm of panel k — the software-pipelined form of lookahead = 1.  The layout is
1-D block-cyclic over the flattened mesh (column j lives on device j mod d),
the distribution ScaLAPACK uses for exactly this reason: every step keeps all
devices busy in the trailing update.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
import numpy as np

from .mesh import ProcessGrid, shard_map
from ..linalg.chol import _chol_blocked
from ..obs import instrument

_AXIS = "d"


@lru_cache(maxsize=32)
def _potrf_pipelined_fn(mesh, n: int, nb: int, d: int, dtype_str: str):
    nt = n // nb
    nt_loc = nt // d

    def local_cols(me):
        """Global block-column index of each local slot: j(s) = s*d + me."""
        return jnp.arange(nt_loc) * d + me

    def factor_panel(col, k):
        """Factor global block column k from its updated full-height column:
        diag Cholesky + panel trsm, rows above the diagonal block zeroed
        (internal::potrf + internal::trsm, potrf.cc:96-119)."""
        rows = jnp.arange(n)
        start = k * nb
        D = lax.dynamic_slice(col, (start, 0), (nb, nb))
        Lkk = _chol_blocked(D)
        below = jnp.where((rows >= start + nb)[:, None], col, 0)
        panel = lax.linalg.triangular_solve(
            Lkk, below, left_side=False, lower=True,
            conjugate_a=True, transpose_a=True)
        panel = lax.dynamic_update_slice(panel, Lkk, (start, 0))
        return jnp.where((rows >= start)[:, None], panel, 0)

    def apply_panel(Lloc, P_k, k, me, j_min):
        """Rank-nb update of every local column with global index >= j_min:
        L[:, j] -= P_k @ P_k[rows of block j]^H (internal::herk/gemm trailing
        update, potrf.cc:136-148)."""
        js = local_cols(me)                            # (nt_loc,)
        Gall = P_k.reshape(nt, nb, nb)
        G = Gall[js]                                   # (nt_loc, nb, nb)
        upd = jnp.einsum("nk,smk->nsm", P_k, jnp.conj(G),
                         precision=lax.Precision.HIGHEST)
        upd = upd.reshape(n, nt_loc * nb)
        mask = jnp.repeat(js >= j_min, nb)[None, :]
        return Lloc - jnp.where(mask, upd, 0)

    def body(k, carry):
        Lloc, P_k = carry
        me = lax.axis_index(_AXIS)
        owner1 = (k + 1) % d
        slot1 = jnp.minimum((k + 1) // d, nt_loc - 1)
        valid1 = k + 1 < nt

        # -- 1. prioritized update of global column k+1 on its owner --------
        col1 = lax.dynamic_slice(Lloc, (0, slot1 * nb), (n, nb))
        G1 = lax.dynamic_slice(P_k, ((k + 1) % nt * nb, 0), (nb, nb))
        col1_upd = col1 - jnp.matmul(P_k, jnp.conj(G1).T,
                                     precision=lax.Precision.HIGHEST)
        mine1 = (me == owner1) & valid1
        # -- 2. broadcast + factor panel k+1 (masked-psum bcast) -----------
        contrib = jnp.where(mine1, col1_upd, jnp.zeros_like(col1_upd))
        bc = lax.psum(contrib, _AXIS)
        kp1 = jnp.minimum(k + 1, nt - 1)
        P_next = factor_panel(bc, kp1)
        P_next = jnp.where(valid1, P_next, jnp.zeros_like(P_next))
        # owner writes its updated (factored) column back
        col1_new = jnp.where(mine1, P_next, col1)
        Lloc = lax.dynamic_update_slice(Lloc, col1_new, (0, slot1 * nb))
        # -- 3. bulk trailing update (independent of step 2's collective) --
        Lloc = apply_panel(Lloc, P_k, k, me, j_min=k + 2)
        return Lloc, P_next

    def fn(Lloc):
        me = lax.axis_index(_AXIS)
        # prologue: factor + broadcast panel 0
        col0 = lax.dynamic_slice(Lloc, (0, 0), (n, nb))
        contrib = jnp.where(me == 0, col0, jnp.zeros_like(col0))
        bc = lax.psum(contrib, _AXIS)
        P0 = factor_panel(bc, 0)
        Lloc = jnp.where(me == 0,
                         lax.dynamic_update_slice(Lloc, P0, (0, 0)), Lloc)
        Lloc, _ = lax.fori_loop(0, nt, body, (Lloc, P0))
        return Lloc

    spec = P(None, _AXIS)
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=spec,
                                 out_specs=spec, check_vma=False))


@instrument
def potrf_pipelined(Af: jax.Array, grid: ProcessGrid, nb: int = 256) -> jax.Array:
    """Distributed lower Cholesky with explicit lookahead pipelining over the
    flattened mesh (1-D block-cyclic columns).  Returns the dense lower factor
    (gathered layout).  See module docstring for the overlap structure.
    """
    n0 = Af.shape[-1]
    d = grid.size
    # the kernel only needs nt % d == 0; clamping nb to ceil(n0/d) bounds the
    # identity-tail padding at one block column per device
    nb = max(1, min(nb, -(-n0 // d)))
    unit = nb * d
    npad = -(-n0 // unit) * unit
    if npad != n0:
        Ap = jnp.zeros((npad, npad), Af.dtype).at[:n0, :n0].set(Af)
        idx = jnp.arange(n0, npad)
        Ap = Ap.at[idx, idx].set(1)
    else:
        Ap = Af
    n = npad
    nt = n // nb
    devices = np.array(grid.mesh.devices).ravel()
    mesh1d = Mesh(devices, (_AXIS,))

    # block-cyclic column permutation: shard s of device m holds global
    # block-column s*d + m; the sharded axis layout is device-major, so
    # pre-permute columns into (device, slot) order and undo after (shared
    # layout bridge with redistribute, distribute.cyclic_permutation)
    from .distribute import cyclic_permutation

    fwd_cols = cyclic_permutation(n, nb, d)
    inv_cols = np.argsort(fwd_cols)

    Aperm = jnp.take(Ap, jnp.asarray(fwd_cols), axis=1)
    Aperm = jax.device_put(Aperm, NamedSharding(mesh1d, P(None, _AXIS)))
    Lperm = _potrf_pipelined_fn(mesh1d, n, nb, d, str(Ap.dtype))(Aperm)
    L = jnp.take(Lperm, jnp.asarray(inv_cols), axis=1)
    L = jnp.tril(L)
    return L[:n0, :n0] if npad != n0 else L
